"""On-chip A/B for the fused Pallas encode kernel (VERDICT r1 #2).

Runs the SAME timed train-step loop as bench.py twice — XLA path vs
``USE_PALLAS_FUSED_ENCODE`` — on the real TPU at the java14m headline
configuration, and prints one JSON line per variant plus a verdict line:

  {"metric": "train_examples_per_sec_per_chip_java14m", "variant": "xla", ...}
  {"metric": "train_examples_per_sec_per_chip_java14m", "variant": "pallas", ...}
  {"verdict": "keep-pallas" | "keep-xla", "speedup": ...}

This is the evidence the USE_PALLAS_FUSED_ENCODE default decision needs;
refuses to run on non-TPU backends (interpreter-mode numbers would be
meaningless). Run it whenever the TPU tunnel is healthy:

  python benchmarks/bench_pallas_encode.py            # full java14m shapes
  BENCH_SMOKE=1 python benchmarks/bench_pallas_encode.py  # harness check
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TOKEN_VOCAB = 1301136
PATH_VOCAB = 911417
TARGET_VOCAB = 261245
BATCH_SIZE = 1024
MAX_CONTEXTS = 200
WARMUP_STEPS = 10
MEASURE_STEPS = 30

SMOKE = os.environ.get('BENCH_SMOKE', '') not in ('', '0', 'false')
if SMOKE:
    TOKEN_VOCAB, PATH_VOCAB, TARGET_VOCAB = 1000, 1000, 500
    BATCH_SIZE, MAX_CONTEXTS = 64, 16
    WARMUP_STEPS, MEASURE_STEPS = 2, 5


def measure(use_pallas: bool) -> float:
    import numpy as np

    from code2vec_tpu.config import Config
    from code2vec_tpu.data.reader import Batch
    from code2vec_tpu.models.backends import create_backend
    from code2vec_tpu.training.trainer import Trainer
    from code2vec_tpu.vocab import SizeOnlyVocabs

    config = Config(
        TRAIN_DATA_PATH_PREFIX='bench', DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='bfloat16', VERBOSE_MODE=0, READER_USE_NATIVE=False,
        TRAIN_BATCH_SIZE=BATCH_SIZE, TEST_BATCH_SIZE=BATCH_SIZE,
        MAX_CONTEXTS=MAX_CONTEXTS, USE_PALLAS_FUSED_ENCODE=use_pallas,
        MAX_TOKEN_VOCAB_SIZE=TOKEN_VOCAB, MAX_PATH_VOCAB_SIZE=PATH_VOCAB,
        MAX_TARGET_VOCAB_SIZE=TARGET_VOCAB)
    backend = create_backend(
        config, SizeOnlyVocabs(TOKEN_VOCAB, PATH_VOCAB, TARGET_VOCAB))
    trainer = Trainer(config, backend)
    state = trainer.init_state(seed=0)

    rng = np.random.default_rng(0)

    def make_batch():
        return Batch(
            source=rng.integers(1, TOKEN_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            path=rng.integers(1, PATH_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            target=rng.integers(1, TOKEN_VOCAB, (BATCH_SIZE, MAX_CONTEXTS)).astype(np.int32),
            mask=np.ones((BATCH_SIZE, MAX_CONTEXTS), np.float32),
            label=rng.integers(1, TARGET_VOCAB, (BATCH_SIZE,)).astype(np.int32),
            weight=np.ones((BATCH_SIZE,), np.float32))

    batches = [make_batch() for _ in range(4)]
    for i in range(WARMUP_STEPS):
        state, loss = trainer.train_step(state, batches[i % len(batches)])
        float(loss)
    start = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, loss = trainer.train_step(state, batches[i % len(batches)])
        float(loss)
    elapsed = time.perf_counter() - start
    return MEASURE_STEPS * BATCH_SIZE / elapsed


def main() -> None:
    import jax
    env_platforms = os.environ.get('JAX_PLATFORMS')
    if env_platforms and jax.config.jax_platforms != env_platforms:
        try:
            jax.config.update('jax_platforms', env_platforms)
        except RuntimeError:
            pass
    platform = jax.devices()[0].platform.lower()
    if not SMOKE and platform not in ('tpu', 'axon'):
        print(json.dumps({'error': 'tpu_unavailable',
                          'detail': f'platform={platform}'}))
        return

    results = {}
    for variant, use_pallas in [('xla', False), ('pallas', True)]:
        try:
            examples_per_sec = measure(use_pallas)
        except Exception as exc:  # a kernel compile failure IS the answer
            print(json.dumps({'variant': variant, 'error': str(exc)[:300]}))
            if variant == 'pallas':
                print(json.dumps({'verdict': 'keep-xla',
                                  'reason': 'pallas path failed'}))
                return
            raise
        results[variant] = examples_per_sec
        print(json.dumps({
            'metric': ('train_examples_per_sec_SMOKE_ONLY' if SMOKE
                       else 'train_examples_per_sec_per_chip_java14m'),
            'variant': variant,
            'value': round(examples_per_sec, 1),
            'unit': 'examples/sec/chip'}))
    speedup = results['pallas'] / results['xla']
    print(json.dumps({
        'verdict': 'keep-pallas' if speedup > 1.02 else 'keep-xla',
        'speedup': round(speedup, 4)}))


if __name__ == '__main__':
    main()
