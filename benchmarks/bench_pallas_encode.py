"""On-chip A/B for the fused Pallas encode kernel (VERDICT r1 #2).

The kernel serves the DETERMINISTIC forward only (training applies dropout
inside the encode block, so ``encode`` routes Pallas exclusively when no
dropout is active — functional.py:120-128); the honest product-level A/B is
therefore the jitted **eval step** (forward + sharded top-k) at the java14m
headline configuration:

  {"metric": "eval_examples_per_sec_per_chip_java14m", "variant": "xla", ...}
  {"metric": "eval_examples_per_sec_per_chip_java14m", "variant": "pallas", ...}
  {"verdict": "keep-pallas" | "keep-xla", "speedup": ...}

The pallas variant additionally verifies the kernel actually ENGAGED by
checking the compiled HLO for the Pallas custom-call — without this, a
platform-predicate mismatch silently compares XLA against itself and the
"A/B" is meaningless.

Run it whenever the TPU tunnel is healthy:

  python benchmarks/bench_pallas_encode.py            # full java14m shapes
  BENCH_SMOKE=1 python benchmarks/bench_pallas_encode.py  # harness check
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
# BENCH_CONTEXTS overrides the bag size: the kernel's best case is
# long-context configs where the encode block dominates the eval step.
_contexts = int(os.environ.get('BENCH_CONTEXTS', '0'))
if _contexts:
    SHAPES = SHAPES._replace(max_contexts=_contexts)
WARMUP_STEPS, MEASURE_STEPS = benchlib.bench_steps(SMOKE)


def measure(use_pallas: bool):
    """Returns (examples_per_sec_per_chip, engaged)."""
    import jax
    import jax.numpy as jnp

    config = benchlib.headline_config(SHAPES,
                                      USE_PALLAS_FUSED_ENCODE=use_pallas)
    trainer, params = benchlib.build_eval_trainer(config, SHAPES)

    # Device-resident batches placed via the trainer's mesh-aware staging —
    # but unlike train steps, eval steps carry no cross-step data
    # dependency, and through this environment's async device tunnel
    # neither blocking on the last output nor block_until_ready over ALL
    # outputs proves the programs executed inside the timed window (both
    # produced physically impossible numbers, e.g. 7.2M "examples/sec" ~
    # 0.14 ms for a 205-GFLOP logits matmul + 261K top-k). Only fetching a
    # VALUE demonstrably waits for remote compute — so thread a scalar from
    # each step's output into the next step's input (weight + 0*token),
    # serializing the chain exactly like train's state dependency, and
    # fetch once at the end: elapsed = sum of true step times + one
    # round-trip.
    placed = benchlib.staged(trainer, benchlib.random_batches(SHAPES, 4))
    # AOT HLO inspection costs a full extra compile of the java14m eval
    # program — only pay it for the variant whose engagement is in doubt.
    engaged = (benchlib.mosaic_engaged(trainer._eval_step, params,
                                       placed[0])
               if use_pallas else False)

    chain_weight = jax.jit(lambda w, t: w + t * 0)

    def run_chain(steps: int) -> float:
        token = jnp.zeros((), jnp.float32)
        for i in range(steps):
            source, path, target, mask, label, weight = placed[i % len(placed)]
            arrays = (source, path, target, mask, label,
                      chain_weight(weight, token))
            out = trainer.eval_step_placed(params, arrays)
            token = out['loss_sum']
        return float(token)

    run_chain(WARMUP_STEPS)
    start = time.perf_counter()
    run_chain(MEASURE_STEPS)
    elapsed = time.perf_counter() - start
    per_chip = (MEASURE_STEPS * SHAPES.batch_size / elapsed
                / len(jax.devices()))
    return per_chip, engaged


def run_variant(variant: str) -> None:
    """Child mode: one A/B arm in this process. Prints the same JSON lines
    the old single-process harness did."""
    import jax
    benchlib.honor_env_platforms()
    platform = jax.devices()[0].platform.lower()
    use_pallas = variant == 'pallas'
    if not SMOKE:
        from code2vec_tpu.ops.pallas_encode import tpu_backend_active
        if not tpu_backend_active():
            # The Pallas route requires device platform 'tpu'; measuring
            # anything else would end in a guaranteed-invalid verdict
            # after minutes of compile + measurement.
            print(json.dumps({'error': 'tpu_unavailable',
                              'detail': f'platform={platform}'}), flush=True)
            sys.exit(2)
    try:
        examples_per_sec, engaged = measure(use_pallas)
    except Exception as exc:  # a kernel compile failure IS the answer
        print(json.dumps({'variant': variant, 'error': str(exc)[:300]}),
              flush=True)
        sys.exit(1)
    if use_pallas and not engaged and not SMOKE:
        # (SMOKE runs off-TPU where the kernel routes to the
        # interpreter or not at all; engagement is a TPU-only check)
        print(json.dumps({
            'variant': variant, 'error': 'kernel_not_engaged',
            'detail': 'compiled eval HLO has no Pallas custom-call; '
                      'the A/B would compare XLA against itself'}),
            flush=True)
        sys.exit(3)
    metric = ('eval_examples_per_sec_SMOKE_ONLY' if SMOKE
              else 'eval_examples_per_sec_per_chip_java14m')
    if _contexts:
        metric += f'_c{_contexts}'  # non-headline bag size
    print(json.dumps({
        'metric': metric,
        'variant': variant,
        'value': round(examples_per_sec, 1),
        'unit': 'examples/sec/chip'}), flush=True)


def main() -> None:
    """Parent: each variant in its own subprocess under a per-arm timeout,
    so a Mosaic compile stall (the observed C=1024 failure mode — 900 s
    stage timeout burned with nothing to show, round-3 capture log) costs
    one arm, not the whole healthy window. The parent imports no jax and
    never touches the tunnel itself."""
    variant = os.environ.get('BENCH_PALLAS_ENCODE_VARIANT', '')
    if variant:
        run_variant(variant)
        return
    import subprocess
    per_arm = float(os.environ.get('BENCH_PALLAS_ARM_TIMEOUT',
                                   '240' if SMOKE else '780'))
    results = {}
    for variant in ('xla', 'pallas'):
        env = dict(os.environ, BENCH_PALLAS_ENCODE_VARIANT=variant)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=per_arm)
            out, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as e:
            out = (e.stdout.decode(errors='replace')
                   if isinstance(e.stdout, bytes) else (e.stdout or ''))
            rc = -1
            print(json.dumps({'variant': variant,
                              'error': 'arm_timeout',
                              'timeout_s': per_arm}), flush=True)
        for line in out.splitlines():
            line = line.strip()
            if not line.startswith('{'):
                continue
            print(line, flush=True)
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get('variant') == variant and 'value' in rec:
                results[variant] = rec['value']
            if rec.get('error') == 'tpu_unavailable':
                # nonzero: the watcher must keep this stage pending.  A
                # bare return here exited 0, so a wedge between the xla
                # and pallas arms done-marked a half-captured A/B with
                # no pallas arm and no verdict (advisor r4, medium).
                sys.exit(2)
        if rc != 0 and variant == 'pallas':
            print(json.dumps({'verdict': 'keep-xla',
                              'reason': 'pallas arm failed or timed out'}),
                  flush=True)
            # nonzero exit keeps the watcher stage PENDING: this verdict
            # is a placeholder, not a measured A/B — a later window must
            # retry rather than lock it in
            sys.exit(4)
        if rc != 0:
            sys.exit(4)
    if 'xla' in results and 'pallas' in results:
        speedup = results['pallas'] / results['xla']
        print(json.dumps({
            'verdict': 'keep-pallas' if speedup > 1.02 else 'keep-xla',
            'speedup': round(speedup, 4)}), flush=True)


if __name__ == '__main__':
    main()
