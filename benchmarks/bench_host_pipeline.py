"""Host input-pipeline benchmark: lines/sec through ``reader.iter_epoch``.

SURVEY.md §7 hard part #3: the host loader must not starve a v3-32 — the
north star is ~18,700 examples/sec aggregate (BASELINE.json), i.e. the
whole pod's appetite served by the input hosts. This benchmark measures
the three host paths on synthetic java14m-shaped data (200 contexts/row):

- ``python``  — pure-Python parse + dict-lookup tokenization
- ``native``  — C++ tokenizer (indices in C++, zero Python inner loop)
- ``cache``   — binary token cache steady state (epoch 2+: sequential
                disk reads + chunk shuffling, no tokenization at all)

Prints one JSON line per variant:
  {"metric": "host_pipeline_examples_per_sec", "variant": ..., "value": ...,
   "vs_north_star": ...}

Usage: python benchmarks/bench_host_pipeline.py [--rows N] [--contexts C]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

NORTH_STAR_EXAMPLES_PER_SEC = 18700.0


def synthesize_dataset(prefix: str, rows: int, contexts: int,
                       n_tokens: int = 2000, n_paths: int = 3000,
                       n_labels: int = 500, seed: int = 0) -> None:
    """java14m-shaped rows: space-padded to exactly ``contexts`` fields.

    Row lengths draw from [C/8, C/2] — most slots padding, like the real
    corpus (contexts/method p50 28 of 200, corpus_stats_r4.json); the
    wire-format byte comparison below is only honest at a realistic
    fill."""
    import pickle
    rng = random.Random(seed)
    tokens = [f'tok{i}' for i in range(n_tokens)]
    paths = [str(rng.getrandbits(31)) for _ in range(n_paths)]
    labels = [f'do|thing|{i}' for i in range(n_labels)]
    with open(prefix + '.train.c2v', 'w') as f:
        for _ in range(rows):
            n = rng.randint(max(1, contexts // 8), max(2, contexts // 2))
            ctxs = ' '.join(
                f'{rng.choice(tokens)},{rng.choice(paths)},{rng.choice(tokens)}'
                for _ in range(n))
            f.write(f'{rng.choice(labels)} {ctxs}{" " * (contexts - n)}\n')
    with open(prefix + '.dict.c2v', 'wb') as f:
        pickle.dump({t: 10 for t in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({l: 10 for l in labels}, f)
        pickle.dump(rows, f)


def consume(batches) -> int:
    total = 0
    for batch in batches:
        total += batch.num_valid_examples
    return total


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--rows', type=int, default=20000)
    parser.add_argument('--contexts', type=int, default=200)
    parser.add_argument('--batch-size', type=int, default=1024)
    parser.add_argument('--variants', default='python,native,cache,wire')
    args = parser.parse_args()

    from code2vec_tpu.config import Config
    from code2vec_tpu.data import native
    from code2vec_tpu.data.cache import TokenCache
    from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
    from code2vec_tpu.vocab import Code2VecVocabs

    workdir = tempfile.mkdtemp(prefix='c2v_hostbench_')
    try:
        prefix = os.path.join(workdir, 'synth')
        synthesize_dataset(prefix, args.rows, args.contexts)

        def make(use_native: bool):
            config = Config(TRAIN_DATA_PATH_PREFIX=prefix, VERBOSE_MODE=0,
                            MAX_CONTEXTS=args.contexts,
                            TRAIN_BATCH_SIZE=args.batch_size,
                            READER_USE_NATIVE=use_native)
            vocabs = Code2VecVocabs(config)
            reader = PathContextReader(vocabs, config, EstimatorAction.Train)
            return config, vocabs, reader

        results = {}
        variants = args.variants.split(',')

        if 'python' in variants:
            _, _, reader = make(use_native=False)
            start = time.perf_counter()
            n = consume(reader.iter_epoch(shuffle=False))
            results['python'] = n / (time.perf_counter() - start)

        if 'native' in variants and native.is_available():
            _, _, reader = make(use_native=True)
            assert reader._native is not None
            start = time.perf_counter()
            n = consume(reader.iter_epoch(shuffle=False))
            results['native'] = n / (time.perf_counter() - start)

        if 'cache' in variants:
            config, vocabs, reader = make(use_native=native.is_available())
            cache = TokenCache.build_or_load(config, vocabs, reader)
            consume(cache.iter_epoch(args.batch_size))  # warm page cache
            start = time.perf_counter()
            n = consume(cache.iter_epoch(args.batch_size, shuffle=True,
                                         seed=1))
            results['cache'] = n / (time.perf_counter() - start)

        for variant, examples_per_sec in results.items():
            print(json.dumps({
                'metric': 'host_pipeline_examples_per_sec',
                'variant': variant,
                'value': round(examples_per_sec, 1),
                'unit': 'examples/sec',
                'vs_north_star': round(
                    examples_per_sec / NORTH_STAR_EXAMPLES_PER_SEC, 3),
            }))

        if 'wire' in variants:
            # bytes/batch each wire format puts on the host->device link
            # over this corpus — the CPU-provable half of the packed
            # format's transfer win (tests/test_host_pipeline_bench.py
            # guards packed <= 50% of planes so it can't silently
            # regress without a TPU)
            from code2vec_tpu.data import packed as packed_lib
            config, vocabs, reader = make(use_native=False)
            totals = {'planes': 0, 'packed': 0}
            batches = 0
            for batch in reader.iter_epoch(shuffle=False):
                totals['planes'] += packed_lib.wire_bytes(batch)
                totals['packed'] += packed_lib.wire_bytes(
                    packed_lib.pack_batch(
                        batch, vocabs.token_vocab.pad_index,
                        vocabs.path_vocab.pad_index))
                batches += 1
            for fmt in ('planes', 'packed'):
                print(json.dumps({
                    'metric': 'wire_bytes_per_batch',
                    'variant': fmt,
                    'value': round(totals[fmt] / max(batches, 1), 1),
                    'unit': 'bytes/batch',
                    'vs_planes': round(totals[fmt] / max(totals['planes'],
                                                         1), 3),
                }))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == '__main__':
    main()
