"""Measure the per-step cost of the telemetry layer in the REAL hot loop
(``Trainer.fit``), telemetry off vs on — the ISSUE 2 acceptance bound is
<1% overhead for the DISABLED path (which must reduce to ``is None``
checks) and the enabled path is reported alongside for honesty.

Methodology: ONE trainer (one compiled step program — building separate
trainers per arm was measured to add ~±10% inter-build variance on CPU,
swamping the signal), with the trainer's telemetry handle toggled
between INTERLEAVED fit windows; the headline per-arm number is the MIN
window (scheduler noise only ever adds time, so min strips it while the
systematic instrumentation cost survives), with the median reported as
the noise floor.

Prints one JSON line per measurement:

  step_ms_fit_telemetry_off   fastest fit window per step, telemetry off
  step_ms_fit_telemetry_on    same trainer/program, telemetry recording +
                              exporters into a temp dir (console line
                              rate-limited away)
  telemetry_overhead_pct      (on - off) / off * 100

BENCH_SMOKE=1 shrinks shapes for CPU validation (same convention as
bench.py).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
STEPS = 8 if SMOKE else 40
REPEATS = 7 if SMOKE else 5


def main() -> None:
    import statistics

    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower(),
                      'smoke': SMOKE, 'steps_per_window': STEPS,
                      'windows_per_arm': REPEATS}), flush=True)
    with tempfile.TemporaryDirectory() as tmp_dir:
        config = benchlib.headline_config(
            SHAPES, NUM_TRAIN_EPOCHS=1,
            NUM_BATCHES_TO_LOG_PROGRESS=max(2, STEPS // 2),
            TELEMETRY=True, TELEMETRY_DIR=tmp_dir,
            TELEMETRY_FLUSH_EVERY_STEPS=max(2, STEPS // 2),
            TELEMETRY_CONSOLE_EVERY_SECS=3600.0)
        trainer, state = benchlib.build_trainer(config, SHAPES)
        tele = trainer._telemetry
        batches = benchlib.random_batches(SHAPES, STEPS)
        # warmup epoch: compiles + capacity stickiness land here
        state = trainer.fit(state, lambda epoch: iter(batches))

        sw = benchlib.bench_timer('fit')
        windows = {'off': [], 'on': []}
        for _rep in range(REPEATS):
            # interleaved arms decorrelate slow machine-state drift
            for label, handle in (('off', None), ('on', tele)):
                trainer._telemetry = handle
                with sw.time():
                    state = trainer.fit(state,
                                        lambda epoch: iter(batches))
                windows[label].append(sw.last)
        trainer._telemetry = tele

        results = {}
        for label in ('off', 'on'):
            per_step = min(windows[label]) / STEPS
            results[label] = per_step
            print(json.dumps(
                {'measure': 'step_ms_fit_telemetry_%s' % label,
                 'value': round(per_step * 1e3, 3),
                 'p50': round(statistics.median(windows[label])
                              / STEPS * 1e3, 3)}), flush=True)
        off, on = results['off'], results['on']
        print(json.dumps({'measure': 'telemetry_overhead_pct',
                          'value': round((on - off) / off * 100, 2)}),
              flush=True)


if __name__ == '__main__':
    main()
