"""Capture the goodput plane's own numbers on the REAL hot loop
(``Trainer.fit`` with telemetry on): the achieved MFU, the goodput
fraction, and the badput breakdown of a steady-state fit window —
the observability layer measuring itself, so a capture records what
"healthy" looks like on this hardware and a later regression has a
baseline to flip against.

Methodology: one trainer, one warmup fit (compiles + capacity
stickiness land there, and are REPORTED as the warmup arm's badput
story), then a measured steady-state fit.  Each fit is one run span in
the ledger; the measures come from that span's ``run_end`` totals and
the MFU gauge of its last flush window — the same numbers
``scripts/goodput_report.py`` renders.

Prints one JSON line per measurement:

  mfu                     model FLOP utilization of the steady fit,
                          last flush window (DEVICE_PEAK_FLOPS
                          denominator — see telemetry/goodput.py)
  goodput_fraction        productive seconds / wall seconds of the
                          steady fit span
  badput_compile_pct      compile badput share of the steady span
  badput_input_wait_pct   input-wait badput share of the steady span
  arithmetic_intensity    train-step FLOPs per HBM byte (AOT
                          cost_analysis)

BENCH_SMOKE=1 shrinks shapes for CPU validation (same convention as
bench.py).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
STEPS = 8 if SMOKE else 40


def _spans(intervals_path):
    """Run spans in ledger order, each with its cumulative ``run_end``
    totals and the last finite window MFU inside the span."""
    spans, current = [], None
    with open(intervals_path) as f:
        for line in f:
            try:
                record = json.loads(line)
            except ValueError:
                continue
            kind = record.get('kind')
            if kind == 'run_start':
                current = {'end': None, 'mfu': None}
            elif current is None:
                continue
            elif kind == 'window' and record.get('mfu'):
                current['mfu'] = record['mfu']
            elif kind == 'run_end':
                current['end'] = record
                spans.append(current)
                current = None
    return spans


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower(),
                      'smoke': SMOKE, 'steps_per_window': STEPS}),
          flush=True)
    with tempfile.TemporaryDirectory() as tmp_dir:
        config = benchlib.headline_config(
            SHAPES, NUM_TRAIN_EPOCHS=1,
            NUM_BATCHES_TO_LOG_PROGRESS=max(2, STEPS // 2),
            TELEMETRY=True, TELEMETRY_DIR=tmp_dir,
            TELEMETRY_FLUSH_EVERY_STEPS=max(2, STEPS // 2),
            TELEMETRY_CONSOLE_EVERY_SECS=3600.0)
        trainer, state = benchlib.build_trainer(config, SHAPES)
        tele = trainer._telemetry
        batches = benchlib.random_batches(SHAPES, STEPS)
        # warmup fit: compiles land in this span's badput, not the
        # measured one's
        state = trainer.fit(state, lambda epoch: iter(batches))
        # steady-state fit: the measured span
        state = trainer.fit(state, lambda epoch: iter(batches))

        spans = _spans(os.path.join(tmp_dir, 'intervals.jsonl'))
        steady, warm = spans[-1], (spans[-2] if len(spans) > 1 else None)
        # run_end totals are per-LEDGER cumulative (one ledger spans
        # both fits); the steady span's own story is its run_end minus
        # the warmup span's
        def delta(field):
            after = steady['end'].get(field, 0.0)
            before = warm['end'].get(field, 0.0) if warm else 0.0
            return after - before

        wall = max(delta('wall_s'), 1e-9)
        print(json.dumps({'measure': 'mfu',
                          'value': round(steady['mfu'] or 0.0, 5)}),
              flush=True)
        print(json.dumps({'measure': 'goodput_fraction',
                          'value': round(delta('productive_s') / wall,
                                         5)}), flush=True)
        steady_badput = steady['end'].get('badput_s', {})
        warm_badput = warm['end'].get('badput_s', {}) if warm else {}
        for kind in ('compile', 'input_wait'):
            secs = steady_badput.get(kind, 0.0) \
                - warm_badput.get(kind, 0.0)
            print(json.dumps(
                {'measure': 'badput_%s_pct' % kind,
                 'value': round(100.0 * secs / wall, 3)}), flush=True)
        print(json.dumps(
            {'measure': 'arithmetic_intensity',
             'value': round(tele.goodput.arithmetic_intensity() or 0.0,
                            3)}), flush=True)


if __name__ == '__main__':
    main()
