"""Summarize benchmarks/results/*.jsonl captures into one table.

The unattended watcher (watch_and_capture.sh) appends stage-wrapped JSON
lines ({"stage", "rc", "secs", "data": {...}}) across rare healthy tunnel
windows; the interactive harnesses emit raw measure lines. This collates
both shapes so the A/B verdicts (rbg dropout, embed-grad, fused CE,
bf16-mu, Pallas C=1024) can be read off — and defaults flipped on
evidence — without re-parsing JSONL by hand.

Run: python benchmarks/summarize_captures.py [--dir benchmarks/results]
"""
from __future__ import annotations

import argparse
import json
import os


def iter_records(path: str):
    with open(path) as f:
        for raw in f:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            stage = rec.get('stage')
            # a durable wedged-tunnel reason record (capture_all.sh's
            # probe_or_record): surface it EXPLICITLY — a wedged round
            # must read as a gap with a reason in the bench trajectory,
            # not as a silently empty file (PRs 4-5 on-chip numbers are
            # owed to exactly this mode)
            if 'tpu_unavailable' in rec:
                yield stage, rec.get('rc'), {
                    'measure': 'TPU UNAVAILABLE',
                    'value': rec['tpu_unavailable'],
                    'attempts': rec.get('attempts'),
                    'secs': rec.get('secs')}
                continue
            data = rec.get('data') if isinstance(rec.get('data'), dict) \
                else (rec if 'stage' not in rec else None)
            # a stage wrapper with null data is a FAILED stage (run_stage
            # writes it when the stage produced no JSON) — surface it,
            # silence here would read as "stage not run yet"
            if data is None and stage is not None:
                yield stage, rec.get('rc'), {'measure': 'STAGE FAILED',
                                             'value': None,
                                             'secs': rec.get('secs')}
            elif data is not None:
                yield stage, rec.get('rc'), data


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--dir', default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'results'))
    args = parser.parse_args()

    names = sorted(n for n in os.listdir(args.dir) if n.endswith('.jsonl'))
    wedged_rounds = 0
    for name in names:
        print(f'== {name}')
        measured = False
        for stage, rc, data in iter_records(os.path.join(args.dir, name)):
            label = (data.get('measure') or data.get('metric')
                     or data.get('probe') or next(iter(data), '?'))
            value = data.get('value')
            extras = {k: v for k, v in data.items()
                      if k in ('examples_per_sec', 'unit', 'vs_baseline',
                               'variant', 'devices', 'opt_sharding',
                               'speedup', 'verdict', 'distribution',
                               'step_ms', 'partition_overhead_vs_1dev',
                               'attempts', 'phase', 'tier', 'bucket',
                               'p50', 'p99',
                               # ragged-fusion A/B axes (ISSUE 10): the
                               # fused-vs-unfused step-time records key
                               # on these to be comparable across
                               # capture rounds; 'kind' disambiguates
                               # train vs train_bwd arms (ISSUE 12)
                               'fill', 'contexts', 'kind',
                               # the memory axis (ISSUE 9): per-stage
                               # peak HBM; None = stats-less backend,
                               # an explicit gap. 'temp_bytes' is the
                               # grad program's AOT temp allocation —
                               # the residual footprint the custom-VJP
                               # recompute backward cuts (ISSUE 12)
                               'peak_hbm_bytes', 'hbm_bytes_in_use',
                               'temp_bytes',
                               # serving-mesh load axes (ISSUE 13):
                               # p99-at-offered-load keyed by replica
                               # count, with shed rate, per-replica
                               # device fill, and the postwarm-compile
                               # check riding each arm record
                               'replicas', 'offered_rows_per_sec',
                               'p50_ms', 'p99_ms', 'shed_rate',
                               'per_replica_fill', 'dispatch_share',
                               'postwarm_compiles', 'host_cores',
                               # memoization-tier arms (ISSUE 16):
                               # cache-served vs live p99 keyed by the
                               # memo arm + Zipf shape, with the
                               # device-work-saved column the tier is
                               # judged on
                               # elastic-fleet transitions (ISSUE 18):
                               # scale-up/scale-down latency and the
                               # transition-vs-steady p99 from the
                               # stepped-load arm, plus the soak's
                               # elastic drill columns
                               'steady_p99_ms', 'up_p99_ms',
                               'down_p99_ms', 'scale_up_total',
                               'scale_down_total',
                               'reached_2_replicas',
                               'drained_to_1_replica',
                               'flap_freezes_total', 'retired_reason',
                               'rid',
                               'process_capacity_rows_per_sec_1r',
                               'memo', 'zipf_alpha', 'hit_rate',
                               'cache_p99_ms', 'live_p99_ms',
                               'semantic_hits', 'semantic_agreement',
                               'device_seconds_per_1k_requests',
                               # goodput plane (ISSUE 17): steady-state
                               # MFU / goodput fraction / badput shares
                               # of the real hot loop, the baseline a
                               # goodput regression flips against
                               'mfu', 'goodput_fraction',
                               'badput_compile_pct',
                               'badput_input_wait_pct',
                               'arithmetic_intensity',
                               'steps_per_window',
                               # quantized index tier (ISSUE 19):
                               # int8/pq arms keyed by 'kind' (above)
                               # — QPS rides 'value'; the bytes/vector
                               # and compression columns are the <=1/4-
                               # of-f16 acceptance, 'self_hit_at1' the
                               # insert arm's queryable-now check
                               'device_bytes_per_vector',
                               'f16_bytes_per_vector',
                               'compression_vs_f16', 'rerank',
                               'nprobe', 'rows', 'self_hit_at1',
                               'segments',
                               # scenario traffic plane (ISSUE 20):
                               # per-scenario x per-language replay
                               # quality, memo hit-rate, shed, and the
                               # retrieval-vs-softmax A/B columns
                               'scenario', 'language', 'exact_match',
                               'f1', 'memo_hit_rate', 'delivered',
                               'shed', 'blend_weight',
                               'softmax_exact', 'retrieval_exact',
                               'softmax_f1', 'retrieval_f1',
                               'availability_burn_share',
                               'p99_burn_share', 'admitted')}
            prefix = f'  [{stage}]' if stage else '  '
            flag = '' if not rc else f'  (rc={rc})'
            if label not in ('TPU UNAVAILABLE', 'STAGE FAILED'):
                measured = True
            print(f'{prefix} {label}: {value} '
                  + ' '.join(f'{k}={v}' for k, v in extras.items()) + flag)
        if not measured:
            wedged_rounds += 1
            print('  (no measurements this round — an explicit GAP in '
                  'the bench trajectory, not a skipped capture)')
    if wedged_rounds:
        print(f'\n{wedged_rounds}/{len(names)} round(s) produced no '
              'measurements (wedged tunnel / failed stages above).')
    print('\nDecision rule (PERF.md): a knob flips default only on a '
          '>=2% measured step-time win at the java14m config; ties keep '
          'reference-parity behavior.')


if __name__ == '__main__':
    main()
