"""Weak scaling on virtual CPU meshes + analytic ICI projection
(VERDICT r2 next #5).

Part 1 — measurement: fixed per-device batch over 1/2/4/8 virtual CPU
devices (data-parallel axis). Virtual devices SHARE the host's cores, so
absolute throughput cannot scale — what this measures is the SPMD
partitioning overhead: with perfect partitioning, t(N) == N * t(1) on a
fixed core budget, and

    overhead(N) = t(N) / (N * t(1)) - 1

is the fraction the gradient psum + sharded-program bookkeeping add on
top of the N-fold compute. That overhead is the piece of multi-chip
scaling this environment CAN falsify (collective deadlocks, pathological
partitions, per-shard recompilation); the ICI part is projected
analytically below from on-chip measurements.

Part 2 — projection (--project): aggregate examples/sec for a v5e-pod
data-parallel mesh at the java14m config, from measured constants:
  * 49.25 ms/chip/step at B=1024 (PERF.md, 2026-07-29 capture)
  * grad psum bytes/step = fp32 grads for 384.4M params = 1.538 GB
  * ring all-reduce moves 2*(N-1)/N * bytes over each chip's ICI links
Overlap assumption: XLA overlaps the psum of layer k's grads with the
backward of layer k-1; the model has effectively 2 big "layers" (tables,
dense), so we project both a fully-overlapped and a zero-overlap bound.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/weak_scaling.py [--project]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# ---- measured constants (PERF.md / BASELINE.json) ----
STEP_MS_PER_CHIP = 49.25        # java14m B=1024, v5e-class, 2026-07-29
BATCH_PER_CHIP = 1024
PARAM_COUNT = 384.4e6           # java14m tables + dense
GRAD_BYTES = PARAM_COUNT * 4    # fp32 grads
# v5e: 4 ICI links/chip x ~45 GB/s each direction (public v5e specs);
# a 2D-torus ring all-reduce sustains ~1 link pair per ring direction
ICI_GBPS_PER_LINK = 45e9
NORTH_STAR_AGG = 18700.0        # BASELINE.json multi-chip reference point


def measure(per_device_batch: int = 64,
            opt_sharding: str = 'mirror') -> None:
    import jax

    from code2vec_tpu import benchlib

    benchlib.honor_env_platforms()  # the sitecustomize preimport pins the
    # platform before this process's JAX_PLATFORMS=cpu is read
    results = []
    n_max = len(jax.devices())
    for n in (1, 2, 4, 8):
        if n > n_max:
            break
        shapes = benchlib.SMOKE_SHAPES._replace(
            batch_size=per_device_batch * n)
        # dtype knobs pinned to the values the committed r3/r5 artifacts
        # were measured under, so re-runs stay comparable as config
        # defaults move (a clean-host isolate showed the nu flip itself
        # is step-time-neutral on virtual CPU meshes —
        # weak_scaling_r5_postflip_note.jsonl)
        config = benchlib.headline_config(
            shapes, COMPUTE_DTYPE='float32', MESH_DATA_AXIS_SIZE=n,
            MESH_MODEL_AXIS_SIZE=1,
            OPTIMIZER_STATE_SHARDING=opt_sharding,
            DROPOUT_PRNG_IMPL='threefry2x32', ADAM_MU_DTYPE='float32',
            ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32')
        from code2vec_tpu.models.backends import create_backend
        from code2vec_tpu.parallel import mesh as mesh_lib
        from code2vec_tpu.training.trainer import Trainer
        from code2vec_tpu.vocab import SizeOnlyVocabs
        backend = create_backend(config, SizeOnlyVocabs(
            shapes.token_vocab, shapes.path_vocab, shapes.target_vocab))
        mesh = mesh_lib.create_mesh(config, devices=jax.devices()[:n])
        trainer = Trainer(config, backend, mesh=mesh)
        state = trainer.init_state(seed=0)
        feeds = benchlib.staged(trainer, benchlib.random_batches(shapes, 4))
        for i in range(3):
            state, loss = trainer.train_step_placed(state,
                                                    feeds[i % len(feeds)])
            float(loss)
        # best-of-3 repeats: shared-core virtual devices time-share with
        # whatever else the host runs, so a single 10-step sample can
        # absorb a transient load spike (the round-4 4-device +69.9%
        # outlier, VERDICT r4 weak #6/#9). The minimum is the estimate
        # least contaminated by foreign load; all repeats + the host
        # load average are recorded as provenance.
        repeat_ms = []
        for _ in range(3):
            t0 = time.perf_counter()
            last = None
            steps = 10
            for i in range(steps):
                state, last = trainer.train_step_placed(
                    state, feeds[i % len(feeds)])
            float(last)
            repeat_ms.append((time.perf_counter() - t0) / steps * 1e3)
        dt = min(repeat_ms) / 1e3
        results.append((n, dt))
        base = results[0][1]
        overhead = dt / (n * base) - 1 if n > 1 else 0.0
        print(json.dumps({
            'measure': 'weak_scaling_virtual_cpu',
            'devices': n,
            'per_device_batch': per_device_batch,
            'opt_sharding': opt_sharding,
            'step_ms': round(dt * 1e3, 2),
            'repeat_step_ms': [round(r, 2) for r in repeat_ms],
            'loadavg_1m': round(os.getloadavg()[0], 2),
            'partition_overhead_vs_1dev': round(overhead, 4),
            # VERDICT r3 weak #5: virtual devices share one host's cores,
            # so N*t(1) is inflated by fixed per-step overheads that
            # amortize at N>1 — negative values are an artifact of the
            # normalizer, not free collectives. This harness falsifies
            # deadlocks/recompilation; it cannot resolve a genuine
            # few-percent collective overhead.
            'normalizer': 'min of 3 repeats vs N*t(1); t(1) inflated by '
                          'fixed overheads on shared-core virtual '
                          'devices; negative overhead is not a real '
                          'win'}), flush=True)


def project() -> None:
    """Aggregate-throughput projection for data-parallel v5e meshes."""
    for n in (4, 8, 16, 32, 64):
        # bidirectional ring over the data axis: each chip sends+receives
        # 2*(N-1)/N * GRAD_BYTES split across 2 ring directions
        ring_bytes = 2 * (n - 1) / n * GRAD_BYTES
        ici_ms = ring_bytes / (2 * ICI_GBPS_PER_LINK) * 1e3
        step = STEP_MS_PER_CHIP
        best = max(step, ici_ms)          # full compute/comm overlap
        worst = step + ici_ms             # zero overlap
        agg_best = n * BATCH_PER_CHIP / (best / 1e3)
        agg_worst = n * BATCH_PER_CHIP / (worst / 1e3)
        print(json.dumps({
            'measure': 'ici_projection_v5e_dp',
            'chips': n,
            'grad_allreduce_ms': round(ici_ms, 2),
            'agg_examples_per_sec_overlapped': round(agg_best, 0),
            'agg_examples_per_sec_no_overlap': round(agg_worst, 0),
            'vs_north_star_18700': round(agg_best / NORTH_STAR_AGG, 2)},
        ), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--project', action='store_true',
                        help='print the analytic ICI projection only')
    parser.add_argument('--per-device-batch', type=int, default=64)
    parser.add_argument('--opt-sharding', choices=['mirror', 'zero'],
                        default='mirror',
                        help="moment layout (ZeRO-1 'zero' adds the "
                             'reduce-scatter/all-gather pair this '
                             'harness then prices)')
    args = parser.parse_args()
    if args.project:
        project()
    else:
        measure(args.per_device_batch, args.opt_sharding)
        project()


if __name__ == '__main__':
    main()
