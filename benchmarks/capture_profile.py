"""Capture on-chip evidence for the train step: a jax.profiler trace plus
the compiled step's XLA cost analysis (FLOPs / bytes accessed), at the
java14m headline configuration.

Outputs:
  profiles/java14m_step/...   profiler trace (TensorBoard/Perfetto viewable)
  one JSON line per artifact on stdout

The cost analysis is the roofline input: with ~0.9 TFLOP of matmul work and
~11 GB of HBM traffic per step (dense Adam over 384M params dominates), the
measured ~49 ms step sits near the HBM bound, not the MXU bound (PERF.md).
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SHAPES = benchlib.JAVA14M


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)
    # A failed artifact must fail the STAGE: the watcher done-marks on
    # rc=0 + any fresh JSON line, and the platform line above would
    # otherwise done-mark a capture whose trace/cost analysis both died
    # (advisor finding, round 5).
    failed = []

    config = benchlib.headline_config(SHAPES)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    (arrays, _), = trainer.stage_batches(iter(benchlib.random_batches(
        SHAPES, 1)))

    # --- XLA cost analysis of the compiled train step
    compiled = trainer._train_step.lower(state, arrays).compile()
    try:
        cost = compiled.cost_analysis()
        cost = cost[0] if isinstance(cost, (list, tuple)) else cost
        flops = float(cost.get('flops', 0.0))
        bytes_accessed = float(cost.get('bytes accessed', 0.0))
        print(json.dumps({
            'artifact': 'train_step_cost_analysis',
            'gflops_per_step': round(flops / 1e9, 1),
            'gbytes_accessed_per_step': round(bytes_accessed / 1e9, 2)}),
            flush=True)
    except Exception as exc:
        failed.append('cost_analysis')
        print(json.dumps({'artifact': 'train_step_cost_analysis',
                          'error': str(exc)[:200]}), flush=True)

    # --- profiler trace over a few chained steps
    trace_dir = os.path.join(REPO, 'profiles', 'java14m_step')
    os.makedirs(trace_dir, exist_ok=True)
    for _ in range(5):  # warmup
        state, loss = trainer.train_step_placed(state, arrays)
    float(loss)
    try:
        jax.profiler.start_trace(trace_dir)
        for _ in range(5):
            state, loss = trainer.train_step_placed(state, arrays)
        float(loss)
        jax.profiler.stop_trace()
        files = []
        for root, _dirs, names in os.walk(trace_dir):
            files += [os.path.relpath(os.path.join(root, n), trace_dir)
                      for n in names]
        print(json.dumps({'artifact': 'profiler_trace', 'dir': trace_dir,
                          'n_files': len(files),
                          'files': sorted(files)[:8]}), flush=True)
    except Exception as exc:
        failed.append('profiler_trace')
        print(json.dumps({'artifact': 'profiler_trace',
                          'error': str(exc)[:300]}), flush=True)

    # --- timed reference point alongside the artifacts
    start = time.perf_counter()
    for _ in range(20):
        state, loss = trainer.train_step_placed(state, arrays)
    float(loss)
    step_ms = (time.perf_counter() - start) / 20 * 1e3
    print(json.dumps({'artifact': 'step_time_ms',
                      'value': round(step_ms, 2)}), flush=True)
    if failed:
        sys.exit(2)   # keep the stage pending for a later healthy window


if __name__ == '__main__':
    main()
