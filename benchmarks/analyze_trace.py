"""Offline step decomposition from a committed jax.profiler xplane trace.

VERDICT r3 #3 wanted the frozen-tables diag to isolate the scatter-add
share of the HBM gap; the tunnel stayed wedged, but the round-2 trace
(`profiles/java14m_step/`) already carries per-op `hlo_category`,
`bytes_accessed`, and Python `source` attribution — enough to answer the
question offline. This tool aggregates the XLA-Ops line of the TPU plane
into ms/step by category, by originating source line, and by op, and
emits one JSON artifact.

Source lines refer to the file state at the commit that captured the
trace (8253ac4); the semantic mapping for the java14m step:
  functional.py:113/115/116 -> token/path/target-token gathers and their
                               backward scatter-adds
  functional.py:156         -> transform matmul (+tanh)
  functional.py:191         -> logits matmul (code @ target_emb.T)
  functional.py:214         -> logsumexp CE
  optax update.py:43        -> the dense Adam update walk

Run: python benchmarks/analyze_trace.py \
        [--trace profiles/java14m_step] [--steps 5] [--out ...]
"""
from __future__ import annotations

import argparse
import collections
import glob
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_xspace(trace_dir: str):
    """Newest capture under ``trace_dir`` (the timestamped dir names sort
    chronologically, so [-1] is the latest — [0] would silently pin the
    analysis to the OLDEST committed trace forever once a re-capture
    lands, e.g. the post-flip profile_v2 stage writing next to the
    round-2 trace)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    paths = sorted(glob.glob(os.path.join(
        trace_dir, 'plugins', 'profile', '*', '*.xplane.pb')))
    if not paths:
        raise FileNotFoundError('no *.xplane.pb under %s' % trace_dir)
    xs = xplane_pb2.XSpace()
    with open(paths[-1], 'rb') as f:
        xs.ParseFromString(f.read())
    return xs, paths[-1]


def decompose(xs, steps: int) -> dict:
    plane = next(pl for pl in xs.planes if pl.name.endswith('TPU:0'))
    smeta = {k: v.name for k, v in plane.stat_metadata.items()}
    emeta = dict(plane.event_metadata.items())

    def stats_of(md):
        out = {}
        for st in md.stats:
            name = smeta[st.metadata_id]
            out[name] = (st.str_value if st.str_value
                         else st.int64_value or st.uint64_value
                         or st.double_value)
        return out

    line = next(l for l in plane.lines if l.name == 'XLA Ops')
    by_cat = collections.Counter()
    by_cat_bytes = collections.Counter()
    by_src = collections.Counter()
    total_ps = 0
    for event in line.events:
        md = emeta[event.metadata_id]
        ms = stats_of(md)
        dur = 0
        for st in event.stats:
            if smeta[st.metadata_id] == 'device_duration_ps':
                dur = st.int64_value or st.uint64_value
        cat = ms.get('hlo_category', '?')
        by_cat[cat] += dur
        by_cat_bytes[cat] += int(ms.get('bytes_accessed', 0) or 0)
        src = str(ms.get('source', '?'))
        if src.startswith(REPO):
            src = src[len(REPO) + 1:]
        by_src[src] += dur
        total_ps += dur

    def ms_per_step(ps):
        return round(ps / 1e9 / steps, 3)

    return {
        'device_op_ms_per_step': ms_per_step(total_ps),
        'by_hlo_category': {
            cat: {'ms_per_step': ms_per_step(ps),
                  'gb_per_step': round(by_cat_bytes[cat] / steps / 1e9, 3)}
            for cat, ps in by_cat.most_common() if ps > 0},
        'by_source_line': {
            src: ms_per_step(ps)
            for src, ps in by_src.most_common(20) if ps > 0},
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--trace', default=os.path.join(
        REPO, 'profiles', 'java14m_step'))
    parser.add_argument('--steps', type=int, default=5,
                        help='train steps inside the trace bracket')
    parser.add_argument('--out', default=os.path.join(
        REPO, 'benchmarks', 'results', 'trace_breakdown_r4.json'))
    args = parser.parse_args()
    xs, path = load_xspace(args.trace)
    result = {
        'measure': 'trace_step_breakdown',
        'trace': os.path.relpath(path, REPO),
        'steps_in_bracket': args.steps,
        'source_line_note': ('source attribution refers to the file state '
                             'at the trace-capturing commit (8253ac4)'),
        **decompose(xs, args.steps),
    }
    print(json.dumps(result))
    with open(args.out, 'w') as f:
        json.dump(result, f, indent=1)


if __name__ == '__main__':
    main()
