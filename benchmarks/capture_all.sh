#!/usr/bin/env bash
# One-shot on-chip capture orchestrator.
#
# The TPU tunnel here wedges for hours at a time; when it comes back the
# healthy window may be short. This script probes first, then runs every
# pending capture in priority order, each under its own hard timeout,
# appending raw results to benchmarks/results/capture_<date>.jsonl so a
# mid-run wedge still leaves durable artifacts.
#
# Every stage's JSON records now carry per-stage peak HBM
# (peak_hbm_bytes / hbm_bytes_in_use from the runtime's memory_stats —
# benchlib.device_memory_record, ISSUE 9), so the bench trajectory
# tracks footprint alongside throughput; summarize_captures.py surfaces
# both, and a stats-less backend reports an explicit null, not a
# missing column.
#
#   bash benchmarks/capture_all.sh
set -u
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y-%m-%dT%H%MZ)
OUT=benchmarks/results/capture_${STAMP}.jsonl
mkdir -p benchmarks/results

probe() {
  BENCH_CHILD=probe timeout 90 python bench.py 2>/dev/null
}

# Bounded retry-with-backoff around the tunnel probe: a transient blip
# (tunnel re-establishing, TPU runtime restarting) must not abort a
# whole capture round, but a genuinely wedged tunnel must fail FAST and
# LOUD — a durable `tpu_unavailable` record in the output (with the
# reason + where in the sequence it died) instead of a silently empty
# round. PRs 4-5 still owe their on-chip numbers to exactly this mode.
PROBE_ATTEMPTS=${PROBE_ATTEMPTS:-3}
PROBE_BACKOFF_SECS=${PROBE_BACKOFF_SECS:-15}

probe_or_record() {  # probe_or_record <where>  -> 0 healthy, 1 wedged
  local where=$1 attempt=1 backoff=${PROBE_BACKOFF_SECS} start=$(date +%s)
  while :; do
    if probe | grep -q '"probe"'; then
      return 0
    fi
    if [ "${attempt}" -ge "${PROBE_ATTEMPTS}" ]; then
      local secs=$(( $(date +%s) - start ))
      printf '{"stage": "probe", "tpu_unavailable": "probe failed %d/%d attempts (%s)", "attempts": %d, "secs": %d}\n' \
             "${attempt}" "${PROBE_ATTEMPTS}" "${where}" \
             "${attempt}" "${secs}" >> "${OUT}"
      echo "tunnel wedged ${where} (${attempt} probe attempts); see ${OUT}" >&2
      return 1
    fi
    echo "probe attempt ${attempt}/${PROBE_ATTEMPTS} failed (${where}); retrying in ${backoff}s" >&2
    sleep "${backoff}"
    backoff=$(( backoff * 2 ))
    attempt=$(( attempt + 1 ))
  done
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "--- stage: ${name}" >&2
  local start=$(date +%s)
  local out
  out=$(timeout "${tmo}" "$@" 2>/dev/null)
  local rc=$?
  local secs=$(( $(date +%s) - start ))
  # keep only JSON lines; tag each with the stage
  while IFS= read -r line; do
    case "${line}" in
      '{'*) printf '{"stage": "%s", "rc": %d, "secs": %d, "data": %s}\n' \
                   "${name}" "${rc}" "${secs}" "${line}" >> "${OUT}" ;;
    esac
  done <<< "${out}"
  if [ ${rc} -ne 0 ] && [ -z "${out}" ]; then
    printf '{"stage": "%s", "rc": %d, "secs": %d, "data": null}\n' \
           "${name}" "${rc}" "${secs}" >> "${OUT}"
  fi
  return ${rc}
}

probe_or_record "before any stage" || exit 3
echo "tunnel healthy; capturing to ${OUT}" >&2

# Priority order: the decisions blocked on each artifact, most important
# first. Re-probe between stages (bounded retry, durable reason record):
# a wedge mid-sequence should stop cheaply rather than eat the remaining
# timeouts.
run_stage bench 900 python bench.py
probe_or_record "after bench" || exit 3
run_stage diag 900 python benchmarks/diag_step_breakdown.py
probe_or_record "after diag" || exit 3
run_stage profile 600 python benchmarks/capture_profile.py
probe_or_record "after profile" || exit 3
run_stage pallas_ab 900 python benchmarks/bench_pallas_encode.py
probe_or_record "after pallas_ab" || exit 3
BENCH_CONTEXTS=1024 run_stage pallas_ab_c1024 900 \
  python benchmarks/bench_pallas_encode.py
probe_or_record "after pallas_ab_c1024" || exit 3
# ragged packed-wire fusion A/B (ISSUEs 10 + 12): packed train, train-
# BACKWARD (value_and_grad step time + grad-program AOT temp bytes, the
# custom-VJP recompute's residual axis) and predict step time AND
# per-arm peak HBM, across THREE arms: unfused (unpack-then-dense),
# fused (the SHIPPED default: fusion + custom-VJP twin train), and
# fused_kernel (+ RAGGED_TRAIN_KERNEL, the Pallas train pair). The
# fusion speedups confirm the default flip vs unpack; the kernel
# verdict (ragged_train_kernel_speedup) compares the pair against the
# fused twin it would replace — first at the java14m headline fill,
# then the fused path's best case (high max_contexts, low fill, where
# the dense planes are mostly padding). scripts/flip_verdict.py
# settles the >=2% flips from these records after the round.
# Per-arm timeout pinned so all THREE arms fit inside the 1300 s stage
# budget (the default 780 s/arm would let one stalled arm eat the
# stage); watch_and_capture.sh carries the big-budget variant for
# compile stalls that need it.
BENCH_PALLAS_ARM_TIMEOUT=390 run_stage pallas_ragged 1300 \
  python benchmarks/bench_pallas_ragged.py
probe_or_record "after pallas_ragged" || exit 3
BENCH_CONTEXTS=1024 BENCH_FILL=0.1 BENCH_PALLAS_ARM_TIMEOUT=390 \
  run_stage pallas_ragged_c1024 1300 \
  python benchmarks/bench_pallas_ragged.py
probe_or_record "after pallas_ragged_c1024" || exit 3
# serving engine A/B (ISSUE 4): naive per-request predict vs the
# micro-batching engine — on-chip latency p50/p99 + throughput; the
# traced arm (ISSUE 8) keeps its span log durable so the per-phase
# attribution survives the round
TRACE_DIR=benchmarks/results/serving_trace_${STAMP}
run_stage serving 900 python benchmarks/bench_serving.py \
  --trace-dir "${TRACE_DIR}"
# phase x bucket x tier p50/p95/p99 off the span log (jax-free, cheap)
if [ -f "${TRACE_DIR}/spans.jsonl" ]; then
  run_stage serving_latency 120 python scripts/latency_report.py \
    --spans "${TRACE_DIR}/spans.jsonl" --json
fi
probe_or_record "after serving" || exit 3
# serving mesh (ISSUE 13): fixed offered load against 1/2/4 replicas —
# sustained admitted throughput, p99-under-load, shed rate, per-replica
# device fill, dispatch share, and the zero-postwarm-compile check over
# the mixed predict + submit_neighbors stream
run_stage mesh 900 python benchmarks/bench_mesh.py
probe_or_record "after mesh" || exit 3
# memoization tier (ISSUE 16): Zipf-replayed duplicate-heavy traffic
# through memo off / exact / exact+semantic — hit rate, cache-served
# vs live p99, shed rate, device-seconds-per-1k-requests, and the
# zero-postwarm-compile check with the cache in front of the fleet
run_stage mesh_memo 900 python benchmarks/bench_mesh.py --zipf-alpha 1.1
probe_or_record "after mesh_memo" || exit 3
# elastic fleet (ISSUE 18): stepped offered load (low -> high -> low)
# against one process replica with the SLO/queue-driven autoscaler
# live — scale-up latency (decision + worker cold start), scale-down
# drain latency, and transition-vs-steady p99
run_stage mesh_stepped 900 python benchmarks/bench_mesh.py --stepped-load
probe_or_record "after mesh_stepped" || exit 3
# mesh chaos soak (ISSUE 14): paced load + periodic kill_worker/
# drop_heartbeat faults against socket-mode workers — zero lost
# admitted requests, zero post-warmup parent compiles, bounded p99
# while the supervisor keeps restoring capacity
run_stage mesh_soak 600 python scripts/mesh_soak.py --mode socket
probe_or_record "after mesh_soak" || exit 3
# embedding index (ISSUE 5): exact vs IVF throughput/recall curves +
# the naive numpy host-loop baseline
run_stage index 900 python benchmarks/bench_index.py --arms base
probe_or_record "after index" || exit 3
# quantized tier (ISSUE 19): f16 vs int8 vs PQ — QPS, recall@10,
# device bytes/vector, zero post-warmup compiles — plus the
# live-insert throughput arm
run_stage index_quant 900 python benchmarks/bench_index.py --arms quant
probe_or_record "after index_quant" || exit 3
# training goodput plane (ISSUE 17): steady-state MFU, goodput
# fraction, and badput shares of the real hot loop — the healthy
# baseline a later goodput regression flips against
run_stage goodput 900 python benchmarks/bench_goodput.py
probe_or_record "after goodput" || exit 3
# scenario traffic plane (ISSUE 20): mixed Java+C# recorded profile
# replayed against a live mesh — per-scenario x per-language
# exact-match/F1, memo hit-rate, shed, p99, per-scenario SLO budget
# burn, the retrieval-vs-softmax A/B verdict, and the zero-postwarm-
# compile check across the mixed-scenario steady state
run_stage scenarios 900 python benchmarks/accuracy_at_scale.py \
  --scenarios --workdir /tmp/acc_scenarios

# settle the queued >=2% flip verdicts from everything this round (and
# prior rounds) captured — durable rows in results/flip_verdicts.json.
# Non-fatal: a partial round still records PENDING with provenance.
python scripts/flip_verdict.py --write || true

echo "capture complete: ${OUT}" >&2
