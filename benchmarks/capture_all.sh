#!/usr/bin/env bash
# One-shot on-chip capture orchestrator.
#
# The TPU tunnel here wedges for hours at a time; when it comes back the
# healthy window may be short. This script probes first, then runs every
# pending capture in priority order, each under its own hard timeout,
# appending raw results to benchmarks/results/capture_<date>.jsonl so a
# mid-run wedge still leaves durable artifacts.
#
#   bash benchmarks/capture_all.sh
set -u
cd "$(dirname "$0")/.."

STAMP=$(date -u +%Y-%m-%dT%H%MZ)
OUT=benchmarks/results/capture_${STAMP}.jsonl
mkdir -p benchmarks/results

probe() {
  BENCH_CHILD=probe timeout 90 python bench.py 2>/dev/null
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "--- stage: ${name}" >&2
  local start=$(date +%s)
  local out
  out=$(timeout "${tmo}" "$@" 2>/dev/null)
  local rc=$?
  local secs=$(( $(date +%s) - start ))
  # keep only JSON lines; tag each with the stage
  while IFS= read -r line; do
    case "${line}" in
      '{'*) printf '{"stage": "%s", "rc": %d, "secs": %d, "data": %s}\n' \
                   "${name}" "${rc}" "${secs}" "${line}" >> "${OUT}" ;;
    esac
  done <<< "${out}"
  if [ ${rc} -ne 0 ] && [ -z "${out}" ]; then
    printf '{"stage": "%s", "rc": %d, "secs": %d, "data": null}\n' \
           "${name}" "${rc}" "${secs}" >> "${OUT}"
  fi
  return ${rc}
}

if ! probe | grep -q '"probe"'; then
  echo "tunnel wedged (probe failed); nothing captured" >&2
  exit 3
fi
echo "tunnel healthy; capturing to ${OUT}" >&2

# Priority order: the decisions blocked on each artifact, most important
# first. Re-probe between stages: a wedge mid-sequence should stop cheaply
# rather than eat the remaining timeouts.
run_stage bench 900 python bench.py
probe >/dev/null || { echo "wedged after bench" >&2; exit 3; }
run_stage diag 900 python benchmarks/diag_step_breakdown.py
probe >/dev/null || { echo "wedged after diag" >&2; exit 3; }
run_stage profile 600 python benchmarks/capture_profile.py
probe >/dev/null || { echo "wedged after profile" >&2; exit 3; }
run_stage pallas_ab 900 python benchmarks/bench_pallas_encode.py
probe >/dev/null || { echo "wedged after pallas_ab" >&2; exit 3; }
BENCH_CONTEXTS=1024 run_stage pallas_ab_c1024 900 \
  python benchmarks/bench_pallas_encode.py
probe >/dev/null || { echo "wedged after pallas_ab_c1024" >&2; exit 3; }
# serving engine A/B (ISSUE 4): naive per-request predict vs the
# micro-batching engine — on-chip latency p50/p99 + throughput
run_stage serving 900 python benchmarks/bench_serving.py
probe >/dev/null || { echo "wedged after serving" >&2; exit 3; }
# embedding index (ISSUE 5): exact vs IVF throughput/recall curves +
# the naive numpy host-loop baseline
run_stage index 900 python benchmarks/bench_index.py

echo "capture complete: ${OUT}" >&2
