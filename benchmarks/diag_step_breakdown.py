"""Diagnose where the on-chip train-step time goes (tunnel vs compute).

Round-2 context: the first driver-captured bench number was 2,420
examples/sec/chip (0.52x V100) at ~423 ms/step, far above the ~25 ms/step
roofline estimate (0.9 TFLOP matmul work + ~11 GB HBM traffic for the dense
Adam update over 384M params).  This script separates:

  rtt            host->device->host round-trip latency of a trivial op
  h2d            per-step batch upload cost (numpy args vs device-resident)
  sync-per-step  the round-1 bench's per-step float(loss) sync
  sync-at-end    enqueue N steps, block once on the final loss
  staged         end-to-end host batches through Trainer.stage_batches

Prints one JSON line per measurement.  Run on the real chip; measured
results are recorded in PERF.md.
"""
from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

# BENCH_SMOKE=1: tiny shapes so the ladder itself can be validated on
# CPU (same convention as bench.py); real captures use java14m shapes.
SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP = 1 if SMOKE else 5
STEPS = 4 if SMOKE else 20


def main() -> None:
    import numpy as np

    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)

    # --- tunnel round-trip latency on a trivial op
    tiny = jax.jit(lambda x: x + 1)
    v = tiny(jax.numpy.zeros(()))
    float(v)
    sw = benchlib.bench_timer('rtt')
    with sw.time():
        for _ in range(20):
            float(tiny(v))
    rtt = sw.last / 20
    print(json.dumps({'measure': 'rtt_trivial_op_ms',
                      'value': round(rtt * 1e3, 2)}), flush=True)

    # The diag ladder's baseline is pinned to threefry dropout + fp32 mu:
    # the config DEFAULTS flipped to 'rbg' + bf16 mu on this ladder's own
    # 2026-07-31 capture, and every variant delta below (no_dropout's
    # ~4.8 ms threefry cost, the rbg_dropout and bf16_mu arms themselves)
    # is defined relative to the threefry/fp32-mu-era baseline the PERF.md
    # tables record. Without the pins a variant equal to the new defaults
    # would measure default-vs-default (~0 delta) and new captures would
    # be incomparable with the 2026-07-29 series.
    BASELINE_PINS = dict(DROPOUT_PRNG_IMPL='threefry2x32',
                         ADAM_MU_DTYPE='float32',
                         ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32')
    config = benchlib.headline_config(SHAPES, **BASELINE_PINS)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    host_batches = benchlib.random_batches(SHAPES, 4)

    # --- upload cost for one batch
    sw = benchlib.bench_timer('h2d')
    with sw.time():
        dev_batches = [jax.block_until_ready(arrays) for arrays, _ in
                       trainer.stage_batches(iter(host_batches))]
    h2d = sw.last / len(host_batches)
    print(json.dumps({'measure': 'h2d_one_batch_ms',
                      'value': round(h2d * 1e3, 2)}), flush=True)

    # --- wire format: bytes/batch + upload cost, planes vs packed, at
    # the REALISTIC java14m fill (full-fill batches would hide the win —
    # the packed size tracks the corpus fill rate; the compute numbers
    # above keep full batches for comparability with prior captures)
    filled = benchlib.random_batches(SHAPES, 4, seed=2,
                                     fill=benchlib.JAVA14M_FILL)
    for wire_label, wire_batches in (
            ('planes', filled),
            ('packed', benchlib.pack_batches(filled, trainer))):
        print(json.dumps({'measure': 'wire_bytes_per_batch',
                          'format': wire_label,
                          'value': benchlib.wire_bytes(wire_batches[0])}),
              flush=True)
        with sw.time():
            for arrays, _b in trainer.stage_batches(iter(wire_batches)):
                jax.block_until_ready(arrays)
        dt = sw.last / len(wire_batches)
        print(json.dumps({'measure': 'h2d_one_batch_%s_ms' % wire_label,
                          'value': round(dt * 1e3, 2)}), flush=True)

    # --- per-shard h2d: each data shard's slice of the packed ctx buffer
    # timed onto its own device (the direct placement stage_batches uses)
    from jax.sharding import NamedSharding

    from code2vec_tpu.parallel import mesh as mesh_lib
    ctx = benchlib.pack_batches(filled[:1], trainer)[0].ctx
    sharding = NamedSharding(trainer.mesh, mesh_lib.batch_spec(ctx.ndim))
    per_shard = []
    for device, index in sharding.addressable_devices_indices_map(
            ctx.shape).items():
        piece = np.ascontiguousarray(ctx[index])
        with sw.time():
            jax.block_until_ready(jax.device_put(piece, device))
        per_shard.append(round(sw.last * 1e3, 2))
    print(json.dumps({'measure': 'h2d_per_shard_ms', 'format': 'packed',
                      'n_shards': len(per_shard), 'values': per_shard}),
          flush=True)

    def timed(label, step_fn, init_state, feeds, sync_each):
        """Warmup + measure one step function; returns the final state so
        variants can keep training off their own state.  sync_each times
        every step individually (per-step stats via the shared Timer);
        sync_end times the whole enqueued window and amortizes the one
        blocking sync."""
        st = init_state
        for i in range(WARMUP):
            st, loss = step_fn(st, feeds[i % len(feeds)])
            float(loss)
        timer = benchlib.bench_timer(label)
        last = None
        if sync_each:
            for i in range(STEPS):
                with timer.time():
                    st, last = step_fn(st, feeds[i % len(feeds)])
                    float(last)
            dt = timer.total / STEPS
        else:
            with timer.time():
                for i in range(STEPS):
                    st, last = step_fn(st, feeds[i % len(feeds)])
                float(last)
            dt = timer.last / STEPS
        print(json.dumps(
            {'measure': label, 'value': round(dt * 1e3, 2),
             'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
            flush=True)
        return st

    state = timed('step_ms_hostargs_sync_each', trainer.train_step, state,
                  host_batches, True)
    state = timed('step_ms_devargs_sync_each', trainer.train_step_placed,
                  state, dev_batches, True)
    state = timed('step_ms_devargs_sync_end', trainer.train_step_placed,
                  state, dev_batches, False)
    state = timed('step_ms_hostargs_sync_end', trainer.train_step, state,
                  host_batches, False)

    # --- is the per-batch upload bandwidth- or latency-bound?  One
    # contiguous array of the same total byte size:
    total_bytes = sum(np.asarray(a).nbytes for a in host_batches[0])
    flat = np.zeros(total_bytes // 4, np.int32)
    jax.block_until_ready(jax.device_put(flat))
    with sw.time():
        for _ in range(5):
            jax.block_until_ready(jax.device_put(flat))
    print(json.dumps({'measure': 'h2d_packed_same_bytes_ms',
                      'value': round(sw.last / 5 * 1e3, 2)}), flush=True)

    # --- does stage_batches overlap uploads behind compute end-to-end?
    fresh = benchlib.random_batches(SHAPES, STEPS, seed=1)
    last = None
    with sw.time():
        for arrays, _b in trainer.stage_batches(iter(fresh)):
            state, last = trainer.train_step_placed(state, arrays)
        float(last)
    dt = sw.last / STEPS
    print(json.dumps(
        {'measure': 'step_ms_staged_hostargs_end_to_end',
         'value': round(dt * 1e3, 2),
         'examples_per_sec': round(SHAPES.batch_size / dt, 1)}), flush=True)

    # --- the same end-to-end staging at REALISTIC fill, both wire
    # formats: (filled - packed) is the transfer time the packed wire
    # buys per step in the transfer-bound regime. The packed arm warms
    # its program (the jitted unpack+step twin) outside the timed window.
    filled_feed = benchlib.random_batches(SHAPES, STEPS, seed=3,
                                          fill=benchlib.JAVA14M_FILL)
    packed_feed = benchlib.pack_batches(filled_feed, trainer)
    # warm with a batch from the SAME feed: pack_batches pins one shared
    # capacity, so this is the exact program the timed loop runs
    for arrays, _b in trainer.stage_batches(iter(packed_feed[:1])):
        state, last = trainer.train_step_placed(state, arrays)
    float(last)
    for wire_label, feed in (('filled', filled_feed),
                             ('packed', packed_feed)):
        last = None
        with sw.time():
            for arrays, _b in trainer.stage_batches(iter(feed)):
                state, last = trainer.train_step_placed(state, arrays)
            float(last)
        dt = sw.last / STEPS
        print(json.dumps(
            {'measure': 'step_ms_staged_hostargs_%s' % wire_label,
             'value': round(dt * 1e3, 2),
             'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
            flush=True)

    # --- config-variant A/Bs, one fresh trainer each. The previous
    # variant's 4.6 GB state is freed before the next is built; memory
    # stays within one trainer + one variant at a time.
    state = dev_batches = fresh = trainer = None  # noqa: F841
    # Each variant = BASELINE_PINS with exactly one knob changed, so every
    # delta is attributable to its label even as config defaults move.
    variants = [
        # how much of the step is the dropout mask's threefry RNG?
        # (B=1024, C=200, 3d=640 -> 131M bernoulli draws per step)
        ('step_ms_devargs_sync_end_no_dropout',
         dict(DROPOUT_KEEP_RATE=1.0)),
        # lazy (sparse-row) Adam for the token/path tables: does cutting
        # the optimizer's O(vocab) HBM walk to O(touched rows) pay?
        # (measured 2026-07-29: 90.85 ms vs dense 49.25 — it does not)
        ('step_ms_devargs_sync_end_lazy_adam',
         dict(LAZY_EMBEDDING_ADAM=True)),
        # hardware RngBitGenerator for the dropout mask vs the ~4.8 ms of
        # threefry the no-dropout variant exposed
        ('step_ms_devargs_sync_end_rbg_dropout',
         dict(DROPOUT_PRNG_IMPL='rbg')),
        # bf16 first moment: ~1.5 GB/step less HBM traffic in the dense
        # Adam update
        ('step_ms_devargs_sync_end_bf16_mu',
         dict(ADAM_MU_DTYPE='bfloat16')),
    ]
    for label, overrides in variants:
        variant_config = benchlib.headline_config(
            SHAPES, **{**BASELINE_PINS, **overrides})
        variant_trainer, variant_state = benchlib.build_trainer(
            variant_config, SHAPES)
        feeds = benchlib.staged(variant_trainer, host_batches)
        timed(label, variant_trainer.train_step_placed, variant_state,
              feeds, False)
        variant_trainer = variant_state = feeds = None  # noqa: F841

    # --- DIAGNOSTIC (not a product knob): how much of the step is the
    # embedding backward (gather-grad -> scatter-adds into the 1.3M/911K
    # tables)? stop_gradient on the tables removes exactly that from the
    # backward while the forward AND the dense Adam walk over the full
    # tables stay; baseline minus this = the scatter/gather-backward cost
    # the cost-analysis roofline can't itemize.
    import optax

    frozen_config = benchlib.headline_config(SHAPES, **BASELINE_PINS)
    frozen_trainer, frozen_state = benchlib.build_trainer(
        frozen_config, SHAPES)
    feeds = benchlib.staged(frozen_trainer, host_batches)
    backend = frozen_trainer.backend
    frozen_opt = optax.adam(frozen_config.LEARNING_RATE)

    def frozen_tables_step(state, arrays):
        def loss_fn(params):
            stopped = params._replace(
                token_embedding=jax.lax.stop_gradient(params.token_embedding),
                path_embedding=jax.lax.stop_gradient(params.path_embedding))
            loss, _aux = backend.loss_fn(stopped, arrays, jax.random.fold_in(
                state.rng, state.step))
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, new_opt = frozen_opt.update(grads, state.opt_state,
                                             state.params)
        new_params = optax.apply_updates(state.params, updates)
        return state._replace(params=new_params, opt_state=new_opt,
                              step=state.step + 1), loss

    frozen_jit = jax.jit(frozen_tables_step, donate_argnums=(0,))
    timed('step_ms_devargs_sync_end_frozen_tables', frozen_jit,
          frozen_state, feeds, False)
    frozen_trainer = frozen_state = feeds = None  # noqa: F841

    # --- top-k micro A/B: monolithic lax.top_k vs the exact grouped
    # two-stage merge over java14m-shaped logits. Chained by feeding each
    # round's max value back into the input (the tunnel's async dispatch
    # makes unchained timings meaningless — see PERF.md).
    import jax.numpy as jnp

    from code2vec_tpu.ops.topk import grouped_top_k

    logits = jax.device_put(np.random.default_rng(0).normal(
        size=(SHAPES.batch_size, 261248)).astype(np.float32))
    jax.block_until_ready(logits)

    def bench_topk(label, fn):
        stepped = jax.jit(lambda x, t: fn(x + t * 0.0, 10))
        token = jnp.zeros((), jnp.float32)
        for _ in range(3):
            values, _ = stepped(logits, token)
            token = values[0, 0]
        float(token)
        topk_sw = benchlib.bench_timer(label)
        with topk_sw.time():
            token = jnp.zeros((), jnp.float32)
            for _ in range(10):
                values, _ = stepped(logits, token)
                token = values[0, 0]
            float(token)
        dt = topk_sw.last / 10
        print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2)}),
              flush=True)

    bench_topk('topk_ms_lax_b1024_v261k', jax.lax.top_k)
    bench_topk('topk_ms_grouped_b1024_v261k', grouped_top_k)


if __name__ == '__main__':
    main()
