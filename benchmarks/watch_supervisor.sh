#!/usr/bin/env bash
# Keep watch_and_capture.sh alive for a whole round (VERDICT r3 #2).
# Respawns the watcher whenever it exits nonzero (gave up / wedged
# mid-capture); stops only when all stages are captured (exit 0) or the
# round budget runs out.  Leaves a committed-able trace either way:
# benchmarks/results/watcher_<round>.log carries every probe heartbeat,
# launch, respawn, and exit.
#
#   bash benchmarks/watch_supervisor.sh [round_budget_seconds]
set -u
cd "$(dirname "$0")/.."
ROUND=${CAPTURE_ROUND:-r4}
BUDGET=${1:-39600}   # default 11 h
HEARTBEAT=benchmarks/results/watcher_${ROUND}.log
mkdir -p benchmarks/results
deadline=$(( $(date +%s) + BUDGET ))
attempt=0
while [ "$(date +%s)" -lt "${deadline}" ]; do
  attempt=$((attempt+1))
  echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) supervisor: launch attempt ${attempt}" >> "${HEARTBEAT}"
  remaining=$(( deadline - $(date +%s) ))
  CAPTURE_ROUND=${ROUND} bash benchmarks/watch_and_capture.sh "${remaining}"
  rc=$?
  echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) supervisor: watcher exited rc=${rc}" >> "${HEARTBEAT}"
  if [ ${rc} -eq 0 ]; then
    echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) supervisor: all stages captured; done" >> "${HEARTBEAT}"
    exit 0
  fi
  sleep 60
done
echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) supervisor: round budget exhausted" >> "${HEARTBEAT}"
exit 3
