"""On-chip A/B: flash-style fused softmax-CE (ops/pallas_ce.py) vs the
materialized-logits XLA path, at the java14m train step.

The fused kernel removes ~4.3 GB/step of (B, 261K) logits HBM traffic
(module docstring) — roughly 5 ms at the measured ~819 GB/s — IF its
blockwise matmuls keep the MXU as busy as XLA's monolithic ones. This
measures the full train step both ways (same chained devargs/sync-at-end
methodology as the other harnesses, PERF.md), plus the combined
fused-CE + rbg-dropout + bf16-mu candidate default set.

Engagement check: before timing the fused arm, the compiled HLO is
searched for the Mosaic custom call so the kernel demonstrably ran
(the same guard bench_pallas_encode.py uses).
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP, STEPS = benchlib.bench_steps(SMOKE)


def measure(label: str, check_engaged: bool = False, **overrides) -> None:
    config = benchlib.headline_config(SHAPES, **overrides)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    feeds = benchlib.staged(trainer, benchlib.random_batches(SHAPES, 4))
    if check_engaged:
        engaged = benchlib.mosaic_engaged(trainer._train_step, state,
                                          feeds[0])
        print(json.dumps({'measure': label + '_kernel_engaged',
                          'value': bool(engaged)}), flush=True)
    for i in range(WARMUP):
        state, loss = trainer.train_step_placed(state, feeds[i % len(feeds)])
        float(loss)
    t0 = time.perf_counter()
    last = None
    for i in range(STEPS):
        state, last = trainer.train_step_placed(state, feeds[i % len(feeds)])
    float(last)
    dt = (time.perf_counter() - t0) / STEPS
    if SMOKE:
        label += '_SMOKE_ONLY'
    print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2),
                      'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
          flush=True)


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)
    measure('step_ms_ce_xla')
    measure('step_ms_ce_fused', check_engaged=True,
            USE_PALLAS_FUSED_CE=True)
    # the candidate full default set if every queued A/B wins. No second
    # engagement check: same kernel flag as the arm above, and each check
    # costs a full extra AOT compile of the java14m step — real money
    # against the tunnel's stage timeouts.
    measure('step_ms_ce_fused_rbg_bf16mu',
            USE_PALLAS_FUSED_CE=True, DROPOUT_PRNG_IMPL='rbg',
            ADAM_MU_DTYPE='bfloat16')


if __name__ == '__main__':
    main()
