"""On-chip A/B: flash-style fused softmax-CE (ops/pallas_ce.py) vs the
materialized-logits XLA path, at the java14m train step.

The fused kernel removes ~4.3 GB/step of (B, 261K) logits HBM traffic
(module docstring) — roughly 5 ms at the measured ~819 GB/s — IF its
blockwise matmuls keep the MXU as busy as XLA's monolithic ones. This
measures the full train step both ways (same chained devargs/sync-at-end
methodology as the other harnesses, PERF.md), plus the combined
fused-CE + rbg-dropout + bf16-mu candidate default set.

Engagement check: before timing the fused arm, the compiled HLO is
searched for the Mosaic custom call so the kernel demonstrably ran
(the same guard bench_pallas_encode.py uses).

Compile-stall resilience (VERDICT r3 #4): the C=1024 encode kernel proved
Mosaic compile can exceed a stage timeout through the tunnel, so each arm
runs in its OWN subprocess under a per-arm timeout; if the fused arm's
compile stalls, the harness retries unattended with smaller vocab tiles
(PALLAS_CE_VOCAB_TILE=512, then 256) instead of burning the whole healthy
window on one hang. Set BENCH_FUSED_CE_ARM to run a single arm directly.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP, STEPS = benchlib.bench_steps(SMOKE)


def measure(label: str, check_engaged: bool = False, **overrides) -> None:
    config = benchlib.headline_config(SHAPES, **overrides)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    feeds = benchlib.staged(trainer, benchlib.random_batches(SHAPES, 4))
    if check_engaged:
        engaged = benchlib.mosaic_engaged(trainer._train_step, state,
                                          feeds[0])
        print(json.dumps({'measure': label + '_kernel_engaged',
                          'value': bool(engaged)}), flush=True)
    for i in range(WARMUP):
        state, loss = trainer.train_step_placed(state, feeds[i % len(feeds)])
        float(loss)
    t0 = time.perf_counter()
    last = None
    for i in range(STEPS):
        state, last = trainer.train_step_placed(state, feeds[i % len(feeds)])
    float(last)
    dt = (time.perf_counter() - t0) / STEPS
    if SMOKE:
        label += '_SMOKE_ONLY'
    print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2),
                      'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
          flush=True)


ARMS = {
    # The xla/fused pair pins threefry + fp32 mu explicitly: the config
    # DEFAULTS flipped to rbg + bf16 mu on the 2026-07-31 capture, and an
    # unpinned pair would (a) stop being comparable with the 2026-07-29/31
    # series PERF.md's fused-CE verdict is built on and (b) make 'fused'
    # config-identical to 'fused_rbg_bf16mu' (default-vs-default, ~0
    # delta).
    'xla': dict(label='step_ms_ce_xla',
                DROPOUT_PRNG_IMPL='threefry2x32', ADAM_MU_DTYPE='float32',
                ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32'),
    'fused': dict(label='step_ms_ce_fused', check_engaged=True,
                  USE_PALLAS_FUSED_CE=True,
                  DROPOUT_PRNG_IMPL='threefry2x32',
                  ADAM_MU_DTYPE='float32',
                  ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32'),
    # the full round-5 default set plus the kernel (its measured -1.4%
    # increment rides on top of the rbg+bf16-mu recipe). No second
    # engagement check: same kernel flag as the arm above, and each check
    # costs a full extra AOT compile of the java14m step — real money
    # against the tunnel's stage timeouts.
    'fused_rbg_bf16mu': dict(label='step_ms_ce_fused_rbg_bf16mu',
                             USE_PALLAS_FUSED_CE=True,
                             DROPOUT_PRNG_IMPL='rbg',
                             ADAM_MU_DTYPE='bfloat16',
                             ADAM_NU_DTYPE='float32',
                             GRADS_DTYPE='float32'),
}


def run_arm(arm: str) -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower(),
                      'arm': arm}), flush=True)
    spec = dict(ARMS[arm])
    label = spec.pop('label')
    check = spec.pop('check_engaged', False)
    measure(label, check_engaged=check, **spec)


def _spawn(arm: str, timeout: float, tile: int | None = None) -> bool:
    """One arm in a subprocess (stdout inherited, so its JSON lines land in
    the capture like before); returns True on clean completion."""
    env = dict(os.environ, BENCH_FUSED_CE_ARM=arm)
    if tile is not None:
        env['PALLAS_CE_VOCAB_TILE'] = str(tile)
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, timeout=timeout)
        ok = proc.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        print(json.dumps({'measure': 'fused_ce_arm_failed', 'arm': arm,
                          'tile': tile,
                          'timeout_s': timeout}), flush=True)
    return ok


def main() -> None:
    arm = os.environ.get('BENCH_FUSED_CE_ARM', '')
    if arm:
        run_arm(arm)
        return
    per_arm = float(os.environ.get('BENCH_FUSED_CE_ARM_TIMEOUT',
                                   '120' if SMOKE else '300'))
    ok = _spawn('xla', per_arm)
    # fused arm: shrink the vocab tile and retry if Mosaic compile stalls
    fused_ok = False
    won_tile = None
    for tile in (None, 512, 256):
        if _spawn('fused', per_arm, tile=tile):
            fused_ok = True
            won_tile = tile
            if tile is not None:
                print(json.dumps({'measure': 'fused_ce_tile_fallback',
                                  'tile': tile}), flush=True)
            break
    if not fused_ok:
        # every tile stalled: rerunning the combined arm would hit the
        # same compile; exit nonzero so the watcher retries the stage in
        # a later window instead of locking in the xla arm alone
        sys.exit(4)
    ok = _spawn('fused_rbg_bf16mu', per_arm, tile=won_tile) and ok
    if not ok:
        sys.exit(4)


if __name__ == '__main__':
    main()
