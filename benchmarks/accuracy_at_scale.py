"""Accuracy-at-scale run: does the framework LEARN at java-small-like scale?

VERDICT r2 missing #2: the only accuracy signals were tiny-corpus overfit
tests. This drives the REAL pipeline end to end at a scale that stresses
vocab truncation, OOV rates and eval throughput:

  scripts/gen_java_corpus.py  (~24K classes / ~110K methods)
    -> c2v-extract --dir      (native extractor, all three splits)
    -> data/preprocess.py     (vocab build WITH truncation: 6K words and
                               4K targets against ~8.7K / ~6.7K corpus
                               uniques — the Zipf tail really truncates)
    -> cli train              (java-small dims: 128/128/384, C=200,
                               per-epoch val eval)
    -> a committed val-F1/loss learning curve (JSON)

The reference does this implicitly via train.sh + best-epoch-by-F1
(reference README.md:87-88). Run on the TPU chip when the tunnel is
healthy (~minutes); CPU works for a reduced profile (--profile cpu).

Usage:
  python benchmarks/accuracy_at_scale.py --workdir /tmp/acc_r3 \
      [--profile tpu|cpu] [--epochs N]

Prints one JSON line per epoch plus a final summary line; the orchestrated
result lands in benchmarks/results/.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import corpus_stats as corpus_stats_mod  # noqa: E402 (sibling module)


def _pythonpath() -> str:
    """REPO prepended to the inherited PYTHONPATH — replacing it outright
    drops the environment's backend-plugin site dir (the axon TPU plugin
    registers from PYTHONPATH via sitecustomize), which kills every child
    that inherits JAX_PLATFORMS=axon before it can initialize a device."""
    inherited = os.environ.get('PYTHONPATH', '')
    return REPO + (os.pathsep + inherited if inherited else '')

# Corpus vocab statistics overflow these on purpose: the 24K-class corpus
# produces ~8.7K unique tokens and ~6.7K unique target names (measured),
# so these caps truncate the Zipf tail into real OOV pressure the way
# java14m's 1.3M-word cap does against its much larger raw vocabulary
WORD_VOCAB = 6000
PATH_VOCAB = 30000
TARGET_VOCAB = 4000

PROFILES = {
    # Base profiles pin '--adam-mu-dtype float32' explicitly: the config
    # DEFAULT flipped to bf16 mu on the 2026-07-31 on-chip A/B, and each
    # *_bf16mu twin below must differ from its base by exactly that one
    # knob — an unpinned base would silently train the twin's config and
    # destroy the A/B.
    # java-small-like: full dims, full contexts. Dropout is pinned 'rbg'
    # to match the committed accuracy_tpu.json capture (2026-07-31
    # 04:05Z, which ran after the rbg default flip landed on disk): the
    # tpu_bf16mu twin below must differ from it by the mu dtype ONLY.
    'tpu': dict(classes=24000, batch=512, contexts=200, epochs=12,
                extra_args=['--dropout-prng', 'rbg',
                            '--adam-mu-dtype', 'float32',
                            '--adam-nu-dtype', 'float32',
                            '--grads-dtype', 'float32']),
    # reduced compute (smaller dims/contexts) so the learning-loop evidence
    # does not need the chip; vocab pressure is unchanged
    'cpu': dict(classes=24000, batch=512, contexts=32, epochs=6,
                extra_args=['--dtype', 'float32',
                            '--dropout-prng', 'threefry2x32',
                            '--adam-mu-dtype', 'float32',
                            '--adam-nu-dtype', 'float32',
                            '--grads-dtype', 'float32']),
    # VERDICT r3 #5 fallback: FULL model dims (128/128/384) and C=200 on
    # CPU — fewer classes/epochs so it finishes in tens of minutes, but
    # the model being validated is the real one, not the 64-dim stand-in
    'cpu_full': dict(classes=8000, batch=512, contexts=200, epochs=5,
                     extra_args=['--dtype', 'float32',
                                 '--dropout-prng', 'threefry2x32',
                                 '--adam-mu-dtype', 'float32',
                                 '--adam-nu-dtype', 'float32',
                                 '--grads-dtype', 'float32']),
    # VERDICT r4 #2: the EXACT bench recipe (bfloat16 compute + Pallas
    # fused CE, interpreted on CPU + rbg dropout) at full dims, so the
    # 21.7K ex/s configuration is shown to reach the same F1 as its fp32
    # twin (accuracy_cpu_full_24k_20ep.json) on the identical dataset
    'cpu_full_bf16': dict(classes=8000, batch=512, contexts=200, epochs=5,
                          extra_args=['--dtype', 'bfloat16',
                                      '--dropout-prng', 'rbg',
                                      '--fused-ce',
                                      '--adam-mu-dtype', 'float32',
                                      '--adam-nu-dtype', 'float32',
                                      '--grads-dtype', 'float32']),
    # ADAM_MU_DTYPE='bfloat16' equivalence twins (the last winning knob
    # from the 2026-07-31 on-chip A/B, -5.1% step time): identical to the
    # profile each shadows plus the bf16 first moment, so the F1 curve
    # pairs 1:1 against accuracy_tpu.json / accuracy_cpu_full_bf16.json.
    'tpu_bf16mu': dict(classes=24000, batch=512, contexts=200, epochs=12,
                       extra_args=['--dropout-prng', 'rbg',
                                   '--adam-mu-dtype', 'bfloat16',
                                   '--adam-nu-dtype', 'float32',
                                   '--grads-dtype', 'float32']),
    # the SHIPPED default recipe on the device (rbg + bf16 mu + bf16 nu
    # after the 2026-07-31 nu flip): pairs 1:1 against
    # accuracy_tpu_bf16mu.json (nu knob only) and accuracy_tpu.json
    'tpu_bf16nu': dict(classes=24000, batch=512, contexts=200, epochs=12,
                       extra_args=['--dropout-prng', 'rbg',
                                   '--adam-mu-dtype', 'bfloat16',
                                   '--adam-nu-dtype', 'bfloat16',
                                   '--grads-dtype', 'float32']),
    'cpu_full_bf16mu': dict(classes=8000, batch=512, contexts=200, epochs=5,
                            extra_args=['--dtype', 'bfloat16',
                                        '--dropout-prng', 'rbg',
                                        '--fused-ce',
                                        '--adam-mu-dtype', 'bfloat16',
                                        '--adam-nu-dtype', 'float32',
                                        '--grads-dtype', 'float32']),
    # ADAM_NU_DTYPE='bfloat16' equivalence twin (flip-rule gate for the
    # bench_moment_dtypes.py A/B): identical to cpu_full_bf16mu plus the
    # bf16 second moment, so its F1 curve pairs 1:1 against
    # accuracy_cpu_full_bf16mu.json — a knob flips only with BOTH a >=2%
    # measured step-time win and this curve matching its fp32-nu twin.
    'cpu_full_bf16nu': dict(classes=8000, batch=512, contexts=200, epochs=5,
                            extra_args=['--dtype', 'bfloat16',
                                        '--dropout-prng', 'rbg',
                                        '--fused-ce',
                                        '--adam-mu-dtype', 'bfloat16',
                                        '--adam-nu-dtype', 'bfloat16',
                                        '--grads-dtype', 'float32']),
    # the C# pipeline at scale (VERDICT-style end-to-end evidence for the
    # second language frontend): gen_csharp_corpus -> c2v-extract --dir
    # over .cs -> preprocess -> train. Same dims/recipe as cpu_full so
    # the two languages' curves compare 1:1.
    'cpu_csharp': dict(classes=8000, batch=512, contexts=200, epochs=5,
                       lang='csharp',
                       extra_args=['--dtype', 'float32',
                                   '--dropout-prng', 'threefry2x32',
                                   '--adam-mu-dtype', 'float32',
                                   '--adam-nu-dtype', 'float32',
                                   '--grads-dtype', 'float32']),
    # GRADS_DTYPE='bfloat16' equivalence twin: the full combined
    # candidate recipe (bf16 grads + bf16 nu on top of the shipped
    # defaults), pairing against cpu_full_bf16nu (grads knob only) and
    # transitively cpu_full_bf16mu.
    'cpu_full_bf16grads': dict(classes=8000, batch=512, contexts=200,
                               epochs=5,
                               extra_args=['--dtype', 'bfloat16',
                                           '--dropout-prng', 'rbg',
                                           '--fused-ce',
                                           '--adam-mu-dtype', 'bfloat16',
                                           '--adam-nu-dtype', 'bfloat16',
                                           '--grads-dtype', 'bfloat16']),
}
CPU_DIMS = dict(TOKEN_EMBEDDINGS_SIZE=64, PATH_EMBEDDINGS_SIZE=64,
                CODE_VECTOR_SIZE=192, TARGET_EMBEDDINGS_SIZE=192)


def run(cmd, **kw):
    print('+ ' + ' '.join(cmd), file=sys.stderr, flush=True)
    subprocess.run(cmd, check=True, **kw)


def build_dataset(workdir: str, classes: int, contexts: int,
                  lang: str = 'java') -> str:
    # every cached artifact is keyed by the parameters that shaped it:
    # the corpus and raw extraction by the class count (and language —
    # java keeps its legacy key so committed workdirs stay warm), the
    # preprocessed dataset additionally by the sampling width — so
    # profiles sharing a workdir can never silently train on each
    # other's corpus size or contexts sampling (either would be a wrong
    # experiment)
    tag = '%d' % classes if lang == 'java' else 'cs_%d' % classes
    corpus = os.path.join(workdir, 'corpus_%s' % tag)
    data = os.path.join(workdir, 'data')
    os.makedirs(data, exist_ok=True)
    if not os.path.isdir(corpus):
        generator = ('gen_java_corpus.py' if lang == 'java'
                     else 'gen_csharp_corpus.py')
        run([sys.executable, os.path.join(REPO, 'scripts', generator),
             '-o', corpus, '--classes', str(classes)])
    extractor = os.path.join(REPO, 'extractor', 'build', 'c2v-extract')
    raw = {}
    for split in ('train', 'val', 'test'):
        raw[split] = os.path.join(data, '%s_%s.raw' % (split, tag))
        if not os.path.isfile(raw[split]):
            with open(raw[split], 'w') as f:
                run([extractor, '--dir', os.path.join(corpus, split),
                     '--max_path_length', '8', '--max_path_width', '2',
                     '--num_threads', '16'], stdout=f)
    prefix = os.path.join(data, 'acc_%s_c%d' % (tag, contexts))
    if not os.path.isfile(prefix + '.train.c2v'):
        run([sys.executable, '-m', 'code2vec_tpu.data.preprocess',
             '-trd', raw['train'], '-vd', raw['val'], '-ted', raw['test'],
             '-mc', str(contexts), '-wvs', str(WORD_VOCAB),
             '-pvs', str(PATH_VOCAB), '-tvs', str(TARGET_VOCAB),
             '-o', prefix, '--seed', '0'],
            cwd=REPO, env=dict(os.environ, PYTHONPATH=_pythonpath()))
    return prefix


# the epoch log line wraps (numpy renders topk_acc across lines), so the
# epoch/loss head and the precision/recall/F1 tail may arrive on different
# lines — parse them separately and pair in order
EPOCH_HEAD_RE = re.compile(
    r'After epoch (\d+): loss: ([\d.]+(?:[eE][+-]?\d+)?)')
EPOCH_TAIL_RE = re.compile(
    r'precision: ([\d.eE+-]+), recall: ([\d.eE+-]+), F1: ([\d.eE+-]+)')


def dataset_stats(prefix: str, raw_train: str) -> dict:
    """Reproducible dataset facts for the artifact: the created vocab
    sizes, and raw vs TRAINED-ON row counts — the .c2v keeps every row,
    but the train reader skips rows whose target fell off the truncated
    vocab (reference parity), so the OOV-pressure number is recomputed
    here exactly the way the reader decides it."""
    import pickle

    def count_lines(path):
        with open(path) as f:
            return sum(1 for _ in f)

    with open(prefix + '.dict.c2v', 'rb') as f:
        word = pickle.load(f)
        path_d = pickle.load(f)
        target = pickle.load(f)
    with open(prefix + '.train.c2v') as f:
        trained_on = sum(1 for line in f
                         if line.split(' ', 1)[0] in target)
    return {
        'train_rows_raw': count_lines(raw_train),
        'train_rows_after_oov_target_drop': trained_on,
        'created_vocab': {'token': len(word), 'path': len(path_d),
                          'target': len(target)},
    }


def majority_baseline(prefix: str) -> dict:
    """Subtoken F1 of constantly predicting the most frequent train label —
    the floor the learned model must clear for the curve to mean anything
    (an OOV-majority predictor is the degenerate strategy vocab truncation
    invites)."""
    import pickle

    sys.path.insert(0, REPO)
    from code2vec_tpu.metrics import SubtokensEvaluationMetric
    from code2vec_tpu.vocab import SPECIAL_WORDS_ONLY_OOV

    with open(prefix + '.dict.c2v', 'rb') as f:
        pickle.load(f)          # word counts
        pickle.load(f)          # path counts
        target_to_count = pickle.load(f)
    majority = max(target_to_count, key=target_to_count.get)
    metric = SubtokensEvaluationMetric(SPECIAL_WORDS_ONLY_OOV.OOV)
    with open(prefix + '.val.c2v') as f:
        rows = [(line.split(' ', 1)[0], [majority]) for line in f if line]
    metric.update_batch(rows)
    return {'predicting': majority,
            'precision': round(metric.precision, 4),
            'recall': round(metric.recall, 4),
            'f1': round(metric.f1, 4)}


def build_mixed_dataset(workdir: str, classes_per_lang: int,
                        contexts: int) -> str:
    """Mixed Java+C# dataset for the --scenarios mode: both languages'
    raw extractions concatenated into ONE preprocess stream, so the
    trained vocab (and the served model) covers both frontends."""
    data = os.path.join(workdir, 'data')
    os.makedirs(data, exist_ok=True)
    extractor = os.path.join(REPO, 'extractor', 'build', 'c2v-extract')
    raws = {split: [] for split in ('train', 'val', 'test')}
    for lang, generator in (('java', 'gen_java_corpus.py'),
                            ('csharp', 'gen_csharp_corpus.py')):
        tag = ('%d' % classes_per_lang if lang == 'java'
               else 'cs_%d' % classes_per_lang)
        corpus = os.path.join(workdir, 'corpus_%s' % tag)
        if not os.path.isdir(corpus):
            run([sys.executable,
                 os.path.join(REPO, 'scripts', generator),
                 '-o', corpus, '--classes', str(classes_per_lang)])
        for split in ('train', 'val', 'test'):
            raw = os.path.join(data, '%s_%s.raw' % (split, tag))
            if not os.path.isfile(raw):
                with open(raw, 'w') as f:
                    run([extractor, '--dir',
                         os.path.join(corpus, split),
                         '--max_path_length', '8',
                         '--max_path_width', '2',
                         '--num_threads', '16'], stdout=f)
            raws[split].append(raw)
    mixed = {}
    for split, parts in raws.items():
        mixed[split] = os.path.join(
            data, '%s_mix_%d.raw' % (split, classes_per_lang))
        if not os.path.isfile(mixed[split]):
            with open(mixed[split], 'w') as out:
                for part in parts:
                    with open(part) as f:
                        out.write(f.read())
    prefix = os.path.join(data, 'acc_mix_%d_c%d'
                          % (classes_per_lang, contexts))
    if not os.path.isfile(prefix + '.train.c2v'):
        run([sys.executable, '-m', 'code2vec_tpu.data.preprocess',
             '-trd', mixed['train'], '-vd', mixed['val'],
             '-ted', mixed['test'], '-mc', str(contexts),
             '-wvs', str(WORD_VOCAB), '-pvs', str(PATH_VOCAB),
             '-tvs', str(TARGET_VOCAB), '-o', prefix, '--seed', '0'],
            cwd=REPO, env=dict(os.environ, PYTHONPATH=_pythonpath()))
    return prefix


def run_scenarios(args) -> None:
    """--scenarios mode (WORKLOADS.md): train a small mixed Java+C#
    model in-process, record a mixed traffic profile, replay it
    against a live mesh under the registered scenarios, and emit
    per-scenario x per-language quality rows plus the built-in
    retrieval-vs-softmax A/B and the post-warmup compile count."""
    smoke = os.environ.get('BENCH_SMOKE') == '1'
    sys.path.insert(0, REPO)
    import numpy as np
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.telemetry import core as tele_core
    from code2vec_tpu.telemetry.jit_tracker import \
        install_compile_listener
    from code2vec_tpu.workloads import profile as profile_lib
    from code2vec_tpu.workloads import replay as replay_lib

    classes = args.classes or (2 if smoke else 48)
    epochs = args.epochs or (1 if smoke else 4)
    contexts = 8 if smoke else 16
    os.makedirs(args.workdir, exist_ok=True)
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    prefix = build_mixed_dataset(args.workdir, classes, contexts)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=prefix, DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=contexts,
        TRAIN_BATCH_SIZE=64, TEST_BATCH_SIZE=64,
        NUM_TRAIN_EPOCHS=epochs, SHUFFLE_BUFFER_SIZE=512,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16',
        SERVING_SLO_AVAILABILITY=0.99,
        # the corpus index is built from predict-path code vectors
        EXPORT_CODE_VECTORS=True,
        BLEND_NEIGHBOR_WEIGHT=args.blend_weight, **CPU_DIMS)
    tele_core.enable()
    install_compile_listener()
    compiles = tele_core.registry().counter('jit/compiles_total')
    model = Code2VecModel(config)
    model.train()

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    mesh = None
    try:
        # retrieval index: train-split code vectors labeled with the
        # TRUE method names — the neighbor votes the blend mixes in
        with open(prefix + '.train.c2v') as f:
            train_lines = [line.rstrip('\n') for line in f if line.strip()]
        cap = 64 if smoke else 512
        train_lines = train_lines[:cap]
        vectors, labels = [], []
        for start in range(0, len(train_lines), 64):
            chunk = train_lines[start:start + 64]
            for line, row in zip(chunk, model.predict(chunk)):
                vectors.append(np.asarray(row.code_vector,
                                          dtype=np.float32))
                labels.append(line.split(' ', 1)[0])

        class _CorpusIndex:
            def __init__(self, rows, names):
                self.vectors = np.stack(rows)
                norms = np.linalg.norm(self.vectors, axis=1,
                                       keepdims=True)
                self.vectors /= np.maximum(norms, 1e-8)
                self.labels = np.array(names, dtype=object)

            def search(self, queries, k):
                q = np.atleast_2d(np.asarray(queries,
                                             dtype=np.float32))
                q = q / np.maximum(
                    np.linalg.norm(q, axis=1, keepdims=True), 1e-8)
                scores = q @ self.vectors.T
                idx = np.argsort(-scores, axis=1)[:, :k]
                return np.take_along_axis(scores, idx, axis=1), idx

        mesh = model.serving_mesh(
            replicas=1, tiers=('topk', 'vectors'),
            memo_cache_bytes=8 << 20)
        mesh.attach_index(_CorpusIndex(vectors, labels))

        profile_dir = os.path.join(args.workdir, 'profile_src')
        records = profile_lib.build_synthetic_profile(
            config, profile_dir,
            classes_per_language=max(1, classes // 4),
            seed=args.seed, rate_rps=20.0 if smoke else 50.0)
        profile_path = os.path.join(args.workdir,
                                    'mixed_profile.jsonl')
        # round-trip through the durable format: the replayed stream is
        # exactly what a recorded profile on disk would deliver
        profile_lib.write_profile(profile_path, records,
                                  meta={'source': 'synthetic'})
        _header, records = profile_lib.read_profile(profile_path)

        def relabeled(name, weight=None):
            out = []
            for record in records:
                twin = dict(record)
                twin['scenario'] = name
                if weight is not None:
                    twin['weight'] = weight
                out.append(twin)
            return out

        # warm every entry point once, then require ZERO compiles for
        # the whole mixed-scenario steady state (the acceptance gate)
        replay_lib.replay(mesh, records, pace=False, seed=args.seed,
                          limit=min(8, len(records)))
        replay_lib.replay(
            mesh, relabeled('retrieval_naming', args.blend_weight),
            pace=False, seed=args.seed, limit=min(4, len(records)))
        warm = compiles.value

        mixed = replay_lib.replay(mesh, records,
                                  rate_scale=args.rate_scale,
                                  seed=args.seed)
        softmax = replay_lib.replay(mesh, relabeled('softmax_naming'),
                                    rate_scale=args.rate_scale,
                                    seed=args.seed)
        retrieval = replay_lib.replay(
            mesh, relabeled('retrieval_naming', args.blend_weight),
            rate_scale=args.rate_scale, seed=args.seed)
        postwarm = compiles.value - warm

        rows = []
        for report in (mixed, softmax, retrieval):
            for scenario, languages in sorted(
                    report['scenarios'].items()):
                for language, cell in sorted(languages.items()):
                    row = {'measure': 'scenario_quality',
                           'scenario': scenario,
                           'language': language, **cell}
                    rows.append(row)
                    emit(row)
        slo = mixed.get('slo') or {}
        for scenario, share in sorted(
                (slo.get('scenarios') or {}).items()):
            emit({'measure': 'scenario_slo', 'scenario': scenario,
                  **share})

        def aggregate(report, name):
            scored = exact = 0
            f1_num = 0.0
            for cell in (report['scenarios'].get(name) or {}).values():
                scored += cell['scored']
                exact += round(cell['exact_match'] * cell['scored'])
                f1_num += cell['f1'] * cell['scored']
            return {'scored': scored,
                    'exact_match': exact / scored if scored else 0.0,
                    'f1': f1_num / scored if scored else 0.0}

        soft = aggregate(softmax, 'softmax_naming')
        retr = aggregate(retrieval, 'retrieval_naming')
        verdict = ('win' if retr['exact_match'] > soft['exact_match']
                   else 'tie' if retr['exact_match']
                   >= soft['exact_match'] else 'loss')
        ab = {'measure': 'retrieval_ab',
              'blend_weight': args.blend_weight,
              'softmax_exact': round(soft['exact_match'], 4),
              'retrieval_exact': round(retr['exact_match'], 4),
              'softmax_f1': round(soft['f1'], 4),
              'retrieval_f1': round(retr['f1'], 4),
              'scored': soft['scored'], 'verdict': verdict}
        emit(ab)
        emit({'measure': 'scenario_postwarm_compiles',
              'value': postwarm})
        emit({'measure': 'scenario_replay_fingerprint',
              'value': mixed['fingerprint'],
              'admitted': mixed['admitted']})

        out = args.out or os.path.join(REPO, 'benchmarks', 'results',
                                       'accuracy_scenarios.json')
        with open(out, 'w') as f:
            json.dump({'profile_records': len(records),
                       'rows': rows, 'retrieval_ab': ab,
                       'slo': slo,
                       'postwarm_compiles': postwarm,
                       'fingerprint': mixed['fingerprint'],
                       'smoke': smoke}, f, indent=1)
        print(json.dumps({'measure': 'scenarios_done',
                          'out': os.path.relpath(out, REPO)}),
              flush=True)
    finally:
        if mesh is not None:
            mesh.close()
        model.close_stores()
        tele_core.disable()
        tele_core.reset()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--workdir', default='/tmp/acc_r3')
    parser.add_argument('--profile', choices=sorted(PROFILES),
                        default='tpu')
    parser.add_argument('--epochs', type=int, default=None)
    parser.add_argument('--classes', type=int, default=None,
                        help='override corpus size (smoke runs)')
    parser.add_argument('--out', default=None,
                        help='result JSON path (default: '
                             'benchmarks/results/accuracy_<profile>.json)')
    parser.add_argument('--scenarios', action='store_true',
                        help='run the scenario traffic plane mode '
                             'instead of a learning-curve profile: '
                             'record a mixed Java+C# profile, replay '
                             'it against a live mesh, emit '
                             'per-scenario x per-language quality '
                             'rows + the retrieval-vs-softmax A/B '
                             '(WORKLOADS.md)')
    parser.add_argument('--blend-weight', type=float, default=0.5,
                        help='retrieval blend weight for the '
                             '--scenarios A/B arm')
    parser.add_argument('--rate-scale', type=float, default=4.0,
                        help='--scenarios replay pacing multiplier '
                             'over the recorded arrival times')
    parser.add_argument('--seed', type=int, default=7,
                        help='--scenarios profile + replay seed')
    args = parser.parse_args()
    if args.scenarios:
        return run_scenarios(args)
    prof = dict(PROFILES[args.profile])
    epochs = args.epochs or prof['epochs']
    if args.classes:
        prof['classes'] = args.classes

    os.makedirs(args.workdir, exist_ok=True)
    prefix = build_dataset(args.workdir, prof['classes'], prof['contexts'],
                           lang=prof.get('lang', 'java'))

    model_dir = os.path.join(args.workdir, 'model_%s' % args.profile)
    cmd = [sys.executable, '-m', 'code2vec_tpu.cli',
           '--data', prefix, '--test', prefix + '.val.c2v',
           '--save', os.path.join(model_dir, 'saved_model'),
           '--framework', 'jax', '--epochs', str(epochs),
           '--batch-size', str(prof['batch'])] + prof['extra_args']
    env = dict(os.environ, PYTHONPATH=_pythonpath())
    if args.profile.startswith('cpu'):
        env['JAX_PLATFORMS'] = 'cpu'
        # dims are Config attributes without CLI flags (reference-style):
        # drive the CLI through a tiny wrapper instead. cpu_full keeps the
        # config's real dims (128/128/384) and only pins MAX_CONTEXTS.
        dims = CPU_DIMS if args.profile == 'cpu' else {}
        wrapper = os.path.join(args.workdir, 'cli_cpu.py')
        with open(wrapper, 'w') as f:
            f.write(
                'import sys\n'
                'sys.argv[0] = "code2vec_tpu.cli"\n'
                'from code2vec_tpu import cli\n'
                'from code2vec_tpu.config import Config\n'
                'overrides = %r\n'
                'original = Config.load_from_args\n'
                'def patched(self, a=None):\n'
                '    original(self, a)\n'
                '    for k, v in overrides.items():\n'
                '        setattr(self, k, v)\n'
                '    self.MAX_CONTEXTS = %d\n'
                '    return self\n'
                'Config.load_from_args = patched\n'
                'cli.main()\n' % (dims, prof['contexts']))
        cmd = [sys.executable, wrapper] + cmd[3:]

    t0 = time.time()
    proc = subprocess.Popen(cmd, cwd=REPO, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    import collections
    curve = []
    lines = collections.deque(maxlen=15)  # error tail only
    pending = None  # (epoch, loss) awaiting its precision/recall/F1 tail
    for line in proc.stdout:
        lines.append(line)
        sys.stderr.write(line)
        head = EPOCH_HEAD_RE.search(line)
        if head:
            pending = (int(head.group(1)), float(head.group(2)))
        tail = EPOCH_TAIL_RE.search(line)
        if tail and pending is not None:
            point = {'epoch': pending[0],
                     'val_loss': pending[1],
                     'precision': float(tail.group(1)),
                     'recall': float(tail.group(2)),
                     'f1': float(tail.group(3)),
                     'elapsed_s': round(time.time() - t0, 1)}
            pending = None
            curve.append(point)
            print(json.dumps({'measure': 'accuracy_epoch', **point}),
                  flush=True)
    rc = proc.wait()
    if rc != 0:
        print(json.dumps({'error': 'train_failed', 'rc': rc,
                          'tail': ''.join(lines)[-2000:]}))
        sys.exit(1)

    out = args.out or os.path.join(
        REPO, 'benchmarks', 'results',
        'accuracy_%s.json' % args.profile)
    baseline = majority_baseline(prefix)
    # corpus-shape evidence (VERDICT r3 #6): Zipf slopes, singleton tail,
    # contexts/method spread vs the reference anchors
    raw_train = os.path.join(os.path.dirname(prefix),
                             'train_%d.raw' % prof['classes'])
    result = {
        'profile': args.profile,
        'dataset': {'word_vocab': WORD_VOCAB, 'path_vocab': PATH_VOCAB,
                    'target_vocab': TARGET_VOCAB,
                    'classes': prof['classes'],
                    'max_contexts': prof['contexts'],
                    'batch': prof['batch'],
                    **dataset_stats(prefix, raw_train)},
        'corpus_stats': {
            'ours': corpus_stats_mod.scan(raw_train),
            'reference_anchor': corpus_stats_mod.REFERENCE_ANCHOR},
        'curve': curve,
        'best_f1': max((p['f1'] for p in curve), default=0.0),
        'majority_baseline': baseline,
        'total_s': round(time.time() - t0, 1),
    }
    with open(out, 'w') as f:
        json.dump(result, f, indent=1)
    print(json.dumps({'measure': 'accuracy_at_scale_best_f1',
                      'value': result['best_f1'],
                      'out': os.path.relpath(out, REPO)}), flush=True)


if __name__ == '__main__':
    main()
