"""On-chip A/B: Adam second-moment storage dtype (ADAM_NU_DTYPE).

The nu tree is the last full-precision optimizer stream in the dense
update after the measured ADAM_MU_DTYPE flip: 1.54 GB fp32 at java14m's
384M params, read+write every step (~1.9 ms/step analytic at the measured
~819 GB/s — PERF.md roofline). This measures the current default recipe
(rbg dropout + bf16 mu, the 2026-07-31 flips) against the same recipe
with nu stored bf16 (training/adam_dtypes.py), to decide whether
ADAM_NU_DTYPE joins the defaults under the >=2% flip rule.

Prints one JSON line per measurement (chained sync-at-end methodology,
benchmarks/diag_step_breakdown.py / PERF.md).
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP, STEPS = benchlib.bench_steps(SMOKE)


def measure(label: str, **overrides) -> None:
    config = benchlib.headline_config(SHAPES, **overrides)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    feeds = benchlib.staged(trainer, benchlib.random_batches(SHAPES, 4))
    for i in range(WARMUP):
        state, loss = trainer.train_step_placed(state, feeds[i % len(feeds)])
        float(loss)
    t0 = time.perf_counter()
    last = None
    for i in range(STEPS):
        state, last = trainer.train_step_placed(state, feeds[i % len(feeds)])
    float(last)
    dt = (time.perf_counter() - t0) / STEPS
    if SMOKE:
        label += '_SMOKE_ONLY'
    print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2),
                      'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
          flush=True)


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)
    # Arms pin every knob the A/B touches — INCLUDING GRADS_DTYPE in the
    # nu-only arms: if its default ever flips, an unpinned baseline
    # would silently absorb the flip and corrupt the nu attribution.
    measure('step_ms_nu_fp32',
            DROPOUT_PRNG_IMPL='rbg', ADAM_MU_DTYPE='bfloat16',
            ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32')
    measure('step_ms_nu_bf16',
            DROPOUT_PRNG_IMPL='rbg', ADAM_MU_DTYPE='bfloat16',
            ADAM_NU_DTYPE='bfloat16', GRADS_DTYPE='float32')
    # Cross-check: bf16 nu alone against the pre-flip parity recipe, so
    # the lever's solo effect is attributable (mirrors how mu was
    # measured in bench_rbg_dropout.py).
    measure('step_ms_nu_bf16_parity_recipe',
            DROPOUT_PRNG_IMPL='threefry2x32', ADAM_MU_DTYPE='float32',
            ADAM_NU_DTYPE='bfloat16', GRADS_DTYPE='float32')
    # GRADS_DTYPE='bfloat16' (bf16 table-grad scatters + grad tree,
    # trainer.py cast_for_grads): solo on the default recipe, then the
    # full combined candidate (rbg + bf16 mu + bf16 nu + bf16 grads).
    measure('step_ms_grads_bf16',
            DROPOUT_PRNG_IMPL='rbg', ADAM_MU_DTYPE='bfloat16',
            ADAM_NU_DTYPE='float32', GRADS_DTYPE='bfloat16')
    measure('step_ms_nu_and_grads_bf16',
            DROPOUT_PRNG_IMPL='rbg', ADAM_MU_DTYPE='bfloat16',
            ADAM_NU_DTYPE='bfloat16', GRADS_DTYPE='bfloat16')


if __name__ == '__main__':
    main()
