"""On-chip A/B: threefry vs hardware-RNG (`rbg`) dropout mask.

The 2026-07-29 diag capture showed dropout's ~131M threefry draws cost
~4.8 ms of the 49.25 ms java14m train step (PERF.md). This measures the
same devargs/sync-at-end step with `DROPOUT_PRNG_IMPL='rbg'` against the
default, to decide whether the knob should become the TPU default.

Prints one JSON line per measurement (same chained methodology as
benchmarks/diag_step_breakdown.py).
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
# Shared methodology: one end-of-chain sync amortizes the ~70 ms tunnel RTT
# to <2.5%/step only at the benchlib step counts (10 warmup / 60 measured);
# hardcoding fewer steps made ms/step incomparable with the diag table.
WARMUP, STEPS = benchlib.bench_steps(SMOKE)


def measure(label: str, **overrides) -> None:
    config = benchlib.headline_config(SHAPES, **overrides)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    feeds = benchlib.staged(trainer, benchlib.random_batches(SHAPES, 4))
    for i in range(WARMUP):
        state, loss = trainer.train_step_placed(state, feeds[i % len(feeds)])
        float(loss)
    t0 = time.perf_counter()
    last = None
    for i in range(STEPS):
        state, last = trainer.train_step_placed(state, feeds[i % len(feeds)])
    float(last)
    dt = (time.perf_counter() - t0) / STEPS
    if SMOKE:
        label += '_SMOKE_ONLY'  # never mistakable for a java14m capture
    print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2),
                      'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
          flush=True)


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)
    # Every arm pins BOTH knobs explicitly: the config DEFAULTS are now
    # 'rbg' + bf16 mu (flipped on this A/B's own 2026-07-31 capture), so
    # any unpinned "baseline" arm would silently measure default vs
    # default and report a ~0 delta.
    pins = dict(ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32')
    measure('step_ms_dropout_threefry', DROPOUT_PRNG_IMPL='threefry2x32',
            ADAM_MU_DTYPE='float32', **pins)
    measure('step_ms_dropout_rbg', DROPOUT_PRNG_IMPL='rbg',
            ADAM_MU_DTYPE='float32', **pins)
    measure('step_ms_bf16_mu', DROPOUT_PRNG_IMPL='threefry2x32',
            ADAM_MU_DTYPE='bfloat16', **pins)
    measure('step_ms_rbg_and_bf16_mu',
            DROPOUT_PRNG_IMPL='rbg', ADAM_MU_DTYPE='bfloat16', **pins)


if __name__ == '__main__':
    main()
