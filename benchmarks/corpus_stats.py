"""Corpus-statistics comparison: the synthetic corpus vs real-Java shape.

VERDICT r3 #6: the accuracy-at-scale corpus is a template grammar; a
committed statistics table is the evidence that its token/path/target
distributions stress the model the way real Java does — or an honest
record of where they don't. Computed from the extractor's raw output
(label ctx ctx ...; ctx = token,path,token):

- unique token / path / target counts and their ratios to method count;
- Zipf slope per vocabulary (least-squares on log rank vs log frequency
  over the top ranks — identifier frequencies in real code follow a
  power law with slope roughly -1);
- contexts/method distribution (mean / p50 / p90 / max);
- singleton fraction (share of vocab seen exactly once — the long tail
  that vocab truncation turns into OOV pressure).

Reference anchors (public facts about the reference's corpora):
- java-small: ~700K methods total (reference README.md:306-311);
- java14m headline vocab truncation: 1.3M token / 911K path / 261K
  target kept from a much larger raw stream (reference README.md:69,
  config.py:47-70 defaults).

Usage:
  python benchmarks/corpus_stats.py --raw /tmp/acc_r4/data/train.raw \
      [--out benchmarks/results/corpus_stats_r4.json]
"""
from __future__ import annotations

import argparse
import json
import math
import os
from collections import Counter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def zipf_slope(counter: Counter, top: int = 1000) -> float:
    """Least-squares slope of log(freq) vs log(rank) over the top ranks.
    Real-code identifier distributions run roughly -1 (Zipf's law); a
    corpus whose slope is much shallower has too little head reuse, much
    steeper has too little tail."""
    freqs = [c for _, c in counter.most_common(min(top, len(counter)))]
    if len(freqs) < 10:
        return float('nan')
    xs = [math.log(r + 1) for r in range(len(freqs))]
    ys = [math.log(f) for f in freqs]
    n = len(xs)
    mx = sum(xs) / n
    my = sum(ys) / n
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    var = sum((x - mx) ** 2 for x in xs)
    return round(cov / var, 3)


def percentile(sorted_vals, q: float):
    if not sorted_vals:
        return 0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def scan(raw_path: str) -> dict:
    tokens = Counter()
    paths = Counter()
    targets = Counter()
    contexts_per_method = []
    with open(raw_path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            targets[parts[0]] += 1
            n = 0
            for ctx in parts[1:]:
                pieces = ctx.split(',')
                if len(pieces) != 3:
                    continue
                tokens[pieces[0]] += 1
                tokens[pieces[2]] += 1
                paths[pieces[1]] += 1
                n += 1
            contexts_per_method.append(n)
    contexts_per_method.sort()
    methods = len(contexts_per_method)

    def vocab_stats(counter: Counter) -> dict:
        singletons = sum(1 for c in counter.values() if c == 1)
        return {
            'unique': len(counter),
            'occurrences': sum(counter.values()),
            'zipf_slope_top1000': zipf_slope(counter),
            'singleton_fraction': round(singletons / max(len(counter), 1),
                                        4),
        }

    return {
        'methods': methods,
        'token': vocab_stats(tokens),
        'path': vocab_stats(paths),
        'target': vocab_stats(targets),
        'contexts_per_method': {
            'mean': round(sum(contexts_per_method) / max(methods, 1), 1),
            'p50': percentile(contexts_per_method, 0.5),
            'p90': percentile(contexts_per_method, 0.9),
            'max': contexts_per_method[-1] if contexts_per_method else 0,
        },
        'uniques_per_1k_methods': {
            'token': round(1000 * len(tokens) / max(methods, 1), 1),
            'path': round(1000 * len(paths) / max(methods, 1), 1),
            'target': round(1000 * len(targets) / max(methods, 1), 1),
        },
    }


REFERENCE_ANCHOR = {
    # public facts about the reference's corpora, for the comparison table
    'java_small_methods': 700_000,          # reference README.md:306-311
    'java14m_vocab_kept': {'token': 1_300_000, 'path': 911_000,
                           'target': 261_000},   # README.md:69
    'identifier_zipf_slope_expected': -1.0,
    'notes': ('java-small publishes only its method count; the vocab-kept '
              'numbers are java14m\'s headline truncation targets. The '
              'synthetic corpus is judged on SHAPE (Zipf slope, singleton '
              'tail, contexts/method spread) and on exercising the same '
              'truncation/OOV machinery, not on absolute scale.'),
}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--raw', required=True,
                        help='extractor raw output (train split)')
    parser.add_argument('--out', default=None)
    parser.add_argument('--label', default='train')
    args = parser.parse_args()
    ours = scan(args.raw)
    result = {
        'measure': 'corpus_stats',
        'split': args.label,
        'raw_file': args.raw,
        'ours': ours,
        'reference_anchor': REFERENCE_ANCHOR,
        'scale_vs_java_small': round(
            ours['methods'] / REFERENCE_ANCHOR['java_small_methods'], 4),
    }
    print(json.dumps(result))
    if args.out:
        with open(args.out, 'w') as f:
            json.dump(result, f, indent=1)


if __name__ == '__main__':
    main()
