"""Embedding-index benchmark: exact vs IVF throughput/recall curves
(ISSUE 5 acceptance).

Measures, on a synthetic clustered corpus (a Gaussian mixture — code
vectors cluster by semantics; that is the paper's premise):

- ``naive``  — the no-index baseline: a per-query NumPy host loop
  (full dot-product scan + argsort), the shape of the reference's
  embedding-similarity demos.
- ``exact``  — the device-resident warm tier (index/exact.py): batched
  queries through the pre-compiled bucket ladder. The post-warmup XLA
  compile count is measured via the telemetry jit listener and emitted
  (must be 0 — asserted in tests/test_bench_smoke.py).
- ``ivf``    — the approximate tier (index/ivf.py): recall@10 vs the
  exact tier and throughput, swept over nprobe.

Prints one JSON line per metric:
  {"metric": "index_exact_queries_per_sec", "value": ...}
  {"metric": "index_naive_queries_per_sec", "value": ...}
  {"metric": "index_exact_speedup_vs_numpy", "value": ...,
   "postwarm_compiles": 0}
  {"metric": "index_ivf_recall_at10", "value": ..., "nprobe": ...}
  {"metric": "index_ivf_curve", "points": [{"nprobe", "recall",
   "queries_per_sec"}, ...]}
  {"metric": "index_quant_recall_at10", "kind": "int8"|"pq", ...}
  {"metric": "index_quant_queries_per_sec", "kind": ...,
   "device_bytes_per_vector": ..., "compression_vs_f16": ...,
   "postwarm_compiles": 0}
  {"metric": "index_quant_insert_vectors_per_sec", "rows": ...,
   "self_hit_at1": ..., "segments": ...}

BENCH_SMOKE=1 shrinks the corpus for a CPU smoke run (metrics carry a
``smoke`` field). On-chip runs go through benchmarks/capture_all.sh
(stage ``index``).

Usage: python benchmarks/bench_index.py [--vectors N] [--dim D]
       [--queries Q] [--clusters C] [--dtype float32|float16]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402


def synthesize_corpus(n: int, dim: int, n_centers: int, seed: int = 0,
                      spread: float = 0.15) -> np.ndarray:
    """Gaussian-mixture corpus: unit-norm centers, intra-cluster noise
    of NORM ~``spread`` (per-coordinate σ = spread/sqrt(dim), so cluster
    tightness is dimension-independent — at σ=0.15 per coordinate a
    384-dim 'cluster' would have noise norm ~3 and be isotropic)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_centers, dim))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, n_centers, n)
    sigma = spread / np.sqrt(dim)
    return (centers[assign]
            + sigma * rng.normal(size=(n, dim))).astype(np.float32)


def naive_numpy_search(vectors_normed: np.ndarray, queries: np.ndarray,
                       k: int):
    """The no-index host loop: one full scan + argsort PER QUERY (the
    reference demo shape). Deliberately per-query — this is the baseline
    the index replaces, not a tuned BLAS batch."""
    out = []
    for q in queries:
        qn = q / max(np.linalg.norm(q), 1e-12)
        scores = vectors_normed @ qn
        top = np.argsort(-scores, kind='stable')[:k]
        out.append(top)
    return np.stack(out)


def main() -> None:
    benchlib.honor_env_platforms()
    smoke = benchlib.smoke_requested()
    parser = argparse.ArgumentParser()
    parser.add_argument('--vectors', type=int,
                        default=6000 if smoke else 50000)
    parser.add_argument('--dim', type=int, default=32 if smoke else 384)
    parser.add_argument('--queries', type=int,
                        default=64 if smoke else 256)
    parser.add_argument('--centers', type=int,
                        default=60 if smoke else 500)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--dtype', default='float32',
                        choices=['float32', 'float16'])
    parser.add_argument('--reps', type=int, default=3,
                        help='repetitions per variant; best wall time '
                             'reported (host-jitter control)')
    parser.add_argument('--arms', default='all',
                        choices=['all', 'base', 'quant'],
                        help="'base' = naive/exact/ivf (capture stage "
                             "`index`), 'quant' = int8/pq + insert "
                             "(stage `index_quant`; the exact tier "
                             "still builds as the recall baseline)")
    args = parser.parse_args()
    base_arms = args.arms in ('all', 'base')
    quant_arms = args.arms in ('all', 'quant')

    from code2vec_tpu.index import store as store_lib
    from code2vec_tpu.index.exact import ExactIndex
    from code2vec_tpu.index.ivf import IVFIndex, measure_recall
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    vectors = synthesize_corpus(args.vectors, args.dim, args.centers)
    rng = np.random.default_rng(1)
    queries = (vectors[rng.choice(args.vectors, args.queries)]
               + (0.05 / np.sqrt(args.dim))
               * rng.normal(size=(args.queries, args.dim))
               ).astype(np.float32)

    workdir = tempfile.mkdtemp(prefix='c2v_idxbench_')
    store = store_lib.build(os.path.join(workdir, 'bench.vecindex'),
                            [vectors], dtype=args.dtype, metric='cosine')

    # ---- naive numpy host loop
    if base_arms:
        normed = store.all_rows().astype(np.float32)
        naive_s = min(benchlib.bench_timer_wall(
            lambda: naive_numpy_search(normed, queries, args.k))
            for _ in range(args.reps))
        emit({'metric': 'index_naive_queries_per_sec',
              'value': args.queries / naive_s})

    # ---- exact tier, warm; compile counter must stay flat after warmup
    core.reset()
    core.enable()
    try:
        install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        index = ExactIndex(store).warmup(args.k)
        index.search(queries, args.k)  # one full-shape warm pass
        warm_compiles = compiles.value
        exact_s = min(benchlib.bench_timer_wall(
            lambda: index.search(queries, args.k))
            for _ in range(args.reps))
        postwarm = compiles.value - warm_compiles
    finally:
        core.disable()
        core.reset()
    emit({'metric': 'index_exact_queries_per_sec',
          'value': args.queries / exact_s, 'dtype': args.dtype,
          'vectors': args.vectors})
    if base_arms:
        emit({'metric': 'index_exact_speedup_vs_numpy',
              'value': naive_s / exact_s, 'postwarm_compiles': postwarm})

    # ---- IVF: recall + throughput across nprobe
    if base_arms:
        ivf = IVFIndex.build(store, persist=False)
        points = []
        nprobe = 1
        while nprobe <= min(64, ivf.n_clusters):
            recall = measure_recall(ivf, index, queries, k=args.k,
                                    nprobe=nprobe)
            ivf.search(queries, args.k, nprobe=nprobe)  # warm this shape
            ivf_s = min(benchlib.bench_timer_wall(
                lambda: ivf.search(queries, args.k, nprobe=nprobe))
                for _ in range(args.reps))
            points.append({'nprobe': nprobe, 'recall': round(recall, 4),
                           'queries_per_sec': args.queries / ivf_s})
            nprobe *= 2
        default_recall = measure_recall(ivf, index, queries, k=args.k)
        emit({'metric': 'index_ivf_recall_at10', 'value': default_recall,
              'nprobe': ivf.nprobe, 'clusters': ivf.n_clusters,
              'vectors': args.vectors})
        emit({'metric': 'index_ivf_curve', 'points': points})

    # ---- quantized tier: f16 (above) vs int8 vs PQ — QPS, recall@10
    # vs exact, device bytes/vector, zero post-warmup compiles
    if quant_arms:
        from code2vec_tpu.index.quant import QuantizedIVFIndex
        f16_bpv = 2 * args.dim
        quant = None
        for kind in ('int8', 'pq'):
            core.reset()
            core.enable()
            try:
                install_compile_listener()
                compiles = core.registry().counter('jit/compiles_total')
                quant = QuantizedIVFIndex.build(store, kind=kind)
                quant.warmup(args.k)
                quant.search(queries, args.k)  # full-shape warm pass
                warm_compiles = compiles.value
                quant_s = min(benchlib.bench_timer_wall(
                    lambda: quant.search(queries, args.k))
                    for _ in range(args.reps))
                postwarm = compiles.value - warm_compiles
            finally:
                core.disable()
                core.reset()
            recall = measure_recall(quant, index, queries, k=args.k)
            emit({'metric': 'index_quant_recall_at10', 'kind': kind,
                  'value': recall, 'rerank': quant.rerank,
                  'vectors': args.vectors})
            emit({'metric': 'index_quant_queries_per_sec', 'kind': kind,
                  'value': args.queries / quant_s,
                  'postwarm_compiles': postwarm,
                  'device_bytes_per_vector': quant.bytes_per_vector,
                  'f16_bytes_per_vector': f16_bpv,
                  'compression_vs_f16': f16_bpv / quant.bytes_per_vector})

        # ---- live-insert arm (on the PQ index from the last loop
        # turn): encode + page + device refresh throughput, and the
        # inserted rows must be queryable immediately (no rebuild)
        insert_rows = 512 if smoke else 8192
        extra = synthesize_corpus(insert_rows, args.dim, args.centers,
                                  seed=7)
        t0 = time.perf_counter()
        row_ids = quant.insert(extra)
        insert_s = time.perf_counter() - t0
        probe = extra[:min(32, insert_rows)].astype(np.float32)
        _scores, got = quant.search(probe, 1)
        hit = float(np.mean([int(got[i, 0]) == int(row_ids[i])
                             for i in range(probe.shape[0])]))
        emit({'metric': 'index_quant_insert_vectors_per_sec',
              'kind': 'pq', 'value': insert_rows / insert_s,
              'rows': insert_rows, 'self_hit_at1': hit,
              'segments': quant.segment_count})

    # per-stage peak HBM (ISSUE 9): covers the exact store residency
    # AND the IVF cluster-sorted copy on this backend
    emit({'metric': 'index_peak_hbm_bytes',
          **benchlib.device_memory_record()})


if __name__ == '__main__':
    main()
