"""On-chip A/B: embedding-table gradient strategies (ops/embed_grad.py).

Measures the full java14m train step under EMBED_GRAD_IMPL in {'dense',
'sorted', 'dedup'} over two index distributions:

- uniform — benchlib.random_batches, the headline bench's synthetic data
  (~93% of gathered token rows unique: dedup has little to combine);
- zipf    — Zipf(1.3)-distributed indices, matching how real corpora hit
  the frequency-ordered vocab (code2vec vocabs are built most-frequent-
  first, so hot rows cluster at low indices); most draws repeat, which is
  the case 'dedup' exists for.

Same chained devargs/sync-at-end methodology as the other harnesses
(PERF.md); prints one JSON line per measurement.
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
WARMUP, STEPS = benchlib.bench_steps(SMOKE)


def zipf_batches(shapes, n: int, seed: int = 0, a: float = 1.3):
    """Synthetic batches whose indices follow a Zipf law over the vocab,
    approximating real frequency-ordered corpus hits."""
    from code2vec_tpu.data.reader import Batch
    rng = np.random.default_rng(seed)

    def draw(vocab, size):
        raw = rng.zipf(a, size=size).astype(np.int64)
        return (1 + (raw - 1) % (vocab - 1)).astype(np.int32)

    batch, contexts = shapes.batch_size, shapes.max_contexts
    return [Batch(
        source=draw(shapes.token_vocab, (batch, contexts)),
        path=draw(shapes.path_vocab, (batch, contexts)),
        target=draw(shapes.token_vocab, (batch, contexts)),
        mask=np.ones((batch, contexts), np.float32),
        label=draw(shapes.target_vocab, (batch,)),
        weight=np.ones((batch,), np.float32)) for _ in range(n)]


def measure(label: str, host_batches, **overrides) -> None:
    config = benchlib.headline_config(SHAPES, **overrides)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    feeds = benchlib.staged(trainer, host_batches)
    for i in range(WARMUP):
        state, loss = trainer.train_step_placed(state, feeds[i % len(feeds)])
        float(loss)
    t0 = time.perf_counter()
    last = None
    for i in range(STEPS):
        state, last = trainer.train_step_placed(state, feeds[i % len(feeds)])
    float(last)
    dt = (time.perf_counter() - t0) / STEPS
    if SMOKE:
        label += '_SMOKE_ONLY'
    print(json.dumps({'measure': label, 'value': round(dt * 1e3, 2),
                      'examples_per_sec': round(SHAPES.batch_size / dt, 1)}),
          flush=True)


def main() -> None:
    import jax

    benchlib.honor_env_platforms()
    print(json.dumps({'platform': jax.devices()[0].platform.lower()}),
          flush=True)
    uniform = benchlib.random_batches(SHAPES, 4)
    zipf = zipf_batches(SHAPES, 4)
    # duplicate-rate context so the verdict is interpretable
    for name, batches in (('uniform', uniform), ('zipf', zipf)):
        tok = np.concatenate([np.asarray(b.source).ravel() for b in batches[:1]]
                             + [np.asarray(b.target).ravel()
                                for b in batches[:1]])
        print(json.dumps({'measure': f'unique_token_rows_frac_{name}',
                          'value': round(len(np.unique(tok)) / tok.size, 4)}),
              flush=True)
    # Arms pin the threefry + fp32-mu baseline knobs: the config DEFAULTS
    # flipped to rbg + bf16 mu on the 2026-07-31 capture, and a re-run
    # must stay comparable with the recorded 2026-07-31 series the
    # EMBED_GRAD_IMPL='dense' verdict cites (PERF.md).
    pins = dict(DROPOUT_PRNG_IMPL='threefry2x32', ADAM_MU_DTYPE='float32',
                ADAM_NU_DTYPE='float32', GRADS_DTYPE='float32')
    for impl in ('dense', 'sorted', 'dedup'):
        measure(f'step_ms_embed_grad_{impl}_uniform', uniform,
                EMBED_GRAD_IMPL=impl, **pins)
    for impl in ('dense', 'sorted', 'dedup'):
        measure(f'step_ms_embed_grad_{impl}_zipf', zipf,
                EMBED_GRAD_IMPL=impl, **pins)


if __name__ == '__main__':
    main()
