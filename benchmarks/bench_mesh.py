"""Serving-mesh benchmark: p99-under-load at FIXED offered load as the
replica count scales (SERVING.md "Serving mesh").

An open-loop load generator submits a mixed tier/size profile — topk +
attention predict requests and ``submit_neighbors`` vectors traffic in
ONE dispatch stream — at a fixed offered rate against a 1-, 2-, and
4-replica mesh over the same model.  Offered load is calibrated to
~2.2x one replica's measured capacity, so the single-replica arm
saturates (admission sheds the excess) while the larger fleets absorb
it: the measured gate is SUSTAINED ADMITTED THROUGHPUT, plus p99
latency over delivered requests, shed/expired rates, per-replica
device fill, and dispatch share.  The telemetry compile counter runs
across every arm — steady-state mesh serving (mixed tiers included)
must compile NOTHING after warmup.

Prints one JSON line per metric:
  {"metric": "mesh_offered_rows_per_sec", "value": ...}
  {"metric": "mesh_admitted_rows_per_sec", "replicas": N, "value": ...,
   "p50_ms": ..., "p99_ms": ..., "shed_rate": ..., "per_replica_fill":
   [...], "dispatch_share": [...], "postwarm_compiles": 0, ...}
  {"metric": "mesh_scaling_2x", "value": admitted_2/admitted_1, ...}

Interpreting the scaling number: replica threads parallelize the
per-batch host pipeline (pack/h2d/dispatch/decode) and concurrent XLA
executions — on a MULTI-core host 2 replicas sustain >= 1.8x one
replica's admitted throughput at this profile; a 1-core container
cannot parallelize anything, so the record carries ``host_cores`` and
the smoke guard (tests/test_bench_smoke.py) gates the ratio assertion
on it.  On-chip runs go through benchmarks/capture_all.sh (stage
``mesh``).

BENCH_SMOKE=1 shrinks shapes, rates, and durations for the CPU smoke
(metrics carry a ``smoke`` field).

Usage: python benchmarks/bench_mesh.py [--replica-counts 1,2,4]
       [--offered-factor 2.2] [--secs S] [--deadline-ms MS]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402
from benchmarks.bench_serving import synthesize_dataset  # noqa: E402


class _MiniIndex:
    """Tiny host-side k-NN over a handful of corpus vectors: enough to
    give the ``submit_neighbors`` leg its real shape (vectors-tier
    dispatch through the shared stream, then an index lookup on the
    completion path) without dragging an index build into the bench."""

    def __init__(self, dim: int, n: int = 64, seed: int = 7):
        rng = np.random.default_rng(seed)
        self.vectors = rng.standard_normal((n, dim)).astype(np.float32)
        self.labels = np.array(['method|%d' % i for i in range(n)],
                               dtype=object)

    def search(self, queries, k):
        scores = queries.astype(np.float32) @ self.vectors.T
        idx = np.argsort(-scores, axis=1)[:, :k]
        return np.take_along_axis(scores, idx, axis=1), idx


def make_profile(lines, n_requests: int, max_lines: int, seed: int = 3):
    """Mixed tier/size request profile: ragged sizes, 60% topk / 20%
    attention / 20% neighbors (vectors tier through submit_neighbors)."""
    rng = random.Random(seed)
    profile = []
    for _ in range(n_requests):
        draw = rng.random()
        kind = ('topk' if draw < 0.6 else
                'attention' if draw < 0.8 else 'neighbors')
        request_lines = [rng.choice(lines)
                         for _ in range(rng.randint(1, max_lines))]
        profile.append((kind, request_lines))
    return profile


def make_zipf_profile(lines, n_requests: int, max_lines: int,
                      n_templates: int, alpha: float, seed: int = 5,
                      vec_dim: int = 0, vec_share: float = 0.15):
    """Zipf-replayed duplicate-heavy traffic: a pool of distinct request
    templates replayed with probability proportional to 1/rank^alpha —
    the fleet-traffic shape the memoization tier exists for (hot
    methods arrive over and over; SERVING.md "Memoization tier").
    ``vec_share`` of the templates are single-row VECTOR neighbor
    queries replayed with per-request jitter: near-identical but never
    byte-identical, so exact dedup cannot catch them — the semantic
    tier's traffic."""
    templates = make_profile(lines, n_templates, max_lines, seed=seed)
    rng = np.random.default_rng(seed)
    if vec_dim:
        for t in range(n_templates):
            if rng.random() < vec_share:
                base = rng.standard_normal(vec_dim).astype(np.float32)
                templates[t] = ('neighbors_vec', base)
    ranks = np.arange(1, n_templates + 1, dtype=np.float64)
    weights = ranks ** -alpha
    weights /= weights.sum()
    picks = rng.choice(n_templates, size=n_requests, p=weights)
    profile = []
    for i in picks:
        kind, payload = templates[int(i)]
        if kind == 'neighbors_vec':
            jitter = rng.standard_normal(vec_dim).astype(np.float32)
            payload = payload + np.float32(1e-4) * jitter
        profile.append((kind, payload))
    return profile


def run_arm(model, index, profile, replicas: int, offered_rows_per_s: float,
            deadline_ms: float, compiles, generators: int = 4) -> dict:
    """One fixed-offered-load arm against an n-replica mesh.  The
    arrival schedule (request i lands at cumulative_rows_before_i /
    offered rate) is precomputed and driven by ``generators`` paced
    submitter threads — caller-thread tokenize is part of the serving
    contract, so a single generator thread would itself become the
    bottleneck and silently under-offer the fleet (the achieved rate is
    reported so a generator-limited arm is visible, not hidden)."""
    import threading
    from code2vec_tpu.serving.errors import (DeadlineExceeded,
                                             EngineOverloaded)
    mesh = model.serving_mesh(
        replicas=replicas, tiers=('topk', 'attention', 'vectors'),
        max_delay_ms=2.0, deadline_ms=deadline_ms)
    mesh.attach_index(index)
    warm_compiles = compiles.value if compiles is not None else 0
    delivered_rows = [0]
    latencies = []
    lat_lock = threading.Lock()
    # absolute arrival offsets for the whole profile
    offsets = []
    cum_rows = 0
    for _kind, lines in profile:
        offsets.append(cum_rows / offered_rows_per_s)
        cum_rows += len(lines)
    shed_counts = [0] * generators
    expired_counts = [0] * generators
    futures_per: list = [[] for _ in range(generators)]
    last_submit = [0.0] * generators
    t0 = time.perf_counter()

    def generator(g: int) -> None:
        for i in range(g, len(profile), generators):
            kind, lines = profile[i]
            target = t0 + offsets[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            t_submit = time.perf_counter()
            try:
                if kind == 'neighbors':
                    future = mesh.submit_neighbors(lines)
                else:
                    future = mesh.submit(lines, tier=kind)
            except EngineOverloaded:
                shed_counts[g] += 1
                last_submit[g] = time.perf_counter()
                continue

            def stamp(done, t_submit=t_submit, rows=len(lines)):
                if done.exception() is None:
                    with lat_lock:
                        latencies.append(time.perf_counter() - t_submit)
                        delivered_rows[0] += rows
            future.add_done_callback(stamp)
            futures_per[g].append(future)
            last_submit[g] = time.perf_counter()

    try:
        threads = [threading.Thread(target=generator, args=(g,))
                   for g in range(generators)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        for g in range(generators):
            for future in futures_per[g]:
                try:
                    future.result(timeout=600)
                except DeadlineExceeded:
                    expired_counts[g] += 1
                except EngineOverloaded:
                    shed_counts[g] += 1
        wall = time.perf_counter() - t0
        submit_wall = max(last_submit) - t0
        stats = mesh.stats()
        per_replica = mesh.replica_stats()
    finally:
        mesh.close()
    postwarm = (compiles.value - warm_compiles
                if compiles is not None else None)
    shed = sum(shed_counts)
    expired = sum(expired_counts)
    lat_ms = np.asarray(sorted(latencies)) * 1e3
    total = len(profile)
    return {
        'replicas': replicas,
        'value': round(delivered_rows[0] / wall, 1),
        'delivered_rows': delivered_rows[0],
        'offered_rows_per_sec': round(offered_rows_per_s, 1),
        'achieved_offer_rows_per_sec':
            round(cum_rows / max(1e-9, submit_wall), 1),
        'wall_s': round(wall, 2),
        'p50_ms': (round(float(np.percentile(lat_ms, 50)), 2)
                   if len(lat_ms) else None),
        'p99_ms': (round(float(np.percentile(lat_ms, 99)), 2)
                   if len(lat_ms) else None),
        'shed_rate': round(shed / total, 3),
        'expired_rate': round(expired / total, 3),
        'mesh_shed_total': stats['shed_total'],
        'mesh_expired_total': stats['expired_total'],
        'per_replica_fill': [
            round(float(s['batch_fill_rate']), 3) for s in per_replica],
        'dispatch_share': [
            round(r['dispatch_share'], 3) for r in stats['replicas']],
        'replica_batches': [r['batches'] for r in stats['replicas']],
        'postwarm_compiles': postwarm,
    }


def run_memo_arm(model, index, profile, offered_rows_per_s: float,
                 deadline_ms: float, compiles, memo_bytes: int,
                 epsilon: float, capacity: float,
                 generators: int = 4) -> dict:
    """One Zipf-replay arm: the same paced open-loop driver as
    ``run_arm``, but latencies split at the SUBMIT boundary — a memo
    hit comes back already resolved (``future.done()`` on return), so
    cache-served and live-served p99 are measured separately.  Device
    work is the mesh's ``rows_dispatched`` (a hit never dispatches);
    device-seconds-per-1k-requests is the host-side proxy
    rows_dispatched / one replica's measured capacity."""
    import threading
    from code2vec_tpu.serving.errors import (DeadlineExceeded,
                                             EngineOverloaded)
    mesh = model.serving_mesh(
        replicas=1, tiers=('topk', 'attention', 'vectors'),
        max_delay_ms=2.0, deadline_ms=deadline_ms,
        memo_cache_bytes=memo_bytes, memo_semantic_epsilon=epsilon)
    mesh.attach_index(index)
    warm_compiles = compiles.value if compiles is not None else 0
    cache_lat: list = []
    live_lat: list = []
    vec_lat: list = []
    lat_lock = threading.Lock()
    offsets = []
    cum_rows = 0
    for kind, payload in profile:
        offsets.append(cum_rows / offered_rows_per_s)
        cum_rows += 1 if kind == 'neighbors_vec' else len(payload)
    shed_counts = [0] * generators
    expired_counts = [0] * generators
    futures_per: list = [[] for _ in range(generators)]
    t0 = time.perf_counter()

    def generator(g: int) -> None:
        for i in range(g, len(profile), generators):
            kind, payload = profile[i]
            target = t0 + offsets[i]
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            t_submit = time.perf_counter()
            try:
                if kind in ('neighbors', 'neighbors_vec'):
                    future = mesh.submit_neighbors(payload)
                else:
                    future = mesh.submit(payload, tier=kind)
            except EngineOverloaded:
                shed_counts[g] += 1
                continue
            if kind == 'neighbors_vec':
                # vector queries never ride the device (the index is
                # host-side here) — timed separately; the semantic
                # tier's effect shows in semantic_hits + vec p99
                def vstamp(done, t_submit=t_submit):
                    if done.exception() is None:
                        with lat_lock:
                            vec_lat.append(
                                time.perf_counter() - t_submit)
                future.add_done_callback(vstamp)
            elif future.done() and future.exception() is None:
                # resolved AT submit: served from the memo tier (a
                # live request cannot complete before submit returns —
                # it has a device round-trip ahead of it)
                with lat_lock:
                    cache_lat.append(time.perf_counter() - t_submit)
            else:
                def stamp(done, t_submit=t_submit):
                    if done.exception() is None:
                        with lat_lock:
                            live_lat.append(
                                time.perf_counter() - t_submit)
                future.add_done_callback(stamp)
            futures_per[g].append(future)

    try:
        threads = [threading.Thread(target=generator, args=(g,))
                   for g in range(generators)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        for g in range(generators):
            for future in futures_per[g]:
                try:
                    future.result(timeout=600)
                except DeadlineExceeded:
                    expired_counts[g] += 1
                except EngineOverloaded:
                    shed_counts[g] += 1
        wall = time.perf_counter() - t0
        stats = mesh.stats()
    finally:
        mesh.close()
    postwarm = (compiles.value - warm_compiles
                if compiles is not None else None)
    memo_stats = stats['memo']
    total = len(profile)
    device_rows = stats['rows_dispatched']

    def p99(lat):
        arr = np.asarray(sorted(lat)) * 1e3
        return round(float(np.percentile(arr, 99)), 3) if len(arr) \
            else None

    return {
        'cache_served': len(cache_lat),
        'live_served': len(live_lat),
        'hit_rate': (round(memo_stats['hit_rate'], 3)
                     if memo_stats else 0.0),
        'memo_entries': memo_stats['entries'] if memo_stats else 0,
        'memo_bytes': memo_stats['bytes'] if memo_stats else 0,
        'semantic_hits': (memo_stats['semantic_hits']
                          if memo_stats else 0),
        'semantic_agreement': (memo_stats['semantic']['agreement']
                               if memo_stats else None),
        'cache_p99_ms': p99(cache_lat),
        'live_p99_ms': p99(live_lat),
        'vec_served': len(vec_lat),
        'vec_p99_ms': p99(vec_lat),
        'shed_rate': round(sum(shed_counts) / total, 3),
        'expired_rate': round(sum(expired_counts) / total, 3),
        'device_rows_dispatched': device_rows,
        'device_rows_per_1k_requests':
            round(device_rows * 1e3 / total, 1),
        'device_seconds_per_1k_requests':
            round(device_rows / max(1e-9, capacity) * 1e3 / total, 4),
        'postwarm_compiles': postwarm,
        'wall_s': round(wall, 2),
    }


def run_stepped_arm(model, lines, capacity: float, max_lines: int,
                    secs: float, compiles) -> list:
    """Stepped-offered-load arm (SERVING.md "Elastic fleet"): one
    process-mode replica with the SLO/queue-driven autoscaler live,
    driven low -> high -> low.  The high step must pull the fleet to 2
    replicas (scale-up latency = load step to the new replica LIVE,
    cold start included); the low step must drain it back to 1
    (scale-down latency = load step to the drained slot retired); p99
    over requests submitted DURING each transition window is reported
    next to steady-state p99 — the cost of an elastic transition is a
    latency bulge, never a lost or misrouted request."""
    import random as random_lib
    import threading
    from code2vec_tpu.serving.errors import ServingError
    config = model.config
    knobs = dict(
        MESH_REPLICA_MODE='process',
        AUTOSCALE_MAX_REPLICAS=2, AUTOSCALE_MIN_REPLICAS=1,
        AUTOSCALE_INTERVAL_SECS=0.25,
        # the shared queue's admission bound caps visible backlog, so
        # the up threshold must sit well UNDER bound/service_rate or a
        # bounded queue can never look busy enough to scale
        AUTOSCALE_UP_QUEUE_SECS=0.02,
        AUTOSCALE_UP_COOLDOWN_SECS=2.0,
        AUTOSCALE_DOWN_COOLDOWN_SECS=2.0,
        AUTOSCALE_DOWN_IDLE_SECS=1.0,
        AUTOSCALE_DOWN_UTILIZATION=0.9,
        AUTOSCALE_FLAP_WINDOW_SECS=120.0, AUTOSCALE_FLAP_LIMIT=20)
    old = {name: getattr(config, name) for name in knobs}
    for name, value in knobs.items():
        setattr(config, name, value)
    try:
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  max_delay_ms=2.0)
    finally:
        for name, value in old.items():
            setattr(config, name, value)
    records = []
    lat = []
    lat_lock = threading.Lock()
    shed = [0]
    rng = random_lib.Random(17)
    live_mark = {'t': None}
    stats_gate = [0.0]

    def live_replicas() -> int:
        # throttled: the pacing loop polls this per submit
        now = time.perf_counter()
        if now < stats_gate[0] and live_mark.get('last') is not None:
            return live_mark['last']
        stats_gate[0] = now + 0.05
        live_mark['last'] = mesh.stats()['replicas_live']
        return live_mark['last']

    def drive(rate_rows_per_s: float, seconds: float = None,
              until=None, timeout: float = 180.0):
        """Paced submits at the offered rate until the duration (or
        the condition) is reached; returns (elapsed_s, condition_met)."""
        t_start = time.perf_counter()
        next_t = t_start
        while True:
            now = time.perf_counter()
            if until is not None and until():
                return now - t_start, True
            if seconds is not None and now - t_start >= seconds:
                return now - t_start, False
            if until is not None and now - t_start >= timeout:
                return now - t_start, False
            n = rng.randint(1, max_lines)
            request_lines = [rng.choice(lines) for _ in range(n)]
            t_submit = time.perf_counter()
            try:
                future = mesh.submit(request_lines, tier='topk')
            except ServingError:
                shed[0] += 1
            else:
                def stamp(done, t_submit=t_submit):
                    # completion-time latency, stamped when the future
                    # RESOLVES (not when the drain loop reaches it)
                    if done.exception() is None:
                        with lat_lock:
                            lat.append((t_submit,
                                        time.perf_counter() - t_submit))
                future.add_done_callback(stamp)
                records.append(future)
            next_t += n / rate_rows_per_s
            pause = next_t - time.perf_counter()
            if pause > 0:
                time.sleep(pause)
            else:
                if -pause > 1.0:
                    # the generator fell behind the schedule (caller-
                    # thread tokenize is part of the serving contract):
                    # don't accumulate debt into a burst, and yield so
                    # the fleet and the autoscaler keep their cores
                    next_t = time.perf_counter()
                time.sleep(0.0005)

    def drive_burst(rows_per_burst: float, period_s: float,
                    seconds: float = None, until=None,
                    timeout: float = 180.0):
        """Bursty offered load: ``rows_per_burst`` rows submitted
        back-to-back each ``period_s``.  A paced generator sharing
        cores with the fleet cannot reliably out-offer it (the
        tokenize-in-caller contract), but a burst pins the bounded
        queue full on every period — the unambiguous shape of a load
        step, which is what the scale-up trigger must see."""
        t_start = time.perf_counter()
        while True:
            now = time.perf_counter()
            if until is not None and until():
                return now - t_start, True
            if seconds is not None and now - t_start >= seconds:
                return now - t_start, False
            if until is not None and now - t_start >= timeout:
                return now - t_start, False
            sent = 0
            while sent < rows_per_burst:
                n = rng.randint(1, max_lines)
                request_lines = [rng.choice(lines) for _ in range(n)]
                t_submit = time.perf_counter()
                try:
                    future = mesh.submit(request_lines, tier='topk')
                except ServingError:
                    shed[0] += 1
                else:
                    def stamp(done, t_submit=t_submit):
                        if done.exception() is None:
                            with lat_lock:
                                lat.append(
                                    (t_submit,
                                     time.perf_counter() - t_submit))
                    future.add_done_callback(stamp)
                    records.append(future)
                sent += n
            rest = period_s - (time.perf_counter() - now)
            if rest > 0:
                time.sleep(rest)

    warm_compiles = compiles.value
    windows = {}
    try:
        # process-replica capacity probe: the thread-mode calibration
        # over-reads a worker's capacity (no IPC, no wire) — the steps
        # are sized against THIS mesh's single replica so 'high' is a
        # genuine 2x overload, not a host-starving flood
        proc_capacity = 0.0
        for _ in range(2):
            probe = []
            probe_rows = 0
            t_probe = time.perf_counter()
            for _ in range(32):
                n = rng.randint(1, max_lines)
                probe_rows += n
                probe.append(mesh.submit(
                    [rng.choice(lines) for _ in range(n)],
                    tier='topk'))
            for future in probe:
                future.result(timeout=600)
            proc_capacity = max(
                proc_capacity,
                probe_rows / (time.perf_counter() - t_probe))
        low = 0.4 * proc_capacity
        high = 2.0 * proc_capacity
        # steady low: one replica is comfortable, no scaling
        drive(low, seconds=max(2.0, secs * 0.4))
        base_up = mesh.stats()['autoscaler']['scale_up_total']
        # ---- STEP UP: the high step must pull a second replica ----
        # 2x offered as half-second bursts of one replica-second of
        # rows each: every burst refills the bounded queue, so the
        # drain estimate stays over the up threshold for as long as
        # the step lasts
        t_step_up = time.perf_counter()
        _, scaled = drive_burst(proc_capacity, 0.5,
                                until=lambda: live_replicas() >= 2)
        t_live2 = time.perf_counter()
        windows['up'] = (t_step_up, t_live2, scaled)
        # steady at 2: the transition bulge must clear
        drive_burst(proc_capacity, 0.5, seconds=max(2.0, secs * 0.3))
        # ---- STEP DOWN: sustained low must drain the extra out ----
        t_step_down = time.perf_counter()
        _, drained = drive(
            low, until=lambda: live_replicas() <= 1
            and mesh.stats()['autoscaler']['scale_down_total'] >= 1)
        t_live1 = time.perf_counter()
        windows['down'] = (t_step_down, t_live1, drained)
        drive(low, seconds=max(1.0, secs * 0.2))
        asc_stats = mesh.stats()['autoscaler']
        retired = [(r['replica'], r['retired_reason'])
                   for r in mesh.stats()['replicas'] if r['retired']]
        # drain every admitted future (latencies stamped by the done
        # callbacks above); failures must all be typed
        typed = 0
        for future in records:
            try:
                future.result(timeout=600)
            except ServingError:
                typed += 1
    finally:
        mesh.close()
    postwarm = compiles.value - warm_compiles

    def p99_ms(pairs):
        arr = np.asarray(sorted(l for _, l in pairs)) * 1e3
        return round(float(np.percentile(arr, 99)), 1) if len(arr) \
            else None

    up_t0, up_t1, scaled = windows['up']
    down_t0, down_t1, drained = windows['down']
    in_up = [p for p in lat if up_t0 <= p[0] < up_t1]
    in_down = [p for p in lat if down_t0 <= p[0] < down_t1]
    steady = [p for p in lat
              if not (up_t0 <= p[0] < up_t1)
              and not (down_t0 <= p[0] < down_t1)]
    out = []
    out.append({'metric': 'mesh_stepped_scale_up_s',
                'value': round(up_t1 - up_t0, 2) if scaled else None,
                'reached_2_replicas': scaled,
                'offered_low_rows_per_sec': round(low, 1),
                'offered_high_rows_per_sec': round(high, 1),
                'process_capacity_rows_per_sec_1r':
                    round(proc_capacity, 1),
                'scale_up_total': asc_stats['scale_up_total'],
                'scale_up_before_step': base_up})
    out.append({'metric': 'mesh_stepped_scale_down_s',
                'value': (round(down_t1 - down_t0, 2)
                          if drained else None),
                'drained_to_1_replica': drained,
                'scale_down_total': asc_stats['scale_down_total'],
                'retired': retired})
    out.append({'metric': 'mesh_stepped_transition_p99_ms',
                'value': p99_ms(in_up + in_down),
                'up_p99_ms': p99_ms(in_up),
                'down_p99_ms': p99_ms(in_down),
                'steady_p99_ms': p99_ms(steady),
                'delivered': len(lat), 'typed_failures': typed,
                'shed_at_admission': shed[0],
                'flap_freezes_total': asc_stats['flap_freezes_total'],
                'postwarm_compiles': postwarm,
                'host_cores': os.cpu_count()})
    return out


def measure_capacity(model, index, profile, reps: int = 2) -> float:
    """One replica's sustainable rows/s: open-loop firehose (no arrival
    pacing, no deadline) through a 1-replica mesh — delivered rows over
    the drain wall clock, best of ``reps`` (the first rep pays
    first-dispatch warm-in; under-measuring capacity would under-size
    the offered load and starve every arm of its saturation regime)."""
    # queue_bound=-1: the firehose deliberately holds the whole profile
    # in flight; the admission bound is the LOAD arms' regime, not the
    # capacity probe's
    mesh = model.serving_mesh(replicas=1,
                             tiers=('topk', 'attention', 'vectors'),
                             max_delay_ms=2.0, queue_bound=-1)
    mesh.attach_index(index)
    best = 0.0
    try:
        for _ in range(reps):
            rows = 0
            futures = []
            t0 = time.perf_counter()
            for kind, lines in profile:
                rows += len(lines)
                if kind == 'neighbors':
                    futures.append(mesh.submit_neighbors(lines))
                else:
                    futures.append(mesh.submit(lines, tier=kind))
            for future in futures:
                future.result(timeout=600)
            best = max(best, rows / (time.perf_counter() - t0))
    finally:
        mesh.close()
    return best


def main() -> None:
    benchlib.honor_env_platforms()
    smoke = benchlib.smoke_requested()
    parser = argparse.ArgumentParser()
    parser.add_argument('--replica-counts', default='1,2,4',
                        help='mesh sizes to drive, comma-separated')
    parser.add_argument('--offered-factor', type=float, default=2.2,
                        help='offered load as a multiple of one '
                             "replica's measured capacity")
    parser.add_argument('--secs', type=float,
                        default=4.0 if smoke else 20.0,
                        help='load duration per arm (approximate: the '
                             'profile is sized as offered x secs)')
    parser.add_argument('--deadline-ms', type=float,
                        default=2000.0,
                        help='per-request SLO deadline under load '
                             '(drives shed/expiry at saturation)')
    parser.add_argument('--stepped-load', action='store_true',
                        help='run the stepped-offered-load elasticity '
                             'arm instead of the replica-scaling arms: '
                             'low -> high -> low against one process '
                             'replica with the autoscaler live; '
                             'reports scale-up/scale-down latency and '
                             'transition p99 (SERVING.md "Elastic '
                             'fleet")')
    parser.add_argument('--zipf-alpha', type=float, default=0.0,
                        help='run the memoization-tier comparison '
                             'instead of the replica-scaling arms: '
                             'replay a Zipf(alpha)-weighted template '
                             'pool through memo off / exact / '
                             'exact+semantic meshes (SERVING.md '
                             '"Memoization tier")')
    parser.add_argument('--memo-templates', type=int, default=None,
                        help='distinct request templates in the Zipf '
                             'pool (default 48 smoke / 256)')
    parser.add_argument('--memo-cache-bytes', type=int,
                        default=64 << 20,
                        help='exact-tier cache budget for the memo '
                             'arms')
    parser.add_argument('--memo-epsilon', type=float, default=0.05,
                        help='semantic-tier epsilon for the '
                             'exact+semantic arm')
    parser.add_argument('--memo-offered-factor', type=float,
                        default=0.8,
                        help='memo arms run below one replica\'s '
                             'capacity (sustainable regime: p99 '
                             'comparisons are about the cache, not '
                             'saturation)')
    parser.add_argument('--max-request-lines', type=int,
                        default=4 if smoke else 8)
    parser.add_argument('--rows', type=int, default=200 if smoke else 2000)
    parser.add_argument('--contexts', type=int, default=6 if smoke else 200)
    parser.add_argument('--tokens', type=int, default=500 if smoke else 20000)
    parser.add_argument('--paths', type=int, default=500 if smoke else 30000)
    parser.add_argument('--labels', type=int, default=100 if smoke else 5000)
    parser.add_argument('--buckets', default='8,32' if smoke else '8,32,128')
    args = parser.parse_args()

    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.telemetry import core as tele_core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener

    workdir = tempfile.mkdtemp(prefix='c2v_meshbench_')
    prefix = os.path.join(workdir, 'synth')
    lines = synthesize_dataset(prefix, args.rows, args.contexts,
                               args.tokens, args.paths, args.labels)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=prefix, DL_FRAMEWORK='jax',
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MAX_CONTEXTS=args.contexts, SERVING_BATCH_BUCKETS=args.buckets,
        # the stepped arm scales PROCESS replicas: workers restore
        # params from the checkpoint store
        MODEL_SAVE_PATH=(os.path.join(workdir, 'model')
                         if args.stepped_load else ''))
    model = Code2VecModel(config)
    index = _MiniIndex(config.CODE_VECTOR_SIZE)

    tele_core.enable()
    install_compile_listener()
    compiles = tele_core.registry().counter('jit/compiles_total')

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    counts = [int(c) for c in args.replica_counts.split(',') if c.strip()]

    # calibration: one replica's capacity on the same mixed profile
    cal_profile = make_profile(lines, 192 if smoke else 512,
                               args.max_request_lines, seed=11)
    capacity = measure_capacity(model, index, cal_profile)

    if args.stepped_load:
        # ---- elasticity arm (stage mesh_stepped) ----
        model.save(state=model.state, epoch=0, wait=True)
        emit({'metric': 'mesh_capacity_rows_per_sec_1r',
              'value': round(capacity, 1)})
        for record in run_stepped_arm(model, lines, capacity,
                                      args.max_request_lines,
                                      args.secs, compiles):
            emit(record)
        emit({'metric': 'mesh_peak_hbm_bytes',
              **benchlib.device_memory_record()})
        return

    if args.zipf_alpha > 0:
        # ---- memoization-tier comparison (stage mesh_memo) ----
        n_templates = (args.memo_templates if args.memo_templates
                       else (48 if smoke else 256))
        offered = args.memo_offered_factor * capacity
        emit({'metric': 'mesh_memo_capacity_rows_per_sec_1r',
              'value': round(capacity, 1)})
        mean_rows = (1 + args.max_request_lines) / 2
        n_requests = max(64, int(offered * args.secs / mean_rows))
        profile = make_zipf_profile(lines, n_requests,
                                    args.max_request_lines,
                                    n_templates, args.zipf_alpha,
                                    vec_dim=config.CODE_VECTOR_SIZE)
        arms = (('off', 0, 0.0),
                ('exact', args.memo_cache_bytes, 0.0),
                ('exact+semantic', args.memo_cache_bytes,
                 args.memo_epsilon))
        for name, memo_bytes, epsilon in arms:
            arm = run_memo_arm(model, index, profile, offered,
                               args.deadline_ms, compiles, memo_bytes,
                               epsilon, capacity)
            arm.update({'metric': 'mesh_memo_arm', 'memo': name,
                        'zipf_alpha': args.zipf_alpha,
                        'templates': n_templates,
                        'requests': len(profile),
                        'offered_rows_per_sec': round(offered, 1),
                        'host_cores': os.cpu_count()})
            emit(arm)
        emit({'metric': 'mesh_peak_hbm_bytes',
              **benchlib.device_memory_record()})
        return

    offered = args.offered_factor * capacity
    emit({'metric': 'mesh_capacity_rows_per_sec_1r',
          'value': round(capacity, 1)})
    emit({'metric': 'mesh_offered_rows_per_sec',
          'value': round(offered, 1), 'factor': args.offered_factor,
          'host_cores': os.cpu_count()})

    # profile sized to ~secs of offered load; mean rows/request =
    # (1 + max)/2
    mean_rows = (1 + args.max_request_lines) / 2
    n_requests = max(32, int(offered * args.secs / mean_rows))
    profile = make_profile(lines, n_requests, args.max_request_lines)
    tiers_served = sorted({kind for kind, _ in profile})

    admitted = {}
    for n in counts:
        arm = run_arm(model, index, profile, n, offered,
                      args.deadline_ms, compiles)
        arm.update({'metric': 'mesh_admitted_rows_per_sec',
                    'tiers': tiers_served,
                    'host_cores': os.cpu_count()})
        admitted[n] = arm['value']
        emit(arm)

    base = counts[0]
    for n in counts[1:]:
        emit({'metric': 'mesh_scaling_%dx' % (n // base),
              'value': round(admitted[n] / max(1e-9, admitted[base]), 3),
              'replicas': n, 'vs_replicas': base,
              'host_cores': os.cpu_count(),
              'note': 'admitted-throughput ratio at fixed offered '
                      'load; >=1.8 expected at 2x on multi-core hosts '
                      '/ on chip'})
    emit({'metric': 'mesh_peak_hbm_bytes',
          **benchlib.device_memory_record()})


if __name__ == '__main__':
    main()
