"""Serving benchmark: naive per-request ``model.predict`` loop vs the
micro-batching engine (serving/engine.py) on a synthetic concurrent
request stream.

Measures, per variant, requests/sec + examples/sec throughput and
per-request latency p50/p99:

- ``naive``  — the reference REPL shape: one ``model.predict`` per
  request, sequential (warmed first, so it is not billed its compiles).
- ``engine`` — open-loop by default (the "heavy traffic" regime: the
  whole request stream is in flight at once and the dispatcher
  coalesces it into bucket-ladder batches); ``--closed-loop`` instead
  runs ``--clients`` concurrent client threads each waiting for its
  result before the next submit, which bounds in-flight requests and
  probes the latency end of the trade.

Prints one JSON line per metric:
  {"metric": "serving_requests_per_sec", "variant": ..., "value": ...}
  {"metric": "serving_latency_ms", "variant": ..., "p50": ..., "p99": ...}
  {"metric": "serving_speedup", "value": ...}

BENCH_SMOKE=1 shrinks shapes and request counts for a CPU smoke run
(rename-proofed: smoke metrics carry a ``smoke`` field). On-chip runs go
through benchmarks/capture_all.sh (stage ``serving``).

Usage: python benchmarks/bench_serving.py [--requests N] [--clients K]
       [--tokens T] [--max-delay-ms MS] [--tier topk|attention|full]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402


def synthesize_dataset(prefix: str, rows: int, contexts: int,
                       n_tokens: int, n_paths: int, n_labels: int,
                       seed: int = 0):
    """Ragged java14m-shaped corpus + dict (bench_host_pipeline shape).
    Returns the raw lines — the request stream draws from them."""
    import pickle
    rng = random.Random(seed)
    tokens = [f'tok{i}' for i in range(n_tokens)]
    paths = [str(rng.getrandbits(31)) for _ in range(n_paths)]
    labels = [f'do|thing|{i}' for i in range(n_labels)]
    lines = []
    for _ in range(rows):
        n = rng.randint(max(1, contexts // 8), max(2, contexts // 2))
        ctxs = ' '.join(
            f'{rng.choice(tokens)},{rng.choice(paths)},{rng.choice(tokens)}'
            for _ in range(n))
        lines.append(f'{rng.choice(labels)} {ctxs}')
    with open(prefix + '.train.c2v', 'w') as f:
        f.write('\n'.join(lines) + '\n')
    with open(prefix + '.dict.c2v', 'wb') as f:
        pickle.dump({t: 10 for t in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({label: 10 for label in labels}, f)
        pickle.dump(rows, f)
    return lines


def make_requests(lines, n_requests: int, max_lines: int, seed: int = 1):
    """Ragged 1..max_lines requests drawn from the corpus lines."""
    rng = random.Random(seed)
    return [[rng.choice(lines) for _ in range(rng.randint(1, max_lines))]
            for _ in range(n_requests)]


def percentiles(latencies_s):
    lat_ms = np.asarray(latencies_s) * 1e3
    return (float(np.percentile(lat_ms, 50)),
            float(np.percentile(lat_ms, 99)))


def run_naive(model, requests):
    model.predict(requests[0])  # warm (one bucket covers the stream)
    latencies = []
    t0 = time.perf_counter()
    for lines in requests:
        r0 = time.perf_counter()
        model.predict(lines)
        latencies.append(time.perf_counter() - r0)
    return time.perf_counter() - t0, latencies


def run_engine_open_loop(model, requests, tier: str, max_delay_ms: float,
                         **engine_kw):
    """Submit the whole stream up front; per-request latency is
    submit -> future-done (a done-callback stamps the clock)."""
    done_at = [0.0] * len(requests)
    # queue_bound=-1: the open-loop regime deliberately holds the WHOLE
    # stream in flight; the default (auto) admission bound would shed it
    with model.serving_engine(tiers=(tier,), max_delay_ms=max_delay_ms,
                              queue_bound=-1, **engine_kw) as engine:
        t0 = time.perf_counter()
        submit_at = []
        futures = []
        for idx, lines in enumerate(requests):
            submit_at.append(time.perf_counter())
            future = engine.submit(lines, tier=tier)
            future.add_done_callback(
                lambda _f, i=idx: done_at.__setitem__(
                    i, time.perf_counter()))
            futures.append(future)
        for future in futures:
            future.result(timeout=600)
        wall = time.perf_counter() - t0
        stats = engine.stats()
    latencies = [done_at[i] - submit_at[i] for i in range(len(requests))]
    return wall, latencies, stats


def run_engine_closed_loop(model, requests, tier: str, clients: int,
                           max_delay_ms: float, **engine_kw):
    latencies = [[] for _ in range(clients)]
    with model.serving_engine(tiers=(tier,), max_delay_ms=max_delay_ms,
                              **engine_kw) as engine:
        def client(idx):
            # closed-loop client: wait for each result before the next
            # submit, so `clients` bounds the in-flight requests
            for lines in requests[idx::clients]:
                r0 = time.perf_counter()
                engine.predict(lines, tier=tier, timeout=600)
                latencies[idx].append(time.perf_counter() - r0)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(clients)]
        t0 = time.perf_counter()
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        wall = time.perf_counter() - t0
        stats = engine.stats()
    return wall, [lat for per in latencies for lat in per], stats


def main() -> None:
    benchlib.honor_env_platforms()
    smoke = benchlib.smoke_requested()
    parser = argparse.ArgumentParser()
    parser.add_argument('--requests', type=int,
                        default=128 if smoke else 512)
    parser.add_argument('--clients', type=int, default=8)
    parser.add_argument('--max-request-lines', type=int,
                        default=4 if smoke else 8)
    parser.add_argument('--rows', type=int, default=200 if smoke else 2000)
    # smoke keeps contexts tiny so the CPU run stays in the regime the
    # engine targets (per-dispatch overhead >> per-row compute — on TPU
    # that is true at full java14m shapes; on CPU only at small ones)
    parser.add_argument('--contexts', type=int,
                        default=6 if smoke else 200)
    parser.add_argument('--tokens', type=int,
                        default=500 if smoke else 20000)
    parser.add_argument('--paths', type=int,
                        default=500 if smoke else 30000)
    parser.add_argument('--labels', type=int,
                        default=100 if smoke else 5000)
    parser.add_argument('--max-delay-ms', type=float, default=5.0)
    parser.add_argument('--tier', default='topk',
                        choices=['topk', 'attention', 'full'])
    # finer than the Config default ladder: open-loop streams land ragged
    # row totals, and fill rate (compute waste) is what the bench probes
    parser.add_argument('--buckets', default='8,32,128,512')
    parser.add_argument('--closed-loop', action='store_true',
                        help='bound in-flight requests to --clients '
                             'closed-loop client threads instead of the '
                             'open-loop full-stream default')
    parser.add_argument('--reps', type=int, default=3,
                        help='repetitions per variant; the best wall '
                             'time is reported (host-jitter control)')
    parser.add_argument('--trace-dir', default=None,
                        help='where the full-capture traced arm writes '
                             'its span log (default: a temp dir); point '
                             'it somewhere durable to keep the spans '
                             'for scripts/latency_report.py')
    args = parser.parse_args()

    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel

    workdir = tempfile.mkdtemp(prefix='c2v_servebench_')
    prefix = os.path.join(workdir, 'synth')
    lines = synthesize_dataset(prefix, args.rows, args.contexts,
                               args.tokens, args.paths, args.labels)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=prefix, DL_FRAMEWORK='jax',
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MAX_CONTEXTS=args.contexts, SERVING_BATCH_BUCKETS=args.buckets,
        SERVING_MAX_DELAY_MS=args.max_delay_ms)
    model = Code2VecModel(config)
    requests = make_requests(lines, args.requests, args.max_request_lines)
    n_lines = sum(len(r) for r in requests)

    def emit(record):
        if smoke:
            record['smoke'] = True
        print(json.dumps(record), flush=True)

    naive_s, naive_lat = min(
        (run_naive(model, requests) for _ in range(args.reps)),
        key=lambda pair: pair[0])
    p50, p99 = percentiles(naive_lat)
    emit({'metric': 'serving_requests_per_sec', 'variant': 'naive',
          'value': args.requests / naive_s})
    emit({'metric': 'serving_examples_per_sec', 'variant': 'naive',
          'value': n_lines / naive_s})
    emit({'metric': 'serving_latency_ms', 'variant': 'naive',
          'p50': p50, 'p99': p99})

    if args.closed_loop:
        runs = [run_engine_closed_loop(model, requests, args.tier,
                                       args.clients, args.max_delay_ms)
                for _ in range(args.reps)]
    else:
        runs = [run_engine_open_loop(model, requests, args.tier,
                                     args.max_delay_ms)
                for _ in range(args.reps)]
    engine_s, engine_lat, stats = min(runs, key=lambda rec: rec[0])
    p50, p99 = percentiles(engine_lat)
    emit({'metric': 'serving_requests_per_sec', 'variant': 'engine',
          'value': args.requests / engine_s, 'tier': args.tier,
          'mode': 'closed' if args.closed_loop else 'open',
          'batches': stats['batches_total'],
          'batch_fill_rate': stats['batch_fill_rate']})
    emit({'metric': 'serving_examples_per_sec', 'variant': 'engine',
          'value': n_lines / engine_s})
    emit({'metric': 'serving_latency_ms', 'variant': 'engine',
          'p50': p50, 'p99': p99})
    emit({'metric': 'serving_speedup', 'value': naive_s / engine_s})
    # per-stage peak HBM (ISSUE 9): measured after both arms, so the
    # peak covers naive AND engine serving on this backend
    emit({'metric': 'serving_peak_hbm_bytes',
          **benchlib.device_memory_record()})

    # ---- tracing overhead at the DEFAULT sample rate (ISSUE 8): the
    # engine arm above ran with the config default (tracer armed,
    # memory-only); an explicit rate-0 arm isolates the tracing cost
    runner = run_engine_closed_loop if args.closed_loop \
        else run_engine_open_loop
    extra = (args.clients,) if args.closed_loop else ()
    off_runs = [runner(model, requests, args.tier, *extra,
                       args.max_delay_ms, tracing_sample_rate=0.0)
                for _ in range(args.reps)]
    off_s = min(rec[0] for rec in off_runs)
    emit({'metric': 'serving_requests_per_sec', 'variant': 'engine_untraced',
          'value': args.requests / off_s})
    emit({'metric': 'serving_tracing_overhead_pct',
          'value': round((engine_s - off_s) / off_s * 100, 2),
          'note': 'engine wall at default TRACING_SAMPLE_RATE vs 0'})

    # ---- full-capture traced arm: span-log-derived latency attribution
    # (every request retained; scripts/latency_report.py reads the same
    # file offline)
    from code2vec_tpu.telemetry.tracing import Tracer
    scripts_dir = os.path.join(REPO, 'scripts')
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import latency_report
    trace_dir = args.trace_dir or os.path.join(workdir, 'trace')
    stale = os.path.join(trace_dir, 'spans.jsonl')
    if os.path.exists(stale):
        # the tracer appends; a reused --trace-dir must not blend a
        # prior run's spans into this run's percentiles
        os.remove(stale)
    tracer = Tracer(trace_dir, sample_rate=1.0,
                    flight_traces=args.requests)
    traced_s, _lat, _stats = runner(model, requests, args.tier, *extra,
                                    args.max_delay_ms, tracer=tracer)
    spans_path = os.path.join(trace_dir, 'spans.jsonl')
    traces = latency_report.group_traces(
        latency_report.load_spans(spans_path))
    roots = sorted(
        float(entry['root'].get('dur_ms', 0.0))
        for entry in traces.values() if entry['root'] is not None)
    emit({'metric': 'serving_latency_ms', 'variant': 'engine_spans',
          'p50': latency_report.percentile(roots, 0.50),
          'p99': latency_report.percentile(roots, 0.99),
          'traces': len(roots), 'spans_path': spans_path})
    per_phase = {}
    for (phase, _tier, _bucket, _replica), durs in \
            latency_report.phase_rows(traces).items():
        per_phase.setdefault(phase, []).extend(durs)
    for phase, durs in sorted(per_phase.items()):
        durs.sort()
        emit({'metric': 'serving_phase_ms', 'phase': phase,
              'count': len(durs),
              'p50': round(latency_report.percentile(durs, 0.50), 3),
              'p99': round(latency_report.percentile(durs, 0.99), 3)})


if __name__ == '__main__':
    main()
