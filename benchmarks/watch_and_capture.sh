#!/usr/bin/env bash
# Poll the TPU tunnel; when a healthy window opens, run the pending
# round-2b captures (stages not covered by the 13:49Z sweep), then exit.
#
#   bash benchmarks/watch_and_capture.sh [max_wait_seconds]
#
# Stages:
#   rbg_dropout     threefry-vs-rbg dropout A/B (bench_rbg_dropout.py)
#   pallas_c1024    long-context Pallas A/B, 1800 s budget (its 900 s
#                   stage timed out on compile in the first sweep)
set -u
cd "$(dirname "$0")/.."

MAX_WAIT=${1:-10800}
STAMP=$(date -u +%Y-%m-%dT%H%MZ)
OUT=benchmarks/results/capture_${STAMP}_r2b.jsonl
mkdir -p benchmarks/results

probe() {
  BENCH_CHILD=probe timeout 90 python bench.py 2>/dev/null | grep -q '"probe"'
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "--- stage: ${name}" >&2
  local start=$(date +%s)
  local out
  out=$(timeout "${tmo}" "$@" 2>/dev/null)
  local rc=$?
  local secs=$(( $(date +%s) - start ))
  while IFS= read -r line; do
    case "${line}" in
      '{'*) printf '{"stage": "%s", "rc": %d, "secs": %d, "data": %s}\n' \
                   "${name}" "${rc}" "${secs}" "${line}" >> "${OUT}" ;;
    esac
  done <<< "${out}"
  if [ ${rc} -ne 0 ] && [ -z "${out}" ]; then
    printf '{"stage": "%s", "rc": %d, "secs": %d, "data": null}\n' \
           "${name}" "${rc}" "${secs}" >> "${OUT}"
  fi
  return ${rc}
}

deadline=$(( $(date +%s) + MAX_WAIT ))
until probe; do
  if [ "$(date +%s)" -ge "${deadline}" ]; then
    echo "gave up waiting for a healthy tunnel after ${MAX_WAIT}s" >&2
    exit 3
  fi
  sleep 180
done
echo "tunnel healthy; capturing to ${OUT}" >&2

run_stage rbg_dropout 900 python benchmarks/bench_rbg_dropout.py
probe || { echo "wedged after rbg_dropout" >&2; exit 3; }
BENCH_CONTEXTS=1024 run_stage pallas_c1024 1800 \
  python benchmarks/bench_pallas_encode.py
probe || { echo "wedged after pallas_c1024" >&2; exit 3; }
# diagnostics last: re-runs the full breakdown incl. the new
# frozen-tables (embedding-backward isolation) and bf16-mu variants
run_stage diag 1200 python benchmarks/diag_step_breakdown.py

echo "capture complete: ${OUT}" >&2
