#!/usr/bin/env bash
# Poll the TPU tunnel; when a healthy window opens, run the pending
# round-3 captures, then exit.
#
#   bash benchmarks/watch_and_capture.sh [max_wait_seconds]
#
# Stages (ordered by VERDICT r2 priority):
#   headline        a fresh bench.py headline capture (short inner budget —
#                   the probe loop here already did the waiting)
#   rbg_dropout     threefry-vs-rbg dropout A/B + bf16-mu combos
#   embed_grad      dense/sorted/dedup table-gradient A/B, uniform+zipf
#   fused_ce        flash-CE Pallas kernel A/B (ops/pallas_ce.py) +
#                   the combined candidate default set
#   diag            step breakdown incl. frozen-tables (scatter isolation)
#   pallas_c1024    long-context Pallas A/B, 1800 s budget (its 900 s
#                   stage timed out on compile in the first sweep)
set -u
cd "$(dirname "$0")/.."

MAX_WAIT=${1:-10800}
STAMP=$(date -u +%Y-%m-%dT%H%MZ)
OUT=benchmarks/results/capture_${STAMP}_r3.jsonl
mkdir -p benchmarks/results

probe() {
  BENCH_CHILD=probe timeout 90 python bench.py 2>/dev/null | grep -q '"probe"'
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  echo "--- stage: ${name}" >&2
  local start=$(date +%s)
  local out
  # Keep stage stderr: a failed unattended stage with no diagnostic is
  # useless when the healthy window it burned won't come back for hours.
  local errlog="${OUT%.jsonl}.${name}.stderr.log"
  out=$(timeout "${tmo}" "$@" 2>>"${errlog}")
  local rc=$?
  local secs=$(( $(date +%s) - start ))
  while IFS= read -r line; do
    case "${line}" in
      '{'*) printf '{"stage": "%s", "rc": %d, "secs": %d, "data": %s}\n' \
                   "${name}" "${rc}" "${secs}" "${line}" >> "${OUT}" ;;
    esac
  done <<< "${out}"
  if [ ${rc} -ne 0 ] && [ -z "${out}" ]; then
    printf '{"stage": "%s", "rc": %d, "secs": %d, "data": null}\n' \
           "${name}" "${rc}" "${secs}" >> "${OUT}"
  fi
  return ${rc}
}

deadline=$(( $(date +%s) + MAX_WAIT ))
until probe; do
  if [ "$(date +%s)" -ge "${deadline}" ]; then
    echo "gave up waiting for a healthy tunnel after ${MAX_WAIT}s" >&2
    exit 3
  fi
  sleep 180
done
echo "tunnel healthy; capturing to ${OUT}" >&2

BENCH_TOTAL_BUDGET=600 run_stage headline 700 python bench.py
probe || { echo "wedged after headline" >&2; exit 3; }
run_stage rbg_dropout 900 python benchmarks/bench_rbg_dropout.py
probe || { echo "wedged after rbg_dropout" >&2; exit 3; }
run_stage embed_grad 1500 python benchmarks/bench_embed_grad.py
probe || { echo "wedged after embed_grad" >&2; exit 3; }
run_stage fused_ce 1200 python benchmarks/bench_fused_ce.py
probe || { echo "wedged after fused_ce" >&2; exit 3; }
# frozen-tables (embedding-backward isolation) and the other breakdown
# variants
run_stage diag 1200 python benchmarks/diag_step_breakdown.py
probe || { echo "wedged after diag" >&2; exit 3; }
BENCH_CONTEXTS=1024 run_stage pallas_c1024 1800 \
  python benchmarks/bench_pallas_encode.py

echo "capture complete: ${OUT}" >&2
