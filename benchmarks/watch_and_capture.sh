#!/usr/bin/env bash
# Poll the TPU tunnel; when a healthy window opens, run the pending
# on-chip captures, then exit 0.  Designed to run under
# watch_supervisor.sh for a whole round: every probe attempt is
# heartbeat-logged, and stages already captured this round are skipped
# on respawn, so a mid-capture wedge costs only the unfinished stage.
#
#   bash benchmarks/watch_and_capture.sh [max_wait_seconds]
#
# Stages (ordered by expected payoff — the offline trace decomposition,
# benchmarks/analyze_trace.py, puts the embedding scatter-add at ~16 ms
# of the 46 ms step, so embed_grad leads the A/Bs):
#   headline        a fresh bench.py headline capture (short inner budget —
#                   the probe loop here already did the waiting)
#   diag            step breakdown incl. frozen-tables (scatter isolation,
#                   cross-checks the trace-derived number on chip)
#   embed_grad      dense/sorted/dedup table-gradient A/B, uniform+zipf
#   fused_ce        flash-CE Pallas kernel A/B (ops/pallas_ce.py) +
#                   the combined candidate default set; Mosaic-compiles
#                   fused_lse_and_pick at java14m shapes first
#   rbg_dropout     threefry-vs-rbg dropout A/B + bf16-mu combos
#   accuracy_tpu    accuracy-at-scale tpu profile (full dims, C=200)
#   pallas_c1024    long-context Pallas A/B, 3100 s budget (its 900 s
#                   stage timed out on compile in the first sweep; the
#                   persistent compile cache makes retries cheap)
set -u
cd "$(dirname "$0")/.."

# Persistent XLA/Mosaic compile cache shared by every stage and every
# respawn: the C=1024 Pallas compile stalled past a 900 s budget in
# round 3 — with the cache, a compile that completes ONCE in any window
# is a disk hit in every later one (VERDICT r4 #7).
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_compile_cache}
mkdir -p "${JAX_COMPILATION_CACHE_DIR}"

ROUND=${CAPTURE_ROUND:-r5}
MAX_WAIT=${1:-999999}
STAMP=$(date -u +%Y-%m-%dT%H%MZ)
OUT=benchmarks/results/capture_${STAMP}_${ROUND}.jsonl
DONEDIR=benchmarks/results/.stages_${ROUND}
HEARTBEAT=benchmarks/results/watcher_${ROUND}.log
mkdir -p benchmarks/results "${DONEDIR}"

hb() { echo "$(date -u +%Y-%m-%dT%H:%M:%SZ) $*" >> "${HEARTBEAT}"; }

# Full-priority probe: used BETWEEN stages inside a healthy window,
# where a deprioritized probe could be starved past its timeout by
# concurrent host work and falsely abort the window as "wedged".
probe() {
  BENCH_CHILD=probe timeout 90 python bench.py 2>/dev/null \
    | grep -q '"probe"'
}

# Deprioritized probe: used in the WAITING loop, where every probe
# against a wedged tunnel burns its full 90 s of CPU in the hung device
# init — un-deprioritized that steals ~50% of this 1-core host for hours
# and contaminated two rounds of weak-scaling numbers
# (weak_scaling_r5_postflip_note.jsonl). setsid gives the probe its own
# scheduler autogroup (per-task nice is weighed only within an
# autogroup when sched_autogroup_enabled=1) and the echo sets that
# autogroup's nice; plain nice is the fallback where /proc autogroup is
# unavailable.
probe_idle() {
  BENCH_CHILD=probe timeout 90 setsid bash -c \
    'echo 19 > /proc/self/autogroup 2>/dev/null || true;
     exec nice -n 19 python bench.py' 2>/dev/null \
    | grep -q '"probe"'
}

run_stage() {  # run_stage <name> <timeout> <cmd...>
  local name=$1 tmo=$2; shift 2
  if [ -e "${DONEDIR}/${name}" ]; then
    echo "--- stage: ${name} (already captured this round, skipping)" >&2
    return 0
  fi
  echo "--- stage: ${name}" >&2
  hb "stage ${name} start"
  local start=$(date +%s)
  local out
  # Keep stage stderr: a failed unattended stage with no diagnostic is
  # useless when the healthy window it burned won't come back for hours.
  local errlog="${OUT%.jsonl}.${name}.stderr.log"
  out=$(timeout "${tmo}" "$@" 2>>"${errlog}")
  local rc=$?
  local secs=$(( $(date +%s) - start ))
  local got=0 fresh=0
  while IFS= read -r line; do
    case "${line}" in
      '{'*) printf '{"stage": "%s", "rc": %d, "secs": %d, "data": %s}\n' \
                   "${name}" "${rc}" "${secs}" "${line}" >> "${OUT}"
            got=1
            # A stale-fallback or error record is provenance, not a
            # capture: bench.py always exits 0 and always prints a line,
            # so done-marking must look at what the line says.
            case "${line}" in
              *'"stale": true'*|*'"capture_error"'*|*'"error"'*) ;;
              *) fresh=1 ;;
            esac ;;
    esac
  done <<< "${out}"
  if [ ${rc} -ne 0 ] && [ ${got} -eq 0 ]; then
    printf '{"stage": "%s", "rc": %d, "secs": %d, "data": null}\n' \
           "${name}" "${rc}" "${secs}" >> "${OUT}"
  fi
  hb "stage ${name} done rc=${rc} secs=${secs} fresh=${fresh}"
  # Mark done only when the stage COMPLETED (rc 0) with a fresh
  # measurement line: stale fallbacks, errors, timeouts, and partial
  # captures (e.g. an A/B whose second arm died) stay pending so a later
  # healthy window retries them instead of locking in half a result.
  if [ ${rc} -eq 0 ] && [ ${fresh} -eq 1 ]; then touch "${DONEDIR}/${name}"; fi
  return ${rc}
}

ALL_STAGES="headline diag embed_grad fused_ce rbg_dropout accuracy_tpu pallas_c1024 headline_v2 accuracy_tpu_bf16mu moment_dtypes headline_v3 accuracy_tpu_bf16nu profile_v2 pallas_ragged pallas_ragged_c1024"

all_captured() {
  local s
  for s in ${ALL_STAGES}; do
    [ -e "${DONEDIR}/${s}" ] || return 1
  done
  return 0
}

hb "watcher launched pid=$$ max_wait=${MAX_WAIT}"
deadline=$(( $(date +%s) + MAX_WAIT ))
n=0
until probe_idle; do
  n=$((n+1))
  hb "probe ${n}: tunnel unhealthy"
  if [ "$(date +%s)" -ge "${deadline}" ]; then
    hb "gave up after ${MAX_WAIT}s"
    echo "gave up waiting for a healthy tunnel after ${MAX_WAIT}s" >&2
    exit 3
  fi
  sleep 180
done
hb "tunnel HEALTHY; capturing to ${OUT}"
echo "tunnel healthy; capturing to ${OUT}" >&2

# headline = the reference-parity recipe (threefry + fp32 mu), pinned via
# BENCH_RECIPE so the vs-V100 parity row stays refreshable now that the
# config defaults carry the measured winners; headline_v2 (below)
# captures the default recipe.
BENCH_TOTAL_BUDGET=600 BENCH_RECIPE=parity run_stage headline 700 python bench.py
probe || { hb "wedged after headline"; exit 3; }
run_stage diag 1200 python benchmarks/diag_step_breakdown.py
probe || { hb "wedged after diag"; exit 3; }
# embed_grad outranks fused_ce since the offline trace decomposition
# (benchmarks/analyze_trace.py): the embedding gather+scatter is ~16 ms
# of the 46 ms step — the single biggest lever
run_stage embed_grad 1500 python benchmarks/bench_embed_grad.py
probe || { hb "wedged after embed_grad"; exit 3; }
# worst-case arm ladder: xla + 3 fused tile retries + combined, 5 x 300 s
run_stage fused_ce 1800 python benchmarks/bench_fused_ce.py
probe || { hb "wedged after fused_ce"; exit 3; }
run_stage rbg_dropout 900 python benchmarks/bench_rbg_dropout.py
probe || { hb "wedged after rbg_dropout"; exit 3; }
# /tmp/acc_r5_corpus holds the round-5 combinatorial-path corpus
# (~93K unique paths — corpus_stats_r5.json); the stage rebuilds any
# missing piece itself with the same layout
run_stage accuracy_tpu 3600 \
  python benchmarks/accuracy_at_scale.py --profile tpu \
  --workdir /tmp/acc_r5_corpus
probe || { hb "wedged after accuracy_tpu"; exit 3; }
# the C=1024 Mosaic compile exceeded a 900 s budget in round 3: give the
# pallas arm most of a LARGER stage (xla's arm at C=1024 is a plain XLA
# compile, minutes at worst), and the persistent compile cache above
# makes any completed compile a disk hit in later windows
# stage budget >= xla worst case (~600 s) + pallas arm 2400 s + slack,
# so the outer timeout can never SIGTERM the parent while a finished
# xla arm's result is still unwritten
BENCH_CONTEXTS=1024 BENCH_PALLAS_ARM_TIMEOUT=2400 run_stage pallas_c1024 3100 \
  python benchmarks/bench_pallas_encode.py
probe || { hb "wedged after pallas_c1024"; exit 3; }
# Round-5 post-flip stages: the 2026-07-31 ladder above measured the A/Bs
# and the winning knobs became config DEFAULTS (DROPOUT_PRNG_IMPL='rbg',
# ADAM_MU_DTYPE='bfloat16').  headline_v2 re-captures bench.py under the
# new defaults (expected ~41 ms/step vs the first window's 47.1 ms);
# accuracy_tpu_bf16mu pairs the on-chip F1 curve against accuracy_tpu.json
# with the bf16 first moment engaged — the last knob lacking an on-device
# learning-curve twin.
# default_v2 pins the rbg+bf16-mu/fp32-nu recipe this stage was defined
# for: the shipped default moved on (bf16 nu), and an unpinned re-run
# would measure the newer recipe under this stage's label
BENCH_TOTAL_BUDGET=600 BENCH_RECIPE=default_v2 run_stage headline_v2 700 python bench.py
probe || { hb "wedged after headline_v2"; exit 3; }
run_stage accuracy_tpu_bf16mu 3600 \
  python benchmarks/accuracy_at_scale.py --profile tpu_bf16mu \
  --workdir /tmp/acc_r5_corpus
probe || { hb "wedged after accuracy_tpu_bf16mu"; exit 3; }
# ADAM_NU_DTYPE / GRADS_DTYPE ladder (training/adam_dtypes.py +
# trainer.py cast_for_grads): the last two fp32 streams in the dense
# update. 5 arms, 2 fresh compiles worst case.
run_stage moment_dtypes 2400 python benchmarks/bench_moment_dtypes.py
probe || { hb "wedged after moment_dtypes"; exit 3; }
# headline under the post-nu-flip defaults (rbg + bf16 mu + bf16 nu;
# the manual 07:16Z capture predicts ~26,777 ex/s/chip)
BENCH_TOTAL_BUDGET=600 BENCH_RECIPE=default run_stage headline_v3 700 python bench.py
probe || { hb "wedged after headline_v3"; exit 3; }
# the shipped default recipe's on-device learning curve (nu-knob-only
# twin of accuracy_tpu_bf16mu)
run_stage accuracy_tpu_bf16nu 3600 \
  python benchmarks/accuracy_at_scale.py --profile tpu_bf16nu \
  --workdir /tmp/acc_r5_corpus
probe || { hb "wedged after accuracy_tpu_bf16nu"; exit 3; }
# fresh jax.profiler trace + XLA cost analysis under the shipped
# defaults (capture_profile.py uses the default recipe): updates the
# roofline decomposition from the 49 ms era to the post-flip step
run_stage profile_v2 1200 python benchmarks/capture_profile.py
probe || { hb "wedged after profile_v2"; exit 3; }
# ragged packed-wire fusion A/B (ISSUE 10): fused vs unpack-then-dense
# packed train/predict step time + per-arm peak HBM, at the headline
# fill and at the fused path's best case (C=1024, fill 0.1). The fused
# arm pays one Mosaic compile; the persistent compile cache above makes
# later windows a disk hit.
run_stage pallas_ragged 1800 python benchmarks/bench_pallas_ragged.py
probe || { hb "wedged after pallas_ragged"; exit 3; }
BENCH_CONTEXTS=1024 BENCH_FILL=0.1 BENCH_PALLAS_ARM_TIMEOUT=2400 \
  run_stage pallas_ragged_c1024 3100 \
  python benchmarks/bench_pallas_ragged.py

# Exit 0 ONLY when every stage holds a fresh capture — otherwise the
# supervisor must keep respawning us for the stages still pending (a
# crashed stage with rc!=0 must not be masked by the trailing echo).
if all_captured; then
  hb "capture complete: ${OUT}"
  echo "capture complete: ${OUT}" >&2
  exit 0
fi
pending=""
for s in ${ALL_STAGES}; do [ -e "${DONEDIR}/${s}" ] || pending="${pending} ${s}"; done
hb "pass finished but stages still pending:${pending}"
echo "stages still pending:${pending}" >&2
exit 4
