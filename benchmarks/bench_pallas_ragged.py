"""On-chip A/B for the ragged packed-wire fusion (ISSUEs 10 + 12).

Measures the packed TRAIN step, the TRAIN BACKWARD (value_and_grad
alone — the custom-VJP recompute kernel pair vs the unpack path's
stored-residual autodiff, isolated from the Adam update that dominates
the full step), and the packed PREDICT step (attention tier) with
``USE_PALLAS_RAGGED_FUSION`` off (unpack-then-dense, the PR-1 path) and
on + ``RAGGED_TRAIN_KERNEL`` (the full Pallas pair, the flip the >=2%
rule gates — scripts/flip_verdict.py settles it from these records), at
the java14m headline shape and realistic fill. Each arm runs in its OWN
subprocess so the per-arm ``peak_hbm_bytes``
(benchlib.device_memory_record) is that arm's peak, not the max over
both; the train-backward record additionally carries the grad program's
AOT ``memory_analysis`` temp bytes — the residual footprint the
recompute backward exists to cut.

Knobs (the capture stages set them):

  BENCH_SMOKE=1       tiny CPU shapes, metrics renamed *_SMOKE_ONLY
  BENCH_CONTEXTS=N    override max_contexts (the fused path's best case
                      is high capacity / low fill, where the dense
                      planes are mostly padding)
  BENCH_FILL=F        mean fill fraction of the packed batches
                      (default benchlib.JAVA14M_FILL = 0.25)

Emits one JSON line per (arm x step kind), then the fused/unfused
speedup + peak-HBM/temp-bytes ratio records summarize_captures.py
surfaces:

  {"measure": "step_ms_ragged_train_fused", "kind": "train", ...}
  {"measure": "step_ms_ragged_train_bwd_fused", "temp_bytes": ..., ...}
  {"measure": "ragged_fusion_train_speedup", "value": ..., ...}
  {"measure": "ragged_train_kernel_speedup", "value": ..., ...}
  {"measure": "ragged_fusion_train_bwd_temp_ratio", "value": ..., ...}
  {"verdict": "keep-fused" | "keep-unfused", ...}   (fusion, vs unpack)
  {"verdict": "kernel-on" | "kernel-off", ...}      (RAGGED_TRAIN_KERNEL)
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
_contexts = int(os.environ.get('BENCH_CONTEXTS', '0'))
if _contexts:
    SHAPES = SHAPES._replace(max_contexts=_contexts)
FILL = float(os.environ.get('BENCH_FILL', str(benchlib.JAVA14M_FILL)))
WARMUP_STEPS, MEASURE_STEPS = benchlib.bench_steps(SMOKE)
# three arms, two decisions:
#   unfused       — fusion OFF (unpack-then-dense, the PR-1 path)
#   fused         — fusion ON, train via the custom-VJP jnp twin: the
#                   SHIPPED default
#   fused_kernel  — fused + RAGGED_TRAIN_KERNEL (the Pallas train pair)
# ragged_fusion_*_speedup (unfused/fused) confirms the default flip;
# ragged_train_kernel_speedup (fused/fused_kernel) is what gates
# RAGGED_TRAIN_KERNEL — the kernel pair must beat the twin it would
# replace, not the unpack path nothing ships anymore.
VARIANTS = ('unfused', 'fused', 'fused_kernel')


def _suffix(name: str) -> str:
    name = name + ('_SMOKE_ONLY' if SMOKE else '')
    return name + (('_c%d' % _contexts) if _contexts else '')


def measure(variant: str):
    """One arm: ({kind: ms_per_step}, grad_temp_bytes, engaged)."""
    import jax
    import jax.numpy as jnp

    fused = variant != 'unfused'
    train_kernel = variant == 'fused_kernel'
    config = benchlib.headline_config(
        SHAPES, USE_PALLAS_RAGGED_FUSION=fused,
        RAGGED_TRAIN_KERNEL=train_kernel)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    host = benchlib.random_batches(SHAPES, 4, seed=1, fill=FILL)
    packed = benchlib.pack_batches(host, trainer)
    placed = benchlib.staged(trainer, packed)

    # engagement check (TPU fused arms only): the compiled attention-tier
    # packed program must contain the Mosaic custom-call, or the "A/B"
    # compares XLA against itself (bench_pallas_encode precedent)
    engaged = False
    if fused and not SMOKE:
        fn = trainer._predict_steps[('attention', 'packed')]
        engaged = benchlib.mosaic_engaged(fn, state.params, placed[0])

    # ---- train: steps serialize on the state dependency; block once
    def train_chain(steps: int) -> float:
        nonlocal state
        loss = None
        for i in range(steps):
            state, loss = trainer.train_step_placed(
                state, placed[i % len(placed)])
        return float(loss)

    train_chain(WARMUP_STEPS)
    t0 = time.perf_counter()
    train_chain(MEASURE_STEPS)
    train_ms = 1e3 * (time.perf_counter() - t0) / MEASURE_STEPS

    # ---- predict (attention tier): thread a scalar from each output
    # into the next input's count so the chain serializes on device
    # exactly like train's state dependency (bench.py methodology)
    chain_count = jax.jit(
        lambda count, token: count + (token * 0).astype(jnp.int32))

    def predict_chain(steps: int) -> float:
        token = jnp.zeros((), jnp.float32)
        for i in range(steps):
            ctx, count, label, weight = placed[i % len(placed)]
            out = trainer.predict_step_placed(
                state.params, (ctx, chain_count(count, token), label,
                               weight), tier='attention')
            token = out['topk_scores'].sum()
        return float(token)

    predict_chain(WARMUP_STEPS)
    t0 = time.perf_counter()
    predict_chain(MEASURE_STEPS)
    predict_ms = 1e3 * (time.perf_counter() - t0) / MEASURE_STEPS

    # ---- train BACKWARD (ISSUE 12): value_and_grad alone, the axis
    # the custom-VJP recompute pair moves, isolated from the Adam
    # update (which walks the full 384M params either way and would
    # dilute the encoder-backward delta at java14m shapes). The arm
    # mirrors its trainer's packed train path: loss_fn_packed always
    # runs the ragged encoder, so the unfused arm must take the
    # unpack-then-dense route explicitly.
    loss_mesh = trainer.mesh if trainer.mesh.size > 1 else None
    rng = jax.random.PRNGKey(7)
    if fused:
        def loss_call(p, arrays):
            return trainer.backend.loss_fn_packed(p, arrays, rng,
                                                  mesh=loss_mesh)[0]
    else:
        from code2vec_tpu.data import packed as packed_lib

        def loss_call(p, arrays):
            ctx, count, label, weight = arrays
            planes = packed_lib.unpack_device(
                ctx, count, config.MAX_CONTEXTS,
                trainer.backend.token_pad_index,
                trainer.backend.path_pad_index)
            return trainer.backend.loss_fn(
                p, planes + (label, weight), rng, mesh=loss_mesh)[0]
    grad_fn = jax.jit(jax.value_and_grad(loss_call))

    def bwd_chain(steps: int) -> float:
        token = jnp.zeros((), jnp.float32)
        for i in range(steps):
            ctx, count, label, weight = placed[i % len(placed)]
            loss, _grads = grad_fn(
                state.params, (ctx, chain_count(count, token), label,
                               weight))
            token = loss
        return float(token)

    bwd_chain(WARMUP_STEPS)
    t0 = time.perf_counter()
    bwd_chain(MEASURE_STEPS)
    bwd_ms = 1e3 * (time.perf_counter() - t0) / MEASURE_STEPS
    # AOT residual footprint of the grad program (temp bytes = XLA's
    # temporary allocation incl. fwd->bwd residuals); None where the
    # backend has no memory analysis
    try:
        analysis = grad_fn.lower(
            state.params, placed[0]).compile().memory_analysis()
        temp_bytes = int(analysis.temp_size_in_bytes)
    except Exception:
        temp_bytes = None
    if train_kernel and not SMOKE:
        # the kernel verdict gates RAGGED_TRAIN_KERNEL: this arm's
        # BACKWARD program must contain the Mosaic custom-call too, or
        # the kernel-vs-twin comparison compares XLA against itself
        engaged = engaged and benchlib.mosaic_engaged(
            grad_fn, state.params, placed[0])
    return ({'train': train_ms, 'predict': predict_ms,
             'train_bwd': bwd_ms}, temp_bytes, engaged)


def run_variant(variant: str) -> None:
    """Child mode: one arm in this process (own peak-HBM watermark)."""
    import jax
    benchlib.honor_env_platforms()
    platform = jax.devices()[0].platform.lower()
    if not SMOKE:
        from code2vec_tpu.ops._pallas_common import tpu_backend_active
        if not tpu_backend_active():
            print(json.dumps({'error': 'tpu_unavailable',
                              'detail': f'platform={platform}'}),
                  flush=True)
            sys.exit(2)
    try:
        step_ms, temp_bytes, engaged = measure(variant)
    except Exception as exc:  # a kernel compile failure IS the answer
        print(json.dumps({'variant': variant, 'error': str(exc)[:300]}),
              flush=True)
        sys.exit(1)
    if variant != 'unfused' and not engaged and not SMOKE:
        print(json.dumps({
            'variant': variant, 'error': 'kernel_not_engaged',
            'detail': 'compiled packed predict/grad HLO has no Mosaic '
                      'custom-call'}), flush=True)
        sys.exit(3)
    memory = benchlib.device_memory_record()
    for kind, value in step_ms.items():
        record = {
            'measure': _suffix('step_ms_ragged_%s_%s' % (kind, variant)),
            'value': round(value, 3), 'unit': 'ms/step',
            'kind': kind, 'variant': variant, 'fill': FILL,
            'contexts': SHAPES.max_contexts,
            'batch': SHAPES.batch_size, **memory}
        if kind == 'train_bwd':
            # the residual-footprint axis: AOT temp bytes of the grad
            # program (None = backend without memory analysis, an
            # explicit gap like peak_hbm_bytes)
            record['temp_bytes'] = temp_bytes
        print(json.dumps(record), flush=True)


def main() -> None:
    """Parent: each arm in its own subprocess under a per-arm timeout
    (a Mosaic compile stall costs one arm, not the healthy window);
    the parent imports no jax and never touches the tunnel."""
    variant = os.environ.get('BENCH_PALLAS_RAGGED_VARIANT', '')
    if variant:
        run_variant(variant)
        return
    import subprocess
    per_arm = float(os.environ.get('BENCH_PALLAS_ARM_TIMEOUT',
                                   '240' if SMOKE else '780'))
    values: dict = {}
    hbm: dict = {}
    temps: dict = {}
    for variant in VARIANTS:
        env = dict(os.environ, BENCH_PALLAS_RAGGED_VARIANT=variant)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=per_arm)
            out, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as e:
            out = (e.stdout.decode(errors='replace')
                   if isinstance(e.stdout, bytes) else (e.stdout or ''))
            rc = -1
            print(json.dumps({'variant': variant, 'error': 'arm_timeout',
                              'timeout_s': per_arm}), flush=True)
        for line in out.splitlines():
            line = line.strip()
            if not line.startswith('{'):
                continue
            print(line, flush=True)
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            # the record carries its kind explicitly — substring-parsing
            # the measure name would confuse 'train' with 'train_bwd'
            kind = rec.get('kind')
            if rec.get('variant') == variant and 'value' in rec and kind:
                values[(kind, variant)] = rec['value']
                hbm[variant] = rec.get('peak_hbm_bytes')
                if rec.get('temp_bytes') is not None:
                    temps[variant] = rec['temp_bytes']
            if rec.get('error') == 'tpu_unavailable':
                # keep the watcher stage PENDING on a wedge mid-A/B
                sys.exit(2)
        if rc != 0:
            if variant == 'unfused':
                sys.exit(4)
            if variant == 'fused':
                print(json.dumps({
                    'verdict': 'keep-unfused',
                    'reason': 'fused arm failed or timed out'}),
                    flush=True)
                sys.exit(4)
            # a dead fused_kernel arm settles ITS verdict without
            # discarding the completed fusion A/B
            print(json.dumps({
                'verdict': 'kernel-off',
                'reason': 'fused_kernel arm failed or timed out'}),
                flush=True)
    speedups = {}
    for kind in ('train', 'predict', 'train_bwd'):
        if (kind, 'unfused') in values and (kind, 'fused') in values \
                and values[(kind, 'fused')] > 0:
            speedups[kind] = values[(kind, 'unfused')] \
                / values[(kind, 'fused')]
            print(json.dumps({
                'measure': _suffix('ragged_fusion_%s_speedup' % kind),
                'value': round(speedups[kind], 4), 'fill': FILL,
                'contexts': SHAPES.max_contexts}), flush=True)
    # the kernel-vs-twin measures: the Pallas train pair against the
    # SHIPPED default it would replace (fused custom-VJP twin) — this,
    # not the unpack comparison, is what gates RAGGED_TRAIN_KERNEL
    kernel_speedups = {}
    for kind, name in (('train', 'ragged_train_kernel_speedup'),
                       ('train_bwd', 'ragged_train_kernel_bwd_speedup')):
        if (kind, 'fused') in values and (kind, 'fused_kernel') in values \
                and values[(kind, 'fused_kernel')] > 0:
            kernel_speedups[kind] = values[(kind, 'fused')] \
                / values[(kind, 'fused_kernel')]
            print(json.dumps({
                'measure': _suffix(name),
                'value': round(kernel_speedups[kind], 4), 'fill': FILL,
                'contexts': SHAPES.max_contexts}), flush=True)
    if hbm.get('unfused') and hbm.get('fused'):
        print(json.dumps({
            'measure': _suffix('ragged_fusion_peak_hbm_ratio'),
            'value': round(hbm['fused'] / hbm['unfused'], 4),
            'fill': FILL, 'contexts': SHAPES.max_contexts}), flush=True)
    if temps.get('unfused') and temps.get('fused'):
        # grad-program temp allocation, custom-VJP vs stored-residual
        # autodiff: the recompute backward's cut (<1 is the win)
        print(json.dumps({
            'measure': _suffix('ragged_fusion_train_bwd_temp_ratio'),
            'value': round(temps['fused'] / temps['unfused'], 4),
            'fill': FILL, 'contexts': SHAPES.max_contexts}), flush=True)
    # both verdicts decide on the ROUNDED speedup with strict '>', the
    # same comparison scripts/flip_verdict.py applies to the emitted
    # (rounded) measure records — so one capture round can never write
    # contradictory decisions at the 2% boundary
    if 'train' in speedups:
        # fusion confirmation (the default is already ON; keep-unfused
        # here argues for reverting it)
        print(json.dumps({
            'verdict': ('keep-fused'
                        if round(speedups['train'], 4) > 1.02
                        else 'keep-unfused'),
            'speedup': round(speedups['train'], 4)}), flush=True)
    if 'train' in kernel_speedups:
        # the >2% rule on the kernel pair (RAGGED_TRAIN_KERNEL)
        print(json.dumps({
            'verdict': ('kernel-on'
                        if round(kernel_speedups['train'], 4) > 1.02
                        else 'kernel-off'),
            'speedup': round(kernel_speedups['train'], 4)}), flush=True)


if __name__ == '__main__':
    main()
