"""On-chip A/B for the ragged packed-wire fusion (ISSUE 10).

Measures the packed TRAIN step and the packed PREDICT step (attention
tier — the encoder + attention softmax both fused paths replace) with
``USE_PALLAS_RAGGED_FUSION`` off (unpack-then-dense, the PR-1 path) and
on (ops/pallas_ragged.py), at the java14m headline shape and realistic
fill. Each arm runs in its OWN subprocess so the per-arm
``peak_hbm_bytes`` (benchlib.device_memory_record) is that arm's peak,
not the max over both — the fused path's claim is a step-time AND an
HBM-footprint win, so both axes ride every record.

Knobs (the capture stages set them):

  BENCH_SMOKE=1       tiny CPU shapes, metrics renamed *_SMOKE_ONLY
  BENCH_CONTEXTS=N    override max_contexts (the fused path's best case
                      is high capacity / low fill, where the dense
                      planes are mostly padding)
  BENCH_FILL=F        mean fill fraction of the packed batches
                      (default benchlib.JAVA14M_FILL = 0.25)

Emits one JSON line per (arm x step kind), then the fused/unfused
speedup + peak-HBM ratio records summarize_captures.py surfaces:

  {"measure": "step_ms_ragged_train_fused", "value": ..., "fill": ...}
  {"measure": "ragged_fusion_train_speedup", "value": ..., ...}
  {"verdict": "keep-fused" | "keep-unfused", ...}
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu import benchlib  # noqa: E402

SMOKE = benchlib.smoke_requested()
SHAPES = benchlib.SMOKE_SHAPES if SMOKE else benchlib.JAVA14M
_contexts = int(os.environ.get('BENCH_CONTEXTS', '0'))
if _contexts:
    SHAPES = SHAPES._replace(max_contexts=_contexts)
FILL = float(os.environ.get('BENCH_FILL', str(benchlib.JAVA14M_FILL)))
WARMUP_STEPS, MEASURE_STEPS = benchlib.bench_steps(SMOKE)
VARIANTS = ('unfused', 'fused')


def _suffix(name: str) -> str:
    name = name + ('_SMOKE_ONLY' if SMOKE else '')
    return name + (('_c%d' % _contexts) if _contexts else '')


def measure(fused: bool):
    """One arm: (train_ms_per_step, predict_ms_per_step, engaged)."""
    import jax
    import jax.numpy as jnp

    config = benchlib.headline_config(
        SHAPES, USE_PALLAS_RAGGED_FUSION=fused)
    trainer, state = benchlib.build_trainer(config, SHAPES)
    host = benchlib.random_batches(SHAPES, 4, seed=1, fill=FILL)
    packed = benchlib.pack_batches(host, trainer)
    placed = benchlib.staged(trainer, packed)

    # engagement check (TPU fused arm only): the compiled attention-tier
    # packed program must contain the Mosaic custom-call, or the "A/B"
    # compares XLA against itself (bench_pallas_encode precedent)
    engaged = False
    if fused and not SMOKE:
        fn = trainer._predict_steps[('attention', 'packed')]
        engaged = benchlib.mosaic_engaged(fn, state.params, placed[0])

    # ---- train: steps serialize on the state dependency; block once
    def train_chain(steps: int) -> float:
        nonlocal state
        loss = None
        for i in range(steps):
            state, loss = trainer.train_step_placed(
                state, placed[i % len(placed)])
        return float(loss)

    train_chain(WARMUP_STEPS)
    t0 = time.perf_counter()
    train_chain(MEASURE_STEPS)
    train_ms = 1e3 * (time.perf_counter() - t0) / MEASURE_STEPS

    # ---- predict (attention tier): thread a scalar from each output
    # into the next input's count so the chain serializes on device
    # exactly like train's state dependency (bench.py methodology)
    chain_count = jax.jit(
        lambda count, token: count + (token * 0).astype(jnp.int32))

    def predict_chain(steps: int) -> float:
        token = jnp.zeros((), jnp.float32)
        for i in range(steps):
            ctx, count, label, weight = placed[i % len(placed)]
            out = trainer.predict_step_placed(
                state.params, (ctx, chain_count(count, token), label,
                               weight), tier='attention')
            token = out['topk_scores'].sum()
        return float(token)

    predict_chain(WARMUP_STEPS)
    t0 = time.perf_counter()
    predict_chain(MEASURE_STEPS)
    predict_ms = 1e3 * (time.perf_counter() - t0) / MEASURE_STEPS
    return train_ms, predict_ms, engaged


def run_variant(variant: str) -> None:
    """Child mode: one arm in this process (own peak-HBM watermark)."""
    import jax
    benchlib.honor_env_platforms()
    platform = jax.devices()[0].platform.lower()
    if not SMOKE:
        from code2vec_tpu.ops._pallas_common import tpu_backend_active
        if not tpu_backend_active():
            print(json.dumps({'error': 'tpu_unavailable',
                              'detail': f'platform={platform}'}),
                  flush=True)
            sys.exit(2)
    fused = variant == 'fused'
    try:
        train_ms, predict_ms, engaged = measure(fused)
    except Exception as exc:  # a kernel compile failure IS the answer
        print(json.dumps({'variant': variant, 'error': str(exc)[:300]}),
              flush=True)
        sys.exit(1)
    if fused and not engaged and not SMOKE:
        print(json.dumps({
            'variant': variant, 'error': 'kernel_not_engaged',
            'detail': 'compiled packed predict HLO has no Mosaic '
                      'custom-call'}), flush=True)
        sys.exit(3)
    memory = benchlib.device_memory_record()
    for kind, value in (('train', train_ms), ('predict', predict_ms)):
        print(json.dumps({
            'measure': _suffix('step_ms_ragged_%s_%s' % (kind, variant)),
            'value': round(value, 3), 'unit': 'ms/step',
            'variant': variant, 'fill': FILL,
            'contexts': SHAPES.max_contexts,
            'batch': SHAPES.batch_size, **memory}), flush=True)


def main() -> None:
    """Parent: each arm in its own subprocess under a per-arm timeout
    (a Mosaic compile stall costs one arm, not the healthy window);
    the parent imports no jax and never touches the tunnel."""
    variant = os.environ.get('BENCH_PALLAS_RAGGED_VARIANT', '')
    if variant:
        run_variant(variant)
        return
    import subprocess
    per_arm = float(os.environ.get('BENCH_PALLAS_ARM_TIMEOUT',
                                   '240' if SMOKE else '780'))
    values: dict = {}
    hbm: dict = {}
    for variant in VARIANTS:
        env = dict(os.environ, BENCH_PALLAS_RAGGED_VARIANT=variant)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=per_arm)
            out, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as e:
            out = (e.stdout.decode(errors='replace')
                   if isinstance(e.stdout, bytes) else (e.stdout or ''))
            rc = -1
            print(json.dumps({'variant': variant, 'error': 'arm_timeout',
                              'timeout_s': per_arm}), flush=True)
        for line in out.splitlines():
            line = line.strip()
            if not line.startswith('{'):
                continue
            print(line, flush=True)
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            measure_name = rec.get('measure', '')
            if rec.get('variant') == variant and 'value' in rec:
                for kind in ('train', 'predict'):
                    if ('_%s_' % kind) in measure_name:
                        values[(kind, variant)] = rec['value']
                        hbm[variant] = rec.get('peak_hbm_bytes')
            if rec.get('error') == 'tpu_unavailable':
                # keep the watcher stage PENDING on a wedge mid-A/B
                sys.exit(2)
        if rc != 0:
            if variant == 'fused':
                print(json.dumps({
                    'verdict': 'keep-unfused',
                    'reason': 'fused arm failed or timed out'}),
                    flush=True)
            sys.exit(4)
    speedups = {}
    for kind in ('train', 'predict'):
        if (kind, 'unfused') in values and (kind, 'fused') in values \
                and values[(kind, 'fused')] > 0:
            speedups[kind] = values[(kind, 'unfused')] \
                / values[(kind, 'fused')]
            print(json.dumps({
                'measure': _suffix('ragged_fusion_%s_speedup' % kind),
                'value': round(speedups[kind], 4), 'fill': FILL,
                'contexts': SHAPES.max_contexts}), flush=True)
    if hbm.get('unfused') and hbm.get('fused'):
        print(json.dumps({
            'measure': _suffix('ragged_fusion_peak_hbm_ratio'),
            'value': round(hbm['fused'] / hbm['unfused'], 4),
            'fill': FILL, 'contexts': SHAPES.max_contexts}), flush=True)
    if 'train' in speedups:
        # the >=2% flip rule (PERF.md) keys on the train step
        print(json.dumps({
            'verdict': ('keep-fused' if speedups['train'] > 1.02
                        else 'keep-unfused'),
            'speedup': round(speedups['train'], 4)}), flush=True)


if __name__ == '__main__':
    main()
