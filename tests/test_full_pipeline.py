"""Full-pipeline integration test (VERDICT r1 #5).

Drives the WHOLE chain the way a user would, end to end:

  Java sources → `c2v-extract --dir` (native binary, via
  scripts/preprocess.sh exactly as documented) → histograms + vocab-aware
  sampling → `.c2v`/`.dict.c2v` → training CLI with per-epoch eval →
  F1 above threshold → `--release` → load the released model → predict
  through the extractor bridge.

A format drift anywhere in the chain (extractor output, preprocess
padding, dict pickle layout, checkpoint naming, release artifact) fails
this test.  Mirrors the reference flow preprocess.sh:41-63 + train.sh +
README's release/predict walkthrough.
"""
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXTRACTOR = os.path.join(REPO, 'extractor', 'build', 'c2v-extract')

pytestmark = pytest.mark.skipif(not os.path.isfile(EXTRACTOR),
                                reason='extractor binary not built')

# Method templates: the name is fully determined by the body shape, so a
# tiny model must overfit. Fields vary per class for vocab variety.
TEMPLATES = [
    ('get{F}', 'int get{F}() {{ return this.{f}; }}'),
    ('set{F}', 'void set{F}(int value) {{ this.{f} = value; }}'),
    ('has{F}', 'boolean has{F}() {{ return this.{f} > 0; }}'),
    ('reset{F}', 'void reset{F}() {{ this.{f} = 0; }}'),
]
FIELDS = ['width', 'height', 'depth']


def _write_project(root, n_classes: int, seed_offset: int = 0) -> None:
    os.makedirs(root, exist_ok=True)
    for i in range(n_classes):
        field = FIELDS[(i + seed_offset) % len(FIELDS)]
        methods = '\n'.join(
            body.format(F=field.capitalize(), f=field)
            for _name, body in TEMPLATES)
        with open(os.path.join(root, f'C{seed_offset}_{i}.java'), 'w') as f:
            f.write('class C%d_%d {\n  int %s;\n%s\n}\n'
                    % (seed_offset, i, field, methods))


def _env() -> dict:
    # the wedged-tunnel bypass: venv python, repo-only PYTHONPATH, CPU pin
    return {
        'PATH': os.pathsep.join([os.path.dirname(sys.executable),
                                 '/usr/bin', '/bin']),
        'HOME': os.environ.get('HOME', '/root'),
        'PYTHONPATH': REPO,
        'JAX_PLATFORMS': 'cpu',
    }


def _run(cmd, cwd, timeout=420, **extra_env):
    proc = subprocess.run(cmd, cwd=cwd, env={**_env(), **extra_env},
                          capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, (
        'command %r failed:\nstdout: %s\nstderr: %s'
        % (cmd, proc.stdout[-3000:], proc.stderr[-3000:]))
    return proc.stdout + proc.stderr


def test_full_pipeline_extract_train_release_predict(tmp_path):
    # --- offline dataset production via the documented script -----------
    _write_project(tmp_path / 'dataset' / 'train', n_classes=30)
    _write_project(tmp_path / 'dataset' / 'train', n_classes=30,
                   seed_offset=1)
    _write_project(tmp_path / 'dataset' / 'val', n_classes=4)
    _write_project(tmp_path / 'dataset' / 'test', n_classes=4,
                   seed_offset=2)
    _run(['bash', os.path.join(REPO, 'scripts', 'preprocess.sh')],
         cwd=str(tmp_path),  # env defaults: dataset/{train,val,test}
         EXTRACTOR=EXTRACTOR, NUM_THREADS='8')
    # preprocess.sh env defaults name the dataset java14m
    data_prefix = tmp_path / 'data' / 'java14m' / 'java14m'
    for suffix in ['.train.c2v', '.val.c2v', '.test.c2v', '.dict.c2v']:
        assert (str(data_prefix) + suffix), suffix
        assert os.path.getsize(str(data_prefix) + suffix) > 0

    # every train row is padded to exactly MAX_CONTEXTS fields
    with open(str(data_prefix) + '.train.c2v') as f:
        first = f.readline().rstrip('\n')
    assert len(first.split(' ')) == 1 + 200  # preprocess.sh default

    # --- train with per-epoch eval via the CLI --------------------------
    save_path = tmp_path / 'models' / 'pipe' / 'saved_model'
    out = _run([sys.executable, '-m', 'code2vec_tpu.cli',
                '--data', str(data_prefix),
                '--test', str(data_prefix) + '.val.c2v',
                '--save', str(save_path),
                '--epochs', '12', '--batch-size', '16',
                '--framework', 'jax', '--dtype', 'float32'],
               cwd=str(tmp_path), timeout=540)
    f1_scores = [float(m) for m in re.findall(r'F1: ([0-9.]+)', out)]
    assert f1_scores, 'no eval F1 reported:\n' + out[-2000:]
    # name is a deterministic function of the body: must overfit
    assert f1_scores[-1] > 0.5, out[-2000:]

    # --- release + load released + evaluate -----------------------------
    _run([sys.executable, '-m', 'code2vec_tpu.cli',
          '--load', str(save_path), '--release'], cwd=str(tmp_path))
    assert (tmp_path / 'models' / 'pipe'
            / 'saved_model__only-weights').is_dir()
    out = _run([sys.executable, '-m', 'code2vec_tpu.cli',
                '--load', str(save_path),
                '--test', str(data_prefix) + '.val.c2v'],
               cwd=str(tmp_path))
    released_f1 = [float(m) for m in re.findall(r'F1: ([0-9.]+)', out)]
    assert released_f1 and abs(released_f1[-1] - f1_scores[-1]) < 1e-6

    # --- predict through the real extractor bridge ----------------------
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.serving.extractor_bridge import Extractor
    from code2vec_tpu.serving.predict import predict_file

    input_java = tmp_path / 'Input.java'
    input_java.write_text(
        'class Q { int width; int getWidth() { return this.width; } }\n')
    config = Config(MODEL_LOAD_PATH=str(save_path), DL_FRAMEWORK='jax',
                    COMPUTE_DTYPE='float32', VERBOSE_MODE=0,
                    READER_USE_NATIVE=False)
    model = Code2VecModel(config)
    extractor = Extractor(config, extractor_command=[EXTRACTOR])
    reports = predict_file(model, extractor, str(input_java))
    assert len(reports) == 1
    method_result, _raw = reports[0]
    assert method_result.original_name == 'get|width'
    # prediction names are subtoken lists (reference common.py:135-158)
    top_names = [p['name'] for p in method_result.predictions]
    assert ['get', 'width'] in top_names[:3], top_names


CS_TEMPLATES = [
    ('Get{F}', 'int Get{F}() {{ return this.{f}; }}'),
    ('Set{F}', 'void Set{F}(int value) {{ this.{f} = value; }}'),
    ('Has{F}', 'bool Has{F}() {{ return this.{f} > 0; }}'),
    ('Reset{F}', 'void Reset{F}() {{ this.{f} = 0; }}'),
]


def _write_cs_project(root, n_classes: int, seed_offset: int = 0) -> None:
    os.makedirs(root, exist_ok=True)
    for i in range(n_classes):
        field = FIELDS[(i + seed_offset) % len(FIELDS)]
        methods = '\n'.join(
            body.format(F=field.capitalize(), f=field)
            for _name, body in CS_TEMPLATES)
        with open(os.path.join(root, f'C{seed_offset}_{i}.cs'), 'w') as f:
            f.write('class C%d_%d {\n  int %s;\n%s\n}\n'
                    % (seed_offset, i, field, methods))


def test_full_pipeline_csharp(tmp_path):
    """BASELINE.json acceptance config: 'C# method-name prediction
    (CSharpExtractor -> path_context_reader)' — the documented
    preprocess_csharp.sh flow end to end into training + eval."""
    _write_cs_project(tmp_path / 'dataset' / 'train', n_classes=30)
    _write_cs_project(tmp_path / 'dataset' / 'train', n_classes=30,
                      seed_offset=1)
    _write_cs_project(tmp_path / 'dataset' / 'val', n_classes=4)
    _write_cs_project(tmp_path / 'dataset' / 'test', n_classes=4,
                      seed_offset=2)
    _run(['bash', os.path.join(REPO, 'scripts', 'preprocess_csharp.sh')],
         cwd=str(tmp_path), EXTRACTOR=EXTRACTOR, NUM_THREADS='8')
    data_prefix = tmp_path / 'data' / 'csharp' / 'csharp'
    for suffix in ['.train.c2v', '.val.c2v', '.test.c2v', '.dict.c2v']:
        assert os.path.getsize(str(data_prefix) + suffix) > 0, suffix

    out = _run([sys.executable, '-m', 'code2vec_tpu.cli',
                '--data', str(data_prefix),
                '--test', str(data_prefix) + '.val.c2v',
                '--save', str(tmp_path / 'models' / 'cs' / 'saved_model'),
                '--epochs', '12', '--batch-size', '16',
                '--framework', 'jax', '--dtype', 'float32'],
               cwd=str(tmp_path), timeout=540)
    f1_scores = [float(m) for m in re.findall(r'F1: ([0-9.]+)', out)]
    assert f1_scores, 'no eval F1 reported:\n' + out[-2000:]
    assert f1_scores[-1] > 0.5, out[-2000:]
