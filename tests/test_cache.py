"""Binary token cache: same rows as the streaming reader, cache reuse,
staleness invalidation, shuffle correctness."""
import numpy as np
import pytest

from code2vec_tpu.data.cache import TokenCache
from code2vec_tpu.data.reader import EstimatorAction, PathContextReader

from tests.test_reader import small_setup, _write_train  # noqa: F401


def _rows_from_batches(batches):
    rows = set()
    for batch in batches:
        for r in range(batch.label.shape[0]):
            if batch.weight[r] > 0:
                rows.add((int(batch.label[r]),
                          tuple(batch.source[r].tolist()),
                          tuple(batch.path[r].tolist()),
                          tuple(batch.mask[r].tolist())))
    return rows


def test_cache_matches_streaming_reader(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 zzz,p2,t1', 'lbl2 s2,p2,t1',
                          'unknown s1,p1,t1', 'lbl2 zz,zz,zz'] * 5)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    assert cache.num_rows == 10  # 2 of 4 lines pass the train filter, x5
    streamed = _rows_from_batches(reader.iter_epoch(shuffle=False))
    cached = _rows_from_batches(cache.iter_epoch(2, shuffle=False))
    assert streamed == cached


def test_cache_is_reused_and_invalidated(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 4)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache1 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache1.num_rows == 4
    # unchanged file -> reused (same meta)
    cache2 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache2.meta == cache1.meta
    # grown file -> rebuilt
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 6)
    cache3 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache3.num_rows == 6


def test_cache_invalidated_by_vocab_content_change(small_setup):  # noqa: F811
    """Same vocab *sizes*, different word→index mapping (the fine-tuning
    trap: sizes pinned at caps while dictionaries.bin differs) must NOT
    reuse the cache — indices would silently be wrong (ADVICE r1)."""
    import pickle

    from code2vec_tpu.config import Config
    from code2vec_tpu.vocab import Code2VecVocabs

    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1'] * 2)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache1 = TokenCache.build_or_load(config, vocabs, reader)

    # Identical sizes, swapped frequency order -> s1/s2 swap indices.
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump({'s2': 10, 's1': 9, 't1': 8}, f)
        pickle.dump({'p2': 7, 'p1': 6}, f)
        pickle.dump({'lbl2': 5, 'lbl1': 4}, f)
        pickle.dump(4, f)
    config2 = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0,
                     MAX_CONTEXTS=4, TRAIN_BATCH_SIZE=2, TEST_BATCH_SIZE=2,
                     SHUFFLE_BUFFER_SIZE=16, READER_USE_NATIVE=False)
    vocabs2 = Code2VecVocabs(config2)
    assert vocabs2.token_vocab.size == vocabs.token_vocab.size
    reader2 = PathContextReader(vocabs2, config2, EstimatorAction.Train)
    cache2 = TokenCache.build_or_load(config2, vocabs2, reader2)
    assert cache2.meta != cache1.meta  # rebuilt, not reused
    s1_new = vocabs2.token_vocab.lookup_index('s1')
    assert any(s1_new in batch.source
               for batch in cache2.iter_epoch(2, shuffle=False))


def test_per_process_caches_partition_the_rows(small_setup):  # noqa: F811
    """Multi-host: each process caches its own line stride in its own
    directory; the per-process caches are disjoint and their union equals
    the single-process cache (VERDICT r1 weak #7)."""
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1', 'lbl1 s2,p1,t1',
             'lbl2 s1,p2,t1', 'lbl1 s1,p2,t1']
    _write_train(prefix, lines)

    full_reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    full = TokenCache.build_or_load(config, vocabs, full_reader)
    full_rows = _rows_from_batches(full.iter_epoch(2, shuffle=False))

    shard_rows = []
    for index in range(2):
        reader = PathContextReader(vocabs, config, EstimatorAction.Train,
                                   process_index=index, process_count=2)
        cache = TokenCache.build_or_load(config, vocabs, reader)
        assert cache.cache_dir.endswith('.tokcache.p%dof2' % index)
        shard_rows.append(
            _rows_from_batches(cache.iter_epoch(1, shuffle=False)))
    assert shard_rows[0].isdisjoint(shard_rows[1])
    assert shard_rows[0] | shard_rows[1] == full_rows


def test_cache_shuffle_is_epoch_dependent_permutation(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1', 'lbl1 s2,p1,t1',
             'lbl2 s1,p2,t1'] * 4
    _write_train(prefix, lines)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)

    def epoch_rows(seed):
        rows = []
        for batch in cache.iter_epoch(4, shuffle=True, seed=seed,
                                      chunk_rows=8):
            for r in range(batch.label.shape[0]):
                if batch.weight[r] > 0:
                    rows.append((int(batch.label[r]),
                                 tuple(batch.source[r].tolist())))
        return rows

    rows0, rows1 = epoch_rows(0), epoch_rows(1)
    assert sorted(rows0) == sorted(rows1)  # same multiset
    assert rows0 != rows1                  # different order


def _write_v1_cache(cache_dir, config, vocabs, reader):
    """Materialize the v1 (padded-plane) on-disk layout for the
    read-compatibility tests — byte-for-byte what the pre-v2 builder
    wrote: source/path/target planes + labels + a meta without a
    version key."""
    import json
    import os

    from code2vec_tpu.data.cache import _fingerprint
    os.makedirs(cache_dir, exist_ok=True)
    handles = {name: open(os.path.join(cache_dir, name), 'wb')
               for name in ('source.bin', 'path.bin', 'target.bin',
                            'label.bin')}
    num_rows = 0
    for batch in reader.iter_epoch(shuffle=False, wire_format='planes'):
        valid = batch.weight > 0
        handles['source.bin'].write(
            np.ascontiguousarray(batch.source[valid]).tobytes())
        handles['path.bin'].write(
            np.ascontiguousarray(batch.path[valid]).tobytes())
        handles['target.bin'].write(
            np.ascontiguousarray(batch.target[valid]).tobytes())
        handles['label.bin'].write(
            np.ascontiguousarray(batch.label[valid]).tobytes())
        num_rows += int(valid.sum())
    for handle in handles.values():
        handle.close()
    meta = _fingerprint(config, vocabs, reader.data_path)
    meta['num_rows'] = num_rows
    with open(os.path.join(cache_dir, 'meta.json'), 'w') as f:
        json.dump(meta, f)


def test_new_cache_builds_v2_packed_on_disk(small_setup):  # noqa: F811
    """A fresh build writes format v2 (ragged ctx triples): smaller than
    the v1 planes at any fill < 3/4, same rows back out."""
    import os
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 s2,p2,t1', 'lbl2 s2,p2,t1'] * 3)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    assert cache.version == 2
    assert os.path.isfile(os.path.join(cache.cache_dir, 'ctx.bin'))
    assert not os.path.exists(os.path.join(cache.cache_dir, 'source.bin'))
    # 6 rows, lengths {2, 1} alternating -> 9 context triples
    assert cache.meta['num_contexts'] == 9
    streamed = _rows_from_batches(reader.iter_epoch(shuffle=False))
    assert _rows_from_batches(cache.iter_epoch(2, shuffle=False)) == streamed


def test_v1_cache_reads_compatibly_and_is_not_rebuilt(small_setup):  # noqa: F811
    """tokcache v1 -> v2 read compatibility: a fresh v1 directory keeps
    serving under the v2 code — identical batches to the streaming
    reader, no rebuild on build_or_load, and it can feed the packed wire
    via host-side packing."""
    from code2vec_tpu.data import packed as packed_lib
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 zzz,p2,t1', 'lbl2 s2,p2,t1'] * 4)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache_dir = str(prefix) + '.train.c2v.tokcache'
    _write_v1_cache(cache_dir, config, vocabs, reader)

    cache = TokenCache.build_or_load(config, vocabs, reader)
    assert cache.cache_dir == cache_dir
    assert cache.version == 1          # served as-is, not rebuilt
    streamed = _rows_from_batches(reader.iter_epoch(shuffle=False))
    assert _rows_from_batches(cache.iter_epoch(2, shuffle=False)) == streamed
    packed = list(cache.iter_epoch(2, shuffle=False, wire_format='packed'))
    assert all(isinstance(p, packed_lib.PackedBatch) for p in packed)
    unpacked = [packed_lib.unpack_batch_host(
        p, config.MAX_CONTEXTS, vocabs.token_vocab.pad_index,
        vocabs.path_vocab.pad_index) for p in packed]
    assert _rows_from_batches(unpacked) == streamed


def test_v2_cache_packed_emission_matches_planes(small_setup):  # noqa: F811
    """One v2 cache, both wire formats, shuffled: identical example
    multiset, and the packed batches unpack bit-exactly to the plane
    batches of the same epoch seed."""
    from code2vec_tpu.data import packed as packed_lib
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1 s1,p1,t1', 'lbl1 s2,p1,t1',
             'lbl2 s1,p2,t1'] * 4
    _write_train(prefix, lines)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    assert cache.version == 2
    planes = list(cache.iter_epoch(4, shuffle=True, seed=3, chunk_rows=8))
    packed = list(cache.iter_epoch(4, shuffle=True, seed=3, chunk_rows=8,
                                   wire_format='packed', data_shards=2))
    assert len(planes) == len(packed)
    for plane_batch, packed_batch in zip(planes, packed):
        assert packed_batch.ctx.shape[0] == 2  # data_shards honored
        restored = packed_lib.unpack_batch_host(
            packed_batch, config.MAX_CONTEXTS,
            vocabs.token_vocab.pad_index, vocabs.path_vocab.pad_index)
        for field in ('source', 'path', 'target', 'mask', 'label',
                      'weight'):
            np.testing.assert_array_equal(getattr(plane_batch, field),
                                          getattr(restored, field),
                                          err_msg=field)


def test_cache_partial_final_batch_padded(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 5)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    batches = list(cache.iter_epoch(2, shuffle=False))
    assert len(batches) == 3
    assert batches[-1].source.shape == (2, config.MAX_CONTEXTS)
    np.testing.assert_array_equal(batches[-1].weight, [1.0, 0.0])
    np.testing.assert_array_equal(batches[-1].mask[1], 0.0)


def test_truncated_cache_shard_raises_rebuild_error(small_setup):  # noqa: F811
    """ISSUE 3 satellite: a truncated ctx.bin (disk-full or killed
    build) must fail at load with a clear rebuild message, not serve
    mis-aligned epochs."""
    import os

    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 s2,p2,t1'] * 6)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    ctx_path = os.path.join(cache.cache_dir, 'ctx.bin')
    with open(ctx_path, 'r+b') as f:
        f.truncate(os.path.getsize(ctx_path) - 4)
    with pytest.raises(ValueError, match='rebuild'):
        TokenCache(cache.cache_dir, config, vocabs)


def test_count_total_mismatch_raises_rebuild_error(small_setup):  # noqa: F811
    """Same-size but inconsistent count.bin (torn write) must be caught
    by the count/ctx reconciliation, not mis-slice every batch."""
    import os

    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 s2,p2,t1'] * 6)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    count_path = os.path.join(cache.cache_dir, 'count.bin')
    counts = np.fromfile(count_path, dtype=np.int32).copy()
    counts[0] += 1  # same byte size, broken offsets
    counts.tofile(count_path)
    with pytest.raises(ValueError, match='rebuild'):
        TokenCache(cache.cache_dir, config, vocabs)
