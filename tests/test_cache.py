"""Binary token cache: same rows as the streaming reader, cache reuse,
staleness invalidation, shuffle correctness."""
import numpy as np
import pytest

from code2vec_tpu.data.cache import TokenCache
from code2vec_tpu.data.reader import EstimatorAction, PathContextReader

from tests.test_reader import small_setup, _write_train  # noqa: F401


def _rows_from_batches(batches):
    rows = set()
    for batch in batches:
        for r in range(batch.label.shape[0]):
            if batch.weight[r] > 0:
                rows.add((int(batch.label[r]),
                          tuple(batch.source[r].tolist()),
                          tuple(batch.path[r].tolist()),
                          tuple(batch.mask[r].tolist())))
    return rows


def test_cache_matches_streaming_reader(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1 zzz,p2,t1', 'lbl2 s2,p2,t1',
                          'unknown s1,p1,t1', 'lbl2 zz,zz,zz'] * 5)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    assert cache.num_rows == 10  # 2 of 4 lines pass the train filter, x5
    streamed = _rows_from_batches(reader.iter_epoch(shuffle=False))
    cached = _rows_from_batches(cache.iter_epoch(2, shuffle=False))
    assert streamed == cached


def test_cache_is_reused_and_invalidated(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 4)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache1 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache1.num_rows == 4
    # unchanged file -> reused (same meta)
    cache2 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache2.meta == cache1.meta
    # grown file -> rebuilt
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 6)
    cache3 = TokenCache.build_or_load(config, vocabs, reader)
    assert cache3.num_rows == 6


def test_cache_invalidated_by_vocab_content_change(small_setup):  # noqa: F811
    """Same vocab *sizes*, different word→index mapping (the fine-tuning
    trap: sizes pinned at caps while dictionaries.bin differs) must NOT
    reuse the cache — indices would silently be wrong (ADVICE r1)."""
    import pickle

    from code2vec_tpu.config import Config
    from code2vec_tpu.vocab import Code2VecVocabs

    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1'] * 2)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache1 = TokenCache.build_or_load(config, vocabs, reader)

    # Identical sizes, swapped frequency order -> s1/s2 swap indices.
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump({'s2': 10, 's1': 9, 't1': 8}, f)
        pickle.dump({'p2': 7, 'p1': 6}, f)
        pickle.dump({'lbl2': 5, 'lbl1': 4}, f)
        pickle.dump(4, f)
    config2 = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0,
                     MAX_CONTEXTS=4, TRAIN_BATCH_SIZE=2, TEST_BATCH_SIZE=2,
                     SHUFFLE_BUFFER_SIZE=16, READER_USE_NATIVE=False)
    vocabs2 = Code2VecVocabs(config2)
    assert vocabs2.token_vocab.size == vocabs.token_vocab.size
    reader2 = PathContextReader(vocabs2, config2, EstimatorAction.Train)
    cache2 = TokenCache.build_or_load(config2, vocabs2, reader2)
    assert cache2.meta != cache1.meta  # rebuilt, not reused
    s1_new = vocabs2.token_vocab.lookup_index('s1')
    assert any(s1_new in batch.source
               for batch in cache2.iter_epoch(2, shuffle=False))


def test_per_process_caches_partition_the_rows(small_setup):  # noqa: F811
    """Multi-host: each process caches its own line stride in its own
    directory; the per-process caches are disjoint and their union equals
    the single-process cache (VERDICT r1 weak #7)."""
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1', 'lbl1 s2,p1,t1',
             'lbl2 s1,p2,t1', 'lbl1 s1,p2,t1']
    _write_train(prefix, lines)

    full_reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    full = TokenCache.build_or_load(config, vocabs, full_reader)
    full_rows = _rows_from_batches(full.iter_epoch(2, shuffle=False))

    shard_rows = []
    for index in range(2):
        reader = PathContextReader(vocabs, config, EstimatorAction.Train,
                                   process_index=index, process_count=2)
        cache = TokenCache.build_or_load(config, vocabs, reader)
        assert cache.cache_dir.endswith('.tokcache.p%dof2' % index)
        shard_rows.append(
            _rows_from_batches(cache.iter_epoch(1, shuffle=False)))
    assert shard_rows[0].isdisjoint(shard_rows[1])
    assert shard_rows[0] | shard_rows[1] == full_rows


def test_cache_shuffle_is_epoch_dependent_permutation(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1', 'lbl1 s2,p1,t1',
             'lbl2 s1,p2,t1'] * 4
    _write_train(prefix, lines)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)

    def epoch_rows(seed):
        rows = []
        for batch in cache.iter_epoch(4, shuffle=True, seed=seed,
                                      chunk_rows=8):
            for r in range(batch.label.shape[0]):
                if batch.weight[r] > 0:
                    rows.append((int(batch.label[r]),
                                 tuple(batch.source[r].tolist())))
        return rows

    rows0, rows1 = epoch_rows(0), epoch_rows(1)
    assert sorted(rows0) == sorted(rows1)  # same multiset
    assert rows0 != rows1                  # different order


def test_cache_partial_final_batch_padded(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1'] * 5)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    cache = TokenCache.build_or_load(config, vocabs, reader)
    batches = list(cache.iter_epoch(2, shuffle=False))
    assert len(batches) == 3
    assert batches[-1].source.shape == (2, config.MAX_CONTEXTS)
    np.testing.assert_array_equal(batches[-1].weight, [1.0, 0.0])
    np.testing.assert_array_equal(batches[-1].mask[1], 0.0)
