"""End-to-end training slice: overfit a tiny synthetic corpus on CPU via the
full Code2VecModel lifecycle (SURVEY.md §4 'tiny-corpus end-to-end
train-overfit test'), for both backends."""
import pickle
import random

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.model_api import Code2VecModel


def make_dataset(tmp_path, n_train=60, max_contexts=6, seed=0):
    """Learnable mapping: label fully determined by the context tokens."""
    rng = random.Random(seed)
    labels = ['get|a', 'set|b', 'run|c', 'close|d']
    tokens = {lbl: [f'tok{lbl[-1]}{j}' for j in range(3)] for lbl in labels}
    paths = ['pA', 'pB', 'pC']

    def example(lbl):
        n = rng.randint(2, max_contexts)
        ctxs = ' '.join(
            '{},{},{}'.format(rng.choice(tokens[lbl]), rng.choice(paths),
                              rng.choice(tokens[lbl]))
            for _ in range(n))
        pad = ' ' * (max_contexts - n)
        return f'{lbl} {ctxs}{pad}'

    train_lines = [example(rng.choice(labels)) for _ in range(n_train)]
    val_lines = [example(rng.choice(labels)) for _ in range(16)]
    prefix = tmp_path / 'tiny'
    (tmp_path / 'tiny.train.c2v').write_text('\n'.join(train_lines) + '\n')
    (tmp_path / 'tiny.val.c2v').write_text('\n'.join(val_lines) + '\n')

    token_count, path_count, target_count = {}, {}, {}
    for line in train_lines:
        parts = line.strip().split(' ')
        target_count[parts[0]] = target_count.get(parts[0], 0) + 1
        for ctx in parts[1:]:
            if not ctx:
                continue
            s, p, t = ctx.split(',')
            token_count[s] = token_count.get(s, 0) + 1
            token_count[t] = token_count.get(t, 0) + 1
            path_count[p] = path_count.get(p, 0) + 1
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump(token_count, f)
        pickle.dump(path_count, f)
        pickle.dump(target_count, f)
        pickle.dump(len(train_lines), f)
    return prefix


@pytest.mark.parametrize('framework', ['jax', 'flax'])
def test_overfit_tiny_corpus(tmp_path, framework):
    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix),
        TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
        DL_FRAMEWORK=framework, COMPUTE_DTYPE='float32',
        MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16,
        NUM_TRAIN_EPOCHS=30, SAVE_EVERY_EPOCHS=1000,  # don't save
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        LEARNING_RATE=0.01)
    model = Code2VecModel(config)

    losses = []
    orig_fit = model.trainer.fit

    def capturing_fit(state, epoch_batches, start_epoch=0, on_epoch_end=None,
                      **kwargs):
        def wrapped_on_epoch_end(epoch, st, batch_num):
            pass  # skip per-epoch evaluate to keep the test fast
        return orig_fit(state, epoch_batches, start_epoch=start_epoch,
                        on_epoch_end=wrapped_on_epoch_end, **kwargs)

    model.trainer.fit = capturing_fit
    model.train()

    results = model.evaluate()
    # the mapping is deterministic from tokens -> label: must overfit
    assert results.topk_acc[0] > 0.9, str(results)
    assert results.subtoken_f1 > 0.9, str(results)


def test_loss_decreases(tmp_path):
    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False, LEARNING_RATE=0.01)
    model = Code2VecModel(config)
    from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
    reader = PathContextReader(model.vocabs, config, EstimatorAction.Train)
    state = model.state
    first_loss = last_loss = None
    for _ in range(10):
        for batch in reader.iter_epoch(shuffle=True, seed=0):
            state, loss = model.trainer.train_step(state, batch)
            if first_loss is None:
                first_loss = float(loss)
            last_loss = float(loss)
    assert last_loss < first_loss * 0.7
