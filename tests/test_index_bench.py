"""CPU guard on the index's throughput win (ISSUE 5 acceptance): warm
exact search must sustain >= 10x the naive per-query NumPy host loop,
with ZERO XLA compiles on the post-warmup query path (asserted via the
telemetry jit-compile counter, same trick as tests/test_serving_bench).
The real curves are captured by ``benchmarks/bench_index.py``."""
import time

import numpy as np

from code2vec_tpu.index import store as store_lib
from code2vec_tpu.index.exact import ExactIndex
from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry.jit_tracker import install_compile_listener


def naive_numpy_search(vectors_normed, queries, k):
    """The no-index baseline: one full scan + argsort per query (the
    reference's embedding-similarity demo shape)."""
    out = []
    for q in queries:
        qn = q / max(np.linalg.norm(q), 1e-12)
        scores = vectors_normed @ qn
        out.append(np.argsort(-scores, kind='stable')[:k])
    return np.stack(out)


def test_exact_search_beats_numpy_loop_10x_with_zero_compiles(tmp_path):
    # sized so per-call fixed costs (jit dispatch, d2h) are small next
    # to the scan itself — the ratio then stays stable even when the
    # suite saturates a small CPU (the flake mode of a timing floor)
    rng = np.random.default_rng(0)
    vectors = rng.normal(size=(16384, 64)).astype(np.float32)
    queries = rng.normal(size=(64, 64)).astype(np.float32)
    k = 10
    store = store_lib.build(str(tmp_path / 'bench.vecindex'), [vectors])
    normed = store.all_rows().astype(np.float32)

    reps = 5
    naive_s = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        naive_idx = naive_numpy_search(normed, queries, k)
        naive_s = min(naive_s, time.perf_counter() - t0)

    core.reset()
    core.enable()
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        index = ExactIndex(store).warmup(k)
        index.search(queries, k)          # warm the 64-query bucket
        warm_compiles = compiles.value
        exact_s = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            _values, exact_idx = index.search(queries, k)
            exact_s = min(exact_s, time.perf_counter() - t0)
        postwarm_compiles = compiles.value - warm_compiles
    finally:
        core.disable()
        core.reset()

    assert postwarm_compiles == 0, (
        '%d XLA compiles on the post-warmup query path'
        % postwarm_compiles)
    # same answers (rank-for-rank; both tie-break by lowest index)
    assert np.array_equal(exact_idx, naive_idx)
    assert naive_s >= 10.0 * exact_s, (
        'exact %.4fs vs naive %.4fs: below the 10x floor (%.1fx)'
        % (exact_s, naive_s, naive_s / exact_s))
