"""Extractor bridge + interactive REPL tests with a scripted fake
extractor (the real native extractor has its own golden tests)."""
import sys

import pytest

from code2vec_tpu import common
from code2vec_tpu.config import Config
from code2vec_tpu.serving.extractor_bridge import Extractor
from code2vec_tpu.serving.predict import InteractivePredictor
from tests.test_train_overfit import make_dataset

FAKE_OUTPUT = ('get|a toka0,pA,toka1 toka1,pB,toka2\n'
               'set|b tokb0,pA,tokb1\n')


@pytest.fixture
def fake_extractor(tmp_path):
    """A stand-in extractor CLI that emits fixed context lines."""
    script = tmp_path / 'fake_extract.py'
    script.write_text(
        'import sys\n'
        'args = sys.argv[1:]\n'
        'assert "--no_hash" in args\n'
        'assert "--file" in args\n'
        'path = args[args.index("--file") + 1]\n'
        'open(path)\n'  # must exist
        'sys.stdout.write(%r)\n' % FAKE_OUTPUT)
    return [sys.executable, str(script)]


def test_extractor_hashes_paths_and_builds_unhash_dict(tmp_path,
                                                       fake_extractor):
    config = Config(TRAIN_DATA_PATH_PREFIX='x', MAX_CONTEXTS=4,
                    VERBOSE_MODE=0)
    input_file = tmp_path / 'Input.java'
    input_file.write_text('class X {}')
    extractor = Extractor(config, extractor_command=fake_extractor)
    lines, unhash = extractor.extract_paths(str(input_file))
    assert len(lines) == 2
    first = lines[0].split(' ')
    assert first[0] == 'get|a'
    src, hashed, tgt = first[1].split(',')
    assert src == 'toka0' and tgt == 'toka1'
    assert hashed == str(common.java_string_hashcode('pA'))
    assert unhash[hashed] == 'pA'
    # padded to MAX_CONTEXTS fields
    assert len(lines[0].rstrip('\n').split(' ')) - 1 == 4


def test_extractor_missing_input_raises(tmp_path, fake_extractor):
    config = Config(TRAIN_DATA_PATH_PREFIX='x', MAX_CONTEXTS=4,
                    VERBOSE_MODE=0)
    extractor = Extractor(config, extractor_command=fake_extractor)
    with pytest.raises(ValueError):
        extractor.extract_paths(str(tmp_path / 'missing.java'))


def test_extractor_head_truncates(tmp_path):
    config = Config(TRAIN_DATA_PATH_PREFIX='x', MAX_CONTEXTS=1,
                    VERBOSE_MODE=0)
    script = tmp_path / 'many.py'
    script.write_text(
        "import sys\n"
        "sys.stdout.write('m a,p1,b c,p2,d e,p3,f\\n')\n")
    input_file = tmp_path / 'Input.java'
    input_file.write_text('x')
    extractor = Extractor(config,
                          extractor_command=[sys.executable, str(script)])
    lines, unhash = extractor.extract_paths(str(input_file))
    contexts = [c for c in lines[0].split(' ')[1:] if c]
    assert len(contexts) == 1  # head-truncation (reference extractor.py:27)
    assert str(common.java_string_hashcode('p1')) in unhash


def test_interactive_repl_end_to_end(tmp_path, fake_extractor, monkeypatch,
                                     capsys):
    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False)
    from code2vec_tpu.model_api import Code2VecModel
    model = Code2VecModel(config)

    input_file = tmp_path / 'Input.java'
    input_file.write_text('class X {}')
    extractor = Extractor(config, extractor_command=fake_extractor)
    predictor = InteractivePredictor(config, model, extractor=extractor,
                                     input_filename=str(input_file))

    answers = iter(['', 'q'])
    monkeypatch.setattr('builtins.input', lambda: next(answers))
    predictor.predict()
    out = capsys.readouterr().out
    assert 'Original name:\tget|a' in out
    assert 'predicted:' in out
    assert 'Attention:' in out
    assert 'context: toka0,pA,toka1' in out  # un-hashed path displayed
    assert 'Exiting...' in out
