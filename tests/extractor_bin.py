"""Shared resolution of the native extractor binary for the test suites.

Default: ``extractor/build/c2v-extract`` (built on demand by
tests/test_extractor.py). ``C2V_EXTRACT_BINARY`` overrides it so
``make asan`` / ``make tsan`` (extractor/Makefile) can point the suites at
an instrumented build — and an override naming a missing file is a skip
with a clear reason, never a cascade of FileNotFoundError.
"""
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OVERRIDE = os.environ.get('C2V_EXTRACT_BINARY')
BINARY = _OVERRIDE or os.path.join(REPO, 'extractor', 'build', 'c2v-extract')


def binary_missing_reason():
    """Skip reason when the resolved binary cannot be used, else None.
    When the env override is set, only that exact file is acceptable —
    building the default binary would silently test the wrong artifact."""
    if _OVERRIDE and not os.path.isfile(_OVERRIDE):
        return ('C2V_EXTRACT_BINARY=%r does not exist (build it with '
                '`make -C extractor build/<name>` first)' % _OVERRIDE)
    return None
