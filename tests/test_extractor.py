"""Golden tests for the native C++ Java extractor (extractor/).

The reference JAR can't run here (no JVM in the image), so goldens are
hand-derived from the reference's documented semantics
(FeatureExtractor.java / Property.java / Common.java — see
extractor/src/pathctx.h)."""
import os
import subprocess

import pytest

from code2vec_tpu import common

from tests.extractor_bin import BINARY, REPO, binary_missing_reason


def _skip_reason():
    reason = binary_missing_reason()
    if reason is not None:
        return reason
    if os.path.isfile(BINARY):
        return None
    proc = subprocess.run(['make'], cwd=os.path.join(REPO, 'extractor'),
                          capture_output=True, text=True)
    return None if proc.returncode == 0 else 'extractor build unavailable'


_REASON = _skip_reason()
pytestmark = pytest.mark.skipif(_REASON is not None, reason=str(_REASON))


def run_extractor(*args):
    return subprocess.run([BINARY, '--max_path_length', '8',
                           '--max_path_width', '2', *args],
                          capture_output=True, text=True)


def extract_file(path, no_hash=True):
    args = ['--file', path] + (['--no_hash'] if no_hash else [])
    proc = run_extractor(*args)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout.splitlines()


def test_simple_method_golden(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('public class T {\n'
                   '    int getSquare(int x) {\n'
                   '        return x * x;\n'
                   '    }\n'
                   '}\n')
    lines = extract_file(str(src))
    assert len(lines) == 1
    parts = lines[0].split(' ')
    assert parts[0] == 'get|square'   # subtoken label
    contexts = parts[1:]
    # the x*x pair: both leaves under the BinaryExpr, childIds 0 and 1
    assert 'x,(NameExpr0)^(BinaryExpr:times)_(NameExpr1),x' in contexts
    # METHOD_NAME substitution for the name leaf
    assert any(',METHOD_NAME' in c or c.startswith('METHOD_NAME,')
               for c in contexts)
    # all-pairs count: leaves are [int, METHOD_NAME, int, x, x, x] = 6
    # -> 15 pairs, minus prunes; every context has 3 comma parts
    assert all(len(c.split(',')) == 3 for c in contexts)


def test_path_length_pruning(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('class T { void f(int a) { g(h(i(j(k(a)))));'
                   ' int z = a; } }')
    lines = extract_file(str(src))
    for ctx in lines[0].split(' ')[1:]:
        path = ctx.split(',')[1]
        # reference pathLength = stack nodes excluding the LCA = number of
        # arrows (FeatureExtractor.java:140-143)
        assert path.count('^') + path.count('_') <= 8, path


def test_snippet_wrap_retry(tmp_path):
    # bare method body: parses only via the reference's class-wrap retry
    src = tmp_path / 'snippet.java'
    src.write_text('int add(int a, int b) { return a + b; }')
    lines = extract_file(str(src))
    assert lines[0].startswith('add ')
    assert '(BinaryExpr:plus)' in lines[0]


def test_hash_mode_matches_java_hashcode(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('class T { int id(int x) { return x; } }')
    no_hash_lines = extract_file(str(src), no_hash=True)
    hashed_lines = extract_file(str(src), no_hash=False)
    raw_ctxs = no_hash_lines[0].split(' ')[1:]
    hashed_ctxs = hashed_lines[0].split(' ')[1:]
    assert len(raw_ctxs) == len(hashed_ctxs)
    for raw, hashed in zip(raw_ctxs, hashed_ctxs):
        raw_source, raw_path, raw_target = raw.split(',')
        hashed_source, hashed_path, hashed_target = hashed.split(',')
        assert (raw_source, raw_target) == (hashed_source, hashed_target)
        assert int(hashed_path) == common.java_string_hashcode(raw_path)


def test_normalization_rules(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('class T { void f() {\n'
                   '  String s = "Hello, World!";\n'
                   '  int n = 123;\n'
                   '  callIt(s, n);\n'
                   '} }')
    line = extract_file(str(src))[0]
    # string literal: lowercase, strip quotes/commas/non-alpha
    assert 'helloworld' in line
    # integer literal name: digits survive normalize (no alpha)
    assert ',123' in line or '123,' in line


def test_method_name_is_label_not_leaf_token(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('class T { void setFooBar(int v) { this.v = v; } }')
    line = extract_file(str(src))[0]
    assert line.split(' ')[0] == 'set|foo|bar'


def test_empty_method_skipped(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('class T { void empty() { } int one() { return 1; } }')
    lines = extract_file(str(src))
    labels = [line.split(' ')[0] for line in lines]
    assert labels == ['one']  # empty body -> length 0 < min_code_len


def test_dir_mode_with_broken_file(tmp_path):
    (tmp_path / 'a').mkdir()
    (tmp_path / 'a' / 'Good.java').write_text(
        'class G { int f(int x) { return x; } }')
    (tmp_path / 'Broken.java').write_text('not java at all {{{')
    proc = run_extractor('--dir', str(tmp_path), '--num_threads', '2',
                         '--no_hash')
    assert proc.returncode == 0
    labels = [line.split(' ')[0] for line in proc.stdout.splitlines()]
    assert labels == ['f']
    assert 'could not parse' in proc.stderr


def test_operators_and_constructs(tmp_path):
    src = tmp_path / 'T.java'
    src.write_text('''
class T {
  int compute(int[] arr, boolean flag) {
    int total = 0;
    for (int i = 0; i < arr.length; i++) {
      if (flag && arr[i] % 2 == 0) { total += arr[i]; }
      else { total -= 1; }
    }
    while (total > 100) { total /= 2; }
    return flag ? total : -total;
  }
}
''')
    line = extract_file(str(src))[0]
    assert line.split(' ')[0] == 'compute'
    for expected in ['BinaryExpr:less', 'UnaryExpr:posIncrement',
                     'AssignExpr:plus', 'ArrayAccessExpr', 'ConditionalExpr',
                     'FieldAccessExpr', 'ForStmt', 'WhileStmt', 'IfStmt']:
        assert expected in line, expected


CSHARP_SAMPLE = '''
using System;

namespace Demo
{
    public class Calc
    {
        // Adds two numbers
        public int AddNumbers(int left, int right)
        {
            var sum = left + right;
            return sum;
        }

        public bool IsPositive(int value) => value > 0;
    }
}
'''


# --------------------------------------------------------------------------
# Hard-corner goldens (VERDICT r4 #10): the Java constructs a hand-written
# parser is most likely to diverge on, pinned context-for-context against
# the reference's javaparser-derived semantics (FeatureExtractor.java:51-75;
# node names audited against the reference JAR's constant pool in
# test_extractor_parity.py). All run under ASan/TSan via `make asan`.

def test_lambda_expression_and_block_bodies(tmp_path):
    src = tmp_path / 'Lambdas.java'
    src.write_text(
        'public class Lambdas {\n'
        '    Runnable makeTask(int count) {\n'
        '        return () -> { int total = count + 1; use(total); };\n'
        '    }\n'
        '    java.util.function.Function<Integer, Integer> '
        'makeAdder(int delta) {\n'
        '        return value -> value + delta;\n'
        '    }\n'
        '    void use(int v) {}\n'
        '}\n')
    lines = extract_file(str(src))
    labels = [line.split(' ')[0] for line in lines]
    # `use` has an empty body: no contexts, skipped (reference parity)
    assert labels == ['make|task', 'make|adder']
    task = lines[0].split(' ')[1:]
    # block-bodied lambda: LambdaExpr -> BlockStmt chain, capture of the
    # enclosing parameter included
    assert ('METHOD_NAME,(NameExpr1)^(MethodDeclaration)_(BlockStmt)_'
            '(ReturnStmt)_(LambdaExpr)_(BlockStmt)_(ExpressionStmt)_'
            '(MethodCallExpr0)_(NameExpr0),use') in task
    adder = lines[1].split(' ')[1:]
    # expression-bodied lambda: its parameter pairs with its body leaves
    assert ('value,(VariableDeclaratorId0)^(Parameter)^(LambdaExpr)_'
            '(BinaryExpr:plus)_(NameExpr0),value') in adder
    assert 'value,(NameExpr0)^(BinaryExpr:plus)_(NameExpr1),delta' in adder


def test_anonymous_class_methods_extract_separately(tmp_path):
    """Methods declared inside an anonymous class body are method
    declarations like any other: the reference visits every
    MethodDeclaration node, so `run` is its own labeled example, with the
    enclosing method's captured variable among its leaves."""
    src = tmp_path / 'Anon.java'
    src.write_text(
        'public class Anon {\n'
        '    Runnable makeWorker(int seed) {\n'
        '        return new Runnable() {\n'
        '            public void run() { int local = seed + 2; '
        'emit(local); }\n'
        '        };\n'
        '    }\n'
        '    void emit(int v) {}\n'
        '}\n')
    lines = extract_file(str(src))
    labels = [line.split(' ')[0] for line in lines]
    # `emit` has an empty body: no contexts, skipped (reference parity)
    assert labels == ['make|worker', 'run']
    run_ctxs = lines[1].split(' ')[1:]
    assert ('METHOD_NAME,(NameExpr1)^(MethodDeclaration)_(BlockStmt)_'
            '(ExpressionStmt)_(VariableDeclarationExpr)_'
            '(VariableDeclarator)_(BinaryExpr:plus)_(NameExpr0),seed'
            ) in run_ctxs
    # the outer method sees the anonymous creation; ObjectCreationExpr is
    # on its paths
    assert any('ObjectCreationExpr' in c for c in lines[0].split(' ')[1:])


def test_nested_generics_with_wildcards(tmp_path):
    src = tmp_path / 'Generics.java'
    src.write_text(
        'import java.util.Map;\n'
        'import java.util.List;\n'
        'public class Generics {\n'
        '    int sumSizes(Map<String, ? extends List<? super Integer>> '
        'table, List<String>[] buckets) {\n'
        '        return table.size() + buckets.length;\n'
        '    }\n'
        '    <T extends Comparable<T>> T pickLarger(T first, T second) {\n'
        '        return first.compareTo(second) > 0 ? first : second;\n'
        '    }\n'
        '}\n')
    lines = extract_file(str(src))
    assert [line.split(' ')[0] for line in lines] == \
        ['sum|sizes', 'pick|larger']
    sizes = lines[0].split(' ')[1:]
    # the doubly-nested wildcard chain, type argument to type argument
    assert ('string,(ClassOrInterfaceType0)^(ClassOrInterfaceType)_'
            '(WildcardType)_(ClassOrInterfaceType)_(WildcardType)_'
            '(PrimitiveType0),int') in sizes
    # generic-array parameter type
    assert any('ArrayType' in c for c in sizes)
    larger = lines[1].split(' ')[1:]
    # bounded type parameter's use site + ternary over the compareTo call
    assert ('t,(ClassOrInterfaceType0)^(Parameter)^(MethodDeclaration)_'
            '(BlockStmt)_(ReturnStmt)_(ConditionalExpr)_'
            '(BinaryExpr:greater)_(MethodCallExpr0)_(NameExpr1),compareto'
            ) in larger


def test_annotations_with_arguments_are_trivia(tmp_path):
    """Documented deviation (extractor/README.md): annotation uses
    contribute no leaves — the annotated member extracts exactly like its
    unannotated twin — and @interface members are not MethodDeclarations
    (reference javaparser models them as AnnotationMemberDeclaration, and
    the reference's visitor only collects MethodDeclaration)."""
    annotated = tmp_path / 'Annot.java'
    annotated.write_text(
        'public class Annot {\n'
        '    @Deprecated\n'
        '    @SuppressWarnings({"unchecked", "rawtypes"})\n'
        '    int legacyCount(@MyTag(limit = 5, name = "rows") int base) {\n'
        '        return base + 1;\n'
        '    }\n'
        '    @interface MyTag { int limit(); String name(); }\n'
        '}\n')
    plain = tmp_path / 'Plain.java'
    plain.write_text(
        'public class Plain {\n'
        '    int legacyCount(int base) {\n'
        '        return base + 1;\n'
        '    }\n'
        '}\n')
    annotated_lines = extract_file(str(annotated))
    assert annotated_lines == extract_file(str(plain))
    assert len(annotated_lines) == 1  # @interface members: no examples


def test_switch_statement_shapes(tmp_path):
    """Pre-Java-8 switch: fall-through case labels, default, break —
    SwitchStmt/SwitchEntryStmt naming per the reference's
    javaparser-3.0.0-alpha.4 (NOT the post-Java-12 SwitchEntry)."""
    src = tmp_path / 'Switches.java'
    src.write_text(
        'public class Switches {\n'
        '    int pickWeight(int kind, int fallback) {\n'
        '        switch (kind) {\n'
        '            case 0: return 10;\n'
        '            case 1:\n'
        '            case 2: return 20;\n'
        '            default: break;\n'
        '        }\n'
        '        int result = fallback;\n'
        '        switch (kind % 3) { case 1: result += 1; break; '
        'default: result -= 1; }\n'
        '        return result;\n'
        '    }\n'
        '}\n')
    ctxs = extract_file(str(src))[0].split(' ')[1:]
    assert ('int,(PrimitiveType0)^(Parameter)^(MethodDeclaration)_'
            '(BlockStmt)_(SwitchStmt)_(NameExpr0),kind') in ctxs
    # case label literal and its entry's return, under the same entry
    assert ('int,(PrimitiveType0)^(Parameter)^(MethodDeclaration)_'
            '(BlockStmt)_(SwitchStmt)_(SwitchEntryStmt)_(ReturnStmt)_'
            '(IntegerLiteralExpr0),10') in ctxs
    # the selector expression of the second switch is a BinaryExpr
    assert any('(SwitchStmt)_(BinaryExpr:remainder)' in c for c in ctxs)


def test_labeled_loops_arrays_varargs_try_instanceof(tmp_path):
    """One method exercising labeled continue/break over nested loops,
    2-D array access, varargs, try/catch/finally, cast + instanceof +
    ternary — the long-tail statement forms real Java hits constantly."""
    src = tmp_path / 'Misc.java'
    src.write_text(
        'public class Misc {\n'
        '    int drainMatrix(int[][] grid, int... extras) '
        'throws Exception {\n'
        '        int total = 0;\n'
        '        outer:\n'
        '        for (int r = 0; r < grid.length; r++) {\n'
        '            for (int c = 0; c < grid[r].length; c++) {\n'
        '                if (grid[r][c] < 0) { continue outer; }\n'
        '                if (grid[r][c] == 99) { break outer; }\n'
        '                total += grid[r][c];\n'
        '            }\n'
        '        }\n'
        '        try { total += extras[0]; } catch '
        '(ArrayIndexOutOfBoundsException e) { total = -total; } '
        'finally { total += 1; }\n'
        '        Object box = (Object) Integer.valueOf(total);\n'
        '        return box instanceof Integer ? '
        '((Integer) box).intValue() : 0;\n'
        '    }\n'
        '}\n')
    lines = extract_file(str(src))
    assert len(lines) == 1
    ctxs = lines[0].split(' ')[1:]
    joined = ' '.join(ctxs)
    for node in ('(LabeledStmt)', '(ArrayAccessExpr)', '(ArrayType)',
                 '(TryStmt)', '(CatchClause)', '(InstanceOfExpr)',
                 '(CastExpr)', '(ConditionalExpr)', '(EnclosedExpr)',
                 '(UnaryExpr:negative)', '(UnaryExpr:posIncrement)',
                 '(AssignExpr:plus)', '(FieldAccessExpr)'):
        assert node in joined, node
    # varargs parameter: its name is a leaf under the method's Parameter
    assert any(c.endswith(',extras') and '(Parameter)' in c for c in ctxs)
    assert all(len(c.split(',')) == 3 for c in ctxs)


def test_csharp_extraction(tmp_path):
    src = tmp_path / 'Calc.cs'
    src.write_text(CSHARP_SAMPLE)
    lines = extract_file(str(src))
    labels = [line.split(' ')[0] for line in lines]
    assert labels == ['add|numbers', 'is|positive']
    add_line = lines[0]
    # Roslyn-style path kinds, no parens (reference Extractor.cs:46-88)
    assert 'AddExpression' in add_line
    assert 'MethodDeclaration' in add_line
    assert 'METHOD_NAME,' in add_line or ',METHOD_NAME' in add_line
    # COMMENT contexts from file trivia in 5-subtoken batches
    assert 'adds|two|numbers,COMMENT,adds|two|numbers' in add_line
    # comment contexts appended to EVERY method (reference quirk)
    assert 'COMMENT' in lines[1]


def test_csharp_variable_grouping_and_self_pairs(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('class T { int Twice(int x) { return x + x; } }')
    lines = extract_file(str(src))
    contexts = lines[0].split(' ')[1:]
    # x appears twice -> self-pair path between the two occurrences
    xx = [c for c in contexts if c.startswith('x,') and c.endswith(',x')]
    assert xx, contexts
    assert 'AddExpression' in xx[0]


def test_csharp_num_whitelist(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('class T { int F(int a) { int b = a + 137; '
                   'int c = b + 5; return c; } }')
    line = extract_file(str(src))[0]
    # 137 not in {0,1,2,3,4,5,10} -> NUM; 5 kept (Utilities.cs:37)
    assert ',NUM' in line or 'NUM,' in line
    assert ',5' in line or '5,' in line


def test_csharp_hash_mode_consistent(tmp_path):
    from code2vec_tpu import common as c
    src = tmp_path / 'T.cs'
    src.write_text('class T { int Id(int x) { return x; } }')
    raw = extract_file(str(src), no_hash=True)[0].split(' ')[1:]
    hashed = extract_file(str(src), no_hash=False)[0].split(' ')[1:]
    for r, h in zip(raw, hashed):
        r_path = r.split(',')[1]
        h_path = h.split(',')[1]
        assert int(h_path) == c.java_string_hashcode(r_path)


def test_csharp_modern_syntax_parses(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  string Render(int? count, string name) {
    var label = name ?? "none";
    var text = $"{label}: {count}";
    if (count is int n && n > 0) { return text.ToUpper(); }
    return items.Where(i => i > 0).Select(i => i * 2).ToString();
  }
}
''')
    lines = extract_file(str(src))
    assert lines and lines[0].startswith('render ')
    assert 'CoalesceExpression' in lines[0]
    assert 'SimpleLambdaExpression' in lines[0]


def test_csharp_linq_query_syntax(tmp_path):
    """LINQ query syntax parses into Roslyn query-clause kinds
    (QueryExpression/FromClause/WhereClause/OrderByClause/SelectClause —
    the reference's Roslyn parse puts these on paths; Extractor.cs
    renders whatever Kind() says)."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  int[] Query(int[] xs) {
    var q = from x in xs where x > 0 orderby x descending select x * 2;
    return q.ToArray();
  }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['query']
    line = lines[0]
    for kind in ('QueryExpression', 'FromClause', 'WhereClause',
                 'OrderByClause', 'DescendingOrdering', 'SelectClause',
                 'QueryBody'):
        assert kind in line, kind
    # the range variable x is a leaf grouped with its uses
    assert 'x,' in line and ',x' in line


def test_csharp_await_and_async_method(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('class T { async Task<int> FetchAsync(int id) '
                   '{ var r = await client.GetAsync(id); return r.Value; } }')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['fetch|async']
    assert 'AwaitExpression' in lines[0]


def test_csharp_local_function_stays_in_outer_method(tmp_path):
    """Roslyn models `int Local(..) {..}` inside a body as a
    LocalFunctionStatement, NOT a MethodDeclaration — the reference's
    visitor extracts MethodDeclarationSyntax only, so the local
    function's leaves belong to the OUTER method's bag."""
    src = tmp_path / 'T.cs'
    src.write_text('class T { int Outer(int n) '
                   '{ int Local(int k) { return k * k; } '
                   'return Local(n) + 1; } }')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['outer']  # ONE method
    line = lines[0]
    assert 'LocalFunctionStatement' in line
    # the local function's k*k self-pair is inside outer's bag
    assert any(c.startswith('k,') and c.endswith(',k')
               for c in line.split(' ')[1:])


def test_csharp_switch_expression(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('class T { string Describe(int code) { return code '
                   'switch { 0 => "zero", 1 => "one", _ => "many" }; } }')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['describe']
    line = lines[0]
    for kind in ('SwitchExpression', 'SwitchExpressionArm',
                 'ConstantPattern'):
        assert kind in line, kind
    # constants route through the NUM whitelist: 0 and 1 are kept
    assert '0,' in line or ',0' in line


def test_csharp_tuple_types_and_literals(tmp_path):
    src = tmp_path / 'T.cs'
    src.write_text('class T { (int, string) Pair(int k) '
                   '{ return (k, k.ToString()); } }')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['pair']
    line = lines[0]
    for kind in ('TupleType', 'TupleElement', 'TupleExpression'):
        assert kind in line, kind


def test_csharp_members_without_bodies_skip_cleanly(tmp_path):
    """Indexers, events and delegate declarations are not methods: they
    must parse (or skip) without dropping the sibling method."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  public int this[int i] { get { return data[i]; } }
  public event EventHandler Changed;
  delegate int Op(int a, int b);
  int After(int x) { return x; }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['after']


def test_csharp_using_declaration_and_deconstruction(tmp_path):
    """C# 8 using declarations (`using var f = ...;` — Roslyn kind stays
    LocalDeclarationStatement) and foreach tuple deconstruction
    (`foreach (var (a, b) in ...)` — ForEachVariableStatement with
    SingleVariableDesignation leaves)."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  void UseDecl(string path) { using var f = Open(path); f.Read(); }
  int Deconstruct(List<(int, int)> pairs) {
    int s = 0;
    foreach (var (a, b) in pairs) { s += a * b; }
    return s;
  }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['use|decl', 'deconstruct']
    assert 'LocalDeclarationStatement' in lines[0]
    assert 'ForEachVariableStatement' in lines[1]
    assert 'SingleVariableDesignation' in lines[1]
    # the designation names pair with their uses: a*b gives the short
    # IdentifierName^MultiplyExpression_IdentifierName path (the
    # designation-to-use self-pair is legitimately length-8-pruned)
    assert any(c.startswith('a,') and c.endswith(',b')
               and 'MultiplyExpression' in c
               for c in lines[1].split(' ')[1:])


def test_csharp_verbatim_interp_generics_constraints(tmp_path):
    """Verbatim strings, interpolation format specifiers, nested generic
    arguments (the >> ambiguity), and generic methods with where-clauses
    all parse without dropping methods."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  string Verbatim(string p) { return @"C:%temp%" + p; }
  string Fmt(double v) { return $"val {v:F2} end"; }
  List<Dictionary<string, int>> Nested(int n) {
    return Make<Dictionary<string, int>>(n);
  }
  T Constrained<T>(T x) where T : class, new() { return x; }
  int Shifty(int x) { return x >> 2; }
}
''')
    labels = [l.split(' ')[0] for l in extract_file(str(src))]
    assert labels == ['verbatim', 'fmt', 'nested', 'constrained', 'shifty']


def test_csharp_review_hardening_corners(tmp_path):
    """Round-5 review reproductions: typed foreach deconstruction,
    await-of-unary, qualified query range-variable types, and `into`
    continuations nesting under QueryContinuation's own QueryBody
    (Roslyn's shape) — each previously dropped the method or diverged
    from the reference parse."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  int TypedDecon(List<(int, int)> xs) {
    foreach ((int a, int b) in xs) { return a + b; } return 0;
  }
  async Task<bool> AwaitNot(Task<bool> t) { return !(await t); }
  int QualifiedQuery(int[] xs) {
    var q = from System.Int32 x in xs select x; return q.Count();
  }
  string GroupInto(int[] xs) {
    var q = from x in xs group x by x into g select g.Key;
    return q.ToString();
  }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == [
        'typed|decon', 'await|not', 'qualified|query', 'group|into']
    assert 'ForEachVariableStatement' in lines[0]
    assert 'DeclarationExpression' in lines[0]
    assert 'AwaitExpression' in lines[1]
    assert 'QueryExpression' in lines[2]
    # post-`into` select nests under the continuation's own QueryBody
    assert 'QueryBody^QueryContinuation' in lines[3] \
        or 'QueryContinuation_QueryBody' in lines[3]


def test_csharp_tuple_switch_and_precedence(tmp_path):
    """Second review round: tuple-governed switch with positional
    patterns (`(x, y) switch { (0, 0) => ... }` — Roslyn
    RecursivePattern/PositionalPatternClause; previously the `(0, 0) =>`
    arm matched the lambda lookahead and the cast path committed on the
    TupleType), and switch binding tighter than binary
    (`a + b switch {...}` is `a + (b switch)` — the SwitchExpression
    must sit UNDER the AddExpression, not above it)."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  string Origin(int x, int y) {
    return (x, y) switch { (0, 0) => "origin", _ => "other" };
  }
  int Bind(int a, int b) { return a + b switch { 0 => 1, _ => 2 }; }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['origin', 'bind']
    assert 'RecursivePattern' in lines[0]
    assert 'PositionalPatternClause' in lines[0]
    assert 'SwitchExpression_AddExpression' not in lines[1]
    assert 'AddExpression' in lines[1] and 'SwitchExpression' in lines[1]


def test_csharp_positional_discard_pattern_has_no_leaf(tmp_path):
    """ADVICE r5 csharp.h:885: `_` inside a positional pattern —
    `(_, 0) => ...` — is a DiscardPattern (Roslyn emits NO identifier
    leaf for it; being leafless it also contributes no path contexts).
    Before the `,`/`)` lookahead fix it fell through to ConstantPattern
    and a spurious `_` identifier leaf appeared in the bag."""
    src = tmp_path / 'T.cs'
    src.write_text('''
class T {
  string Axis(int x, int y) {
    return (x, y) switch { (_, 0) => "xaxis", (0, _) => "yaxis",
                           _ => "other" };
  }
}
''')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['axis']
    contexts = lines[0].split(' ')[1:]
    leaves = {piece for ctx in contexts
              for piece in (ctx.split(',')[0], ctx.split(',')[-1])}
    assert '_' not in leaves
    # the positional pattern itself still parses as Roslyn's shape, and
    # the sibling constant subpatterns keep their Subpattern ancestry
    assert 'RecursivePattern' in lines[0]
    assert 'Subpattern^PositionalPatternClause' in lines[0]


def test_csharp_await_of_signed_expression(tmp_path):
    """ADVICE r5 csharp.h:1203: `await -Fetch(id)` / `await +Fetch(id)`
    are AwaitExpression(UnaryMinus/Plus(...)) — before the starts_unary
    fix the prefix sign demoted `await` to an identifier leaf inside a
    Subtract/AddExpression."""
    src = tmp_path / 'T.cs'
    src.write_text('class T {\n'
                   '  async Task<int> Neg(int id) '
                   '{ return await -Fetch(id); }\n'
                   '  async Task<int> Pos(int id) '
                   '{ return await +Fetch(id); }\n'
                   '}\n')
    lines = extract_file(str(src))
    assert [l.split(' ')[0] for l in lines] == ['neg', 'pos']
    assert 'AwaitExpression_UnaryMinusExpression' in lines[0]
    assert 'AwaitExpression_UnaryPlusExpression' in lines[1]
    for line in lines:
        leaves = {piece for ctx in line.split(' ')[1:]
                  for piece in (ctx.split(',')[0], ctx.split(',')[-1])}
        assert 'await' not in leaves
        assert 'SubtractExpression' not in line
        assert 'AddExpression' not in line


def test_csharp_corpus_generator_roundtrip(tmp_path):
    """scripts/gen_csharp_corpus.py emits parseable C# at smoke scale:
    every generated file extracts with zero stderr errors, labels carry
    the generator's verb vocabulary, and the C#-native members put the
    new parser kinds (SwitchExpression / TupleType) into the corpus's
    path space — the at-scale analog run by the cpu_csharp accuracy
    profile (benchmarks/accuracy_at_scale.py)."""
    import subprocess
    import sys as _sys
    out = tmp_path / 'corpus'
    subprocess.run([_sys.executable,
                    os.path.join(REPO, 'scripts', 'gen_csharp_corpus.py'),
                    '-o', str(out), '--classes', '40', '--seed', '3'],
                   check=True, capture_output=True)
    proc = run_extractor('--dir', str(out / 'train'), '--num_threads', '4',
                         '--no_hash')
    assert proc.returncode == 0
    assert not proc.stderr.strip(), proc.stderr[:500]
    lines = proc.stdout.splitlines()
    assert len(lines) > 50
    joined = '\n'.join(lines)
    assert 'SwitchExpression' in joined
    assert 'TupleType' in joined
    labels = {line.split(' ')[0] for line in lines}
    assert any(l.startswith('get|') for l in labels)
    assert any(l.startswith('describe|') for l in labels)


def test_interactive_repl_with_real_extractor(tmp_path, monkeypatch, capsys):
    """End-to-end: real binary feeds the REPL (reference flow:
    interactive_predict.py + extractor.py + JAR)."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.serving.extractor_bridge import Extractor
    from code2vec_tpu.serving.predict import InteractivePredictor
    from tests.test_train_overfit import make_dataset

    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False)
    model = Code2VecModel(config)

    input_file = tmp_path / 'Input.java'
    input_file.write_text('class X { int getSquare(int x) '
                          '{ return x * x; } }')
    extractor = Extractor(config, extractor_command=[BINARY])
    predictor = InteractivePredictor(config, model, extractor=extractor,
                                     input_filename=str(input_file))
    answers = iter(['', 'q'])
    monkeypatch.setattr('builtins.input', lambda: next(answers))
    predictor.predict()
    out = capsys.readouterr().out
    assert 'Original name:\tget|square' in out
    assert 'Attention:' in out
    # attention paths are displayed un-hashed
    assert '(BinaryExpr:times)' in out


def test_interactive_repl_serves_csharp_input(tmp_path, monkeypatch,
                                              capsys):
    """The REPL serves the C# frontend through the same bridge: the
    extractor dispatches on the .cs extension and the attention display
    shows un-hashed Roslyn-kind paths. The model here is UNTRAINED over
    a synthetic vocab — this covers the REPL-to-C#-extractor bridge and
    display contract, not C# prediction quality (that is the cpu_csharp
    accuracy profile's job)."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.serving.extractor_bridge import Extractor
    from code2vec_tpu.serving.predict import InteractivePredictor
    from tests.test_train_overfit import make_dataset

    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False)
    model = Code2VecModel(config)

    input_file = tmp_path / 'Input.cs'
    input_file.write_text('class X { int GetSquare(int x) '
                          '{ return x * x; } }')
    extractor = Extractor(config, extractor_command=[BINARY])
    predictor = InteractivePredictor(config, model, extractor=extractor,
                                     input_filename=str(input_file))
    answers = iter(['', 'q'])
    monkeypatch.setattr('builtins.input', lambda: next(answers))
    predictor.predict()
    out = capsys.readouterr().out
    assert 'Original name:\tget|square' in out
    assert 'Attention:' in out
    # C# paths display un-hashed with Roslyn kind names
    assert 'MultiplyExpression' in out


def test_constructor_only_class_emits_nothing_without_error(tmp_path):
    """Reference parity (FeatureExtractor.java:51-75 + FunctionVisitor):
    constructors are not MethodDeclarations, so a valid class whose only
    function members are constructors yields ZERO rows and NO parse error
    — it must not poison --dir batches with 'could not parse'."""
    src = tmp_path / 'Node.java'
    src.write_text('public class Node {\n'
                   '    public String name;\n'
                   '    public Node(String name) {\n'
                   '        try { this.name = name.trim(); }\n'
                   '        catch (Exception e) { e.printStackTrace(); }\n'
                   '    }\n'
                   '}\n')
    proc = run_extractor('--file', str(src))
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.strip() == ''
    assert 'could not parse' not in proc.stderr


def test_reference_java_sources_extract_cleanly():
    """Real-world Java stress: the reference's own JavaExtractor sources
    (generics, annotations with arguments, lambdas, nested classes,
    try/catch, varargs) must extract without a single parse failure."""
    ref = '/root/reference/JavaExtractor'
    if not os.path.isdir(ref):
        pytest.skip('reference sources unavailable')
    proc = run_extractor('--dir', ref, '--num_threads', '4')
    assert proc.returncode == 0, proc.stderr
    rows = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(rows) >= 40          # the repo holds ~45 real methods
    assert 'could not parse' not in proc.stderr
    labels = {row.split(' ', 1)[0] for row in rows}
    # spot-check real method names survived subtokenization
    assert 'to|string' in labels and 'get|path' in labels


def test_reference_csharp_sources_extract_cleanly():
    """Real-world C# stress: the reference's CSharpExtractor sources
    (LINQ, properties, generics, Roslyn API calls) must extract without
    a parse failure."""
    ref = '/root/reference/CSharpExtractor'
    if not os.path.isdir(ref):
        pytest.skip('reference sources unavailable')
    proc = run_extractor('--lang', 'csharp', '--dir', ref,
                         '--num_threads', '4')
    assert proc.returncode == 0, proc.stderr
    rows = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(rows) >= 20          # the repo holds ~25 real methods
    labels = {row.split(' ', 1)[0] for row in rows}
    assert 'find|path' in labels and 'extract|single|file' in labels


def test_parser_survives_seeded_mutation_fuzz(tmp_path):
    """Bounded fuzz over the recovery paths: random byte-level mutations
    of valid generated Java must always terminate with rc 0 (clean rows
    or silence) or rc 1 ('could not parse') — never crash, hang, or
    sanitizer-abort. Runs the ASan binary when present."""
    import random
    rng = random.Random(0xC2C)
    base = ('public class Fz {\n'
            '  private int count; private String name;\n'
            '  public int getCount() { return this.count; }\n'
            '  public void setName(String v) { this.name = v; }\n'
            '  public int pick(int a, int b) { return a > b ? a : b; }\n'
            '  public Fz(int c) { try { this.count = c; }'
            ' catch (Exception e) { } }\n'
            '}\n')
    asan = BINARY + '-asan'
    binary = asan if os.path.isfile(asan) else BINARY
    chars = '{}()<>;,."@|&*+-=/\\\x00\xe4'
    for trial in range(120):
        text = list(base)
        for _ in range(rng.randint(1, 8)):
            op = rng.random()
            pos = rng.randrange(len(text))
            if op < 0.4:
                text[pos] = rng.choice(chars)
            elif op < 0.7:
                del text[pos]
            else:
                text.insert(pos, rng.choice(chars))
        src = tmp_path / ('F%03d.java' % trial)
        src.write_text(''.join(text), errors='replace')
        proc = subprocess.run(
            [binary, '--max_path_length', '8', '--max_path_width', '2',
             '--file', str(src)],
            capture_output=True, text=True, timeout=30,
            env=dict(os.environ,
                     ASAN_OPTIONS='halt_on_error=1:detect_leaks=1'))
        assert proc.returncode in (0, 1), (
            'trial %d: rc=%d\nstderr: %s\nsource: %r'
            % (trial, proc.returncode, proc.stderr[-500:], ''.join(text)))


def test_csharp_parser_survives_seeded_mutation_fuzz(tmp_path):
    """Same bounded fuzz as the Java parser, over the C# frontend's
    recovery paths (csharp.h is a separate hand-written parser)."""
    import random
    rng = random.Random(0xC5)
    base = ('public class Fz {\n'
            '  private int count; private string name;\n'
            '  public int GetCount() { return this.count; }\n'
            '  public void SetName(string v) { this.name = v; }\n'
            '  public int Pick(int a, int b) => a > b ? a : b;\n'
            '  public bool Check(string s) { foreach (var c in s) '
            '{ if (c == \'x\') { return true; } } return false; }\n'
            # round-5 grammar: mutations must stress the NEW recovery
            # paths too (queries, switch expressions + positional
            # patterns, tuples, await, local functions, deconstruction)
            '  public int Sum(int[] xs) { var q = from x in xs '
            'where x > 0 select x * 2; return q.Count(); }\n'
            '  public string Band(int x, int y) { return (x, y) switch '
            '{ (0, 0) => "o", _ => "m" }; }\n'
            '  public async Task<int> Go(int id) '
            '{ return await Fetch(id); }\n'
            '  public int Outer(int n) { int Local(int k) '
            '{ return k * k; } return Local(n); }\n'
            '  public (int, string) Pair(int k) '
            '{ return (k, k.ToString()); }\n'
            '  public int Decon(List<(int, int)> ps) { foreach '
            '(var (a, b) in ps) { return a + b; } return 0; }\n'
            '}\n')
    asan = BINARY + '-asan'
    binary = asan if os.path.isfile(asan) else BINARY
    chars = '{}()<>;,."@|&*+-=/\\\x00\xe4'
    for trial in range(120):
        text = list(base)
        for _ in range(rng.randint(1, 8)):
            op = rng.random()
            pos = rng.randrange(len(text))
            if op < 0.4:
                text[pos] = rng.choice(chars)
            elif op < 0.7:
                del text[pos]
            else:
                text.insert(pos, rng.choice(chars))
        src = tmp_path / ('F%03d.cs' % trial)
        src.write_text(''.join(text), errors='replace')
        proc = subprocess.run(
            [binary, '--lang', 'csharp', '--max_path_length', '8',
             '--max_path_width', '2', '--file', str(src)],
            capture_output=True, text=True, timeout=30,
            env=dict(os.environ,
                     ASAN_OPTIONS='halt_on_error=1:detect_leaks=1'))
        assert proc.returncode in (0, 1), (
            'trial %d: rc=%d\nstderr: %s\nsource: %r'
            % (trial, proc.returncode, proc.stderr[-500:], ''.join(text)))
