"""Property tests for the top-k merge primitives (ISSUE 5 satellite):
``padded_local_topk`` / ``merge_topk_host`` against an ``np.argsort``
reference — k > n_shard sentinel handling and deterministic
tie-breaking by lowest index — plus the axis-general ``sharded_top_k``
(the index's data-axis layout) and the ``grouped_top_k`` k > v cap."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops.topk import (grouped_top_k, merge_topk_host,
                                   padded_local_topk, sharded_top_k)
from code2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def reference_topk(x: np.ndarray, k: int):
    """Ground truth: value desc, ties by LOWEST index (stable argsort
    of -x), exactly lax.top_k's documented semantics."""
    idx = np.argsort(-x, axis=-1, kind='stable')[..., :k]
    return np.take_along_axis(x, idx, axis=-1), idx


def shard_merge(x: np.ndarray, k: int, bounds):
    """Per-shard padded_local_topk + host merge over arbitrary (possibly
    k-smaller) column shards of x."""
    values, indices = [], []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        v, i = padded_local_topk(jnp.asarray(x[..., lo:hi]), k)
        v, i = np.asarray(v), np.asarray(i)
        indices.append(np.where(i >= 0, i + lo, i))
        values.append(v)
    return merge_topk_host(np.concatenate(values, axis=-1),
                           np.concatenate(indices, axis=-1), k)


@pytest.mark.parametrize('k', [1, 3, 7, 16])
def test_shard_merge_matches_argsort_reference(k):
    rng = np.random.default_rng(0)
    for trial in range(20):
        n = int(rng.integers(k, 60))
        # integer-valued floats: ties are EXACT, so tie-breaking order
        # is actually exercised (continuous draws almost never tie)
        x = rng.integers(0, 6, (4, n)).astype(np.float32)
        n_shards = int(rng.integers(1, 6))
        cuts = np.sort(rng.integers(0, n + 1, n_shards - 1))
        bounds = np.concatenate([[0], cuts, [n]])
        got_v, got_i = shard_merge(x, k, bounds)
        want_v, want_i = reference_topk(x, k)
        assert np.array_equal(got_v, want_v), (trial, bounds)
        assert np.array_equal(got_i, want_i), (trial, bounds)


def test_padded_local_topk_pads_with_sentinels():
    values, indices = padded_local_topk(jnp.asarray([3.0, 1.0, 2.0]), 5)
    assert np.array_equal(np.asarray(values)[:3], [3.0, 2.0, 1.0])
    assert np.all(np.isneginf(np.asarray(values)[3:]))
    assert np.array_equal(np.asarray(indices), [0, 2, 1, -1, -1])


def test_merge_surfaces_sentinels_only_when_candidates_run_out():
    # 2 real candidates, k=4: the tail must be the sentinel pair, and
    # the real ones must lead in value order
    values = np.asarray([[1.0, -np.inf, 2.0, -np.inf]])
    indices = np.asarray([[5, -1, 9, -1]])
    got_v, got_i = merge_topk_host(values, indices, 4)
    assert np.array_equal(got_i, [[9, 5, -1, -1]])
    assert np.array_equal(got_v[0, :2], [2.0, 1.0])
    assert np.all(np.isneginf(got_v[0, 2:]))


def test_merge_breaks_value_ties_by_lowest_index():
    values = np.asarray([[7.0, 7.0, 7.0, 5.0]])
    indices = np.asarray([[40, 3, 17, 1]])
    _v, got_i = merge_topk_host(values, indices, 3)
    assert np.array_equal(got_i, [[3, 17, 40]])


def _mesh(data, model):
    devices = np.asarray(jax.devices()[:data * model]).reshape(data, model)
    return jax.sharding.Mesh(devices, (DATA_AXIS, MODEL_AXIS))


def test_sharded_top_k_breaks_ties_by_index_across_shards():
    """The cross-shard merge must match single-device lax.top_k on a
    tie-heavy input — including ties that straddle shard boundaries."""
    mesh = _mesh(2, 4)
    rng = np.random.default_rng(1)
    x = rng.integers(0, 3, (8, 64)).astype(np.float32)
    placed = jax.device_put(
        jnp.asarray(x), jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(DATA_AXIS, MODEL_AXIS)))
    got_v, got_i = jax.jit(lambda a: sharded_top_k(a, 10, mesh))(placed)
    want_v, want_i = jax.lax.top_k(jnp.asarray(x), 10)
    assert np.array_equal(np.asarray(got_v), np.asarray(want_v))
    assert np.array_equal(np.asarray(got_i), np.asarray(want_i))


def test_sharded_top_k_over_data_axis():
    """The index layout: batch replicated, columns sharded over DATA —
    must agree with lax.top_k including integer ties."""
    mesh = _mesh(8, 1)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 4, (5, 96)).astype(np.float32)
    placed = jax.device_put(
        jnp.asarray(x), jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(None, DATA_AXIS)))
    got_v, got_i = jax.jit(
        lambda a: sharded_top_k(a, 7, mesh, shard_axis=DATA_AXIS,
                                batch_axis=None))(placed)
    want_v, want_i = jax.lax.top_k(jnp.asarray(x), 7)
    assert np.array_equal(np.asarray(got_v), np.asarray(want_v))
    assert np.array_equal(np.asarray(got_i), np.asarray(want_i))


def test_grouped_top_k_caps_k_at_vocab():
    x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 50)),
                    jnp.float32)
    values, indices = grouped_top_k(x, 200)
    assert values.shape == (2, 50)
    ref_v, ref_i = jax.lax.top_k(x, 50)
    assert np.array_equal(np.asarray(values), np.asarray(ref_v))
    assert np.array_equal(np.asarray(indices), np.asarray(ref_i))
