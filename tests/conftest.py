"""Test harness: force an 8-virtual-device CPU platform.

Multi-chip logic is tested without TPU hardware via XLA's virtual host
devices (SURVEY.md §4) — the TPU answer to "multi-node tests without a
cluster".

Note: this environment pre-imports jax at interpreter startup
(sitecustomize), so setting JAX_PLATFORMS in os.environ here is too late;
``jax.config.update`` still works because backends initialize lazily.
XLA_FLAGS must be set before the first backend init, which also still holds.
"""
import os

_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
# Newer jax (the toolchain this repo was grown on) defaults the
# partitionable threefry; 0.4.x defaults it off. The partitionable
# generator is counter-based PER ELEMENT, so a (N, d) draw's first rows
# equal a smaller (n, d) draw's — the property the cross-allocation
# parity tests (fused-CE padded table vs plain; mesh vs single-device)
# rely on to get identical initial params from differently-padded shapes.
jax.config.update('jax_threefry_partitionable', True)


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' (ROADMAP.md); register the marker
    # so the opt-in heavy tests (e.g. the 50k-vector IVF recall
    # acceptance) don't warn as typos
    config.addinivalue_line(
        'markers', 'slow: heavy acceptance tests, excluded from tier-1')


# Tier-1 runtime-budget guard (ISSUE 17): the suite runs under a hard
# wall-clock cap (ROADMAP.md), and single tests creeping past ~20s are
# how the cap gets eaten one PR at a time.  Flag them loudly at the end
# of the run so the offender is moved behind @pytest.mark.slow (or
# shrunk) BEFORE the cap is at risk — a warning, not a failure, because
# CI machines vary.
TIER1_SINGLE_TEST_BUDGET_S = 20.0
_over_budget = []


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    outcome = yield
    report = outcome.get_result()
    if report.when != 'call':
        return
    if item.get_closest_marker('slow') is not None:
        return  # opted out of tier-1: its duration is its own business
    if report.duration > TIER1_SINGLE_TEST_BUDGET_S:
        _over_budget.append((item.nodeid, report.duration))


def pytest_terminal_summary(terminalreporter):
    if not _over_budget:
        return
    terminalreporter.section('tier-1 runtime budget')
    terminalreporter.write_line(
        'WARNING: %d test(s) exceeded the ~%.0fs single-test tier-1 '
        'budget — mark them @pytest.mark.slow or shrink them '
        '(tests/conftest.py):' % (len(_over_budget),
                                  TIER1_SINGLE_TEST_BUDGET_S))
    for nodeid, duration in sorted(_over_budget, key=lambda x: -x[1]):
        terminalreporter.write_line('  %7.1fs  %s' % (duration, nodeid))
