"""Test harness: force an 8-virtual-device CPU platform BEFORE jax imports.

Multi-chip logic is tested without TPU hardware via XLA's virtual host
devices (SURVEY.md §4) — the TPU answer to "multi-node tests without a
cluster".
"""
import os

os.environ['JAX_PLATFORMS'] = 'cpu'
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()
