"""Test harness: force an 8-virtual-device CPU platform.

Multi-chip logic is tested without TPU hardware via XLA's virtual host
devices (SURVEY.md §4) — the TPU answer to "multi-node tests without a
cluster".

Note: this environment pre-imports jax at interpreter startup
(sitecustomize), so setting JAX_PLATFORMS in os.environ here is too late;
``jax.config.update`` still works because backends initialize lazily.
XLA_FLAGS must be set before the first backend init, which also still holds.
"""
import os

_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
