"""Test harness: force an 8-virtual-device CPU platform.

Multi-chip logic is tested without TPU hardware via XLA's virtual host
devices (SURVEY.md §4) — the TPU answer to "multi-node tests without a
cluster".

Note: this environment pre-imports jax at interpreter startup
(sitecustomize), so setting JAX_PLATFORMS in os.environ here is too late;
``jax.config.update`` still works because backends initialize lazily.
XLA_FLAGS must be set before the first backend init, which also still holds.
"""
import os

_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
# Newer jax (the toolchain this repo was grown on) defaults the
# partitionable threefry; 0.4.x defaults it off. The partitionable
# generator is counter-based PER ELEMENT, so a (N, d) draw's first rows
# equal a smaller (n, d) draw's — the property the cross-allocation
# parity tests (fused-CE padded table vs plain; mesh vs single-device)
# rely on to get identical initial params from differently-padded shapes.
jax.config.update('jax_threefry_partitionable', True)


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' (ROADMAP.md); register the marker
    # so the opt-in heavy tests (e.g. the 50k-vector IVF recall
    # acceptance) don't warn as typos
    config.addinivalue_line(
        'markers', 'slow: heavy acceptance tests, excluded from tier-1')
