"""Extractor-bridge hardening (serving/extractor_bridge.py, ISSUE 7):
per-call timeout with stderr surfaced, typed crash-vs-content errors,
pool retry-with-backoff, and the circuit-breaker drill (injected crashes
trip open -> fail fast -> half-open recovery). All drills run against
tiny fake extractor scripts — no JVM, no native build needed."""
import stat
import sys
import time

import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.errors import (ExtractorCrash, ExtractorError,
                                         ExtractorUnavailable)
from code2vec_tpu.serving.extractor_bridge import Extractor, ExtractorPool


@pytest.fixture(autouse=True)
def clear_fault_plan():
    faults.configure('')
    yield
    faults.configure('')


def _script(tmp_path, name, body):
    """An executable fake-extractor python script; returns its command."""
    path = tmp_path / name
    path.write_text('#!/usr/bin/env python3\n' + body)
    path.chmod(path.stat().st_mode | stat.S_IXUSR)
    return [sys.executable, str(path)]


OK_BODY = "print('get|name a,somePath,b c,otherPath,d')\n"


def _config(**overrides):
    defaults = dict(MAX_CONTEXTS=6, EXTRACTOR_BACKOFF_SECS=0.0)
    defaults.update(overrides)
    return Config(**defaults)


# ------------------------------------------------------------------ timeout
def test_wedged_extractor_times_out_typed(tmp_path):
    """Satellite bugfix: a wedged extractor must fail the CALL (typed,
    bounded), never hang the caller forever."""
    command = _script(tmp_path, 'wedge.py',
                      "import sys, time\n"
                      "sys.stderr.write('jvm stuck in GC')\n"
                      "sys.stderr.flush()\n"
                      "time.sleep(60)\n")
    extractor = Extractor(_config(EXTRACTOR_TIMEOUT_SECS=0.3),
                          extractor_command=command)
    t0 = time.perf_counter()
    with pytest.raises(ExtractorCrash, match='timed out'):
        extractor.extract_paths(str(tmp_path / 'T.java'))
    assert time.perf_counter() - t0 < 10.0  # bounded, not 60s


def test_crash_surfaces_stderr(tmp_path):
    command = _script(tmp_path, 'crash.py',
                      "import sys\n"
                      "sys.stderr.write('boom: parse table corrupt')\n"
                      "sys.exit(3)\n")
    extractor = Extractor(_config(), extractor_command=command)
    with pytest.raises(ExtractorCrash, match='parse table corrupt'):
        extractor.extract_paths(str(tmp_path / 'T.java'))


def test_no_paths_is_content_error_not_crash(tmp_path):
    command = _script(tmp_path, 'empty.py', "pass\n")
    extractor = Extractor(_config(), extractor_command=command)
    with pytest.raises(ValueError) as excinfo:
        extractor.extract_paths(str(tmp_path / 'T.java'))
    assert not isinstance(excinfo.value, ExtractorCrash)


def test_extract_paths_output_contract(tmp_path):
    command = _script(tmp_path, 'ok.py', OK_BODY)
    extractor = Extractor(_config(), extractor_command=command)
    lines, path_unhash = extractor.extract_paths(str(tmp_path / 'T.java'))
    assert len(lines) == 1 and lines[0].startswith('get|name ')
    assert set(path_unhash.values()) == {'somePath', 'otherPath'}


# ---------------------------------------------------------------- pool/retry
def test_pool_retries_transient_crashes_with_backoff(tmp_path):
    """First two invocations crash, the third succeeds: retries absorb
    the blips, the call succeeds, the breaker never trips."""
    marker = tmp_path / 'attempts'
    command = _script(
        tmp_path, 'flaky.py',
        "import os, sys\n"
        "path = %r\n"
        "n = int(open(path).read()) if os.path.exists(path) else 0\n"
        "open(path, 'w').write(str(n + 1))\n"
        "if n < 2:\n"
        "    sys.stderr.write('transient')\n"
        "    sys.exit(1)\n"
        "%s" % (str(marker), OK_BODY))
    with ExtractorPool(_config(EXTRACTOR_RETRIES=2),
                       extractor_command=command) as pool:
        lines, _ = pool.extract_paths(str(tmp_path / 'T.java'),
                                      timeout=30)
    assert len(lines) == 1
    assert marker.read_text() == '3'
    assert pool.retries_total.snapshot() == 2
    assert pool.state() == 'closed'


def test_pool_exhausted_retries_raise_last_crash(tmp_path):
    command = _script(tmp_path, 'crash.py',
                      "import sys\n"
                      "sys.stderr.write('always down')\n"
                      "sys.exit(1)\n")
    with ExtractorPool(_config(EXTRACTOR_RETRIES=1,
                               EXTRACTOR_BREAKER_THRESHOLD=99),
                       extractor_command=command) as pool:
        with pytest.raises(ExtractorCrash, match='always down'):
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.retries_total.snapshot() == 1


def test_content_error_rides_pool_unretried(tmp_path):
    command = _script(tmp_path, 'empty.py', "pass\n")
    with ExtractorPool(_config(EXTRACTOR_RETRIES=3),
                       extractor_command=command) as pool:
        with pytest.raises(ValueError) as excinfo:
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert not isinstance(excinfo.value, ExtractorError)
        assert pool.retries_total.snapshot() == 0  # never retried
        assert pool.state() == 'closed'            # never counted


# ------------------------------------------------------------ breaker drill
def test_breaker_drill_open_fail_fast_half_open_recovery(tmp_path):
    """The ISSUE 7 acceptance drill: injected extractor crashes trip the
    breaker open -> calls fail fast (no subprocess) -> after the
    cooldown a half-open probe succeeds and closes it again."""
    command = _script(tmp_path, 'ok.py', OK_BODY)
    config = _config(EXTRACTOR_RETRIES=0, EXTRACTOR_BREAKER_THRESHOLD=2,
                     EXTRACTOR_BREAKER_COOLDOWN_SECS=0.3)
    with ExtractorPool(config, extractor_command=command) as pool:
        # calls 0 and 1 crash (injected): threshold 2 trips the breaker
        faults.configure('extractor_crash@call=0..1')
        for _ in range(2):
            with pytest.raises(ExtractorCrash, match='FAULT_INJECT'):
                pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'open'
        assert pool.breaker_open_total.snapshot() == 1
        # open: fail fast, typed, and FAST (no spawn, no timeout wait)
        t0 = time.perf_counter()
        with pytest.raises(ExtractorUnavailable):
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert time.perf_counter() - t0 < 0.1
        # cooldown elapses; the half-open probe (fault window passed)
        # succeeds and closes the breaker
        time.sleep(0.35)
        lines, _ = pool.extract_paths(str(tmp_path / 'T.java'),
                                      timeout=30)
        assert len(lines) == 1
        assert pool.state() == 'closed'
        # healthy again: subsequent calls flow normally
        pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)


def test_breaker_half_open_failure_reopens(tmp_path):
    command = _script(tmp_path, 'ok.py', OK_BODY)
    config = _config(EXTRACTOR_RETRIES=0, EXTRACTOR_BREAKER_THRESHOLD=1,
                     EXTRACTOR_BREAKER_COOLDOWN_SECS=0.2)
    with ExtractorPool(config, extractor_command=command) as pool:
        # crash call 0 (trips open) AND call 1 (the half-open probe)
        faults.configure('extractor_crash@call=0..1')
        with pytest.raises(ExtractorCrash):
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'open'
        time.sleep(0.25)
        with pytest.raises(ExtractorCrash):  # probe runs, crashes
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'open'        # re-opened
        assert pool.breaker_open_total.snapshot() == 2
        time.sleep(0.25)                     # second probe succeeds
        pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'closed'


def test_unexpected_probe_exception_releases_slot(tmp_path):
    """An exception OUTSIDE the crash/content taxonomy during the
    half-open probe must release the probe slot (not wedge the breaker
    half-open forever) without judging the extractor."""
    command = _script(tmp_path, 'ok.py', OK_BODY)
    config = _config(EXTRACTOR_RETRIES=0, EXTRACTOR_BREAKER_THRESHOLD=1,
                     EXTRACTOR_BREAKER_COOLDOWN_SECS=0.2)
    with ExtractorPool(config, extractor_command=command) as pool:
        faults.configure('extractor_crash@call=0')
        with pytest.raises(ExtractorCrash):
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'open'
        faults.configure('')
        time.sleep(0.25)
        real = pool.extractor.extract_paths
        pool.extractor.extract_paths = lambda path: (_ for _ in ()).throw(
            RuntimeError('weird'))
        with pytest.raises(RuntimeError, match='weird'):
            pool._call(str(tmp_path / 'T.java'))  # the half-open probe
        pool.extractor.extract_paths = real
        # the slot was released: the NEXT call claims the probe and
        # closes the breaker — no permanent half-open wedge
        pool.extract_paths(str(tmp_path / 'T.java'), timeout=30)
        assert pool.state() == 'closed'


def test_timeout_zero_disables_bound(tmp_path):
    command = _script(tmp_path, 'ok.py', OK_BODY)
    extractor = Extractor(_config(EXTRACTOR_TIMEOUT_SECS=0.0),
                          extractor_command=command)
    lines, _ = extractor.extract_paths(str(tmp_path / 'T.java'))
    assert len(lines) == 1


def test_spawn_failure_is_crash(tmp_path):
    extractor = Extractor(
        _config(), extractor_command=[str(tmp_path / 'does-not-exist')])
    with pytest.raises(ExtractorCrash, match='failed to run'):
        extractor.extract_paths(str(tmp_path / 'T.java'))
