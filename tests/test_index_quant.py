"""Quantized IVF tier tests (ISSUE 19): int8/PQ codecs, the warm
LUT-gather program (zero post-warmup compiles), exact re-rank parity,
the HBM budget gate on attach AND append, incremental inserts
(queryable without rebuild, versioned segment sidecars, reopen), and
the append-then-compact bit-for-rank property suite — empty segments,
duplicate vectors, and inserts that land mid-compaction included."""
import gc
import os
import threading

import numpy as np
import pytest

from code2vec_tpu.index import store as store_lib
from code2vec_tpu.index.exact import ExactIndex
from code2vec_tpu.index.ivf import measure_recall
from code2vec_tpu.index.quant import (QuantizedIVFIndex, encode_int8,
                                      resolve_pq_m, train_int8)
from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry import memory
from code2vec_tpu.telemetry.memory import MemoryBudgetExceeded

from test_index import clustered_corpus, reference_search


@pytest.fixture(autouse=True)
def fresh_state():
    memory.reset()
    core.reset()
    core.disable()
    yield
    memory.reset()
    core.reset()
    core.disable()


def small_store(tmp_path, n=800, dim=16, centers=12, seed=0,
                metric='cosine', labels=True, name='q.vecindex'):
    vecs = clustered_corpus(n, dim, centers=centers, seed=seed)
    return store_lib.build(
        str(tmp_path / name), [vecs], metric=metric,
        labels=(['m%d' % i for i in range(n)] if labels else None)), vecs


# ------------------------------------------------------------- codecs
def test_resolve_pq_m_divides_dim():
    assert resolve_pq_m(64) == 16
    assert resolve_pq_m(64, 32) == 32
    assert resolve_pq_m(30, 8) == 6     # clamped down to a divisor
    assert resolve_pq_m(7) == 1


def test_int8_codec_round_trip_error_bounded():
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(512, 24)).astype(np.float32)
    scale = train_int8(vecs)
    codes = encode_int8(vecs, scale)
    assert codes.dtype == np.int8
    recon = codes.astype(np.float32) * scale[None, :]
    # symmetric per-dim quantization: error under half a step
    assert np.abs(recon - vecs).max() <= (scale.max() / 2) + 1e-6


# --------------------------------------------- search parity + recall
@pytest.mark.parametrize('kind', ['int8', 'pq'])
def test_full_probe_full_rerank_matches_reference(tmp_path, kind):
    """With every list probed and re-rank covering the candidate set,
    the quantized tier is bit-for-rank the reference: quantization only
    ORDERS the candidate funnel, the exact re-rank decides."""
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind=kind, seed=0,
                                    rerank=10 ** 6)
    queries = vecs[::97][:12]
    values, ids = index.search(queries, 10, nprobe=index.n_clusters)
    ref_values, ref_ids = reference_search(vecs, queries, 10)
    np.testing.assert_array_equal(ids, ref_ids)
    np.testing.assert_allclose(values, ref_values, rtol=1e-5,
                               atol=1e-6)


@pytest.mark.parametrize('kind', ['int8', 'pq'])
def test_rerank_recovers_recall_over_quantized_order(tmp_path, kind):
    store, vecs = small_store(tmp_path, n=2000, dim=32, centers=24)
    exact = ExactIndex(store)
    rng = np.random.default_rng(3)
    queries = (vecs[rng.choice(2000, 32)]
               + 0.01 * rng.normal(size=(32, 32))).astype(np.float32)
    index = QuantizedIVFIndex.build(store, kind=kind, seed=0, rerank=0)
    bare = measure_recall(index, exact, queries, k=10)
    index.rerank = 128
    reranked = measure_recall(index, exact, queries, k=10)
    assert reranked >= bare
    assert reranked >= 0.9, (bare, reranked)


def test_pq_device_bytes_per_vector_quarter_of_f16(tmp_path):
    store, _vecs = small_store(tmp_path, dim=16)
    index = QuantizedIVFIndex.build(store, kind='pq', seed=0)
    assert index.bytes_per_vector * 4 <= 2 * store.dim
    int8_index = QuantizedIVFIndex.build(store, kind='int8', seed=0)
    assert int8_index.bytes_per_vector * 2 <= 2 * store.dim


def test_zero_postwarm_compiles_across_query_buckets(tmp_path):
    from code2vec_tpu.telemetry.jit_tracker import \
        install_compile_listener
    store, vecs = small_store(tmp_path, n=600)
    index = QuantizedIVFIndex.build(store, kind='pq', seed=0)
    core.reset()
    core.enable()
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        # warm: full probe (capacity rung is query-independent there)
        # plus the default-nprobe traffic we will repeat
        for bucket in (1, 8, 64):
            index.search(vecs[:bucket], 10, nprobe=index.n_clusters)
            index.search(vecs[7:7 + bucket], 10)
        warm = compiles.value
        for bucket in (1, 8, 64):
            index.search(vecs[200:200 + bucket], 10,
                         nprobe=index.n_clusters)
            index.search(vecs[7:7 + bucket], 10)
        assert compiles.value - warm == 0, (
            '%d XLA compiles on the post-warmup query path'
            % (compiles.value - warm))
    finally:
        core.disable()


# ------------------------------------------------------- budget gates
def test_budget_refused_attach_is_typed_with_zero_allocation(tmp_path):
    store, _vecs = small_store(tmp_path)
    QuantizedIVFIndex.build(store, kind='int8', seed=0)  # sidecars
    gc.collect()
    memory.configure(budget_bytes=64, dump_dir=str(tmp_path))
    before = memory.backend_memory()['live_bytes']
    with pytest.raises(MemoryBudgetExceeded, match='index attach'):
        QuantizedIVFIndex(store_lib.VectorStore(store.path))
    gc.collect()
    assert memory.backend_memory()['live_bytes'] == before
    assert memory.ledger().bucket_bytes('index') == 0


def test_budget_refused_append_keeps_index_serving(tmp_path):
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind='int8', seed=0)
    memory.configure(
        budget_bytes=memory.ledger().attributed_bytes() + 8,
        dump_dir=str(tmp_path))
    with pytest.raises(MemoryBudgetExceeded, match='append segment'):
        index.insert(vecs[:4])
    memory.configure(budget_bytes=0)
    values, ids = index.search(vecs[:2], 5)
    assert (ids[:, 0] >= 0).all()


def test_ledger_keys_index_bucket_per_segment(tmp_path):
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind='pq', seed=0,
                                    segment_rows=8, compact_segments=0)
    index.insert(vecs[:20])     # 3 segments (8 + 8 + 4)
    snapshot = memory.ledger().snapshot(reconcile=False)
    keys = [entry['key'] for entry
            in snapshot['buckets']['index']['entries']]
    assert len([key for key in keys if ':seg0' in key]) == 3
    assert any(key.endswith(':base') for key in keys)


# ------------------------------------------------- inserts + segments
def test_insert_queryable_without_rebuild_and_labels(tmp_path):
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind='int8', seed=0)
    new = (vecs[37:40] + 0.001).astype(np.float32)
    ids = index.insert(new, labels=['n0', 'n1', 'n2'])
    assert ids.tolist() == [800, 801, 802]
    assert index.count == 803
    _values, got = index.search(new, 5)
    for j in range(3):
        assert ids[j] in got[j]
    assert index.labels[-3:].tolist() == ['n0', 'n1', 'n2']


def test_reopen_serves_uncompacted_segments(tmp_path):
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind='pq', seed=0)
    ids = index.insert(vecs[11:14] + 0.002)
    reopened = QuantizedIVFIndex(store_lib.VectorStore(store.path))
    assert reopened.segment_count == 1
    assert reopened.count == index.count
    values_a, ids_a = index.search(vecs[:8], 10)
    values_b, ids_b = reopened.search(vecs[:8], 10)
    np.testing.assert_array_equal(ids_a, ids_b)
    np.testing.assert_allclose(values_a, values_b, rtol=1e-6)
    assert ids[0] in reopened.search(vecs[11:12] + 0.002, 5)[1][0]


def test_auto_compaction_triggers_on_segment_count(tmp_path):
    store, vecs = small_store(tmp_path)
    index = QuantizedIVFIndex.build(store, kind='int8', seed=0,
                                    segment_rows=4, compact_segments=2)
    index.insert(vecs[:4] + 0.001)
    index.insert(vecs[4:8] + 0.001)
    assert index.segment_count == 2 and index.compactions == 0
    index.insert(vecs[8:12] + 0.001)     # 3rd segment -> compact
    assert index.segment_count == 0
    assert index.compactions == 1
    assert index.store.count == 812
    assert index.version == 1


# ------------------------------------- compaction parity (property)
def _search_all(index, queries, k):
    """Full-probe, full-rerank search: candidate order is decided by
    the exact re-rank, so results are bit-for-rank reproducible."""
    index.rerank = 10 ** 6
    return index.search(queries, k, nprobe=index.n_clusters)


@pytest.mark.parametrize('kind', ['int8', 'pq'])
def test_append_then_compact_bit_for_rank_vs_fresh_build(
        tmp_path, kind):
    """ISSUE 19 satellite: append-segments-then-compaction must be
    bit-for-rank identical (under exact re-rank) to a fresh build over
    the same corpus — including empty segments and duplicate
    vectors."""
    base = clustered_corpus(600, 16, centers=10, seed=4)
    extra1 = clustered_corpus(40, 16, centers=10, seed=5)
    dupes = base[100:110].copy()           # exact duplicates
    extra2 = clustered_corpus(25, 16, centers=10, seed=6)
    store, _ = small_store(tmp_path, n=600, dim=16, centers=10, seed=4,
                           labels=False)
    index = QuantizedIVFIndex.build(store, kind=kind, seed=0,
                                    segment_rows=16, compact_segments=0)
    index.insert(extra1)
    index.insert(np.empty((0, 16), np.float32))   # empty segment
    index.insert(dupes)
    index.insert(extra2)
    queries = np.concatenate([base[::151][:4], extra1[:2], dupes[:2]])
    pre_values, pre_ids = _search_all(index, queries, 10)
    index.compact()
    post_values, post_ids = _search_all(index, queries, 10)
    np.testing.assert_array_equal(pre_ids, post_ids)
    np.testing.assert_allclose(pre_values, post_values, rtol=1e-6)
    # fresh build over the SAME corpus in the same row order
    full = np.concatenate([base, extra1, dupes, extra2])
    fresh_store = store_lib.build(str(tmp_path / 'fresh.vecindex'),
                                  [full], labels=None)
    fresh = QuantizedIVFIndex.build(fresh_store, kind=kind, seed=0)
    fresh_values, fresh_ids = _search_all(fresh, queries, 10)
    np.testing.assert_array_equal(post_ids, fresh_ids)
    np.testing.assert_allclose(post_values, fresh_values, rtol=1e-6)


def test_insert_landing_mid_compaction_is_not_lost(tmp_path):
    """Inserts racing a compaction serialize behind the index lock:
    the late batch lands as a fresh segment against the compacted base
    and stays queryable."""
    store, vecs = small_store(tmp_path, n=400)
    index = QuantizedIVFIndex.build(store, kind='int8', seed=0,
                                    compact_segments=0)
    index.insert(vecs[:6] + 0.001)
    racer_ids = []
    started = threading.Event()

    def racer():
        started.wait()
        racer_ids.append(index.insert(vecs[6:9] + 0.002))

    thread = threading.Thread(target=racer)
    thread.start()
    started.set()
    index.compact()
    thread.join()
    assert len(racer_ids) == 1
    _values, got = index.search(vecs[6:9] + 0.002, 5)
    for j, rid in enumerate(racer_ids[0]):
        assert rid in got[j]
    # every row accounted for: base 400 + first batch 6 + racer 3
    assert index.count == 409
    index.compact()
    assert index.store.count == 409
    _values2, got2 = index.search(vecs[6:9] + 0.002, 5)
    np.testing.assert_array_equal(got, got2)


# ------------------------------------------------------ 50k acceptance
@pytest.mark.slow
@pytest.mark.parametrize('kind', ['int8', 'pq'])
def test_quant_recall_at_default_nprobe_50k(tmp_path, kind):
    """ISSUE 19 acceptance (slow tier): recall@10 >= 0.95 vs exact at
    the default nprobe with the default re-rank on the 50k clustered
    corpus, at <= 1/2 (int8) / <= 1/4 (pq) the device bytes/vector of
    f16."""
    vecs = clustered_corpus(50000, 64, centers=500, seed=11)
    store = store_lib.build(str(tmp_path / 'big.vecindex'), [vecs])
    exact = ExactIndex(store)
    index = QuantizedIVFIndex.build(store, kind=kind, seed=0)
    rng = np.random.default_rng(12)
    queries = (vecs[rng.choice(50000, 128)]
               + 0.01 * rng.normal(size=(128, 64))).astype(np.float32)
    recall = measure_recall(index, exact, queries, k=10)
    assert recall >= 0.95, recall
    ceiling = 2 * store.dim // (2 if kind == 'int8' else 4)
    assert index.bytes_per_vector <= ceiling


# ----------------------------------------------------- store plumbing
def test_store_take_gathers_across_shards(tmp_path):
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(700, 8)).astype(np.float32)
    store = store_lib.build(str(tmp_path / 's.vecindex'), [vecs],
                            metric='dot', shard_rows=256)
    ids = np.array([0, 255, 256, 511, 512, 699, 3])
    np.testing.assert_allclose(store.take(ids), vecs[ids], rtol=1e-6)
    with pytest.raises(IndexError):
        store.take(np.array([700]))


def test_store_append_rows_extends_shards_and_labels(tmp_path):
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(300, 8)).astype(np.float32)
    store = store_lib.build(str(tmp_path / 's.vecindex'), [vecs],
                            metric='cosine', shard_rows=256,
                            labels=['m%d' % i for i in range(300)])
    extra = rng.normal(size=(10, 8)).astype(np.float32)
    start, end = store.append_rows(extra, labels=['x%d' % i
                                                  for i in range(10)])
    assert (start, end) == (300, 310)
    assert store.count == 310
    # appended rows normalized like build() (cosine store)
    np.testing.assert_allclose(
        store.take(np.arange(300, 310)),
        store_lib.normalize_rows(extra), rtol=1e-5)
    assert store.labels[-1] == 'x9'
    # a reopened view sees the grown store
    reopened = store_lib.VectorStore(store.path)
    assert reopened.count == 310
    assert reopened.labels[305] == 'x5'
    # unlabeled store refuses labels (would mis-align)
    bare = store_lib.build(str(tmp_path / 'b.vecindex'), [vecs],
                           metric='dot')
    with pytest.raises(ValueError, match='labels'):
        bare.append_rows(extra, labels=['z'] * 10)
