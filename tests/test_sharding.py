"""Multi-chip semantics on the 8-virtual-device CPU mesh (SURVEY.md §4):
DP-only, TP-only and mixed meshes must produce the same numbers as a
single-device run — sharding is configuration, not semantics."""
import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import Batch
from code2vec_tpu.models.backends import create_backend
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.training.trainer import Trainer
from code2vec_tpu.vocab import Code2VecVocabs, SizeOnlyVocabs


def _make_batch(rng, B=16, C=8, Vt=40, Vp=12):
    source = rng.integers(1, Vt, (B, C)).astype(np.int32)
    path = rng.integers(1, Vp, (B, C)).astype(np.int32)
    target = rng.integers(1, Vt, (B, C)).astype(np.int32)
    mask = np.ones((B, C), np.float32)
    label = rng.integers(1, 20, (B,)).astype(np.int32)
    weight = np.ones((B,), np.float32)
    return Batch(source=source, path=path, target=target, mask=mask,
                 label=label, weight=weight)


def _config(data_axis, model_axis, framework='jax', **overrides):
    kwargs = dict(
        TRAIN_DATA_PATH_PREFIX='unused', DL_FRAMEWORK=framework,
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=8, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MESH_DATA_AXIS_SIZE=data_axis, MESH_MODEL_AXIS_SIZE=model_axis,
        MAX_TOKEN_VOCAB_SIZE=40, MAX_PATH_VOCAB_SIZE=12,
        MAX_TARGET_VOCAB_SIZE=24, TOKEN_EMBEDDINGS_SIZE=8,
        PATH_EMBEDDINGS_SIZE=8, CODE_VECTOR_SIZE=24,
        TARGET_EMBEDDINGS_SIZE=24, LEARNING_RATE=0.01)
    kwargs.update(overrides)
    return Config(**kwargs)


def _trainer(data_axis, model_axis, framework='jax', **overrides):
    config = _config(data_axis, model_axis, framework, **overrides)
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend = create_backend(config, vocabs)
    return Trainer(config, backend)


def _run_steps(trainer, n=3, seed=0, make_batch=_make_batch):
    state = trainer.init_state(seed=123)
    rng = np.random.default_rng(seed)
    losses = []
    for _ in range(n):
        batch = make_batch(rng)
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    return state, losses


def test_mesh_shapes():
    assert mesh_lib.create_mesh(_config(8, 1)).shape == {'data': 8, 'model': 1}
    assert mesh_lib.create_mesh(_config(4, 2)).shape == {'data': 4, 'model': 2}
    assert mesh_lib.create_mesh(_config(-1, 2)).shape == {'data': 4, 'model': 2}
    with pytest.raises(ValueError):
        mesh_lib.create_mesh(_config(3, 2))


def test_param_placement_on_mixed_mesh():
    trainer = _trainer(4, 2)
    state = trainer.init_state()
    named = trainer.backend.named_params(state.params)
    # embeddings row-sharded over model axis
    assert named.token_embedding.sharding.spec == P('model', None)
    assert named.target_embedding.sharding.spec == P('model', None)
    # dense params replicated
    assert named.transform.sharding.spec in (P(), P(None, None))
    # Adam moments inherit the table sharding (name-based mapping)
    mu = state.opt_state[0].mu
    leaf = mu.token_embedding if hasattr(mu, 'token_embedding') \
        else mu['token_embedding']
    assert leaf.sharding.spec == P('model', None)


@pytest.mark.parametrize('mesh_shape', [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_training_matches_single_device(mesh_shape):
    # ground truth: 1x1 mesh on device 0
    config1 = _config(1, 1)
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend1 = create_backend(config1, vocabs)
    mesh1 = mesh_lib.create_mesh(config1, devices=jax.devices()[:1])
    trainer1 = Trainer(config1, backend1, mesh=mesh1)
    _, losses1 = _run_steps(trainer1)

    trainerN = _trainer(*mesh_shape)
    _, lossesN = _run_steps(trainerN)
    np.testing.assert_allclose(losses1, lossesN, rtol=2e-4, atol=1e-5)


def test_eval_step_on_sharded_mesh_matches_single_device():
    config1 = _config(1, 1)
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend1 = create_backend(config1, vocabs)
    mesh1 = mesh_lib.create_mesh(config1, devices=jax.devices()[:1])
    trainer1 = Trainer(config1, backend1, mesh=mesh1)
    state1, _ = _run_steps(trainer1)

    trainerN = _trainer(2, 4)
    stateN, _ = _run_steps(trainerN)

    rng = np.random.default_rng(7)
    batch = _make_batch(rng)
    out1 = trainer1.eval_step(state1.params, batch)
    outN = trainerN.eval_step(stateN.params, batch)
    np.testing.assert_array_equal(np.asarray(out1['topk_indices']),
                                  np.asarray(outN['topk_indices']))
    np.testing.assert_allclose(np.asarray(out1['topk_scores']),
                               np.asarray(outN['topk_scores']),
                               rtol=2e-4, atol=1e-5)


def test_shard_contexts_divisibility_validated_upfront():
    config = _config(2, 4)
    config.SHARD_CONTEXTS = True
    config.MAX_CONTEXTS = 6  # not divisible by model axis 4
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend = create_backend(config, vocabs)
    with pytest.raises(ValueError, match='SHARD_CONTEXTS'):
        Trainer(config, backend)


def test_row_alignment_divisibility_validated_upfront():
    config = _config(2, 4)
    config.PARAM_ROW_ALIGNMENT = 6  # not divisible by model axis 4
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend = create_backend(config, vocabs)
    with pytest.raises(ValueError, match='PARAM_ROW_ALIGNMENT'):
        Trainer(config, backend)


def test_shard_contexts_training_matches_unsharded():
    config = _config(2, 4)
    config.SHARD_CONTEXTS = True  # MAX_CONTEXTS=8 divisible by 4
    vocabs = SizeOnlyVocabs(40, 12, 24)
    backend = create_backend(config, vocabs)
    trainer_sp = Trainer(config, backend)
    _, losses_sp = _run_steps(trainer_sp)

    config1 = _config(1, 1)
    backend1 = create_backend(config1, SizeOnlyVocabs(40, 12, 24))
    mesh1 = mesh_lib.create_mesh(config1, devices=jax.devices()[:1])
    trainer1 = Trainer(config1, backend1, mesh=mesh1)
    _, losses1 = _run_steps(trainer1)
    np.testing.assert_allclose(losses1, losses_sp, rtol=2e-4, atol=1e-5)


def test_shard_contexts_long_bag_matches_unsharded():
    """Long-context scaling (SURVEY.md §5): a 1024-context bag sharded
    over the model axis (the order-free 'ring attention' analog — the
    attention reductions compile to XLA collectives) must match the
    unsharded numbers. This is the MAX_CONTEXTS-scaling story, not just
    the divisibility smoke at C=8."""
    LONG_C = 1024
    config = _config(2, 4)
    config.MAX_CONTEXTS = LONG_C
    config.SHARD_CONTEXTS = True
    vocabs = SizeOnlyVocabs(40, 12, 24)
    trainer_sp = Trainer(config, create_backend(config, vocabs))

    config1 = _config(1, 1)
    config1.MAX_CONTEXTS = LONG_C
    backend1 = create_backend(config1, SizeOnlyVocabs(40, 12, 24))
    mesh1 = mesh_lib.create_mesh(config1, devices=jax.devices()[:1])
    trainer1 = Trainer(config1, backend1, mesh=mesh1)

    def make_long_batch(rng):
        batch = _make_batch(rng, B=8, C=LONG_C)
        # half the contexts masked: the masked-softmax denominator must
        # psum identically across context shards
        return batch._replace(
            mask=(np.arange(LONG_C)[None, :] < LONG_C // 2)
            .astype(np.float32).repeat(8, axis=0))

    _, losses1 = _run_steps(trainer1, n=2, seed=7,
                            make_batch=make_long_batch)
    _, losses_sp = _run_steps(trainer_sp, n=2, seed=7,
                              make_batch=make_long_batch)
    np.testing.assert_allclose(losses1, losses_sp, rtol=2e-4, atol=1e-5)


def test_profile_trace_capture_smoke(tmp_path):
    """--profile (jax.profiler window inside fit): must produce a trace
    artifact — guards the path so the on-chip profiling day isn't spent
    debugging the harness (VERDICT r1 #2 groundwork)."""
    config = _config(8, 1)
    config.NUM_TRAIN_EPOCHS = 1
    config.PROFILE_DIR = str(tmp_path / 'trace')
    config.PROFILE_START_STEP = 1
    config.PROFILE_NUM_STEPS = 2
    vocabs = SizeOnlyVocabs(40, 12, 24)
    trainer = Trainer(config, create_backend(config, vocabs))
    state = trainer.init_state(seed=0)
    rng = np.random.default_rng(0)
    batches = [_make_batch(rng) for _ in range(6)]
    trainer.fit(state, lambda epoch: iter(batches), start_epoch=0)
    trace_files = list((tmp_path / 'trace').rglob('*'))
    assert any(f.is_file() for f in trace_files), 'no trace artifacts'


def test_checkpoint_metadata_mismatch_is_clear_error(tmp_path):
    from code2vec_tpu.checkpoints import CheckpointStore
    store = CheckpointStore(str(tmp_path / 'm'),
                            metadata={'param_row_alignment': 128})
    store._write_metadata()
    store2 = CheckpointStore(str(tmp_path / 'm'),
                             metadata={'param_row_alignment': 256})
    with pytest.raises(ValueError, match='param_row_alignment'):
        store2.verify_metadata()


def _mu_leaf(state):
    mu = state.opt_state[0].mu
    return mu.token_embedding if hasattr(mu, 'token_embedding') \
        else mu['token_embedding']


def test_zero_opt_state_sharding_matches_mirror():
    """OPTIMIZER_STATE_SHARDING='zero' shards the moment tables over the
    whole (data, model) mesh: same losses as the mirrored layout, and the
    zero sharding survives the donated train step (no silent re-layout
    back to replicated-along-data)."""
    zero = _trainer(4, 2, PARAM_ROW_ALIGNMENT=8,
                    OPTIMIZER_STATE_SHARDING='zero')
    mirror = _trainer(4, 2, PARAM_ROW_ALIGNMENT=8)
    state_z, losses_z = _run_steps(zero, n=3)
    _, losses_m = _run_steps(mirror, n=3)
    np.testing.assert_allclose(losses_z, losses_m, rtol=2e-4, atol=1e-5)
    assert _mu_leaf(state_z).sharding.spec == P(('data', 'model'), None)
    # params stay replicated along data (ZeRO-1, not ZeRO-3)
    named = zero.backend.named_params(state_z.params)
    assert named.token_embedding.sharding.spec == P('model', None)


def test_remat_encode_on_mesh_matches_default():
    """jax.checkpoint around encode composes with the sharded train step
    (SHARD_CONTEXTS sequence parallelism included): identical losses."""
    _, plain = _run_steps(_trainer(4, 2, SHARD_CONTEXTS=True), n=2)
    _, remat = _run_steps(_trainer(4, 2, SHARD_CONTEXTS=True,
                                   REMAT_ENCODE=True), n=2)
    np.testing.assert_allclose(remat, plain, rtol=1e-6)


def test_zero_opt_state_requires_whole_mesh_alignment():
    with pytest.raises(ValueError, match='data\\*model'):
        _trainer(4, 2, PARAM_ROW_ALIGNMENT=2,
                 OPTIMIZER_STATE_SHARDING='zero')


@pytest.mark.parametrize('fused', [False, True])
def test_bf16_grads_on_mixed_mesh_tracks_fp32_twin(fused):
    """The combined pod recipe: GRADS_DTYPE='bfloat16' (bf16 compute, as
    verify() requires) on a (4,2) DP+TP mesh, with and without the
    shard_mapped fused CE. A FIXED batch makes the trajectory strictly
    descend, so a silently dead bf16 cotangent path (grads zeroed through
    the psum/shard_map or fused-CE vjp) fails the descent assertion —
    proximity alone cannot catch it: over a few steps the loss moves less
    than any usable tolerance (review r5 measurement). The bf16 arm must
    also track the fp32 twin within grad-rounding tolerance."""
    rng = np.random.default_rng(3)
    fixed = _make_batch(rng)

    def make_fixed(_rng):
        return fixed

    base = _trainer(4, 2, COMPUTE_DTYPE='bfloat16',
                    GRADS_DTYPE='float32', USE_PALLAS_FUSED_CE=fused)
    lo = _trainer(4, 2, COMPUTE_DTYPE='bfloat16',
                  GRADS_DTYPE='bfloat16', USE_PALLAS_FUSED_CE=fused)
    _, base_losses = _run_steps(base, n=5, make_batch=make_fixed)
    _, lo_losses = _run_steps(lo, n=5, make_batch=make_fixed)
    # the bf16-grads arm LEARNS: repeated-batch loss must clearly drop
    # (a dead-grad arm stays flat at the step-1 value)
    assert lo_losses[-1] < lo_losses[0] - 0.05, (fused, lo_losses)
    for a, b in zip(base_losses, lo_losses):
        assert abs(a - b) / max(abs(a), 1e-6) < 0.03, (fused, base_losses,
                                                       lo_losses)


def test_fused_ce_changes_target_table_allocation():
    """USE_PALLAS_FUSED_CE (and the mesh model axis under it) grows the
    target-table allocation; the padded row count is what checkpoint
    metadata records ('target_vocab_rows') so a resume whose allocation
    differs fails with a clear config error instead of an opaque orbax
    shape mismatch — while resumes whose padding coincides still load."""
    from code2vec_tpu.models.backends import (JaxBackend,
                                              target_row_alignment)
    from code2vec_tpu.ops.pallas_ce import VOCAB_TILE

    base = _config(1, 1, PARAM_ROW_ALIGNMENT=8)
    assert target_row_alignment(base) == 8
    fused = _config(1, 1, PARAM_ROW_ALIGNMENT=8, USE_PALLAS_FUSED_CE=True)
    assert target_row_alignment(fused) == VOCAB_TILE
    fused_tp = _config(4, 2, PARAM_ROW_ALIGNMENT=8,
                       USE_PALLAS_FUSED_CE=True)
    assert target_row_alignment(fused_tp) == 2 * VOCAB_TILE

    vocabs = SizeOnlyVocabs(40, 12, 24)
    assert JaxBackend(base, vocabs).sizes['target_vocab_size'] == 24
    assert JaxBackend(fused, vocabs).sizes['target_vocab_size'] == \
        VOCAB_TILE
    assert JaxBackend(fused_tp, vocabs).sizes['target_vocab_size'] == \
        2 * VOCAB_TILE


def test_sharded_top_k_matches_lax_top_k():
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from code2vec_tpu.ops.topk import sharded_top_k
    config = _config(2, 4)
    mesh = mesh_lib.create_mesh(config)
    rng = np.random.default_rng(0)
    # distinct values so tie-breaking can't differ
    logits = rng.permutation(16 * 64).reshape(16, 64).astype(np.float32)
    ref_vals, ref_idx = jax.lax.top_k(jnp.asarray(logits), 10)
    placed = jax.device_put(logits, NamedSharding(mesh, P('data', 'model')))
    vals, idx = jax.jit(
        lambda x: sharded_top_k(x, 10, mesh))(placed)
    np.testing.assert_array_equal(np.asarray(ref_idx), np.asarray(idx))
    np.testing.assert_allclose(np.asarray(ref_vals), np.asarray(vals))

    # k larger than the per-shard width (V/m = 2 < k = 5): every shard
    # contributes all columns
    small = rng.permutation(16 * 8).reshape(16, 8).astype(np.float32)
    ref_vals5, ref_idx5 = jax.lax.top_k(jnp.asarray(small), 5)
    placed5 = jax.device_put(small, NamedSharding(mesh, P('data', 'model')))
    vals5, idx5 = jax.jit(lambda x: sharded_top_k(x, 5, mesh))(placed5)
    np.testing.assert_array_equal(np.asarray(ref_idx5), np.asarray(idx5))
    np.testing.assert_allclose(np.asarray(ref_vals5), np.asarray(vals5))


def test_flax_backend_shards_too():
    trainer = _trainer(4, 2, framework='flax')
    _, losses = _run_steps(trainer, n=2)
    assert all(np.isfinite(losses))


def test_bf16_mu_matches_layout_on_tp_mesh():
    """ADAM_MU_DTYPE='bfloat16' on a (4, 2) mesh: the bf16 first moment
    must mirror the row-sharded table layout (mu sharded like params) and
    training must still run."""
    import jax.numpy as jnp

    trainer = _trainer(4, 2, ADAM_MU_DTYPE='bfloat16')
    state, losses = _run_steps(trainer, n=2)
    assert np.isfinite(losses).all()

    mu = state.opt_state[0].mu
    leaves = jax.tree_util.tree_leaves(mu)
    assert {leaf.dtype for leaf in leaves} == {np.dtype(jnp.bfloat16)}
    # the token table's mu shards over 'model' rows exactly like the param
    token_mu = mu.token_embedding
    token_param = state.params.token_embedding
    assert token_mu.sharding.spec == token_param.sharding.spec


def test_rbg_dropout_trains_on_tp_mesh():
    """DROPOUT_PRNG_IMPL='rbg' on a (4, 2) mesh with SHARD_CONTEXTS: the
    (B, C, 3d) rng_bit_generator mask draw must lower through SPMD
    partitioning (it was only exercised single-device before) and produce
    finite, decreasing-ish losses like the threefry path."""
    trainer = _trainer(4, 2, DROPOUT_PRNG_IMPL='rbg', SHARD_CONTEXTS=True)
    _, losses = _run_steps(trainer, n=3)
    assert np.isfinite(losses).all()
    # seed-deterministic, so this is not flaky: a degenerate rbg mask
    # (e.g. all-dropped) would keep loss pinned at ~ln(V) instead
    assert losses[-1] < losses[0]

    # same data, threefry path: rbg is a different (valid) random stream,
    # so only coarse agreement is expected — both must actually learn
    trainer_tf = _trainer(4, 2, SHARD_CONTEXTS=True)
    _, losses_tf = _run_steps(trainer_tf, n=3)
    assert np.isfinite(losses_tf).all()
    assert abs(losses[0] - losses_tf[0]) < 1.0
