"""Serving engine (serving/engine.py + serving/bulk.py): bucket
selection, deadline coalescing, exact parity with ``model.predict``,
output tiers, oversize splitting, and the corpus-scale bulk paths."""
import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.serving import engine as engine_lib
from tests.test_train_overfit import make_dataset

# the four labels/token families of make_dataset's corpus
PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]


# ------------------------------------------------------------ pure units
def test_batch_ladder_rounds_to_data_axis():
    assert engine_lib.batch_ladder([8, 64], 8) == (8, 64)
    # rounded up to the axis, deduplicated, sorted
    assert engine_lib.batch_ladder([1, 8, 10, 60], 8) == (8, 16, 64)
    with pytest.raises(ValueError):
        engine_lib.batch_ladder([0], 8)


def test_pick_bucket_smallest_cover():
    ladder = (8, 16, 64)
    assert engine_lib.pick_bucket(1, ladder) == 8
    assert engine_lib.pick_bucket(8, ladder) == 8
    assert engine_lib.pick_bucket(9, ladder) == 16
    assert engine_lib.pick_bucket(64, ladder) == 64
    assert engine_lib.pick_bucket(65, ladder) is None


def test_capacity_ladder_covers_and_grows_geometrically():
    assert packed_lib.capacity_ladder(6) == (64,)
    assert packed_lib.capacity_ladder(64) == (64,)
    assert packed_lib.capacity_ladder(65) == (64, 65)
    assert packed_lib.capacity_ladder(1600) == (64, 256, 1024, 1600)
    ladder = packed_lib.capacity_ladder(25600)
    assert ladder[-1] == 25600
    assert all(a < b for a, b in zip(ladder, ladder[1:]))
    with pytest.raises(ValueError):
        packed_lib.capacity_ladder(0)


def test_capacity_rungs_are_exact_pack_targets():
    """pack_ragged with capacity_minimum=<rung> must land EXACTLY on the
    rung for any total <= rung — that is what makes every dispatched
    wire shape one of the pre-compiled ladder shapes."""
    rng = np.random.default_rng(0)
    for rung in packed_lib.capacity_ladder(1600):
        count = np.array([3, 0, 5, 1], np.int32)
        ctx_rows = rng.integers(
            1, 100, (int(count.sum()), 3)).astype(np.int32)
        ctx = packed_lib.pack_ragged(ctx_rows, count, 0, 0,
                                     capacity_minimum=rung)
        assert ctx.shape == (1, rung, 3)


def test_shard_totals():
    count = np.array([1, 2, 3, 4], np.int32)
    np.testing.assert_array_equal(
        packed_lib.shard_totals(count, 2), [3, 7])
    with pytest.raises(ValueError):
        packed_lib.shard_totals(count, 3)


# -------------------------------------------------------------- fixtures
@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16')
    return Code2VecModel(config)


# --------------------------------------------------------------- engine
def test_engine_matches_model_predict_exactly(model):
    direct = model.predict(PREDICT_LINES)
    with model.serving_engine(tiers=('attention',),
                              max_delay_ms=0.0) as engine:
        served = engine.predict(PREDICT_LINES, tier='attention',
                                timeout=60)
    assert len(served) == len(direct) == len(PREDICT_LINES)
    for s, d in zip(served, direct):
        assert s.original_name == d.original_name
        assert s.topk_predicted_words == d.topk_predicted_words
        np.testing.assert_array_equal(s.topk_predicted_words_scores,
                                      d.topk_predicted_words_scores)
        assert s.attention_per_context == d.attention_per_context
        assert s.code_vector is None and d.code_vector is None


def test_deadline_coalescing_batches_concurrent_requests(model):
    """Requests submitted inside one deadline window ride ONE dispatched
    micro-batch, and each future gets exactly its own rows back."""
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=500.0) as engine:
        futures = [engine.submit([line], tier='topk')
                   for line in PREDICT_LINES]
        results = [f.result(timeout=60) for f in futures]
        stats = engine.stats()
    assert stats['batches_total'] == 1
    assert stats['requests_total'] == len(PREDICT_LINES)
    assert stats['last_dispatch']['requests'] == len(PREDICT_LINES)
    assert stats['last_dispatch']['rows'] == len(PREDICT_LINES)
    direct = model.predict(PREDICT_LINES)
    for (res,), d in zip(results, direct):
        assert res.original_name == d.original_name
        assert res.topk_predicted_words == d.topk_predicted_words


def test_bucket_selection_smallest_cover(model):
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=0.0) as engine:
        engine.predict([PREDICT_LINES[0]], tier='topk', timeout=60)
        first = dict(engine.stats()['last_dispatch'])
        nine = [PREDICT_LINES[i % 3] for i in range(9)]
        engine.predict(nine, tier='topk', timeout=60)
        second = dict(engine.stats()['last_dispatch'])
    assert first == {'bucket': 8, 'rows': 1, 'capacity': 64,
                     'requests': 1}
    assert second['bucket'] == 16 and second['rows'] == 9
    assert engine.stats()['batch_fill_rate'] == pytest.approx(9 / 16)


def test_topk_tier_is_attention_and_vector_free(model):
    direct = model.predict(PREDICT_LINES)
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=0.0) as engine:
        served = engine.predict(PREDICT_LINES, tier='topk', timeout=60)
    for s, d in zip(served, direct):
        assert s.topk_predicted_words == d.topk_predicted_words
        np.testing.assert_array_equal(s.topk_predicted_words_scores,
                                      d.topk_predicted_words_scores)
        assert s.attention_per_context == {}
        assert s.code_vector is None


def test_oversize_request_splits_across_buckets(model):
    lines = [PREDICT_LINES[i % 3] for i in range(20)]
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=0.0) as engine:
        served = engine.predict(lines, tier='topk', timeout=60)
        stats = engine.stats()
    assert len(served) == 20
    assert stats['batches_total'] == 2  # 16-row chunk + 4-row chunk
    # row results are independent of batch membership (per-row softmax)
    direct = model.predict(lines)
    for s, d in zip(served, direct):
        assert s.original_name == d.original_name
        assert s.topk_predicted_words == d.topk_predicted_words
        np.testing.assert_allclose(s.topk_predicted_words_scores,
                                   d.topk_predicted_words_scores,
                                   rtol=1e-5, atol=1e-7)


def test_cancelled_request_does_not_poison_batchmates(model):
    """A caller cancelling its future (these futures are never marked
    running, so cancel() always succeeds) must not break delivery to
    the other requests coalesced into the same micro-batch."""
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=300.0) as engine:
        doomed = engine.submit([PREDICT_LINES[0]], tier='topk')
        survivor = engine.submit([PREDICT_LINES[1]], tier='topk')
        assert doomed.cancel()
        results = survivor.result(timeout=60)
        stats = engine.stats()
    assert stats['batches_total'] == 1  # same micro-batch
    assert results[0].topk_predicted_words == \
        model.predict([PREDICT_LINES[1]])[0].topk_predicted_words


def test_engine_empty_submit_and_close_semantics(model):
    engine = model.serving_engine(tiers=('topk',), warmup=False,
                                  max_delay_ms=0.0)
    assert engine.submit([], tier='topk').result(timeout=5) == []
    with pytest.raises(ValueError):
        engine.submit(PREDICT_LINES, tier='vectors')  # not warmed
    engine.close()
    engine.close()  # idempotent
    with pytest.raises(RuntimeError):
        engine.submit(PREDICT_LINES, tier='topk')


# ----------------------------------------------------------------- bulk
def test_bulk_export_code_vectors(model, tmp_path):
    corpus = tmp_path / 'corpus.c2v'
    lines = [PREDICT_LINES[i % 3] for i in range(10)]
    corpus.write_text('\n'.join(lines) + '\n')
    from code2vec_tpu.serving import bulk
    total, out_path = bulk.export_code_vectors(model, str(corpus))
    assert total == 10
    rows = [np.array(line.split(), dtype=float)
            for line in open(out_path).read().splitlines()]
    assert len(rows) == 10
    dim = model.config.CODE_VECTOR_SIZE
    assert all(r.shape == (dim,) for r in rows)
    # parity with the engine's vectors tier (batch shapes differ, so
    # allclose, not bit equality)
    with model.serving_engine(tiers=('vectors',),
                              max_delay_ms=0.0) as engine:
        served = engine.predict(lines, tier='vectors', timeout=60)
    for file_vec, res in zip(rows, served):
        np.testing.assert_allclose(file_vec, res.code_vector,
                                   rtol=1e-4, atol=1e-6)


def test_bulk_predict_streams_in_order(model):
    lines = [PREDICT_LINES[i % 3] for i in range(11)]
    from code2vec_tpu.serving import bulk
    results = list(bulk.bulk_predict(model, iter(lines), tier='topk',
                                     batch_size=8))
    assert len(results) == 11
    direct = model.predict(lines)
    for r, d in zip(results, direct):
        assert r.original_name == d.original_name
        assert r.topk_predicted_words == d.topk_predicted_words
        assert r.attention_per_context == {}
