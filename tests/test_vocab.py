import io
import pickle

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.vocab import (
    SPECIAL_WORDS_JOINED_OOV_PAD, SPECIAL_WORDS_ONLY_OOV,
    SPECIAL_WORDS_SEPARATE_OOV_PAD, Code2VecVocabs, Vocab, VocabType)


def test_joined_policy_single_special_index():
    # <PAD_OR_OOV> occupies one index even though it's three names
    # (reference vocabularies.py:31-35, 51).
    vocab = Vocab(VocabType.Token, ['a', 'b'], SPECIAL_WORDS_JOINED_OOV_PAD)
    assert vocab.size == 3
    assert vocab.pad_index == vocab.oov_index == 0
    assert vocab.word_to_index == {'<PAD_OR_OOV>': 0, 'a': 1, 'b': 2}


def test_separate_policy():
    vocab = Vocab(VocabType.Token, ['a'], SPECIAL_WORDS_SEPARATE_OOV_PAD)
    assert vocab.size == 3
    assert vocab.pad_index == 0
    assert vocab.oov_index == 1


def test_lookup_with_oov_default():
    vocab = Vocab(VocabType.Token, ['a', 'b'], SPECIAL_WORDS_JOINED_OOV_PAD)
    assert vocab.lookup_index('a') == 1
    assert vocab.lookup_index('unknown') == vocab.oov_index
    np.testing.assert_array_equal(
        vocab.lookup_indices(['a', 'zzz', 'b']), np.array([1, 0, 2]))
    assert vocab.lookup_word(2) == 'b'
    assert vocab.lookup_word(999) == vocab.special_words.OOV


def test_create_from_freq_dict_truncation():
    # Top-max_size words by count (reference vocabularies.py:99-106).
    vocab = Vocab.create_from_freq_dict(
        VocabType.Token, {'rare': 1, 'common': 100, 'mid': 10}, 2,
        SPECIAL_WORDS_JOINED_OOV_PAD)
    assert vocab.size == 3  # 1 special + 2 kept
    assert 'common' in vocab.word_to_index
    assert 'mid' in vocab.word_to_index
    assert 'rare' not in vocab.word_to_index


def test_save_load_roundtrip():
    vocab = Vocab(VocabType.Target, ['x', 'y', 'z'], SPECIAL_WORDS_ONLY_OOV)
    buf = io.BytesIO()
    vocab.save_to_file(buf)
    buf.seek(0)
    loaded = Vocab.load_from_file(VocabType.Target, buf, SPECIAL_WORDS_ONLY_OOV)
    assert loaded.word_to_index == vocab.word_to_index
    assert loaded.index_to_word == vocab.index_to_word
    assert loaded.size == vocab.size


def test_save_strips_specials_reference_layout():
    # The on-disk layout must match the reference exactly: three pickles,
    # specials stripped (reference vocabularies.py:57-66).
    vocab = Vocab(VocabType.Token, ['a', 'b'], SPECIAL_WORDS_JOINED_OOV_PAD)
    buf = io.BytesIO()
    vocab.save_to_file(buf)
    buf.seek(0)
    word_to_index = pickle.load(buf)
    index_to_word = pickle.load(buf)
    size = pickle.load(buf)
    assert word_to_index == {'a': 1, 'b': 2}
    assert index_to_word == {1: 'a', 2: 'b'}
    assert size == 2


def test_load_wrong_policy_raises():
    vocab = Vocab(VocabType.Token, ['a'], SPECIAL_WORDS_SEPARATE_OOV_PAD)
    buf = io.BytesIO()
    vocab.save_to_file(buf)
    buf.seek(0)
    with pytest.raises(ValueError):
        Vocab.load_from_file(VocabType.Token, buf, SPECIAL_WORDS_JOINED_OOV_PAD)


def _write_dict_c2v(path, token_counts, path_counts, target_counts, n=7):
    with open(path, 'wb') as f:
        pickle.dump(token_counts, f)
        pickle.dump(path_counts, f)
        pickle.dump(target_counts, f)
        pickle.dump(n, f)


def test_code2vec_vocabs_from_freq_dicts(tmp_path):
    prefix = tmp_path / 'data'
    _write_dict_c2v(str(prefix) + '.dict.c2v',
                    {'tok1': 5, 'tok2': 3}, {'p1': 4}, {'t1': 9, 't2': 2})
    config = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0)
    vocabs = Code2VecVocabs(config)
    assert vocabs.token_vocab.size == 3   # 1 special + 2
    assert vocabs.path_vocab.size == 2
    assert vocabs.target_vocab.size == 3
    # joined policy by default: PAD == OOV == index 0 for all three
    assert vocabs.token_vocab.pad_index == 0
    assert vocabs.target_vocab.oov_index == 0


def test_code2vec_vocabs_save_and_reload(tmp_path):
    prefix = tmp_path / 'data'
    _write_dict_c2v(str(prefix) + '.dict.c2v',
                    {'tok1': 5}, {'p1': 4}, {'t1': 9})
    config = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0)
    vocabs = Code2VecVocabs(config)
    model_dir = tmp_path / 'model'
    model_dir.mkdir()
    sidecar = Config.get_vocabularies_path_from_model_path(
        str(model_dir / 'saved_model'))
    vocabs.save(sidecar)

    config2 = Config(MODEL_LOAD_PATH=str(model_dir / 'saved_model'),
                     VERBOSE_MODE=0)
    vocabs2 = Code2VecVocabs(config2)
    assert vocabs2.token_vocab.word_to_index == vocabs.token_vocab.word_to_index
    assert vocabs2.path_vocab.word_to_index == vocabs.path_vocab.word_to_index
    assert vocabs2.target_vocab.word_to_index == vocabs.target_vocab.word_to_index
    # content hash must be stable across the save/load round trip, or the
    # token cache would needlessly rebuild on every resume/fine-tune run
    assert vocabs2.content_hash() == vocabs.content_hash()


def test_index_to_word_array():
    vocab = Vocab(VocabType.Token, ['a', 'b'], SPECIAL_WORDS_JOINED_OOV_PAD)
    arr = vocab.index_to_word_array()
    assert list(arr) == ['<PAD_OR_OOV>', 'a', 'b']
