"""CPU guard on the serving engine's throughput win (ISSUE 4 acceptance):
on a synthetic concurrent request stream the engine must sustain >= 5x
the naive per-request ``model.predict`` loop, with ZERO XLA compiles
after warmup (asserted via the telemetry jit-compile counter). The real
numbers are captured by ``benchmarks/bench_serving.py`` at full size."""
import time

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
from tests.test_train_overfit import make_dataset

LINE_POOL = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0',
    'close|d tokd0,pB,tokd1 tokd1,pC,tokd2 tokd0,pA,tokd2',
]


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving_bench'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,64')
    return Code2VecModel(config)


def make_requests(n=64, seed=0):
    """Ragged 1-4 line requests, the shape of concurrent REPL traffic."""
    rng = np.random.default_rng(seed)
    return [[LINE_POOL[int(i)] for i in
             rng.integers(0, len(LINE_POOL), int(rng.integers(1, 5)))]
            for _ in range(n)]


def test_engine_beats_naive_loop_5x_with_zero_postwarm_compiles(model):
    requests = make_requests()
    n_lines = sum(len(r) for r in requests)

    core.reset()
    core.enable()
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')

        # ---- naive loop, warmed: every request size pads to bucket 8,
        # so one warm call covers the whole measured loop
        model.predict(requests[0])
        naive_t0 = time.perf_counter()
        naive_results = [model.predict(lines) for lines in requests]
        naive_s = time.perf_counter() - naive_t0

        # ---- engine, warmed ladder; snapshot the compile counter AFTER
        # warmup — the measured load must add nothing to it
        with model.serving_engine(tiers=('topk',),
                                  max_delay_ms=2.0) as engine:
            warm_compiles = compiles.value
            engine_t0 = time.perf_counter()
            futures = [engine.submit(lines, tier='topk')
                       for lines in requests]
            engine_results = [f.result(timeout=120) for f in futures]
            engine_s = time.perf_counter() - engine_t0
            postwarm_compiles = compiles.value - warm_compiles
            stats = engine.stats()
    finally:
        core.disable()
        core.reset()

    assert postwarm_compiles == 0, (
        '%d XLA compiles during the post-warmup serving load (stats=%r)'
        % (postwarm_compiles, stats))
    # every request answered, in shape
    assert [len(r) for r in engine_results] == \
        [len(r) for r in naive_results] == [len(r) for r in requests]
    for served, direct in zip(engine_results, naive_results):
        for s, d in zip(served, direct):
            assert s.topk_predicted_words == d.topk_predicted_words
    # the engine coalesced: far fewer device dispatches than requests
    assert stats['batches_total'] < len(requests) / 2
    naive_rps = len(requests) / naive_s
    engine_rps = len(requests) / engine_s
    assert engine_rps >= 5.0 * naive_rps, (
        'engine %.1f req/s (%d lines in %.3fs, %d batches) vs naive '
        '%.1f req/s (%.3fs): below the 5x floor'
        % (engine_rps, n_lines, engine_s, stats['batches_total'],
           naive_rps, naive_s))
