"""CPU guard on the serving engine's throughput win (ISSUE 4 acceptance):
on a synthetic concurrent request stream the engine must sustain >= 5x
the naive per-request ``model.predict`` loop, with ZERO XLA compiles
after warmup (asserted via the telemetry jit-compile counter). The real
numbers are captured by ``benchmarks/bench_serving.py`` at full size."""
import time

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
from tests.test_train_overfit import make_dataset

LINE_POOL = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0',
    'close|d tokd0,pB,tokd1 tokd1,pC,tokd2 tokd0,pA,tokd2',
]


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving_bench'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,64')
    return Code2VecModel(config)


def make_requests(n=64, seed=0):
    """Ragged 1-4 line requests, the shape of concurrent REPL traffic."""
    rng = np.random.default_rng(seed)
    return [[LINE_POOL[int(i)] for i in
             rng.integers(0, len(LINE_POOL), int(rng.integers(1, 5)))]
            for _ in range(n)]


def test_engine_beats_naive_loop_5x_with_zero_postwarm_compiles(model):
    requests = make_requests()
    n_lines = sum(len(r) for r in requests)

    core.reset()
    core.enable()
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')

        # ---- naive loop, warmed: every request size pads to bucket 8,
        # so one warm call covers the whole measured loop
        model.predict(requests[0])
        naive_t0 = time.perf_counter()
        naive_results = [model.predict(lines) for lines in requests]
        naive_s = time.perf_counter() - naive_t0

        # ---- engine, warmed ladder; snapshot the compile counter AFTER
        # warmup — the measured load must add nothing to it
        with model.serving_engine(tiers=('topk',),
                                  max_delay_ms=2.0) as engine:
            warm_compiles = compiles.value
            engine_t0 = time.perf_counter()
            futures = [engine.submit(lines, tier='topk')
                       for lines in requests]
            engine_results = [f.result(timeout=120) for f in futures]
            engine_s = time.perf_counter() - engine_t0
            postwarm_compiles = compiles.value - warm_compiles
            stats = engine.stats()
    finally:
        core.disable()
        core.reset()

    assert postwarm_compiles == 0, (
        '%d XLA compiles during the post-warmup serving load (stats=%r)'
        % (postwarm_compiles, stats))
    # every request answered, in shape
    assert [len(r) for r in engine_results] == \
        [len(r) for r in naive_results] == [len(r) for r in requests]
    for served, direct in zip(engine_results, naive_results):
        for s, d in zip(served, direct):
            assert s.topk_predicted_words == d.topk_predicted_words
    # the engine coalesced: far fewer device dispatches than requests
    assert stats['batches_total'] < len(requests) / 2
    naive_rps = len(requests) / naive_s
    engine_rps = len(requests) / engine_s
    assert engine_rps >= 5.0 * naive_rps, (
        'engine %.1f req/s (%d lines in %.3fs, %d batches) vs naive '
        '%.1f req/s (%.3fs): below the 5x floor'
        % (engine_rps, n_lines, engine_s, stats['batches_total'],
           naive_rps, naive_s))


# ------------------------------------------------- ISSUE 8: tracing
def _span_sequence_cost_per_request(reps=2000):
    """Seconds/request of the EXACT span sequence the engine records per
    request at the default sample rate (memory-only tracer), tight-
    looped.  This is the systematic tracing cost, measured without the
    engine's condvar round trips — a noise-free estimator of the same
    quantity the A/B windows estimate."""
    from code2vec_tpu.telemetry.tracing import Tracer
    tracer = Tracer(None, sample_rate=0.01)
    t0 = time.perf_counter()
    for _ in range(reps):
        trace = tracer.begin('serving.request',
                             attrs={'tier': 'topk', 'rows': 2,
                                    'deadline_ms': None})
        now = time.perf_counter()
        trace.span_at('serving.admission', now, now)
        trace.span_at('serving.tokenize', now, now)
        queue = trace.span('serving.queue_wait')
        trace.end(queue)
        trace.span_at('serving.coalesce', now, now,
                      attrs={'requests': 1, 'overlaps': 'queue_wait'})
        trace.span_at('serving.pack', now, now,
                      attrs={'bucket': 8, 'capacity': 16,
                             'batch_rows': 2, 'tier': 'topk'})
        trace.span_at('serving.h2d', now, now)
        trace.span_at('serving.dispatch', now, now,
                      attrs={'shadow': False})
        dev = trace.span_at('serving.device_execute', now, now)
        trace.span_at('serving.fetch', now, now, parent=dev)
        trace.span_at('serving.decode', now, now)
        trace.span_at('serving.deliver', now, now, attrs={'rows': 2})
        trace.finish(status='ok')
    return (time.perf_counter() - t0) / reps


def test_tracing_default_rate_overhead_under_3pct(model):
    """Tracing at the DEFAULT sample rate must cost < 3% requests/sec
    vs TRACING_SAMPLE_RATE=0.  Two estimators of the same overhead:
    interleaved A/B windows (bench_telemetry_overhead.py methodology —
    min window per arm), and the tight-looped span-sequence cost
    against the per-request floor.  Scheduler jitter on the engine's
    condvar round trips can only inflate the A/B estimate (both arms
    ride identical thread paths), so the SMALLER estimate is the honest
    one — a real >=3% cost would show in both."""
    requests = make_requests(n=12, seed=3)
    engines = {
        'off': model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                    tracing_sample_rate=0.0),
        'on': model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                   tracing_sample_rate=0.01),
    }
    try:
        assert engines['off']._tracer is None
        assert engines['on']._tracer is not None
        for engine in engines.values():  # warm both paths end to end
            for lines in requests[:4]:
                engine.predict(lines, timeout=60)
        walls = {'off': [], 'on': []}
        for _rep in range(8):
            # interleaved arms decorrelate slow machine-state drift
            for label, engine in engines.items():
                t0 = time.perf_counter()
                for lines in requests:
                    engine.predict(lines, timeout=60)
                walls[label].append(time.perf_counter() - t0)
    finally:
        for engine in engines.values():
            engine.close()
    off, on = min(walls['off']), min(walls['on'])
    ab_overhead = (on - off) / off
    per_request_floor = off / len(requests)
    direct_overhead = _span_sequence_cost_per_request() \
        / per_request_floor
    overhead = min(ab_overhead, direct_overhead)
    assert overhead < 0.03, (
        'tracing at the default sample rate costs %.1f%% requests/sec '
        '(A/B %.1f%%: off %.3fs vs on %.3fs per %d-request window; '
        'direct span-sequence cost %.1f%% of the %.2fms/request floor)'
        % (100 * overhead, 100 * ab_overhead, off, on, len(requests),
           100 * direct_overhead, 1e3 * per_request_floor))


def test_span_log_reports_p50_p99_per_phase(model, tmp_path):
    """The bench's span-log route: a fully-captured stream yields
    per-phase p50/p99 (not just requests/sec) through the
    scripts/latency_report.py helpers."""
    import os
    import sys
    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scripts_dir = os.path.join(REPO, 'scripts')
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import latency_report

    from code2vec_tpu.telemetry.tracing import Tracer
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    requests = make_requests(n=24, seed=5)
    with model.serving_engine(tiers=('topk',), max_delay_ms=2.0,
                              tracer=tracer) as engine:
        futures = [engine.submit(lines, tier='topk')
                   for lines in requests]
        for future in futures:
            future.result(timeout=120)
    records = latency_report.load_spans(str(tmp_path / 'spans.jsonl'))
    traces = latency_report.group_traces(records)
    assert len(traces) == len(requests)
    rows = latency_report.phase_rows(traces)
    phases = {phase for (phase, _tier, _bucket, _replica) in rows}
    assert {'serving.request', 'serving.queue_wait', 'serving.pack',
            'serving.device_execute', 'serving.decode',
            'serving.deliver'} <= phases, phases
    # per-phase percentiles are well-formed and cover every request
    for (phase, tier, _bucket, _replica), durs in rows.items():
        assert tier == 'topk'
        p50 = latency_report.percentile(durs, 0.50)
        p99 = latency_report.percentile(durs, 0.99)
        assert 0.0 <= p50 <= p99, (phase, p50, p99)
    request_rows = [durs for (phase, _t, _b, _r), durs in rows.items()
                    if phase == 'serving.request']
    assert sum(len(durs) for durs in request_rows) == len(requests)
