"""Native C++ tokenizer: byte-identical semantics with the Python path."""
import numpy as np
import pytest

from code2vec_tpu.data import native
from code2vec_tpu.data.reader import EstimatorAction, PathContextReader

from tests.test_reader import small_setup  # noqa: F401  (fixture)

pytestmark = pytest.mark.skipif(not native.is_available(),
                                reason='native toolchain unavailable')


def _readers(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    py_reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    py_reader._native = None
    config_native = config
    native_reader = PathContextReader(vocabs, config_native,
                                      EstimatorAction.Train)
    native_reader._native = native.get_tokenizer(vocabs, config_native)
    return py_reader, native_reader


LINES = [
    'lbl1 s1,p1,t1 zzz,p2,t1 s2,qqq,qq  ',
    ' s1,p1,t1',                # empty label -> OOV (CSV default is OOV)
    'unknownlbl s1,p1,t1',
    'lbl2 zz,zz,zz',
    'lbl2 s2,p2,t1 s1,p1',      # malformed 2-part context
    'lbl1 ,, s1,p1,t1',         # empty parts
    'onlylabel',
    'lbl1 s1',                  # single-part context
]


def test_native_matches_python(small_setup):  # noqa: F811
    py_reader, native_reader = _readers(small_setup)
    py_batch = py_reader.tokenize_lines(LINES)
    native_batch = native_reader.tokenize_lines(LINES)
    np.testing.assert_array_equal(py_batch.source, native_batch.source)
    np.testing.assert_array_equal(py_batch.path, native_batch.path)
    np.testing.assert_array_equal(py_batch.target, native_batch.target)
    np.testing.assert_array_equal(py_batch.mask, native_batch.mask)
    np.testing.assert_array_equal(py_batch.label, native_batch.label)


def test_native_used_in_full_epoch(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    with open(str(prefix) + '.train.c2v', 'w') as f:
        f.write('lbl1 s1,p1,t1\nlbl2 s2,p2,t1\nunknown s1,p1,t1\n' * 10)
    py_reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    py_reader._native = None
    native_reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    native_reader._native = native.get_tokenizer(vocabs, config)
    py_batches = list(py_reader.iter_epoch(shuffle=False))
    native_batches = list(native_reader.iter_epoch(shuffle=False))
    assert len(py_batches) == len(native_batches)
    for a, b in zip(py_batches, native_batches):
        np.testing.assert_array_equal(a.source, b.source)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.weight, b.weight)


def test_native_serves_the_evaluate_path(small_setup):  # noqa: F811
    """Evaluate readers use the native tokenizer for indices and retain
    only the label strings (VERDICT r1 #7) — identical batches to the
    Python path, label strings included."""
    config, vocabs, prefix = small_setup
    config.READER_USE_NATIVE = True
    with open(str(prefix) + '.val.c2v', 'w') as f:
        # 3 evaluable rows + 1 the eval filter drops (no valid context)
        f.write('lbl1 s1,p1,t1\nunknown s2,p2,t1\nlbl2 zz,zz,zz\n'
                'lbl2 s2,p1,t1\n')
    config.TEST_DATA_PATH = str(prefix) + '.val.c2v'

    native_reader = PathContextReader(vocabs, config,
                                      EstimatorAction.Evaluate)
    assert native_reader._native is not None  # no Python fallback for eval
    assert native_reader.keep_label_strings
    assert not native_reader.keep_context_strings
    py_reader = PathContextReader(vocabs, config, EstimatorAction.Evaluate)
    py_reader._native = None

    py_batches = list(py_reader.iter_epoch(shuffle=False))
    native_batches = list(native_reader.iter_epoch(shuffle=False))
    assert len(py_batches) == len(native_batches) == 2
    for a, b in zip(py_batches, native_batches):
        np.testing.assert_array_equal(a.source, b.source)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.label, b.label)
        np.testing.assert_array_equal(a.weight, b.weight)
        np.testing.assert_array_equal(a.label_strings, b.label_strings)
        assert b.source_strings is None  # predict-only payload

    # predict still carries the full string payload (attention display)
    predict_reader = PathContextReader(vocabs, config,
                                       EstimatorAction.Predict)
    assert predict_reader._native is None
    batch = predict_reader.process_input_rows(['lbl1 s1,p1,t1'])
    assert batch.source_strings is not None


def test_native_multithreaded_large_batch(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    tokenizer = native.get_tokenizer(vocabs, config)
    lines = ['lbl1 s1,p1,t1 s2,p2,t1'] * 500  # > threading threshold
    batch = tokenizer.tokenize_lines(lines)
    assert batch.source.shape == (500, config.MAX_CONTEXTS)
    assert (batch.mask[:, :2] == 1.0).all()
    assert (batch.mask[:, 2:] == 0.0).all()
