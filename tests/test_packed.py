"""Packed wire format (data/packed.py): the round trip is BIT-exact.

Property tests over randomized plane batches — interior all-PAD holes,
PAD-filled tails, zero-weight padding rows, nonzero PAD indices
(SEPARATE_OOV_AND_PAD-style), per-shard packing — against both the numpy
reference inverse and the jitted device unpack; plus the trainer
integration (packed vs plane steps produce identical losses, params and
eval outputs on the 8-virtual-device mesh) and the direct per-device
placement path of shard_batch."""
import numpy as np
import pytest

from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.data.reader import (Batch, EstimatorAction,
                                      PathContextReader, context_valid_mask)

from tests.test_reader import small_setup, _write_train  # noqa: F401
from tests.test_stage_batches import make_batches, make_trainer


def random_plane_batch(rng, batch_size, contexts, token_pad=0, path_pad=0,
                       hole_rate=0.3, pad_row_rate=0.2):
    """A Batch with every structural corner the reader can produce:
    random per-row effective lengths, interior holes (slots whose three
    parts are all PAD — mask 0 mid-row), and zero-weight padding rows
    filled exactly like reader._pad_batch fills them."""
    source = rng.integers(0, 30, (batch_size, contexts)).astype(np.int32)
    path = rng.integers(0, 14, (batch_size, contexts)).astype(np.int32)
    target = rng.integers(0, 30, (batch_size, contexts)).astype(np.int32)
    holes = rng.random((batch_size, contexts)) < hole_rate
    lengths = rng.integers(0, contexts + 1, (batch_size,))
    tail = np.arange(contexts)[None, :] >= lengths[:, None]
    weight = (rng.random((batch_size,)) > pad_row_rate).astype(np.float32)
    label = rng.integers(0, 10, (batch_size,)).astype(np.int32)
    for dead in (holes, tail, (weight == 0)[:, None] & np.ones(
            (1, contexts), bool)):
        source[dead] = token_pad
        path[dead] = path_pad
        target[dead] = token_pad
    label[weight == 0] = 0
    mask = context_valid_mask(source, path, target, token_pad, path_pad)
    return Batch(source=source, path=path, target=target, mask=mask,
                 label=label, weight=weight)


def assert_batches_bit_equal(a: Batch, b: Batch):
    for name in ('source', 'path', 'target', 'mask', 'label', 'weight'):
        np.testing.assert_array_equal(getattr(a, name), getattr(b, name),
                                      err_msg=name)


class TestRoundTrip:
    @pytest.mark.parametrize('token_pad,path_pad', [(0, 0), (1, 2)])
    @pytest.mark.parametrize('data_shards', [1, 2, 4])
    def test_host_round_trip_property(self, token_pad, path_pad,
                                      data_shards):
        rng = np.random.default_rng(7)
        for trial in range(25):
            contexts = int(rng.choice([3, 5, 8, 13]))
            batch = random_plane_batch(rng, 8, contexts, token_pad,
                                       path_pad)
            packed = packed_lib.pack_batch(batch, token_pad, path_pad,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            restored = packed_lib.unpack_batch_host(packed, contexts,
                                                    token_pad, path_pad)
            assert_batches_bit_equal(batch, restored)

    @pytest.mark.parametrize('data_shards', [1, 4])
    def test_device_unpack_matches_planes_bit_exactly(self, data_shards):
        import jax

        rng = np.random.default_rng(11)
        for trial in range(10):
            batch = random_plane_batch(rng, 8, 6, 1, 2)
            packed = packed_lib.pack_batch(batch, 1, 2,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            unpack = jax.jit(lambda c, n: packed_lib.unpack_device(
                c, n, 6, 1, 2))
            source, path, target, mask = unpack(packed.ctx, packed.count)
            np.testing.assert_array_equal(np.asarray(source), batch.source)
            np.testing.assert_array_equal(np.asarray(path), batch.path)
            np.testing.assert_array_equal(np.asarray(target), batch.target)
            np.testing.assert_array_equal(np.asarray(mask), batch.mask)

    def test_capacity_smaller_than_batch(self):
        """More examples than context rows (sparse batch: most rows
        empty) — the unpack's index bookkeeping must follow the (B,)
        example axis, not the capacity axis (regression: eval of a tiny
        corpus at B=1024 crashed the packed unpack)."""
        import jax

        contexts = 6
        batch_size = 64
        rng = np.random.default_rng(2)
        batch = random_plane_batch(rng, batch_size, contexts)
        lengths = np.zeros((batch_size,), np.int64)
        lengths[:4] = [1, 2, 0, 3]  # everything else fully empty
        dead = np.arange(contexts)[None, :] >= lengths[:, None]
        source = batch.source.copy(); source[dead] = 0
        path = batch.path.copy(); path[dead] = 0
        target = batch.target.copy(); target[dead] = 0
        mask = context_valid_mask(source, path, target, 0, 0)
        batch = batch._replace(source=source, path=path, target=target,
                               mask=mask)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] < batch_size
        restored = packed_lib.unpack_batch_host(packed, contexts, 0, 0)
        assert_batches_bit_equal(batch, restored)
        out = jax.jit(lambda c, n: packed_lib.unpack_device(
            c, n, contexts, 0, 0))(packed.ctx, packed.count)
        np.testing.assert_array_equal(np.asarray(out[0]), batch.source)
        np.testing.assert_array_equal(np.asarray(out[3]), batch.mask)

    def test_all_padding_batch(self):
        """The multi-host eval filler shape: every row weight 0."""
        contexts = 5
        zero = Batch(source=np.zeros((4, contexts), np.int32),
                     path=np.zeros((4, contexts), np.int32),
                     target=np.zeros((4, contexts), np.int32),
                     mask=np.zeros((4, contexts), np.float32),
                     label=np.zeros((4,), np.int32),
                     weight=np.zeros((4,), np.float32))
        packed = packed_lib.pack_batch(zero, 0, 0, capacity_minimum=4)
        assert packed.num_valid_examples == 0
        restored = packed_lib.unpack_batch_host(packed, contexts, 0, 0)
        assert_batches_bit_equal(zero, restored)

    def test_string_fields_ride_along(self):
        rng = np.random.default_rng(3)
        batch = random_plane_batch(rng, 4, 3)._replace(
            label_strings=np.array(['a', 'b', 'c', 'd'], dtype=object))
        packed = packed_lib.pack_batch(batch, 0, 0)
        assert packed.label_strings is batch.label_strings
        restored = packed_lib.unpack_batch_host(packed, 3, 0, 0)
        assert restored.label_strings is batch.label_strings


def test_bucketed_capacity_properties():
    minimum = 64
    for total in (0, 1, 63, 64, 65, 511, 512, 8191, 30720, 1 << 20):
        cap = packed_lib.bucketed_capacity(total, minimum)
        assert cap >= max(total, minimum)
        # waste bounded: bucket is ~total/8
        assert cap <= max(total * 1.25 + minimum, minimum)
    # bucketing collapses nearby totals to one capacity (bounded jit
    # specializations)
    caps = {packed_lib.bucketed_capacity(t) for t in range(30000, 33000)}
    assert len(caps) <= 2


def test_wire_bytes_shrink_at_realistic_fill():
    from code2vec_tpu import benchlib
    shapes = benchlib.BenchShapes(token_vocab=1000, path_vocab=1000,
                                  target_vocab=500, batch_size=256,
                                  max_contexts=64)
    batch = benchlib.random_batches(shapes, 1, seed=0, fill=0.25)[0]
    packed = packed_lib.pack_batch(batch, 0, 0)
    assert packed_lib.wire_bytes(packed) <= \
        0.5 * packed_lib.wire_bytes(batch)


class TestTrainerIntegration:
    """Packed and plane wires must be indistinguishable past the device
    unpack: identical losses, updated params, and eval/predict outputs,
    on the full 8-virtual-device data-parallel mesh.

    These tests pin USE_PALLAS_RAGGED_FUSION=False: they assert the
    UNPACK path's defining property — bit-exactness against the plane
    wire — which the (now default-ON) ragged fused encoder trades for
    fp32-rounding parity (tests/test_pallas_ragged.py owns that
    regime)."""

    def _batches_and_packed(self, trainer, n=3):
        rng = np.random.default_rng(5)
        batches = []
        for _ in range(n):
            batch = random_plane_batch(rng, 8, 4, pad_row_rate=0.1)
            # trainer vocab sizes are small; clamp labels into range
            batch = batch._replace(
                label=np.clip(batch.label, 0, 15).astype(np.int32))
            batches.append(batch)
        shards = trainer.mesh.shape['data']
        packed = [packed_lib.pack_batch(b, 0, 0, data_shards=shards,
                                        capacity_minimum=4)
                  for b in batches]
        return batches, packed

    def test_train_steps_bit_equal(self):
        import jax

        trainer = make_trainer(USE_PALLAS_RAGGED_FUSION=False)
        batches, packed = self._batches_and_packed(trainer)
        state_a = trainer.init_state(seed=0)
        state_b = trainer.init_state(seed=0)
        for batch, pb in zip(batches, packed):
            state_a, loss_a = trainer.train_step(state_a, batch)
            state_b, loss_b = trainer.train_step(state_b, pb)
            assert float(loss_a) == float(loss_b)
        for leaf_a, leaf_b in zip(
                jax.tree_util.tree_leaves(state_a.params),
                jax.tree_util.tree_leaves(state_b.params)):
            np.testing.assert_array_equal(np.asarray(leaf_a),
                                          np.asarray(leaf_b))

    def test_eval_and_predict_outputs_equal(self):
        trainer = make_trainer(USE_PALLAS_RAGGED_FUSION=False)
        batches, packed = self._batches_and_packed(trainer, n=1)
        params = trainer.init_state(seed=1).params
        out_planes = trainer.eval_step(params, batches[0])
        out_packed = trainer.eval_step(params, packed[0])
        np.testing.assert_array_equal(
            np.asarray(out_planes['topk_indices']),
            np.asarray(out_packed['topk_indices']))
        assert float(out_planes['loss_sum']) == \
            float(out_packed['loss_sum'])
        assert float(out_planes['weight_sum']) == \
            float(out_packed['weight_sum'])
        pred_planes = trainer.predict_step(params, batches[0])
        pred_packed = trainer.predict_step(params, packed[0])
        # the two packed programs differ in capacity (predict_step packs
        # with the default bucket) — XLA may fuse the float softmax a ulp
        # apart across programs even though the unpacked int planes are
        # bit-equal (asserted in TestRoundTrip); compare to float32 ulp
        np.testing.assert_allclose(
            np.asarray(pred_planes['attention']),
            np.asarray(pred_packed['attention']), rtol=1e-6, atol=0)

    def test_staged_fit_loop_runs_on_packed(self):
        """stage_batches -> train_step_placed end to end over packed
        batches (the fit() hot path), donation enabled (the default)."""
        trainer = make_trainer(DEVICE_PREFETCH_BATCHES=2,
                               USE_PALLAS_RAGGED_FUSION=False)
        _batches, packed = self._batches_and_packed(trainer, n=4)
        state = trainer.init_state(seed=0)
        steps = 0
        for arrays, host_batch in trainer.stage_batches(iter(packed)):
            assert len(arrays) == 4
            assert host_batch.num_valid_examples >= 0
            state, loss = trainer.train_step_placed(state, arrays)
            steps += 1
        assert steps == 4
        assert np.isfinite(float(loss))

    def test_mismatched_shard_count_raises(self):
        trainer = make_trainer()
        rng = np.random.default_rng(9)
        batch = random_plane_batch(rng, 8, 4)
        wrong = packed_lib.pack_batch(batch, 0, 0, data_shards=2,
                                      capacity_minimum=4)
        with pytest.raises(ValueError, match='data_shards'):
            trainer.train_step(trainer.init_state(seed=0), wrong)


def test_shard_batch_direct_matches_default():
    """The staging ring's per-device direct placement must produce the
    same values and shardings as the whole-array path."""
    import jax

    from code2vec_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.create_mesh()
    rng = np.random.default_rng(0)
    arrays = (rng.integers(0, 99, (16, 4)).astype(np.int32),       # planes
              rng.integers(0, 99, (8, 12, 3)).astype(np.int32),    # packed
              rng.random((16,)).astype(np.float32))
    default = mesh_lib.shard_batch(arrays, mesh)
    direct = mesh_lib.shard_batch(arrays, mesh, direct=True)
    for a, b in zip(default, direct):
        assert a.sharding.is_equivalent_to(b.sharding, np.ndim(a))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # placed arrays behave as jit inputs identically
    summed = jax.jit(lambda x: x.sum())(direct[1])
    assert int(summed) == int(arrays[1].sum())


def test_reader_emits_packed_wire(small_setup):  # noqa: F811
    """reader.iter_epoch(wire_format='packed') must mirror the planes
    stream batch-for-batch (same filter semantics, same short-final-batch
    padding) through the host unpack."""
    config, vocabs, prefix = small_setup
    _write_train(prefix, [
        'lbl1 s1,p1,t1 zzz,p2,t1',   # kept (train filter)
        'unknown s1,p1,t1',          # dropped: OOV target
        'lbl2 zz,zz,zz',             # dropped: no valid contexts
        'lbl2 s2,p2,t1',             # kept
        'lbl1 s1,p2,t1',             # kept -> short final batch, padded
    ])
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    planes = list(reader.iter_epoch(shuffle=False))
    packed = list(reader.iter_epoch(shuffle=False, wire_format='packed'))
    assert len(planes) == len(packed)
    assert all(isinstance(p, packed_lib.PackedBatch) for p in packed)
    token_pad = vocabs.token_vocab.pad_index
    path_pad = vocabs.path_vocab.pad_index
    for plane_batch, packed_batch in zip(planes, packed):
        assert_batches_bit_equal(
            plane_batch,
            packed_lib.unpack_batch_host(packed_batch, config.MAX_CONTEXTS,
                                         token_pad, path_pad))
    # the padded tail row survives as weight 0 / count 0
    assert packed[-1].weight[-1] == 0.0
    assert packed[-1].count[-1] == 0


def test_eval_reader_packed_keeps_label_strings(small_setup):  # noqa: F811
    config, vocabs, prefix = small_setup
    with open(str(prefix) + '.test.c2v', 'w') as f:
        f.write('lbl1 s1,p1,t1\nlbl2 s2,p2,t1\n')
    config.TEST_DATA_PATH = str(prefix) + '.test.c2v'
    reader = PathContextReader(vocabs, config, EstimatorAction.Evaluate)
    packed = list(reader.iter_epoch(shuffle=False, wire_format='packed'))
    assert packed and packed[0].label_strings is not None
    assert list(packed[0].label_strings) == ['lbl1', 'lbl2']
