import json

from code2vec_tpu.config import Config
from code2vec_tpu.metrics_writer import MetricsWriter, maybe_create


def test_scalars_append_jsonl(tmp_path):
    writer = MetricsWriter(str(tmp_path / 'logs'))
    writer.scalar('train/loss', 1.5, 10)
    writer.scalar('eval/f1', 0.25, 1)
    writer.close()
    lines = (tmp_path / 'logs' / 'metrics.jsonl').read_text().splitlines()
    records = [json.loads(line) for line in lines]
    assert records[0]['tag'] == 'train/loss'
    assert records[0]['value'] == 1.5
    assert records[0]['step'] == 10
    assert records[1]['tag'] == 'eval/f1'


def test_maybe_create_respects_flag(tmp_path):
    config = Config(TRAIN_DATA_PATH_PREFIX='x', USE_TENSORBOARD=False)
    assert maybe_create(config) is None
    config2 = Config(TRAIN_DATA_PATH_PREFIX='x', USE_TENSORBOARD=True,
                     MODEL_SAVE_PATH=str(tmp_path / 'm' / 'saved'))
    writer = maybe_create(config2)
    assert writer is not None
    assert writer.logdir == str(tmp_path / 'm' / 'summaries')
    writer.close()


def test_append_mode_survives_reopen(tmp_path):
    logdir = str(tmp_path / 'logs')
    w1 = MetricsWriter(logdir)
    w1.scalar('a', 1.0, 1)
    w1.close()
    w2 = MetricsWriter(logdir)
    w2.scalar('a', 2.0, 2)
    w2.close()
    lines = (tmp_path / 'logs' / 'metrics.jsonl').read_text().splitlines()
    assert len(lines) == 2


def test_writes_are_buffered_until_threshold_or_flush(tmp_path):
    path = tmp_path / 'logs' / 'metrics.jsonl'
    writer = MetricsWriter(str(tmp_path / 'logs'), buffer_records=3)
    writer.scalar('a', 1.0, 1)
    writer.scalar('a', 2.0, 2)
    assert not path.exists()          # buffered: no per-scalar I/O
    writer.scalar('a', 3.0, 3)        # hits the threshold
    assert len(path.read_text().splitlines()) == 3
    writer.scalar('a', 4.0, 4)
    writer.flush()                    # explicit flush drains the tail
    assert len(path.read_text().splitlines()) == 4
    writer.close()


def test_context_manager_flushes_on_exit(tmp_path):
    path = tmp_path / 'logs' / 'metrics.jsonl'
    with MetricsWriter(str(tmp_path / 'logs')) as writer:
        writer.scalar('a', 1.0, 1)
        assert not path.exists()
    assert len(path.read_text().splitlines()) == 1


def test_close_is_idempotent(tmp_path):
    writer = MetricsWriter(str(tmp_path / 'logs'))
    writer.scalar('a', 1.0, 1)
    writer.close()
    writer.close()
    lines = (tmp_path / 'logs' / 'metrics.jsonl').read_text().splitlines()
    assert len(lines) == 1


def test_atexit_flush_covers_unclosed_writers(tmp_path):
    path = tmp_path / 'logs' / 'metrics.jsonl'
    writer = MetricsWriter(str(tmp_path / 'logs'))
    writer.scalar('a', 1.0, 1)
    assert not path.exists()
    writer._atexit_flush()            # what interpreter exit would run
    assert len(path.read_text().splitlines()) == 1
    writer.close()


def test_write_failure_is_logged_once_not_fatal(tmp_path):
    """ISSUE 3 satellite: a failing metrics append (read-only/full disk)
    must neither crash the training run nor be swallowed silently — the
    first failure warns, close() reports the dropped total.

    Records are captured with a handler on the module logger directly:
    Config.get_logger pins ``code2vec_tpu``.propagate=False, so once any
    earlier test built a Config, caplog's root handler never sees these
    records (ordering-dependent flake otherwise)."""
    import logging

    records = []
    handler = logging.Handler()
    handler.emit = records.append
    module_logger = logging.getLogger('code2vec_tpu.metrics_writer')
    module_logger.addHandler(handler)
    old_level = module_logger.level
    module_logger.setLevel(logging.WARNING)
    try:
        writer = MetricsWriter(str(tmp_path / 'logs'), buffer_records=1)
        # point the stream at a DIRECTORY: every append raises OSError
        writer._path = str(tmp_path / 'logs')
        writer.scalar('a', 1.0, 1)   # must not raise
        writer.scalar('a', 2.0, 2)   # second failure: silent
        warnings = [r for r in records if 'DROPPED' in r.getMessage()]
        assert len(warnings) == 1
        records.clear()
        writer.close()
        assert any('2 record(s) dropped' in r.getMessage()
                   for r in records)
    finally:
        module_logger.removeHandler(handler)
        module_logger.setLevel(old_level)
