"""Mesh transport units (serving/transport.py, ISSUE 14): the framed
wire's integrity contract — a worker dying mid-write (partial frame) or
stream corruption fails TYPED (``WireError``) instead of poisoning the
receiver — over both carriers (pipe and TCP), plus the socket listener's
rid-keyed handshake."""
import multiprocessing
import socket
import threading

import pytest

from code2vec_tpu.serving import transport as transport_lib
from code2vec_tpu.serving.errors import WireError


# ------------------------------------------------------------- framing
def test_frame_roundtrip():
    message = ('dispatch', 7, 'topk', [b'payload', {'k': 1}])
    assert transport_lib.decode_frame(
        transport_lib.encode_frame(message)) == message


def test_truncated_frame_fails_typed():
    frame = transport_lib.encode_frame(('result', 3, ['x'] * 100))
    # a mid-write death can cut anywhere: header, or inside the payload
    for cut in (1, 5, transport_lib._HEADER_LEN + 3, len(frame) - 1):
        with pytest.raises(WireError, match='truncated'):
            transport_lib.decode_frame(frame[:cut])


def test_corrupted_frame_fails_typed():
    frame = bytearray(transport_lib.encode_frame(('result', 3, 'data')))
    frame[-1] ^= 0xFF  # payload bit-flip: CRC catches it
    with pytest.raises(WireError, match='CRC'):
        transport_lib.decode_frame(bytes(frame))
    with pytest.raises(WireError, match='magic'):
        transport_lib.decode_frame(b'XX' + bytes(frame)[2:])


def test_absurd_length_header_fails_fast():
    import struct
    header = (b'c2' + struct.pack('>II', 1 << 40 & 0xFFFFFFFF, 0))
    # craft a length just over the bound without allocating it
    bad = b'c2' + struct.pack(
        '>II', transport_lib.MAX_FRAME_BYTES + 1, 0)
    with pytest.raises(WireError, match='bound'):
        transport_lib.decode_frame(bad + b'x')
    del header


# ---------------------------------------------------------------- pipe
def test_pipe_transport_roundtrip_and_poison():
    parent, child = multiprocessing.Pipe()
    a = transport_lib.PipeTransport(parent)
    b = transport_lib.PipeTransport(child)
    a.send(('heartbeat', -1, {'inflight': 0}))
    assert b.poll(1.0)
    assert b.recv() == ('heartbeat', -1, {'inflight': 0})
    # raw garbage on the same pipe — the receiver fails TYPED, it does
    # not unpickle an attacker-shaped or half-written object
    child.send_bytes(b'not a frame at all')
    with pytest.raises(WireError):
        a.recv()
    # a partial frame (sender died mid-write of a large message)
    frame = transport_lib.encode_frame(('result', 0, list(range(1000))))
    child.send_bytes(frame[:len(frame) // 2])
    with pytest.raises(WireError, match='truncated'):
        a.recv()
    child.close()
    with pytest.raises((EOFError, OSError)):
        a.recv()
    a.close()


# -------------------------------------------------------------- socket
def test_socket_transport_roundtrip_partial_and_eof():
    left, right = socket.socketpair()
    a = transport_lib.SocketTransport(left)
    b = transport_lib.SocketTransport(right)
    big = ('dispatch', 1, 'topk', ['x' * 4096] * 16)
    a.send(big)
    a.send(('stats', 2))
    assert b.recv() == big  # reassembled across stream reads
    assert b.recv() == ('stats', 2)
    # partial frame then close: the worker died mid-write — typed
    frame = transport_lib.encode_frame(('result', 9, 'tail'))
    left.sendall(frame[:len(frame) - 3])
    left.close()
    with pytest.raises(WireError, match='mid-frame'):
        b.recv()
    # clean close at a frame boundary is a plain EOF (worker exit)
    left2, right2 = socket.socketpair()
    c = transport_lib.SocketTransport(left2)
    d = transport_lib.SocketTransport(right2)
    c.close()
    with pytest.raises(EOFError):
        d.recv()
    b.close()
    d.close()


def test_socket_listener_claims_by_rid_and_validates_hello():
    listener = transport_lib.SocketListener('127.0.0.1')
    try:
        # a spawned worker's rid is expect()ed BEFORE it dials; an
        # unregistered rid would queue for adoption instead
        listener.expect('r0')
        listener.expect('r1')
        listener.expect('rX')
        # dial out of order: r1 first, then r0 — claims are rid-keyed
        t1 = transport_lib.dial(listener.address, 'r1', pid=111)
        t0 = transport_lib.dial(listener.address, 'r0', pid=100)
        got0, hello0 = listener.claim('r0', timeout=10.0)
        got1, hello1 = listener.claim('r1', timeout=10.0)
        assert hello0['pid'] == 100 and hello1['pid'] == 111
        t0.send(('ready', {'params_step': 5}))
        assert got0.recv() == ('ready', {'params_step': 5})
        got1.send(('close', 0))
        assert t1.recv() == ('close', 0)
        # a peer speaking the wrong protocol version is rejected TYPED
        # at the hello — never claimable, even though it was expected
        bad = socket.create_connection(listener.address, timeout=5.0)
        bad_transport = transport_lib.SocketTransport(bad)
        bad_transport.send(
            ('hello', 'rX', transport_lib.WIRE_PROTO + 1, 1))
        kind, why = bad_transport.recv()[:2]
        assert kind == 'adopt_rejected' and 'proto' in why
        with pytest.raises(TimeoutError):
            listener.claim('rX', timeout=0.8)
        assert listener.rejected_total == 1
        for transport in (t0, t1, got0, got1):
            transport.close()
        bad.close()
    finally:
        listener.close()
    assert listener.closed
    # close() reaped the accept thread; a late dial is refused
    with pytest.raises(RuntimeError):
        transport_lib.dial(listener.address, 'r9', pid=9,
                           timeout=0.5, attempts=1)


def test_listener_claim_cancellable():
    listener = transport_lib.SocketListener('127.0.0.1')
    cancel = threading.Event()
    result = {}

    def wait():
        try:
            listener.claim('r0', timeout=30.0, cancel=cancel)
        except BaseException as exc:
            result['exc'] = exc

    thread = threading.Thread(target=wait)
    thread.start()
    cancel.set()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert 'cancelled' in str(result['exc'])
    listener.close()
