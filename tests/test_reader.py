import pickle

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data import (Batch, EstimatorAction, PathContextReader,
                               parse_c2v_line)
from code2vec_tpu.vocab import Code2VecVocabs


@pytest.fixture
def small_setup(tmp_path):
    """Vocab: tokens {s1,s2,t1}, paths {p1,p2}, targets {lbl1,lbl2}."""
    prefix = tmp_path / 'ds'
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump({'s1': 10, 's2': 9, 't1': 8}, f)
        pickle.dump({'p1': 7, 'p2': 6}, f)
        pickle.dump({'lbl1': 5, 'lbl2': 4}, f)
        pickle.dump(4, f)
    config = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0,
                    MAX_CONTEXTS=4, TRAIN_BATCH_SIZE=2, TEST_BATCH_SIZE=2,
                    SHUFFLE_BUFFER_SIZE=16, READER_USE_NATIVE=False)
    vocabs = Code2VecVocabs(config)
    return config, vocabs, prefix


def _write_train(prefix, lines):
    with open(str(prefix) + '.train.c2v', 'w') as f:
        f.write('\n'.join(lines) + '\n')


def test_parse_line_pads_contexts():
    row = parse_c2v_line('lbl s1,p1,t1 s2,p2,t1', 4)
    assert row.label_str == 'lbl'
    assert row.source_strs == ['s1', 's2', '', '']
    assert row.path_strs == ['p1', 'p2', '', '']
    assert row.target_strs == ['t1', 't1', '', '']


def test_parse_line_truncates_extra_contexts():
    row = parse_c2v_line('lbl a,b,c d,e,f g,h,i', 2)
    assert row.source_strs == ['a', 'd']


def test_tokenize_semantics(small_setup):
    config, vocabs, prefix = small_setup
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    # token vocab: <PAD_OR_OOV>=0, s1=1, s2=2, t1=3 (freq order)
    batch = reader.tokenize_lines(['lbl1 s1,p1,t1 zzz,p2,t1 s2,qqq,qq  '])
    np.testing.assert_array_equal(batch.source[0], [1, 0, 2, 0])
    np.testing.assert_array_equal(batch.path[0], [1, 2, 0, 0])
    np.testing.assert_array_equal(batch.target[0], [3, 3, 0, 0])
    # ctx1 fully valid; ctx2 has OOV source but valid path+target -> valid;
    # ctx3 has valid source only -> valid; ctx4 empty -> invalid.
    np.testing.assert_array_equal(batch.mask[0], [1.0, 1.0, 1.0, 0.0])
    assert batch.label[0] == vocabs.target_vocab.lookup_index('lbl1')


def test_all_oov_context_is_masked_with_joined_policy(small_setup):
    # With PAD==OOV, a context whose three parts are all out-of-vocab maps
    # to index 0 everywhere and must be masked out — the reference's
    # hashtable-default behaviour (path_context_reader.py:209-214).
    config, vocabs, prefix = small_setup
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    batch = reader.tokenize_lines(['lbl1 zz,zz,zz s1,p1,t1'])
    np.testing.assert_array_equal(batch.mask[0], [0.0, 1.0, 0.0, 0.0])


def test_train_filter_drops_oov_targets_and_empty_rows(small_setup):
    config, vocabs, prefix = small_setup
    _write_train(prefix, [
        'lbl1 s1,p1,t1',          # kept
        'unknownlbl s1,p1,t1',    # dropped: OOV target (train only)
        'lbl2 zz,zz,zz',          # dropped: no valid contexts
        'lbl2 s2,p2,t1',          # kept
    ])
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    batches = list(reader.iter_epoch(shuffle=False))
    assert len(batches) == 1
    assert batches[0].num_valid_examples == 2
    labels = set(batches[0].label.tolist())
    assert labels == {vocabs.target_vocab.lookup_index('lbl1'),
                      vocabs.target_vocab.lookup_index('lbl2')}


def test_eval_keeps_oov_targets(small_setup):
    config, vocabs, prefix = small_setup
    test_file = str(prefix) + '.val.c2v'
    with open(test_file, 'w') as f:
        f.write('unknownlbl s1,p1,t1\nlbl1 s1,p1,t1\n')
    config.TEST_DATA_PATH = test_file
    reader = PathContextReader(vocabs, config, EstimatorAction.Evaluate)
    batches = list(reader.iter_epoch(shuffle=False))
    assert len(batches) == 1
    assert batches[0].num_valid_examples == 2
    # eval keeps the label string for host-side metrics
    assert batches[0].label_strings[0] == 'unknownlbl'
    assert batches[0].label[0] == vocabs.target_vocab.oov_index


def test_final_partial_batch_is_padded_static(small_setup):
    config, vocabs, prefix = small_setup
    _write_train(prefix, [
        'lbl1 s1,p1,t1', 'lbl2 s1,p1,t1', 'lbl1 s2,p2,t1',
    ])
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    batches = list(reader.iter_epoch(shuffle=False))
    assert len(batches) == 2
    # static shape everywhere
    for batch in batches:
        assert batch.source.shape == (2, 4)
        assert batch.weight.shape == (2,)
    assert batches[1].num_valid_examples == 1
    np.testing.assert_array_equal(batches[1].weight, [1.0, 0.0])
    np.testing.assert_array_equal(batches[1].mask[1], [0, 0, 0, 0])


def test_shuffle_is_a_permutation(small_setup):
    config, vocabs, prefix = small_setup
    lines = ['lbl1 s1,p1,t1'] * 3 + ['lbl2 s2,p2,t1'] * 3
    _write_train(prefix, lines)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    all_labels = []
    for batch in reader.iter_epoch(shuffle=True, seed=0):
        all_labels.extend(batch.label[batch.weight > 0].tolist())
    assert sorted(all_labels) == sorted(
        [vocabs.target_vocab.lookup_index('lbl1')] * 3
        + [vocabs.target_vocab.lookup_index('lbl2')] * 3)


def test_prefetched_equals_sync(small_setup):
    config, vocabs, prefix = small_setup
    _write_train(prefix, ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1'] * 3)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    sync = list(reader.iter_epoch(shuffle=False))
    prefetched = list(reader.iter_epoch_prefetched(shuffle=False))
    assert len(sync) == len(prefetched)
    for a, b in zip(sync, prefetched):
        np.testing.assert_array_equal(a.source, b.source)
        np.testing.assert_array_equal(a.label, b.label)


def test_prefetched_abandoned_early_does_not_leak_thread(small_setup):
    import threading
    config, vocabs, prefix = small_setup
    config.READER_PREFETCH_BATCHES = 1
    _write_train(prefix, ['lbl1 s1,p1,t1', 'lbl2 s2,p2,t1'] * 20)
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    before = threading.active_count()
    for _ in range(5):
        it = reader.iter_epoch_prefetched(shuffle=False)
        next(it)        # take one batch...
        it.close()      # ...then abandon mid-epoch
    assert threading.active_count() <= before


def test_process_input_rows_never_filters(small_setup):
    config, vocabs, prefix = small_setup
    reader = PathContextReader(vocabs, config, EstimatorAction.Predict)
    batch = reader.process_input_rows(['unknownlbl zz,zz,zz'])
    assert batch.label.shape == (1,)
    assert batch.label_strings[0] == 'unknownlbl'
    np.testing.assert_array_equal(batch.mask[0], [0, 0, 0, 0])
