"""tier-1 guard: fault-point names cannot drift from the catalog/doc
(scripts/check_fault_points.py; ISSUE 3 satellite — same pattern as
tests/test_metrics_schema.py)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, 'scripts'))

import check_fault_points  # noqa: E402


def test_fire_regex_matches_wrapped_calls():
    content = ("faults.maybe_fire(\n    'corrupt_snapshot')\n"
               "if faults.maybe_fire('hang_input'):\n"
               "faults.maybe_fire('nan_loss', step=batch_num)\n"
               "plan.maybe_fire(point, step)  # no literal: ignored\n")
    names = [m.group(1)
             for m in check_fault_points.FIRE_RE.finditer(content)]
    assert names == ['corrupt_snapshot', 'hang_input', 'nan_loss']


def test_every_fault_site_is_cataloged_and_documented():
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts',
                                      'check_fault_points.py')],
        capture_output=True, text=True,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert result.returncode == 0, result.stdout + result.stderr


def test_every_cataloged_point_has_a_site_and_vice_versa():
    from code2vec_tpu.resilience.faults import FAULT_POINTS
    sites = check_fault_points.find_sites()
    assert sites, 'lint found no fault sites — regex broke'
    emitted = {name for _rel, _line, name in sites}
    assert emitted <= set(FAULT_POINTS)
    # every cataloged point is wired somewhere (a spec naming an unwired
    # point would silently inject nothing)
    assert set(FAULT_POINTS) <= emitted
    assert 'definitely_not_a_point' not in FAULT_POINTS
