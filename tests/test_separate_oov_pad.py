"""SEPARATE_OOV_AND_PAD=True policy end to end (reference
vocabularies.py:26-29, 204-209: tokens/paths get distinct <PAD>/<OOV>,
targets get only <OOV>)."""
import pickle

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data import native
from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
from code2vec_tpu.vocab import Code2VecVocabs


@pytest.fixture
def separate_setup(tmp_path):
    prefix = tmp_path / 'ds'
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump({'s1': 10, 's2': 9}, f)
        pickle.dump({'p1': 7}, f)
        pickle.dump({'lbl1': 5, 'lbl2': 4}, f)
        pickle.dump(4, f)
    config = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0,
                    MAX_CONTEXTS=3, TRAIN_BATCH_SIZE=2, TEST_BATCH_SIZE=2,
                    SEPARATE_OOV_AND_PAD=True, READER_USE_NATIVE=False)
    vocabs = Code2VecVocabs(config)
    return config, vocabs, prefix


def test_vocab_indices_under_separate_policy(separate_setup):
    config, vocabs, prefix = separate_setup
    assert vocabs.token_vocab.pad_index == 0
    assert vocabs.token_vocab.oov_index == 1
    assert vocabs.token_vocab.size == 4      # PAD, OOV, s1, s2
    assert vocabs.path_vocab.size == 3
    # targets: OOV only (reference vocabularies.py:207-208)
    assert vocabs.target_vocab.oov_index == 0
    assert vocabs.target_vocab.size == 3


def test_mask_distinguishes_oov_from_pad(separate_setup):
    config, vocabs, prefix = separate_setup
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    batch = reader.tokenize_lines(['lbl1 zz,zz,zz s1,p1,s2 '])
    # all-OOV context: indices are OOV(!=PAD) -> context IS valid under the
    # separate policy (unlike the joined policy where OOV==PAD)
    np.testing.assert_array_equal(batch.mask[0], [1.0, 1.0, 0.0])
    np.testing.assert_array_equal(batch.source[0], [1, 2, 0])  # OOV,s1,PAD


def test_native_tokenizer_separate_policy(separate_setup):
    if not native.is_available():
        pytest.skip('native toolchain unavailable')
    config, vocabs, prefix = separate_setup
    reader = PathContextReader(vocabs, config, EstimatorAction.Train)
    reader._native = None
    tokenizer = native.get_tokenizer(vocabs, config)
    lines = ['lbl1 zz,zz,zz s1,p1,s2 ', ' s1,p1,s2', 'unknown s2,p1,s1']
    py_batch = reader.tokenize_lines(lines)
    native_batch = tokenizer.tokenize_lines(lines)
    np.testing.assert_array_equal(py_batch.source, native_batch.source)
    np.testing.assert_array_equal(py_batch.path, native_batch.path)
    np.testing.assert_array_equal(py_batch.target, native_batch.target)
    np.testing.assert_array_equal(py_batch.mask, native_batch.mask)
    np.testing.assert_array_equal(py_batch.label, native_batch.label)


def test_training_smoke_under_separate_policy(tmp_path):
    from tests.test_train_overfit import make_dataset
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix),
        TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=3,
        SAVE_EVERY_EPOCHS=1000, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False, SEPARATE_OOV_AND_PAD=True,
        LEARNING_RATE=0.01)
    model = Code2VecModel(config)
    model.train()
    results = model.evaluate()
    assert np.isfinite(results.subtoken_f1)
