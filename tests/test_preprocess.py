import pickle
import random

from code2vec_tpu.data import preprocess


def test_build_histograms(tmp_path):
    raw = tmp_path / 'raw.txt'
    raw.write_text('lbl1 a,p1,b a,p2,c\nlbl2 a,p1,b\n')
    token_count, path_count, target_count = preprocess.build_histograms(str(raw))
    assert token_count == {'a': 3, 'b': 2, 'c': 1}
    assert path_count == {'p1': 2, 'p2': 1}
    assert target_count == {'lbl1': 1, 'lbl2': 1}


def test_truncate_to_max_size():
    counts = {'a': 10, 'b': 8, 'c': 8, 'd': 5}
    # sorted desc: [10,8,8,5]; counts[2]=8 -> cutoff 9 -> only a
    assert preprocess.truncate_to_max_size(counts, 2) == {'a': 10}
    assert preprocess.truncate_to_max_size(counts, 4) == counts


def test_process_file_pads_and_drops_empty(tmp_path):
    raw = tmp_path / 'raw.txt'
    raw.write_text('lbl1 a,p1,b\nlbl2\n')
    total = preprocess.process_file(
        str(raw), 'train', str(tmp_path / 'out'),
        word_to_count={'a': 1, 'b': 1}, path_to_count={'p1': 1},
        max_contexts=3)
    assert total == 1
    lines = (tmp_path / 'out.train.c2v').read_text().splitlines()
    assert len(lines) == 1
    # padded with trailing spaces to exactly max_contexts fields
    assert lines[0] == 'lbl1 a,p1,b  '
    assert len(lines[0].split(' ')) == 1 + 3


def test_process_file_prefers_full_found_contexts(tmp_path):
    raw = tmp_path / 'raw.txt'
    # 3 contexts, max 2: two are fully in-vocab, one isn't -> the full ones win
    raw.write_text('lbl a,p1,b zz,zz,zz b,p1,a\n')
    preprocess.process_file(
        str(raw), 'train', str(tmp_path / 'out'),
        word_to_count={'a': 1, 'b': 1}, path_to_count={'p1': 1},
        max_contexts=2, rng=random.Random(0))
    line = (tmp_path / 'out.train.c2v').read_text().splitlines()[0]
    contexts = [c for c in line.split(' ')[1:] if c]
    assert set(contexts) == {'a,p1,b', 'b,p1,a'}


def test_end_to_end_preprocess_and_dict(tmp_path):
    for role in ['train', 'val', 'test']:
        (tmp_path / f'{role}.raw').write_text(
            'lbl1 a,p1,b a,p2,c\nlbl2 a,p1,b\n')
    out = tmp_path / 'ds'
    preprocess.preprocess_dataset(
        train_raw=str(tmp_path / 'train.raw'),
        val_raw=str(tmp_path / 'val.raw'),
        test_raw=str(tmp_path / 'test.raw'),
        output_name=str(out), max_contexts=4, seed=0)
    for role in ['train', 'val', 'test']:
        assert (tmp_path / f'ds.{role}.c2v').exists()
    with open(str(out) + '.dict.c2v', 'rb') as f:
        word_to_count = pickle.load(f)
        path_to_count = pickle.load(f)
        target_to_count = pickle.load(f)
        num_examples = pickle.load(f)
    assert word_to_count == {'a': 3, 'b': 2, 'c': 1}
    assert path_to_count == {'p1': 2, 'p2': 1}
    assert target_to_count == {'lbl1': 1, 'lbl2': 1}
    assert num_examples == 2
