"""Forward-pass parity: functional.encode vs a straight NumPy transcription
of the reference math (tensorflow_model.py:236-265)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models import functional


def numpy_reference_forward(params, source, path, target, mask):
    """Literal NumPy rendering of _calculate_weighted_contexts + logits
    (reference tensorflow_model.py:236-265, 226, is_evaluating=True)."""
    tok, pth, tgt_emb = (np.asarray(params.token_embedding),
                         np.asarray(params.path_embedding),
                         np.asarray(params.target_embedding))
    transform = np.asarray(params.transform)
    attention = np.asarray(params.attention)
    ctx = np.concatenate([tok[source], pth[path], tok[target]], axis=-1)
    x = np.tanh(ctx @ transform)                      # (B, C, D)
    scores = (x @ attention)[..., 0]                  # (B, C)
    with np.errstate(divide='ignore'):
        scores = scores + np.log(mask)                # log(0) = -inf
    scores -= scores.max(axis=1, keepdims=True)
    e = np.exp(scores)
    attn = e / e.sum(axis=1, keepdims=True)
    code = (x * attn[..., None]).sum(axis=1)          # (B, D)
    logits = code @ tgt_emb.T
    return code, attn, logits


@pytest.fixture
def tiny_params():
    return functional.init_params(
        jax.random.PRNGKey(0), token_vocab_size=11, path_vocab_size=7,
        target_vocab_size=5, token_dim=6, path_dim=4, code_dim=8)


def _random_batch(rng, B=3, C=5, Vt=11, Vp=7):
    source = rng.integers(0, Vt, (B, C)).astype(np.int32)
    path = rng.integers(0, Vp, (B, C)).astype(np.int32)
    target = rng.integers(0, Vt, (B, C)).astype(np.int32)
    mask = (rng.random((B, C)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0  # at least one valid context per row
    return source, path, target, mask


def test_encode_matches_numpy_reference(tiny_params):
    rng = np.random.default_rng(1)
    source, path, target, mask = _random_batch(rng)
    code, attn = functional.encode(tiny_params, source, path, target, mask)
    logits = functional.compute_logits(tiny_params, code)
    ref_code, ref_attn, ref_logits = numpy_reference_forward(
        tiny_params, source, path, target, mask)
    np.testing.assert_allclose(np.asarray(code), ref_code, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(attn), ref_attn, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(logits), ref_logits, rtol=2e-5,
                               atol=1e-5)


def test_masked_contexts_get_zero_attention(tiny_params):
    rng = np.random.default_rng(2)
    source, path, target, mask = _random_batch(rng)
    mask[:, 2:] = 0.0
    _, attn = functional.encode(tiny_params, source, path, target, mask)
    attn = np.asarray(attn)
    assert attn[:, 2:].max() < 1e-25  # zero at fp32 resolution
    np.testing.assert_allclose(attn.sum(axis=1), 1.0, rtol=1e-6)


def test_all_invalid_row_is_finite(tiny_params):
    # Static-shape padding rows must not NaN (the reference never sees such
    # rows; we mask them out of the loss instead).
    B, C = 2, 5
    source = np.zeros((B, C), np.int32)
    path = np.zeros((B, C), np.int32)
    target = np.zeros((B, C), np.int32)
    mask = np.zeros((B, C), np.float32)
    code, attn = functional.encode(tiny_params, source, path, target, mask)
    assert np.isfinite(np.asarray(code)).all()
    assert np.isfinite(np.asarray(attn)).all()


def test_loss_ignores_zero_weight_rows(tiny_params):
    rng = np.random.default_rng(3)
    source, path, target, mask = _random_batch(rng, B=4)
    label = rng.integers(0, 5, (4,)).astype(np.int32)
    weight = np.array([1, 1, 0, 0], np.float32)
    loss_full, _ = functional.loss_and_aux(
        tiny_params, source, path, target, mask, label, weight)
    # corrupt the zero-weight rows: loss must not change
    source2 = source.copy()
    source2[2:] = 0
    mask2 = mask.copy()
    mask2[2:] = 0
    label2 = label.copy()
    label2[2:] = 0
    loss_corrupted, _ = functional.loss_and_aux(
        tiny_params, source2, path, target, mask2, label2, weight)
    np.testing.assert_allclose(float(loss_full), float(loss_corrupted),
                               rtol=1e-6)


def test_dropout_train_vs_eval(tiny_params):
    rng = np.random.default_rng(4)
    source, path, target, mask = _random_batch(rng)
    code_eval, _ = functional.encode(tiny_params, source, path, target, mask)
    code_train, _ = functional.encode(
        tiny_params, source, path, target, mask,
        dropout_rng=jax.random.PRNGKey(0), dropout_keep_rate=0.5)
    assert not np.allclose(np.asarray(code_eval), np.asarray(code_train))
    # keep=1.0 disables dropout even with an rng
    code_keep1, _ = functional.encode(
        tiny_params, source, path, target, mask,
        dropout_rng=jax.random.PRNGKey(0), dropout_keep_rate=1.0)
    np.testing.assert_allclose(np.asarray(code_eval), np.asarray(code_keep1))


def test_dropout_rbg_impl(tiny_params):
    """DROPOUT_PRNG_IMPL='rbg' draws the mask from the hardware generator:
    still deterministic per key, still a genuine dropout mask, but a
    different stream than threefry (no cross-impl reproducibility claim)."""
    rng = np.random.default_rng(6)
    source, path, target, mask = _random_batch(rng)

    def enc(key, impl):
        out, _ = functional.encode(
            tiny_params, source, path, target, mask,
            dropout_rng=jax.random.PRNGKey(key), dropout_keep_rate=0.5,
            dropout_prng_impl=impl)
        return np.asarray(out)

    code_eval, _ = functional.encode(tiny_params, source, path, target, mask)
    a, b = enc(0, 'rbg'), enc(0, 'rbg')
    np.testing.assert_allclose(a, b)                 # keyed-deterministic
    assert not np.allclose(a, np.asarray(code_eval))  # dropout applied
    assert not np.allclose(a, enc(1, 'rbg'))          # key-sensitive
    # under jit too (the trainer always runs it jitted)
    jitted = jax.jit(lambda k: functional.encode(
        tiny_params, source, path, target, mask, dropout_rng=k,
        dropout_keep_rate=0.5, dropout_prng_impl='rbg')[0])
    # rtol 1e-5: jit fuses the mask-and-scale differently from eager on
    # some jax versions (0.4.x CPU measured 1.3e-6 relative)
    np.testing.assert_allclose(np.asarray(jitted(jax.random.PRNGKey(0))), a,
                               rtol=1e-5)


def test_bfloat16_compute_close_to_fp32(tiny_params):
    rng = np.random.default_rng(5)
    source, path, target, mask = _random_batch(rng)
    code32, _ = functional.encode(tiny_params, source, path, target, mask)
    code16, _ = functional.encode(tiny_params, source, path, target, mask,
                                  dtype=jnp.bfloat16)
    assert code16.dtype == jnp.float32  # outputs promoted back
    np.testing.assert_allclose(np.asarray(code32), np.asarray(code16),
                               rtol=0.05, atol=0.05)


def test_init_matches_reference_initializer_stats(tiny_params):
    # variance_scaling(1.0, fan_out, uniform): limit = sqrt(3/fan_out)
    tok = np.asarray(tiny_params.token_embedding)
    limit = np.sqrt(3.0 / tok.shape[1])
    assert tok.max() <= limit and tok.min() >= -limit
    tgt = np.asarray(tiny_params.target_embedding)
    limit_t = np.sqrt(3.0 / tgt.shape[1])
    assert tgt.max() <= limit_t and tgt.min() >= -limit_t


def test_remat_encode_is_bit_identical(tiny_params):
    """REMAT_ENCODE recomputes the encode activations in the backward —
    same ops, same dropout PRNG draws in the replay, so loss AND grads
    must be bit-identical to the stored-activation path (with dropout on,
    proving the PRNG replay identity)."""
    rng = np.random.default_rng(11)
    source, path, target, mask = _random_batch(rng)
    label = jnp.asarray(rng.integers(0, 5, (3,)).astype(np.int32))
    weight = jnp.ones((3,), jnp.float32)
    drng = jax.random.PRNGKey(7)

    def loss(p, remat):
        value, _ = functional.loss_and_aux(
            p, source, path, target, mask, label, weight,
            dropout_rng=drng, dropout_keep_rate=0.75, remat_encode=remat)
        return value

    # jitted: eager-mode remat replays through a different op schedule on
    # some jax versions (0.4.x CPU: ~2e-8 grad wobble); the trainer only
    # ever runs the remat path under jit, where the identity is exact
    plain, plain_g = jax.jit(
        jax.value_and_grad(lambda p: loss(p, False)))(tiny_params)
    remat, remat_g = jax.jit(
        jax.value_and_grad(lambda p: loss(p, True)))(tiny_params)
    assert float(plain) == float(remat)
    for a, b in zip(jax.tree_util.tree_leaves(plain_g),
                    jax.tree_util.tree_leaves(remat_g)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
