import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.model_api import Code2VecModel
from code2vec_tpu.parallel.distributed import maybe_initialize_distributed
from tests.test_train_overfit import make_dataset


def test_mid_epoch_evaluation_fires(tmp_path):
    """Reference Keras evaluated every NUM_TRAIN_BATCHES_TO_EVALUATE
    batches mid-epoch (keras_model.py:326-345)."""
    prefix = make_dataset(tmp_path, n_train=96)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix),
        TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=2,
        SAVE_EVERY_EPOCHS=1000, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False, NUM_TRAIN_BATCHES_TO_EVALUATE=4)
    model = Code2VecModel(config)
    eval_count = [0]
    orig_evaluate = model.evaluate

    def counting_evaluate(**kwargs):
        eval_count[0] += 1
        return orig_evaluate(**kwargs)

    model.evaluate = counting_evaluate
    model.train()
    # 96 examples / 16 = 6 batches/epoch, 2 epochs = 12 batches ->
    # mid-epoch evals at batches 4 and 8 (12 coincides with epoch end)
    # plus the 2 per-epoch evals
    assert eval_count[0] >= 4


def test_reader_process_striding(tmp_path):
    """Each process reads a disjoint line stride and emits its share of the
    global batch (multi-host input sharding)."""
    import pickle
    from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
    from code2vec_tpu.vocab import Code2VecVocabs
    prefix = tmp_path / 'ds'
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump({'s%d' % i: 10 - i for i in range(8)}, f)
        pickle.dump({'p1': 7}, f)
        pickle.dump({'lbl%d' % i: 8 - i for i in range(8)}, f)
        pickle.dump(8, f)
    lines = ['lbl%d s%d,p1,s%d' % (i, i, i) for i in range(8)]
    (tmp_path / 'ds.train.c2v').write_text('\n'.join(lines) + '\n')
    config = Config(TRAIN_DATA_PATH_PREFIX=str(prefix), VERBOSE_MODE=0,
                    MAX_CONTEXTS=2, TRAIN_BATCH_SIZE=4,
                    READER_USE_NATIVE=False)
    vocabs = Code2VecVocabs(config)
    seen = []
    for process_index in range(2):
        reader = PathContextReader(vocabs, config, EstimatorAction.Train,
                                   process_index=process_index,
                                   process_count=2)
        rows = []
        for batch in reader.iter_epoch(shuffle=False):
            assert batch.label.shape[0] == 2  # local share of global 4
            rows.extend(batch.label[batch.weight > 0].tolist())
        seen.append(set(rows))
    assert seen[0].isdisjoint(seen[1])
    assert len(seen[0] | seen[1]) == 8  # every line covered exactly once


def test_distributed_init_is_noop_single_host(monkeypatch):
    for var in ('JAX_COORDINATOR_ADDRESS', 'TPU_WORKER_HOSTNAMES',
                'MEGASCALE_COORDINATOR_ADDRESS'):
        monkeypatch.delenv(var, raising=False)
    assert maybe_initialize_distributed() is False


def test_shard_cycling_warns():
    """Multi-host guard (VERDICT r2 weak #4): the fixed-step epoch
    iterator must yield exactly steps_per_epoch batches, stay SILENT for
    the routine <=1-batch top-up that line-striding produces, and warn
    loudly when a shard runs short by more than one batch (a skewed data
    split silently re-weighting that shard's examples)."""
    from code2vec_tpu.model_api import fixed_step_iterator

    # pathological shard: 3 local batches against 8 fixed steps
    messages = []
    out = list(fixed_step_iterator(lambda: iter(['a', 'b', 'c']), 8,
                                   process_index=1, log=messages.append))
    assert out == ['a', 'b', 'c', 'a', 'b', 'c', 'a', 'b']
    assert len(messages) == 1
    assert 'cycling its local data' in messages[0]
    assert 'process 1' in messages[0]

    # routine imbalance: one batch short -> silent top-up
    messages = []
    out = list(fixed_step_iterator(lambda: iter(['a', 'b', 'c']), 4,
                                   process_index=0, log=messages.append))
    assert out == ['a', 'b', 'c', 'a']
    assert messages == []

    # exact fit: no cycling, no warning
    messages = []
    out = list(fixed_step_iterator(lambda: iter(['a', 'b']), 2,
                                   process_index=0, log=messages.append))
    assert out == ['a', 'b']
    assert messages == []

    # empty shard: explicit error, not a silent hang
    import pytest as _pytest
    with _pytest.raises(ValueError, match='no training batches'):
        list(fixed_step_iterator(lambda: iter([]), 2, 0, messages.append))
