"""Output-order guarantees the index builder depends on (ISSUE 5
satellite): ``serving/bulk`` vector export must map row i ↔ kept
example i in corpus order across batch boundaries and the short final
batch, and the serving engine's oversize split + re-join must deliver
results in request order."""
import numpy as np
import pytest

from code2vec_tpu.config import Config
from tests.test_train_overfit import make_dataset

LABELS = ['get|a', 'set|b', 'run|c', 'close|d']


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('bulk_order'),
                          n_train=60)
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,64', EXPORT_CODE_VECTORS=True)
    return Code2VecModel(config)


def predict_vector(model, line: str) -> np.ndarray:
    (result,) = model.predict([line])
    assert result.code_vector is not None
    return np.asarray(result.code_vector, np.float32)


def cosine(a, b) -> float:
    return float(np.dot(a, b)
                 / max(np.linalg.norm(a) * np.linalg.norm(b), 1e-12))


def test_bulk_vector_rows_align_with_kept_examples(model, tmp_path):
    """Row i of the streamed export must be the vector of the i-th KEPT
    corpus example — across multiple batches, a short final batch, and
    a filtered (contextless) row in the middle of the file."""
    from code2vec_tpu.serving.bulk import iter_code_vector_batches
    corpus_lines = open(
        model.config.train_data_path).read().splitlines()[:35]
    # a row with NO valid context: dropped by the evaluate-path filter,
    # so everything after it shifts — exactly what an off-by-one in the
    # split/re-join would scramble
    corpus_lines.insert(10, 'orphan|label ' + ' ' * 5)
    corpus = tmp_path / 'order.c2v'
    corpus.write_text('\n'.join(corpus_lines) + '\n')

    kept = [line for i, line in enumerate(corpus_lines) if i != 10]
    chunks = list(iter_code_vector_batches(model, str(corpus),
                                           with_labels=True))
    vectors = np.concatenate([v for v, _labels in chunks])
    labels = np.concatenate([lab for _v, lab in chunks])
    assert vectors.shape[0] == len(kept) == 35
    assert [str(l) for l in labels] == [line.split()[0] for line in kept]
    # 36 rows at TEST_BATCH_SIZE=16 -> 3 batches incl. short final
    assert len(chunks) == 3
    for i in (0, 9, 10, 17, 33, 34):   # spans every batch boundary
        direct = predict_vector(model, kept[i])
        assert cosine(vectors[i], direct) > 0.999, i


def test_export_code_vectors_text_matches_stream(model, tmp_path):
    """The .vectors text export is the same stream, formatted — and
    --vectors-dtype float16 changes precision, not order."""
    from code2vec_tpu.serving.bulk import (export_code_vectors,
                                           iter_code_vector_batches)
    corpus = model.config.train_data_path
    streamed = np.concatenate(
        [v for v, _l in iter_code_vector_batches(model, corpus)])
    n, out_path = export_code_vectors(model, corpus,
                                      output_path=str(tmp_path / 'v32'))
    text32 = np.loadtxt(out_path, dtype=np.float32, ndmin=2)
    assert n == streamed.shape[0]
    np.testing.assert_allclose(text32, streamed, atol=1e-6)
    n16, out16 = export_code_vectors(model, corpus, dtype='float16',
                                     output_path=str(tmp_path / 'v16'))
    text16 = np.loadtxt(out16, dtype=np.float32, ndmin=2)
    assert n16 == n
    np.testing.assert_allclose(text16, streamed, atol=2e-2, rtol=1e-2)


def test_engine_oversize_split_rejoins_in_order(model):
    """A request larger than the top batch bucket splits into chunks and
    re-joins: result i must be line i's vector (vectors tier — the
    composition submit_neighbors rides)."""
    reader_lines = open(
        model.config.train_data_path).read().splitlines()[:20]
    with model.serving_engine(tiers=('vectors',)) as engine:
        # top bucket is 64 — rebuild a tiny ladder so 20 lines oversize
        engine.buckets = (8,)
        results = engine.submit(reader_lines,
                                tier='vectors').result(timeout=300)
    assert len(results) == len(reader_lines)
    for i in (0, 7, 8, 9, 15, 19):     # spans the 8-row chunk seams
        direct = predict_vector(model, reader_lines[i])
        assert cosine(np.asarray(results[i].code_vector, np.float32),
                      direct) > 0.999, i
