"""Checkpoint / resume / release round-trips (reference parity:
tensorflow_model.py:370-377, keras_model.py:230-296)."""
import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.model_api import Code2VecModel
from tests.test_train_overfit import make_dataset


def _train_config(tmp_path, prefix, **overrides):
    defaults = dict(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        MODEL_SAVE_PATH=str(tmp_path / 'models' / 'saved_model'))
    defaults.update(overrides)
    return Config(**defaults)


def test_save_creates_sidecar_and_checkpoints(tmp_path):
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix)
    model = Code2VecModel(config)
    model.train()
    model_dir = tmp_path / 'models'
    assert (model_dir / 'dictionaries.bin').exists()
    assert (model_dir / 'saved_model__entire-model').is_dir()


# tier-1 budget (conftest report): the same-backend pairs carry the
# round-trip property; the cross-backend pairs re-run the full train
# for ~6s each and ride in the slow tier
@pytest.mark.parametrize('train_framework,load_framework',
                         [('jax', 'jax'), ('flax', 'flax'),
                          pytest.param('jax', 'flax',
                                       marks=pytest.mark.slow),
                          pytest.param('flax', 'jax',
                                       marks=pytest.mark.slow)])
def test_load_params_reproduces_predictions(tmp_path, train_framework,
                                            load_framework):
    """Checkpoints use a canonical params layout: a model trained under
    either backend loads (params-only) under either backend — a capability
    the reference lacked (README.md:210)."""
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, DL_FRAMEWORK=train_framework)
    model = Code2VecModel(config)
    model.train()
    line = 'get|a toka0,pA,toka1 toka1,pB,toka2    '
    before = model.predict([line])[0]

    config2 = Config(
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'),
        DL_FRAMEWORK=load_framework, COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        VERBOSE_MODE=0, READER_USE_NATIVE=False)
    model2 = Code2VecModel(config2)
    after = model2.predict([line])[0]
    assert before.topk_predicted_words == after.topk_predicted_words
    np.testing.assert_allclose(before.topk_predicted_words_scores,
                               after.topk_predicted_words_scores, rtol=1e-5)


def test_release_under_other_framework_preserves_meta(tmp_path):
    """--release under the other backend must not relabel the training
    checkpoint's framework in meta.json — the cross-framework resume
    diagnostic depends on the original writer's value."""
    import json
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, DL_FRAMEWORK='jax',
                           NUM_TRAIN_EPOCHS=1)
    Code2VecModel(config).train()

    load_path = str(tmp_path / 'models' / 'saved_model')
    config_r = Config(MODEL_LOAD_PATH=load_path, RELEASE=True,
                      DL_FRAMEWORK='flax', COMPUTE_DTYPE='float32',
                      MAX_CONTEXTS=6, VERBOSE_MODE=0,
                      READER_USE_NATIVE=False)
    model_r = Code2VecModel(config_r)
    model_r.release_model()
    with open(load_path + '.meta.json') as f:
        meta = json.load(f)
    assert meta['framework'] == 'jax'
    assert meta['checkpoint_layout'] == 'canonical-v1'


def test_cross_framework_training_resume_raises_clearly(tmp_path):
    """Optimizer state is backend-specific: resuming TRAINING under the
    other framework must fail with an explanation, not an orbax shape
    error (params-only loads are covered by the test above)."""
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, DL_FRAMEWORK='jax',
                           NUM_TRAIN_EPOCHS=1)
    Code2VecModel(config).train()

    config2 = _train_config(
        tmp_path, prefix, DL_FRAMEWORK='flax', NUM_TRAIN_EPOCHS=2,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    with pytest.raises(ValueError, match='framework'):
        Code2VecModel(config2)


def test_resume_training_continues_from_epoch(tmp_path):
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=2)
    model = Code2VecModel(config)
    model.train()

    # resume with --load and --data: starts at epoch 2
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=4,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert model2._start_epoch == 2
    assert int(model2.state.step) > 0
    model2.train()  # runs epochs 2..3 without error


@pytest.mark.parametrize('saved_mu,resume_mu',
                         [('float32', 'bfloat16'),
                          pytest.param('bfloat16', 'float32',
                                       marks=pytest.mark.slow)])
def test_resume_across_adam_mu_dtype(tmp_path, saved_mu, resume_mu):
    """ADAM_MU_DTYPE's default flipped fp32 -> bf16 (2026-07-31 A/B):
    resuming an older checkpoint under the new default (and vice versa)
    must adapt — restore as stored, cast mu to the configured dtype —
    not fail with an orbax dtype mismatch (advisor r5)."""
    import jax
    import jax.numpy as jnp

    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           ADAM_MU_DTYPE=saved_mu)
    Code2VecModel(config).train()

    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, ADAM_MU_DTYPE=resume_mu,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert model2._start_epoch == 1
    mu = model2.state.opt_state[0].mu
    mu_dtypes = {leaf.dtype for leaf in jax.tree_util.tree_leaves(mu)}
    assert mu_dtypes == {np.dtype(getattr(jnp, resume_mu))}
    model2.train()  # epoch 1 runs under the configured mu dtype


@pytest.mark.parametrize('saved_nu,resume_nu',
                         [('float32', 'bfloat16'),
                          pytest.param('bfloat16', 'float32',
                                       marks=pytest.mark.slow)])
def test_resume_across_adam_nu_dtype(tmp_path, saved_nu, resume_nu):
    """ADAM_NU_DTYPE is gated on the same flip rule as mu was: cross-dtype
    resume must adapt in both directions — restore the second moment as
    stored, cast to the configured dtype (checkpoints._MOMENT_FIELDS
    covers both moments)."""
    import jax
    import jax.numpy as jnp

    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           ADAM_NU_DTYPE=saved_nu)
    Code2VecModel(config).train()

    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, ADAM_NU_DTYPE=resume_nu,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert model2._start_epoch == 1
    nu = model2.state.opt_state[0].nu
    nu_dtypes = {leaf.dtype for leaf in jax.tree_util.tree_leaves(nu)}
    assert nu_dtypes == {np.dtype(getattr(jnp, resume_nu))}
    model2.train()  # epoch 1 runs under the configured nu dtype


@pytest.mark.slow  # two full trains (~10s); tier-1 budget headroom
def test_resume_across_opt_state_sharding_modes(tmp_path):
    """A checkpoint written with the mirrored moment layout resumes under
    OPTIMIZER_STATE_SHARDING='zero' (and the moments land zero-sharded):
    orbax re-shards onto the restore target's layout, so the knob is a
    runtime choice, not a checkpoint property."""
    from jax.sharding import PartitionSpec as P

    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           PARAM_ROW_ALIGNMENT=8,
                           MESH_DATA_AXIS_SIZE=4, MESH_MODEL_AXIS_SIZE=2)
    Code2VecModel(config).train()

    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, PARAM_ROW_ALIGNMENT=8,
        MESH_DATA_AXIS_SIZE=4, MESH_MODEL_AXIS_SIZE=2,
        OPTIMIZER_STATE_SHARDING='zero',
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    mu = model2.state.opt_state[0].mu
    leaf = mu.token_embedding if hasattr(mu, 'token_embedding') \
        else mu['token_embedding']
    assert leaf.sharding.spec == P(('data', 'model'), None)
    model2.train()  # epoch 1 runs under the zero layout without error


@pytest.mark.slow  # three full trains (~11s); tier-1 budget headroom
def test_resume_across_fused_ce_and_mesh_reshape(tmp_path):
    """ADVICE r3: the fused-CE target-table allocation folds in the vocab
    tile and mesh model-axis size, so its row count is topology-dependent —
    restore must pad/slice the masked padding rows instead of rejecting the
    checkpoint, in BOTH directions (fused-CE -> plain slice, plain ->
    fused-CE pad)."""
    prefix = make_dataset(tmp_path)
    # save under fused CE + model axis 2: rows align to VOCAB_TILE*2
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           PARAM_ROW_ALIGNMENT=8,
                           MESH_DATA_AXIS_SIZE=4, MESH_MODEL_AXIS_SIZE=2,
                           USE_PALLAS_FUSED_CE=True)
    model = Code2VecModel(config)
    model.train()
    line = 'get|a toka0,pA,toka1 toka1,pB,toka2    '
    before = model.predict([line])[0]
    fused_rows = model.backend.sizes['target_vocab_size']

    # training resume with fused CE OFF on a plain mesh: rows shrink to the
    # plain alignment; Adam moments slice with the table
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, PARAM_ROW_ALIGNMENT=8,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert model2.backend.sizes['target_vocab_size'] < fused_rows
    assert (model2.state.params.target_embedding.shape[0]
            == model2.backend.sizes['target_vocab_size'])
    after = model2.predict([line])[0]
    # the fused allocation's top-k can run past the valid vocab into masked
    # padding columns; the sliced model can't — compare the valid prefix
    n = min(len(before.topk_predicted_words), len(after.topk_predicted_words))
    assert before.topk_predicted_words[:n] == after.topk_predicted_words[:n]
    np.testing.assert_allclose(before.topk_predicted_words_scores[:n],
                               after.topk_predicted_words_scores[:n],
                               rtol=1e-5)
    model2.train()  # epoch 1 runs with the sliced moments without error
    # train() wrote a NEWER checkpoint — the state model3 restores below.
    # Compare against a fresh prediction of THAT state: the pre-train
    # `after` only matches when the extra epoch happens to move nothing
    # (it did on the original toolchain, by convergence luck, but the
    # pad-direction claim is about the restore, not about training being
    # a no-op).
    after_train = model2.predict([line])[0]

    # params-only load back UNDER fused CE (pad direction)
    config3 = Config(
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'),
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        VERBOSE_MODE=0, READER_USE_NATIVE=False, PARAM_ROW_ALIGNMENT=8,
        USE_PALLAS_FUSED_CE=True)
    model3 = Code2VecModel(config3)
    assert model3.backend.sizes['target_vocab_size'] > \
        model2.backend.sizes['target_vocab_size']
    padded = model3.predict([line])[0]
    m = min(len(padded.topk_predicted_words),
            len(after_train.topk_predicted_words))
    assert padded.topk_predicted_words[:m] == \
        after_train.topk_predicted_words[:m]


@pytest.mark.slow  # train + release + resume (~10s); budget headroom
def test_release_rows_rewrite_does_not_poison_older_checkpoints(tmp_path):
    """ADVICE r4: one meta.json serves the whole history, and its
    target_vocab_rows tracks only the NEWEST writer — after a --release
    under a plain (smaller-rows) config, a resume of the older fused-CE
    entire-model checkpoint used to build restore targets with the
    release's row count against the checkpoint's larger arrays. The
    restore must read the saved row count from the artifact itself
    (orbax array metadata), not the shared sidecar."""
    import json
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1,
                           PARAM_ROW_ALIGNMENT=8, USE_PALLAS_FUSED_CE=True)
    model = Code2VecModel(config)
    model.train()
    line = 'get|a toka0,pA,toka1 toka1,pB,toka2    '
    before = model.predict([line])[0]
    fused_rows = model.backend.sizes['target_vocab_size']

    # --release under a plain config rewrites the sidecar's rows
    load_path = str(tmp_path / 'models' / 'saved_model')
    config_r = Config(MODEL_LOAD_PATH=load_path, RELEASE=True,
                      DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32',
                      MAX_CONTEXTS=6, VERBOSE_MODE=0,
                      READER_USE_NATIVE=False, PARAM_ROW_ALIGNMENT=8)
    Code2VecModel(config_r).release_model()
    with open(load_path + '.meta.json') as f:
        sidecar_rows = json.load(f)['target_vocab_rows']
    assert sidecar_rows < fused_rows

    # resume TRAINING from the fused-CE entire-model checkpoint: its
    # arrays hold fused_rows rows while the sidecar now says sidecar_rows
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, PARAM_ROW_ALIGNMENT=8,
        USE_PALLAS_FUSED_CE=True, MODEL_LOAD_PATH=load_path)
    model2 = Code2VecModel(config2)
    assert model2._start_epoch == 1
    assert (model2.state.params.target_embedding.shape[0] == fused_rows)
    after = model2.predict([line])[0]
    assert before.topk_predicted_words == after.topk_predicted_words
    np.testing.assert_allclose(before.topk_predicted_words_scores,
                               after.topk_predicted_words_scores, rtol=1e-5)
    model2.train()  # epoch 1 runs from the restored moments without error


def test_step_interval_saves_and_midepoch_resume(tmp_path):
    """SAVE_EVERY_N_STEPS (VERDICT r1 #8): step-keyed async snapshots
    during the epoch bound preemption loss, in their OWN short-retention
    store (they must not evict epoch-boundary history); resume prefers the
    newest state across both stores and restarts an interrupted epoch."""
    # 60 examples, batch 16 -> 4 (padded) steps/epoch, 8 steps over 2 epochs
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=2,
                           SAVE_EVERY_EPOCHS=1, SAVE_EVERY_N_STEPS=2)
    model = Code2VecModel(config)
    model.train()

    store = model._store_for(config.MODEL_SAVE_PATH)
    # epoch-boundary saves keep their own retention window...
    assert sorted(store.manager().all_steps()) == [4, 8]
    # ...interval snapshots fire between boundaries (the step-4 interval is
    # deduplicated against the epoch-0 boundary save)
    assert sorted(store.snapshot_manager().all_steps()) == [2, 6]
    model.close_stores()

    # newest checkpoint (step 8 = end of epoch 1) must record epoch 1 even
    # though a step interval also landed on that boundary -> resume at
    # epoch 2, not a replay of epoch 1
    config2 = _train_config(
        tmp_path, prefix, NUM_TRAIN_EPOCHS=2, SAVE_EVERY_N_STEPS=0,
        MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))
    model2 = Code2VecModel(config2)
    assert int(model2.state.step) == 8
    assert model2._start_epoch == 2
    model2.close_stores()

    # drop the epoch-boundary checkpoints: the newest mid-epoch snapshot
    # (step 6, inside epoch 1) must restart epoch 1
    import shutil
    entire = tmp_path / 'models' / 'saved_model__entire-model'
    shutil.rmtree(entire / '8')
    shutil.rmtree(entire / '4')
    model3 = Code2VecModel(config2)
    assert int(model3.state.step) == 6
    assert model3._start_epoch == 1  # restart the interrupted epoch
    model3.train()  # completes epoch 1 without error


def test_release_params_only(tmp_path):
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix)
    model = Code2VecModel(config)
    model.train()

    load_path = str(tmp_path / 'models' / 'saved_model')
    config_release = Config(
        MODEL_LOAD_PATH=load_path, RELEASE=True, DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, VERBOSE_MODE=0,
        READER_USE_NATIVE=False)
    model_r = Code2VecModel(config_release)
    model_r.release_model()
    weights_dir = tmp_path / 'models' / 'saved_model__only-weights'
    assert weights_dir.is_dir()

    # a released model loads (params-only path preferred) and predicts
    config3 = Config(
        MODEL_LOAD_PATH=load_path, DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, VERBOSE_MODE=0,
        READER_USE_NATIVE=False)
    model3 = Code2VecModel(config3)
    result = model3.predict(['get|a toka0,pA,toka1    '])[0]
    assert len(result.topk_predicted_words) > 0


def test_word2vec_export(tmp_path):
    from code2vec_tpu.vocab import VocabType
    prefix = make_dataset(tmp_path)
    config = _train_config(tmp_path, prefix, NUM_TRAIN_EPOCHS=1)
    model = Code2VecModel(config)
    dest = tmp_path / 'tokens.w2v'
    model.save_word2vec_format(str(dest), VocabType.Token)
    lines = dest.read_text().splitlines()
    vocab_size, dim = map(int, lines[0].split())
    assert vocab_size == model.vocabs.token_vocab.size
    assert dim == config.TOKEN_EMBEDDINGS_SIZE
    assert len(lines) == vocab_size + 1
