"""REAL multi-process distributed tests (VERDICT r1 #4).

Two OS processes join a ``jax.distributed`` cluster over localhost on the
CPU platform (2 virtual devices each → a 4-device global mesh), then train
and evaluate through the full ``Code2VecModel`` lifecycle.  This exercises
what single-process virtual-device tests cannot: per-process data striding,
globally agreed fixed step counts, cross-process collective pairing, and
the metric-counter all-gather — the deadlock class multi-host guards
against only exists across real process boundaries.

Asserts eval parity: per-example metrics are independent of batch
membership and every example is evaluated exactly once on exactly one
process, so the merged 2-process counters must equal the single-process
result bit-for-bit (loss to float tolerance — summation order differs).
"""
import contextlib
import fcntl
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from tests.test_train_overfit import make_dataset

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, 'tests', 'distributed_worker.py')


def _cpu_multiprocess_collectives_supported() -> bool:
    """True iff this jaxlib can run cross-process collectives on the CPU
    backend. The CPU collectives layer (gloo/mpi) ships with the
    ``jax_cpu_collectives_implementation`` config option; without it every
    cross-process psum raises "Multiprocess computations aren't
    implemented on the CPU backend" — an environment limit of the
    installed toolchain, not a product regression (CHANGES.md PR 1)."""
    import jax
    return hasattr(jax.config, 'jax_cpu_collectives_implementation')


# Applied to every test that spawns a real 2-process cluster; the pure
# fixed_step_iterator tests below run everywhere.
needs_cpu_collectives = pytest.mark.skipif(
    not _cpu_multiprocess_collectives_supported(),
    reason='environment-limited: this jaxlib has no CPU multi-process '
           'collectives, so cross-process CPU clusters cannot run '
           '(known-skip, CHANGES.md PR 1)')

# Cross-invocation serialization: two clusters racing on one loaded host is
# the observed flake mode (a worker starts late and misses the join
# barrier).  flock is advisory but both sides of any plausible race are
# this same harness, so it is sufficient — and it serializes across
# pytest-xdist workers and concurrent pytest invocations alike.
_LOCK_PATH = os.path.join(tempfile.gettempdir(), 'code2vec_tpu_dist_test.lock')


@contextlib.contextmanager
def _cluster_lock():
    with open(_LOCK_PATH, 'w') as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('localhost', 0))
        return s.getsockname()[1]


def _worker_env() -> dict:
    # PYTHONPATH=REPO only (no axon sitecustomize: a wedged TPU tunnel must
    # not hang the CPU worker processes); 2 virtual CPU devices per process.
    return {
        'PATH': os.environ.get('PATH', '/usr/bin:/bin'),
        'HOME': os.environ.get('HOME', '/root'),
        'PYTHONPATH': REPO,
        'JAX_PLATFORMS': 'cpu',
        'XLA_FLAGS': '--xla_force_host_platform_device_count=2',
    }


def _launch_cluster_once(tmp_path, prefix, num_processes, train_epochs,
                         timeout, data_cache, model_axis, lr):
    """One cluster attempt. Returns (records, None) or (None, failure_str)."""
    port = _free_port()
    outs = []
    procs = []
    for pid in range(num_processes):
        out = tmp_path / (f'result_p{num_processes}_{pid}_{train_epochs}'
                          f'_{data_cache}_m{model_axis}_lr{lr}.json')
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER,
             '--coordinator', f'localhost:{port}',
             '--process_id', str(pid),
             '--num_processes', str(num_processes),
             '--prefix', str(prefix),
             '--out', str(out),
             '--train_epochs', str(train_epochs),
             '--data_cache', str(data_cache),
             '--model_axis', str(model_axis),
             '--lr', str(lr)],
            env=_worker_env(), cwd=str(tmp_path),  # eval log.txt goes here
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    failure = None
    # one shared deadline, not timeout-per-worker: two wedged workers must
    # not serialize into 2x the budget while the cluster lock is held
    deadline = time.monotonic() + timeout
    try:
        for pid, proc in enumerate(procs):
            try:
                stdout, _ = proc.communicate(
                    timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                failure = failure or f'worker {pid} timed out after {timeout}s'
                continue
            if proc.returncode != 0:
                failure = failure or ('worker %d failed (rc=%d):\n%s' % (
                    pid, proc.returncode, (stdout or '')[-4000:]))
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
    if failure is not None:
        return None, failure
    records = []
    for out in outs:
        with open(out) as f:
            records.append(json.load(f))
    return records, None


def _run_cluster(tmp_path, prefix, num_processes: int, train_epochs: int,
                 timeout: float = 420.0, data_cache: int = 1,
                 model_axis: int = 1, lr: float = 0.01) -> list:
    """Run one cluster under the inter-process lock, retrying the join once.

    The only observed flake mode is a worker missing the 120s join barrier
    under host load (VERDICT r2 weak #3); the worker now fails fast on
    that, and one full-cluster retry on a fresh port absorbs it.  Genuine
    failures fail both attempts and report the second's output.
    """
    with _cluster_lock():
        for attempt in (1, 2):
            records, failure = _launch_cluster_once(
                tmp_path, prefix, num_processes, train_epochs, timeout,
                data_cache, model_axis, lr)
            if records is not None:
                return records
            if attempt == 1:
                print(f'cluster attempt 1 failed ({failure[:200]}); '
                      f'retrying once on a fresh port', file=sys.stderr)
        pytest.fail(f'cluster failed twice; last failure:\n{failure}')


@pytest.fixture(scope='module')
def dataset(tmp_path_factory):
    return make_dataset(tmp_path_factory.mktemp('dist'))


@needs_cpu_collectives
def test_two_process_eval_matches_single_process(tmp_path, dataset):
    two = _run_cluster(tmp_path, dataset, num_processes=2, train_epochs=0)
    one = _run_cluster(tmp_path, dataset, num_processes=1, train_epochs=0)

    assert [r['process_count'] for r in two] == [2, 2]
    assert two[0]['n_global_devices'] == 4
    assert two[0]['n_local_devices'] == 2

    # both processes computed (and must agree on) the merged global result
    assert two[0]['topk_acc'] == two[1]['topk_acc']
    assert two[0]['f1'] == two[1]['f1']

    # exact counter parity with the single-process evaluation
    baseline = one[0]
    np.testing.assert_array_equal(two[0]['topk_acc'], baseline['topk_acc'])
    assert two[0]['precision'] == baseline['precision']
    assert two[0]['recall'] == baseline['recall']
    assert two[0]['f1'] == baseline['f1']
    # loss: same examples, different summation order
    assert baseline['loss'] is not None
    np.testing.assert_allclose(two[0]['loss'], baseline['loss'], rtol=1e-5)


@needs_cpu_collectives
@pytest.mark.parametrize('data_cache', [1, 0],
                         ids=['process-cache', 'streaming'])
def test_two_process_train_and_eval_completes(tmp_path, dataset, data_cache):
    """Striding + fixed train step counts + per-epoch multi-host eval with
    real collectives, over BOTH multi-host input paths (per-process token
    cache and streaming): the run completing at all proves no step-count
    mismatch deadlocked the mesh."""
    records = _run_cluster(tmp_path, dataset, num_processes=2,
                           train_epochs=2, data_cache=data_cache)
    assert [r['trained_epochs'] for r in records] == [2, 2]
    for r in records:
        assert r['loss'] is not None and np.isfinite(r['loss'])
    # trained params are identical on both processes, so the final merged
    # eval must agree exactly
    assert records[0]['topk_acc'] == records[1]['topk_acc']
    assert records[0]['f1'] == records[1]['f1']
    # the IN-TRAINING per-epoch evals are the same merged computation:
    # identical on both processes, and the last one (final params) must
    # equal the standalone post-train evaluate bit-for-bit
    history = records[0]['eval_history']
    assert len(history) == 2
    assert history == records[1]['eval_history']
    assert history[-1]['f1'] == records[0]['f1']
    assert history[-1]['topk_acc'] == records[0]['topk_acc']


@needs_cpu_collectives
def test_midtrain_eval_matches_single_process(tmp_path, dataset):
    """VERDICT r4 #6: the training loop's per-epoch eval must produce the
    exact single-process numbers, not a process-local approximation. With
    lr=0 the params stay at the seed-42 init on ANY process count, so the
    mid-train eval F1 is directly comparable across cluster sizes."""
    two = _run_cluster(tmp_path, dataset, num_processes=2, train_epochs=1,
                       lr=0.0)
    one = _run_cluster(tmp_path, dataset, num_processes=1, train_epochs=1,
                       lr=0.0)
    h_two, h_one = two[0]['eval_history'], one[0]['eval_history']
    assert len(h_two) == len(h_one) == 1
    assert h_two == two[1]['eval_history']
    assert h_two[0]['f1'] == h_one[0]['f1']
    assert h_two[0]['precision'] == h_one[0]['precision']
    assert h_two[0]['recall'] == h_one[0]['recall']
    assert h_two[0]['topk_acc'] == h_one[0]['topk_acc']
    np.testing.assert_allclose(h_two[0]['loss'], h_one[0]['loss'],
                               rtol=1e-5)


@needs_cpu_collectives
def test_two_process_tensor_parallel_eval_matches(tmp_path, dataset):
    """TP across the process boundary: a 2x2 (data, model) mesh over two
    processes row-shards the embedding tables and column-shards the softmax
    so the top-k merge and metric collectives cross processes. Metrics are
    mesh-independent, so the result must equal the model_axis=1 run."""
    tp = _run_cluster(tmp_path, dataset, num_processes=2, train_epochs=0,
                      model_axis=2)
    dp = _run_cluster(tmp_path, dataset, num_processes=2, train_epochs=0)

    assert tp[0]['topk_acc'] == tp[1]['topk_acc']
    np.testing.assert_array_equal(tp[0]['topk_acc'], dp[0]['topk_acc'])
    assert tp[0]['precision'] == dp[0]['precision']
    assert tp[0]['recall'] == dp[0]['recall']
    assert tp[0]['f1'] == dp[0]['f1']
    np.testing.assert_allclose(tp[0]['loss'], dp[0]['loss'], rtol=1e-5)


@needs_cpu_collectives
def test_two_process_tensor_parallel_train_completes(tmp_path, dataset):
    """One epoch of training on the cross-process 2x2 mesh (DP gradient
    psum + row-sharded table updates + sharded-softmax backward all with
    real process boundaries) completes and both processes agree."""
    records = _run_cluster(tmp_path, dataset, num_processes=2,
                           train_epochs=1, model_axis=2)
    assert [r['trained_epochs'] for r in records] == [1, 1]
    for r in records:
        assert r['loss'] is not None and np.isfinite(r['loss'])
    assert records[0]['topk_acc'] == records[1]['topk_acc']


# ---------------------------------------------------------------------------
# fixed_step_iterator cycling warning (VERDICT r2 weak #4 / r3 #8)

def test_fixed_step_iterator_warns_on_starved_shard():
    """A shard that exhausts far short of the fixed step count must log the
    over-weighting warning as it cycles its local data."""
    from code2vec_tpu.model_api import fixed_step_iterator
    messages = []
    batches = lambda: iter([{'b': 0}, {'b': 1}])     # 2 of 8 fixed steps
    out = list(fixed_step_iterator(batches, 8, process_index=3,
                                   log=messages.append))
    assert len(out) == 8                      # the mesh stays in step
    assert [b['b'] for b in out] == [0, 1] * 4
    warnings = [m for m in messages if 'WARNING' in m]
    assert len(warnings) == 1                 # once, not every pass
    assert 'process 3' in warnings[0]
    assert 'exhausted its shard after 2 of 8' in warnings[0]


def test_fixed_step_iterator_silent_on_routine_topup():
    """Line-striding keeps imbalance <=1 batch; that routine top-up must
    NOT warn."""
    from code2vec_tpu.model_api import fixed_step_iterator
    messages = []
    batches = lambda: iter([{'b': i} for i in range(7)])   # 7 of 8 steps
    out = list(fixed_step_iterator(batches, 8, process_index=0,
                                   log=messages.append))
    assert len(out) == 8
    assert not messages
