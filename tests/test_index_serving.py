"""ISSUE 5 e2e: the full retrieval loop — extract (native extractor) →
vectors-tier predict → neighbor search — plus the service-layer build /
query orchestration and the CLI flag surface."""
import json
import os

import numpy as np
import pytest

from code2vec_tpu.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXTRACTOR = os.path.join(REPO, 'extractor', 'build', 'c2v-extract')

JAVA_SOURCE = '''
class Probe {
  int width;
  int getWidth() { return this.width; }
  void setWidth(int value) { this.width = value; }
  boolean hasWidth() { return this.width > 0; }
  void resetWidth() { this.width = 0; }
}
'''


def write_corpus_from_lines(tmp_path, lines):
    """Context lines -> .c2v corpus + .dict.c2v pickles (the vocab the
    model builds from), like a preprocessed dataset would."""
    import pickle
    prefix = tmp_path / 'ds'
    (tmp_path / 'ds.train.c2v').write_text('\n'.join(lines) + '\n')
    token_count, path_count, target_count = {}, {}, {}
    for line in lines:
        parts = line.strip().split(' ')
        target_count[parts[0]] = target_count.get(parts[0], 0) + 1
        for ctx in parts[1:]:
            if not ctx:
                continue
            s, p, t = ctx.split(',')
            token_count[s] = token_count.get(s, 0) + 1
            token_count[t] = token_count.get(t, 0) + 1
            path_count[p] = path_count.get(p, 0) + 1
    with open(str(prefix) + '.dict.c2v', 'wb') as f:
        pickle.dump(token_count, f)
        pickle.dump(path_count, f)
        pickle.dump(target_count, f)
        pickle.dump(len(lines), f)
    return prefix


@pytest.mark.slow  # heaviest index-family test (~4s): the fast path
# is covered by test_build_query_and_jsonl_batch_mode below
@pytest.mark.skipif(not os.path.isfile(EXTRACTOR),
                    reason='extractor binary not built')
def test_extract_to_neighbors_round_trip(tmp_path):
    """Acceptance: extract real Java -> corpus + index -> paste a method
    back through the engine -> its own corpus row is the top neighbor,
    labeled with its method name, in one warm round-trip."""
    from code2vec_tpu.index.service import build_index
    from code2vec_tpu.model_api import Code2VecModel
    from code2vec_tpu.serving.extractor_bridge import Extractor

    java_path = tmp_path / 'Probe.java'
    java_path.write_text(JAVA_SOURCE)
    config = Config(MAX_CONTEXTS=32)
    lines, _unhash = Extractor(config).extract_paths(str(java_path))
    assert len(lines) == 4  # the four methods above
    prefix = write_corpus_from_lines(tmp_path, lines)

    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=32, TRAIN_BATCH_SIZE=8,
        TEST_BATCH_SIZE=8, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,64', INDEX_NEIGHBORS_K=4)
    model = Code2VecModel(config)
    index = build_index(model, config,
                        source=str(prefix) + '.train.c2v')
    assert index.count == 4
    with model.serving_engine(tiers=('vectors',)) as engine:
        engine.attach_index(index)
        # "paste a method": re-extract and submit each method's contexts
        for i, line in enumerate(lines):
            (result,) = engine.predict_neighbors([line], k=2,
                                                 timeout=300)
            assert result.indices[0] == i
            assert result.labels[0] == line.split()[0]
            assert abs(result.scores[0] - 1.0) < 1e-4


@pytest.fixture(scope='module')
def model():
    from code2vec_tpu.model_api import Code2VecModel
    from tests.test_train_overfit import make_dataset
    import tempfile
    import pathlib
    prefix = make_dataset(pathlib.Path(tempfile.mkdtemp('idx_serving')))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,64')
    return Code2VecModel(config)


def test_build_query_and_jsonl_batch_mode(model, tmp_path):
    """--build-index + --query-neighbors equivalent: corpus-built exact
    index with labels, batch JSONL emission, self-retrieval at rank 0."""
    from code2vec_tpu.index.service import (build_index, load_index,
                                            query_neighbors_file)
    config = model.config
    corpus = config.train_data_path
    index = build_index(model, config, source=corpus,
                        out_dir=str(tmp_path / 'c.vecindex'))
    assert index.count == 60 and index.labels is not None
    n, out_path = query_neighbors_file(
        model, config, index=index, corpus_path=corpus,
        output_path=str(tmp_path / 'n.jsonl'))
    assert n == 60
    records = [json.loads(line) for line in open(out_path)]
    assert len(records) == 60
    for record in records[:8]:
        top = record['neighbors'][0]
        assert top['label'] == record['name']
        assert abs(top['score'] - 1.0) < 1e-4
    # reopen from disk at the exact tier
    reloaded = load_index(str(tmp_path / 'c.vecindex'), config, model)
    values, indices = reloaded.search(
        np.asarray(index._matrix)[:3], 1)
    assert list(indices[:, 0]) == [0, 1, 2]


def test_submit_neighbors_accepts_raw_vectors(model, tmp_path):
    from code2vec_tpu.index.service import build_index
    config = model.config
    index = build_index(model, config, source=config.train_data_path,
                        out_dir=str(tmp_path / 'v.vecindex'))
    row = np.asarray(index._matrix)[5]
    with model.serving_engine(tiers=('vectors',)) as engine:
        engine.attach_index(index)
        (result,) = engine.submit_neighbors(row, k=3).result(timeout=300)
    assert result.indices[0] == 5


def test_submit_neighbors_requires_vectors_tier_and_index(model):
    with model.serving_engine(tiers=('topk',), warmup=False) as engine:
        with pytest.raises(ValueError, match='vectors'):
            engine.attach_index(object())
        with pytest.raises(RuntimeError, match='index'):
            engine.submit_neighbors(['x y,z,w'])


def test_cli_flags_map_to_config():
    config = Config().load_from_args([
        '--load', 'm/s', '--build-index', 'corpus.c2v',
        '--index-path', 'idx.vecindex', '--query-neighbors', 'q.c2v',
        '--index-kind', 'ivf', '--index-metric', 'dot',
        '--nprobe', '4', '--index-clusters', '32', '--neighbors-k', '7',
        '--vectors-dtype', 'float16', '--export_vocab_vectors', 'vocab'])
    assert config.BUILD_INDEX_FROM == 'corpus.c2v'
    assert config.INDEX_PATH == 'idx.vecindex'
    assert config.QUERY_NEIGHBORS_PATH == 'q.c2v'
    assert config.INDEX_KIND == 'ivf'
    assert config.INDEX_METRIC == 'dot'
    assert config.INDEX_NPROBE == 4
    assert config.INDEX_CLUSTERS == 32
    assert config.INDEX_NEIGHBORS_K == 7
    assert config.VECTORS_DTYPE == 'float16'
    assert config.EXPORT_VOCAB_VECTORS == 'vocab'


def test_query_neighbors_without_index_is_rejected(tmp_path):
    config = Config(MODEL_LOAD_PATH=str(tmp_path / 's'),
                    QUERY_NEIGHBORS_PATH='q.c2v')
    with pytest.raises(ValueError, match='query-neighbors'):
        config.verify()


def test_export_vocab_vectors_files_index_as_name_store(model, tmp_path):
    """ISSUE 5 satellite: --export_vocab_vectors writes both tables in
    word2vec text format, and the target table indexes into a
    nearest-method-NAME store."""
    from code2vec_tpu.index import store as store_lib
    from code2vec_tpu.index.exact import ExactIndex
    from code2vec_tpu.vocab import VocabType
    prefix = str(tmp_path / 'vocab')
    model.save_word2vec_format(prefix + '.tokens.txt', VocabType.Token)
    model.save_word2vec_format(prefix + '.targets.txt', VocabType.Target)
    store = store_lib.build_from_word2vec(prefix + '.targets.txt')
    assert store.count == model.vocabs.target_vocab.size
    index = ExactIndex(store)
    table = model.get_vocab_embedding_as_np_array(VocabType.Target)
    _v, indices = index.search(table[2], 1)
    assert indices[0, 0] == 2
    assert index.labels[2] == model.vocabs.target_vocab.index_to_word[2]
