"""Telemetry layer tests (ISSUE 2): core instrument semantics, windowed
percentiles, registry isolation, exporter round-trips, the jit trackers,
the on-demand trace controller, and the trainer's step-phase breakdown on
a tiny synthetic corpus."""
import json
import os
import threading
import time

import pytest

from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry.core import Timer
from code2vec_tpu.telemetry.exporters import (ConsoleExporter, JsonlExporter,
                                              PrometheusExporter)
from code2vec_tpu.telemetry.jit_tracker import (CapacityTracker,
                                                install_compile_listener)
from code2vec_tpu.telemetry.trace import TraceController


@pytest.fixture(autouse=True)
def fresh_registry():
    """Registry reset between tests: telemetry state is process-global by
    design, so every test starts and ends clean."""
    core.reset()
    core.disable()
    yield
    core.reset()
    core.disable()


# ------------------------------------------------------------- instruments
def test_counter_semantics():
    counter = core.registry().counter('t/c')
    counter.inc()
    counter.inc(5)
    assert counter.value == 6


def test_counter_thread_safety():
    counter = core.registry().counter('t/c')

    def spin():
        for _ in range(1000):
            counter.inc()

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert counter.value == 4000


def test_gauge_last_write_wins():
    gauge = core.registry().gauge('t/g')
    gauge.set(3.5)
    gauge.set(1.25)
    assert gauge.value == 1.25


def test_timer_stats_and_percentiles():
    timer = Timer('t/ms')
    for ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 100):
        timer.record(ms / 1e3)
    stats = timer.snapshot()
    assert stats['count'] == 10
    assert stats['last_ms'] == pytest.approx(100.0)
    assert stats['max_ms'] == pytest.approx(100.0)
    assert stats['mean_ms'] == pytest.approx(14.5)
    assert 5.0 <= stats['p50_ms'] <= 6.0
    assert stats['p95_ms'] >= 9.0
    assert stats['total_s'] == pytest.approx(0.145)


def test_timer_window_bounds_stats_not_count():
    timer = Timer('t/ms', window=4)
    for ms in (1000, 1000, 1000, 1, 1, 1, 1):  # old spikes roll out
        timer.record(ms / 1e3)
    stats = timer.snapshot()
    assert stats['count'] == 7          # cumulative
    assert stats['p95_ms'] == pytest.approx(1.0)   # window forgot spikes
    # max is windowed too: a warmup compile must not pin the exported
    # max for the rest of a multi-hour run
    assert stats['max_ms'] == pytest.approx(1.0)


def test_timer_context_manager_records():
    timer = Timer('t/ms')
    with timer.time():
        time.sleep(0.01)
    assert timer.count == 1
    assert timer.last >= 0.009


# ---------------------------------------------------------------- registry
def test_registry_get_or_create_identity_and_type_check():
    reg = core.registry()
    assert reg.counter('t/a') is reg.counter('t/a')
    with pytest.raises(TypeError):
        reg.gauge('t/a')


def test_registry_reset_clears():
    reg = core.registry()
    reg.counter('t/a').inc()
    core.reset()
    assert reg.counter('t/a').value == 0


def test_enable_disable_flag():
    assert not core.enabled()
    core.enable()
    assert core.enabled()
    core.disable()
    assert not core.enabled()


# --------------------------------------------------------------- exporters
def test_jsonl_round_trip(tmp_path):
    reg = core.registry()
    reg.counter('train/steps_total').inc(7)
    reg.gauge('train/examples_per_sec').set(123.5)
    timer = reg.timer('step/dispatch_ms')
    timer.record(0.002)
    timer.record(0.004)
    exporter = JsonlExporter(str(tmp_path))
    exporter.flush(reg, step=42)
    exporter.flush(reg, step=43)
    records = [json.loads(line) for line in
               (tmp_path / 'metrics.jsonl').read_text().splitlines()]
    by_tag = {}
    for record in records:
        by_tag.setdefault(record['tag'], []).append(record)
    assert [r['value'] for r in by_tag['train/steps_total']] == [7, 7]
    assert by_tag['train/examples_per_sec'][0]['value'] == 123.5
    timer_rec = by_tag['step/dispatch_ms'][0]
    assert timer_rec['count'] == 2
    assert timer_rec['value'] == pytest.approx(3.0)       # mean ms
    assert timer_rec['p50_ms'] > 0 and timer_rec['max_ms'] >= 4.0
    assert all(r['step'] in (42, 43) for r in records)


def test_jsonl_skips_empty_timers(tmp_path):
    reg = core.registry()
    reg.timer('step/sync_ms')  # created, never recorded
    JsonlExporter(str(tmp_path)).flush(reg, step=0)
    assert not (tmp_path / 'metrics.jsonl').exists()


def test_prometheus_textfile(tmp_path):
    reg = core.registry()
    reg.counter('jit/compiles_total').inc(3)
    reg.gauge('input/packed_fill_rate').set(0.75)
    reg.timer('step/h2d_ms').record(0.001)
    PrometheusExporter(str(tmp_path)).flush(reg, step=1)
    text = (tmp_path / 'metrics.prom').read_text()
    assert 'code2vec_jit_compiles_total 3' in text
    assert '# TYPE code2vec_jit_compiles_total counter' in text
    assert 'code2vec_input_packed_fill_rate 0.75' in text
    # timers export per-stat gauges (a real 'summary' family needs
    # quantile labels + _sum; strict parsers drop the file otherwise)
    assert '# TYPE code2vec_step_h2d_ms_p50_ms gauge' in text
    assert 'code2vec_step_h2d_ms_mean_ms 1' in text
    assert 'code2vec_step_h2d_ms_count 1' in text
    assert 'summary' not in text
    assert not (tmp_path / 'metrics.prom.tmp').exists()  # atomic rename


def test_prometheus_replica_labels_grouped_per_family(tmp_path):
    """Replica-labeled series (mesh replicas, catalog 'Instance
    labels'): one HELP/TYPE header per FAMILY with the labeled samples
    contiguous under it — strict expfmt parsers drop the whole file on
    a repeated header or a split family (the full-name sort would
    otherwise interleave r0's timer stat families with r1's)."""
    from code2vec_tpu.telemetry.core import ScopedRegistry
    reg = core.registry()
    for rid in ('r0', 'r1'):
        scoped = ScopedRegistry(reg, 'replica', rid)
        scoped.counter('serving/shed_total').inc(2)
        scoped.timer('serving/dispatch_ms').record(0.002)
    PrometheusExporter(str(tmp_path)).flush(reg, step=1)
    lines = (tmp_path / 'metrics.prom').read_text().splitlines()
    assert 'code2vec_serving_shed_total{replica="r0"} 2' in lines
    assert 'code2vec_serving_shed_total{replica="r1"} 2' in lines
    # headers once per family; labeled samples directly follow theirs
    for family in ('code2vec_serving_shed_total',
                   'code2vec_serving_dispatch_ms_mean_ms',
                   'code2vec_serving_dispatch_ms_count'):
        types = [i for i, line in enumerate(lines)
                 if line == '# TYPE %s %s'
                 % (family, 'counter' if family.endswith(('total',
                                                          'count'))
                    else 'gauge')]
        assert len(types) == 1, (family, lines)
        samples = [i for i, line in enumerate(lines)
                   if line.startswith(family + '{')]
        assert len(samples) == 2, (family, lines)
        # contiguous group right under the single header
        assert samples == [types[0] + 1, types[0] + 2], (family, lines)


def test_console_exporter_rate_limited():
    lines = []
    exporter = ConsoleExporter(lines.append, min_interval_s=3600.0)
    reg = core.registry()
    exporter.flush(reg, step=1)
    exporter.flush(reg, step=2)  # inside the interval: suppressed
    assert len(lines) == 1
    assert 'telemetry step 1' in lines[0]


# ------------------------------------------------------------ jit tracking
def test_capacity_tracker_counts_respecializations_once_per_bucket():
    lines = []
    tracker = CapacityTracker(log=lines.append)
    tracker.observe(64, step=0)    # initial specialization: not a re-spec
    tracker.observe(64, step=1)
    tracker.observe(128, step=2)   # growth: one re-spec
    tracker.observe(128, step=3)
    reg = core.registry()
    assert reg.counter('jit/respecializations_total').value == 1
    assert reg.gauge('jit/packed_capacity').value == 128
    assert len(lines) == 2 and 'bucket 128' in lines[1]


def test_compile_listener_counts_jax_compiles():
    import jax
    import jax.numpy as jnp
    assert install_compile_listener()
    core.enable()
    before = core.registry().counter('jit/compiles_total').value
    # a shape this process has certainly not compiled yet
    jax.jit(lambda x: x * 3 + 1)(jnp.ones((17, 3))).block_until_ready()
    after = core.registry().counter('jit/compiles_total').value
    assert after > before
    assert core.registry().timer('jit/compile_ms').count > 0


# ----------------------------------------------------------- trace control
class _FakeProfiler:
    def __init__(self, monkeypatch):
        import jax
        self.calls = []
        monkeypatch.setattr(jax.profiler, 'start_trace',
                            lambda d: self.calls.append(('start', d)))
        monkeypatch.setattr(jax.profiler, 'stop_trace',
                            lambda: self.calls.append(('stop', None)))


def test_trace_controller_at_step(tmp_path, monkeypatch):
    fake = _FakeProfiler(monkeypatch)
    ctl = TraceController(str(tmp_path), trace_at_step=3, num_steps=2)
    for step in range(8):
        ctl.maybe_update(step)
    assert [c[0] for c in fake.calls] == ['start', 'stop']
    assert fake.calls[0][1].endswith(os.path.join('traces', 'step3'))
    assert core.registry().counter('trace/captures_total').value == 1


def test_trace_controller_touch_file(tmp_path, monkeypatch):
    fake = _FakeProfiler(monkeypatch)
    ctl = TraceController(str(tmp_path), trace_at_step=-1, num_steps=1,
                          poll_every=2)
    ctl.maybe_update(0)
    assert not fake.calls
    (tmp_path / 'TRACE_NOW').touch()
    ctl.maybe_update(1)          # off-poll step: not yet seen
    assert not fake.calls
    ctl.maybe_update(2)          # poll step: consume + start
    assert fake.calls == [('start', str(tmp_path / 'traces' / 'step2'))]
    assert not (tmp_path / 'TRACE_NOW').exists()
    ctl.maybe_update(3)
    assert [c[0] for c in fake.calls] == ['start', 'stop']
    # repeatable: touch again for another capture
    (tmp_path / 'TRACE_NOW').touch()
    ctl.maybe_update(4)
    assert [c[0] for c in fake.calls] == ['start', 'stop', 'start']


def test_trace_controller_env_var(tmp_path, monkeypatch):
    monkeypatch.setenv('TELEMETRY_TRACE_AT_STEP', '5')
    ctl = TraceController(str(tmp_path), trace_at_step=-1)
    assert ctl.trace_at_step == 5
    # an explicit config value wins over the env
    ctl2 = TraceController(str(tmp_path), trace_at_step=9)
    assert ctl2.trace_at_step == 9


def test_env_trace_var_implies_telemetry_layer(monkeypatch):
    """TELEMETRY_TRACE_AT_STEP exists for runs launched by scripts you
    can't edit — without implying TELEMETRY it would be silently inert
    (no TraceController is ever built)."""
    from code2vec_tpu.config import Config
    monkeypatch.setenv('TELEMETRY_TRACE_AT_STEP', '500')
    config = Config().load_from_args(['--data', 'x'])
    assert config.TELEMETRY
    assert config.TELEMETRY_TRACE_AT_STEP == 500
    # the explicit flag wins over the env var
    config2 = Config().load_from_args(['--data', 'x',
                                       '--trace-at-step', '9'])
    assert config2.TELEMETRY_TRACE_AT_STEP == 9
    monkeypatch.delenv('TELEMETRY_TRACE_AT_STEP')
    config3 = Config().load_from_args(['--data', 'x'])
    assert not config3.TELEMETRY


# ------------------------------------------- trainer phase breakdown (e2e)
def _read_tags(path):
    records = [json.loads(line) for line in
               open(path).read().splitlines()]
    by_tag = {}
    for record in records:
        by_tag.setdefault(record['tag'], []).append(record)
    return by_tag


def test_fit_phase_breakdown_tiny_corpus(tmp_path):
    """The ISSUE 2 acceptance smoke: a CPU fit with telemetry enabled
    must produce a metrics.jsonl with per-step phase timings, throughput
    counters, and at least one jit-compilation event — plus epoch/eval
    wall-time through the MetricsWriter."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from tests.test_train_overfit import make_dataset

    prefix = make_dataset(tmp_path)
    tele_dir = tmp_path / 'tele'
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix),
        TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
        MODEL_SAVE_PATH=str(tmp_path / 'model' / 'saved'),
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=2,
        SAVE_EVERY_EPOCHS=1000, SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0,
        READER_USE_NATIVE=False, NUM_BATCHES_TO_LOG_PROGRESS=2,
        USE_TENSORBOARD=True,
        TELEMETRY=True, TELEMETRY_DIR=str(tele_dir),
        TELEMETRY_FLUSH_EVERY_STEPS=2, TELEMETRY_CONSOLE_EVERY_SECS=0.0)
    model = Code2VecModel(config)
    model.train()

    by_tag = _read_tags(tele_dir / 'metrics.jsonl')
    # per-step phase timings (batch-wait, h2d, step, sync)
    for phase in ('step/batch_wait_ms', 'step/h2d_ms', 'step/dispatch_ms',
                  'step/sync_ms', 'step/total_ms'):
        assert phase in by_tag, sorted(by_tag)
        assert by_tag[phase][-1]['count'] > 0
    # throughput counters and rates
    assert by_tag['train/steps_total'][-1]['value'] >= 6  # 60/16*2 epochs
    assert by_tag['train/examples_total'][-1]['value'] >= 100
    assert by_tag['train/contexts_total'][-1]['value'] > 0
    assert any(r['value'] > 0 for r in by_tag['train/examples_per_sec'])
    # at least one jit-compilation event
    assert by_tag['jit/compiles_total'][-1]['value'] >= 1
    assert by_tag['jit/compile_ms'][-1]['count'] >= 1
    # packed wire: capacity gauge + pipeline health
    assert by_tag['jit/packed_capacity'][-1]['value'] > 0
    assert by_tag['input/batches_total'][-1]['value'] > 0
    assert 0 < by_tag['input/packed_fill_rate'][-1]['value'] <= 1.0
    assert by_tag['input/cache_miss_total'][-1]['value'] == 1
    assert by_tag['train/epoch_wall_time_s'][-1]['value'] > 0
    # the Prometheus textfile tracks the same registry
    prom = (tele_dir / 'metrics.prom').read_text()
    assert 'code2vec_train_steps_total' in prom

    # epoch + eval wall time through the MetricsWriter (satellite 2)
    writer_tags = _read_tags(tmp_path / 'model' / 'summaries'
                             / 'metrics.jsonl')
    assert len(writer_tags['train/epoch_wall_time_s']) == 2  # one/epoch
    assert all(r['value'] > 0
               for r in writer_tags['train/epoch_wall_time_s'])
    assert 'eval/wall_time_s' in writer_tags
    assert writer_tags['eval/wall_time_s'][-1]['value'] > 0

    # fit's teardown must drop the process-global flag: later
    # non-telemetry runs in this process must not keep recording
    assert not core.enabled()

    # a second open of the same dataset hits the token cache (no need to
    # train a whole second model for the counter)
    from code2vec_tpu.data.cache import TokenCache
    from code2vec_tpu.data.reader import EstimatorAction, PathContextReader
    reader = PathContextReader(model.vocabs, config, EstimatorAction.Train)
    core.enable()  # as a live telemetry run would be
    TokenCache.build_or_load(config, model.vocabs, reader)
    assert core.registry().counter('input/cache_hit_total').value >= 1


# --------------------------------------- ISSUE 8: concurrency coverage
def test_trace_controller_touch_during_active_capture_defers(
        tmp_path, monkeypatch):
    """A TRACE_NOW touched while a capture is ALIVE must not try to nest
    (jax.profiler cannot); it stays on disk and arms the next window."""
    fake = _FakeProfiler(monkeypatch)
    ctl = TraceController(str(tmp_path), trace_at_step=-1, num_steps=4,
                          poll_every=1)
    (tmp_path / 'TRACE_NOW').touch()
    ctl.maybe_update(0)  # consume + start; active through step 3
    assert [c[0] for c in fake.calls] == ['start']
    (tmp_path / 'TRACE_NOW').touch()  # touched mid-capture
    ctl.maybe_update(1)
    ctl.maybe_update(2)
    assert [c[0] for c in fake.calls] == ['start'], 'nested start'
    ctl.maybe_update(4)  # window over: stop
    assert [c[0] for c in fake.calls] == ['start', 'stop']
    # the deferred touch arms the NEXT window and is consumed exactly once
    ctl.maybe_update(5)
    assert [c[0] for c in fake.calls] == ['start', 'stop', 'start']
    assert not (tmp_path / 'TRACE_NOW').exists()


def test_trace_controller_touch_consumed_exactly_once(
        tmp_path, monkeypatch):
    """One touch = one capture: after the armed window starts, later
    poll steps must not re-start from the same (deleted) touch file."""
    fake = _FakeProfiler(monkeypatch)
    ctl = TraceController(str(tmp_path), trace_at_step=-1, num_steps=1,
                          poll_every=1)
    (tmp_path / 'TRACE_NOW').touch()
    ctl.maybe_update(0)
    ctl.maybe_update(1)  # stop
    for step in range(2, 6):
        ctl.maybe_update(step)  # no touch file: must stay idle
    assert [c[0] for c in fake.calls] == ['start', 'stop']


def test_jsonl_exporter_concurrent_flushers_no_torn_lines(tmp_path):
    """ISSUE 8 satellite: the trainer's hot-loop flush and a serving
    engine's (or harness's) flush may share one exporter; concurrent
    appends must never interleave mid-record."""
    import json as json_lib
    import threading
    core.reset()
    reg = core.registry()
    for i in range(40):  # a payload big enough to span write buffers
        reg.gauge('stress/gauge_with_a_deliberately_long_name_%03d'
                  % i).set(float(i))
    exporter = JsonlExporter(str(tmp_path))
    n_threads, n_flushes = 8, 25

    def flusher(idx):
        for k in range(n_flushes):
            exporter.flush(reg, step=idx * n_flushes + k)

    threads = [threading.Thread(target=flusher, args=(i,))
               for i in range(n_threads)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    with open(tmp_path / 'metrics.jsonl') as f:
        lines = [line for line in f.read().splitlines() if line]
    # every line parses (no torn/interleaved records) and the record
    # count is exactly flushes x instruments
    records = [json_lib.loads(line) for line in lines]
    assert len(records) == n_threads * n_flushes * 40
    assert all(r['tag'].startswith('stress/') for r in records)
    core.reset()
