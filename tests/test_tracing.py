"""Per-request distributed tracing (ISSUE 8; telemetry/tracing.py,
OBSERVABILITY.md "Per-request serving traces").

Three layers:

1. **tracer core** — head sampling vs tail retention, the bounded
   flight-recorder ring with debounced dumps, shed-burst detection, and
   valid JSONL under concurrent writers;
2. **latency_report** — the phase x bucket x tier table, queue-vs-device
   decomposition, span trees, and the Perfetto conversion over synthetic
   spans;
3. **the acceptance drill** — overload (queue bound + injected
   ``slow_dispatch``) plus extractor_crash and a canary rollback:
   every submitted request's full span tree reconstructs from the JSONL
   log, shed/expired/closed requests carry their reason span, per-phase
   durations sum to within tolerance of end-to-end latency,
   latency_report produces the breakdown from that log, and the compile
   counter confirms ZERO post-warmup compiles with tracing enabled.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
_SCRIPTS = os.path.join(REPO, 'scripts')
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

import latency_report  # noqa: E402

from code2vec_tpu.config import Config  # noqa: E402
from code2vec_tpu.resilience import faults  # noqa: E402
from code2vec_tpu.serving.errors import (DeadlineExceeded,  # noqa: E402
                                         EngineClosed, EngineOverloaded)
from code2vec_tpu.telemetry.tracing import (SPAN_CATALOG,  # noqa: E402
                                            Tracer)
from tests.test_train_overfit import make_dataset  # noqa: E402

PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]

#: disjoint per-request phases whose durations must (nearly) tile the
#: root span of a delivered request
PHASE_CHAIN = latency_report.PHASE_CHAIN


@pytest.fixture(autouse=True)
def clear_fault_plan():
    faults.configure('')
    yield
    faults.configure('')


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('tracing'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8')
    return Code2VecModel(config)


def _wait_until(predicate, timeout=10.0, what='condition'):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return
        time.sleep(0.001)
    raise AssertionError('timed out waiting for %s' % what)


def _stall_dispatcher(engine, line):
    """Submit a plug request and wait for the dispatcher to POP it into
    the injected slow_dispatch stall (test_serving_resilience idiom)."""
    plug = engine.submit([line], tier='topk')
    _wait_until(lambda: engine.queue_depth.snapshot() == 0,
                what='dispatcher to pop the plug batch')
    return plug


def _read_traces(spans_path):
    return latency_report.group_traces(
        latency_report.load_spans(spans_path))


def _names(entry):
    return [rec['name'] for rec in entry['spans']]


# ------------------------------------------------------------ tracer core
def test_head_sampling_and_tail_retention(tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=0.0, slow_ms=50.0)
    # fast + ok + unsampled: counted, ringed, NOT written
    tracer.begin('serving.request').finish(status='ok')
    assert not os.path.exists(tracer.spans_path)
    # shed: tail-retained regardless of sampling
    trace = tracer.begin('serving.request')
    trace.event('serving.shed', attrs={'reason': 'queue bound'})
    trace.finish(status='shed')
    # slow: tail-retained past TRACING_SLOW_MS
    slow = tracer.begin('serving.request')
    slow.root.t0 -= 0.2  # 200ms ago
    slow.finish(status='ok')
    traces = _read_traces(tracer.spans_path)
    statuses = sorted(e['root']['status'] for e in traces.values())
    assert statuses == ['ok', 'shed']
    assert tracer.stats()['traces_total'] == 3
    assert tracer.stats()['retained_total'] == 2
    # sampled=1.0 writes everything
    always = Tracer(str(tmp_path / 'b'), sample_rate=1.0)
    always.begin('serving.request').finish(status='ok')
    assert len(_read_traces(always.spans_path)) == 1


def test_finish_is_idempotent_and_closes_open_spans(tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    trace = tracer.begin('serving.request')
    open_span = trace.span('serving.queue_wait')
    trace.finish(status='closed', reason='shutdown')
    trace.finish(status='ok')  # second finish: dropped
    trace.span_at('serving.pack', 0.0, 1.0)  # post-finish span: dropped
    traces = _read_traces(tracer.spans_path)
    (entry,) = traces.values()
    assert entry['root']['status'] == 'closed'
    assert entry['root']['attrs']['reason'] == 'shutdown'
    names = _names(entry)
    assert names.count('serving.request') == 1
    assert 'serving.pack' not in names
    # the open queue span was closed AT finish, not truncated
    queue = [r for r in entry['spans']
             if r['name'] == 'serving.queue_wait']
    assert queue and queue[0]['t1'] >= queue[0]['t0']
    assert open_span.span_id > 0


def test_flight_ring_bounded_dump_and_debounce(tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=0.0, flight_traces=4,
                    dump_min_interval_s=3600.0)
    for _ in range(10):
        tracer.begin('serving.request').finish(status='ok')
    path = tracer.dump_flight('close', force=True)
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert lines[0]['flight'] == 'close' and lines[0]['traces'] == 4
    assert sum(1 for rec in lines[1:] if rec.get('parent') is None) == 4
    # debounced: a second dump of the same event inside the window skips
    assert tracer.dump_flight('close') is None
    assert tracer.dump_flight('close', force=True) is not None
    # memory-only tracers never dump
    assert Tracer(None).dump_flight('close', force=True) is None


def test_shed_burst_triggers_overload_dump(tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=0.0, shed_burst=3,
                    shed_window_s=60.0)
    tracer.begin('serving.request').finish(status='shed')
    for _ in range(2):
        tracer.note_shed()
    assert not os.path.exists(
        os.path.join(str(tmp_path), 'flight_overload.jsonl'))
    tracer.note_shed()  # third shed inside the window: burst
    assert os.path.exists(
        os.path.join(str(tmp_path), 'flight_overload.jsonl'))
    assert tracer.stats()['flight_dumps_total'] == 1


def test_concurrent_trace_writers_produce_valid_jsonl(tmp_path):
    """ISSUE 8 satellite: submitters, the dispatcher, and decode workers
    finish traces concurrently; the span log must never tear."""
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    n_threads, n_traces, n_spans = 8, 20, 6

    def worker(idx):
        for k in range(n_traces):
            trace = tracer.begin('serving.request',
                                 attrs={'tier': 'topk', 'rows': idx})
            for s in range(n_spans):
                trace.span_at('serving.pack', float(k), float(k + 1),
                              attrs={'bucket': s})
            trace.finish(status='ok')

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    with open(tracer.spans_path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    traces = latency_report.group_traces(records)
    assert len(traces) == n_threads * n_traces
    for entry in traces.values():
        assert entry['root'] is not None
        assert len(entry['spans']) == 1 + n_spans
    assert tracer.stats()['traces_total'] == n_threads * n_traces


# --------------------------------------------------------- latency_report
def _synth_records():
    recs = []

    def span(trace, sid, parent, name, t0, t1, status=None, attrs=None):
        rec = {'trace': trace, 'span': sid, 'parent': parent,
               'name': name, 't0': t0, 't1': t1,
               'dur_ms': (t1 - t0) * 1e3}
        if attrs:
            rec['attrs'] = attrs
        if status:
            rec['status'] = status
        recs.append(rec)

    span('t1', 0, None, 'serving.request', 0.0, 0.1, status='ok',
         attrs={'tier': 'topk', 'rows': 2})
    span('t1', 1, 0, 'serving.queue_wait', 0.0, 0.04)
    span('t1', 2, 0, 'serving.pack', 0.04, 0.05,
         attrs={'bucket': 8, 'tier': 'topk'})
    span('t1', 3, 0, 'serving.device_execute', 0.05, 0.09)
    span('t1', 4, 3, 'serving.fetch', 0.06, 0.09)
    span('t2', 0, None, 'serving.request', 0.0, 0.01, status='shed',
         attrs={'tier': 'full', 'reason': 'queue bound'})
    span('t2', 1, 0, 'serving.shed', 0.01, 0.01)
    return recs


def test_latency_report_tables_and_decomposition():
    traces = latency_report.group_traces(_synth_records())
    rows = latency_report.phase_rows(traces)
    # replica '-' = single-engine traffic (a mesh stamps its replica id
    # on the pack span, scripts/latency_report.py per-replica columns)
    assert rows[('serving.request', 'topk', '8', '-')] == [100.0]
    # shed trace never dispatched: bucket '-'
    assert rows[('serving.shed', 'full', '-', '-')] == [0.0]
    decomp = latency_report.decomposition(traces)
    assert decomp['end_to_end'] == [100.0]
    assert decomp['queue_wait'] == [pytest.approx(40.0)]
    assert decomp['device'] == [pytest.approx(40.0)]
    assert latency_report.status_counts(traces) == {'ok': 1, 'shed': 1}
    # nearest-rank percentiles
    assert latency_report.percentile([1.0, 2.0, 10.0], 0.5) == 2.0
    assert latency_report.percentile([], 0.99) == 0.0


def test_latency_report_tree_and_perfetto(tmp_path):
    traces = latency_report.group_traces(_synth_records())
    (t1_lines,) = [latency_report.format_tree(entry)
                   for tid, entry in traces.items() if tid == 't1']
    assert 'serving.request' in t1_lines[0]
    # fetch nests two deep (request -> device_execute -> fetch)
    (fetch_line,) = [line for line in t1_lines if 'serving.fetch' in line]
    assert fetch_line.startswith('  ' * 3)
    events = latency_report.to_perfetto(traces)
    assert len(events) == 7
    assert all(e['ph'] == 'X' and e['ts'] >= 0 and e['dur'] >= 0
               for e in events)
    lanes = {e['tid'] for e in events}
    assert len(lanes) == 2  # one lane per trace


# --------------------------------------------------- engine span lifecycle
def test_span_tree_complete_with_oversize_split_and_join(model, tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    lines = PREDICT_LINES * 7  # 21 rows > bucket 8: splits into 3 chunks
    with model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                              tracer=tracer) as engine:
        single = engine.predict(PREDICT_LINES[:1], tier='topk',
                                timeout=60)
        assert single[0].topk_predicted_words
        results = engine.predict(lines, tier='topk', timeout=120)
        assert len(results) == len(lines)
    traces = _read_traces(tracer.spans_path)
    assert len(traces) == 2
    by_rows = {e['root']['attrs']['rows']: e for e in traces.values()}
    # the single request carries the full disjoint phase chain
    names = _names(by_rows[1])
    for phase in PHASE_CHAIN:
        if phase == 'serving.stall':
            continue  # drills only
        assert phase in names, (phase, names)
    # the oversize request: 3 chunk spans, phases nested under them,
    # one join, root finished ok
    oversize = by_rows[21]
    assert oversize['root']['status'] == 'ok'
    chunks = [r for r in oversize['spans'] if r['name'] == 'serving.chunk']
    assert [c['attrs']['rows'] for c in chunks] == [8, 8, 5]
    assert sum(1 for r in oversize['spans']
               if r['name'] == 'serving.join') == 1
    chunk_ids = {c['span'] for c in chunks}
    packs = [r for r in oversize['spans'] if r['name'] == 'serving.pack']
    assert len(packs) == 3
    assert all(p['parent'] in chunk_ids for p in packs)
    # chunk spans were closed at deliver, not left open
    assert all(c['t1'] > c['t0'] for c in chunks)


def test_phase_durations_sum_to_end_to_end(model, tmp_path):
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    with model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                              tracer=tracer) as engine:
        futures = [engine.submit([line], tier='topk')
                   for line in PREDICT_LINES * 3]
        for future in futures:
            future.result(timeout=60)
    traces = _read_traces(tracer.spans_path)
    assert len(traces) == 9
    for entry in traces.values():
        total = float(entry['root']['dur_ms'])
        phase_sum = sum(float(r['dur_ms']) for r in entry['spans']
                        if r['name'] in PHASE_CHAIN)
        # disjoint phases tile the root up to scheduler gaps (handoffs
        # between submitter/dispatcher/decode threads): they must cover
        # most of it and can overshoot only by clock-read epsilon
        assert phase_sum <= total * 1.05 + 2.0, (phase_sum, total)
        assert phase_sum >= total * 0.5, \
            'phases cover %.2f of %.2fms only: %r' % (
                phase_sum, total,
                [(r['name'], r['dur_ms']) for r in entry['spans']])


def test_canary_shadow_span_and_rollback_flight_dump(model, tmp_path):
    import jax
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    broken = jax.tree_util.tree_map(lambda leaf: -leaf, model.params)
    jax.block_until_ready(broken)
    with model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                              tracer=tracer) as engine:
        handle = engine.load_params(broken, canary_batches=1,
                                    min_agreement=0.9)
        engine.predict(PREDICT_LINES, tier='topk', timeout=60)
        report = handle.result(timeout=60)
    assert report['swapped'] is False
    traces = _read_traces(tracer.spans_path)
    shadows = [e for e in traces.values()
               if e['root']['name'] == 'serving.canary_shadow']
    assert len(shadows) == 1
    attrs = shadows[0]['root']['attrs']
    assert attrs['rows'] == 3 and 'agree_rows' in attrs
    assert os.path.exists(
        os.path.join(str(tmp_path), 'flight_rollover_rollback.jsonl'))


def test_extractor_pool_spans_and_breaker_flight_dump(tmp_path):
    from code2vec_tpu.serving.extractor_bridge import ExtractorPool
    tracer = Tracer(str(tmp_path), sample_rate=1.0)
    config = Config(MAX_CONTEXTS=6, EXTRACTOR_RETRIES=1,
                    EXTRACTOR_BACKOFF_SECS=0.0,
                    EXTRACTOR_BREAKER_THRESHOLD=2,
                    EXTRACTOR_BREAKER_COOLDOWN_SECS=60.0)
    faults.configure('extractor_crash@call=0..63')
    with ExtractorPool(config,
                       extractor_command=[sys.executable, '-c', 'pass'],
                       tracer=tracer) as pool:
        from code2vec_tpu.serving.errors import (ExtractorCrash,
                                                 ExtractorUnavailable)
        for _ in range(2):  # threshold crashes (each retried once)
            with pytest.raises(ExtractorCrash):
                pool.extract_paths(str(tmp_path / 'T.java'), timeout=60)
        assert pool.state() == 'open'
        with pytest.raises(ExtractorUnavailable):
            pool.extract_paths(str(tmp_path / 'T.java'), timeout=60)
    traces = _read_traces(tracer.spans_path)
    calls = [e for e in traces.values()
             if e['root']['name'] == 'extractor.call']
    statuses = sorted(e['root']['status'] for e in calls)
    assert statuses == ['crash', 'crash', 'unavailable']
    crash_attrs = [e['root']['attrs'] for e in calls
                   if e['root']['status'] == 'crash']
    # attempt count rides the span: 1 original + 1 retry
    assert all(a['attempts'] == 2 for a in crash_attrs)
    assert all(a['breaker'] in ('closed', 'half-open', 'open')
               for a in crash_attrs)
    assert os.path.exists(
        os.path.join(str(tmp_path), 'flight_breaker_open.jsonl'))


# ------------------------------------------------------- acceptance drill
def test_overload_drill_reconstructs_every_request(model, tmp_path):
    """ISSUE 8 acceptance: overload + slow_dispatch, then a fail-fast
    close with queued work — every submitted request's span tree
    reconstructs from the JSONL log with its terminal reason, the
    flight recorder dumps on the shed burst AND on close, latency_report
    produces the phase x bucket x tier breakdown from that log, and the
    compile counter stays flat post-warmup with tracing enabled."""
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    line = PREDICT_LINES[0]
    tracer = Tracer(str(tmp_path), sample_rate=1.0, shed_burst=3,
                    shed_window_s=30.0)
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                  queue_bound=8, tracer=tracer)
    core.reset()
    core.enable()
    submitted = 0
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        engine.predict([line], tier='topk', timeout=60)  # end-to-end warm
        submitted += 1
        warm_compiles = compiles.value

        faults.configure('slow_dispatch@req=0..63')
        plug = _stall_dispatcher(engine, line)
        submitted += 1
        # deadlined requests expire behind the >=250ms stall; the
        # deadline sits above any plausible drain estimate (seeded from
        # the warm request's sojourn) but a loaded host can still push
        # the estimate over it — those shed at admission instead, and
        # the tallies below absorb either path
        doomed, early_shed = [], 0
        for _ in range(4):
            submitted += 1
            try:
                doomed.append(engine.submit([line], tier='topk',
                                            deadline_ms=150.0))
            except EngineOverloaded:
                early_shed += 1
        # open-loop burst: the queued doomed requests occupy part of the
        # bound, the rest fill it, the overflow sheds; total sheds are 6
        # either way (>= the burst threshold of 3, dumping the recorder)
        admitted, shed = [], 0
        for _ in range(10):
            submitted += 1
            try:
                admitted.append(engine.submit([line], tier='topk'))
            except EngineOverloaded:
                shed += 1
        assert len(admitted) == 8 - len(doomed)
        assert shed == 10 - len(admitted)
        for future in doomed:
            assert isinstance(future.exception(timeout=60),
                              DeadlineExceeded)
        for future in admitted + [plug]:
            future.result(timeout=60)
        # park two more behind a fresh stall, then fail-fast close: the
        # queued traces must still get their terminal serving.closed span
        plug2 = _stall_dispatcher(engine, line)
        submitted += 1
        queued = [engine.submit([line], tier='topk') for _ in range(2)]
        submitted += 2
        postwarm_compiles = compiles.value - warm_compiles
    finally:
        faults.configure('')
        engine.close()
        # an INJECTED tracer is the injector's to close (a mesh shares
        # one across replicas — a retiring replica must not end the
        # fleet's flight recorder); this test owns it, so the close
        # dump happens here
        tracer.close()
        core.disable()
        core.reset()
    plug2.result(timeout=60)  # in-flight batch still delivered
    for future in queued:
        assert isinstance(future.exception(timeout=10), EngineClosed)
    assert postwarm_compiles == 0, (
        '%d XLA compiles during the traced drill' % postwarm_compiles)

    # ---- every submitted request reconstructs, with its reason
    traces = _read_traces(os.path.join(str(tmp_path), 'spans.jsonl'))
    requests = {tid: e for tid, e in traces.items()
                if e['root']['name'] == 'serving.request'}
    assert len(requests) == submitted
    statuses = {}
    for entry in requests.values():
        statuses.setdefault(entry['root']['status'],
                            []).append(entry)
    # warm + 2 plugs + the burst admits
    assert len(statuses.get('ok', ())) == 3 + len(admitted)
    assert len(statuses.get('shed', ())) == early_shed + shed == 6
    assert len(statuses.get('expired', ())) == len(doomed)
    assert len(statuses.get('closed', ())) == 2
    for entry in statuses['shed']:
        (reason,) = [r for r in entry['spans']
                     if r['name'] == 'serving.shed']
        assert 'shed at admission' in reason['attrs']['reason']
    for entry in statuses.get('expired', ()):
        names = _names(entry)
        assert 'serving.expired' in names
        assert 'serving.queue_wait' in names  # admitted, then expired
        assert 'serving.pack' not in names    # never dispatched
    for entry in statuses['closed']:
        (reason,) = [r for r in entry['spans']
                     if r['name'] == 'serving.closed']
        assert 'close(drain=True)' in reason['attrs']['reason']
    # delivered requests: full chain, stall span included, durations
    # sum to within tolerance of the recorded end-to-end latency
    stalled = 0
    for entry in statuses['ok']:
        names = _names(entry)
        for phase in ('serving.queue_wait', 'serving.pack',
                      'serving.device_execute', 'serving.decode',
                      'serving.deliver'):
            assert phase in names, (phase, names)
        stalled += int('serving.stall' in names)
        total = float(entry['root']['dur_ms'])
        phase_sum = sum(float(r['dur_ms']) for r in entry['spans']
                        if r['name'] in PHASE_CHAIN)
        assert phase_sum <= total * 1.05 + 2.0
        assert phase_sum >= total * 0.5, (phase_sum, total)
    assert stalled >= 5  # the drill's stalls are visible in the trees

    # ---- flight recorder: shed burst + close
    assert os.path.exists(
        os.path.join(str(tmp_path), 'flight_overload.jsonl'))
    close_dump = os.path.join(str(tmp_path), 'flight_close.jsonl')
    assert os.path.exists(close_dump)
    dumped = latency_report.load_spans(close_dump)
    assert {r['name'] for r in dumped} >= {'serving.request',
                                           'serving.shed'}

    # ---- latency_report produces the breakdown + perfetto conversion
    perfetto_path = str(tmp_path / 'serving_trace.json')
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts',
                                      'latency_report.py'),
         '--spans', os.path.join(str(tmp_path), 'spans.jsonl'),
         '--json', '--perfetto', perfetto_path],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    phase_rows = [r for r in rows if r['measure'] == 'phase_latency_ms']
    assert any(r['phase'] == 'serving.queue_wait' and r['tier'] == 'topk'
               and r['bucket'] == '8' for r in phase_rows)
    assert any(r['phase'] == 'serving.shed' and r['bucket'] == '-'
               for r in phase_rows)
    assert all(r['p50'] <= r['p99'] for r in phase_rows)
    decomp = [r for r in rows
              if r['measure'] == 'latency_decomposition_ms']
    assert {r['part'] for r in decomp} >= {'end_to_end', 'queue_wait',
                                           'device'}
    with open(perfetto_path) as f:
        perfetto = json.load(f)
    assert perfetto['traceEvents'], 'empty perfetto conversion'
