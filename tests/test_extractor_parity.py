"""Extractor parity hardening (VERDICT r1 #9).

- The operator-spelling table is pinned against GROUND TRUTH extracted
  from the reference's checked-in fat JAR (javaparser-3.0.0-alpha.4 enum
  constant pools — see java_parser.h header for provenance): every
  Binary/Unary/Assign operator rendering is asserted here.
- A differential fuzz proves ``--no_hash`` and hashed output are the same
  extraction modulo ``java_string_hashcode`` on the path field.
- The constructs extractor/README.md flags as deviating (annotations,
  records, explicit generic calls, C# interpolated strings) get tests
  that pin the documented behavior instead of prose.
"""
import os
import random
import subprocess

import pytest

from code2vec_tpu import common

from tests.extractor_bin import BINARY, REPO, binary_missing_reason

pytestmark = pytest.mark.skipif(
    binary_missing_reason() is not None or not os.path.isfile(BINARY),
    reason=str(binary_missing_reason() or 'extractor binary not built'))


def extract(path, no_hash=True, lang=None):
    args = [BINARY, '--max_path_length', '8', '--max_path_width', '2',
            '--file', str(path)]
    if no_hash:
        args.append('--no_hash')
    if lang:
        args += ['--lang', lang]
    proc = subprocess.run(args, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout.splitlines()


def all_paths(lines):
    paths = set()
    for line in lines:
        for ctx in line.split(' ')[1:]:
            pieces = ctx.split(',')
            if len(pieces) == 3:
                paths.add(pieces[1])
    return paths


# ---------------------------------------------------------------- operators

# Ground truth: enum constant names from the reference JAR's
# {Binary,Unary,Assign}Expr$Operator class files (alpha.4 has no toString
# override, so Property.java's getOperator().toString() emits these).
BINARY_OPERATORS = {
    '||': 'or', '&&': 'and', '|': 'binOr', '^': 'xor', '&': 'binAnd',
    '==': 'equals', '!=': 'notEquals', '<': 'less', '>': 'greater',
    '<=': 'lessEquals', '>=': 'greaterEquals', '<<': 'lShift',
    '>>': 'rSignedShift', '>>>': 'rUnsignedShift', '+': 'plus',
    '-': 'minus', '*': 'times', '/': 'divide', '%': 'remainder'}
UNARY_OPERATORS = {
    'prefix ++': 'preIncrement', 'prefix --': 'preDecrement',
    'postfix ++': 'posIncrement', 'postfix --': 'posDecrement',
    '!': 'not', '~': 'inverse', 'unary -': 'negative',
    'unary +': 'positive'}
ASSIGN_OPERATORS = {
    '=': 'assign', '+=': 'plus', '-=': 'minus', '*=': 'star',
    '/=': 'slash', '%=': 'rem', '&=': 'and', '|=': 'or', '^=': 'xor',
    '<<=': 'lShift', '>>=': 'rSignedShift', '>>>=': 'rUnsignedShift'}


def test_every_binary_operator_spelling(tmp_path):
    body = '\n'.join(
        f'boolean m{i}(int a, int b) {{ return (a {op} b) == (a {op} b); }}'
        if name in ('or', 'and') and op in ('||', '&&') else
        f'long m{i}(int a, int b) {{ return (long) (a {op} b); }}'
        for i, (op, name) in enumerate(BINARY_OPERATORS.items())
        if op not in ('||', '&&', '==', '!=', '<', '>', '<=', '>='))
    comparisons = '\n'.join(
        f'boolean c{i}(int a, int b) {{ return a {op} b; }}'
        for i, op in enumerate(['==', '!=', '<', '>', '<=', '>=']))
    logical = ('boolean l0(boolean a, boolean b) { return a || b; }\n'
               'boolean l1(boolean a, boolean b) { return a && b; }\n')
    src = tmp_path / 'B.java'
    src.write_text('class B {\n%s\n%s\n%s\n}\n'
                   % (body, comparisons, logical))
    paths = all_paths(extract(src))
    seen = '\n'.join(sorted(paths))
    for op, name in BINARY_OPERATORS.items():
        assert f'BinaryExpr:{name})' in seen, (op, name)


def test_every_unary_and_assign_operator_spelling(tmp_path):
    src = tmp_path / 'U.java'
    src.write_text(
        'class U {\n'
        '  void u(int a, boolean f) {\n'
        '    ++a; --a; a++; a--;\n'
        '    boolean g = !f; int inv = ~a; int neg = -a; int pos = +a;\n'
        '  }\n'
        '  void s(int a) {\n'
        '    a = 1; a += 1; a -= 1; a *= 2; a /= 2; a %= 2;\n'
        '    a &= 3; a |= 3; a ^= 3; a <<= 1; a >>= 1; a >>>= 1;\n'
        '  }\n'
        '}\n')
    paths = all_paths(extract(src))
    seen = '\n'.join(sorted(paths))
    for desc, name in UNARY_OPERATORS.items():
        assert f'UnaryExpr:{name})' in seen, (desc, name)
    for op, name in ASSIGN_OPERATORS.items():
        assert f'AssignExpr:{name})' in seen, (op, name)


# --------------------------------------------------------- differential fuzz

def _random_java_method(rng: random.Random, index: int) -> str:
    """Small random method exercising operators, calls, arrays, literals."""
    ops = list(BINARY_OPERATORS)
    names = ['alpha', 'beta', 'gamma', 'deltaVal']
    expr = rng.choice(names)
    for _ in range(rng.randint(1, 6)):
        op = rng.choice(ops)
        operand = rng.choice(
            [rng.choice(names), str(rng.randint(0, 99)),
             f'{rng.choice(names)}[{rng.randint(0, 3)}]',
             f'compute{rng.randint(0, 5)}({rng.choice(names)})'])
        expr = f'({expr} {op} {operand})'
    stmts = [f'int {n} = {rng.randint(0, 9)};' for n in names[:2]]
    if rng.random() < 0.5:
        stmts.append(f'if ({names[0]} < {names[1]}) {{ {names[0]}++; }}')
    if rng.random() < 0.3:
        stmts.append(f'for (int k = 0; k < 4; k++) {{ {names[1]} += k; }}')
    return ('  long doWork%d(int[] alpha, int beta, int gamma, int deltaVal)'
            ' {\n    %s\n    return (long) %s;\n  }\n'
            % (index, '\n    '.join(stmts), expr))


@pytest.mark.parametrize('seed', [0, 1, 2, 3])
def test_hashed_output_is_no_hash_output_hashed(tmp_path, seed):
    """Differential fuzz: the hashed and --no_hash modes must be the SAME
    extraction — identical labels and tokens, path field related by
    java_string_hashcode (reference ProgramRelation.java:18-33)."""
    rng = random.Random(seed)
    methods = ''.join(_random_java_method(rng, i)
                      for i in range(rng.randint(2, 5)))
    src = tmp_path / f'F{seed}.java'
    src.write_text('class F%d {\n%s}\n' % (seed, methods))

    plain = extract(src, no_hash=True)
    hashed = extract(src, no_hash=False)
    assert len(plain) == len(hashed) and plain, 'method counts differ'
    for plain_line, hashed_line in zip(plain, hashed):
        plain_parts = plain_line.split(' ')
        hashed_parts = hashed_line.split(' ')
        assert plain_parts[0] == hashed_parts[0]      # label
        assert len(plain_parts) == len(hashed_parts)  # context count
        for plain_ctx, hashed_ctx in zip(plain_parts[1:], hashed_parts[1:]):
            if not plain_ctx:
                assert not hashed_ctx
                continue
            src_tok, path, tgt_tok = plain_ctx.split(',')
            h_src, h_path, h_tgt = hashed_ctx.split(',')
            assert (src_tok, tgt_tok) == (h_src, h_tgt)
            assert h_path == str(common.java_string_hashcode(path))


# ------------------------------------------------- node-type name audit

REFERENCE_JAR = os.path.join(
    '/root', 'reference', 'JavaExtractor', 'JPredict', 'target',
    'JavaExtractor-0.0.1-SNAPSHOT.jar')


@pytest.mark.skipif(not os.path.isfile(REFERENCE_JAR),
                    reason='reference JAR not present')
def test_every_emitted_node_type_exists_in_reference_javaparser():
    """Path strings render node-class simple names (Property.java:28-31);
    every name our parser can emit must be a real javaparser-3.0.0-alpha.4
    AST class, read straight from the reference JAR's file list — a
    misspelled or postdated node name would silently fork the path
    vocabulary."""
    import re
    import zipfile
    with zipfile.ZipFile(REFERENCE_JAR) as jar:
        reference_classes = {
            os.path.basename(name)[:-len('.class')]
            for name in jar.namelist()
            if name.startswith('com/github/javaparser/ast/')
            and name.endswith('.class')
            and '$' not in os.path.basename(name)}
    assert len(reference_classes) > 100  # sanity: the AST package is large

    emitted = set()
    for source in ['java_parser.h', 'pathctx.h']:
        path = os.path.join(REPO, 'extractor', 'src', source)
        with open(path) as f:
            emitted |= set(re.findall(r'make(?:_op)?\("([A-Za-z]+)"',
                                      f.read()))
    # "PrimitiveType" renames and "GenericClass" (Property.java:28-54) are
    # rendering-time substitutions, also checked against the same list
    emitted |= {'PrimitiveType'}
    unknown = sorted(emitted - reference_classes - {'GenericClass'})
    assert not unknown, (
        'node types not in javaparser-3.0.0-alpha.4: %s' % unknown)


# ------------------------------------------------- deviating constructs

def test_annotated_method_still_extracts(tmp_path):
    """Annotations are skipped as trivia (they contribute no leaves);
    the annotated method itself extracts normally."""
    src = tmp_path / 'A.java'
    src.write_text(
        'class A {\n'
        '  @Override\n'
        '  @SuppressWarnings("unchecked")\n'
        '  int getValue(@Deprecated int raw) { return raw + 1; }\n'
        '}\n')
    lines = extract(src)
    assert len(lines) == 1
    assert lines[0].split(' ')[0] == 'get|value'
    assert 'Annotation' not in lines[0]  # no annotation nodes in paths


def test_record_is_skipped_but_siblings_extract(tmp_path):
    """Records postdate javaparser-3.0.0-alpha.4 (the reference JAR cannot
    parse them at all — it drops the whole file); here the record is
    skipped and sibling classes in the same file still extract."""
    src = tmp_path / 'R.java'
    src.write_text(
        'record Point(int x, int y) {\n'
        '  int area() { return x * y; }\n'
        '}\n'
        'class Keeper {\n'
        '  int keep(int v) { return v + 2; }\n'
        '}\n')
    lines = extract(src)
    labels = [line.split(' ')[0] for line in lines]
    assert labels == ['keep']  # record method dropped, sibling kept


def test_explicit_generic_method_call(tmp_path):
    """Explicit type-witness calls parse; the type argument is consumed
    as part of the call (alpha.4 javaparser models it similarly as part
    of the MethodCallExpr)."""
    src = tmp_path / 'G.java'
    src.write_text(
        'class G {\n'
        '  java.util.List<String> empty() {\n'
        '    return java.util.Collections.<String>emptyList();\n'
        '  }\n'
        '}\n')
    lines = extract(src)
    assert len(lines) == 1
    assert lines[0].split(' ')[0] == 'empty'
    assert 'MethodCallExpr' in lines[0]


def test_csharp_interpolated_string_single_literal(tmp_path):
    """C#: interpolated strings are lexed as ONE literal token (holes are
    not parsed as sub-expressions) — documented deviation, pinned here."""
    src = tmp_path / 'I.cs'
    src.write_text(
        'class I {\n'
        '  string Greet(string name) { return $"hello {name}!"; }\n'
        '}\n')
    lines = extract(src, lang='csharp')
    assert len(lines) == 1
    assert lines[0].split(' ')[0] == 'greet'
    # the hole's variable does not appear as its own leaf token paired
    # with others beyond the literal itself
    assert 'InterpolatedStringExpression' not in lines[0]
