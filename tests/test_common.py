import io

import numpy as np

from code2vec_tpu import common


def test_normalize_word():
    # reference common.py:12-18
    assert common.normalize_word('FooBar') == 'foobar'
    assert common.normalize_word('foo_bar2') == 'foobar'
    assert common.normalize_word('123') == '123'      # fully non-alpha: lowercase as-is
    assert common.normalize_word('<OOV>') == 'oov'


def test_get_subtokens():
    assert common.get_subtokens('get|name') == ['get', 'name']
    assert common.get_subtokens('main') == ['main']


def test_legal_method_name():
    # reference common.py:122-124
    assert common.legal_method_name('<OOV>', 'get|name')
    assert not common.legal_method_name('<OOV>', '<OOV>')
    assert not common.legal_method_name('<OOV>', 'get2')
    assert not common.legal_method_name('<OOV>', '')


def test_filter_impossible_names():
    assert common.filter_impossible_names(
        '<OOV>', ['<OOV>', 'a|b', 'x1', 'main']) == ['a|b', 'main']


def test_first_match_rank_counts_only_legal_predictions():
    # Rank is the index within the FILTERED list (reference common.py:180-187).
    found = common.get_first_match_word_from_top_predictions(
        '<OOV>', 'getName', ['<OOV>', 'bad1', 'other', 'get|name'])
    assert found == (1, 'get|name')   # '<OOV>'/'bad1' skipped: rank 1, not 3
    assert common.get_first_match_word_from_top_predictions(
        '<OOV>', 'getName', ['foo', 'bar']) is None


def test_load_histogram_cutoff(tmp_path):
    # Cutoff is one plus the count of the max_size-th word (common.py:56-57).
    hist = tmp_path / 'hist.txt'
    hist.write_text('a 10\nb 8\nc 8\nd 5\ne 1\n')
    full = common.load_histogram(str(hist))
    assert full == {'a': 10, 'b': 8, 'c': 8, 'd': 5, 'e': 1}
    limited = common.load_histogram(str(hist), max_size=2)
    # sorted counts: [10, 8, 8, 5, 1]; counts[2]=8 -> cutoff 9 -> only 'a'
    assert limited == {'a': 10}


def test_count_lines(tmp_path):
    path = tmp_path / 'f.txt'
    path.write_bytes(b'a\nb\nc\n')
    assert common.count_lines_in_file(str(path)) == 3


def test_java_string_hashcode():
    # Values from Java's String#hashCode (reference extractor.py:40-49).
    assert common.java_string_hashcode('foo') == 101574
    assert common.java_string_hashcode('') == 0
    # Must reproduce 32-bit signed overflow behaviour.
    assert common.java_string_hashcode('polygenelubricants') == -2147483648


def test_save_word2vec_file():
    buf = io.StringIO()
    matrix = np.array([[1.0, 2.0], [3.0, 4.0]])
    common.save_word2vec_file(buf, {0: 'w0', 1: 'w1'}, matrix)
    lines = buf.getvalue().splitlines()
    assert lines[0] == '2 2'
    assert lines[1].startswith('w0 1.0')
    assert lines[2].startswith('w1 3.0')


def test_get_unique_list_preserves_order():
    assert common.get_unique_list(['b', 'a', 'b', 'c']) == ['b', 'a', 'c']
