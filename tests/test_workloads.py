"""Scenario traffic plane, pure half (ISSUE 20, WORKLOADS.md): the
registry's typed semantics, the durable profile format (strict reader,
atomic writer, bounded recorder tap), the seed-deterministic replay
plan + admitted fingerprint, the retrieval blend math (weight
semantics, typed no-index fallback, deterministic tie-breaks), the
per-scenario SLO burn attribution, the latency_report scenario axis
over synthetic spans, and language inference at the predict entry
point.  Mesh-backed drills live in tests/test_workloads_replay.py."""
import json
import os
import subprocess
import sys
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
_SCRIPTS = os.path.join(REPO, 'scripts')
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

import latency_report  # noqa: E402

from code2vec_tpu.serving import slo as slo_lib  # noqa: E402
from code2vec_tpu.serving.extractor_bridge import (  # noqa: E402
    Extractor, infer_language)
from code2vec_tpu.serving.predict import resolve_input_path  # noqa: E402
from code2vec_tpu.workloads import (  # noqa: E402
    Scenario, UnknownScenario, get_scenario, register_scenario,
    scenario_names)
from code2vec_tpu.workloads import blend as blend_lib  # noqa: E402
from code2vec_tpu.workloads import profile as profile_lib  # noqa: E402
from code2vec_tpu.workloads import replay as replay_lib  # noqa: E402


# ------------------------------------------------------------ registry
def test_builtin_scenarios_registered():
    for name in ('java_naming', 'csharp_naming', 'softmax_naming',
                 'retrieval_naming', 'neighbor_search'):
        assert name in scenario_names()
    assert get_scenario('retrieval_naming').kind == 'blend'
    assert get_scenario('neighbor_search').kind == 'neighbors'
    # the A/B pair carries BOTH languages (mixed-stream scenarios)
    assert set(get_scenario('softmax_naming').languages) == \
        {'java', 'csharp'}
    assert set(get_scenario('retrieval_naming').languages) == \
        {'java', 'csharp'}


def test_registry_semantics():
    with pytest.raises(UnknownScenario) as err:
        get_scenario('no_such_workload')
    # the typed error names what IS registered (stale-profile triage)
    assert 'java_naming' in str(err.value)
    s = Scenario('wl_test_scn', kind='predict')
    assert register_scenario(s) is s
    # identical re-registration is a no-op...
    register_scenario(Scenario('wl_test_scn', kind='predict'))
    # ...a conflicting one raises unless replace=True
    with pytest.raises(ValueError):
        register_scenario(Scenario('wl_test_scn', kind='neighbors'))
    register_scenario(Scenario('wl_test_scn', kind='neighbors'),
                      replace=True)
    assert get_scenario('wl_test_scn').kind == 'neighbors'


def test_scenario_validation():
    with pytest.raises(ValueError):
        Scenario('bad', kind='stream')
    with pytest.raises(ValueError):
        Scenario('bad', languages=())


# ------------------------------------------------------- profile format
def _records():
    return [
        {'t': 0.0, 'scenario': 'java_naming', 'language': 'java',
         'lines': ['get|a x,p,y'], 'label': 'get|a'},
        {'t': 0.25, 'scenario': 'neighbor_search',
         'vector': [0.5, -1.0], 'k': 3},
        {'t': 0.5, 'scenario': 'retrieval_naming', 'language': 'csharp',
         'lines': ['set|b u,q,v'], 'label': 'set|b', 'weight': 0.5},
    ]


def test_profile_round_trip(tmp_path):
    path = str(tmp_path / 'p.jsonl')
    profile_lib.write_profile(path, _records(), meta={'source': 'test'})
    header, records = profile_lib.read_profile(path)
    assert header['workload_profile'] == profile_lib.PROFILE_VERSION
    assert header['records'] == 3 and header['source'] == 'test'
    assert records == _records()
    # atomic write: no .tmp debris left behind
    assert not os.path.exists(path + '.tmp')


@pytest.mark.parametrize('bad', [
    {'t': 0.0, 'lines': ['l a,b,c']},                     # no scenario
    {'t': -1.0, 'scenario': 's', 'lines': ['l a,b,c']},   # negative t
    {'scenario': 's', 'lines': ['l a,b,c']},              # missing t
    {'t': 0.0, 'scenario': 's'},                  # neither lines/vector
    {'t': 0.0, 'scenario': 's', 'lines': ['l'], 'qps': 9},  # drifted key
])
def test_profile_rejects_malformed_records(tmp_path, bad):
    path = str(tmp_path / 'bad.jsonl')
    with pytest.raises(profile_lib.ProfileError):
        profile_lib.write_profile(path, [bad])
    # the strict reader rejects the same record smuggled past the writer
    with open(path, 'w') as f:
        f.write(json.dumps({'workload_profile': 1, 'records': 1}) + '\n')
        f.write(json.dumps(bad) + '\n')
    with pytest.raises(profile_lib.ProfileError):
        profile_lib.read_profile(path)


def test_profile_rejects_non_profiles(tmp_path):
    path = str(tmp_path / 'notes.jsonl')
    with open(path, 'w') as f:
        f.write('{"stage": "soak"}\n')  # some other JSONL artifact
    with pytest.raises(profile_lib.ProfileError):
        profile_lib.read_profile(path)
    with open(path, 'w') as f:
        f.write('not json\n')
    with pytest.raises(profile_lib.ProfileError):
        profile_lib.read_profile(path)


def test_recorder_relative_time_bounds_and_save(tmp_path):
    rec = profile_lib.ProfileRecorder(max_records=2)
    rec.record('java_naming', lines=['get|a x,p,y'], language='java',
               label='get|a')
    rec.record('neighbor_search', vector=np.array([[1.0, 2.0]]), k=4)
    rec.record('java_naming', lines=['run|c z,p,w'])  # over the bound
    assert len(rec) == 2 and rec.dropped == 1
    records = rec.records()
    # timestamps are RELATIVE to the first record and monotone
    assert records[0]['t'] == 0.0
    assert records[1]['t'] >= 0.0
    # ndarray queries are flattened to plain json-durable floats
    assert records[1]['vector'] == [1.0, 2.0]
    assert records[1]['k'] == 4
    path = str(tmp_path / 'rec.jsonl')
    assert rec.save(path) == 2
    header, loaded = profile_lib.read_profile(path)
    assert header['source'] == 'recorded'
    assert loaded == records


# -------------------------------------------------- replay plan + hash
def test_plan_replay_deterministic_and_seed_scoped():
    records = _records() * 4  # 12 records, repeated ts exercise ties
    full_a = replay_lib.plan_replay(records, rate_scale=2.0, seed=1)
    full_b = replay_lib.plan_replay(records, rate_scale=2.0, seed=99)
    # full replays are seed-INDEPENDENT: the seed only drives limit
    # subsampling, so the admitted-set fingerprint is a pure function
    # of (profile, rate_scale)
    assert replay_lib.admitted_fingerprint(full_a) == \
        replay_lib.admitted_fingerprint(full_b)
    assert len(full_a) == len(records)
    # pacing: t / rate_scale, stable order on ties (profile order)
    assert full_a[0][0] == 0.0
    assert [t for t, _r in full_a] == sorted(t for t, _r in full_a)
    assert full_a[1][1]['scenario'] == 'java_naming'  # tie kept order
    # limited replays are seed-DETERMINISTIC: same seed same subsample
    lim_a = replay_lib.plan_replay(records, seed=7, limit=5)
    lim_b = replay_lib.plan_replay(records, seed=7, limit=5)
    assert len(lim_a) == 5
    assert replay_lib.admitted_fingerprint(lim_a) == \
        replay_lib.admitted_fingerprint(lim_b)
    # ...and a different seed picks a different subsample (5-of-12 has
    # 792 outcomes; seeds 7 vs 8 differ for this fixed input)
    lim_c = replay_lib.plan_replay(records, seed=8, limit=5)
    assert replay_lib.admitted_fingerprint(lim_a) != \
        replay_lib.admitted_fingerprint(lim_c)
    with pytest.raises(ValueError):
        replay_lib.plan_replay(records, rate_scale=0.0)


def test_fingerprint_is_content_sensitive():
    records = _records()
    base = replay_lib.admitted_fingerprint(
        replay_lib.plan_replay(records))
    mutated = [dict(r) for r in records]
    mutated[0]['label'] = 'other|name'
    assert replay_lib.admitted_fingerprint(
        replay_lib.plan_replay(mutated)) != base
    # rate scale changes submission times, hence the fingerprint
    assert replay_lib.admitted_fingerprint(
        replay_lib.plan_replay(records, rate_scale=2.0)) != base


# ----------------------------------------------------------- blend math
class _Row:
    """Duck-typed ModelPredictionResults row for the pure blend math."""

    def __init__(self, words, scores, name='q|uery'):
        self.original_name = name
        self.topk_predicted_words = list(words)
        self.topk_predicted_words_scores = np.asarray(
            scores, dtype=np.float32)


class _Nbrs:
    def __init__(self, labels, scores):
        self.labels = list(labels)
        self.scores = np.asarray(scores, dtype=np.float32)


def test_neighbor_votes_sum_per_label_and_degenerate():
    votes = blend_lib.neighbor_votes(['get|a', 'set|b', 'get|a'],
                                     [2.0, 2.0, 2.0])
    # equal scores: uniform thirds, repeated label votes twice
    assert abs(votes['get|a'] - 2.0 / 3.0) < 1e-9
    assert abs(votes['set|b'] - 1.0 / 3.0) < 1e-9
    assert abs(sum(votes.values()) - 1.0) < 1e-9
    assert blend_lib.neighbor_votes([], []) == {}
    # degenerate scores (all -inf) stay defined: uniform, not NaN
    votes = blend_lib.neighbor_votes(['a', 'b'],
                                     [float('-inf'), float('-inf')])
    assert abs(votes['a'] - 0.5) < 1e-9


def test_blend_row_weight_semantics():
    base = _Row(['get|a', 'set|b'], [0.7, 0.3])
    nbrs = _Nbrs(['run|c', 'run|c'], [1.0, 1.0])
    # weight=1: pure retrieval — the neighbor label outranks softmax
    pure = blend_lib.blend_row(base, nbrs, 1.0)
    assert pure.predicted_words[0] == 'run|c'
    assert pure.source == blend_lib.SOURCE_BLEND
    # mid weight: blended score is (1-w)*p + w*vote exactly
    mid = blend_lib.blend_row(base, nbrs, 0.5)
    got = dict(zip(mid.predicted_words, mid.predicted_scores))
    assert abs(got['get|a'] - 0.5 * 0.7) < 1e-6
    # candidate count bounded by the base row's k by default
    assert len(mid.predicted_words) == 2
    # out-of-range weights clamp instead of corrupting the mix
    clamped = blend_lib.blend_row(base, nbrs, 5.0)
    assert clamped.weight == 1.0
    # determinism: same inputs, identical ranking and scores
    again = blend_lib.blend_row(base, nbrs, 0.5)
    assert again.predicted_words == mid.predicted_words
    np.testing.assert_array_equal(again.predicted_scores,
                                  mid.predicted_scores)


def test_blend_row_tie_break_is_softmax_rank_then_label():
    # both candidates end at the same blended score: softmax's own
    # ranking wins the tie, so cache/replay runs agree bit-for-bit
    base = _Row(['b|x', 'a|y'], [0.5, 0.5])
    out = blend_lib.blend_row(base, _Nbrs([], []), 0.0)
    assert out.predicted_words == ['b|x', 'a|y']


def test_blend_row_none_neighbors_is_typed_fallback():
    base = _Row(['get|a', 'set|b'], [0.7, 0.3])
    out = blend_lib.blend_row(base, None, 0.5)
    assert out.source == blend_lib.SOURCE_FALLBACK
    assert out.predicted_words == ['get|a', 'set|b']
    np.testing.assert_allclose(out.predicted_scores, [0.7, 0.3],
                               rtol=1e-6)
    assert out.base is base and out.neighbors is None


# ------------------------------------------- SLO burn attribution
def test_slo_scenario_burn_attribution():
    mon = slo_lib.SloMonitor(availability=0.99, p99_ms=50.0)
    for _ in range(3):
        mon.observe_good(latency_s=0.001, scenario='java_naming')
    mon.observe_bad('shed', scenario='retrieval_naming')
    mon.observe_bad('failed', scenario='retrieval_naming')
    mon.observe_bad('shed', scenario='java_naming')
    mon.observe_good(latency_s=9.0, scenario='retrieval_naming')  # slow
    mon.observe_good(latency_s=0.001)  # unlabeled: no scenario row
    scn = mon.stats()['scenarios']
    assert set(scn) == {'java_naming', 'retrieval_naming'}
    assert scn['java_naming']['good'] == 3
    assert scn['retrieval_naming']['bad'] == 2
    assert scn['retrieval_naming']['slow'] == 1
    # burn shares: which workload eats the budget, summing to 1
    assert abs(scn['retrieval_naming']['availability_burn_share']
               - 2.0 / 3.0) < 1e-9
    assert abs(scn['java_naming']['availability_burn_share']
               - 1.0 / 3.0) < 1e-9
    assert scn['retrieval_naming']['p99_burn_share'] == 1.0


# -------------------------------------- latency_report scenario axis
def test_trace_scenario_and_fleet_axis(tmp_path):
    records = [
        # labeled at admission: scenario rides the root attrs
        {'trace': 'S1', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.040,
         'dur_ms': 40.0, 'status': 'ok', 'sampled': True,
         'attrs': {'tier': 'topk', 'scenario': 'java_naming'}},
        {'trace': 'S1', 'span': 1, 'parent': 0,
         'name': 'serving.pack', 't0': 0.001, 't1': 0.002,
         'dur_ms': 1.0,
         'attrs': {'bucket': 8, 'tier': 'topk', 'replica': 'r0'}},
        # labeled only on a worker span (dispatch trace context)
        {'trace': 'S2', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.020,
         'dur_ms': 20.0, 'status': 'ok', 'sampled': True,
         'attrs': {'tier': 'topk'}},
        {'trace': 'S2', 'span': 1, 'parent': 0,
         'name': 'serving.pack', 't0': 0.001, 't1': 0.002,
         'dur_ms': 1.0,
         'attrs': {'bucket': 8, 'tier': 'topk', 'replica': 'r1',
                   'scenario': 'retrieval_naming'}},
        # unlabeled traffic buckets under '-'
        {'trace': 'S3', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.010,
         'dur_ms': 10.0, 'status': 'ok', 'sampled': True,
         'attrs': {'tier': 'topk'}},
        {'trace': 'S3', 'span': 1, 'parent': 0,
         'name': 'serving.pack', 't0': 0.001, 't1': 0.002,
         'dur_ms': 1.0,
         'attrs': {'bucket': 8, 'tier': 'topk', 'replica': 'r0'}},
    ]
    path = str(tmp_path / 'spans.jsonl')
    with open(path, 'w') as f:
        for rec in records:
            f.write(json.dumps(rec) + '\n')
    traces = latency_report.group_traces(latency_report.load_spans(path))
    assert latency_report.trace_scenario(traces['S1']) == 'java_naming'
    assert latency_report.trace_scenario(traces['S2']) == \
        'retrieval_naming'
    assert latency_report.trace_scenario(traces['S3']) == '-'
    fleet = latency_report.fleet_decomposition(traces)
    # same replica+tier splits per scenario — NO new span names needed
    assert fleet[('r0', 'topk', 'java_naming')]['end_to_end'] == [40.0]
    assert fleet[('r1', 'topk', 'retrieval_naming')]['end_to_end'] == \
        [20.0]
    assert fleet[('r0', 'topk', '-')]['end_to_end'] == [10.0]


# --------------------------------- language inference (satellite fix)
def test_infer_language_by_extension():
    assert infer_language('Input.java') == 'java'
    assert infer_language('/tmp/Program.CS') == 'csharp'
    assert infer_language('notes.txt') is None
    assert infer_language('Makefile') is None


def test_extractor_selects_frontend_from_extension(tmp_path,
                                                   monkeypatch):
    from code2vec_tpu.config import Config
    from code2vec_tpu.serving import extractor_bridge
    seen = {}

    def fake_run(command, **_kwargs):
        seen['command'] = list(command)
        return types.SimpleNamespace(returncode=0,
                                     stdout='lab a,p,b\n', stderr='')
    monkeypatch.setattr(extractor_bridge.subprocess, 'run', fake_run)
    config = Config(MAX_CONTEXTS=6)
    extractor = Extractor(config, extractor_command=['fake-extract'])
    extractor.extract_paths(str(tmp_path / 'A.java'))
    assert '--lang' not in seen['command']  # java is every default
    extractor.extract_paths(str(tmp_path / 'A.cs'))
    assert seen['command'][-2:] == ['--lang', 'csharp']


def test_resolve_input_path_both_extensions(tmp_path):
    java = tmp_path / 'Input.java'
    cs = tmp_path / 'Input.cs'
    # existing file: unchanged, no sibling scan
    java.write_text('class A {}')
    assert resolve_input_path(str(java)) == str(java)
    # missing .java with exactly one known-extension sibling: the C#
    # frontend is reached with ZERO flags (the satellite fix)
    java.unlink()
    cs.write_text('class A {}')
    assert resolve_input_path(str(java)) == str(cs)
    # the reverse direction resolves too
    assert resolve_input_path(str(tmp_path / 'Input.cs')) == str(cs)
    # ambiguous (both exist): configured name wins, unchanged
    java.write_text('class A {}')
    assert resolve_input_path(str(java)) == str(java)
    # no candidates at all: unchanged (caller surfaces the miss)
    assert resolve_input_path(str(tmp_path / 'Other.java')) == \
        str(tmp_path / 'Other.java')


# ------------------------------------------- synthetic profile builder
@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO, 'extractor', 'build',
                                    'c2v-extract')),
    reason='native extractor not built')
def test_build_synthetic_profile_mixed_and_deterministic(tmp_path):
    from code2vec_tpu.config import Config
    config = Config(MAX_CONTEXTS=200)
    kwargs = dict(classes_per_language=1, seed=3, rate_rps=100.0,
                  methods_per_class=(2, 2))
    a = profile_lib.build_synthetic_profile(
        config, str(tmp_path / 'a'), **kwargs)
    b = profile_lib.build_synthetic_profile(
        config, str(tmp_path / 'b'), **kwargs)
    assert a == b  # byte-identical under (seed, classes)
    langs = {r['language'] for r in a}
    assert langs == {'java', 'csharp'}  # one MIXED stream
    scns = {r['scenario'] for r in a}
    assert scns == {'java_naming', 'csharp_naming'}
    for r in a:
        assert r['label'] == r['lines'][0].split(' ', 1)[0]
    ts = [r['t'] for r in a]
    assert ts[0] == 0.0 and ts == sorted(ts)
    # round-trips the durable format
    path = str(tmp_path / 'syn.jsonl')
    profile_lib.write_profile(path, a, meta={'source': 'synthetic'})
    _header, loaded = profile_lib.read_profile(path)
    assert loaded == a
