"""bench.py harness smoke (BENCH_SMOKE shapes, CPU): guards the benchmark
entry point against import/config rot between rounds."""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_bench_smoke(**env_overrides):
    """One bench.py smoke run; returns the parsed final JSON line."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO, **env_overrides)
    proc = subprocess.run([sys.executable, os.path.join(REPO, 'bench.py')],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert lines
    return lines, json.loads(lines[-1])


def test_bench_smoke_emits_one_json_line():
    lines, record = run_bench_smoke()
    assert len(lines) == 1
    assert set(record) == {'metric', 'value', 'unit', 'vs_baseline',
                           'recipe', 'knobs', 'wire_bytes_per_batch',
                           'peak_hbm_bytes', 'hbm_bytes_in_use'}
    # the packed wire format must be strictly smaller at realistic fill
    wire = record['wire_bytes_per_batch']
    assert 0 < wire['packed'] < wire['planes']
    # the memory axis (ISSUE 9) rides every headline record; the CPU
    # smoke backend has no memory_stats, so the gap is an EXPLICIT null
    assert record['peak_hbm_bytes'] is None
    # a smoke line must never masquerade as the java14m number
    assert record['metric'] == 'train_examples_per_sec_SMOKE_ONLY'
    assert record['vs_baseline'] == 0.0
    assert record['value'] > 0
    assert record['recipe'] == 'default'
    # the shipped defaults (the measured 2026-07-31 winners)
    assert record['knobs'] == {'dropout_prng': 'rbg',
                               'adam_mu': 'bfloat16',
                               'adam_nu': 'bfloat16',
                               'grads': 'float32'}


def test_bench_recipe_parity_pins_knobs():
    """BENCH_RECIPE=parity must actually PIN the reference-parity knobs
    (not just relabel the line): the vs-V100 comparison row is only
    refreshable if the measured config is threefry + fp32 mu. The knob
    echo comes from the resolved Config, so a regression that drops the
    overrides fails here even with the label intact."""
    _, record = run_bench_smoke(BENCH_CHILD='1', BENCH_RECIPE='parity')
    assert record['recipe'] == 'parity'
    assert record['value'] > 0
    assert record['knobs'] == {'dropout_prng': 'threefry2x32',
                               'adam_mu': 'float32',
                               'adam_nu': 'float32',
                               'grads': 'float32'}


def test_bench_unknown_recipe_resolves_to_default():
    """An unknown BENCH_RECIPE must fall back to 'default' instead of
    crashing the driver. Pure import-time string resolution — no
    measurement subprocess needed."""
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO,
               BENCH_RECIPE='no-such-recipe')
    proc = subprocess.run(
        [sys.executable, '-c',
         'import bench; print(bench.BENCH_RECIPE, bench.RECIPE_OVERRIDES)'],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip() == 'default {}'


# tier-1 runtime budget (ISSUE 17): the four heaviest bench smokes
# move behind the slow marker — capture_all.sh runs the real stages
# on-chip, and test_bench_smoke_emits_one_json_line keeps the
# import/config-rot canary in tier-1
@pytest.mark.slow
def test_bench_fused_ce_smoke_runs_all_arms():
    """The staged fused-CE A/B harness must survive import/config rot:
    one healthy tunnel window is too expensive to spend on a crash."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_fused_ce.py')],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    measures = {r['measure'] for r in records if 'measure' in r}
    assert {'step_ms_ce_xla_SMOKE_ONLY', 'step_ms_ce_fused_SMOKE_ONLY',
            'step_ms_ce_fused_rbg_bf16mu_SMOKE_ONLY'} <= measures


@pytest.mark.slow
def test_bench_pallas_ragged_smoke_runs_all_arms():
    """ISSUEs 10 + 12: the ragged-fusion A/B harness must survive
    import/config rot, run all THREE arms (unfused / fused-twin /
    fused_kernel), carry the peak-HBM fields on every arm record (None
    on the stats-less CPU backend — an explicit gap), measure the
    train-BACKWARD arm (value_and_grad step time + the grad program's
    AOT temp bytes, the residual-footprint axis), and emit both verdict
    families: fusion-vs-unpack speedups AND the kernel-vs-shipped-twin
    records that actually gate RAGGED_TRAIN_KERNEL."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_pallas_ragged.py')],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    measures = {r['measure']: r for r in records if 'measure' in r}
    assert {'step_ms_ragged_train_unfused_SMOKE_ONLY',
            'step_ms_ragged_train_fused_SMOKE_ONLY',
            'step_ms_ragged_train_fused_kernel_SMOKE_ONLY',
            'step_ms_ragged_train_bwd_unfused_SMOKE_ONLY',
            'step_ms_ragged_train_bwd_fused_SMOKE_ONLY',
            'step_ms_ragged_train_bwd_fused_kernel_SMOKE_ONLY',
            'step_ms_ragged_predict_unfused_SMOKE_ONLY',
            'step_ms_ragged_predict_fused_SMOKE_ONLY',
            'ragged_fusion_train_speedup_SMOKE_ONLY',
            'ragged_fusion_train_bwd_speedup_SMOKE_ONLY',
            'ragged_fusion_predict_speedup_SMOKE_ONLY',
            'ragged_train_kernel_speedup_SMOKE_ONLY',
            'ragged_train_kernel_bwd_speedup_SMOKE_ONLY'} <= \
        set(measures)
    for name, rec in measures.items():
        if name.startswith('step_ms_'):
            assert rec['value'] > 0
            # the memory axis rides every arm record; CPU smoke has no
            # memory_stats, so the gap is an EXPLICIT null
            assert 'peak_hbm_bytes' in rec and \
                rec['peak_hbm_bytes'] is None
            assert rec['fill'] == 0.25
        if '_train_bwd_' in name and name.startswith('step_ms_'):
            # XLA:CPU supports memory_analysis, so the smoke asserts a
            # REAL temp-bytes number (on-chip it feeds the temp ratio)
            assert rec['kind'] == 'train_bwd'
            assert isinstance(rec['temp_bytes'], int)
    # the temp-bytes ratio record (the residual win axis) must ride
    assert 'ragged_fusion_train_bwd_temp_ratio_SMOKE_ONLY' in measures
    verdicts = [r for r in records if 'verdict' in r]
    assert len(verdicts) == 2
    assert verdicts[0]['verdict'] in ('keep-fused', 'keep-unfused')
    assert verdicts[1]['verdict'] in ('kernel-on', 'kernel-off')


@pytest.mark.slow
def test_bench_mesh_smoke_fixed_offered_load():
    """ISSUE 13: the serving-mesh load harness must survive import/
    config rot, drive 1- and 2-replica arms at the same fixed offered
    load with the mixed predict + submit_neighbors profile, report p99 /
    shed-rate / per-replica fill / dispatch share per arm, and show
    ZERO post-warmup compiles (mixed-tier continuous batching never
    escapes the warm ladder).  The >=1.8x admitted-throughput scaling
    at 2 replicas is physics-gated on host cores: replica threads
    cannot parallelize anything on a 1-core container (the arm records
    carry host_cores so captures stay interpretable)."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_mesh.py'),
         '--replica-counts', '1,2'],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    assert all(r.get('smoke') for r in records)
    by_metric = {}
    for r in records:
        by_metric.setdefault(r['metric'], []).append(r)
    assert by_metric['mesh_capacity_rows_per_sec_1r'][0]['value'] > 0
    offered = by_metric['mesh_offered_rows_per_sec'][0]['value']
    assert offered > 0
    arms = {r['replicas']: r
            for r in by_metric['mesh_admitted_rows_per_sec']}
    assert set(arms) == {1, 2}
    for n, arm in arms.items():
        assert arm['value'] > 0
        assert arm['p50_ms'] <= arm['p99_ms']
        assert 0.0 <= arm['shed_rate'] <= 1.0
        assert len(arm['per_replica_fill']) == n
        assert len(arm['dispatch_share']) == n
        # mixed-tier continuous batching compiled NOTHING post-warmup
        assert arm['postwarm_compiles'] == 0, arm
        assert set(arm['tiers']) == {'topk', 'attention', 'neighbors'}
        # the threaded load generator held the offered schedule
        assert arm['achieved_offer_rows_per_sec'] >= 0.5 * offered, arm
    # the 1-replica arm saturates at ~2.2x capacity offered load: the
    # shed defense must actually be shedding
    assert arms[1]['shed_rate'] > 0.1, arms[1]
    # 2 replicas split the one shared queue's stream about evenly
    share = arms[2]['dispatch_share']
    assert 0.2 <= share[0] <= 0.8, share
    (scaling,) = by_metric['mesh_scaling_2x']
    assert scaling['value'] > 0
    if (os.cpu_count() or 1) >= 2:
        # the acceptance floor holds wherever replica threads can
        # actually run in parallel; a 1-core container records the
        # ratio but cannot gate on it (nothing scales on one core)
        assert scaling['value'] >= 1.8, scaling


@pytest.mark.slow
def test_bench_mesh_stepped_load_smoke():
    """ISSUE 18: the stepped-offered-load elasticity arm must survive
    import/config rot — low -> high -> low against one process replica
    with the SLO/queue-driven autoscaler live: the high step pulls a
    second replica (scale-up latency reported, cold start included),
    the low step drains it back out typed ('autoscale'), transition
    p99 is reported next to steady-state p99, and the parent compiles
    NOTHING after warmup across both transitions."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_mesh.py'),
         '--stepped-load'],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    by_metric = {r['metric']: r for r in records}
    up = by_metric['mesh_stepped_scale_up_s']
    assert up['reached_2_replicas'] is True
    assert up['value'] is not None and up['value'] > 0
    assert up['scale_up_total'] >= 1
    assert up['process_capacity_rows_per_sec_1r'] > 0
    down = by_metric['mesh_stepped_scale_down_s']
    assert down['drained_to_1_replica'] is True
    assert down['value'] is not None and down['scale_down_total'] >= 1
    assert ['r1', 'autoscale'] in down['retired']
    p99 = by_metric['mesh_stepped_transition_p99_ms']
    assert p99['value'] is not None
    assert p99['steady_p99_ms'] is not None
    assert p99['postwarm_compiles'] == 0
    assert p99['typed_failures'] == 0


def _run_mesh_soak(extra_args=(), timeout=600, smoke=True):
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO)
    if smoke:
        env['BENCH_SMOKE'] = '1'
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'mesh_soak.py'),
         *extra_args],
        capture_output=True, text=True, timeout=timeout, env=env)
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    return proc, {r['metric']: r for r in records}


@pytest.mark.slow
def test_mesh_soak_smoke_self_heals_without_losing_requests():
    """ISSUE 14: the chaos soak must survive import/config rot AND its
    assertions must hold on the smoke shapes — paced load while the
    fault grammar periodically SIGKILLs worker replicas: zero lost
    admitted requests (every future resolves, results or typed), at
    least one supervised restart actually fired, zero post-warmup
    compiles in the parent, and a bounded p99."""
    proc, by_metric = _run_mesh_soak()
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    assert all(r.get('smoke') for r in by_metric.values())
    summary = by_metric['mesh_soak_requests']
    assert summary['value'] > 0 and summary['ok'] > 0
    assert summary['lost'] == 0 and summary['untyped_failures'] == 0
    assert by_metric['mesh_soak_lost_requests']['value'] == 0
    restarts = by_metric['mesh_soak_restarts']
    assert restarts['value'] >= 1, restarts  # the chaos actually bit
    assert restarts['redispatched'] >= 0
    p99 = by_metric['mesh_soak_p99_ms']
    assert p99['value'] is not None and p99['value'] <= p99['bound_ms']
    assert by_metric['mesh_soak_postwarm_compiles']['value'] == 0
    # ISSUE 16: the soak runs with the memo tier ON and mid-soak
    # rollover drills — the cache must serve under chaos, every
    # completed rollover must have bumped the generation, and zero
    # stale serves (asserted inline by the soak: rc 0 covers it)
    memo = by_metric['mesh_soak_memo']
    assert memo['value'] > 0 and memo['hit_rate'] > 0, memo
    assert memo['rollovers'] >= 1, memo
    assert memo['generation'] >= memo['rollovers'], memo
    # ISSUE 18: the elastic drill rode the same soak — a scale-up
    # completed UNDER the kill chaos and the scaled-up replica drained
    # back out typed during a partition window (rc 0 already covers
    # the zero-lost contract across both transitions)
    scale = by_metric['mesh_soak_scale_up_ms']
    assert scale['value'] is not None and scale['rid'], scale
    drain = by_metric['mesh_soak_drain_partition_ms']
    assert drain['value'] is not None, drain
    assert drain['retired_reason'] == 'drain', drain


@pytest.mark.slow
def test_mesh_soak_full_run():
    """The full-duration chaos soak (capture_all.sh stage mesh_soak):
    same contract, real durations, socket transport."""
    proc, by_metric = _run_mesh_soak(
        extra_args=['--mode', 'socket'], timeout=900, smoke=False)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    assert by_metric['mesh_soak_lost_requests']['value'] == 0
    assert by_metric['mesh_soak_restarts']['value'] >= 1
    assert by_metric['mesh_soak_postwarm_compiles']['value'] == 0


def test_bench_index_smoke_meets_acceptance():
    """ISSUE 5 acceptance on the CPU smoke shapes: >= 10x the naive
    NumPy host loop, zero post-warmup compiles on the query path, and
    IVF recall@10 >= 0.95 at the default nprobe."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    # best-of-4 reps: the >=10x floor is a warm-dispatch-vs-numpy ratio
    # (nominal ~20x); best-of-2 was observed tipping to ~9.5x under
    # full-suite machine load, so give min() more draws rather than
    # weaken the acceptance threshold
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_index.py'), '--reps', '4',
         '--arms', 'base'],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = {r['metric']: r for r in
               (json.loads(line) for line in proc.stdout.splitlines()
                if line.strip())}
    assert all(r.get('smoke') for r in records.values())
    speedup = records['index_exact_speedup_vs_numpy']
    assert speedup['value'] >= 10.0, speedup
    assert speedup['postwarm_compiles'] == 0, speedup
    recall = records['index_ivf_recall_at10']
    assert recall['value'] >= 0.95, recall
    curve = records['index_ivf_curve']['points']
    assert curve and all(
        {'nprobe', 'recall', 'queries_per_sec'} <= set(p) for p in curve)


def test_bench_index_quant_arms_smoke():
    """Quantized-tier arms (capture stage ``index_quant``) on the CPU
    smoke shapes: both kinds hit the recall floor with zero post-warmup
    compiles, PQ compresses >= 4x vs f16 (the <= 1/4 acceptance), and
    the insert arm's rows are self-findable (queryable, no rebuild)."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_index.py'), '--reps', '2',
         '--arms', 'quant'],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line) for line in proc.stdout.splitlines()
               if line.strip()]
    by_kind = {}
    for rec in records:
        if 'kind' in rec:
            by_kind.setdefault(rec['metric'], {})[rec['kind']] = rec
    for kind in ('int8', 'pq'):
        recall = by_kind['index_quant_recall_at10'][kind]
        assert recall['value'] >= 0.95, recall
        qps = by_kind['index_quant_queries_per_sec'][kind]
        assert qps['postwarm_compiles'] == 0, qps
    assert (by_kind['index_quant_queries_per_sec']['pq']
            ['compression_vs_f16']) >= 4.0
    insert = by_kind['index_quant_insert_vectors_per_sec']['pq']
    assert insert['self_hit_at1'] >= 0.9, insert
    assert insert['segments'] >= 1, insert


def test_workloads_files_stay_within_tier1_budget():
    """ISSUE 20 satellite: the scenario-traffic-plane test files ride
    tier-1 with TINY in-code profiles — the full replay drills are
    slow-marked.  The suite sits close to the tier-1 wall-clock cap,
    so the headroom contract is enforced here: both files, cold
    interpreter, well under the budget.  A full-corpus replay sneaking
    into the tier-1 lane fails THIS assert before it blows the cap."""
    import time
    env = dict(os.environ, JAX_PLATFORMS='cpu', PYTHONPATH=REPO)
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, '-m', 'pytest',
         os.path.join(REPO, 'tests', 'test_workloads.py'),
         os.path.join(REPO, 'tests', 'test_workloads_replay.py'),
         '-q', '-m', 'not slow', '-p', 'no:cacheprovider'],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-500:]
    # nominal ~6s cold; 120s leaves room for a loaded machine while
    # still catching a drift into minutes
    assert elapsed < 120.0, 'workloads tier-1 tests took %.1fs' % elapsed


@pytest.mark.slow
def test_bench_scenarios_smoke_mixed_replay(tmp_path):
    """ISSUE 20: the --scenarios stage (capture_all.sh ``scenarios``)
    must survive import/config rot on the CPU smoke shapes: one
    recorded-then-replayed mixed Java+C# profile reports per-scenario
    x per-language quality + hit-rate + shed + p99, per-scenario SLO
    burn, the retrieval-vs-softmax A/B verdict (beats or ties — the
    acceptance gate), ZERO post-warmup compiles across the whole
    mixed-scenario steady state, and a stable replay fingerprint."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    out = tmp_path / 'scenarios.json'
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'accuracy_at_scale.py'),
         '--scenarios', '--workdir', str(tmp_path / 'wd'),
         '--out', str(out)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-2000:]
    records = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith('{'):
            records.append(json.loads(line))
    quality = [r for r in records if r.get('measure') ==
               'scenario_quality']
    cells = {(r['scenario'], r['language']) for r in quality}
    # the as-labeled arm plus both A/B relabelings, both languages
    assert {('java_naming', 'java'), ('csharp_naming', 'csharp'),
            ('softmax_naming', 'java'), ('softmax_naming', 'csharp'),
            ('retrieval_naming', 'java'),
            ('retrieval_naming', 'csharp')} <= cells
    for r in quality:
        assert r['requests'] == r['delivered'] + r['shed'] + r['errors']
        assert 0.0 <= r['memo_hit_rate'] <= 1.0
        assert r['p50_ms'] <= r['p99_ms']
    slo = [r for r in records if r.get('measure') == 'scenario_slo']
    assert {r['scenario'] for r in slo} >= {'java_naming',
                                            'csharp_naming'}
    (ab,) = [r for r in records if r.get('measure') == 'retrieval_ab']
    assert ab['verdict'] in ('win', 'tie'), ab  # beats or ties
    assert ab['scored'] > 0
    (compiles,) = [r for r in records
                   if r.get('measure') == 'scenario_postwarm_compiles']
    assert compiles['value'] == 0, compiles
    (fp,) = [r for r in records
             if r.get('measure') == 'scenario_replay_fingerprint']
    assert fp['admitted'] > 0 and len(fp['value']) == 64
    saved = json.loads(out.read_text())
    assert saved['fingerprint'] == fp['value']
    assert saved['retrieval_ab']['verdict'] == ab['verdict']


def test_bench_sigterm_flushes_fallback_line(tmp_path):
    """VERDICT r3 #1: the driver kills bench.py with SIGTERM at its own
    timeout; the supervisor must flush a parseable fallback line and die
    cleanly instead of leaving `parsed: null`.  Run against an isolated
    results dir with a known committed capture."""
    repo_copy = tmp_path / 'benchdir'
    repo_copy.mkdir()
    results = repo_copy / 'benchmarks' / 'results'
    results.mkdir(parents=True)
    (results / 'capture_2026-01-01T0000Z_rT.jsonl').write_text(
        json.dumps({'stage': 'headline', 'rc': 0, 'secs': 1, 'data': {
            'metric': 'train_examples_per_sec_per_chip_java14m',
            'value': 1234.5, 'unit': 'examples/sec/chip',
            'vs_baseline': 0.263}}) + '\n')
    import shutil
    shutil.copy(os.path.join(REPO, 'bench.py'), repo_copy / 'bench.py')
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='',
               BENCH_TOTAL_BUDGET='600')
    proc = subprocess.Popen(
        [sys.executable, str(repo_copy / 'bench.py')],
        cwd=repo_copy, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    import time
    time.sleep(3)
    proc.terminate()
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 0
    record = json.loads(out.strip().splitlines()[-1])
    # VERDICT r4 #8: headline fields stay honest on a failed fresh run —
    # value 0.0 + error, the old capture only under last_known_good.
    assert record['value'] == 0.0
    assert record['vs_baseline'] == 0.0
    assert record['error'] == 'tpu_unavailable'
    assert 'killed by signal 15' in record['detail']
    assert record['last_known_good']['value'] == 1234.5
    assert record['last_known_good']['source_file'].endswith(
        'capture_2026-01-01T0000Z_rT.jsonl')


def test_last_known_good_prefers_filename_stamp_over_mtime(tmp_path):
    """ADVICE r3: git clones don't preserve mtimes, so recency must come
    from the ISO stamp embedded in capture filenames — an older capture
    touched later must not win."""
    import bench
    results = tmp_path / 'benchmarks' / 'results'
    results.mkdir(parents=True)
    mk = lambda name, value: (results / name).write_text(json.dumps({
        'metric': bench.METRIC_NAME, 'value': value,
        'unit': 'examples/sec/chip', 'vs_baseline': 1.0}) + '\n')
    mk('capture_2026-07-29T1349Z_old.jsonl', 111.0)
    mk('capture_2026-07-30T0100Z_new.jsonl', 222.0)
    # give the OLD file the newest mtime (what a checkout can do)
    os.utime(results / 'capture_2026-07-29T1349Z_old.jsonl')
    older = os.path.getmtime(results / 'capture_2026-07-29T1349Z_old.jsonl') - 100
    os.utime(results / 'capture_2026-07-30T0100Z_new.jsonl', (older, older))
    got = bench._last_known_good(str(results))
    assert got['value'] == 222.0


def test_summarize_captures_folds_tpu_unavailable_reasons(tmp_path):
    """ISSUE 8 satellite: wedged rounds must show up in the bench
    trajectory as EXPLICIT gaps with their reason record, not as
    silently empty files."""
    (tmp_path / 'capture_wedged.jsonl').write_text(
        '{"stage": "probe", "tpu_unavailable": '
        '"probe failed 3/3 attempts (before any stage)", '
        '"attempts": 3, "secs": 95}\n')
    (tmp_path / 'capture_ok.jsonl').write_text(
        '{"stage": "bench", "rc": 0, "secs": 60, '
        '"data": {"measure": "examples_per_sec", "value": 24948}}\n')
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, 'benchmarks', 'summarize_captures.py'),
         '--dir', str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    assert 'TPU UNAVAILABLE' in out
    assert 'probe failed 3/3 attempts (before any stage)' in out
    assert 'no measurements this round' in out
    assert '1/2 round(s) produced no measurements' in out
    # the healthy round still reads normally
    assert 'examples_per_sec: 24948' in out
