"""bench.py harness smoke (BENCH_SMOKE shapes, CPU): guards the benchmark
entry point against import/config rot between rounds."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_emits_one_json_line():
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run([sys.executable, os.path.join(REPO, 'bench.py')],
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert set(record) == {'metric', 'value', 'unit', 'vs_baseline'}
    # a smoke line must never masquerade as the java14m number
    assert record['metric'] == 'train_examples_per_sec_SMOKE_ONLY'
    assert record['vs_baseline'] == 0.0
    assert record['value'] > 0


def test_bench_fused_ce_smoke_runs_all_arms():
    """The staged fused-CE A/B harness must survive import/config rot:
    one healthy tunnel window is too expensive to spend on a crash."""
    env = dict(os.environ, BENCH_SMOKE='1', JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'benchmarks',
                                      'bench_fused_ce.py')],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line)
               for line in proc.stdout.splitlines() if line.strip()]
    measures = {r['measure'] for r in records if 'measure' in r}
    assert {'step_ms_ce_xla_SMOKE_ONLY', 'step_ms_ce_fused_SMOKE_ONLY',
            'step_ms_ce_fused_rbg_bf16mu_SMOKE_ONLY'} <= measures
