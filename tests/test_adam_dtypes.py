"""training/adam_dtypes.py — Adam with reduced-precision moment storage.

The transform must (a) reproduce ``optax.adam`` exactly when no dtype is
narrowed (it replaces it in the trainer only when ADAM_NU_DTYPE='bfloat16',
so the swap must be semantics-free), (b) store the moments in the
configured dtypes while computing the update in fp32, and (c) drive a real
train step through the Trainer.

Reference anchor: the reference's Adam is fp32-moment
tf.compat.v1.train.AdamOptimizer (/root/reference/tensorflow_model.py:232);
moment STORAGE dtype is a TPU HBM knob gated by the PERF.md flip rule.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from code2vec_tpu import benchlib
from code2vec_tpu.training import adam_dtypes


def _params():
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        'table': jax.random.normal(k1, (64, 8), jnp.float32),
        'dense': {'w': jax.random.normal(k2, (8, 4), jnp.float32),
                  'b': jax.random.normal(k3, (4,), jnp.float32)},
    }


def _grads(step: int):
    key = jax.random.PRNGKey(100 + step)
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        'table': jax.random.normal(k1, (64, 8), jnp.float32),
        'dense': {'w': jax.random.normal(k2, (8, 4), jnp.float32),
                  'b': jax.random.normal(k3, (4,), jnp.float32)},
    }


def test_matches_optax_adam_when_not_narrowed():
    """mu_dtype/nu_dtype = None must be a drop-in for optax.adam."""
    params_ref = _params()
    params_new = _params()
    opt_ref = optax.adam(1e-3)
    opt_new = adam_dtypes.adam(1e-3)
    state_ref = opt_ref.init(params_ref)
    state_new = opt_new.init(params_new)
    for step in range(5):
        g = _grads(step)
        upd_ref, state_ref = opt_ref.update(g, state_ref, params_ref)
        upd_new, state_new = opt_new.update(g, state_new, params_new)
        params_ref = optax.apply_updates(params_ref, upd_ref)
        params_new = optax.apply_updates(params_new, upd_new)
    for leaf_ref, leaf_new in zip(jax.tree_util.tree_leaves(params_ref),
                                  jax.tree_util.tree_leaves(params_new)):
        np.testing.assert_allclose(leaf_ref, leaf_new, rtol=1e-6, atol=1e-7)
    # same state tree structure/field names -> checkpoint-compatible
    assert (jax.tree_util.tree_structure(state_ref)
            == jax.tree_util.tree_structure(state_new))


def test_narrowed_moments_store_bf16_and_track_fp32():
    """bf16 mu+nu storage: state leaves are bf16, the trajectory stays
    within bf16 rounding of the fp32-moment trajectory."""
    params_ref = _params()
    params_new = _params()
    opt_ref = optax.adam(1e-3)
    opt_new = adam_dtypes.adam(1e-3, mu_dtype=jnp.bfloat16,
                               nu_dtype=jnp.bfloat16)
    state_ref = opt_ref.init(params_ref)
    state_new = opt_new.init(params_new)
    for field in ('mu', 'nu'):
        for leaf in jax.tree_util.tree_leaves(
                getattr(state_new[0], field)):
            assert leaf.dtype == jnp.bfloat16
    for step in range(10):
        g = _grads(step)
        upd_ref, state_ref = opt_ref.update(g, state_ref, params_ref)
        upd_new, state_new = opt_new.update(g, state_new, params_new)
        params_ref = optax.apply_updates(params_ref, upd_ref)
        params_new = optax.apply_updates(params_new, upd_new)
    for field in ('mu', 'nu'):
        for leaf in jax.tree_util.tree_leaves(
                getattr(state_new[0], field)):
            assert leaf.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; after 10 steps of lr=1e-3 updates the
    # drift must stay at bf16-rounding scale, not blow up
    for leaf_ref, leaf_new in zip(jax.tree_util.tree_leaves(params_ref),
                                  jax.tree_util.tree_leaves(params_new)):
        np.testing.assert_allclose(np.asarray(leaf_ref),
                                   np.asarray(leaf_new),
                                   rtol=0.05, atol=5e-4)


def test_update_math_is_fp32_despite_bf16_storage():
    """The sqrt denominator must be formed from an fp32 upcast: feeding a
    gradient whose square underflows bf16 (but not fp32) must still move
    the parameter by a finite, fp32-accurate amount."""
    params = {'w': jnp.zeros((4,), jnp.float32)}
    opt = adam_dtypes.adam(1e-3, mu_dtype=jnp.bfloat16,
                           nu_dtype=jnp.bfloat16)
    state = opt.init(params)
    g = {'w': jnp.full((4,), 1e-3, jnp.float32)}
    upd, state = opt.update(g, state, params)
    # first-step Adam update is ~ -lr * sign(g) regardless of magnitude
    np.testing.assert_allclose(np.asarray(upd['w']),
                               -1e-3 * np.ones(4), rtol=1e-2)
    assert np.all(np.isfinite(np.asarray(upd['w'])))


def test_bf16_grads_keep_fp32_moment_math():
    """With bf16 gradients and bf16-stored moments, the nu EMA must not
    accumulate in bf16: a (1-b2)*g^2 increment ~1e-3 of nu is below bf16
    epsilon and would be silently dropped, freezing nu. Feed constant
    grads: after N steps nu must track the fp32-reference within rounding
    instead of sticking at its first value."""
    params = {'w': jnp.zeros((8,), jnp.float32)}
    opt = adam_dtypes.adam(1e-3, mu_dtype=jnp.bfloat16,
                           nu_dtype=jnp.bfloat16)
    state = opt.init(params)
    g32 = jnp.full((8,), 0.5, jnp.float32)
    g = {'w': g32.astype(jnp.bfloat16)}
    for _ in range(20):
        upd, state = opt.update(g, state, params)
        params = optax.apply_updates(params, upd)
    # fp32 EMA reference after 20 steps of constant g
    nu_ref = float(0.25 * (1 - 0.999 ** 20))
    nu_got = float(np.asarray(state[0].nu['w'].astype(jnp.float32))[0])
    # one bf16 rounding per step compounds; 2% tolerance catches the
    # bf16-EMA failure mode (nu stuck ~16x low) without flaking
    assert abs(nu_got - nu_ref) / nu_ref < 0.02


def test_trainer_bf16_grads_path():
    """GRADS_DTYPE='bfloat16' threads through the Trainer: the step runs,
    params stay fp32 masters, and the loss matches the fp32-grads step
    within bf16 grad-rounding tolerance. COMPUTE_DTYPE is bf16 — the only
    combination verify() allows, and the one where the forward is
    bit-identical between the two arms."""
    shapes = benchlib.SMOKE_SHAPES
    losses = {}
    for grads_dtype in ('float32', 'bfloat16'):
        config = benchlib.headline_config(
            shapes, COMPUTE_DTYPE='bfloat16', GRADS_DTYPE=grads_dtype)
        config.verify()
        trainer, state = benchlib.build_trainer(config, shapes)
        feeds = benchlib.staged(trainer, benchlib.random_batches(shapes, 2))
        for i in range(3):
            state, loss = trainer.train_step_placed(
                state, feeds[i % len(feeds)])
        losses[grads_dtype] = float(loss)
        for leaf in jax.tree_util.tree_leaves(state.params):
            assert leaf.dtype == jnp.float32
    # identical forward; grads differ only by one bf16 rounding, so after
    # 3 steps the trajectories must still agree to ~1e-2
    assert abs(losses['float32'] - losses['bfloat16']) \
        / max(abs(losses['float32']), 1e-6) < 0.02


def test_trainer_bf16_grads_differentiates_bf16_params():
    """The mechanism, not just the trajectory: under GRADS_DTYPE='bfloat16'
    the loss must be differentiated wrt PRE-CAST bf16 params (that is what
    makes the cotangents — and the table-grad scatters — bf16 in HBM). A
    regression that drops cast_for_grads would still pass the
    loss-proximity test above; this one records the param dtype the loss
    actually sees during tracing."""
    shapes = benchlib.SMOKE_SHAPES
    config = benchlib.headline_config(
        shapes, COMPUTE_DTYPE='bfloat16', GRADS_DTYPE='bfloat16')
    config.verify()
    trainer, state = benchlib.build_trainer(config, shapes)
    seen = []
    orig_loss_fn = trainer.backend.loss_fn

    def spy_loss_fn(params, arrays, dropout_rng, mesh=None):
        seen.append(params.token_embedding.dtype)
        return orig_loss_fn(params, arrays, dropout_rng, mesh=mesh)

    trainer.backend.loss_fn = spy_loss_fn
    trainer._build_steps()  # re-trace with the spy in place
    feeds = benchlib.staged(trainer, benchlib.random_batches(shapes, 1))
    trainer.train_step_placed(state, feeds[0])
    assert seen and all(dt == jnp.bfloat16 for dt in seen)


def test_grads_dtype_rejects_lazy_adam():
    config = benchlib.headline_config(
        benchlib.SMOKE_SHAPES, GRADS_DTYPE='bfloat16',
        LAZY_EMBEDDING_ADAM=True)
    with pytest.raises(ValueError, match='GRADS_DTYPE'):
        config.verify()  # model_api.py:99 runs this at construction


def test_grads_dtype_rejects_fp32_compute():
    """bf16 grads require bf16 compute: under fp32 compute the pre-cast
    would silently bf16-round every weight in the training forward while
    eval uses the uncast params (code-review r5 finding)."""
    config = benchlib.headline_config(
        benchlib.SMOKE_SHAPES, COMPUTE_DTYPE='float32',
        GRADS_DTYPE='bfloat16')
    with pytest.raises(ValueError, match="COMPUTE_DTYPE"):
        config.verify()


@pytest.mark.parametrize('nu_dtype', ['float32', 'bfloat16'])
def test_trainer_consumes_adam_nu_dtype(nu_dtype):
    """Config.ADAM_NU_DTYPE threads through Trainer: the live opt_state's
    nu leaves carry the configured dtype and a train step runs."""
    shapes = benchlib.SMOKE_SHAPES
    config = benchlib.headline_config(
        shapes, COMPUTE_DTYPE='float32', ADAM_NU_DTYPE=nu_dtype)
    trainer, state = benchlib.build_trainer(config, shapes)
    nu = state.opt_state[0].nu
    want = jnp.bfloat16 if nu_dtype == 'bfloat16' else jnp.float32
    for leaf in jax.tree_util.tree_leaves(nu):
        assert leaf.dtype == want
    feeds = benchlib.staged(trainer, benchlib.random_batches(shapes, 1))
    state2, loss = trainer.train_step_placed(state, feeds[0])
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(state2.opt_state[0].nu):
        assert leaf.dtype == want
