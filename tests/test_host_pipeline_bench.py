"""Smoke for the host input-pipeline benchmark (VERDICT r1 #7): guards
the script against import/config rot; the real numbers are captured by
running it at full size (see PARITY.md 'Host pipeline throughput').

Also the CPU-only guard on the packed wire format's byte win: on the
java14m-shaped synthetic corpus the packed bytes/batch must stay <= 50%
of the plane format's, so the transfer-bound optimization (ISSUE 1,
PERF.md 'Wire format') cannot silently regress without a TPU."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, 'benchmarks', 'bench_host_pipeline.py')


def run_bench(*extra_args):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    proc = subprocess.run(
        [sys.executable, SCRIPT, *extra_args],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip()]


def test_host_pipeline_bench_emits_json_lines():
    records = run_bench('--rows', '400', '--contexts', '8',
                        '--batch-size', '64')
    throughput = [r for r in records
                  if r['metric'] == 'host_pipeline_examples_per_sec']
    variants = {r['variant'] for r in throughput}
    assert 'python' in variants and 'cache' in variants
    for record in throughput:
        assert record['value'] > 0
        assert 'vs_north_star' in record


def test_packed_wire_bytes_at_most_half_of_planes():
    """The acceptance floor for the packed format: >= 2x fewer bytes per
    batch on a java14m-shaped corpus (row lengths [C/8, C/2] — see
    synthesize_dataset). C and B are large enough that the capacity
    bucketing overhead cannot mask the fill-rate win."""
    records = run_bench('--rows', '2000', '--contexts', '64',
                        '--batch-size', '256', '--variants', 'wire')
    wire = {r['variant']: r for r in records
            if r['metric'] == 'wire_bytes_per_batch'}
    assert set(wire) == {'planes', 'packed'}
    assert wire['planes']['value'] > 0
    assert wire['packed']['value'] <= 0.5 * wire['planes']['value'], wire
