"""Smoke for the host input-pipeline benchmark (VERDICT r1 #7): guards
the script against import/config rot; the real numbers are captured by
running it at full size (see PARITY.md 'Host pipeline throughput')."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, 'benchmarks', 'bench_host_pipeline.py')


def test_host_pipeline_bench_emits_json_lines():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS='cpu')
    proc = subprocess.run(
        [sys.executable, SCRIPT, '--rows', '400', '--contexts', '8',
         '--batch-size', '64'],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    records = [json.loads(line) for line in proc.stdout.splitlines()
               if line.strip()]
    variants = {r['variant'] for r in records}
    assert 'python' in variants and 'cache' in variants
    for record in records:
        assert record['metric'] == 'host_pipeline_examples_per_sec'
        assert record['value'] > 0
        assert 'vs_north_star' in record
