"""Fused softmax-CE kernel (ops/pallas_ce.py) vs the jnp reference path,
in interpreter mode on CPU: forward values, both gradients, vocab padding
masks, and the loss_and_aux integration."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.models import functional
from code2vec_tpu.ops import pallas_ce

pytestmark = pytest.mark.skipif(not pallas_ce.PALLAS_AVAILABLE,
                                reason='pallas unavailable')


def _case(rng, batch=16, dim=8, vocab=40, num_valid=None):
    code = rng.normal(size=(batch, dim)).astype(np.float32)
    w = rng.normal(size=(vocab, dim)).astype(np.float32)
    label = rng.integers(0, num_valid or vocab, (batch,)).astype(np.int32)
    weight = (rng.random(batch) > 0.2).astype(np.float32)
    return (jnp.asarray(code), jnp.asarray(w), jnp.asarray(label),
            jnp.asarray(weight))


def _reference(code, w, label, weight, num_valid, dtype=jnp.float32):
    params = functional.Code2VecParams(
        token_embedding=None, path_embedding=None, target_embedding=w,
        transform=None, attention=None)
    logits = functional.compute_logits(params, code, dtype=dtype,
                                       num_valid_targets=num_valid)
    return functional.weighted_ce_sums(logits, label, weight)


@pytest.mark.parametrize('num_valid', [40, 33])
def test_forward_matches_reference(num_valid):
    code, w, label, weight = _case(np.random.default_rng(0),
                                   num_valid=num_valid)
    want_ce, want_w = _reference(code, w, label, weight, num_valid)
    got_ce, got_w = pallas_ce.fused_weighted_ce_sums(
        w, code, label, weight, num_valid, interpret=True)
    np.testing.assert_allclose(float(got_ce), float(want_ce), rtol=1e-5)
    np.testing.assert_allclose(float(got_w), float(want_w))


@pytest.mark.parametrize('num_valid', [40, 33])
def test_gradients_match_reference(num_valid):
    code, w, label, weight = _case(np.random.default_rng(1),
                                   num_valid=num_valid)

    def ref_loss(c, t):
        ce_sum, w_sum = _reference(c, t, label, weight, num_valid)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    def fused_loss(c, t):
        ce_sum, w_sum = pallas_ce.fused_weighted_ce_sums(
            t, c, label, weight, num_valid, interpret=True)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    want_dc, want_dw = jax.grad(ref_loss, argnums=(0, 1))(code, w)
    got_dc, got_dw = jax.grad(fused_loss, argnums=(0, 1))(code, w)
    np.testing.assert_allclose(np.asarray(got_dc), np.asarray(want_dc),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(want_dw),
                               rtol=1e-4, atol=1e-6)


def test_vocab_padding_to_tile_multiple():
    """Vocab far below one VOCAB_TILE: the pad columns must not leak into
    lse and their dW must come back exactly zero-shaped (w's own shape)."""
    code, w, label, weight = _case(np.random.default_rng(2), vocab=40)
    got_ce, _ = pallas_ce.fused_weighted_ce_sums(
        w, code, label, weight, 40, interpret=True)
    want_ce, _ = _reference(code, w, label, weight, 40)
    np.testing.assert_allclose(float(got_ce), float(want_ce), rtol=1e-5)

    dw = jax.grad(lambda t: pallas_ce.fused_weighted_ce_sums(
        t, code, label, weight, 40, interpret=True)[0])(w)
    assert dw.shape == w.shape


def test_online_lse_across_many_blocks(monkeypatch):
    """Force multiple grid steps (tiny tile) so the online max/sumexp
    rescaling actually runs, with adversarial magnitude jumps between
    blocks."""
    monkeypatch.setattr(pallas_ce, 'VOCAB_TILE', 8)
    rng = np.random.default_rng(3)
    code, w, label, weight = _case(rng, vocab=64)
    # scale blocks very differently so the running max moves mid-stream
    scales = np.repeat([1.0, 30.0, 0.01, 10.0, 0.1, 20.0, 2.0, 5.0], 8)
    w = jnp.asarray(np.asarray(w) * scales[:, None])
    want_ce, _ = _reference(code, w, label, weight, 64)
    got_ce, _ = pallas_ce.fused_weighted_ce_sums(
        w, code, label, weight, 64, interpret=True)
    np.testing.assert_allclose(float(got_ce), float(want_ce), rtol=1e-5)


def test_loss_and_aux_integration():
    """loss_and_aux(use_fused_ce=True) equals the default path bit-close
    on the same inputs."""
    rng = np.random.default_rng(4)
    B, C, Vt, Vp, Vy, d, D = 8, 6, 30, 10, 20, 4, 12
    params = functional.init_params(
        jax.random.PRNGKey(0), token_vocab_size=Vt, path_vocab_size=Vp,
        target_vocab_size=Vy, token_dim=d, path_dim=d, code_dim=D)
    source = jnp.asarray(rng.integers(1, Vt, (B, C)).astype(np.int32))
    path = jnp.asarray(rng.integers(1, Vp, (B, C)).astype(np.int32))
    target = jnp.asarray(rng.integers(1, Vt, (B, C)).astype(np.int32))
    mask = jnp.ones((B, C), jnp.float32)
    label = jnp.asarray(rng.integers(1, Vy, (B,)).astype(np.int32))
    weight = jnp.ones((B,), jnp.float32)

    want, _ = functional.loss_and_aux(params, source, path, target, mask,
                                      label, weight, num_valid_targets=Vy)
    got, _ = functional.loss_and_aux(params, source, path, target, mask,
                                     label, weight, num_valid_targets=Vy,
                                     use_fused_ce=True)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)

    want_g = jax.grad(lambda p: functional.loss_and_aux(
        p, source, path, target, mask, label, weight,
        num_valid_targets=Vy)[0])(params)
    got_g = jax.grad(lambda p: functional.loss_and_aux(
        p, source, path, target, mask, label, weight,
        num_valid_targets=Vy, use_fused_ce=True)[0])(params)
    for name in ('target_embedding', 'transform', 'token_embedding'):
        np.testing.assert_allclose(
            np.asarray(getattr(got_g, name)),
            np.asarray(getattr(want_g, name)), rtol=1e-4, atol=1e-6)


def test_full_train_step_with_fused_ce():
    """A jitted Trainer step with USE_PALLAS_FUSED_CE produces the same
    losses as the default path (interpreter mode on CPU) — the kernel
    composes with donation, optimizer update, and the trainer jit."""
    from tests.test_embed_grad import _single_device_trainer
    from tests.test_sharding import _run_steps

    _, dense = _run_steps(_single_device_trainer(), n=2)
    _, fused = _run_steps(
        _single_device_trainer(USE_PALLAS_FUSED_CE=True), n=2)
    np.testing.assert_allclose(fused, dense, rtol=1e-5)


@pytest.mark.parametrize('num_valid', [64, 50, 20])
def test_sharded_matches_reference(monkeypatch, num_valid):
    """The shard_mapped kernel on a (4, 2) mesh: row-sharded table,
    batch-sharded code, online stats merged over the model axis. num_valid
    50 cuts mid-shard; 20 < V/m = 32 leaves shard 1 with zero valid rows
    (the degenerate-shard underflow path)."""
    from code2vec_tpu.parallel import mesh as mesh_lib
    from tests.test_sharding import _config

    monkeypatch.setattr(pallas_ce, 'VOCAB_TILE', 8)
    mesh = mesh_lib.create_mesh(_config(4, 2))
    code, w, label, weight = _case(np.random.default_rng(5), vocab=64,
                                   num_valid=num_valid)
    want_ce, want_w = _reference(code, w, label, weight, num_valid)
    got_ce, got_w = pallas_ce.sharded_fused_weighted_ce_sums(
        w, code, label, weight, num_valid, mesh, interpret=True)
    np.testing.assert_allclose(float(got_ce), float(want_ce), rtol=1e-5)
    np.testing.assert_allclose(float(got_w), float(want_w))

    def ref_loss(c, t):
        ce_sum, w_sum = _reference(c, t, label, weight, num_valid)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    def fused_loss(c, t):
        ce_sum, w_sum = pallas_ce.sharded_fused_weighted_ce_sums(
            t, c, label, weight, num_valid, mesh, interpret=True)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    want_dc, want_dw = jax.grad(ref_loss, argnums=(0, 1))(code, w)
    got_dc, got_dw = jax.grad(fused_loss, argnums=(0, 1))(code, w)
    np.testing.assert_allclose(np.asarray(got_dc), np.asarray(want_dc),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(want_dw),
                               rtol=1e-4, atol=1e-6)


def test_sharded_non_tile_multiple_shards(monkeypatch):
    """Per-shard rows NOT a VOCAB_TILE multiple (vshard=20, tile=8): the
    kernel pads each shard's block to 24 columns, so a neighbor shard's
    VALID weight-1 label (e.g. global 21 on shard 1) collides with shard
    0's pad window [20, 24) — the forward pick must gate that match out
    (regression: ungated, shard 0 psums the -1e30 sentinel into picked
    and the loss explodes)."""
    from code2vec_tpu.parallel import mesh as mesh_lib
    from tests.test_sharding import _config

    monkeypatch.setattr(pallas_ce, 'VOCAB_TILE', 8)
    mesh = mesh_lib.create_mesh(_config(4, 2))
    rng = np.random.default_rng(8)
    code, w, _, _ = _case(rng, vocab=40)
    # every global label index appears somewhere; all rows carry weight 1
    label = jnp.asarray((np.arange(16) + 14) % 40, dtype=jnp.int32)
    weight = jnp.ones((16,), jnp.float32)
    want_ce, _ = _reference(code, w, label, weight, 40)
    got_ce, _ = pallas_ce.sharded_fused_weighted_ce_sums(
        w, code, label, weight, 40, mesh, interpret=True)
    np.testing.assert_allclose(float(got_ce), float(want_ce), rtol=1e-5)

    def fused_loss(c, t):
        ce_sum, w_sum = pallas_ce.sharded_fused_weighted_ce_sums(
            t, c, label, weight, 40, mesh, interpret=True)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    def ref_loss(c, t):
        ce_sum, w_sum = _reference(c, t, label, weight, 40)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    want_dc, want_dw = jax.grad(ref_loss, argnums=(0, 1))(code, w)
    got_dc, got_dw = jax.grad(fused_loss, argnums=(0, 1))(code, w)
    np.testing.assert_allclose(np.asarray(got_dc), np.asarray(want_dc),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(want_dw),
                               rtol=1e-4, atol=1e-6)


def test_bfloat16_compute_close_to_xla_path():
    """The on-chip A/B (bench_fused_ce.py) runs the headline bfloat16
    config: the kernel's bf16 arms must track the XLA path's bf16 CE
    within bf16 tolerance, value and grads. The arms legitimately differ
    beyond rounding: compute_logits' bf16 matmul rounds its logits to
    bf16, while the kernel keeps fp32 accumulation — hence the loose
    tolerances."""
    code, w, label, weight = _case(np.random.default_rng(6), num_valid=40)

    def ref_loss(c, t):
        ce_sum, w_sum = _reference(c, t, label, weight, 40,
                                   dtype=jnp.bfloat16)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    def fused_loss(c, t):
        ce_sum, w_sum = pallas_ce.fused_weighted_ce_sums(
            t, c, label, weight, 40, dtype=jnp.bfloat16, interpret=True)
        return ce_sum / jnp.maximum(w_sum, 1.0)

    np.testing.assert_allclose(float(fused_loss(code, w)),
                               float(ref_loss(code, w)), rtol=2e-2)
    want_dc, want_dw = jax.grad(ref_loss, argnums=(0, 1))(code, w)
    got_dc, got_dw = jax.grad(fused_loss, argnums=(0, 1))(code, w)
    np.testing.assert_allclose(np.asarray(got_dc), np.asarray(want_dc),
                               rtol=5e-2, atol=5e-3)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(want_dw),
                               rtol=5e-2, atol=5e-3)


@pytest.mark.parametrize('shard_contexts', [False, True])
def test_full_train_step_with_fused_ce_on_mesh(shard_contexts):
    """End to end on the (4, 2) mesh: jitted train steps with the
    shard_mapped fused CE match the dense path's losses — the kernel
    composes with GSPMD around it (sharded tables, DP grad psum, and the
    contexts-axis sequence parallelism which also uses the model axis)."""
    from tests.test_sharding import _run_steps, _trainer

    _, dense = _run_steps(_trainer(4, 2, SHARD_CONTEXTS=shard_contexts), n=2)
    _, fused = _run_steps(_trainer(4, 2, USE_PALLAS_FUSED_CE=True,
                                   SHARD_CONTEXTS=shard_contexts), n=2)
    np.testing.assert_allclose(fused, dense, rtol=1e-5)


def test_target_table_padded_to_tile():
    """With the knob on, the target table allocation is a VOCAB_TILE
    multiple so the kernel's own pad is a no-op on the hot path."""
    from code2vec_tpu.models.backends import JaxBackend
    from code2vec_tpu.vocab import SizeOnlyVocabs
    from tests.test_sharding import _config

    config = _config(1, 1, USE_PALLAS_FUSED_CE=True, PARAM_ROW_ALIGNMENT=8)
    backend = JaxBackend(config, SizeOnlyVocabs(40, 12, 24))
    assert backend.sizes['target_vocab_size'] % pallas_ce.VOCAB_TILE == 0
    assert backend.num_valid_targets == 24


def test_vocab_tile_override_validation():
    """ADVICE r4: a bad PALLAS_CE_VOCAB_TILE must degrade to the default
    with a warning, never crash the import or silently pick an unrunnable
    tile; oversize tiles are accepted with a VMEM warning (Mosaic gives
    the real verdict)."""
    import warnings
    from code2vec_tpu.ops.pallas_ce import (_DEFAULT_VOCAB_TILE,
                                            _parse_vocab_tile)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        assert _parse_vocab_tile('abc') == _DEFAULT_VOCAB_TILE
        assert _parse_vocab_tile('100') == _DEFAULT_VOCAB_TILE
        assert _parse_vocab_tile('-256') == _DEFAULT_VOCAB_TILE
        assert _parse_vocab_tile('2048') == 2048
    assert len(caught) == 4
    with warnings.catch_warnings():
        warnings.simplefilter('error')
        assert _parse_vocab_tile('256') == 256
        assert _parse_vocab_tile('1024') == 1024
