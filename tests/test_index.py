"""Embedding-index correctness (ISSUE 5 tentpole): store round-trips,
exact k-NN bit-for-rank against a NumPy reference (mesh-sharded AND
streamed host-merge tiers, random and tie-heavy inputs, k > n_shard),
IVF recall, and the float16 store parity satellite."""
import os

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.index import store as store_lib
from code2vec_tpu.index.exact import ExactIndex, search_streamed
from code2vec_tpu.index.ivf import IVFIndex, measure_recall
from code2vec_tpu.parallel import mesh as mesh_lib


def reference_search(vectors, queries, k, metric='cosine'):
    """NumPy ground truth: float32 scores, ties by lowest index."""
    vectors = np.asarray(vectors, np.float32)
    queries = np.atleast_2d(np.asarray(queries, np.float32))
    if metric == 'cosine':
        vectors = store_lib.normalize_rows(vectors)
        queries = store_lib.normalize_rows(queries)
    scores = (queries @ vectors.T).astype(np.float32)
    idx = np.argsort(-scores, axis=-1, kind='stable')[:, :k]
    return np.take_along_axis(scores, idx, axis=-1), idx


def clustered_corpus(n, dim, centers, seed=0, spread=0.15):
    """Gaussian mixture with noise NORM ~spread (per-coordinate σ
    scaled by 1/sqrt(dim)) — cluster tightness independent of dim."""
    rng = np.random.default_rng(seed)
    c = rng.normal(size=(centers, dim))
    c /= np.linalg.norm(c, axis=1, keepdims=True)
    assign = rng.integers(0, centers, n)
    return (c[assign]
            + (spread / np.sqrt(dim)) * rng.normal(size=(n, dim))
            ).astype(np.float32)


# ------------------------------------------------------------------ store
def test_store_round_trip_with_labels_and_shards(tmp_path):
    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(700, 16)).astype(np.float32)
    labels = ['m%d' % i for i in range(700)]
    store = store_lib.build(str(tmp_path / 's.vecindex'),
                            [vecs[:300], vecs[300:]], metric='dot',
                            labels=labels, shard_rows=256)
    assert (store.count, store.dim) == (700, 16)
    assert store.shards == [256, 256, 188]
    assert not store.normalized
    np.testing.assert_array_equal(store.all_rows(), vecs)
    assert list(store.labels[:2]) == ['m0', 'm1']
    # reopen from disk
    reopened = store_lib.VectorStore(store.path)
    np.testing.assert_array_equal(reopened.all_rows(), vecs)
    assert reopened.label_of(699) == 'm699'


def test_store_cosine_normalizes_and_float16_halves_bytes(tmp_path):
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(64, 32)).astype(np.float32)
    vecs[7] = 0.0  # zero row must survive normalization as zero
    s32 = store_lib.build(str(tmp_path / 'f32.vecindex'), [vecs])
    s16 = store_lib.build(str(tmp_path / 'f16.vecindex'), [vecs],
                          dtype='float16')
    assert s32.normalized and s16.normalized
    norms = np.linalg.norm(np.asarray(s32.all_rows(), np.float32), axis=1)
    assert np.allclose(np.delete(norms, 7), 1.0, atol=1e-5)
    assert norms[7] == 0.0
    bytes32 = os.path.getsize(os.path.join(s32.path, 'shard_00000.bin'))
    bytes16 = os.path.getsize(os.path.join(s16.path, 'shard_00000.bin'))
    assert bytes16 * 2 == bytes32


def test_store_builders_from_text_and_word2vec(tmp_path):
    rng = np.random.default_rng(2)
    vecs = rng.normal(size=(20, 8)).astype(np.float32)
    vectors_path = tmp_path / 'corpus.c2v.vectors'
    with open(vectors_path, 'w') as f:
        for vec in vecs:
            f.write(' '.join(map(str, vec)) + '\n')
    st = store_lib.build_from_vectors_file(str(vectors_path),
                                           metric='dot')
    assert st.count == 20 and st.path == str(vectors_path) + '.vecindex'
    np.testing.assert_allclose(np.asarray(st.all_rows()), vecs,
                               rtol=1e-6)
    # word2vec text (--export_vocab_vectors / --save_word2v output)
    w2v_path = tmp_path / 'targets.txt'
    with open(w2v_path, 'w') as f:
        f.write('20 8\n')
        for i, vec in enumerate(vecs):
            f.write('word|%d ' % i + ' '.join(map(str, vec)) + '\n')
    sw = store_lib.build_from_word2vec(str(w2v_path), metric='dot')
    assert sw.count == 20
    assert sw.label_of(3) == 'word|3'
    np.testing.assert_allclose(np.asarray(sw.all_rows()), vecs,
                               rtol=1e-6)


def test_store_rejects_misaligned_labels(tmp_path):
    with pytest.raises(ValueError, match='label'):
        store_lib.build(str(tmp_path / 'bad.vecindex'),
                        [np.ones((4, 3), np.float32)], labels=['a', 'b'])


# ------------------------------------------------------------------ exact
@pytest.mark.parametrize('metric', ['cosine', 'dot'])
def test_exact_matches_numpy_bit_for_rank(tmp_path, metric):
    rng = np.random.default_rng(3)
    vecs = rng.normal(size=(500, 24)).astype(np.float32)
    queries = rng.normal(size=(13, 24)).astype(np.float32)
    store = store_lib.build(str(tmp_path / ('%s.vecindex' % metric)),
                            [vecs], metric=metric)
    _want_v, want_i = reference_search(vecs, queries, 10, metric)
    # device-resident, sharded over the 8-device test mesh's data axis
    mesh = mesh_lib.create_mesh(Config(MODEL_LOAD_PATH='unused'))
    got_v, got_i = ExactIndex(store, mesh=mesh).warmup(10).search(
        queries, 10)
    assert np.array_equal(got_i, want_i)
    # unsharded twin agrees too
    got_v1, got_i1 = ExactIndex(store).search(queries, 10)
    assert np.array_equal(got_i1, want_i)
    np.testing.assert_allclose(got_v, got_v1, atol=2e-6)


def test_exact_breaks_ties_by_lowest_index(tmp_path):
    # integer grid vectors: EXACT score ties across many rows
    rng = np.random.default_rng(4)
    vecs = rng.integers(0, 2, (96, 8)).astype(np.float32)
    store = store_lib.build(str(tmp_path / 'ties.vecindex'), [vecs],
                            metric='dot')
    queries = rng.integers(0, 2, (6, 8)).astype(np.float32)
    _v, want_i = reference_search(vecs, queries, 12, 'dot')
    _v, got_i = ExactIndex(store).search(queries, 12)
    assert np.array_equal(got_i, want_i)
    _v, streamed_i = search_streamed(store, queries, 12)
    assert np.array_equal(streamed_i, want_i)


def test_streamed_matches_device_including_k_above_shard(tmp_path):
    """The host-merge tier: shards of 40 rows with k=64 > n_shard —
    the −inf/−1 sentinel path — must stay bit-for-rank with the
    device-resident tier and the NumPy reference."""
    rng = np.random.default_rng(5)
    vecs = rng.normal(size=(150, 12)).astype(np.float32)
    store = store_lib.build(str(tmp_path / 'st.vecindex'), [vecs],
                            shard_rows=40)
    queries = rng.normal(size=(3, 12)).astype(np.float32)
    _want_v, want_i = reference_search(vecs, queries, 64)
    got_v, got_i = ExactIndex(store).search(queries, 64)
    streamed_v, streamed_i = search_streamed(store, queries, 64)
    assert np.array_equal(got_i, want_i)
    assert np.array_equal(streamed_i, want_i)
    np.testing.assert_allclose(streamed_v, got_v, atol=2e-6)


def test_exact_caps_k_at_store_size(tmp_path):
    vecs = np.eye(5, dtype=np.float32)
    store = store_lib.build(str(tmp_path / 'tiny.vecindex'), [vecs],
                            metric='dot')
    values, indices = ExactIndex(store).search(vecs[0], 50)
    assert indices.shape == (1, 5)
    assert indices[0, 0] == 0 and values[0, 0] == 1.0


# -------------------------------------------------------------------- ivf
def test_ivf_recall_and_full_probe_equivalence(tmp_path):
    vecs = clustered_corpus(3000, 24, centers=40, seed=6)
    store = store_lib.build(str(tmp_path / 'ivf.vecindex'), [vecs])
    exact = ExactIndex(store)
    ivf = IVFIndex.build(store)
    rng = np.random.default_rng(7)
    queries = (vecs[rng.choice(3000, 48)]
               + 0.01 * rng.normal(size=(48, 24))).astype(np.float32)
    recall = measure_recall(ivf, exact, queries, k=10)
    assert recall >= 0.9, recall
    # probing EVERY list degenerates to exact search
    assert measure_recall(ivf, exact, queries, k=10,
                          nprobe=ivf.n_clusters) == 1.0
    # sidecar reload answers identically
    reloaded = IVFIndex(store_lib.VectorStore(store.path))
    v1, i1 = ivf.search(queries[:5], 10)
    v2, i2 = reloaded.search(queries[:5], 10)
    assert np.array_equal(i1, i2)


def test_ivf_pads_with_sentinels_when_lists_run_dry(tmp_path):
    """k larger than the probed lists' candidates: the tail must be the
    −1/−inf sentinel pair, and real rows must never repeat."""
    vecs = clustered_corpus(120, 8, centers=12, seed=8)
    store = store_lib.build(str(tmp_path / 'dry.vecindex'), [vecs])
    ivf = IVFIndex.build(store)
    values, indices = ivf.search(vecs[:2], 60, nprobe=1)
    for row_i in indices:
        real = row_i[row_i >= 0]
        assert len(set(real.tolist())) == len(real)
        assert len(real) < 60  # one list cannot hold them all
    assert np.all(np.isneginf(values[indices < 0]))


def test_float16_store_recall_parity(tmp_path):
    """ISSUE 5 satellite: --vectors-dtype float16 halves the footprint;
    recall@10 vs the float32 exact ranking must be unchanged within
    tolerance."""
    vecs = clustered_corpus(2000, 32, centers=30, seed=9)
    s32 = store_lib.build(str(tmp_path / 'p32.vecindex'), [vecs])
    s16 = store_lib.build(str(tmp_path / 'p16.vecindex'), [vecs],
                          dtype='float16')
    rng = np.random.default_rng(10)
    queries = (vecs[rng.choice(2000, 64)]
               + 0.01 * rng.normal(size=(64, 32))).astype(np.float32)
    _v, idx32 = ExactIndex(s32).search(queries, 10)
    _v, idx16 = ExactIndex(s16).search(queries, 10)
    overlap = np.mean([
        len(set(a.tolist()) & set(b.tolist())) / 10.0
        for a, b in zip(idx32, idx16)])
    assert overlap >= 0.97, overlap


@pytest.mark.slow
def test_ivf_recall_at_default_nprobe_50k(tmp_path):
    """ISSUE 5 acceptance (slow tier): recall@10 >= 0.95 at the default
    nprobe on a >= 50k-vector corpus."""
    vecs = clustered_corpus(50000, 64, centers=500, seed=11)
    store = store_lib.build(str(tmp_path / 'big.vecindex'), [vecs])
    exact = ExactIndex(store)
    ivf = IVFIndex.build(store)
    rng = np.random.default_rng(12)
    queries = (vecs[rng.choice(50000, 128)]
               + 0.01 * rng.normal(size=(128, 64))).astype(np.float32)
    recall = measure_recall(ivf, exact, queries, k=10)
    assert recall >= 0.95, recall


# -------------------------------------------------------- schema coverage
def test_metrics_lint_covers_index_package():
    """ISSUE 5 satellite: the schema lint must scan code2vec_tpu/index/
    — an uncataloged metric there has to fail tier-1."""
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, 'scripts'))
    import check_metrics_schema
    emissions = check_metrics_schema.find_emissions()
    index_sites = [name for rel, _line, name in emissions
                   if rel.startswith(os.path.join('code2vec_tpu',
                                                  'index'))]
    assert 'index/queries_total' in index_sites
    assert 'index/recall_at10' in index_sites
