"""Training goodput plane tests (ISSUE 17): badput-ledger interval
classification, MFU math against hand-computed FLOPs, the step-time
anomaly watchdog's fire/cooldown contract and its TraceController
auto-capture, the jax-free goodput_report CLI, and the zero-overhead
guarantee with telemetry off."""
import json
import os
import sys
import time
import types

import pytest

from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry import goodput
from code2vec_tpu.telemetry.trace import TraceController

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_registry():
    """Registry + active-ledger reset between tests: both are
    process-global by design, so every test starts and ends clean."""
    core.reset()
    core.enable()
    goodput.deactivate()
    yield
    goodput.deactivate()
    core.reset()
    core.disable()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, seconds):
        self.t += seconds


def make_ledger(tmp_path=None, **kwargs):
    path = str(tmp_path / 'intervals.jsonl') if tmp_path else None
    clock = FakeClock()
    return goodput.GoodputLedger(path, clock=clock, **kwargs), clock


def read_records(path):
    return [json.loads(line) for line in
            open(path).read().splitlines()]


# ------------------------------------------------- ledger classification
def test_ledger_classifies_every_second(tmp_path):
    """The accounting contract: productive + typed badput == wall, with
    warmup, input-wait excess, compile, eval, and checkpoint each landing
    in their own bucket."""
    led, clock = make_ledger(tmp_path)
    led.run_start(step=0)

    # iteration 1: 0.3s input wait (threshold excess is badput), 0.1s
    # compile inside the step, 1.0s total -> clean remainder is warmup
    led.note_input_wait(0.3)
    led.on_compile(0.1)
    clock.advance(1.0)
    clean, had_compile = led.step_done(1, 1.0)
    assert had_compile
    expected_wait = 0.3 - goodput.INPUT_WAIT_THRESHOLD_S
    assert clean == pytest.approx(1.0 - expected_wait - 0.1)

    # iteration 2: clean 0.5s step -> productive
    led.note_input_wait(0.001)  # under threshold: not badput
    clock.advance(0.5)
    clean, had_compile = led.step_done(2, 0.5)
    assert not had_compile
    assert clean == pytest.approx(0.5)

    # epoch end, outside any iteration: eval then checkpoint intervals
    with led.interval(goodput.KIND_EVAL):
        clock.advance(2.0)
    with led.interval(goodput.KIND_CHECKPOINT):
        clock.advance(0.25)
    led.run_end(step=2)

    snap = led.snapshot()
    bad = snap['badput_s']
    assert bad['input_wait'] == pytest.approx(expected_wait)
    assert bad['compile'] == pytest.approx(0.1)
    assert bad['warmup'] == pytest.approx(1.0 - expected_wait - 0.1)
    assert bad['eval'] == pytest.approx(2.0)
    assert bad['checkpoint'] == pytest.approx(0.25)
    assert snap['productive_s'] == pytest.approx(0.5)
    # honesty check: buckets + productive == ledger wall
    assert snap['productive_s'] + sum(bad.values()) \
        == pytest.approx(snap['wall_s'])
    kinds = [r['kind'] for r in read_records(led._path)]
    assert kinds == ['run_start', 'interval', 'interval', 'run_end']


def test_nested_interval_marks_absorb_into_outermost(tmp_path):
    """model_api's eval funnel runs inside the trainer's eval-callback
    wrap: the wall seconds must count once, under the OUTER kind."""
    led, clock = make_ledger(tmp_path)
    with led.interval(goodput.KIND_EVAL):
        clock.advance(1.0)
        with led.interval(goodput.KIND_CHECKPOINT):
            clock.advance(0.5)
        clock.advance(0.5)
    snap = led.snapshot()
    assert snap['badput_s']['eval'] == pytest.approx(2.0)
    assert snap['badput_s']['checkpoint'] == 0.0
    intervals = [r for r in read_records(led._path)
                 if r['kind'] == 'interval']
    assert len(intervals) == 1 and intervals[0]['type'] == 'eval'


def test_compile_inside_interval_absorbed_not_double_billed():
    """An eval program compiling inside an eval mark: the interval
    already accrues that wall; billing compile too would push the badput
    sum past wall time."""
    led, clock = make_ledger()
    with led.interval(goodput.KIND_EVAL):
        clock.advance(1.0)
        led.on_compile(0.8)
    bad = led.snapshot()['badput_s']
    assert bad['compile'] == 0.0
    assert bad['eval'] == pytest.approx(1.0)


def test_mark_replay_bills_retrained_steps_as_rewind_replay():
    led, clock = make_ledger()
    led.note_input_wait(0.0)
    clock.advance(0.1)
    led.step_done(1, 0.1)  # warmup
    led.mark_replay(2)
    for step in (2, 3):
        led.note_input_wait(0.0)
        clock.advance(0.2)
        led.step_done(step, 0.2)
    led.note_input_wait(0.0)
    clock.advance(0.3)
    led.step_done(4, 0.3)
    snap = led.snapshot()
    assert snap['badput_s']['rewind_replay'] == pytest.approx(0.4)
    assert snap['productive_s'] == pytest.approx(0.3)


def test_run_end_idempotent_per_span(tmp_path):
    """The preempt exit writes run_end with its reason; the fit-finally
    shutdown must not write a second."""
    led, _clock = make_ledger(tmp_path)
    led.run_start()
    led.run_end(step=5, reason='preempt')
    led.run_end(step=5)  # shutdown's duplicate: dropped
    ends = [r for r in read_records(led._path) if r['kind'] == 'run_end']
    assert len(ends) == 1 and ends[0]['reason'] == 'preempt'
    # a new span re-opens
    led.run_start(step=5)
    led.run_end(step=9)
    ends = [r for r in read_records(led._path) if r['kind'] == 'run_end']
    assert len(ends) == 2


def test_harvest_window_rebases_open_interval():
    """A long eval spanning a flush boundary: the elapsed portion bills
    to the closing window, the rest to the next — never double."""
    led, clock = make_ledger()
    led.run_start()
    ctx = led.interval(goodput.KIND_EVAL)
    ctx.__enter__()
    clock.advance(3.0)
    window = led.harvest_window()
    assert window['badput/eval'] == pytest.approx(3.0)
    clock.advance(2.0)
    ctx.__exit__(None, None, None)
    window = led.harvest_window()
    assert window['badput/eval'] == pytest.approx(2.0)
    assert led.snapshot()['badput_s']['eval'] == pytest.approx(5.0)


# --------------------------------------------------------- MFU / roofline
def test_mfu_math():
    # 1e12 flops in 2s against 4 devices of 1e12 peak -> 1/8
    assert goodput.mfu(1e12, 2.0, 1e12, 4) == pytest.approx(0.125)
    assert goodput.mfu(0.0, 1.0, 1e12, 1) == 0.0


def test_resolve_peak_flops_precedence(monkeypatch):
    monkeypatch.delenv(goodput.ENV_DEVICE_PEAK_FLOPS, raising=False)
    # explicit config wins over everything
    assert goodput.resolve_peak_flops(7e12, 'TPU v4') == 7e12
    # env var next
    monkeypatch.setenv(goodput.ENV_DEVICE_PEAK_FLOPS, '9e12')
    assert goodput.resolve_peak_flops(-1.0, 'TPU v4') == 9e12
    monkeypatch.delenv(goodput.ENV_DEVICE_PEAK_FLOPS)
    # then the device-kind table (prefix match)
    assert goodput.resolve_peak_flops(-1.0, 'TPU v4 (chip)') \
        == goodput.KNOWN_DEVICE_PEAK_FLOPS['TPU v4']
    assert goodput.resolve_peak_flops(-1.0, 'TPU v5 lite podslice') \
        == goodput.KNOWN_DEVICE_PEAK_FLOPS['TPU v5 lite']
    # unknown kind -> conservative default
    assert goodput.resolve_peak_flops(-1.0, 'FPGA x1') \
        == goodput.DEFAULT_PEAK_FLOPS


def test_program_cost_matches_hand_computed_flops():
    """Lowered.cost_analysis on a plain matmul must report the textbook
    2*M*K*N flops — the foundation the MFU numerator rests on."""
    import jax
    import jax.numpy as jnp

    from code2vec_tpu.training.trainer import Trainer

    m, k, n = 8, 16, 32
    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    cost = Trainer._program_cost(jax.jit(jnp.dot), a, b)
    assert cost is not None
    assert cost['flops'] == pytest.approx(2 * m * k * n)
    assert cost['bytes_accessed'] > 0


def test_ledger_window_flops_follow_dispatch_shape():
    led, clock = make_ledger()
    led.set_step_cost('packed:64', 100.0, 50.0)
    led.set_step_cost('packed:128', 300.0, 100.0)
    for step, shape in ((1, 'packed:64'), (2, 'packed:128'),
                        (3, 'packed:128')):
        led.note_input_wait(0.0)
        clock.advance(0.1)
        led.step_done(step, 0.1, shape)
    window = led.harvest_window()
    assert window['flops'] == pytest.approx(100.0 + 300.0 + 300.0)
    assert window['steps'] == 3
    assert led.arithmetic_intensity() == pytest.approx(3.0)


# --------------------------------------------------- anomaly watchdog
def _feed_baseline(dog, shape='s', n=20, step_s=0.01, start_step=0):
    for i in range(n):
        assert not dog.observe(shape, step_s, start_step + i)
    return start_step + n


def test_watchdog_fires_once_then_cooldown(tmp_path):
    clock = FakeClock()
    captures = []
    dog = goodput.StepAnomalyWatchdog(
        6.0, cooldown_s=600.0, dump_dir=str(tmp_path),
        on_capture=captures.append, clock=clock)
    step = _feed_baseline(dog)
    # a sustained regression: fires only after `sustain` consecutive
    # outliers, and auto-captures on the first fire
    assert not dog.observe('s', 0.1, step)
    assert not dog.observe('s', 0.1, step + 1)
    assert dog.observe('s', 0.1, step + 2)
    assert captures == [step + 2]
    assert core.registry().counter('goodput/anomalies_total').value == 1
    assert core.registry().counter('goodput/autocaptures_total').value == 1

    # flight dump: fire record + recent window samples
    dump = tmp_path / 'flight_step_anomaly.jsonl'
    records = read_records(dump)
    assert records[0]['kind'] == 'anomaly'
    assert records[0]['autocapture'] is True
    assert records[0]['step'] == step + 2
    assert len(records) > dog.min_samples

    # second anomaly inside the cooldown: counted + dumped, NO capture
    clock.advance(10.0)
    for i in range(3):
        fired = dog.observe('s', 0.1, step + 3 + i)
    assert fired
    assert core.registry().counter('goodput/anomalies_total').value == 2
    assert core.registry().counter('goodput/autocaptures_total').value == 1
    assert captures == [step + 2]
    assert read_records(dump)[0]['autocapture'] is False

    # past the cooldown: the next fire captures again
    clock.advance(600.0)
    for i in range(3):
        fired = dog.observe('s', 0.1, step + 6 + i)
    assert fired
    assert len(captures) == 2


def test_watchdog_interleaved_normal_steps_reset_streak():
    dog = goodput.StepAnomalyWatchdog(6.0, cooldown_s=600.0,
                                      clock=FakeClock())
    step = _feed_baseline(dog)
    assert not dog.observe('s', 0.1, step)
    assert not dog.observe('s', 0.1, step + 1)
    assert not dog.observe('s', 0.01, step + 2)  # streak broken
    assert not dog.observe('s', 0.1, step + 3)
    assert not dog.observe('s', 0.1, step + 4)
    assert core.registry().counter('goodput/anomalies_total').value == 0


def test_watchdog_sigma_zero_disables():
    dog = goodput.StepAnomalyWatchdog(0.0, cooldown_s=600.0,
                                      clock=FakeClock())
    assert not dog.enabled
    for i in range(40):
        assert not dog.observe('s', 10.0, i)


def test_watchdog_baselines_per_shape():
    """A bigger bucket's slower steps are its own normal, not an anomaly
    against the smaller bucket's baseline."""
    dog = goodput.StepAnomalyWatchdog(6.0, cooldown_s=600.0,
                                      clock=FakeClock())
    step = _feed_baseline(dog, shape='packed:64', step_s=0.01)
    # first sightings of a slower shape: baseline still filling
    for i in range(10):
        assert not dog.observe('packed:128', 0.05, step + i)


def test_autocapture_arms_trace_controller_exactly_once(
        tmp_path, monkeypatch):
    """The full anomaly -> profiler-capture path: the watchdog's
    on_capture arms the TraceController at the anomalous step; the next
    maybe_update starts exactly one capture, and the cooldown prevents a
    second."""
    import jax
    calls = []
    monkeypatch.setattr(jax.profiler, 'start_trace',
                        lambda d: calls.append(('start', d)))
    monkeypatch.setattr(jax.profiler, 'stop_trace',
                        lambda: calls.append(('stop', None)))
    ctl = TraceController(str(tmp_path), trace_at_step=-1, num_steps=2)
    clock = FakeClock()
    dog = goodput.StepAnomalyWatchdog(6.0, cooldown_s=600.0,
                                      on_capture=ctl.request, clock=clock)
    step = _feed_baseline(dog)
    fired_at = None
    for i in range(3):
        if dog.observe('s', 0.1, step + i):
            fired_at = step + i
        ctl.maybe_update(step + i)
    assert fired_at is not None
    # the fire armed the controller at the anomalous step; the trainer's
    # next maybe_update (same batch counter) starts the capture
    for i in range(3, 8):
        dog.observe('s', 0.1, step + i)
        ctl.maybe_update(step + i)
    starts = [c for c in calls if c[0] == 'start']
    assert len(starts) == 1
    assert starts[0][1].endswith('step%d' % fired_at)
    assert [c[0] for c in calls][:2] == ['start', 'stop']


# ------------------------------------------- throughput rate attribution
def test_examples_per_sec_excludes_eval_and_checkpoint_wall(tmp_path):
    """Satellite regression: a slow eval inside the flush window must
    not dilute train/examples_per_sec (the gauge measures train steps,
    not eval wall)."""
    from code2vec_tpu.telemetry.stepwatch import StepTelemetry
    cfg = types.SimpleNamespace(TELEMETRY_DIR=str(tmp_path),
                                TELEMETRY_FLUSH_EVERY_STEPS=100,
                                TELEMETRY_CONSOLE_EVERY_SECS=3600.0)
    st = StepTelemetry(cfg)
    try:
        st.resume()
        st.count_batch(1000, 5000)
        # a fake 8s eval recorded by the ledger's rate-excluded marking
        st.goodput._clock = FakeClock(0.0)
        with st.goodput.interval(goodput.KIND_EVAL):
            st.goodput._clock.advance(8.0)
        # pretend the window spans 10 wall seconds
        st._window_t0 = time.monotonic() - 10.0
        st.flush_now(100)
        rate = st.registry.gauge('train/examples_per_sec').value
        # 1000 examples over (10 - 8) train seconds, not over 10
        assert rate == pytest.approx(1000 / 2.0, rel=0.05)
    finally:
        st.shutdown(100)


# ------------------------------------------------------- report CLI
def _scripts_import(name):
    scripts_dir = os.path.join(REPO, 'scripts')
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    return __import__(name)


def _write_ledger(path, spans):
    with open(path, 'w') as f:
        for record in spans:
            f.write(json.dumps(record) + '\n')


def test_goodput_report_render_json_and_merge(tmp_path, capsys):
    goodput_report = _scripts_import('goodput_report')
    base = {'compile': 3.0, 'input_wait': 0.5, 'checkpoint': 1.0,
            'eval': 2.0, 'rewind': 0.5, 'rewind_replay': 1.0,
            'preempt': 0.2, 'warmup': 0.8}
    _write_ledger(tmp_path / 'intervals.jsonl', [
        {'kind': 'run_start', 'wall': 100.0, 'step': 0},
        {'kind': 'window', 'wall': 110.0, 'step': 50, 'elapsed_s': 10.0,
         'productive_s': 6.0, 'steps': 50, 'flops': 5e12, 'mfu': 0.41,
         'badput_s': {'compile': 3.0}},
        {'kind': 'anomaly', 'wall': 115.0, 'step': 70, 'shape':
         'packed:64', 'step_ms': 120.0, 'median_ms': 10.0,
         'mad_scale_ms': 1.0, 'sigma': 110.0, 'autocapture': True},
        {'kind': 'run_end', 'wall': 120.0, 'step': 90, 'reason':
         'preempt', 'wall_s': 20.0, 'productive_s': 10.0, 'steps': 90,
         'badput_s': base},
        # restart after a 30s scheduler gap; second span crashes (no
        # run_end) and is reconstructed from its windows
        {'kind': 'run_start', 'wall': 150.0, 'step': 90},
        {'kind': 'window', 'wall': 160.0, 'step': 140, 'elapsed_s': 10.0,
         'productive_s': 9.0, 'steps': 50, 'flops': 6e12, 'mfu': 0.5,
         'badput_s': {'input_wait': 0.5}},
    ])
    assert goodput_report.main([str(tmp_path / 'intervals.jsonl')]) == 0
    out = capsys.readouterr().out
    assert 'rewind_replay' in out and 'restart_gap' in out
    assert 'unattributed' in out
    assert 'MFU timeline' in out
    assert 'step-time anomalies (1)' in out
    assert 'profiler capture auto-triggered' in out
    assert 'no run_end record' in out

    assert goodput_report.main([str(tmp_path), '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    # wall = span1 20 + gap 30 + span2 (windows) 10
    assert payload['wall_s'] == pytest.approx(60.0)
    assert payload['productive_s'] == pytest.approx(19.0)
    assert payload['badput_s']['restart_gap'] == pytest.approx(30.0)
    # honesty row: buckets + productive sum to wall
    total = payload['productive_s'] + sum(payload['badput_s'].values())
    assert total == pytest.approx(payload['wall_s'])

    # multi-process merge: a directory renders every proc's ledger
    _write_ledger(tmp_path / 'intervals.proc1.jsonl', [
        {'kind': 'run_start', 'wall': 100.0, 'step': 0},
        {'kind': 'run_end', 'wall': 120.0, 'step': 90, 'reason': 'done',
         'wall_s': 20.0, 'productive_s': 15.0, 'steps': 90,
         'badput_s': {'compile': 5.0}},
    ])
    assert goodput_report.main([str(tmp_path), '--json']) == 0
    lines = capsys.readouterr().out.splitlines()
    assert len(lines) == 2
    procs = {json.loads(line)['proc'] for line in lines}
    assert procs == {'proc0', 'proc1'}


def test_goodput_report_missing_dir_fails_typed(tmp_path):
    goodput_report = _scripts_import('goodput_report')
    with pytest.raises(FileNotFoundError):
        goodput_report.main([str(tmp_path)])


def test_flip_verdict_ignores_goodput_columns(tmp_path):
    """A capture round carrying the new goodput measures must not
    confuse the flip ledger: untracked measures are ignored, tracked
    verdicts still settle."""
    flip_verdict = _scripts_import('flip_verdict')
    results = tmp_path / 'results'
    results.mkdir()
    with open(results / 'capture.jsonl', 'w') as f:
        for rec in ({'measure': 'mfu', 'value': 0.42},
                    {'measure': 'goodput_fraction', 'value': 0.93},
                    {'measure': 'badput_compile_pct', 'value': 1.2},
                    {'stage': 'goodput', 'rc': 0,
                     'data': {'measure': 'arithmetic_intensity',
                              'value': 161.0}}):
            f.write(json.dumps(rec) + '\n')
    rc = flip_verdict.main(['--dir', str(results), '--root',
                            str(tmp_path), '--json'])
    # 3 = "all tracked verdicts pending" (this round carried none of
    # them) — the point is a clean exit, not a settle
    assert rc in (0, 3)


# --------------------------------------------------- zero-overhead guard
def test_goodput_inactive_without_telemetry(tmp_path):
    """Telemetry off => no active ledger: every module-level mark site
    reduces to one attribute read and a no-op."""
    assert goodput.active() is None
    goodput.on_compile(1.0)  # no-op, no error
    with goodput.interval(goodput.KIND_EVAL):
        pass
    assert goodput.active() is None
    # and the trainer-side gate: a telemetry-less trainer holds None, so
    # the hot loop never touches goodput objects (same is-None contract
    # as the rest of the telemetry integration)
    assert not os.listdir(str(tmp_path))  # nothing written anywhere


def test_stepwatch_shutdown_deactivates_global_ledger(tmp_path):
    from code2vec_tpu.telemetry.stepwatch import StepTelemetry
    cfg = types.SimpleNamespace(TELEMETRY_DIR=str(tmp_path))
    st = StepTelemetry(cfg)
    st.resume()
    assert goodput.active() is st.goodput
    st.shutdown(0)
    assert goodput.active() is None
    assert not core.enabled()


# ------------------------------------------------- acceptance (slow, e2e)
def _drill_config(tmp_path, **overrides):
    from code2vec_tpu.config import Config
    from tests.test_train_overfit import make_dataset
    prefix = make_dataset(tmp_path)
    defaults = dict(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, SAVE_EVERY_EPOCHS=1000,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        TEST_DATA_PATH=str(tmp_path / 'tiny.val.c2v'),
        MODEL_SAVE_PATH=str(tmp_path / 'models' / 'saved_model'),
        TELEMETRY=True, TELEMETRY_DIR=str(tmp_path / 'tele'),
        TELEMETRY_CONSOLE_EVERY_SECS=3600.0)
    defaults.update(overrides)
    return Config(**defaults)


def _read_tags(path):
    by_tag = {}
    for line in open(path).read().splitlines():
        record = json.loads(line)
        by_tag.setdefault(record['tag'], []).append(record)
    return by_tag


@pytest.mark.slow
def test_goodput_acceptance_rewind_run_reconstructs(tmp_path):
    """ISSUE 17 acceptance: a CPU fit with eval + checkpoints + one
    injected divergence rewind -> the report reconstructs the run
    (buckets sum to wall within 2%, the rewind attributed) and
    train/mfu stays finite with zero post-warmup compiles."""
    import math

    from code2vec_tpu.model_api import Code2VecModel
    config = _drill_config(
        tmp_path, NUM_TRAIN_EPOCHS=8, LEARNING_RATE=0.01,
        SAVE_EVERY_N_STEPS=2, NUM_BATCHES_TO_LOG_PROGRESS=2,
        TELEMETRY_FLUSH_EVERY_STEPS=4, FAULT_INJECT='nan_loss@step=5')
    Code2VecModel(config).train()

    goodput_report = _scripts_import('goodput_report')
    spans = goodput_report.split_spans(goodput_report.load_records(
        str(tmp_path / 'tele' / 'intervals.jsonl')))
    summary = goodput_report.summarize(spans)
    wall = summary['wall_s']
    assert summary['badput_s']['unattributed'] / wall < 0.02
    assert summary['badput_s']['rewind'] > 0
    assert summary['badput_s']['rewind_replay'] > 0
    assert 0 < summary['goodput_fraction'] < 1

    by_tag = _read_tags(tmp_path / 'tele' / 'metrics.jsonl')
    mfus = [r['value'] for r in by_tag['train/mfu']]
    assert mfus and all(math.isfinite(m) and m > 0 for m in mfus)
    # zero post-warmup compiles: the counter is flat over the last
    # half of the run (the rewind restores params, same shapes)
    compiles = [r['value'] for r in by_tag['jit/compiles_total']]
    assert compiles[-1] == compiles[len(compiles) // 2]


@pytest.mark.slow
def test_goodput_acceptance_slow_step_fault_autocaptures_once(tmp_path):
    """ISSUE 17 acceptance: an injected sustained slow-step window
    fires the watchdog, dumps flight_step_anomaly.jsonl, and
    auto-captures EXACTLY one profiler trace (cooldown blocks the
    rest)."""
    import glob

    from code2vec_tpu.model_api import Code2VecModel
    config = _drill_config(
        tmp_path, NUM_TRAIN_EPOCHS=14, NUM_BATCHES_TO_LOG_PROGRESS=4,
        TELEMETRY_FLUSH_EVERY_STEPS=8,
        FAULT_INJECT='slow_step@step=30..44')
    Code2VecModel(config).train()

    tele = tmp_path / 'tele'
    by_tag = _read_tags(tele / 'metrics.jsonl')
    assert by_tag['goodput/anomalies_total'][-1]['value'] >= 1
    assert by_tag['goodput/autocaptures_total'][-1]['value'] == 1
    records = read_records(tele / 'flight_step_anomaly.jsonl')
    assert records[0]['kind'] == 'anomaly'
    assert records[0]['shape'].startswith('packed:')
    trace_dirs = glob.glob(str(tele / 'traces' / 'step*'))
    assert len(trace_dirs) == 1
    assert os.listdir(trace_dirs[0])  # real profiler output landed
    anomalies = [r for r in read_records(tele / 'intervals.jsonl')
                 if r['kind'] == 'anomaly']
    assert sum(1 for a in anomalies if a['autocapture']) == 1
