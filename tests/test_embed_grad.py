"""Correctness of the selectable embedding-gradient strategies
(ops/embed_grad.py): 'sorted' and 'dedup' must reproduce plain autodiff's
table gradient, duplicates and all — they reshape the scatter, not the
math."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops.embed_grad import IMPLS, table_grad, take_rows


def _case(rng, n_rows=50, d=8, shape=(6, 17)):
    table = rng.normal(size=(n_rows, d)).astype(np.float32)
    # heavy duplication: draws from a small row range so most rows are hit
    # multiple times and several not at all
    idx = rng.integers(0, n_rows, size=shape).astype(np.int32)
    g = rng.normal(size=shape + (d,)).astype(np.float32)
    return jnp.asarray(table), jnp.asarray(idx), jnp.asarray(g)


@pytest.mark.parametrize('impl', IMPLS)
def test_forward_equals_take(impl):
    table, idx, _ = _case(np.random.default_rng(0))
    got = take_rows(table, idx, impl=impl)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(jnp.take(table, idx, axis=0)))


@pytest.mark.parametrize('impl', ['sorted', 'dedup'])
def test_table_grad_matches_autodiff(impl):
    rng = np.random.default_rng(1)
    table, idx, g = _case(rng)

    def loss(t, implementation):
        rows = take_rows(t, idx, impl=implementation)
        return jnp.vdot(rows, g)

    want = jax.grad(lambda t: loss(t, 'dense'))(table)
    got = jax.grad(lambda t: loss(t, impl))(table)
    # summation order differs (sorted/segmented vs scatter order), so exact
    # equality is not guaranteed — but at these magnitudes fp32 stays tight
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize('impl', ['sorted', 'dedup'])
def test_table_grad_extremes(impl):
    """All-same index (one giant run) and all-unique indices (no runs)."""
    rng = np.random.default_rng(2)
    d = 4
    table = jnp.asarray(rng.normal(size=(10, d)).astype(np.float32))

    same = jnp.full((31,), 7, jnp.int32)
    g = jnp.asarray(rng.normal(size=(31, d)).astype(np.float32))
    want = table_grad(g, same, 10, jnp.float32, 'dense')
    got = table_grad(g, same, 10, jnp.float32, impl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-6)

    unique = jnp.asarray(rng.permutation(10).astype(np.int32))
    gu = jnp.asarray(rng.normal(size=(10, d)).astype(np.float32))
    want = table_grad(gu, unique, 10, jnp.float32, 'dense')
    got = table_grad(gu, unique, 10, jnp.float32, impl)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _single_device_trainer(**overrides):
    from code2vec_tpu.models.backends import create_backend
    from code2vec_tpu.parallel import mesh as mesh_lib
    from code2vec_tpu.training.trainer import Trainer
    from code2vec_tpu.vocab import SizeOnlyVocabs
    from tests.test_sharding import _config

    config = _config(1, 1, **overrides)
    backend = create_backend(config, SizeOnlyVocabs(40, 12, 24))
    mesh = mesh_lib.create_mesh(config, devices=jax.devices()[:1])
    return Trainer(config, backend, mesh=mesh)


@pytest.mark.parametrize('impl', ['sorted', 'dedup'])
def test_train_step_loss_matches_dense(impl):
    """A jitted train step under each impl produces (near-)identical losses
    to the dense default — same model, same data, same dropout stream."""
    from tests.test_sharding import _run_steps

    _, dense_losses = _run_steps(_single_device_trainer(), n=2)
    _, losses = _run_steps(
        _single_device_trainer(EMBED_GRAD_IMPL=impl), n=2)
    np.testing.assert_allclose(losses, dense_losses, rtol=1e-5)


def test_flax_backend_honors_impl():
    """The flax backend delegates loss/grad to the jax twin
    (backends.py::FlaxBackend.loss_fn), so EMBED_GRAD_IMPL applies under
    BOTH frameworks — this pins that the knob is not silently ignored
    when DL_FRAMEWORK='flax' (the default)."""
    from tests.test_sharding import _run_steps, _trainer

    _, dense = _run_steps(_trainer(4, 2, framework='flax'), n=2)
    _, dedup = _run_steps(
        _trainer(4, 2, framework='flax', EMBED_GRAD_IMPL='dedup'), n=2)
    assert np.isfinite(dedup).all()
    np.testing.assert_allclose(dedup, dense, rtol=1e-5)


@pytest.mark.parametrize('impl', ['sorted', 'dedup'])
def test_train_step_on_tp_mesh(impl):
    """The sort/scan/scatter backward must lower through SPMD partitioning
    on a (4, 2) mesh with row-sharded tables and produce the same losses as
    the single-device run."""
    from tests.test_sharding import _run_steps, _trainer

    _, single = _run_steps(
        _single_device_trainer(EMBED_GRAD_IMPL=impl), n=2)
    _, sharded = _run_steps(_trainer(4, 2, EMBED_GRAD_IMPL=impl), n=2)
    np.testing.assert_allclose(sharded, single, rtol=1e-5)
