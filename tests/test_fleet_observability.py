"""Fleet observability plane (ISSUE 15): cross-process trace
stitching over the mesh wire, worker telemetry/ledger backhaul merged
into ONE fleet export, clock-offset estimation, SLO burn-rate
alarming, flight-dump namespacing, and the ``latency_report.py
--fleet`` view — unit-drilled piece by piece, then end-to-end through
a socket-mode worker kill (the delivered request's stitched tree
shows BOTH incarnations' device work)."""
import json
import os
import sys
import threading
import time
import types

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
_SCRIPTS = os.path.join(REPO, 'scripts')
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

import latency_report  # noqa: E402

from code2vec_tpu.config import Config  # noqa: E402
from code2vec_tpu.resilience import faults  # noqa: E402
from code2vec_tpu.serving import slo as slo_lib  # noqa: E402
from code2vec_tpu.serving import transport as transport_lib  # noqa: E402
from code2vec_tpu.serving.errors import (EngineOverloaded,  # noqa: E402
                                         WireError)
from code2vec_tpu.telemetry import core as tele_core  # noqa: E402
from code2vec_tpu.telemetry import tracing as tracing_lib  # noqa: E402
from tests.test_train_overfit import make_dataset  # noqa: E402

PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]


@pytest.fixture(autouse=True)
def clear_fault_plan():
    faults.configure('')
    yield
    faults.configure('')


# --------------------------------------------------- clock offset units
def test_clock_offset_monotone_under_skewed_clock():
    """The min-filter estimate only ever tightens (monotonically
    nonincreasing), and under a skewed remote clock it recovers the
    true offset up to the smallest observed one-way delay — enough to
    ORDER cross-host stamps."""
    true_offset = -123.456  # remote clock runs 123.456s AHEAD
    rng = np.random.default_rng(3)
    clock = transport_lib.ClockOffset()
    assert clock.offset == 0.0 and clock.samples == 0
    estimates = []
    delays = []
    for _ in range(200):
        delay = float(rng.uniform(0.0005, 0.050))  # wire delay >= 0
        remote_t = float(rng.uniform(0, 1000))
        clock.observe(remote_t, remote_t + true_offset + delay)
        delays.append(delay)
        estimates.append(clock.offset)
    # monotone nonincreasing, never below the true offset
    assert all(b <= a + 1e-12 for a, b in zip(estimates, estimates[1:]))
    assert clock.offset >= true_offset
    assert clock.offset - true_offset <= min(delays) + 1e-9
    # ordering: a remote stamp shifted by the estimate lands within
    # min-delay of its true parent-clock instant
    assert abs((500.0 + clock.offset) - (500.0 + true_offset)) \
        <= min(delays) + 1e-9
    # None samples are ignored, not crashes
    clock.observe(None)
    assert clock.samples == 200


# ------------------------------------------------ typed heartbeat units
def test_heartbeat_schema_validation_typed():
    good = transport_lib.Heartbeat(inflight=2, t_mono=1.0)
    assert transport_lib.check_heartbeat(good) is good
    with pytest.raises(WireError, match='payload schema'):
        transport_lib.check_heartbeat({'inflight': 2})  # the old shape
    with pytest.raises(WireError, match='schema'):
        transport_lib.check_heartbeat(
            transport_lib.Heartbeat(schema=transport_lib.
                                    HEARTBEAT_SCHEMA + 1))


def test_heartbeat_rides_the_frame_wire():
    payload = transport_lib.Heartbeat(
        inflight=1, t_mono=2.5,
        spans=[{'seq': 0, 'member': 0, 'spans': []}],
        telemetry={'jit/compiles_total': 4},
        ledger={'attributed_bytes': 128})
    back = transport_lib.decode_frame(
        transport_lib.encode_frame(('heartbeat', -1, payload)))
    beat = transport_lib.check_heartbeat(back[2])
    assert beat.t_mono == 2.5
    assert beat.telemetry == {'jit/compiles_total': 4}
    assert beat.ledger['attributed_bytes'] == 128


# -------------------------------------------------- adopt_spans units
def _remote_records():
    return [
        {'trace': 'x', 'span': 0, 'parent': None,
         'name': 'serving.remote', 't0': 10.0, 't1': 12.0,
         'attrs': {'replica': 'r0', 'pid': 111}},
        {'trace': 'x', 'span': 1, 'parent': 0,
         'name': 'serving.device_execute', 't0': 10.5, 't1': 11.5},
        {'trace': 'x', 'span': 2, 'parent': 1,
         'name': 'serving.fetch', 't0': 11.0, 't1': 11.4},
    ]


def test_adopt_spans_remaps_ids_applies_offset_and_parents():
    tracer = tracing_lib.Tracer(None, sample_rate=1.0)
    trace = tracer.begin('serving.request')
    chunk = trace.span('serving.chunk')
    assert trace.adopt_spans(_remote_records(), offset_s=-7.0,
                             parent=chunk) == 3
    by_name = {s.name: s for s in trace._spans}
    remote = by_name['serving.remote']
    dev = by_name['serving.device_execute']
    fetch = by_name['serving.fetch']
    # fresh local ids, no collision with the existing spans
    ids = [s.span_id for s in trace._spans]
    assert len(ids) == len(set(ids))
    # the remote root grafts under the member's span; internal links
    # survive the remap
    assert remote.parent_id == chunk.span_id
    assert dev.parent_id == remote.span_id
    assert fetch.parent_id == dev.span_id
    # stamps shifted onto the parent clock
    assert remote.t0 == 3.0 and dev.t1 == 4.5
    trace.finish()
    # a finished trace is already serialized: late spans are refused
    assert trace.adopt_spans(_remote_records()) == 0


def test_adopt_spans_two_incarnations_never_collide():
    tracer = tracing_lib.Tracer(None, sample_rate=1.0)
    trace = tracer.begin('serving.request')
    assert trace.adopt_spans(_remote_records(), 0.0) == 3
    second = _remote_records()
    second[0]['attrs'] = {'replica': 'r0', 'pid': 222}
    assert trace.adopt_spans(second, 0.0) == 3
    remotes = [s for s in trace._spans if s.name == 'serving.remote']
    assert len(remotes) == 2
    assert {s.attrs['pid'] for s in remotes} == {111, 222}
    devs = [s for s in trace._spans
            if s.name == 'serving.device_execute']
    assert {d.parent_id for d in devs} == \
        {r.span_id for r in remotes}


# ---------------------------------------------- remote span sink units
def test_remote_sink_collect_is_seq_keyed_and_drain_age_gated():
    sink = tracing_lib.RemoteSpanSink('r1')
    ctx = {'trace_id': 'abc', 'sampled': True}
    t_a = sink.begin('serving.remote', ctx, seq=4, member=0)
    t_b = sink.begin('serving.remote', ctx, seq=5, member=0)
    t_a.span_at('serving.device_execute', 1.0, 2.0)
    t_a.finish()
    t_b.finish()
    sink.wait_finished([t_a, t_b], timeout=2.0)
    # a concurrent heartbeat with an age gate leaves fresh bundles for
    # their own result frame
    assert sink.drain(min_age_s=60.0) == []
    got = sink.collect(4)
    assert [b['seq'] for b in got] == [4]
    names = [r['name'] for r in got[0]['spans']]
    assert names == ['serving.remote', 'serving.device_execute']
    assert got[0]['spans'][0]['attrs']['replica'] == 'r1'
    # the leftover (seq 5) is the orphan sweep's
    leftovers = sink.drain()
    assert [b['seq'] for b in leftovers] == [5]
    assert sink.drain() == []


def test_remote_sink_outbox_bounded():
    """With heartbeats disabled nothing sweeps orphans: the outbox
    caps (oldest dropped, counted) instead of growing the worker
    without bound."""
    sink = tracing_lib.RemoteSpanSink('r1', max_bundles=2)
    ctx = {'trace_id': 'abc', 'sampled': True}
    for seq in range(5):
        sink.begin('serving.remote', ctx, seq=seq, member=0).finish()
    assert sink.dropped_bundles == 3
    assert [b['seq'] for b in sink.drain()] == [3, 4]


# ------------------------------------------------- SLO monitor units
def test_slo_monitor_quiet_at_baseline_fires_on_burn_latched(tmp_path):
    tracer = tracing_lib.Tracer(str(tmp_path), sample_rate=1.0)
    monitor = slo_lib.SloMonitor(
        availability=0.99, p99_ms=50.0, fast_window_s=30.0,
        slow_window_s=60.0, burn_threshold=5.0, min_events=10,
        tracer=tracer)
    assert monitor.enabled
    for _ in range(50):
        monitor.observe_good(0.005)
    assert monitor.alerts_total.value == 0  # baseline stays quiet
    stats = monitor.stats()
    assert stats['availability_burn_fast'] == 0.0
    # an injected burn (every request shed) crosses both windows
    for _ in range(30):
        monitor.observe_bad('shed')
    assert monitor.alerts_total.value == 1  # latched: fired ONCE
    assert monitor.stats()['alerting']['availability'] is True
    path = os.path.join(str(tmp_path), 'flight_slo_burn.jsonl')
    assert os.path.exists(path)
    header = json.loads(open(path).readline())
    assert header['flight'] == 'slo_burn'
    # p99 leg: slow deliveries burn the 1% latency budget
    for _ in range(40):
        monitor.observe_good(0.500)
    assert monitor.stats()['alerting']['p99'] is True
    assert monitor.alerts_total.value == 2
    assert monitor.slow_total.value == 40


def test_slo_burns_evict_at_read_time():
    """A stats() read long after a burst reports the burn as OVER
    (windows evict at read time), not the burst-time value forever."""
    monitor = slo_lib.SloMonitor(
        availability=0.9, fast_window_s=0.2, slow_window_s=0.3,
        burn_threshold=2.0, min_events=5)
    for _ in range(10):
        monitor.observe_bad('shed')
    assert monitor.stats()['availability_burn_fast'] > 2.0
    time.sleep(0.4)  # both windows age out with NO further traffic
    stale = monitor.stats()
    assert stale['availability_burn_fast'] == 0.0
    assert stale['fast_window_events'] == 0


def test_slo_monitor_disabled_legs():
    monitor = slo_lib.SloMonitor()  # no targets: a no-op observer
    assert not monitor.enabled
    monitor.observe_good(10.0)
    monitor.observe_bad('shed')
    assert monitor.alerts_total.value == 0


# ------------------------------------- flight namespacing + report glob
def test_flight_dumps_namespaced_by_instance_and_globbed(tmp_path):
    out = str(tmp_path)
    parent = tracing_lib.Tracer(out, sample_rate=1.0)
    worker = tracing_lib.Tracer(out, sample_rate=1.0, instance='r1')
    trace = parent.begin('serving.request', attrs={'tier': 'topk'})
    trace.span_at('serving.device_execute', 0.0, 1.0)
    trace.finish()
    remote = worker.begin('serving.request', attrs={'tier': 'topk'})
    remote.finish()
    assert parent.dump_flight('overload', force=True).endswith(
        'flight_overload.jsonl')
    assert worker.dump_flight('overload', force=True).endswith(
        'flight_overload_r1.jsonl')
    # the two processes never clobber one postmortem file...
    assert os.path.exists(os.path.join(out, 'flight_overload.jsonl'))
    assert os.path.exists(
        os.path.join(out, 'flight_overload_r1.jsonl'))
    # ...and the report reads BOTH forms from either entry point, with
    # cross-file dedup
    for entry in ('flight_overload.jsonl', 'flight_overload_r1.jsonl'):
        records = latency_report.load_spans(os.path.join(out, entry))
        traces = latency_report.group_traces(records)
        assert trace.trace_id in traces
        assert remote.trace_id in traces
        assert len(traces[trace.trace_id]['spans']) == 2  # deduped
    # an underscore-bearing event without an instance stays itself
    parent.dump_flight('slo_burn', force=True)
    match = latency_report.FLIGHT_RE.match('flight_slo_burn.jsonl')
    assert match.group('event') == 'slo_burn'
    assert match.group('inst') is None


# -------------------------------------------- fleet report on synthetic
def _synthetic_stitched_log(path):
    """Two delivered traces: one stitched worker-mode (with wire gap),
    one wire-truncated (no device attribution)."""
    records = [
        # stitched: root 0..100ms, queue 5..25, remote 30..90 with
        # device 40..80 — wire = 100 - 20 - 60 = 20ms
        {'trace': 'T1', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.100,
         'dur_ms': 100.0, 'status': 'ok', 'sampled': True,
         'attrs': {'tier': 'topk'}},
        {'trace': 'T1', 'span': 1, 'parent': 0,
         'name': 'serving.queue_wait', 't0': 0.005, 't1': 0.025,
         'dur_ms': 20.0},
        {'trace': 'T1', 'span': 2, 'parent': 0,
         'name': 'serving.remote', 't0': 0.030, 't1': 0.090,
         'dur_ms': 60.0, 'attrs': {'replica': 'r0', 'pid': 7}},
        {'trace': 'T1', 'span': 3, 'parent': 2,
         'name': 'serving.pack', 't0': 0.030, 't1': 0.035,
         'dur_ms': 5.0,
         'attrs': {'bucket': 8, 'tier': 'topk', 'replica': 'r0'}},
        {'trace': 'T1', 'span': 4, 'parent': 2,
         'name': 'serving.device_execute', 't0': 0.040, 't1': 0.080,
         'dur_ms': 40.0},
        # truncated: delivered but its worker spans never stitched
        {'trace': 'T2', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.050,
         'dur_ms': 50.0, 'status': 'ok', 'sampled': True,
         'attrs': {'tier': 'topk'}},
        {'trace': 'T2', 'span': 1, 'parent': 0,
         'name': 'serving.queue_wait', 't0': 0.0, 't1': 0.010,
         'dur_ms': 10.0},
        # a shed trace: not delivered, so never "unstitched"
        {'trace': 'T3', 'span': 0, 'parent': None,
         'name': 'serving.request', 't0': 0.0, 't1': 0.001,
         'dur_ms': 1.0, 'status': 'shed', 'sampled': True,
         'attrs': {'tier': 'topk'}},
    ]
    with open(path, 'w') as f:
        for rec in records:
            f.write(json.dumps(rec) + '\n')


def test_latency_report_fleet_decomposition_and_unstitched(tmp_path,
                                                           capsys):
    spans = str(tmp_path / 'spans.jsonl')
    _synthetic_stitched_log(spans)
    traces = latency_report.group_traces(
        latency_report.load_spans(spans))
    assert latency_report.unstitched_traces(traces) == ['T2']
    fleet = latency_report.fleet_decomposition(traces)
    # unlabeled traffic lands under scenario '-' on the new axis
    parts = fleet[('r0', 'topk', '-')]
    assert parts['end_to_end'] == [100.0]
    assert parts['queue_wait'] == [20.0]
    assert parts['device'] == [40.0]
    assert parts['worker_host'] == [20.0]   # remote 60 - device 40
    assert abs(parts['wire'][0] - 20.0) < 1e-6  # 100 - 20 - 60
    # the truncated trace has no replica attribution: lands under '-'
    assert fleet[('-', 'topk', '-')]['wire'] == [0.0]
    # CLI --fleet --json emits the rows
    assert latency_report.main(
        ['--spans', spans, '--fleet', '--json', '--top', '0']) == 0
    out = capsys.readouterr().out
    rows = [json.loads(line) for line in out.splitlines()]
    unstitched = [r for r in rows
                  if r['measure'] == 'unstitched_traces']
    assert unstitched[0]['value'] == 1
    wire_rows = [r for r in rows
                 if r['measure'] == 'fleet_decomposition_ms'
                 and r['part'] == 'wire' and r['replica'] == 'r0']
    assert wire_rows and abs(wire_rows[0]['p50'] - 20.0) < 1e-6


# ---------------------------------------------- fleet telemetry merge
@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('fleet_obs'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16')
    return Code2VecModel(config)


def _fake_worker(rid):
    clock = transport_lib.ClockOffset()
    clock.observe(0.0, 0.0015)
    return types.SimpleNamespace(rid=rid, clock=clock, _merge_last={})


def test_worker_telemetry_merges_replica_labeled_no_family_splits(
        model, tmp_path):
    """The fleet merge: worker snapshots land replica-labeled in the
    parent registry, counters accumulate by delta across incarnation
    resets, and the Prometheus export stays one contiguous group per
    family (strict expfmt parsers reject split families)."""
    from code2vec_tpu.telemetry.exporters import PrometheusExporter
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              mode='thread')
    tele_core.reset()
    tele_core.enable()
    try:
        timer_stats = {'count': 4, 'mean_ms': 2.0, 'p50_ms': 2.0,
                       'p95_ms': 3.0, 'max_ms': 3.5, 'last_ms': 1.5,
                       'total_s': 0.008}
        snapshot = {
            'serving/requests_total{replica=r7}': 5,
            'serving/latency_ms{replica=r7}': timer_stats,
            'jit/compiles_total': 12,
            'mem/attributed_bytes': 4096.0,
            'not/cataloged': 1.0,
        }
        w7 = _fake_worker('r7')
        w8 = _fake_worker('r8')
        mesh._on_worker_telemetry(w7, snapshot, None)
        mesh._on_worker_telemetry(w8, {'jit/compiles_total': 3}, None)
        reg = tele_core.registry()
        # labeled names keep their label; unlabeled gain the replica's
        assert reg.get(
            'serving/requests_total{replica=r7}').snapshot() == 5
        assert reg.get(
            'jit/compiles_total{replica=r7}').snapshot() == 12
        assert reg.get(
            'jit/compiles_total{replica=r8}').snapshot() == 3
        assert reg.get(
            'mem/attributed_bytes{replica=r7}').snapshot() == 4096.0
        assert reg.get('not/cataloged') is None  # refused the export
        # the parent's own (unlabeled) counter is untouched
        assert reg.get('jit/compiles_total') is None
        # delta merge: monotone growth accumulates, an incarnation
        # reset (restart) keeps accumulating instead of rewinding
        mesh._on_worker_telemetry(w7, {'jit/compiles_total': 15}, None)
        assert reg.get(
            'jit/compiles_total{replica=r7}').snapshot() == 15
        w7b = _fake_worker('r7')  # restarted incarnation, counts reset
        mesh._on_worker_telemetry(w7b, {'jit/compiles_total': 2}, None)
        assert reg.get(
            'jit/compiles_total{replica=r7}').snapshot() == 17
        # clock offset exported per replica
        assert reg.get(
            'mesh/clock_offset_ms{replica=r7}').snapshot() > 0
        # Prometheus export: every family contiguous, replica series
        # distinct
        exporter = PrometheusExporter(str(tmp_path))
        exporter.flush(reg, step=0)
        text = open(exporter.path).read().splitlines()
        fam_of = lambda line: line.split('{')[0].split(' ')[0]  # noqa: E731
        seen, last = {}, None
        for line in text:
            if line.startswith('#'):
                continue
            fam = fam_of(line)
            if fam != last and fam in seen:
                raise AssertionError('family %r split in the fleet '
                                     'export' % fam)
            seen[fam] = True
            last = fam
        lat = [line for line in text
               if line.startswith('code2vec_serving_latency_ms_mean_ms')]
        assert any('replica="r7"' in line for line in lat)
        assert mesh.stats()['worker_snapshots_total'] == 4
    finally:
        mesh.close()
        tele_core.disable()
        tele_core.reset()


def test_mesh_slo_monitor_fires_on_reject_all_burn(model):
    """The mesh-integrated burn alarm: an injected reject_all drill
    sheds every submit, which burns the availability budget and fires
    the monitor; a healthy stream beforehand stays quiet."""
    from tests.test_serving_mesh import _cfg
    with _cfg(model, SERVING_SLO_AVAILABILITY=0.5,
              SERVING_SLO_FAST_WINDOW_SECS=30.0,
              SERVING_SLO_SLOW_WINDOW_SECS=60.0,
              SERVING_SLO_BURN_THRESHOLD=1.5):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='thread', max_delay_ms=0.0)
    try:
        assert mesh._slo is not None and mesh._slo.enabled
        for _ in range(8):
            mesh.predict([PREDICT_LINES[0]], tier='topk', timeout=120)
        assert mesh.stats()['slo']['alerts_total'] == 0  # quiet
        faults.configure('reject_all@req=0..9999')
        shed = 0
        for _ in range(40):
            try:
                mesh.submit([PREDICT_LINES[0]], tier='topk')
            except EngineOverloaded:
                shed += 1
        assert shed == 40
        stats = mesh.stats()['slo']
        assert stats['alerts_total'] >= 1
        assert stats['alerting']['availability'] is True
        assert stats['availability_burn_fast'] > 1.5
        assert stats['bad_total'] == 40
    finally:
        faults.configure('')
        mesh.close()


# ------------------------------------- e2e: stitched socket kill drill
def _wait_until(predicate, timeout=60.0, what='condition'):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError('timed out waiting for %s' % what)


def test_socket_stitched_kill_drill_both_incarnations(tmp_path_factory):
    """The stitching acceptance drill: a socket-mode worker executes a
    batch on device, its spans ship (heartbeat), then it is SIGKILLed
    BEFORE the result frame.  The redispatched request is served by the
    restarted incarnation — and its delivered trace tree contains BOTH
    incarnations' `serving.remote` envelopes with device-execute spans,
    phase stamps ordered by the clock-offset estimate.  Along the way:
    the fleet merge carries the worker's telemetry + ledger, and zero
    delivered traces finish unstitched."""
    from tests.test_serving_mesh import _cfg, _checkpointed_model
    from code2vec_tpu.telemetry.jit_tracker import \
        install_compile_listener
    model = _checkpointed_model(tmp_path_factory, 'stitch')
    tele_core.reset()
    tele_core.enable()
    mesh = None
    try:
        install_compile_listener()
        telemetry_dir = os.path.join(
            os.path.dirname(model.config.MODEL_SAVE_PATH), 'telemetry')
        with _cfg(model,
                  # trigger counts are 0-based: fires on the SECOND
                  # dispatch this incarnation serves
                  FAULT_INJECT='kill_worker_after_execute@dispatch=1',
                  TRACING_SAMPLE_RATE=1.0,
                  MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=8,
                  MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5):
            mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                      mode='socket', max_delay_ms=0.0)
            # dispatch #1: a clean stitched round trip
            clean = mesh.submit([PREDICT_LINES[0]], tier='topk')
            assert clean.result(timeout=300)
            # dispatch #2 fires the fault: executed, spans beat home,
            # killed before the result frame -> redispatch -> the NEW
            # incarnation serves it
            doomed = mesh.submit([PREDICT_LINES[1]], tier='topk')
            assert doomed.result(timeout=600)
            _wait_until(lambda: mesh.stats()['restarts_total'] >= 1,
                        timeout=300.0, what='supervised restart')
            stats = mesh.stats()
            assert stats['redispatched_total'] >= 1
            assert stats['adopted_spans_total'] > 0
            # worker backhaul surfaced: ledger rollup + clock offset
            _wait_until(
                lambda: mesh.stats()['replicas'][0]['worker_memory']
                is not None, timeout=60.0, what='ledger backhaul')
            row = mesh.stats()['replicas'][0]
            assert 'attributed_bytes' in row['worker_memory']
            assert 'buckets' in row['worker_memory']
            assert row['clock_offset_ms'] is not None
            # fleet merge reached the parent registry replica-labeled
            # (an external-dispatch worker emits dispatch-side series:
            # batches, never submit-side requests)
            _wait_until(
                lambda: tele_core.registry().get(
                    'serving/batches_total{replica=r0}') is not None,
                timeout=60.0, what='fleet telemetry merge')
            assert tele_core.registry().get(
                'serving/batches_total{replica=r0}').snapshot() >= 1
        mesh.close()
        spans_path = os.path.join(telemetry_dir, 'spans.jsonl')
        traces = latency_report.group_traces(
            latency_report.load_spans(spans_path))
        # every delivered trace is stitched
        delivered = {tid: e for tid, e in traces.items()
                     if e['root'] is not None
                     and e['root'].get('status') in (None, 'ok')
                     and (e['root'].get('attrs') or {}).get('mesh')}
        assert delivered
        assert not [tid for tid in
                    latency_report.unstitched_traces(traces)
                    if tid in delivered]
        # the redispatched trace shows BOTH incarnations' device work
        stitched = None
        for entry in delivered.values():
            names = [r['name'] for r in entry['spans']]
            if names.count('serving.remote') >= 2 and \
                    'serving.redispatch' in names:
                stitched = entry
                break
        assert stitched is not None, \
            'no delivered trace carries both incarnations'
        remotes = [r for r in stitched['spans']
                   if r['name'] == 'serving.remote']
        pids = {(r.get('attrs') or {}).get('pid') for r in remotes}
        assert len(pids) == 2, 'expected two worker incarnations'
        remote_ids = {r['span'] for r in remotes}
        devs = [r for r in stitched['spans']
                if r['name'] == 'serving.device_execute']
        assert len(devs) >= 2
        # each remote envelope contains a device-execute child
        dev_parents = {d['parent'] for d in devs}
        assert remote_ids <= dev_parents
        # stitched stamps are ordered: every remote span sits inside
        # the root's window (clock offset applied), and phase sums
        # stay within the end-to-end envelope
        root = stitched['root']
        for rec in stitched['spans']:
            assert rec['t0'] >= root['t0'] - 0.05
            assert rec['t1'] <= root['t1'] + 0.05
        phase_ms = sum(r['dur_ms'] for r in stitched['spans']
                       if r['name'] in ('serving.admission',
                                        'serving.tokenize',
                                        'serving.queue_wait'))
        assert phase_ms <= root['dur_ms'] * 1.05 + 5.0
        # the fleet report decomposes queue / wire / device for r0
        fleet = latency_report.fleet_decomposition(traces)
        r0_rows = [key for key in fleet if key[0] == 'r0']
        assert r0_rows
        parts = fleet[r0_rows[0]]
        assert parts['device'] and parts['device'][-1] > 0
    finally:
        if mesh is not None:
            mesh.close()
        model.close_stores()
        tele_core.disable()
        tele_core.reset()
