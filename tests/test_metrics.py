import numpy as np

from code2vec_tpu.metrics import (SubtokensEvaluationMetric,
                                  TopKAccuracyEvaluationMetric,
                                  decode_topk_batch)

OOV = '<PAD_OR_OOV>'


def test_topk_accuracy_rank_semantics():
    # Hit at rank r counts toward all k >= r (reference
    # tensorflow_model.py:506-512); rank counts only legal predictions.
    metric = TopKAccuracyEvaluationMetric(top_k=3, oov_word=OOV)
    metric.update_batch([
        ('getName', ['get|name', 'x', 'y']),          # hit at rank 0
        ('setValue', [OOV, 'badword', 'set|value']),  # legal-filtered rank 1
        ('foo', ['bar', 'baz', 'qux']),               # miss
    ])
    np.testing.assert_allclose(metric.topk_correct_predictions,
                               [1 / 3, 2 / 3, 2 / 3])


def test_topk_match_uses_normalization():
    metric = TopKAccuracyEvaluationMetric(top_k=1, oov_word=OOV)
    # normalize_word('get|name') == 'getname' == normalize_word('getName')
    metric.update_batch([('getName', ['get|name'])])
    assert metric.topk_correct_predictions[0] == 1.0


def test_subtoken_metric_counter_semantics():
    # Exact Counter overlap semantics (reference tensorflow_model.py:458-469):
    # prediction 'get|name|name' vs original 'get|value':
    #   predicted Counter: get:1, name:2 ; original Counter: get:1, value:1
    #   TP = 1 (get), FP = 2 (name x2), FN = 1 (value)
    metric = SubtokensEvaluationMetric(oov_word=OOV)
    metric.update_batch([('get|value', ['get|name|name'])])
    assert metric.nr_true_positives == 1
    assert metric.nr_false_positives == 2
    assert metric.nr_false_negatives == 1
    assert metric.precision == 1 / 3
    assert metric.recall == 1 / 2
    np.testing.assert_allclose(metric.f1, 2 * (1 / 3) * (1 / 2) / (1 / 3 + 1 / 2))


def test_subtoken_metric_takes_first_legal_prediction():
    metric = SubtokensEvaluationMetric(oov_word=OOV)
    metric.update_batch([('get|value', [OOV, 'bad2', 'get|value', 'other'])])
    assert metric.precision == 1.0
    assert metric.recall == 1.0


def test_subtoken_metric_no_legal_predictions_counts_all_misses():
    # Deviation from reference (which crashes, :460): empty prediction.
    metric = SubtokensEvaluationMetric(oov_word=OOV)
    metric.update_batch([('get|value', [OOV, 'x9'])])
    assert metric.nr_true_positives == 0
    assert metric.nr_false_negatives == 2
    assert metric.nr_false_positives == 1  # the empty-string token


def test_decode_topk_batch_skips_padding_rows():
    index_to_word = np.array(['<PAD_OR_OOV>', 'alpha', 'beta'], dtype=object)
    topk = np.array([[1, 2], [2, 0]], dtype=np.int32)
    results = decode_topk_batch(topk, index_to_word,
                                ['origA', ''], np.array([1.0, 0.0]))
    assert results == [('origA', ['alpha', 'beta'])]
