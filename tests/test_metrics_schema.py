"""tier-1 guard: metric names cannot drift from the catalog/doc
(scripts/check_metrics_schema.py; ISSUE 2 satellite)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, 'scripts'))

import check_metrics_schema  # noqa: E402


def test_emission_regex_matches_wrapped_calls():
    content = ("reg.counter(\n    'input/cache_miss_total').inc()\n"
               "writer.scalar('train/loss', x, step)\n"
               "registry.get('step/h2d_ms')\n"
               "meta.get(k)  # no literal: ignored\n"
               "os.environ.get('TELEMETRY_TRACE_AT_STEP')  # no slash\n")
    names = [m.group(1)
             for m in check_metrics_schema.EMIT_RE.finditer(content)]
    assert names == ['input/cache_miss_total', 'train/loss', 'step/h2d_ms']


def test_every_emitted_metric_is_cataloged_and_documented():
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts',
                                      'check_metrics_schema.py')],
        capture_output=True, text=True,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert result.returncode == 0, result.stdout + result.stderr


def test_unknown_metric_is_flagged():
    from code2vec_tpu.telemetry.catalog import CATALOG, base_name
    emissions = check_metrics_schema.find_emissions()
    assert emissions, 'lint found no emission sites — regex broke'
    # instance-labeled literals ('goodput/badput_s{kind=%s}') validate
    # against their label-free catalog family, same resolution as the
    # metrics-schema rule and the Prometheus exporter
    assert all(base_name(name) in CATALOG
               for _rel, _line, name in emissions)
    # and the failure path actually fires on a bogus name
    assert 'definitely/not_a_metric' not in CATALOG
