"""Lazy (sparse-row) Adam: math vs a numpy reference, TF1 lazy-moment
semantics through the Trainer, backend agnosticism, and mesh parity."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import Batch
from code2vec_tpu.models.backends import create_backend
from code2vec_tpu.ops.lazy_adam import sparse_row_adam
from code2vec_tpu.parallel import mesh as mesh_lib
from code2vec_tpu.training.trainer import Trainer
from code2vec_tpu.vocab import SizeOnlyVocabs


def numpy_lazy_adam(table, mu, nu, dense_grad, rows, lr, step,
                    b1=0.9, b2=0.999, eps=1e-8):
    """Straight-line reference: one update per UNIQUE touched row."""
    table, mu, nu = table.copy(), mu.copy(), nu.copy()
    lr_t = lr * np.sqrt(1 - b2 ** step) / (1 - b1 ** step)
    for r in np.unique(rows):
        g = dense_grad[r]
        mu[r] = b1 * mu[r] + (1 - b1) * g
        nu[r] = b2 * nu[r] + (1 - b2) * g * g
        table[r] = table[r] - lr_t * mu[r] / (np.sqrt(nu[r]) + eps)
    return table, mu, nu


def test_sparse_row_adam_matches_numpy_with_duplicates():
    rng = np.random.default_rng(0)
    v, d = 12, 5
    table = rng.normal(size=(v, d)).astype(np.float32)
    mu = rng.normal(size=(v, d)).astype(np.float32) * 0.1
    nu = np.abs(rng.normal(size=(v, d))).astype(np.float32) * 0.01
    grad = rng.normal(size=(v, d)).astype(np.float32)
    rows = np.array([3, 7, 3, 0, 7, 7, 11], np.int32)  # heavy duplication
    grad[[r for r in range(v) if r not in rows]] = 0.0

    got_t, got_m, got_v = sparse_row_adam(
        jnp.asarray(table), jnp.asarray(mu), jnp.asarray(nu),
        jnp.asarray(grad), jnp.asarray(rows),
        learning_rate=0.01, step=jnp.asarray(3))
    want_t, want_m, want_v = numpy_lazy_adam(table, mu, nu, grad, rows,
                                             lr=0.01, step=3)
    np.testing.assert_allclose(np.asarray(got_t), want_t, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_m), want_m, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(got_v), want_v, rtol=1e-6)
    # untouched rows bit-identical
    untouched = [r for r in range(v) if r not in rows]
    np.testing.assert_array_equal(np.asarray(got_t)[untouched],
                                  table[untouched])


VOCAB_TOK, VOCAB_PATH, VOCAB_TGT = 48, 24, 16


def make_trainer(framework='jax', **overrides):
    overrides.setdefault('LAZY_EMBEDDING_ADAM', True)
    config = Config(
        TRAIN_DATA_PATH_PREFIX='unused', DL_FRAMEWORK=framework,
        VERBOSE_MODE=0, READER_USE_NATIVE=False, MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=8, TEST_BATCH_SIZE=8, COMPUTE_DTYPE='float32',
        MAX_TOKEN_VOCAB_SIZE=VOCAB_TOK, MAX_PATH_VOCAB_SIZE=VOCAB_PATH,
        MAX_TARGET_VOCAB_SIZE=VOCAB_TGT, TOKEN_EMBEDDINGS_SIZE=8,
        PATH_EMBEDDINGS_SIZE=8, CODE_VECTOR_SIZE=24,
        TARGET_EMBEDDINGS_SIZE=24, PARAM_ROW_ALIGNMENT=8,
        LEARNING_RATE=0.01, **overrides)
    backend = create_backend(
        config, SizeOnlyVocabs(VOCAB_TOK, VOCAB_PATH, VOCAB_TGT))
    return Trainer(config, backend)


def batch_touching(tok_lo, tok_hi, seed=0):
    """All token/target indices drawn from [tok_lo, tok_hi)."""
    rng = np.random.default_rng(seed)
    b, c = 8, 6
    return Batch(
        source=rng.integers(tok_lo, tok_hi, (b, c)).astype(np.int32),
        path=rng.integers(1, VOCAB_PATH, (b, c)).astype(np.int32),
        target=rng.integers(tok_lo, tok_hi, (b, c)).astype(np.int32),
        mask=np.ones((b, c), np.float32),
        label=rng.integers(1, VOCAB_TGT, (b,)).astype(np.int32),
        weight=np.ones((b,), np.float32))


def canonical(trainer, params):
    named = trainer.backend.named_params(params)
    return {k: np.asarray(v) for k, v in named._asdict().items()}


def test_lazy_moments_skip_untouched_rows():
    """LazyAdam semantics: a row touched in step 1 but absent from step 2
    must not move in step 2 (dense Adam — the reference-parity default —
    would decay its momentum and apply the drift)."""
    trainer = make_trainer()
    state = trainer.init_state(seed=0)
    low = batch_touching(1, 8, seed=0)    # rows 1..7
    high = batch_touching(30, 40, seed=1)  # rows 30..39

    state, _ = trainer.train_step(state, low)
    after_step1 = canonical(trainer, state.params)
    state, _ = trainer.train_step(state, high)
    after_step2 = canonical(trainer, state.params)

    # rows 1..7 moved in step 1...
    assert not np.allclose(after_step1['token_embedding'][1:8],
                           canonical(trainer,
                                     trainer.init_state(seed=0).params)
                           ['token_embedding'][1:8])
    # ...and stayed EXACTLY put in step 2 (lazy moments)
    np.testing.assert_array_equal(after_step2['token_embedding'][1:8],
                                  after_step1['token_embedding'][1:8])
    # while step 2's own rows moved
    assert not np.allclose(after_step2['token_embedding'][30:40],
                           after_step1['token_embedding'][30:40])
    # dense params (transform) moved both steps
    assert not np.allclose(after_step2['transform'], after_step1['transform'])


def test_lazy_loss_decreases():
    trainer = make_trainer()
    state = trainer.init_state(seed=0)
    batch = batch_touching(1, VOCAB_TOK)
    first = last = None
    for _ in range(30):
        state, loss = trainer.train_step(state, batch)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first * 0.7, (first, last)


def test_lazy_backend_parity_jax_vs_flax():
    """Same canonical params + same batch -> identical params after one
    lazy step under either backend."""
    t_jax = make_trainer('jax')
    t_flax = make_trainer('flax')
    s_jax = t_jax.init_state(seed=0)
    start = canonical(t_jax, s_jax.params)
    s_flax = t_flax.state_from_params(
        t_flax.backend.from_canonical(dict(start)), step=0, seed=0)
    # align the dropout key; COPY the leaves (train_step donates its
    # state, so sharing buffers across the two states would leave the
    # second step reading deleted arrays)
    s_flax = s_flax._replace(rng=jnp.array(np.asarray(s_jax.rng)),
                             step=jnp.array(np.asarray(s_jax.step)))

    batch = batch_touching(1, VOCAB_TOK)
    s_jax, loss_jax = t_jax.train_step(s_jax, batch)
    s_flax, loss_flax = t_flax.train_step(s_flax, batch)
    assert float(loss_jax) == pytest.approx(float(loss_flax), rel=1e-6)
    a = canonical(t_jax, s_jax.params)
    b = canonical(t_flax, s_flax.params)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)


@pytest.mark.xfail(
    jax.__version__.startswith('0.4.'),
    reason='environment-limited: GSPMD scatter semantics gap breaks the '
           'opt-in lazy-Adam sparse-row update on multi-device meshes '
           'under jax 0.4.x (known-xfail, CHANGES.md PR 1); the dense '
           'default path is unaffected (test_lazy_vs_dense_*)',
    strict=False)
def test_lazy_mesh_parity():
    """A 4x2 mesh lazy step equals the single-device result."""
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip('needs 8 virtual devices')
    t_single = make_trainer()
    mesh = mesh_lib.create_mesh(
        Config(TRAIN_DATA_PATH_PREFIX='unused', MESH_DATA_AXIS_SIZE=4,
               MESH_MODEL_AXIS_SIZE=2, VERBOSE_MODE=0),
        devices=devices[:8])
    t_mesh = make_trainer(MESH_DATA_AXIS_SIZE=4, MESH_MODEL_AXIS_SIZE=2)
    assert t_mesh.mesh.shape == mesh.shape

    s_single = t_single.init_state(seed=0)
    start = canonical(t_single, s_single.params)
    s_mesh = t_mesh.state_from_params(
        t_mesh.backend.from_canonical(dict(start)), step=0, seed=0)
    s_mesh = s_mesh._replace(rng=jnp.array(np.asarray(s_single.rng)),
                             step=jnp.array(np.asarray(s_single.step)))

    batch = batch_touching(1, VOCAB_TOK)
    s_single, loss_a = t_single.train_step(s_single, batch)
    s_mesh, loss_b = t_mesh.train_step(s_mesh, batch)
    assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-5)
    a = canonical(t_single, s_single.params)
    b = canonical(t_mesh, s_mesh.params)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-5, atol=1e-6,
                                   err_msg=k)


def test_lazy_checkpoint_resume(tmp_path):
    """Full save/resume round-trip with the lazy optimizer state (orbax
    must restore the LazyAdamState pytree, moments included)."""
    from code2vec_tpu.model_api import Code2VecModel
    from tests.test_train_overfit import make_dataset
    prefix = make_dataset(tmp_path)
    common = dict(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, SAVE_EVERY_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False, LAZY_EMBEDDING_ADAM=True,
        MODEL_SAVE_PATH=str(tmp_path / 'models' / 'saved_model'))
    model = Code2VecModel(Config(NUM_TRAIN_EPOCHS=1, **common))
    model.train()

    resumed = Code2VecModel(Config(
        NUM_TRAIN_EPOCHS=2, **dict(
            common,
            MODEL_LOAD_PATH=str(tmp_path / 'models' / 'saved_model'))))
    assert resumed._start_epoch == 1
    # restored moments are a LazyAdamState with the right leaves
    from code2vec_tpu.ops.lazy_adam import LazyAdamState
    opt = resumed.state.opt_state
    assert isinstance(opt, LazyAdamState) or hasattr(opt, 'mu')
    resumed.train()  # second epoch runs without error


def test_bf16_mu_adam_trains():
    """ADAM_MU_DTYPE='bfloat16' (dense Adam only) stores the first moment
    in bf16 and still reduces the loss; the second moment is PINNED fp32
    here (ADAM_NU_DTYPE has its own default and tests —
    test_adam_dtypes.py), and checkpoint restore targets carry the same
    dtypes."""
    import jax
    import jax.numpy as jnp

    trainer = make_trainer(LAZY_EMBEDDING_ADAM=False,
                           ADAM_MU_DTYPE='bfloat16',
                           ADAM_NU_DTYPE='float32')
    state = trainer.init_state(seed=0)
    mu_dtypes = {leaf.dtype for leaf in jax.tree_util.tree_leaves(
        state.opt_state[0].mu)}
    nu_dtypes = {leaf.dtype for leaf in jax.tree_util.tree_leaves(
        state.opt_state[0].nu)}
    assert mu_dtypes == {np.dtype(jnp.bfloat16)}
    assert nu_dtypes == {np.dtype(jnp.float32)}

    batch = batch_touching(1, VOCAB_TOK, seed=2)
    state, loss0 = trainer.train_step(state, batch)  # donates old state
    loss = loss0
    for _ in range(20):
        state, loss = trainer.train_step(state, batch)
    assert float(loss) < float(loss0)

    # resume consistency: abstract_state derives from the configured
    # optimizer, so the restore target must be bf16-mu too
    _, abstract_opt = trainer.abstract_state()
    abs_mu = {leaf.dtype for leaf in jax.tree_util.tree_leaves(
        abstract_opt[0].mu)}
    assert abs_mu == {np.dtype(jnp.bfloat16)}


def test_bf16_mu_ignored_with_lazy_adam():
    """ADAM_MU_DTYPE='bfloat16' is the config DEFAULT; lazy Adam keeps
    fp32 moments, does not consume the knob, and must warn (not raise —
    raising would break lazy users who never touched the default)."""
    import logging

    import jax
    import jax.numpy as jnp

    # attach a handler directly: earlier tests may have configured the
    # package logger in ways that stop propagation to pytest's caplog
    records = []

    class _Collect(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    logger = logging.getLogger('code2vec_tpu.training.trainer')
    handler = _Collect(level=logging.WARNING)
    logger.addHandler(handler)
    try:
        trainer = make_trainer(ADAM_MU_DTYPE='bfloat16')
    finally:
        logger.removeHandler(handler)
    assert any('ignored' in msg for msg in records)
    state = trainer.init_state(seed=0)
    float_dtypes = {leaf.dtype
                    for leaf in jax.tree_util.tree_leaves(state.opt_state)
                    if hasattr(leaf, 'dtype')
                    and jnp.issubdtype(leaf.dtype, jnp.floating)}
    # every floating moment the lazy path stores stays fp32
    assert float_dtypes == {np.dtype(jnp.float32)}
