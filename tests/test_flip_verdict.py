"""scripts/flip_verdict.py: the >=2% flip decisions settle mechanically
from capture rounds — pending while every round is wedged, flip/keep the
moment a healthy on-chip record lands, smoke lines never decide, and the
--write record is durable JSON with provenance."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, 'scripts', 'flip_verdict.py')


def run_cli(results_dir, root, *extra):
    proc = subprocess.run(
        [sys.executable, CLI, '--dir', str(results_dir), '--root',
         str(root), '--json', *extra],
        capture_output=True, text=True, timeout=60)
    rows = [json.loads(line) for line in proc.stdout.splitlines()
            if line.strip().startswith('{')]
    return proc, {r['measure']: r for r in rows}


def write_jsonl(path, records):
    with open(path, 'w') as f:
        for rec in records:
            f.write(json.dumps(rec) + '\n')


def test_all_wedged_rounds_stay_pending(tmp_path):
    results = tmp_path / 'results'
    results.mkdir()
    write_jsonl(results / 'capture_a.jsonl', [
        {'stage': 'probe',
         'tpu_unavailable': 'probe failed 3/3 attempts', 'attempts': 3},
    ])
    # a smoke line must NOT settle an on-chip verdict
    write_jsonl(results / 'capture_b.jsonl', [
        {'measure': 'ragged_train_kernel_speedup_SMOKE_ONLY',
         'value': 2.1},
        {'stage': 'probe', 'tpu_unavailable': 'wedged again'},
    ])
    proc, rows = run_cli(results, tmp_path)
    assert proc.returncode == 3  # all pending, scriptable
    assert rows['ragged_train_kernel_speedup']['verdict'] == 'pending'
    assert rows['ragged_fusion_train_speedup']['verdict'] == 'pending'
    assert rows['ragged_fusion_predict_speedup']['verdict'] == 'pending'
    assert rows['ragged_train_kernel_speedup'][
        'wedged_capture_rounds'] == 2


def test_healthy_round_settles_flip_and_keep(tmp_path):
    results = tmp_path / 'results'
    results.mkdir()
    # an older wedged round, then a healthy one — newest wins
    write_jsonl(results / 'capture_a.jsonl', [
        {'stage': 'probe', 'tpu_unavailable': 'wedged'}])
    write_jsonl(results / 'capture_b.jsonl', [
        {'stage': 'pallas_ragged', 'rc': 0, 'secs': 100, 'data': {
            'measure': 'ragged_train_kernel_speedup', 'value': 1.07,
            'fill': 0.25, 'contexts': 200}},
        {'stage': 'pallas_ragged_c1024', 'rc': 0, 'secs': 90, 'data': {
            'measure': 'ragged_train_kernel_speedup_c1024',
            'value': 1.31, 'fill': 0.1, 'contexts': 1024}},
        # raw (un-wrapped) measure lines are the other capture shape
        {'measure': 'ragged_fusion_predict_speedup', 'value': 1.01},
    ])
    proc, rows = run_cli(results, tmp_path, '--write')
    assert proc.returncode == 0
    kernel = rows['ragged_train_kernel_speedup']
    assert kernel['verdict'] == 'flip'
    assert kernel['value'] == 1.07
    assert kernel['knob'] == 'RAGGED_TRAIN_KERNEL'
    assert kernel['source'] == 'capture_b.jsonl'
    # the capacity-suffixed arm corroborates, it does not decide
    assert kernel['corroborating'] == {
        'ragged_train_kernel_speedup_c1024': 1.31}
    assert rows['ragged_fusion_predict_speedup']['verdict'] == 'keep'
    # no record of the fusion-train confirmation yet: stays pending
    assert rows['ragged_fusion_train_speedup']['verdict'] == 'pending'
    # the durable record (rows in TRACKED order)
    with open(results / 'flip_verdicts.json') as f:
        history = json.load(f)
    assert [h['verdict'] for h in history] == ['flip', 'pending', 'keep']
    assert all('checked_at' in h for h in history)
    # a second --write APPENDS (history, not overwrite)
    proc2, _ = run_cli(results, tmp_path, '--write')
    with open(results / 'flip_verdicts.json') as f:
        assert len(json.load(f)) == 6


def test_driver_snapshots_counted_as_wedged_queue(tmp_path):
    results = tmp_path / 'results'
    results.mkdir()
    (tmp_path / 'BENCH_r09.json').write_text(json.dumps({
        'n': 9, 'rc': 0, 'parsed': {
            'metric': 'train_examples_per_sec_per_chip_java14m',
            'value': 0.0, 'error': 'tpu_unavailable'}}))
    # a second mode: rc!=0 with only the probe-timeout message in the
    # raw tail (BENCH_r03-style) must count as wedged too
    (tmp_path / 'BENCH_r03.json').write_text(json.dumps({
        'n': 3, 'rc': 124, 'parsed': None,
        'tail': 'probe child timed out after 90s (wedged backend?)'}))
    proc, rows = run_cli(results, tmp_path)
    assert proc.returncode == 3
    assert rows['ragged_train_kernel_speedup'][
        'wedged_driver_snapshots'] == '2/2'


def test_unknown_measure_rejected(tmp_path):
    results = tmp_path / 'results'
    results.mkdir()
    proc = subprocess.run(
        [sys.executable, CLI, '--dir', str(results), '--root',
         str(tmp_path), '--measure', 'not_a_tracked_measure'],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert 'unknown measure' in proc.stderr
