"""Ragged fused encode + attention (ops/pallas_ragged.py) vs the
unpack-then-dense path, under the tests/test_packed.py property regime:
interior holes, pad rows, capacity < batch, fill rates from empty to
full, nonzero PAD indices, per-shard packing. The jnp twin is exercised
everywhere (it is the train path and the non-TPU fallback); the Pallas
kernel runs in interpreter mode on CPU, single-shard, flat multi-shard,
and shard_mapped over the 8-virtual-device mesh. Trainer integration
covers packed train/eval and all four predict tiers, plus the
zero-post-warmup-compiles guard on the fused programs."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.models import functional
from code2vec_tpu.ops import pallas_ragged

from tests.test_packed import random_plane_batch
from tests.test_stage_batches import make_trainer

pytestmark = pytest.mark.skipif(not pallas_ragged.PALLAS_AVAILABLE,
                                reason='pallas unavailable')


def small_params(rng_seed=0, token_vocab=32, path_vocab=16,
                 target_vocab=16, token_dim=8, path_dim=6, code_dim=24):
    return functional.init_params(
        jax.random.PRNGKey(rng_seed), token_vocab_size=token_vocab,
        path_vocab_size=path_vocab, target_vocab_size=target_vocab,
        token_dim=token_dim, path_dim=path_dim, code_dim=code_dim)


def dense_reference(params, batch):
    """The unpack-then-dense ground truth: the packed round trip is
    BIT-exact (tests/test_packed.py), so encoding the original planes IS
    encoding the unpacked wire."""
    return functional.encode(params, batch.source, batch.path,
                             batch.target, batch.mask)


def ragged(params, packed, max_contexts, token_pad, path_pad, **kw):
    return pallas_ragged.ragged_encode(
        params.token_embedding, params.path_embedding, params.transform,
        params.attention, jnp.asarray(packed.ctx),
        jnp.asarray(packed.count), max_contexts=max_contexts,
        token_pad=token_pad, path_pad=path_pad, **kw)


def assert_encode_close(got, want, rtol=2e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=rtol, atol=atol, err_msg='code')
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                               rtol=rtol, atol=atol, err_msg='attention')


class TestTwinVsDense:
    """The jnp twin (train path / non-TPU fallback) against the dense
    encode, over the full structural property space."""

    @pytest.mark.parametrize('token_pad,path_pad', [(0, 0), (1, 2)])
    @pytest.mark.parametrize('data_shards', [1, 2, 4])
    def test_property_regime(self, token_pad, path_pad, data_shards):
        rng = np.random.default_rng(7)
        params = small_params()
        for _trial in range(8):
            contexts = int(rng.choice([3, 5, 8, 13]))
            batch = random_plane_batch(rng, 8, contexts, token_pad,
                                       path_pad)
            packed = packed_lib.pack_batch(batch, token_pad, path_pad,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            got = ragged(params, packed, contexts, token_pad, path_pad,
                         use_kernel=False)
            assert_encode_close(got, dense_reference(params, batch))

    def test_capacity_rungs_agree(self):
        """The same batch packed at every serving-ladder capacity rung
        must produce identical outputs — capacity padding is inert."""
        rng = np.random.default_rng(3)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        want = dense_reference(params, batch)
        for rung in (4, 16, 64, 256):
            packed = packed_lib.pack_batch(batch, 0, 0,
                                           capacity_minimum=rung)
            assert packed.ctx.shape[1] >= rung
            got = ragged(params, packed, 6, 0, 0, use_kernel=False)
            assert_encode_close(got, want)

    def test_all_padding_batch_matches_dense_uniform(self):
        """count == 0 rows: the dense path produces a FINITE uniform
        attention (1/C) and code = x_pad; the fused fixup must match."""
        contexts = 5
        from code2vec_tpu.data.reader import Batch
        zero = Batch(source=np.zeros((4, contexts), np.int32),
                     path=np.zeros((4, contexts), np.int32),
                     target=np.zeros((4, contexts), np.int32),
                     mask=np.zeros((4, contexts), np.float32),
                     label=np.zeros((4,), np.int32),
                     weight=np.zeros((4,), np.float32))
        params = small_params()
        packed = packed_lib.pack_batch(zero, 0, 0, capacity_minimum=4)
        got = ragged(params, packed, contexts, 0, 0, use_kernel=False)
        assert_encode_close(got, dense_reference(params, zero))
        np.testing.assert_allclose(np.asarray(got[1]),
                                   np.full((4, contexts), 1.0 / contexts))

    def test_capacity_smaller_than_batch(self):
        """More examples than context rows (the sparse-eval regression
        shape from tests/test_packed.py)."""
        from code2vec_tpu.data.reader import Batch, context_valid_mask
        contexts, batch_size = 6, 64
        rng = np.random.default_rng(2)
        batch = random_plane_batch(rng, batch_size, contexts)
        lengths = np.zeros((batch_size,), np.int64)
        lengths[:4] = [1, 2, 0, 3]
        dead = np.arange(contexts)[None, :] >= lengths[:, None]
        source = batch.source.copy(); source[dead] = 0
        path = batch.path.copy(); path[dead] = 0
        target = batch.target.copy(); target[dead] = 0
        mask = context_valid_mask(source, path, target, 0, 0)
        batch = batch._replace(source=source, path=path, target=target,
                               mask=mask)
        params = small_params()
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] < batch_size
        got = ragged(params, packed, contexts, 0, 0, use_kernel=False)
        assert_encode_close(got, dense_reference(params, batch))

    def test_gradients_match_dense(self):
        """loss_and_aux_packed's backward (the fused TRAIN path) against
        the unpack-then-dense loss, all five parameter gradients."""
        rng = np.random.default_rng(1)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        batch = batch._replace(
            label=np.clip(batch.label, 0, 15).astype(np.int32))
        packed = packed_lib.pack_batch(batch, 0, 0, data_shards=2,
                                       capacity_minimum=4)

        def dense_loss(p):
            return functional.loss_and_aux(
                p, batch.source, batch.path, batch.target, batch.mask,
                batch.label, batch.weight, num_valid_targets=16)[0]

        def ragged_loss(p):
            return functional.loss_and_aux_packed(
                p, jnp.asarray(packed.ctx), jnp.asarray(packed.count),
                jnp.asarray(packed.label), jnp.asarray(packed.weight),
                max_contexts=6, token_pad=0, path_pad=0,
                num_valid_targets=16)[0]

        loss_d, grads_d = jax.value_and_grad(dense_loss)(params)
        loss_r, grads_r = jax.value_and_grad(ragged_loss)(params)
        np.testing.assert_allclose(float(loss_r), float(loss_d),
                                   rtol=1e-5)
        for name, got, want in zip(params._fields,
                                   jax.tree_util.tree_leaves(grads_r),
                                   jax.tree_util.tree_leaves(grads_d)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=1e-6,
                                       err_msg=name)

    def test_dropout_runs_and_is_finite(self):
        """Dropout draws over the PACKED layout (a different seed-keyed
        stream than the dense path — the DROPOUT_PRNG_IMPL precedent),
        so the contract is a finite loss + finite grads, not bit
        parity."""
        params = small_params()
        batch = random_plane_batch(np.random.default_rng(5), 8, 6)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)

        def loss(p):
            return functional.loss_and_aux_packed(
                p, jnp.asarray(packed.ctx), jnp.asarray(packed.count),
                jnp.asarray(np.clip(packed.label, 0, 15)),
                jnp.asarray(packed.weight),
                max_contexts=6, token_pad=0, path_pad=0,
                num_valid_targets=16,
                dropout_rng=jax.random.PRNGKey(7),
                dropout_keep_rate=0.75)[0]

        value, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(value))
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree_util.tree_leaves(grads))

    def test_kernel_refuses_dropout(self):
        params = small_params()
        packed = packed_lib.pack_batch(
            random_plane_batch(np.random.default_rng(0), 8, 4), 0, 0,
            capacity_minimum=4)
        with pytest.raises(ValueError, match='deterministic forward'):
            ragged(params, packed, 4, 0, 0, use_kernel=True,
                   interpret=True, dropout_rng=jax.random.PRNGKey(0),
                   dropout_keep_rate=0.5)


class TestKernelInterpret:
    """The Pallas kernel in interpreter mode — no TPU needed for the
    FuseMax single-pass logic."""

    @pytest.mark.parametrize('data_shards', [1, 2])
    def test_kernel_matches_dense(self, data_shards):
        rng = np.random.default_rng(11)
        params = small_params()
        for _trial in range(6):
            contexts = int(rng.choice([3, 5, 8]))
            batch = random_plane_batch(rng, 8, contexts, 1, 2)
            packed = packed_lib.pack_batch(batch, 1, 2,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            got = ragged(params, packed, contexts, 1, 2,
                         use_kernel=True, interpret=True)
            assert_encode_close(got, dense_reference(params, batch))

    def test_multi_tile_online_rescale(self, monkeypatch):
        """Force several grid steps (tiny slot tile) so segments SPAN
        tiles and the running (m, z, acc) rescale actually runs, with
        the per-example stream crossing every tile boundary."""
        monkeypatch.setattr(pallas_ragged, 'SLOT_TILE', 8)
        rng = np.random.default_rng(13)
        params = small_params()
        batch = random_plane_batch(rng, 8, 13, hole_rate=0.4)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] > 8  # really multi-tile
        got = ragged(params, packed, 13, 0, 0, use_kernel=True,
                     interpret=True)
        assert_encode_close(got, dense_reference(params, batch))

    def test_kernel_shard_mapped_on_mesh(self):
        """The multi-device route: pallas_call is opaque to GSPMD, so
        the kernel must be shard_mapped over the data axis — parity on
        the 8-virtual-device mesh."""
        from code2vec_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.create_mesh()
        shards = mesh.shape['data']
        rng = np.random.default_rng(17)
        params = small_params()
        batch = random_plane_batch(rng, 2 * shards, 5, 1, 2)
        packed = packed_lib.pack_batch(batch, 1, 2, data_shards=shards,
                                       capacity_minimum=4)
        got = ragged(params, packed, 5, 1, 2, use_kernel=True,
                     interpret=True, mesh=mesh)
        assert_encode_close(got, dense_reference(params, batch))

    def test_bf16_compute_smoke(self):
        """bf16 is the production compute dtype: the kernel and twin
        must agree with the dense bf16 path to bf16 resolution."""
        rng = np.random.default_rng(19)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        want = functional.encode(params, batch.source, batch.path,
                                 batch.target, batch.mask,
                                 dtype=jnp.bfloat16)
        for kw in ({'use_kernel': False},
                   {'use_kernel': True, 'interpret': True}):
            got = ragged(params, packed, 6, 0, 0, dtype=jnp.bfloat16,
                         **kw)
            assert_encode_close(got, want, rtol=0.03, atol=0.02)


@pytest.fixture(scope='module')
def trainer_pair():
    """One (plain, fused) trainer pair shared by the integration tests:
    Trainer construction compiles the full step-program family on the
    8-device mesh, so rebuilding per test would dominate the file's
    tier-1 budget. Dropout off: the two layouts draw different masks."""
    plain = make_trainer(DROPOUT_KEEP_RATE=1.0)
    fused = make_trainer(DROPOUT_KEEP_RATE=1.0,
                         USE_PALLAS_RAGGED_FUSION=True)
    return plain, fused


class TestTrainerIntegration:
    """USE_PALLAS_RAGGED_FUSION threaded through the packed train/eval/
    predict steps: fused vs unpack-then-dense on the 8-virtual-device
    mesh (CPU, so the twin runs — the same code the TPU train path
    uses)."""

    def _packed(self, trainer, n=3):
        rng = np.random.default_rng(5)
        shards = trainer.mesh.shape['data']
        out = []
        for _ in range(n):
            batch = random_plane_batch(rng, 8, 4, pad_row_rate=0.1)
            batch = batch._replace(
                label=np.clip(batch.label, 0, 15).astype(np.int32))
            out.append(packed_lib.pack_batch(batch, 0, 0,
                                             data_shards=shards,
                                             capacity_minimum=4))
        return out

    def test_train_steps_match(self, trainer_pair):
        plain, fused = trainer_pair
        packed = self._packed(plain)
        state_a = plain.init_state(seed=0)
        state_b = fused.init_state(seed=0)
        for pb in packed:
            state_a, loss_a = plain.train_step(state_a, pb)
            state_b, loss_b = fused.train_step(state_b, pb)
            np.testing.assert_allclose(float(loss_b), float(loss_a),
                                       rtol=1e-5)
        for leaf_a, leaf_b in zip(
                jax.tree_util.tree_leaves(state_a.params),
                jax.tree_util.tree_leaves(state_b.params)):
            np.testing.assert_allclose(np.asarray(leaf_b),
                                       np.asarray(leaf_a),
                                       rtol=2e-4, atol=1e-6)

    def test_eval_and_all_predict_tiers_match(self, trainer_pair):
        plain, fused = trainer_pair
        packed = self._packed(plain, n=1)
        params = plain.init_state(seed=1).params
        out_a = plain.eval_step(params, packed[0])
        out_b = fused.eval_step(params, packed[0])
        np.testing.assert_array_equal(np.asarray(out_a['topk_indices']),
                                      np.asarray(out_b['topk_indices']))
        np.testing.assert_allclose(float(out_b['loss_sum']),
                                   float(out_a['loss_sum']), rtol=1e-5)
        assert float(out_a['weight_sum']) == float(out_b['weight_sum'])
        from code2vec_tpu.training.trainer import PREDICT_TIERS
        for tier in PREDICT_TIERS:
            pa = plain.predict_step(params, packed[0], tier=tier)
            pb = fused.predict_step(params, packed[0], tier=tier)
            assert set(pa) == set(pb), tier
            for key in pa:
                np.testing.assert_allclose(
                    np.asarray(pb[key]).astype(np.float64),
                    np.asarray(pa[key]).astype(np.float64),
                    rtol=1e-5, atol=1e-6, err_msg='%s/%s' % (tier, key))

    def test_zero_postwarm_compiles(self, trainer_pair):
        """The fused packed programs must be as shape-stable as the
        unpack path: repeated dispatches on warm (bucket, capacity,
        tier) shapes add NOTHING to the compile counter — the serving
        ladder's steady-state contract. (Predict is deterministic, so
        the shared dropout-off trainer is exactly the serving shape.)"""
        from code2vec_tpu.parallel import mesh as mesh_lib
        from code2vec_tpu.telemetry import core
        from code2vec_tpu.telemetry.jit_tracker import \
            install_compile_listener
        from code2vec_tpu.training.trainer import PREDICT_TIERS
        fused = trainer_pair[1]
        packed = self._packed(fused, n=2)
        params = fused.init_state(seed=0).params
        placed = [mesh_lib.shard_batch(pb.device_arrays(), fused.mesh,
                                       False) for pb in packed]
        assert placed[0][0].shape == placed[1][0].shape  # same capacity
        core.reset()
        core.enable()
        try:
            assert install_compile_listener()
            compiles = core.registry().counter('jit/compiles_total')
            for tier in PREDICT_TIERS:  # warm every fused program
                fused.predict_step_placed(params, placed[0], tier=tier)
            warm = compiles.value
            for tier in PREDICT_TIERS:
                for arrays in placed:
                    out = fused.predict_step_placed(params, arrays,
                                                    tier=tier)
                    jax.block_until_ready(out)
            assert compiles.value - warm == 0, (
                '%d XLA compiles after warmup on fixed packed shapes'
                % (compiles.value - warm))
        finally:
            core.disable()
            core.reset()
        # the ledger's executables bucket stays complete: the AOT
        # memory_analysis the serving warmup records per (bucket x
        # capacity x tier) must measure the FUSED program too
        info = fused.predict_program_memory(params, placed[0],
                                            tier='attention')
        assert info is not None and set(info) == {
            'generated_code_bytes', 'temp_bytes', 'argument_bytes',
            'output_bytes'}

    def test_lazy_adam_falls_back_for_train_only(self):
        """LAZY_EMBEDDING_ADAM needs the unpacked plane indices: the
        packed TRAIN step keeps the unpack path (and still runs), while
        predict stays fused."""
        fused = make_trainer(DROPOUT_KEEP_RATE=1.0,
                             USE_PALLAS_RAGGED_FUSION=True,
                             LAZY_EMBEDDING_ADAM=True)
        packed = self._packed(fused, n=1)
        state = fused.init_state(seed=0)
        state, loss = fused.train_step(state, packed[0])
        assert np.isfinite(float(loss))
        out = fused.predict_step(state.params, packed[0], tier='topk')
        assert np.asarray(out['topk_indices']).shape[0] == 8
