"""Ragged fused encode + attention (ops/pallas_ragged.py) vs the
unpack-then-dense path, under the tests/test_packed.py property regime:
interior holes, pad rows, capacity < batch, fill rates from empty to
full, nonzero PAD indices, per-shard packing. The jnp twin is exercised
everywhere (it is the non-TPU fallback); both Pallas kernels — the
forward and the custom-VJP recompute backward — run in interpreter mode
on CPU, single-shard, flat multi-shard, multi-tile, and shard_mapped
over the 8-virtual-device mesh. TestFusedBackward owns the train-path
acceptance: five-param gradient parity across the regime, dropout-mask
bit-match between the fused pair and the twin, bf16 smoke, and the
no-per-slot-residuals contract (vjp-closure assertion). Trainer
integration covers packed train/eval and all four predict tiers, the
zero-post-warmup-compiles guards on predict AND the fused train step,
and lazy Adam training fused off the packed-stream rows."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.data import packed as packed_lib
from code2vec_tpu.models import functional
from code2vec_tpu.ops import pallas_ragged

from tests.test_packed import random_plane_batch
from tests.test_stage_batches import make_trainer

pytestmark = pytest.mark.skipif(not pallas_ragged.PALLAS_AVAILABLE,
                                reason='pallas unavailable')


def small_params(rng_seed=0, token_vocab=32, path_vocab=16,
                 target_vocab=16, token_dim=8, path_dim=6, code_dim=24):
    return functional.init_params(
        jax.random.PRNGKey(rng_seed), token_vocab_size=token_vocab,
        path_vocab_size=path_vocab, target_vocab_size=target_vocab,
        token_dim=token_dim, path_dim=path_dim, code_dim=code_dim)


def dense_reference(params, batch):
    """The unpack-then-dense ground truth: the packed round trip is
    BIT-exact (tests/test_packed.py), so encoding the original planes IS
    encoding the unpacked wire."""
    return functional.encode(params, batch.source, batch.path,
                             batch.target, batch.mask)


def ragged(params, packed, max_contexts, token_pad, path_pad, **kw):
    return pallas_ragged.ragged_encode(
        params.token_embedding, params.path_embedding, params.transform,
        params.attention, jnp.asarray(packed.ctx),
        jnp.asarray(packed.count), max_contexts=max_contexts,
        token_pad=token_pad, path_pad=path_pad, **kw)


def assert_encode_close(got, want, rtol=2e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]),
                               rtol=rtol, atol=atol, err_msg='code')
    np.testing.assert_allclose(np.asarray(got[1]), np.asarray(want[1]),
                               rtol=rtol, atol=atol, err_msg='attention')


class TestTwinVsDense:
    """The jnp twin (train path / non-TPU fallback) against the dense
    encode, over the full structural property space."""

    @pytest.mark.parametrize('token_pad,path_pad', [(0, 0), (1, 2)])
    # tier-1 budget: 1 (unsharded) and 4 (the real mesh width) bound
    # the property space; the intermediate width rides the slow tier
    @pytest.mark.parametrize(
        'data_shards',
        [1, pytest.param(2, marks=pytest.mark.slow), 4])
    def test_property_regime(self, token_pad, path_pad, data_shards):
        rng = np.random.default_rng(7)
        params = small_params()
        for _trial in range(8):
            contexts = int(rng.choice([3, 5, 8, 13]))
            batch = random_plane_batch(rng, 8, contexts, token_pad,
                                       path_pad)
            packed = packed_lib.pack_batch(batch, token_pad, path_pad,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            got = ragged(params, packed, contexts, token_pad, path_pad,
                         use_kernel=False)
            assert_encode_close(got, dense_reference(params, batch))

    def test_capacity_rungs_agree(self):
        """The same batch packed at every serving-ladder capacity rung
        must produce identical outputs — capacity padding is inert."""
        rng = np.random.default_rng(3)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        want = dense_reference(params, batch)
        for rung in (4, 16, 64, 256):
            packed = packed_lib.pack_batch(batch, 0, 0,
                                           capacity_minimum=rung)
            assert packed.ctx.shape[1] >= rung
            got = ragged(params, packed, 6, 0, 0, use_kernel=False)
            assert_encode_close(got, want)

    def test_all_padding_batch_matches_dense_uniform(self):
        """count == 0 rows: the dense path produces a FINITE uniform
        attention (1/C) and code = x_pad; the fused fixup must match."""
        contexts = 5
        from code2vec_tpu.data.reader import Batch
        zero = Batch(source=np.zeros((4, contexts), np.int32),
                     path=np.zeros((4, contexts), np.int32),
                     target=np.zeros((4, contexts), np.int32),
                     mask=np.zeros((4, contexts), np.float32),
                     label=np.zeros((4,), np.int32),
                     weight=np.zeros((4,), np.float32))
        params = small_params()
        packed = packed_lib.pack_batch(zero, 0, 0, capacity_minimum=4)
        got = ragged(params, packed, contexts, 0, 0, use_kernel=False)
        assert_encode_close(got, dense_reference(params, zero))
        np.testing.assert_allclose(np.asarray(got[1]),
                                   np.full((4, contexts), 1.0 / contexts))

    def test_capacity_smaller_than_batch(self):
        """More examples than context rows (the sparse-eval regression
        shape from tests/test_packed.py)."""
        from code2vec_tpu.data.reader import Batch, context_valid_mask
        contexts, batch_size = 6, 64
        rng = np.random.default_rng(2)
        batch = random_plane_batch(rng, batch_size, contexts)
        lengths = np.zeros((batch_size,), np.int64)
        lengths[:4] = [1, 2, 0, 3]
        dead = np.arange(contexts)[None, :] >= lengths[:, None]
        source = batch.source.copy(); source[dead] = 0
        path = batch.path.copy(); path[dead] = 0
        target = batch.target.copy(); target[dead] = 0
        mask = context_valid_mask(source, path, target, 0, 0)
        batch = batch._replace(source=source, path=path, target=target,
                               mask=mask)
        params = small_params()
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] < batch_size
        got = ragged(params, packed, contexts, 0, 0, use_kernel=False)
        assert_encode_close(got, dense_reference(params, batch))

    def test_gradients_match_dense(self):
        """loss_and_aux_packed's backward (the fused TRAIN path) against
        the unpack-then-dense loss, all five parameter gradients."""
        rng = np.random.default_rng(1)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        batch = batch._replace(
            label=np.clip(batch.label, 0, 15).astype(np.int32))
        packed = packed_lib.pack_batch(batch, 0, 0, data_shards=2,
                                       capacity_minimum=4)

        def dense_loss(p):
            return functional.loss_and_aux(
                p, batch.source, batch.path, batch.target, batch.mask,
                batch.label, batch.weight, num_valid_targets=16)[0]

        def ragged_loss(p):
            return functional.loss_and_aux_packed(
                p, jnp.asarray(packed.ctx), jnp.asarray(packed.count),
                jnp.asarray(packed.label), jnp.asarray(packed.weight),
                max_contexts=6, token_pad=0, path_pad=0,
                num_valid_targets=16)[0]

        loss_d, grads_d = jax.value_and_grad(dense_loss)(params)
        loss_r, grads_r = jax.value_and_grad(ragged_loss)(params)
        np.testing.assert_allclose(float(loss_r), float(loss_d),
                                   rtol=1e-5)
        for name, got, want in zip(params._fields,
                                   jax.tree_util.tree_leaves(grads_r),
                                   jax.tree_util.tree_leaves(grads_d)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-4, atol=1e-6,
                                       err_msg=name)

    def test_dropout_runs_and_is_finite(self):
        """Dropout draws over the PACKED layout (a different seed-keyed
        stream than the dense path — the DROPOUT_PRNG_IMPL precedent),
        so the contract is a finite loss + finite grads, not bit
        parity."""
        params = small_params()
        batch = random_plane_batch(np.random.default_rng(5), 8, 6)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)

        def loss(p):
            return functional.loss_and_aux_packed(
                p, jnp.asarray(packed.ctx), jnp.asarray(packed.count),
                jnp.asarray(np.clip(packed.label, 0, 15)),
                jnp.asarray(packed.weight),
                max_contexts=6, token_pad=0, path_pad=0,
                num_valid_targets=16,
                dropout_rng=jax.random.PRNGKey(7),
                dropout_keep_rate=0.75)[0]

        value, grads = jax.value_and_grad(loss)(params)
        assert np.isfinite(float(value))
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree_util.tree_leaves(grads))

    def test_kernel_dropout_bit_matches_twin_draw(self):
        """Dropout moved INTO the fused pair: the packed-layout keep
        mask is drawn outside the kernel and applied to its embedding
        inputs, so with the same threaded key the kernel forward and
        the jnp twin consume bit-identical inputs — outputs agree to
        fp32 rounding, across prng impls."""
        params = small_params()
        packed = packed_lib.pack_batch(
            random_plane_batch(np.random.default_rng(0), 8, 4), 0, 0,
            capacity_minimum=4)
        for impl in ('threefry2x32', 'rbg'):
            kw = dict(dropout_rng=jax.random.PRNGKey(3),
                      dropout_keep_rate=0.5, dropout_prng_impl=impl)
            twin = ragged(params, packed, 4, 0, 0, use_kernel=False,
                          **kw)
            kern = ragged(params, packed, 4, 0, 0, use_kernel=True,
                          interpret=True, **kw)
            assert_encode_close(kern, twin)


class TestKernelInterpret:
    """The Pallas kernel in interpreter mode — no TPU needed for the
    FuseMax single-pass logic."""

    @pytest.mark.parametrize('data_shards', [1, 2])
    def test_kernel_matches_dense(self, data_shards):
        rng = np.random.default_rng(11)
        params = small_params()
        for _trial in range(6):
            contexts = int(rng.choice([3, 5, 8]))
            batch = random_plane_batch(rng, 8, contexts, 1, 2)
            packed = packed_lib.pack_batch(batch, 1, 2,
                                           data_shards=data_shards,
                                           capacity_minimum=4)
            got = ragged(params, packed, contexts, 1, 2,
                         use_kernel=True, interpret=True)
            assert_encode_close(got, dense_reference(params, batch))

    def test_multi_tile_online_rescale(self, monkeypatch):
        """Force several grid steps (tiny slot tile) so segments SPAN
        tiles and the running (m, z, acc) rescale actually runs, with
        the per-example stream crossing every tile boundary."""
        monkeypatch.setattr(pallas_ragged, 'SLOT_TILE', 8)
        rng = np.random.default_rng(13)
        params = small_params()
        batch = random_plane_batch(rng, 8, 13, hole_rate=0.4)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] > 8  # really multi-tile
        got = ragged(params, packed, 13, 0, 0, use_kernel=True,
                     interpret=True)
        assert_encode_close(got, dense_reference(params, batch))

    def test_kernel_shard_mapped_on_mesh(self):
        """The multi-device route: pallas_call is opaque to GSPMD, so
        the kernel must be shard_mapped over the data axis — parity on
        the 8-virtual-device mesh."""
        from code2vec_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.create_mesh()
        shards = mesh.shape['data']
        rng = np.random.default_rng(17)
        params = small_params()
        batch = random_plane_batch(rng, 2 * shards, 5, 1, 2)
        packed = packed_lib.pack_batch(batch, 1, 2, data_shards=shards,
                                       capacity_minimum=4)
        got = ragged(params, packed, 5, 1, 2, use_kernel=True,
                     interpret=True, mesh=mesh)
        assert_encode_close(got, dense_reference(params, batch))

    def test_bf16_compute_smoke(self):
        """bf16 is the production compute dtype: the kernel and twin
        must agree with the dense bf16 path to bf16 resolution."""
        rng = np.random.default_rng(19)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        want = functional.encode(params, batch.source, batch.path,
                                 batch.target, batch.mask,
                                 dtype=jnp.bfloat16)
        for kw in ({'use_kernel': False},
                   {'use_kernel': True, 'interpret': True}):
            got = ragged(params, packed, 6, 0, 0, dtype=jnp.bfloat16,
                         **kw)
            assert_encode_close(got, want, rtol=0.03, atol=0.02)


def _packed_losses(params, packed, contexts, token_pad=0, path_pad=0,
                   **kw):
    """value_and_grad-ready packed loss closure (custom VJP by
    default; kw overrides select the kernel pair / autodiff twin)."""
    def loss(p):
        return functional.loss_and_aux_packed(
            p, jnp.asarray(packed.ctx), jnp.asarray(packed.count),
            jnp.asarray(np.clip(packed.label, 0, 15)),
            jnp.asarray(packed.weight), max_contexts=contexts,
            token_pad=token_pad, path_pad=path_pad,
            num_valid_targets=16, **kw)[0]
    return loss


def _dense_loss(params, batch):
    def loss(p):
        return functional.loss_and_aux(
            p, batch.source, batch.path, batch.target, batch.mask,
            np.clip(batch.label, 0, 15).astype(np.int32), batch.weight,
            num_valid_targets=16)[0]
    return loss


def assert_grads_close(got, want, fields, rtol=2e-4, atol=1e-6):
    for name, a, b in zip(fields, jax.tree_util.tree_leaves(got),
                          jax.tree_util.tree_leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol, err_msg=name)


class TestFusedBackward:
    """The custom-VJP recompute backward (ragged_encode_code): gradient
    parity for all five params against the unpack-then-dense loss across
    the packed property regime, the Pallas backward kernel in
    interpreter mode (single-shard, flat multi-shard, multi-tile,
    shard_mapped), dropout-mask bit-match between the fused pair and the
    twin, bf16 smoke, and the no-per-slot-residuals contract."""

    def test_grad_parity_property_regime(self):
        """Holes, pad rows (count == 0), fill rates, shard counts: the
        custom-VJP gradients must match the dense path's for all five
        params (the fp32-rounding regime)."""
        rng = np.random.default_rng(23)
        params = small_params()
        for shards in (1, 2, 4):
            contexts = int(rng.choice([3, 5, 8, 13]))
            batch = random_plane_batch(rng, 8, contexts, hole_rate=0.4,
                                       pad_row_rate=0.3)
            packed = packed_lib.pack_batch(batch, 0, 0,
                                           data_shards=shards,
                                           capacity_minimum=4)
            loss_d, grads_d = jax.value_and_grad(
                _dense_loss(params, batch))(params)
            loss_r, grads_r = jax.value_and_grad(
                _packed_losses(params, packed, contexts))(params)
            np.testing.assert_allclose(float(loss_r), float(loss_d),
                                       rtol=1e-5)
            assert_grads_close(grads_r, grads_d, params._fields)

    def test_grad_parity_capacity_rungs(self):
        """The same batch packed at every serving-ladder rung must
        produce identical gradients — backward capacity padding is as
        inert as forward's."""
        rng = np.random.default_rng(29)
        params = small_params()
        batch = random_plane_batch(rng, 8, 6)
        _, grads_d = jax.value_and_grad(_dense_loss(params,
                                                    batch))(params)
        for rung in (4, 16, 64, 256):
            packed = packed_lib.pack_batch(batch, 0, 0,
                                           capacity_minimum=rung)
            _, grads_r = jax.value_and_grad(
                _packed_losses(params, packed, 6))(params)
            assert_grads_close(grads_r, grads_d, params._fields)

    def test_kernel_backward_matches_dense(self):
        """The Pallas backward kernel (interpreter mode), single-shard
        and flat multi-shard, against the dense gradients."""
        rng = np.random.default_rng(31)
        params = small_params()
        for shards in (1, 2):
            batch = random_plane_batch(rng, 8, 7, 1, 2)
            packed = packed_lib.pack_batch(batch, 1, 2,
                                           data_shards=shards,
                                           capacity_minimum=4)
            _, grads_d = jax.value_and_grad(_dense_loss(params,
                                                        batch))(params)
            _, grads_k = jax.value_and_grad(_packed_losses(
                params, packed, 7, 1, 2,
                use_ragged_kernel=True))(params)
            assert_grads_close(grads_k, grads_d, params._fields)

    def test_kernel_backward_multi_tile(self, monkeypatch):
        """Segments spanning several grid steps: the backward kernel
        reads saved (m, z) — no running rescale — but its per-tile
        accumulation of the dense grads must still sum across tiles."""
        monkeypatch.setattr(pallas_ragged, 'SLOT_TILE', 8)
        rng = np.random.default_rng(37)
        params = small_params()
        batch = random_plane_batch(rng, 8, 13, hole_rate=0.4)
        packed = packed_lib.pack_batch(batch, 0, 0, capacity_minimum=4)
        assert packed.ctx.shape[1] > 8
        _, grads_t = jax.value_and_grad(
            _packed_losses(params, packed, 13))(params)
        _, grads_k = jax.value_and_grad(_packed_losses(
            params, packed, 13, use_ragged_kernel=True))(params)
        assert_grads_close(grads_k, grads_t, params._fields)

    def test_kernel_backward_shard_mapped_on_mesh(self):
        """The multi-device route: forward AND backward kernels
        shard_mapped over the data axis, gradient parity on the
        8-virtual-device mesh."""
        from code2vec_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.create_mesh()
        shards = mesh.shape['data']
        rng = np.random.default_rng(41)
        params = small_params()
        batch = random_plane_batch(rng, 2 * shards, 5, 1, 2)
        packed = packed_lib.pack_batch(batch, 1, 2, data_shards=shards,
                                       capacity_minimum=4)
        _, grads_d = jax.value_and_grad(_dense_loss(params,
                                                    batch))(params)
        _, grads_k = jax.value_and_grad(_packed_losses(
            params, packed, 5, 1, 2, use_ragged_kernel=True,
            ragged_mesh=mesh))(params)
        assert_grads_close(grads_k, grads_d, params._fields)

    @pytest.mark.slow  # three consumers x jit (~11s); budget headroom
    def test_dropout_bit_match_fused_vs_twin(self):
        """One threaded key, three consumers — the autodiff twin, the
        custom-VJP twin pair, the custom-VJP kernel pair — must all
        draw the SAME packed-layout mask: identical losses to fp32
        rounding and matching gradients (the recompute backward
        re-draws the mask rather than storing it)."""
        rng = np.random.default_rng(43)
        params = small_params()
        packed = packed_lib.pack_batch(
            random_plane_batch(rng, 8, 6), 0, 0, data_shards=2,
            capacity_minimum=4)
        for impl in ('threefry2x32', 'rbg'):
            kw = dict(dropout_rng=jax.random.PRNGKey(11),
                      dropout_keep_rate=0.75, dropout_prng_impl=impl)
            loss_a, grads_a = jax.value_and_grad(_packed_losses(
                params, packed, 6, ragged_custom_vjp=False,
                use_ragged_kernel=False, **kw))(params)
            loss_v, grads_v = jax.value_and_grad(_packed_losses(
                params, packed, 6, **kw))(params)
            loss_k, grads_k = jax.value_and_grad(_packed_losses(
                params, packed, 6, use_ragged_kernel=True,
                **kw))(params)
            np.testing.assert_allclose(float(loss_v), float(loss_a),
                                       rtol=1e-6)
            np.testing.assert_allclose(float(loss_k), float(loss_a),
                                       rtol=1e-6)
            assert_grads_close(grads_v, grads_a, params._fields)
            assert_grads_close(grads_k, grads_a, params._fields)

    def test_bf16_backward_smoke(self):
        """bf16 compute: the custom-VJP gradients track the autodiff
        twin's to bf16 resolution."""
        rng = np.random.default_rng(47)
        params = small_params()
        packed = packed_lib.pack_batch(
            random_plane_batch(rng, 8, 6), 0, 0, capacity_minimum=4)
        _, grads_a = jax.value_and_grad(_packed_losses(
            params, packed, 6, dtype=jnp.bfloat16,
            ragged_custom_vjp=False))(params)
        _, grads_v = jax.value_and_grad(_packed_losses(
            params, packed, 6, dtype=jnp.bfloat16))(params)
        assert_grads_close(grads_v, grads_a, params._fields,
                           rtol=0.05, atol=0.02)

    def test_count_zero_rows_route_through_x_pad(self):
        """count == 0 rows take code = x_pad = tanh(pad_ctx @ W): a
        NONZERO cotangent on their code vectors (sum-of-code, unlike
        the weight-masked loss) must flow through that expression
        exactly as the autodiff twin's does."""
        contexts = 5
        from code2vec_tpu.data.reader import Batch
        zero = Batch(source=np.ones((4, contexts), np.int32),
                     path=np.ones((4, contexts), np.int32),
                     target=np.ones((4, contexts), np.int32),
                     mask=np.zeros((4, contexts), np.float32),
                     label=np.zeros((4,), np.int32),
                     weight=np.zeros((4,), np.float32))
        zero = zero._replace(source=np.zeros_like(zero.source),
                             path=np.zeros_like(zero.path),
                             target=np.zeros_like(zero.target))
        params = small_params()
        packed = packed_lib.pack_batch(zero, 0, 0, capacity_minimum=4)

        def code_sum(p, custom_vjp):
            return pallas_ragged.ragged_encode_code(
                p.token_embedding, p.path_embedding, p.transform,
                p.attention, jnp.asarray(packed.ctx),
                jnp.asarray(packed.count), token_pad=0, path_pad=0,
                use_kernel=False, custom_vjp=custom_vjp).sum()

        grads_a = jax.grad(lambda p: code_sum(p, False))(params)
        grads_v = jax.grad(lambda p: code_sum(p, True))(params)
        # encoder params only: target_embedding is out of scope here
        for name in ('token_embedding', 'path_embedding', 'transform',
                     'attention'):
            np.testing.assert_allclose(
                np.asarray(getattr(grads_v, name)),
                np.asarray(getattr(grads_a, name)),
                rtol=2e-4, atol=1e-6, err_msg=name)
        assert float(jnp.abs(grads_v.transform).sum()) > 0.0

    def test_custom_vjp_saves_no_per_slot_residuals(self):
        """THE residual contract (acceptance): the vjp closure of the
        custom-VJP packed loss holds NO floating residual of per-slot
        rank — the (D, cap, 3d) gathered embeddings, the dropout masks
        and the (D, cap, D) activations are recomputed, not stored —
        while the autodiff twin's closure demonstrably stores them
        (the check would catch a silent regression to storing)."""
        rng = np.random.default_rng(53)
        params = small_params()
        packed = packed_lib.pack_batch(
            random_plane_batch(rng, 8, 6), 0, 0, data_shards=2,
            capacity_minimum=4)
        kw = dict(dropout_rng=jax.random.PRNGKey(5),
                  dropout_keep_rate=0.75)

        def residual_shapes(ragged_custom_vjp):
            # floating rank-3+ residuals = the per-slot tensors ((D,
            # cap, d) embeddings, (D, cap, Dc) activations); the int32
            # ctx wire and tiny CE-tail leaves are inputs/bookkeeping
            loss = _packed_losses(params, packed, 6,
                                  ragged_custom_vjp=ragged_custom_vjp,
                                  **kw)
            _, f_vjp = jax.vjp(loss, params)
            return [tuple(leaf.shape)
                    for leaf in jax.tree_util.tree_leaves(f_vjp)
                    if hasattr(leaf, 'ndim') and leaf.ndim >= 3
                    and jnp.issubdtype(leaf.dtype, jnp.floating)]

        assert residual_shapes(True) == []
        assert len(residual_shapes(False)) > 0


@pytest.fixture(scope='module')
def trainer_pair():
    """One (plain, fused) trainer pair shared by the integration tests:
    Trainer construction compiles the full step-program family on the
    8-device mesh, so rebuilding per test would dominate the file's
    tier-1 budget. Dropout off: the two layouts draw different masks.
    The fused trainer deliberately relies on the config DEFAULT (ON
    since the custom-VJP backward landed); the plain arm pins the
    unpack path."""
    plain = make_trainer(DROPOUT_KEEP_RATE=1.0,
                         USE_PALLAS_RAGGED_FUSION=False)
    fused = make_trainer(DROPOUT_KEEP_RATE=1.0)
    return plain, fused


class TestTrainerIntegration:
    """USE_PALLAS_RAGGED_FUSION threaded through the packed train/eval/
    predict steps: fused vs unpack-then-dense on the 8-virtual-device
    mesh (CPU, so the twin runs — the same code the TPU train path
    uses)."""

    def _packed(self, trainer, n=3):
        rng = np.random.default_rng(5)
        shards = trainer.mesh.shape['data']
        out = []
        for _ in range(n):
            batch = random_plane_batch(rng, 8, 4, pad_row_rate=0.1)
            batch = batch._replace(
                label=np.clip(batch.label, 0, 15).astype(np.int32))
            out.append(packed_lib.pack_batch(batch, 0, 0,
                                             data_shards=shards,
                                             capacity_minimum=4))
        return out

    def test_train_steps_match(self, trainer_pair):
        plain, fused = trainer_pair
        packed = self._packed(plain)
        state_a = plain.init_state(seed=0)
        state_b = fused.init_state(seed=0)
        for pb in packed:
            state_a, loss_a = plain.train_step(state_a, pb)
            state_b, loss_b = fused.train_step(state_b, pb)
            np.testing.assert_allclose(float(loss_b), float(loss_a),
                                       rtol=1e-5)
        for leaf_a, leaf_b in zip(
                jax.tree_util.tree_leaves(state_a.params),
                jax.tree_util.tree_leaves(state_b.params)):
            np.testing.assert_allclose(np.asarray(leaf_b),
                                       np.asarray(leaf_a),
                                       rtol=2e-4, atol=1e-6)

    def test_eval_and_all_predict_tiers_match(self, trainer_pair):
        plain, fused = trainer_pair
        packed = self._packed(plain, n=1)
        params = plain.init_state(seed=1).params
        out_a = plain.eval_step(params, packed[0])
        out_b = fused.eval_step(params, packed[0])
        np.testing.assert_array_equal(np.asarray(out_a['topk_indices']),
                                      np.asarray(out_b['topk_indices']))
        np.testing.assert_allclose(float(out_b['loss_sum']),
                                   float(out_a['loss_sum']), rtol=1e-5)
        assert float(out_a['weight_sum']) == float(out_b['weight_sum'])
        from code2vec_tpu.training.trainer import PREDICT_TIERS
        for tier in PREDICT_TIERS:
            pa = plain.predict_step(params, packed[0], tier=tier)
            pb = fused.predict_step(params, packed[0], tier=tier)
            assert set(pa) == set(pb), tier
            for key in pa:
                np.testing.assert_allclose(
                    np.asarray(pb[key]).astype(np.float64),
                    np.asarray(pa[key]).astype(np.float64),
                    rtol=1e-5, atol=1e-6, err_msg='%s/%s' % (tier, key))

    def test_zero_postwarm_compiles(self, trainer_pair):
        """The fused packed programs must be as shape-stable as the
        unpack path: repeated dispatches on warm (bucket, capacity,
        tier) shapes add NOTHING to the compile counter — the serving
        ladder's steady-state contract. (Predict is deterministic, so
        the shared dropout-off trainer is exactly the serving shape.)"""
        from code2vec_tpu.parallel import mesh as mesh_lib
        from code2vec_tpu.telemetry import core
        from code2vec_tpu.telemetry.jit_tracker import \
            install_compile_listener
        from code2vec_tpu.training.trainer import PREDICT_TIERS
        fused = trainer_pair[1]
        packed = self._packed(fused, n=2)
        params = fused.init_state(seed=0).params
        placed = [mesh_lib.shard_batch(pb.device_arrays(), fused.mesh,
                                       False) for pb in packed]
        assert placed[0][0].shape == placed[1][0].shape  # same capacity
        core.reset()
        core.enable()
        try:
            assert install_compile_listener()
            compiles = core.registry().counter('jit/compiles_total')
            for tier in PREDICT_TIERS:  # warm every fused program
                fused.predict_step_placed(params, placed[0], tier=tier)
            warm = compiles.value
            for tier in PREDICT_TIERS:
                for arrays in placed:
                    out = fused.predict_step_placed(params, arrays,
                                                    tier=tier)
                    jax.block_until_ready(out)
            assert compiles.value - warm == 0, (
                '%d XLA compiles after warmup on fixed packed shapes'
                % (compiles.value - warm))
        finally:
            core.disable()
            core.reset()
        # the ledger's executables bucket stays complete: the AOT
        # memory_analysis the serving warmup records per (bucket x
        # capacity x tier) must measure the FUSED program too
        info = fused.predict_program_memory(params, placed[0],
                                            tier='attention')
        assert info is not None and set(info) == {
            'generated_code_bytes', 'temp_bytes', 'argument_bytes',
            'output_bytes'}

    def test_zero_postwarm_compiles_fused_train(self, trainer_pair):
        """The custom-VJP train step is as shape-stable as the rest:
        repeated train dispatches on a warm (shards, capacity) shape add
        NOTHING to the compile counter — the recompute backward, the
        dropout re-draw and the table scatter-adds all key on the same
        packed shapes."""
        from code2vec_tpu.telemetry import core
        from code2vec_tpu.telemetry.jit_tracker import \
            install_compile_listener
        fused = trainer_pair[1]
        packed = self._packed(fused, n=3)
        assert packed[0].ctx.shape == packed[1].ctx.shape
        state = fused.init_state(seed=0)
        core.reset()
        core.enable()
        try:
            assert install_compile_listener()
            compiles = core.registry().counter('jit/compiles_total')
            state, _ = fused.train_step(state, packed[0])  # warm
            warm = compiles.value
            for pb in packed:
                state, loss = fused.train_step(state, pb)
                jax.block_until_ready(loss)
            assert compiles.value - warm == 0, (
                '%d XLA compiles after warmup on the fused train step'
                % (compiles.value - warm))
        finally:
            core.disable()
            core.reset()
        # the bench A/B's memory axis: the train program's AOT analysis
        # must resolve on this backend too (temp_bytes is the residual
        # claim's measurable)
        from code2vec_tpu.parallel import mesh as mesh_lib
        placed = mesh_lib.shard_batch(packed[0].device_arrays(),
                                      fused.mesh, False)
        info = fused.train_program_memory(state, placed)
        assert info is not None and 'temp_bytes' in info

    def test_lazy_adam_trains_fused_with_parity(self):
        """The lifted `ragged and lazy` exclusion (ISSUE 12): lazy Adam
        now trains FUSED — the custom-VJP backward's table grads are
        dense scatter-adds over the packed stream, and the sparse-row
        update reads its touched rows straight off the packed ctx
        indices. Touched-row sets are provably identical to the unpack
        path's (every slot up to each example's effective length + the
        PAD row), so params must match the unpack-then-dense lazy step
        to fp32 rounding — including rows a batch did NOT touch staying
        bit-identical (the lazy semantics)."""
        fused = make_trainer(DROPOUT_KEEP_RATE=1.0,
                             LAZY_EMBEDDING_ADAM=True)
        plain = make_trainer(DROPOUT_KEEP_RATE=1.0,
                             LAZY_EMBEDDING_ADAM=True,
                             USE_PALLAS_RAGGED_FUSION=False)
        packed = self._packed(fused, n=2)
        state_f = fused.init_state(seed=0)
        state_p = plain.init_state(seed=0)
        for pb in packed:
            state_f, loss_f = fused.train_step(state_f, pb)
            state_p, loss_p = plain.train_step(state_p, pb)
            np.testing.assert_allclose(float(loss_f), float(loss_p),
                                       rtol=1e-5)
        for name, leaf_f, leaf_p in zip(
                state_f.params._fields,
                jax.tree_util.tree_leaves(state_f.params),
                jax.tree_util.tree_leaves(state_p.params)):
            np.testing.assert_allclose(np.asarray(leaf_f),
                                       np.asarray(leaf_p),
                                       rtol=2e-4, atol=1e-6,
                                       err_msg=name)
        out = fused.predict_step(state_f.params, packed[0], tier='topk')
        assert np.asarray(out['topk_indices']).shape[0] == 8
