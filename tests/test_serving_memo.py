"""Memoization-tier drills (serving/memo.py + the mesh admission wiring,
ISSUE 16): one shared request-identity definition (canonicalize_contexts)
across engine/mesh/memo key, exact-tier hits resolved AT SUBMIT with
memo-vs-live bit identity (including the oversize split/re-join path and
permuted context order), degraded-tier answers that cannot poison the
full-tier key, the rollover-invalidation drill (fleet swap -> every
pre-swap entry misses via ONE generation bump, not per-entry eviction;
a rolled-back canary leaves the cache warm), the epsilon-gated semantic
tier with its shadow-sampled top-1 agreement export, and LRU/ledger
byte accounting."""
import collections

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import canonicalize_contexts, parse_c2v_line
from code2vec_tpu.serving import memo as memo_lib
from code2vec_tpu.telemetry import memory as memory_lib
from tests.test_train_overfit import make_dataset

PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]

# same requests, context multisets permuted within each line (plus
# stray whitespace): identical canonical form, so identical memo keys
PERMUTED_LINES = [
    'get|a toka1,pB,toka2 toka0,pA,toka1',
    'set|b  tokb0,pA,tokb1',
    'run|c tokc1,pB,tokc2 tokc0,pC,tokc1 tokc2,pA,tokc0',
]


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving_memo'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16')
    return Code2VecModel(config)


def _assert_rows_identical(a_rows, b_rows):
    """Bit identity between two result lists (the memo acceptance bar:
    a cache-served answer is indistinguishable from the live one)."""
    assert len(a_rows) == len(b_rows)
    for a, b in zip(a_rows, b_rows):
        assert a.original_name == b.original_name
        assert a.topk_predicted_words == b.topk_predicted_words
        if a.topk_predicted_words_scores is None:
            assert b.topk_predicted_words_scores is None
        else:
            np.testing.assert_array_equal(a.topk_predicted_words_scores,
                                          b.topk_predicted_words_scores)
        assert a.attention_per_context == b.attention_per_context
        if a.code_vector is None:
            assert b.code_vector is None
        else:
            np.testing.assert_array_equal(a.code_vector, b.code_vector)


# --------------------------------------------------- canonical identity
def test_canonicalize_contexts_semantics():
    # sort each line's context multiset, label kept first; duplicates
    # are KEPT — a repeated context weights attention twice, so the
    # count is part of request identity
    assert canonicalize_contexts(['lab c,p,d a,p,b a,p,b']) == \
        ['lab a,p,b a,p,b c,p,d']
    # split matches parse_c2v_line (single-space separators): empty
    # slots from doubled spaces are dropped; blank lines survive
    # positionally
    assert canonicalize_contexts(['lab  x,y,z ', '', 'l2 a,b,c']) == \
        ['lab x,y,z', '', 'l2 a,b,c']
    # idempotent: canonical input is a fixed point
    lines = canonicalize_contexts(PERMUTED_LINES)
    assert canonicalize_contexts(lines) == lines
    # line ORDER is preserved — results are positional
    swapped = canonicalize_contexts([PREDICT_LINES[1], PREDICT_LINES[0]])
    assert swapped[0].startswith('set|b')


def test_canonicalize_truncates_in_extraction_order():
    """REVIEW fix: truncation to MAX_CONTEXTS happens in ORIGINAL
    extraction order, BEFORE the canonical sort — the context subset
    that survives is exactly the subset the evaluate-path reader
    (parse_c2v_line, which never canonicalizes) keeps."""
    line = 'lab c,p,3 a,p,1 b,p,2'
    # sort-first would keep {a,b}; extraction-order keeps {c,a}
    assert canonicalize_contexts([line], 2) == ['lab a,p,1 c,p,3']
    # an empty slot from a doubled space occupies a context slot in
    # parse_c2v_line, so it must occupy one during truncation here too
    gapped = 'lab a,p,1  b,p,2'
    assert canonicalize_contexts([gapped], 2) == ['lab a,p,1']
    # idempotent at fixed max_contexts
    once = canonicalize_contexts([line, gapped], 2)
    assert canonicalize_contexts(once, 2) == once
    # the canonical line tokenizes to the same label + valid-context
    # multiset as the raw line, at every truncation width
    wide = 'l ' + ' '.join('t%d,p,%d' % (i, i) for i in range(10))
    for raw in (line, gapped, wide):
        for m in (1, 2, 4, 8):
            canon = canonicalize_contexts([raw], m)[0]
            raw_row = parse_c2v_line(raw, m)
            canon_row = parse_c2v_line(canon, m)
            assert canon_row.label_str == raw_row.label_str

            def valid_ctxs(row):
                return sorted(t for t in zip(row.source_strs,
                                             row.path_strs,
                                             row.target_strs) if any(t))
            assert valid_ctxs(canon_row) == valid_ctxs(raw_row)


def test_request_key_scopes_tier_and_k_and_line_order():
    canon = canonicalize_contexts(PREDICT_LINES)
    permuted = canonicalize_contexts(PERMUTED_LINES)
    assert memo_lib.request_key(canon, 'topk') == \
        memo_lib.request_key(permuted, 'topk')
    assert memo_lib.request_key(canon, 'topk') != \
        memo_lib.request_key(canon, 'full')
    assert memo_lib.request_key(canon, 'neighbors', k=5) != \
        memo_lib.request_key(canon, 'neighbors', k=10)
    reordered = [canon[1], canon[0], canon[2]]
    assert memo_lib.request_key(canon, 'topk') != \
        memo_lib.request_key(reordered, 'topk')


# ------------------------------------------------------ MemoCache units
def test_memo_cache_lru_eviction_and_ledger_bytes():
    cache = memo_lib.MemoCache(4096)
    try:
        keys = [memo_lib.request_key(['l%d a,b,c' % i], 'topk')
                for i in range(8)]
        row = [{'scores': np.zeros(128, np.float64)}]  # ~1k + overhead
        for key in keys:
            assert cache.insert(key, row, cache.generation)
        stats = cache.stats()
        assert stats['evictions'] > 0
        assert stats['bytes'] <= cache.capacity_bytes
        # the LRU survivor set is the most-recent suffix
        assert cache.lookup(keys[0]) is None
        assert cache.lookup(keys[-1]) is not None
        # ledger: memo bucket carries the cache's host bytes
        assert memory_lib.ledger().bucket_bytes('memo') == stats['bytes']
        # a result larger than the whole budget is skipped
        huge = [{'scores': np.zeros(4096, np.float64)}]
        assert not cache.insert(keys[0], huge, cache.generation)
        # an insert carrying a stale generation is refused (a request
        # in flight across a rollover can never poison the new cache)
        old_gen = cache.generation
        cache.bump_generation(3)
        assert not cache.insert(keys[0], row, old_gen)
        assert cache.lookup(keys[-1]) is None  # swap invalidated all
        assert cache.stats()['params_step'] == 3
    finally:
        cache.close()
    assert memory_lib.ledger().bucket_bytes('memo') == 0


def test_memo_cache_generation_bump_is_not_eviction():
    cache = memo_lib.MemoCache(1 << 20)
    try:
        key = memo_lib.request_key(['l a,b,c'], 'topk')
        cache.insert(key, [{'s': np.zeros(8)}], cache.generation)
        before = cache.stats()
        assert before['entries'] == 1
        cache.bump_generation()
        after = cache.stats()
        assert after['generation'] == before['generation'] + 1
        assert after['entries'] == 0 and after['bytes'] == 0
        # the drill's distinguishing assertion: atomic version bump,
        # NOT a per-entry eviction walk
        assert after['evictions'] == before['evictions'] == 0
        assert cache.lookup(key) is None
    finally:
        cache.close()


def test_memo_stale_generation_eviction_reexports_gauges():
    """The defensive stale-generation eviction in lookup must re-export
    memo/bytes, memo/entries and the ledger bucket immediately — not
    leave them stale until the next insert."""
    cache = memo_lib.MemoCache(1 << 20)
    try:
        key = memo_lib.request_key(['l a,b,c'], 'topk')
        cache.insert(key, [{'s': np.zeros(64)}], cache.generation)
        assert cache.bytes_gauge.snapshot() > 0
        assert memory_lib.ledger().bucket_bytes('memo') > 0
        # forge the unreachable-in-practice state the branch defends
        # against: an entry whose generation mismatches the cache's
        cache._entries[key].generation += 1
        assert cache.lookup(key) is None
        assert cache.bytes_gauge.snapshot() == 0
        assert cache.entries_gauge.snapshot() == 0
        assert memory_lib.ledger().bucket_bytes('memo') == 0
        assert cache.stats()['entries'] == 0
    finally:
        cache.close()


def test_memo_hits_isolated_from_caller_mutation():
    """Neither the first (delivering) caller nor any hit-served caller
    can poison the cache by mutating what they were handed: inserts
    snapshot, hits get fresh copies (copy_results)."""
    from code2vec_tpu.index.service import NeighborResult
    cache = memo_lib.MemoCache(1 << 20)
    try:
        key = memo_lib.request_key(['l a,b,c'], 'neighbors', k=2)
        live = [NeighborResult(indices=np.array([2, 0]),
                               scores=np.array([0.9, 0.5], np.float32),
                               labels=['c', 'a'])]
        cache.insert(key, live, cache.generation)
        # the delivering caller mutates its rows AFTER delivery
        live[0].scores[:] = -1.0
        live[0].labels.append('poison')
        hit = cache.lookup(key)
        assert type(hit[0]) is NeighborResult  # NamedTuple type kept
        np.testing.assert_array_equal(
            hit[0].scores, np.array([0.9, 0.5], np.float32))
        assert hit[0].labels == ['c', 'a']
        # a hit-served caller mutates what IT got back
        hit[0].scores[:] = 7.0
        hit[0].labels.clear()
        again = cache.lookup(key)
        assert again[0] is not hit[0]
        np.testing.assert_array_equal(
            again[0].scores, np.array([0.9, 0.5], np.float32))
        assert again[0].labels == ['c', 'a']
    finally:
        cache.close()


def test_memo_semantic_serves_isolated_copies():
    from code2vec_tpu.index.service import neighbors_from_search
    cache = memo_lib.MemoCache(1 << 20, semantic_epsilon=0.05,
                               semantic_shadow_every=100)
    try:
        vec = np.array([1.0, 0.0, 0.0], np.float32)
        rows = neighbors_from_search(np.array([[0.9, 0.5]]),
                                     np.array([[2, 0]]), ['a', 'b', 'c'])
        cache.semantic_insert(vec[None, :], rows, 4, cache.generation)
        rows[0].scores[:] = -1.0  # delivering caller mutates after
        served, shadow = cache.semantic_lookup(vec, 4)
        assert not shadow
        np.testing.assert_array_almost_equal(served.scores, [0.9, 0.5])
        served.scores[:] = 5.0  # hit caller mutates its copy
        served2, _ = cache.semantic_lookup(vec, 4)
        assert served2 is not served
        np.testing.assert_array_almost_equal(served2.scores, [0.9, 0.5])
    finally:
        cache.close()


def test_memo_semantic_shadow_sampling_and_agreement():
    from code2vec_tpu.index.service import neighbors_from_search
    cache = memo_lib.MemoCache(1 << 20, semantic_epsilon=0.05,
                               semantic_shadow_every=2)
    try:
        vec = np.array([1.0, 0.0, 0.0, 0.0], np.float32)
        rows = neighbors_from_search(np.array([[0.9, 0.5]]),
                                     np.array([[2, 0]]),
                                     ['a', 'b', 'c'])
        assert cache.semantic_insert(vec[None, :], rows, 10,
                                     cache.generation) == 1
        near = vec * 1.001 + np.array([0.0, 1e-3, 0.0, 0.0], np.float32)
        hit = cache.semantic_lookup(near, 10)
        assert hit is not None and hit[1] is False  # served
        hit2 = cache.semantic_lookup(near, 10)
        assert hit2 is not None and hit2[1] is True  # shadow sample
        # beyond epsilon, or a different k: no candidate
        far = np.array([0.0, 1.0, 0.0, 0.0], np.float32)
        assert cache.semantic_lookup(far, 10) is None
        assert cache.semantic_lookup(near, 5) is None
        # shadow agreement export: 1 agree + 1 disagree -> rate 0.5
        cache.note_semantic_agreement(rows[0], rows[0])
        other = neighbors_from_search(np.array([[0.8, 0.1]]),
                                      np.array([[1, 0]]), ['a', 'b', 'c'])
        cache.note_semantic_agreement(rows[0], other[0])
        stats = cache.stats()['semantic']
        assert stats['samples'] == 2
        assert stats['agreement'] == pytest.approx(0.5)
        assert cache.agreement_gauge.snapshot() == pytest.approx(0.5)
    finally:
        cache.close()


def test_memo_semantic_off_by_default_stores_nothing():
    cache = memo_lib.MemoCache(1 << 20)  # epsilon 0 = tier OFF
    try:
        vec = np.ones((1, 4), np.float32)
        assert cache.semantic_insert(vec, [object()], 10,
                                     cache.generation) == 0
        assert cache.semantic_lookup(vec[0], 10) is None
        assert cache.stats()['semantic']['rows'] == 0
    finally:
        cache.close()


# ------------------------------------------------- mesh admission wiring
def test_mesh_exact_hit_at_submit_bit_identical_to_live(model):
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'attention'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        live = mesh.predict(PREDICT_LINES, tier='attention', timeout=60)
        # the duplicate — context order permuted — is served AT SUBMIT:
        # the future comes back already resolved, before tokenize,
        # before the queue, before the device
        handle = mesh.submit(PERMUTED_LINES, tier='attention')
        assert handle.done()
        cached = handle.result()
        _assert_rows_identical(cached, live)
        # ... and bit-identical to an independent live compute
        _assert_rows_identical(cached, model.predict(PREDICT_LINES))
        stats = mesh.stats()['memo']
        assert stats['hits'] == 1 and stats['entries'] >= 1
        assert stats['bytes'] > 0
    finally:
        mesh.close()


def test_mesh_memo_off_by_default(model):
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              max_delay_ms=0.0)
    try:
        assert mesh.stats()['memo'] is None
        mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        handle = mesh.submit(PREDICT_LINES, tier='topk')
        assert not handle.done() or handle.result()  # went live
        handle.result(timeout=60)
    finally:
        mesh.close()


def test_mesh_oversize_split_rejoin_memo_bit_identity(model):
    """A request wider than the top batch bucket (16) is split into
    chunks and re-joined; the memo insert fires on the CALLER-VISIBLE
    future after the join, so the cached answer covers all rows in
    order."""
    lines = [PREDICT_LINES[i % 3] for i in range(20)]
    permuted = [PERMUTED_LINES[i % 3] for i in range(20)]
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        live = mesh.predict(lines, tier='topk', timeout=120)
        assert len(live) == 20
        handle = mesh.submit(permuted, tier='topk')
        assert handle.done()
        _assert_rows_identical(handle.result(), live)
        # independent live compute (model.predict serves the full tier:
        # compare the fields the topk tier produces)
        for cached, ref in zip(handle.result(), model.predict(lines)):
            assert cached.topk_predicted_words == ref.topk_predicted_words
            np.testing.assert_array_equal(
                cached.topk_predicted_words_scores,
                ref.topk_predicted_words_scores)
    finally:
        mesh.close()


def test_mesh_degraded_tier_cannot_poison_full_key(model, monkeypatch):
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'full'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        orig_admit = mesh._queue.admit

        def degrading_admit(n, tier, deadline_s):
            return orig_admit(n, 'topk' if tier == 'full' else tier,
                              deadline_s)

        monkeypatch.setattr(mesh._queue, 'admit', degrading_admit)
        degraded = mesh.predict(PREDICT_LINES, tier='full', timeout=60)
        assert all(not r.attention_per_context for r in degraded)
        monkeypatch.undo()
        # the degraded answer was keyed under its EFFECTIVE tier: the
        # full-tier ask misses and computes live, with attention
        handle = mesh.submit(PREDICT_LINES, tier='full')
        assert not handle.done()
        full = handle.result(timeout=60)
        assert all(r.attention_per_context for r in full)
        # ... while a topk ask is a legitimate hit on the degraded row
        topk_handle = mesh.submit(PREDICT_LINES, tier='topk')
        assert topk_handle.done()
        _assert_rows_identical(topk_handle.result(), degraded)
    finally:
        mesh.close()


# ------------------------------------------------ rollover invalidation
def test_rollover_invalidation_drill(model):
    """Fleet swap -> every pre-swap memo entry is a MISS via one atomic
    generation bump (evictions stay 0); a rolled-BACK canary leaves the
    cache warm."""
    import jax
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
        broken = jax.tree_util.tree_map(lambda leaf: -leaf, model.params)
        jax.block_until_ready(broken)
        mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        warm_hit = mesh.submit(PREDICT_LINES, tier='topk')
        assert warm_hit.done()
        gen_before = mesh.stats()['memo']['generation']

        # ---- canaried fleet swap: the CONCLUDE callback must bump
        handle = mesh.load_params(same, canary_batches=2,
                                  min_agreement=0.9)
        for _ in range(12):
            if handle.done():
                break
            mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        assert handle.result(timeout=60)['swapped'] is True
        stats = mesh.stats()['memo']
        assert stats['generation'] == gen_before + 1
        assert stats['entries'] == 0 and stats['bytes'] == 0
        assert stats['evictions'] == 0  # version bump, not eviction
        stale = mesh.submit(PREDICT_LINES, tier='topk')
        assert not stale.done()  # pre-swap entry can never serve
        stale.result(timeout=60)

        # ---- rolled-back canary: cache stays WARM
        rewarmed = mesh.submit(PREDICT_LINES, tier='topk')
        assert rewarmed.done()  # the post-swap compute re-cached it
        handle = mesh.load_params(broken, canary_batches=2,
                                  min_agreement=0.9)
        for _ in range(12):
            if handle.done():
                break
            mesh.predict([PREDICT_LINES[0]], tier='topk', timeout=60)
        assert handle.result(timeout=60)['swapped'] is False
        stats = mesh.stats()['memo']
        assert stats['generation'] == gen_before + 1  # unchanged
        still_warm = mesh.submit(PREDICT_LINES, tier='topk')
        assert still_warm.done()
    finally:
        mesh.close()


# ------------------------------------------------------- semantic tier
class _FakeIndex:
    """Deterministic stand-in for index/service.py's loaded index."""

    def __init__(self, dim, n=8, seed=0):
        rng = np.random.default_rng(seed)
        store = rng.normal(size=(n, dim)).astype(np.float32)
        self._store = store / np.linalg.norm(store, axis=1,
                                             keepdims=True)
        self.labels = ['lab%d' % i for i in range(n)]

    def search(self, vectors, k):
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        sims = vectors @ self._store.T
        idx = np.argsort(-sims, axis=1)[:, :k]
        return np.take_along_axis(sims, idx, axis=1), idx


def test_mesh_neighbors_exact_and_semantic_tiers(model):
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20,
                              memo_semantic_epsilon=0.05)
    try:
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        mesh.attach_index(_FakeIndex(dim=vec.shape[0]))
        # line-path exact tier: keyed per k
        first = mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        again = mesh.submit_neighbors(list(PERMUTED_LINES), k=4)
        assert again.done()
        assert [r.labels for r in again.result()] == \
            [r.labels for r in first]
        # keyed per k: the k=6 ask is NOT served from the k=4 entry
        # (it may still complete synchronously — its inner vectors-tier
        # submit is itself a legitimate memo hit)
        hits_before = mesh.stats()['memo']['hits']
        other_k = mesh.submit_neighbors(PREDICT_LINES, k=6).result(60)
        assert len(other_k[0].labels) == 6
        assert mesh.stats()['memo']['hits'] == hits_before + 1  # vectors
        # ndarray-path semantic tier: a near-identical single-row query
        # is served from the cached neighbor result; every 8th
        # candidate hit shadow-samples top-1 agreement instead
        live = mesh.submit_neighbors(vec, k=4).result(60)
        serves = 0
        for i in range(10):
            near = vec * np.float32(1.0 + 1e-5 * (i + 1))
            out = mesh.submit_neighbors(near, k=4).result(60)
            assert out[0].labels == live[0].labels
        stats = mesh.stats()['memo']
        assert stats['semantic']['serves'] >= 8
        assert stats['semantic']['samples'] >= 1  # shadow ran live
        assert stats['semantic']['agreement'] == pytest.approx(1.0)
        assert stats['semantic_hits'] >= 1
    finally:
        mesh.close()


class _SloStub:
    """Records SloMonitor observations (serving/slo.py interface)."""

    def __init__(self):
        self.good = 0
        self.bad = 0

    def observe_good(self, latency_s=None, scenario=None):
        self.good += 1

    def observe_bad(self, reason='failed', scenario=None):
        self.bad += 1

    def stats(self):
        return {'good': self.good, 'bad': self.bad}


def test_mesh_neighbors_memo_stands_down_during_canary(model):
    """REVIEW fix: while a canary rollover is in flight, BOTH
    submit_neighbors memo tiers (exact nkey + semantic) must run live,
    like submit() — cache-served duplicates would starve the shadow
    scorer.  Also: cache-served neighbors requests must stay in the
    SLO good-rate denominator."""
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20,
                              memo_semantic_epsilon=0.05)
    try:
        slo = _SloStub()
        mesh._slo = slo
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        mesh.attach_index(_FakeIndex(dim=vec.shape[0]))
        # warm both tiers
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        mesh.submit_neighbors(vec, k=4).result(60)
        # duplicates are hits while no rollover is in flight — and each
        # cache-served request is observed into the SLO good stream
        good_before = slo.good
        warm = mesh.submit_neighbors(PREDICT_LINES, k=4)
        assert warm.done()
        assert slo.good == good_before + 1
        near = vec * np.float32(1.00001)
        sem = mesh.submit_neighbors(near, k=4)
        assert sem.done()
        assert slo.good == good_before + 2
        serves_before = mesh.stats()['memo']['semantic']['serves']
        hits_before = mesh.stats()['memo']['hits']
        # arm a fake in-flight rollover: both tiers stand down
        mesh._rollover = {'replica': None, 'handle': None}
        try:
            rolled = mesh.submit_neighbors(PREDICT_LINES, k=4)
            assert not rolled.done()  # ran live, not cache-served
            rolled.result(60)
            sem_rolled = mesh.submit_neighbors(near, k=4)
            sem_rolled.result(60)
            stats = mesh.stats()['memo']
            assert stats['hits'] == hits_before  # exact tier stood down
            assert stats['semantic']['serves'] == serves_before
        finally:
            mesh._rollover = None
        # rollover concluded: duplicates serve from cache again
        assert mesh.submit_neighbors(PREDICT_LINES, k=4).done()
    finally:
        mesh.close()


# ------------------------------------------ index-generation keying
def test_memo_index_generation_two_axes():
    """ISSUE 19 bugfix: memo generations key on (params step, index
    version).  An index swap bumps ONLY the index axis — neighbor
    entries (pinned to an index generation) invalidate atomically while
    predict entries (index-independent) keep serving; a params bump
    still clears everything."""
    cache = memo_lib.MemoCache(1 << 20)
    try:
        pkey = memo_lib.request_key(['l a,b,c'], 'topk')
        nkey = memo_lib.request_key(['l a,b,c'], 'neighbors', k=4)
        row = [{'s': np.zeros(8)}]
        assert cache.insert(pkey, row, cache.generation)
        assert cache.insert(nkey, row, cache.generation,
                            index_generation=cache.index_generation)
        before = cache.stats()
        assert before['entries'] == 2
        cache.bump_index_generation()
        after = cache.stats()
        assert after['index_generation'] == \
            before['index_generation'] + 1
        assert after['generation'] == before['generation']
        assert cache.lookup(nkey) is None       # index-dependent: gone
        assert cache.lookup(pkey) is not None   # index-independent: warm
        assert after['entries'] == 1
        assert after['evictions'] == 0  # version bump, not eviction
        # byte accounting stays consistent through the selective drop
        assert memory_lib.ledger().bucket_bytes('memo') == \
            cache.stats()['bytes'] > 0
        # an insert carrying a stale index generation is refused (a
        # neighbor request in flight across an index swap can never
        # poison the new cache)
        assert not cache.insert(
            nkey, row, cache.generation,
            index_generation=after['index_generation'] - 1)
        assert cache.insert(nkey, row, cache.generation,
                            index_generation=cache.index_generation)
        # the params axis still clears BOTH kinds of entry
        cache.bump_generation()
        assert cache.lookup(pkey) is None
        assert cache.lookup(nkey) is None
    finally:
        cache.close()


def test_memo_index_bump_drops_semantic_and_refuses_stale():
    """The semantic tier answers from cached index results, so an index
    swap drops it wholesale; a stale-index-generation semantic insert
    is refused."""
    from code2vec_tpu.index.service import neighbors_from_search
    cache = memo_lib.MemoCache(1 << 20, semantic_epsilon=0.05,
                               semantic_shadow_every=100)
    try:
        vec = np.array([1.0, 0.0, 0.0, 0.0], np.float32)
        rows = neighbors_from_search(np.array([[0.9, 0.5]]),
                                     np.array([[2, 0]]),
                                     ['a', 'b', 'c'])
        assert cache.semantic_insert(
            vec[None, :], rows, 4, cache.generation,
            index_generation=cache.index_generation) == 1
        assert cache.semantic_lookup(vec, 4) is not None
        cache.bump_index_generation()
        assert cache.semantic_lookup(vec, 4) is None
        assert cache.semantic_insert(
            vec[None, :], rows, 4, cache.generation,
            index_generation=cache.index_generation - 1) == 0
        assert cache.semantic_lookup(vec, 4) is None
    finally:
        cache.close()


# ------------------------------------------------ index rollover drills
class _WorstIndex(_FakeIndex):
    """Deterministically DISAGREEING candidate: returns the worst-k
    rows, disjoint from _FakeIndex's top-k when k <= n/2."""

    def search(self, vectors, k):
        vectors = np.atleast_2d(np.asarray(vectors, np.float32))
        sims = vectors @ self._store.T
        idx = np.argsort(sims, axis=1)[:, :k]
        return np.take_along_axis(sims, idx, axis=1), idx


class _BoomIndex:
    def search(self, vectors, k):
        raise RuntimeError('candidate index cannot answer')


class _CountingIndex:
    """Search-call counter: a cache-served neighbors answer never
    touches the index, a live one always does.  (.done() alone cannot
    distinguish them — the chain resolves synchronously whenever the
    inner vectors-tier submit is itself a legitimate memo hit.)"""

    def __init__(self, inner):
        self._inner = inner
        self.searches = 0

    def search(self, vectors, k):
        self.searches += 1
        return self._inner.search(vectors, k)

    @property
    def labels(self):
        return self._inner.labels


def test_mesh_index_rollover_swap_invalidates_neighbors_not_predict(
        model):
    """Agreeing candidate swaps in: index version + memo index
    generation bump, every cached neighbor result misses, predict
    entries survive (the model didn't change)."""
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        live = _CountingIndex(_FakeIndex(dim=vec.shape[0]))
        mesh.attach_index(live)
        # warm one neighbor entry and one predict entry
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        searches = live.searches
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert live.searches == searches  # duplicate served from cache
        mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        assert mesh.submit(PREDICT_LINES, tier='topk').done()
        stats = mesh.stats()
        version_before = stats['index_version']
        igen_before = stats['memo']['index_generation']
        gen_before = stats['memo']['generation']
        # same seed -> identical store -> agreement 1.0
        cand = _CountingIndex(_FakeIndex(dim=vec.shape[0]))
        handle = mesh.rollover_index(cand, shadow_queries=1,
                                     min_agreement=0.9)
        # drive the shadow with a DIFFERENT query than the probe key:
        # a driver admitted right after the conclusion would re-insert
        # its own key under the new generation, which must not turn
        # the staleness probe below into a legitimate hit
        for _ in range(12):
            if handle.done():
                break
            mesh.submit_neighbors([PREDICT_LINES[0]], k=4).result(60)
        report = handle.result(timeout=60)
        assert report['swapped'] is True
        assert report['agreement'] == pytest.approx(1.0)
        assert report['index_version'] == version_before + 1
        stats = mesh.stats()
        assert stats['index_version'] == version_before + 1
        assert stats['index_rollover_total'] >= 1
        assert stats['memo']['index_generation'] == igen_before + 1
        assert stats['memo']['generation'] == gen_before  # untouched
        # the pre-swap neighbor entry can never serve again: the
        # duplicate must run LIVE against the new index
        searches = cand.searches
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert cand.searches > searches
        # ... while the predict entry survives the swap
        assert mesh.submit(PREDICT_LINES, tier='topk').done()
    finally:
        mesh.close()


def test_mesh_index_rollover_rollback_keeps_memo_warm(model):
    """Disagreeing candidate rolls back: the serving index, its
    version, and every cached neighbor result stay live — the
    candidate never serves a request."""
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        live = _CountingIndex(_FakeIndex(dim=vec.shape[0]))
        mesh.attach_index(live)
        first = mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        stats = mesh.stats()
        version_before = stats['index_version']
        igen_before = stats['memo']['index_generation']
        handle = mesh.rollover_index(_WorstIndex(dim=vec.shape[0]),
                                     shadow_queries=1,
                                     min_agreement=0.9)
        for _ in range(12):
            if handle.done():
                break
            mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        report = handle.result(timeout=60)
        assert report['swapped'] is False
        assert report['agreement'] == pytest.approx(0.0)
        stats = mesh.stats()
        assert stats['index_version'] == version_before
        assert stats['index_rollover_rollbacks_total'] >= 1
        assert stats['memo']['index_generation'] == igen_before
        # rollback left the neighbor memo warm: the duplicate is
        # answered without a live index search
        searches = live.searches
        warm = mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert live.searches == searches
        assert [r.labels for r in warm] == [r.labels for r in first]
    finally:
        mesh.close()


def test_mesh_index_rollover_candidate_error_and_validation(model):
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        # no index attached yet: nothing to roll over
        with pytest.raises(RuntimeError, match='no index attached'):
            mesh.rollover_index(_FakeIndex(dim=4))
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        live = _FakeIndex(dim=vec.shape[0])
        mesh.attach_index(live)
        with pytest.raises(ValueError, match='shadow_queries'):
            mesh.rollover_index(_FakeIndex(dim=vec.shape[0]),
                                shadow_queries=0)
        with pytest.raises(ValueError, match='candidate index'):
            mesh.rollover_index(object())
        # a candidate that cannot answer the shadow queries must never
        # swap in: the handle raises, the old index keeps serving
        first = mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        handle = mesh.rollover_index(_BoomIndex(), shadow_queries=1)
        deadline = 60
        while not handle.done() and deadline:
            mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
            deadline -= 1
        with pytest.raises(RuntimeError, match='cannot answer'):
            handle.result(timeout=60)
        stats = mesh.stats()
        assert stats['index_version'] == 0
        assert stats['index_rollover_rollbacks_total'] >= 1
        again = mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert [r.labels for r in again] == [r.labels for r in first]
    finally:
        mesh.close()


def test_mesh_neighbors_memo_stands_down_during_index_rollover(model):
    """While an index rollover is armed, submit_neighbors duplicates
    run LIVE (both the exact nkey and semantic tiers) — cache-served
    answers would starve the shadow scorer, exactly like the params
    canary stand-down."""
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20,
                              memo_semantic_epsilon=0.05)
    try:
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        live = _CountingIndex(_FakeIndex(dim=vec.shape[0]))
        mesh.attach_index(live)
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        mesh.submit_neighbors(vec, k=4).result(60)
        searches = live.searches
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert live.searches == searches  # warm: served from cache
        serves_before = mesh.stats()['memo']['semantic']['serves']
        # arm a minimal in-flight rollover state ('concluding' makes
        # the shadow scorer a no-op, so it never concludes under us)
        mesh._index_rollover = {'concluding': True}
        try:
            mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
            assert live.searches == searches + 1  # exact tier ran live
            near = vec * np.float32(1.00001)
            mesh.submit_neighbors(near, k=4).result(60)
            assert live.searches == searches + 2  # semantic ran live
            stats = mesh.stats()['memo']
            assert stats['semantic']['serves'] == serves_before
        finally:
            mesh._index_rollover = None
        # concluded: duplicates serve from cache again
        searches = live.searches
        mesh.submit_neighbors(PREDICT_LINES, k=4).result(60)
        assert live.searches == searches
    finally:
        mesh.close()


def test_mesh_semantic_tier_defaults_off(model):
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              max_delay_ms=0.0,
                              memo_cache_bytes=32 << 20)
    try:
        vec = mesh.predict([PREDICT_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        mesh.attach_index(_FakeIndex(dim=vec.shape[0]))
        mesh.submit_neighbors(vec, k=4).result(60)
        mesh.submit_neighbors(vec * np.float32(1.00001),
                              k=4).result(60)
        stats = mesh.stats()['memo']
        assert stats['semantic']['epsilon'] == 0.0
        assert stats['semantic']['rows'] == 0
        assert stats['semantic']['serves'] == 0
    finally:
        mesh.close()
