"""End-to-end CLI dispatch tests (reference code2vec.py:16-38 flows).

One model is trained once per module and shared by the eval/export/release
tests (training is the slow part: jit compile + 2 epochs).
"""
import pytest

from code2vec_tpu.cli import main
from tests.test_train_overfit import make_dataset


@pytest.fixture(scope='module')
def trained_model(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp('cli')
    prefix = make_dataset(tmp_path)
    save = tmp_path / 'models' / 'm' / 'saved_model'
    main(['--data', str(prefix), '--test', str(tmp_path / 'tiny.val.c2v'),
          '--framework', 'jax', '--dtype', 'float32', '--batch-size', '16',
          '--epochs', '2', '--save', str(save), '-v', '0'])
    return tmp_path, save


def test_cli_train_eval_save(trained_model):
    tmp_path, save = trained_model
    assert (tmp_path / 'models' / 'm' / 'dictionaries.bin').exists()
    assert (tmp_path / 'models' / 'm' / 'saved_model__entire-model').is_dir()


def test_cli_eval_only_and_release(trained_model):
    tmp_path, save = trained_model
    main(['--load', str(save), '--test', str(tmp_path / 'tiny.val.c2v'),
          '--framework', 'jax', '--dtype', 'float32', '--batch-size', '16',
          '-v', '0'])
    main(['--load', str(save), '--release', '--framework', 'jax',
          '--dtype', 'float32', '-v', '0'])
    assert (tmp_path / 'models' / 'm' / 'saved_model__only-weights').is_dir()


def test_cli_w2v_export(trained_model):
    tmp_path, save = trained_model
    w2v = tmp_path / 'tokens.w2v'
    t2v = tmp_path / 'targets.w2v'
    main(['--load', str(save), '--save_word2v', str(w2v),
          '--save_target2v', str(t2v), '--framework', 'jax',
          '--dtype', 'float32', '-v', '0'])
    assert w2v.exists() and t2v.exists()
    header = w2v.read_text().splitlines()[0].split()
    assert int(header[1]) == 128  # token embedding dim (default)


def test_cli_bulk_vectors_export(trained_model):
    """--bulk-vectors: the serving/bulk.py streaming path (vectors-only
    program over eval-sized batches), no --test needed."""
    import shutil
    tmp_path, save = trained_model
    corpus = tmp_path / 'bulk.c2v'
    shutil.copyfile(tmp_path / 'tiny.val.c2v', corpus)
    main(['--load', str(save), '--bulk-vectors', str(corpus),
          '--framework', 'jax', '--dtype', 'float32', '--batch-size', '16',
          '-v', '0'])
    vectors = corpus.with_name('bulk.c2v.vectors')
    assert vectors.exists()
    lines = vectors.read_text().splitlines()
    assert len(lines) == 16  # every val example has a valid context
    assert len(lines[0].split()) == 384  # code vector size


def test_cli_export_code_vectors(trained_model):
    tmp_path, save = trained_model
    main(['--load', str(save), '--test', str(tmp_path / 'tiny.val.c2v'),
          '--export_code_vectors', '--framework', 'jax', '--dtype', 'float32',
          '--batch-size', '16', '-v', '0'])
    vectors = tmp_path / 'tiny.val.c2v.vectors'
    assert vectors.exists()
    lines = vectors.read_text().splitlines()
    assert len(lines) == 16  # val examples
    assert len(lines[0].split()) == 384  # code vector size


def test_cli_build_index_and_query_neighbors(trained_model):
    """--build-index + --query-neighbors + --export_vocab_vectors: the
    index dispatch chain through cli.main (ISSUE 5)."""
    import json
    tmp_path, save = trained_model
    corpus = tmp_path / 'tiny.val.c2v'
    main(['--load', str(save), '--framework', 'jax', '--dtype', 'float32',
          '--batch-size', '16', '-v', '0',
          '--build-index', str(corpus), '--vectors-dtype', 'float16',
          '--query-neighbors', str(corpus), '--neighbors-k', '3',
          '--export_vocab_vectors', str(tmp_path / 'vocab')])
    assert (corpus.with_name('tiny.val.c2v.vecindex') / 'meta.json'
            ).exists()
    assert (tmp_path / 'vocab.tokens.txt').exists()
    assert (tmp_path / 'vocab.targets.txt').exists()
    out = corpus.with_name('tiny.val.c2v.neighbors.jsonl')
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(records) == 16
    top = records[0]['neighbors'][0]
    # a corpus row queried against its own index is its own neighbor
    assert top['row'] == 0 and abs(top['score'] - 1.0) < 1e-2
    assert top['label'] == records[0]['name']


def test_cli_requires_train_or_load():
    with pytest.raises(ValueError):
        main(['-v', '0'])


def test_cli_bad_mesh_is_clear_error():
    with pytest.raises(ValueError, match='DATAxMODEL'):
        main(['--data', 'x', '--mesh', 'bogus', '-v', '0'])
