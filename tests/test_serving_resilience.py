"""Serving resilience drills (serving/engine.py + serving/errors.py,
ISSUE 7): deterministic overload (bounded queue + injected
``slow_dispatch`` -> shed/expiry/degrade with typed errors), canaried
zero-downtime checkpoint rollover with ZERO post-warmup XLA compiles,
fail-fast vs drain close semantics, and the submit/close/attach_index
stress test. The extractor-bridge drills live in
tests/test_extractor_resilience.py; the fault-window grammar they all
ride is unit-tested here too."""
import threading
import time

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving.errors import (DeadlineExceeded, EngineClosed,
                                         EngineOverloaded, ServingError)
from tests.test_train_overfit import make_dataset

PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]


@pytest.fixture(autouse=True)
def clear_fault_plan():
    """The plan is process-global by design: every test starts and ends
    disarmed."""
    faults.configure('')
    yield
    faults.configure('')


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving_res'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16')
    return Code2VecModel(config)


def _wait_until(predicate, timeout=10.0, what='condition'):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return
        time.sleep(0.001)
    raise AssertionError('timed out waiting for %s' % what)


def _stall_dispatcher(engine, line):
    """Submit one plug request and wait until the dispatcher POPPED it —
    at which point it is inside the injected ``slow_dispatch`` stall and
    everything submitted next queues behind the stall deterministically.
    Returns the plug future."""
    plug = engine.submit([line], tier='topk')
    # queue depth drops to 0 at pop time, before the stall sleep
    _wait_until(lambda: engine.queue_depth.snapshot() == 0,
                what='dispatcher to pop the plug batch')
    return plug


# ----------------------------------------------------- fault-window grammar
def test_fault_window_parse():
    assert faults.parse_spec('extractor_crash@call=0..2') == {
        'extractor_crash': (0, 2)}
    assert faults.parse_spec(
        'slow_dispatch@req=1..1,nan_loss@step=7') == {
            'slow_dispatch': (1, 1), 'nan_loss': 7}
    with pytest.raises(ValueError):
        faults.parse_spec('slow_dispatch@req=3..1')   # hi < lo
    with pytest.raises(ValueError):
        faults.parse_spec('slow_dispatch@req=-1..2')  # negative lo
    with pytest.raises(ValueError):
        faults.parse_spec('no_such_point@call=0..1')  # unknown point


def test_fault_window_fires_every_count_inside_then_disarms():
    faults.configure('slow_dispatch@req=1..2')
    fired = [faults.maybe_fire('slow_dispatch') for _ in range(5)]
    assert fired == [False, True, True, False, False]


def test_fault_single_shot_still_single_shot():
    faults.configure('slow_dispatch@req=1')
    fired = [faults.maybe_fire('slow_dispatch') for _ in range(4)]
    assert fired == [False, True, False, False]


# ---------------------------------------------------------- admission drills
def test_reject_all_drill_sheds_typed(model):
    with model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                              queue_bound=64) as engine:
        faults.configure('reject_all@req=0..1')
        for _ in range(2):
            with pytest.raises(EngineOverloaded):
                engine.submit(PREDICT_LINES[:1], tier='topk')
        # window passed: traffic flows again
        results = engine.predict(PREDICT_LINES[:1], tier='topk',
                                 timeout=60)
        assert results[0].topk_predicted_words
        assert engine.stats()['shed_total'] == 2


def test_drain_estimate_sheds_undeliverable_deadline(model):
    with model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                              queue_bound=64) as engine:
        engine.predict(PREDICT_LINES[:1], tier='topk', timeout=60)
        # pin the observed service rate at 1 row/s: any multi-row
        # deadlined request is then hopeless at admission
        with engine._lock:
            engine._service_rows_per_s = 1.0
        with pytest.raises(EngineOverloaded, match='drain estimate'):
            engine.submit(PREDICT_LINES, tier='topk', deadline_ms=100.0)
        # no deadline -> no drain check: the same submission is admitted
        assert len(engine.predict(PREDICT_LINES, tier='topk',
                                  timeout=60)) == 3


def test_service_rate_aggregates_parallel_completions(model):
    """Regression: with SERVING_DECODE_WORKERS > 1, near-simultaneous
    batch completions span microseconds — a per-completion-gap rate
    would explode by orders of magnitude and admit deadlines the queue
    cannot meet. The estimator aggregates over a sliding window and
    keeps the (low-biased) sojourn seed until the window spans a
    measurable interval."""
    import types
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                  warmup=False)
    try:
        req = types.SimpleNamespace(t_enqueue=time.perf_counter() - 1.0)
        engine._note_service(100, [req])  # seeds ~100 rows/s (sojourn)
        for _ in range(8):                # a burst microseconds apart
            engine._note_service(100, [req])
        rate = engine._service_rows_per_s
        assert rate < 1000, 'burst inflated the service rate: %r' % rate
        # once the window spans real time it reports honest throughput
        time.sleep(0.06)
        engine._note_service(100, [req])
        assert engine._service_rows_per_s > rate
    finally:
        engine.close()


def test_oversize_request_admitted_alone_then_bounds_queue(model):
    """The admission bound rejects pile-up, not request size: a single
    request larger than the whole bound keeps submit's oversize-
    splitting contract on an idle queue, and while it drains everything
    behind it is shed."""
    lines = PREDICT_LINES * 2  # 6 rows > bound
    bound = 4
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                  queue_bound=bound)
    try:
        faults.configure('slow_dispatch@req=0..63')
        plug = _stall_dispatcher(engine, PREDICT_LINES[0])
        # queue is empty (plug already popped): the oversize is admitted
        oversize = engine.submit(lines, tier='topk')
        # ... and now ITS size bounds the queue: pile-up behind it sheds
        with pytest.raises(EngineOverloaded):
            engine.submit(PREDICT_LINES[:1], tier='topk')
        faults.configure('')
        results = oversize.result(timeout=60)
        assert [r.original_name for r in results] == \
            [model.predict([line])[0].original_name for line in lines]
        plug.result(timeout=60)
        assert engine.stats()['shed_total'] == 1
    finally:
        faults.configure('')
        engine.close()


def test_overload_drill_sheds_expires_and_results_bit_identical(model):
    """The ISSUE 7 acceptance drill: bounded queue + injected
    ``slow_dispatch``; an open-loop burst sheds at admission and expires
    deadlined queued work with typed errors, queue depth never exceeds
    the bound, and every ADMITTED request's results are bit-identical to
    the unloaded path."""
    line = PREDICT_LINES[0]
    unloaded = model.predict([line])[0]
    bound = 8
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                  queue_bound=bound)
    try:
        faults.configure('slow_dispatch@req=0..63')
        plug = _stall_dispatcher(engine, line)
        # 4 deadlined requests queue behind the >=250ms stall with a
        # 60ms SLO: they MUST expire at pop time, never dispatch
        doomed = [engine.submit([line], tier='topk', deadline_ms=60.0)
                  for _ in range(4)]
        # open-loop burst of undeadlined singles: 4 more fill the bound
        # (4 deadlined rows are already queued), the rest shed
        admitted, shed = [], 0
        for _ in range(10):
            try:
                admitted.append(engine.submit([line], tier='topk'))
            except EngineOverloaded:
                shed += 1
        assert shed == 6 and len(admitted) == 4
        peak = engine.stats()['queue_peak_rows']
        assert peak <= bound, 'queue overshot the bound: %d' % peak
        for future in doomed:
            assert isinstance(future.exception(timeout=60),
                              DeadlineExceeded)
        for future in admitted + [plug]:
            (result,) = future.result(timeout=60)
            assert result.original_name == unloaded.original_name
            assert result.topk_predicted_words == \
                unloaded.topk_predicted_words
            np.testing.assert_array_equal(
                result.topk_predicted_words_scores,
                unloaded.topk_predicted_words_scores)
        stats = engine.stats()
        assert stats['shed_total'] == 6
        assert stats['expired_total'] == 4
    finally:
        faults.configure('')
        engine.close()


def test_degradation_ladder_downgrades_full_under_sustained_load(model):
    """Past 75% queue fill the ladder serves 'full' as 'topk' (typed in
    _DEGRADE_LADDER), and drops back once the queue drains."""
    line = PREDICT_LINES[0]
    engine = model.serving_engine(
        tiers=('topk', 'attention', 'full'), max_delay_ms=0.0,
        queue_bound=8)
    try:
        faults.configure('slow_dispatch@req=0..63')
        plug = _stall_dispatcher(engine, line)
        backlog = [engine.submit([line], tier='topk') for _ in range(6)]
        # 6 queued + 1 reserved = 7/8 fill >= 0.75: overload level 2
        degraded = engine.submit([line], tier='full')
        assert engine.stats()['overload_level'] == 2
        assert engine.stats()['degraded_total'] == 1
        (result,) = degraded.result(timeout=60)
        # served as bare topk: no attention decode, no code vector
        assert result.attention_per_context == {}
        assert result.code_vector is None
        for future in backlog + [plug]:
            future.result(timeout=60)
    finally:
        faults.configure('')
        engine.close()
    # a fresh unloaded engine serves 'full' at full fidelity again
    with model.serving_engine(tiers=('topk', 'full'),
                              max_delay_ms=0.0) as calm:
        (result,) = calm.predict([line], tier='full', timeout=60)
        assert result.attention_per_context != {}
        assert result.code_vector is not None


# ------------------------------------------------------------ close semantics
def test_default_close_fails_queued_futures_typed(model):
    line = PREDICT_LINES[0]
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0)
    faults.configure('slow_dispatch@req=0..63')
    plug = _stall_dispatcher(engine, line)
    queued = [engine.submit([line], tier='topk') for _ in range(3)]
    engine.close()
    # the in-flight batch still delivers; the queued ones fail typed
    assert plug.result(timeout=60)[0].topk_predicted_words
    for future in queued:
        assert isinstance(future.exception(timeout=10), EngineClosed)
    with pytest.raises(EngineClosed):
        engine.submit([line], tier='topk')
    assert not engine._dispatcher.is_alive()


def test_close_drain_serves_everything_admitted(model):
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=10_000.0)
    # parked in the coalescing window: nothing dispatched yet
    futures = [engine.submit([line], tier='topk')
               for line in PREDICT_LINES]
    engine.close(drain=True)
    for future, line in zip(futures, PREDICT_LINES):
        (result,) = future.result(timeout=60)
        assert result.topk_predicted_words == \
            model.predict([line])[0].topk_predicted_words
    assert not engine._dispatcher.is_alive()


def test_concurrent_submit_close_attach_index_stress(model):
    """Satellite: racing submit()/close()/attach_index() must resolve
    EVERY returned future (result or typed ServingError) and leak no
    dispatcher thread."""

    class _FakeIndex:
        labels = np.array(['m'], dtype=object)

        def search(self, vectors, k):
            n = vectors.shape[0]
            return (np.zeros((n, k), np.float32),
                    np.zeros((n, k), np.int64))

    engine = model.serving_engine(tiers=('topk', 'vectors'),
                                  max_delay_ms=1.0)
    futures = []
    futures_lock = threading.Lock()
    begun = threading.Barrier(6)  # 4 submitters + attacher + main

    def submitter(i):
        begun.wait()
        while True:
            try:
                future = engine.submit(
                    [PREDICT_LINES[i % len(PREDICT_LINES)]], tier='topk')
            except EngineClosed:
                return
            except EngineOverloaded:
                continue
            with futures_lock:
                futures.append(future)

    def attacher():
        begun.wait()
        for _ in range(50):
            engine.attach_index(_FakeIndex())
            time.sleep(0.001)

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(4)] + [threading.Thread(target=attacher)]
    for thread in threads:
        thread.start()
    begun.wait()
    time.sleep(0.25)  # let traffic flow
    engine.close()
    for thread in threads:
        thread.join(timeout=60)
        assert not thread.is_alive()
    assert futures, 'stress produced no traffic'
    unresolved = [f for f in futures if not f.done()]
    assert not unresolved, '%d futures left unresolved' % len(unresolved)
    for future in futures:
        exc = future.exception()
        assert exc is None or isinstance(exc, ServingError), repr(exc)
    assert not engine._dispatcher.is_alive()
    assert not any(t.name.startswith('serving-dispatch')
                   for t in threading.enumerate())


# ------------------------------------------------------------------ rollover
def test_rollover_canary_swap_rollback_and_zero_compiles(model):
    """Acceptance: a LIVE load_params rollover (canary pass -> swap, and
    canary fail -> rollback) adds ZERO XLA compiles after warmup — the
    shadow dispatches reuse the warm ladder."""
    import jax
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    lines = PREDICT_LINES
    core.reset()
    core.enable()
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0)
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        # candidates are built BEFORE the compile snapshot: the -leaf op
        # itself compiles a (tiny) program that is not rollover machinery
        same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
        broken = jax.tree_util.tree_map(lambda leaf: -leaf, model.params)
        import jax as _jax
        _jax.block_until_ready(broken)
        before = engine.predict(lines, tier='topk', timeout=60)
        warm_compiles = compiles.value

        # ---- canary PASS: identical params agree 100% -> swap
        handle = engine.load_params(same, canary_batches=2,
                                    min_agreement=0.9)
        for _ in range(3):  # live traffic feeds the canary
            engine.predict(lines, tier='topk', timeout=60)
        report = handle.result(timeout=60)
        assert report['swapped'] is True
        assert report['agreement'] == pytest.approx(1.0)
        assert report['rows'] >= 2 * len(lines)
        assert engine.params is same

        # ---- canary FAIL: negated params disagree -> rollback
        handle = engine.load_params(broken, canary_batches=2,
                                    min_agreement=0.9)
        for _ in range(3):
            engine.predict(lines, tier='topk', timeout=60)
        report = handle.result(timeout=60)
        assert report['swapped'] is False
        assert report['agreement'] < 0.9
        assert engine.params is same  # rollback kept the serving set
        stats = engine.stats()
        assert stats['rollover_total'] == 1
        assert stats['rollover_rollbacks_total'] == 1

        # ---- the whole double rollover compiled NOTHING new
        assert compiles.value - warm_compiles == 0, (
            '%d XLA compiles during live rollover'
            % (compiles.value - warm_compiles))
        after = engine.predict(lines, tier='topk', timeout=60)
        for a, b in zip(before, after):
            assert a.topk_predicted_words == b.topk_predicted_words
            np.testing.assert_array_equal(a.topk_predicted_words_scores,
                                          b.topk_predicted_words_scores)
    finally:
        engine.close()
        core.disable()
        core.reset()


def test_canary_rejected_on_vectors_only_engine(model):
    """A vectors-only engine produces no top-1 predictions to canary
    against: an armed canary would never conclude and wedge every later
    rollover, so load_params must reject it loudly (canary_batches=0
    still swaps)."""
    import jax
    engine = model.serving_engine(tiers=('vectors',), max_delay_ms=0.0,
                                  warmup=False)
    try:
        same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
        with pytest.raises(RuntimeError, match='vectors-only'):
            engine.load_params(same, canary_batches=2)
        report = engine.load_params(same, canary_batches=0).result(10)
        assert report['swapped'] is True
    finally:
        engine.close()


def test_rollover_api_guards(model):
    import jax
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0,
                                  warmup=False)
    same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
    # no param source on this engine config? the module fixture has no
    # save/load path only when neither is set — here TRAIN prefix only,
    # so step refs must fail loudly while pytrees work
    if engine._param_source is None:
        with pytest.raises(RuntimeError, match='param source'):
            engine.load_params(7)
        with pytest.raises(RuntimeError, match='param source'):
            engine.follow_checkpoints(poll_secs=1.0)
    armed = engine.load_params(same, canary_batches=5)
    with pytest.raises(RuntimeError, match='already in flight'):
        engine.load_params(same, canary_batches=5)
    engine.close()
    # close() fails the armed canary typed, and post-close loads reject
    assert isinstance(armed.exception(timeout=10), EngineClosed)
    with pytest.raises(EngineClosed):
        engine.load_params(same, canary_batches=0)


def test_param_source_step_rollover_and_follow(tmp_path_factory):
    """End-to-end param source: retained steps resolve by number, the
    newest-step poll sees new saves, and --serve-follow-checkpoints
    rolls them in live (canary disabled for determinism)."""
    import jax.numpy as jnp
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('rollsrc'))
    save_path = str(tmp_path_factory.mktemp('rollsrc_model') / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_CANARY_BATCHES=0)
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)  # step 0
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0)
    try:
        source = engine._param_source
        assert source is not None
        assert source.newest_step() == 0
        report = engine.load_params(0).result(timeout=60)
        assert report['swapped'] is True and report['step'] == 0
        assert engine.stats()['params_step'] == 0
        with pytest.raises(ValueError, match='step 7'):
            engine.load_params(7).result(timeout=60)
        # a newer save appears; the follow poller rolls it in live
        newer = model.state._replace(step=jnp.asarray(9, jnp.int32))
        model.save(state=newer, epoch=0, wait=True)
        assert source.newest_step() == 9
        engine.follow_checkpoints(poll_secs=0.05)
        _wait_until(lambda: engine.stats()['params_step'] == 9,
                    timeout=30.0, what='follow-checkpoints rollover')
    finally:
        engine.close()
        model.close_stores()


def test_follow_single_poller_and_transient_load_retry(tmp_path_factory):
    """Regressions: concurrent follow_checkpoints() calls must start
    exactly ONE poller thread (the check-and-assign is locked; close()
    only joins the stored one), and a step whose restore fails
    transiently — a poll racing an in-progress checkpoint write, a
    filesystem blip — must stay eligible for the next poll instead of
    being marked attempted and skipped forever."""
    import jax.numpy as jnp
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('followretry'))
    save_path = str(tmp_path_factory.mktemp('followretry_model') / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_CANARY_BATCHES=0)
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)  # step 0
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0)
    try:
        source = engine._param_source
        real_load = source.load
        blips = {'left': 2}

        def flaky_load(ref):
            if blips['left'] > 0:
                blips['left'] -= 1
                raise IOError('transient restore blip')
            return real_load(ref)

        source.load = flaky_load
        newer = model.state._replace(step=jnp.asarray(9, jnp.int32))
        model.save(state=newer, epoch=0, wait=True)
        barrier = threading.Barrier(8)

        def race():
            barrier.wait()
            engine.follow_checkpoints(poll_secs=0.05)

        workers = [threading.Thread(target=race) for _ in range(8)]
        for w in workers:
            w.start()
        for w in workers:
            w.join()
        pollers = [t for t in threading.enumerate()
                   if t.name == 'serving-follow' and t.is_alive()]
        assert len(pollers) == 1, \
            'duplicate follow pollers: %r' % pollers
        # the first two polls hit the blip; step 9 must still roll in
        _wait_until(lambda: engine.stats()['params_step'] == 9,
                    timeout=30.0, what='retry after transient load blip')
        assert blips['left'] == 0
    finally:
        engine.close()
        model.close_stores()
    # close() joined the (single) registered poller
    assert not any(t.name == 'serving-follow' and t.is_alive()
                   for t in threading.enumerate())
    with pytest.raises(EngineClosed):
        engine.follow_checkpoints(poll_secs=1.0)


def test_canary_timeout_rolls_back_on_vectors_only_traffic(model):
    """A canary armed on a MIXED-tier engine passes the vectors-only
    guard, but pure vectors traffic (submit_neighbors) never scores a
    top-1 comparison: without the timeout the rollover would never
    decide and every later load_params would raise 'already in
    flight' forever."""
    import jax
    engine = model.serving_engine(tiers=('vectors', 'topk'),
                                  max_delay_ms=0.0, warmup=False)
    try:
        same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
        handle = engine.load_params(same, canary_batches=2)
        engine.canary_timeout_s = 0.05
        time.sleep(0.1)
        # vectors dispatches shadow-score nothing, but DO check the age
        engine.predict(PREDICT_LINES, tier='vectors', timeout=60)
        report = handle.result(timeout=10)
        assert report['swapped'] is False
        assert 'timed out' in report['reason']
        assert engine.rollover_rollbacks_total.value == 1
        # the wedge is gone: a fresh rollover proceeds
        assert engine.load_params(
            same, canary_batches=0).result(10)['swapped'] is True
    finally:
        engine.close()


def test_follow_baseline_skips_already_serving_step(tmp_path_factory):
    """The follow poller starts baselined at the restored step: its
    first poll must NOT pay a restore + canary to re-roll the params
    the engine is already serving, while genuinely newer steps still
    roll in."""
    import jax.numpy as jnp
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('followbase'))
    save_path = str(tmp_path_factory.mktemp('followbase_model') / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_CANARY_BATCHES=0)
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)  # step 0
    engine = model.serving_engine(tiers=('topk',), max_delay_ms=0.0)
    try:
        assert engine.stats()['params_step'] == 0  # wired baseline
        engine.follow_checkpoints(poll_secs=0.05)
        time.sleep(0.5)  # several polls over the already-serving step
        assert engine.rollover_total.value == 0, \
            'first poll re-rolled the already-serving step'
        newer = model.state._replace(step=jnp.asarray(3, jnp.int32))
        model.save(state=newer, epoch=0, wait=True)
        _wait_until(lambda: engine.stats()['params_step'] == 3,
                    timeout=30.0, what='follow rollover of newer step')
        assert engine.rollover_total.value == 1
    finally:
        engine.close()
        model.close_stores()
