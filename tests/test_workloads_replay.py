"""Scenario traffic plane, mesh half (ISSUE 20, WORKLOADS.md): the
recorded-then-replayed round trip through a live ServingMesh, replay
determinism (bit-identical admitted set AND bit-identical results), a
mixed Java+C# stream with ZERO post-warmup compiles, retrieval-blend
weight=0 bit-parity against the plain softmax path, and the typed
no-index fallback.  Tier-1 drills use tiny in-code profiles; the full
synthetic-corpus replay is slow-marked (tests/test_bench_smoke.py
budgets this file's tier-1 wall time)."""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from code2vec_tpu.config import Config  # noqa: E402
from code2vec_tpu.telemetry import core as tele_core  # noqa: E402
from code2vec_tpu.telemetry.jit_tracker import \
    install_compile_listener  # noqa: E402
from code2vec_tpu.workloads import blend as blend_lib  # noqa: E402
from code2vec_tpu.workloads import profile as profile_lib  # noqa: E402
from code2vec_tpu.workloads import replay as replay_lib  # noqa: E402
from tests.test_serving_memo import _FakeIndex  # noqa: E402
from tests.test_train_overfit import make_dataset  # noqa: E402

JAVA_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0',
]
CSHARP_LINES = [
    'set|b tokb0,pA,tokb1',
    'read|d tokd0,pB,tokd1 tokd1,pC,tokd2',
]


def _mixed_records():
    """A tiny in-code mixed Java+C# profile covering all three entry
    points (predict / blend), with labels from the line heads."""
    records = []
    t = 0.0
    for line in JAVA_LINES:
        records.append({'t': t, 'scenario': 'java_naming',
                        'language': 'java', 'lines': [line],
                        'label': line.split(' ', 1)[0]})
        t += 0.001
    for line in CSHARP_LINES:
        records.append({'t': t, 'scenario': 'csharp_naming',
                        'language': 'csharp', 'lines': [line],
                        'label': line.split(' ', 1)[0]})
        t += 0.001
    for line, language in ((JAVA_LINES[0], 'java'),
                           (CSHARP_LINES[0], 'csharp')):
        records.append({'t': t, 'scenario': 'retrieval_naming',
                        'language': language, 'lines': [line],
                        'label': line.split(' ', 1)[0],
                        'weight': 0.5, 'k': 4})
        t += 0.001
    return records


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('workloads_replay'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8',
        # SLO targets ON so the replay report carries per-scenario
        # error-budget burn attribution (generous: burn math, not
        # alert flakes, is under test)
        SERVING_SLO_AVAILABILITY=0.5, SERVING_SLO_P99_MS=60_000.0)
    return Code2VecModel(config)


@pytest.fixture(scope='module')
def mesh(model):
    """One warmed mesh with an attached index, shared by the tier-1
    drills (mesh warmup is the expensive part; the memo serving
    bit-identical answers across tests is the tier's contract)."""
    tele_core.reset()
    tele_core.enable()
    assert install_compile_listener()
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              memo_cache_bytes=8 << 20)
    try:
        vec = mesh.predict([JAVA_LINES[0]], tier='vectors',
                           timeout=60)[0].code_vector
        mesh.attach_index(_FakeIndex(dim=vec.shape[0]))
        yield mesh
    finally:
        mesh.close()
        tele_core.disable()
        tele_core.reset()


# ------------------------------------------------ typed no-index path
def test_blend_fallback_without_index(model):
    """No attached index degrades TYPED (source='softmax_fallback' +
    counter), never raises — a profile with retrieval_naming records
    replays against an index-less mesh and still answers."""
    tele_core.reset()
    tele_core.enable()
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              memo_cache_bytes=4 << 20)
    try:
        rows = mesh.submit_blended(JAVA_LINES).result(60)
        assert [r.source for r in rows] == \
            [blend_lib.SOURCE_FALLBACK] * len(JAVA_LINES)
        snap = tele_core.registry().snapshot()
        assert snap.get('mesh/blend_fallback_total', 0) >= 1
        # the fallback rows still rank: softmax words/scores untouched
        for row in rows:
            np.testing.assert_array_equal(
                row.predicted_scores, row.base.topk_predicted_words_scores)
        with pytest.raises(ValueError):
            mesh.submit_blended(JAVA_LINES, weight=1.5)
    finally:
        mesh.close()
        tele_core.disable()
        tele_core.reset()


# ------------------------------------------------- weight=0 bit-parity
def test_blend_weight_zero_is_bit_identical_to_softmax(mesh):
    """The A/B baseline contract: weight=0 short-circuits to the plain
    submit path and wraps the UNTOUCHED result — bit-identical scores,
    source='softmax' (index attached, so NOT the fallback)."""
    plain = mesh.submit(CSHARP_LINES).result(60)
    wrapped = mesh.submit_blended(CSHARP_LINES, weight=0.0).result(60)
    assert len(wrapped) == len(plain)
    for blend_row, base_row in zip(wrapped, plain):
        assert blend_row.source == blend_lib.SOURCE_SOFTMAX
        assert blend_row.predicted_words == \
            list(base_row.topk_predicted_words)
        np.testing.assert_array_equal(
            blend_row.predicted_scores,
            base_row.topk_predicted_words_scores)
    # a real blend on the same mesh re-ranks with neighbor votes and
    # says so
    blended = mesh.submit_blended(CSHARP_LINES, weight=0.5,
                                  k=4).result(60)
    assert all(r.source == blend_lib.SOURCE_BLEND for r in blended)
    assert all(r.neighbors is not None for r in blended)


# ------------------------------------- recorded-then-replayed round trip
def test_record_then_replay_round_trip(mesh, tmp_path):
    """Live traffic -> admission tap -> durable profile -> replay of
    that profile against the same mesh, joined to a per-scenario x
    per-language report."""
    recorder = profile_lib.ProfileRecorder()
    mesh.record_traffic(recorder)
    try:
        futures = [
            mesh.submit([JAVA_LINES[0]], scenario='java_naming',
                        language='java'),
            mesh.submit([CSHARP_LINES[0]], scenario='csharp_naming',
                        language='csharp'),
            mesh.submit_blended([JAVA_LINES[1]], weight=0.5, k=4,
                                scenario='retrieval_naming',
                                language='java'),
            mesh.submit([CSHARP_LINES[1]]),  # unlabeled -> fallback name
        ]
        for future in futures:
            future.result(60)
    finally:
        mesh.record_traffic(None)
    records = recorder.records()
    assert len(records) == 4
    # ONE tap record per caller-visible request: the blend's inner
    # submit + submit_neighbors legs must not re-record
    assert [r['scenario'] for r in records] == \
        ['java_naming', 'csharp_naming', 'retrieval_naming',
         'softmax_naming']
    assert records[2]['weight'] == 0.5 and records[2]['k'] == 4
    # labels recovered from the context-line heads at admission
    assert records[0]['label'] == 'get|a'
    path = str(tmp_path / 'recorded.jsonl')
    assert recorder.save(path) == 4
    header, loaded = profile_lib.read_profile(path)
    assert header['source'] == 'recorded'
    report = replay_lib.replay(mesh, loaded, pace=False)
    assert report['admitted'] == 4
    cells = report['scenarios']
    assert cells['java_naming']['java']['delivered'] == 1
    assert cells['csharp_naming']['csharp']['delivered'] == 1
    assert cells['retrieval_naming']['java']['delivered'] == 1
    assert cells['softmax_naming']['-']['delivered'] == 1
    # every labeled record scored against its recorded label
    for name in ('java_naming', 'csharp_naming', 'retrieval_naming'):
        cell = next(iter(cells[name].values()))
        assert cell['scored'] == 1
        assert 0.0 <= cell['f1'] <= 1.0
    # identical requests were served once live already: the replay is
    # memo traffic, visible in the per-scenario hit rate
    assert cells['java_naming']['java']['memo_hit_rate'] == 1.0
    # per-scenario SLO burn attribution rides the report
    assert 'java_naming' in report['slo']['scenarios']
    assert report['slo']['scenarios']['java_naming']['good'] >= 1


# ----------------------------------------------------- determinism drill
def test_replay_determinism_bit_identical_results(mesh):
    """Same profile + same seed => the identical admitted set (plan
    fingerprint) AND bit-identical per-request results — the memo
    tier's cache-serve bit-identity extended to whole replays."""
    records = _mixed_records()
    plan_a = replay_lib.plan_replay(records, rate_scale=4.0, seed=11)
    plan_b = replay_lib.plan_replay(records, rate_scale=4.0, seed=11)
    assert replay_lib.admitted_fingerprint(plan_a) == \
        replay_lib.admitted_fingerprint(plan_b)

    def run_words_scores():
        out = []
        for _t, record in plan_a:
            if record['scenario'] == 'retrieval_naming':
                rows = mesh.submit_blended(
                    record['lines'], weight=record['weight'],
                    k=record['k'], scenario='retrieval_naming',
                    language=record.get('language')).result(60)
                out.append((list(rows[0].predicted_words),
                            np.asarray(rows[0].predicted_scores)))
            else:
                rows = mesh.submit(
                    record['lines'], scenario=record['scenario'],
                    language=record.get('language')).result(60)
                out.append((list(rows[0].topk_predicted_words),
                            np.asarray(
                                rows[0].topk_predicted_words_scores)))
        return out

    first = run_words_scores()
    second = run_words_scores()
    for (words_a, scores_a), (words_b, scores_b) in zip(first, second):
        assert words_a == words_b
        np.testing.assert_array_equal(scores_a, scores_b)
    # the aggregated reports agree on every deterministic field
    rep_a = replay_lib.replay(mesh, records, rate_scale=4.0, seed=11,
                              pace=False)
    rep_b = replay_lib.replay(mesh, records, rate_scale=4.0, seed=11,
                              pace=False)
    assert rep_a['fingerprint'] == rep_b['fingerprint']
    for name, langs in rep_a['scenarios'].items():
        for language, cell in langs.items():
            other = rep_b['scenarios'][name][language]
            for key in ('requests', 'delivered', 'shed', 'scored',
                        'exact_match', 'f1'):
                assert cell[key] == other[key], (name, language, key)


# ------------------------------------- mixed stream, zero new compiles
def test_mixed_stream_zero_postwarm_compiles(mesh):
    """Java and C# records ride the SAME compiled buckets (path
    contexts are language-agnostic at serve time): a mixed-scenario
    steady state triggers zero post-warmup compiles (acceptance)."""
    compiles = tele_core.registry().counter('jit/compiles_total')
    # warm every entry path the mixed profile uses (shared mesh is
    # already warm from earlier drills; this makes the test order-
    # independent rather than relying on it)
    mesh.submit([JAVA_LINES[0]]).result(60)
    mesh.submit_blended([JAVA_LINES[0]], weight=0.5, k=4).result(60)
    warm = compiles.value
    report = replay_lib.replay(mesh, _mixed_records(), pace=False)
    assert compiles.value - warm == 0
    assert report['admitted'] == 6
    # both languages answered in the same steady state
    assert report['scenarios']['java_naming']['java']['delivered'] == 2
    assert report['scenarios']['csharp_naming']['csharp'][
        'delivered'] == 2
    assert report['scenarios']['retrieval_naming']['java'][
        'delivered'] == 1
    assert report['scenarios']['retrieval_naming']['csharp'][
        'delivered'] == 1


# ------------------------------------------------ full drill (slow-mark)
@pytest.mark.slow
@pytest.mark.skipif(
    not os.path.exists(os.path.join(REPO, 'extractor', 'build',
                                    'c2v-extract')),
    reason='native extractor not built')
def test_full_synthetic_replay_drill(model, tmp_path):
    """The full pipeline at real (paced) rates: synthetic mixed-corpus
    profile -> durable file -> paced replay with rate scaling against
    a fresh mesh, reporting quality, hit-rate, shed, p99, and SLO
    burn per scenario x language."""
    records = profile_lib.build_synthetic_profile(
        model.config, str(tmp_path / 'corpus'),
        classes_per_language=2, seed=5, rate_rps=40.0)
    assert {r['language'] for r in records} == {'java', 'csharp'}
    path = str(tmp_path / 'synthetic.jsonl')
    profile_lib.write_profile(path, records,
                              meta={'source': 'synthetic'})
    _header, loaded = profile_lib.read_profile(path)
    tele_core.reset()
    tele_core.enable()
    mesh = model.serving_mesh(replicas=1, tiers=('topk', 'vectors'),
                              memo_cache_bytes=8 << 20)
    try:
        vec = mesh.predict([loaded[0]['lines'][0]], tier='vectors',
                           timeout=60)[0].code_vector
        mesh.attach_index(_FakeIndex(dim=vec.shape[0]))
        report = replay_lib.replay(mesh, loaded, rate_scale=8.0,
                                   seed=5, pace=True, timeout_s=120.0)
        assert report['admitted'] == len(loaded)
        for name in ('java_naming', 'csharp_naming'):
            cell = next(iter(report['scenarios'][name].values()))
            assert cell['delivered'] + cell['shed'] + cell['errors'] \
                == cell['requests']
            assert cell['p99_ms'] >= cell['p50_ms'] >= 0.0
        assert report['slo']['good_total'] > 0
        # paced replays of the same profile share one fingerprint
        again = replay_lib.plan_replay(loaded, rate_scale=8.0, seed=5)
        assert replay_lib.admitted_fingerprint(again) == \
            report['fingerprint']
    finally:
        mesh.close()
        tele_core.disable()
        tele_core.reset()
