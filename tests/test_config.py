import os

import pytest

from code2vec_tpu.config import Config


def test_defaults_match_reference():
    # reference config.py:46-70
    config = Config()
    assert config.NUM_TRAIN_EPOCHS == 20
    assert config.TRAIN_BATCH_SIZE == 1024
    assert config.MAX_CONTEXTS == 200
    assert config.MAX_TOKEN_VOCAB_SIZE == 1301136
    assert config.MAX_TARGET_VOCAB_SIZE == 261245
    assert config.MAX_PATH_VOCAB_SIZE == 911417
    assert config.TOKEN_EMBEDDINGS_SIZE == 128
    assert config.PATH_EMBEDDINGS_SIZE == 128
    assert config.DROPOUT_KEEP_RATE == 0.75
    assert config.SEPARATE_OOV_AND_PAD is False
    assert config.TOP_K_WORDS_CONSIDERED_DURING_PREDICTION == 10
    assert config.MAX_TO_KEEP == 10


def test_context_vector_size():
    config = Config()
    # reference config.py:143-147
    assert config.context_vector_size == 2 * 128 + 128 == 384
    assert config.CODE_VECTOR_SIZE == config.context_vector_size
    assert config.TARGET_EMBEDDINGS_SIZE == config.CODE_VECTOR_SIZE


def test_file_naming_contract():
    # reference config.py:179-230
    config = Config(TRAIN_DATA_PATH_PREFIX='data/java14m/java14m')
    assert config.train_data_path == 'data/java14m/java14m.train.c2v'
    assert config.word_freq_dict_path == 'data/java14m/java14m.dict.c2v'
    assert Config.get_vocabularies_path_from_model_path(
        'models/java14m/saved_model_iter8') == 'models/java14m/dictionaries.bin'
    assert Config.get_entire_model_path('m/p') == 'm/p__entire-model'
    assert Config.get_model_weights_path('m/p') == 'm/p__only-weights'


def test_steps_per_epoch():
    config = Config(TRAIN_DATA_PATH_PREFIX='x', NUM_TRAIN_EXAMPLES=2500)
    assert config.train_steps_per_epoch == 3  # ceil(2500/1024)


def test_verify_requires_train_or_load():
    with pytest.raises(ValueError):
        Config().verify()


def test_verify_passes_for_training():
    Config(TRAIN_DATA_PATH_PREFIX='x').verify()


def test_cli_parsing(tmp_path):
    config = Config().load_from_args([
        '--data', 'd/prefix', '--test', 'd/prefix.val.c2v',
        '--save', str(tmp_path / 'model'), '--framework', 'jax',
        '--mesh', '4x2', '--dtype', 'float32', '--batch-size', '256',
        '--embed-grad', 'dedup', '--fused-ce', '--ragged-fusion'])
    assert config.TRAIN_DATA_PATH_PREFIX == 'd/prefix'
    assert config.TEST_DATA_PATH == 'd/prefix.val.c2v'
    assert config.DL_FRAMEWORK == 'jax'
    assert config.MESH_DATA_AXIS_SIZE == 4
    assert config.MESH_MODEL_AXIS_SIZE == 2
    assert config.COMPUTE_DTYPE == 'float32'
    assert config.TRAIN_BATCH_SIZE == 256
    assert config.EMBED_GRAD_IMPL == 'dedup'
    assert config.USE_PALLAS_FUSED_CE is True
    assert config.USE_PALLAS_RAGGED_FUSION is True
    config.verify()

    # undecided perf knobs default OFF (reference-parity behavior until
    # their on-chip A/Bs decide otherwise); the ragged fusion flipped ON
    # when its custom-VJP backward landed (structural win on every
    # backend), with --no-ragged-fusion as the opt-out and the TRAIN
    # kernel pair still gated behind the >=2% on-chip verdict
    plain = Config().load_from_args(['--data', 'd/prefix'])
    assert plain.USE_PALLAS_FUSED_CE is False
    assert plain.USE_PALLAS_RAGGED_FUSION is True
    assert plain.RAGGED_TRAIN_KERNEL is False
    assert plain.EMBED_GRAD_IMPL == 'dense'

    unfused = Config().load_from_args(['--data', 'd/prefix',
                                       '--no-ragged-fusion'])
    assert unfused.USE_PALLAS_RAGGED_FUSION is False
    kernel = Config().load_from_args(['--data', 'd/prefix',
                                      '--ragged-train-kernel'])
    assert kernel.RAGGED_TRAIN_KERNEL is True


def test_iter_yields_fields():
    names = dict(Config())
    assert 'MAX_CONTEXTS' in names
    assert not any(name.startswith('_') for name in names)
