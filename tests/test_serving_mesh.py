"""Serving mesh drills (serving/mesh.py + serving/frontqueue.py,
ISSUEs 13 + 14): shared-queue admission parity vs a single engine
(admitted results bit-identical), continuous cross-tier batching with
ZERO post-warmup compiles, replica-labeled metrics without registry
collisions, a breaker-tripped replica weighted out WITHOUT wedging the
queue, coordinated canary -> fleet-swap / rollback, replica retirement
drain, the fleet-level overload drill through the fault grammar's
serving points, the process/socket worker wire, and the self-healing
drills: SIGKILL mid-batch -> crash-safe redispatch + supervised restart
+ rejoin at the fleet's rolled-to step, heartbeat-miss liveness on a
hung or partitioned worker, and the restart budget retiring a flapping
replica typed."""
import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.resilience import faults
from code2vec_tpu.serving import frontqueue as frontqueue_lib
from code2vec_tpu.serving import mesh as mesh_lib
from code2vec_tpu.serving import transport as transport_lib
from code2vec_tpu.serving.autoscaler import Autoscaler
from code2vec_tpu.serving.engine import _Request
from code2vec_tpu.serving.errors import (DeadlineExceeded, EngineClosed,
                                         EngineOverloaded)
from tests.test_train_overfit import make_dataset

PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
    'run|c tokc0,pC,tokc1 tokc2,pA,tokc0 tokc1,pB,tokc2',
]


@pytest.fixture(autouse=True)
def clear_fault_plan():
    faults.configure('')
    yield
    faults.configure('')


@pytest.fixture(scope='module')
def model(tmp_path_factory):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('serving_mesh'))
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8,16')
    return Code2VecModel(config)


def _wait_until(predicate, timeout=15.0, what='condition'):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return
        time.sleep(0.002)
    raise AssertionError('timed out waiting for %s' % what)


def _fake_request(rows: int, deadline_s=None) -> _Request:
    batch = types.SimpleNamespace(label=np.zeros((rows,), np.int32))
    from concurrent.futures import Future
    return _Request(batch, 'topk', future=Future(), deadline_s=deadline_s)


# ------------------------------------------------------ FrontQueue units
def test_frontqueue_bound_sheds_typed_by_reason():
    queue = frontqueue_lib.FrontQueue(('topk',), bound=8,
                                      fleet_rate=lambda: 0.0)
    assert queue.admit(4, 'topk', None) == 'topk'
    queue.enqueue('topk', [_fake_request(4)], 4)  # reservation -> queued
    with pytest.raises(EngineOverloaded, match='queue bound'):
        queue.admit(8, 'topk', None)
    assert queue.shed_total.snapshot() == 1
    assert queue.shed_bound_total.snapshot() == 1
    # the fleet drain estimate sheds undeliverable deadlines, typed
    queue2 = frontqueue_lib.FrontQueue(('topk',), bound=1024,
                                       fleet_rate=lambda: 1.0)
    with pytest.raises(EngineOverloaded, match='fleet drain estimate'):
        queue2.admit(100, 'topk', deadline_s=0.1)
    assert queue2.shed_deadline_total.snapshot() == 1
    # no deadline -> no drain check
    assert queue2.admit(100, 'topk', None) == 'topk'


def test_frontqueue_oversize_admitted_alone_then_bounds():
    """Pile-up, not size: one request larger than the whole bound is
    admitted on an idle queue; everything behind it sheds."""
    queue = frontqueue_lib.FrontQueue(('topk',), bound=4,
                                      fleet_rate=lambda: 0.0)
    assert queue.admit(10, 'topk', None) == 'topk'
    with pytest.raises(EngineOverloaded):
        queue.admit(1, 'topk', None)


def test_frontqueue_degrades_under_shared_fill():
    queue = frontqueue_lib.FrontQueue(('topk', 'full'), bound=8,
                                      fleet_rate=lambda: 0.0)
    queue.admit(6, 'topk', None)  # 6/8 reserved: level 2 at next admit
    assert queue.admit(1, 'full', None) == 'topk'
    assert queue.degraded_total.snapshot() == 1
    # never degrade onto a cold program: 'attention' tier not warmed,
    # so level 1 would keep 'full' as-is — exercised via warmed set
    queue2 = frontqueue_lib.FrontQueue(('full',), bound=8,
                                       fleet_rate=lambda: 0.0)
    queue2.admit(6, 'full', None)
    assert queue2.admit(1, 'full', None) == 'full'
    assert queue2.degraded_total.snapshot() == 0


def test_frontqueue_pop_coalesces_inserts_and_expires():
    queue = frontqueue_lib.FrontQueue(('topk',), bound=None,
                                      fleet_rate=lambda: 0.0)
    first = _fake_request(2)
    queue.admit(2, 'topk', None)
    queue.enqueue('topk', [first], 2)

    # a late arrival inside the coalescing window is folded into the
    # still-gathering micro-batch (continuous insert)
    late = _fake_request(3)

    def arrive_late():
        time.sleep(0.05)
        queue.admit(3, 'topk', None)
        queue.enqueue('topk', [late], 3)

    threading.Thread(target=arrive_late).start()
    tier, taken, rows, expired = queue.pop_coalesced(
        16, max_delay_s=0.4, alive=lambda: True)
    assert tier == 'topk' and rows == 5 and not expired
    assert taken == [first, late]

    # an already-deadlined queued request expires at pop, never taken
    dead = _fake_request(1, deadline_s=0.01)
    queue.admit(1, 'topk', None)
    queue.enqueue('topk', [dead], 1)
    time.sleep(0.05)
    _tier, taken, rows, expired = queue.pop_coalesced(
        16, max_delay_s=0.0, alive=lambda: True)
    assert expired == [dead] and not taken and rows == 0
    assert queue.expired_total.snapshot() == 1

    # a dead replica leaves without taking work
    queue.admit(1, 'topk', None)
    queue.enqueue('topk', [_fake_request(1)], 1)
    assert queue.pop_coalesced(16, 0.0, alive=lambda: False) is None
    assert queue.depth_rows() == 1


def test_frontqueue_requeue_front_order_exclusion_and_closed():
    """Crash-safe redispatch mechanics: a crashed batch's members go
    back to the FRONT in their original order with deadlines intact;
    the dead incarnation cannot re-claim them (exclusion by claim
    token); a fail-fast-closed queue refuses so the caller fails them
    typed."""
    queue = frontqueue_lib.FrontQueue(('topk',), bound=None,
                                      fleet_rate=lambda: 0.0)
    waiting = _fake_request(1)
    queue.admit(1, 'topk', None)
    queue.enqueue('topk', [waiting], 1)
    dead_token = object()
    crashed = [_fake_request(2), _fake_request(3)]
    for request in crashed:
        request.redispatched = True
        request.exclude = dead_token
    assert queue.requeue_front('topk', crashed) is True
    assert queue.depth_rows() == 6
    # the dead incarnation skips its own crashed members — they stay
    # at the front for a sibling
    tier, taken, rows, expired = queue.pop_coalesced(
        16, 0.0, alive=lambda: True, claim=dead_token)
    assert taken == [waiting] and rows == 1 and not expired
    assert queue.depth_rows() == 5
    # a DIFFERENT incarnation (sibling or supervised restart) takes
    # them, in their original order, from the front
    _t, taken, rows, _e = queue.pop_coalesced(
        16, 0.0, alive=lambda: True, claim=object())
    assert taken == crashed and rows == 5
    # an already-expired member still sheds typed at pop, deadline
    # intact through the requeue
    expired_member = _fake_request(1, deadline_s=0.01)
    time.sleep(0.05)
    assert queue.requeue_front('topk', [expired_member]) is True
    _t, taken, _r, expired = queue.pop_coalesced(
        16, 0.0, alive=lambda: True)
    assert expired == [expired_member] and not taken
    # fail-fast close refuses the requeue
    queue.close()
    assert queue.requeue_front('topk', [_fake_request(1)]) is False


# ------------------------------------------------------- admission parity
def test_mesh_matches_single_engine_bit_identical(model):
    """Shared-queue admission parity: results served THROUGH the mesh
    are bit-identical to the single engine's (same tokenizer, same
    bucket/capacity selection, same warm programs)."""
    with model.serving_engine(tiers=('topk',),
                              max_delay_ms=0.0) as engine:
        single = [engine.predict([line], tier='topk', timeout=60)[0]
                  for line in PREDICT_LINES]
    with model.serving_mesh(replicas=2, tiers=('topk',),
                            max_delay_ms=0.0) as mesh:
        meshed = [mesh.predict([line], tier='topk', timeout=60)[0]
                  for line in PREDICT_LINES]
        # oversize split still holds through the shared queue
        lines = [PREDICT_LINES[i % 3] for i in range(20)]
        wide = mesh.predict(lines, tier='topk', timeout=60)
    for m, s in zip(meshed, single):
        assert m.original_name == s.original_name
        assert m.topk_predicted_words == s.topk_predicted_words
        np.testing.assert_array_equal(m.topk_predicted_words_scores,
                                      s.topk_predicted_words_scores)
    assert len(wide) == 20
    direct = model.predict(lines)
    for w, d in zip(wide, direct):
        assert w.topk_predicted_words == d.topk_predicted_words


# -------------------------------------- mixed tiers, compiles, metrics
class _FakeIndex:
    labels = np.array(['m0', 'm1'], dtype=object)

    def search(self, vectors, k):
        n = vectors.shape[0]
        return (np.zeros((n, k), np.float32),
                np.zeros((n, k), np.int64))


def test_mesh_mixed_tier_stream_zero_compiles_and_labeled_metrics(model):
    """Acceptance: one dispatch stream serves predict tiers AND
    submit_neighbors with ZERO post-warmup compiles; coexisting
    replicas mirror their instruments replica-LABELED, so the registry
    neither double-counts counters nor overwrites gauges."""
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    core.reset()
    core.enable()
    mesh = model.serving_mesh(
        replicas=2, tiers=('topk', 'attention', 'vectors'),
        max_delay_ms=1.0)
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        mesh.attach_index(_FakeIndex())
        warm = compiles.value
        futures = []
        for i in range(24):
            kind = ('topk', 'attention', 'neighbors')[i % 3]
            lines = [PREDICT_LINES[i % 3]]
            if kind == 'neighbors':
                futures.append(mesh.submit_neighbors(lines))
            else:
                futures.append(mesh.submit(lines, tier=kind))
        for future in futures:
            assert future.result(timeout=60)
        assert compiles.value - warm == 0, (
            '%d compiles during mixed-tier mesh serving'
            % (compiles.value - warm))
        # both replicas served the one stream
        stats = mesh.stats()
        assert all(r['batches'] > 0 for r in stats['replicas'])
        # replica-labeled mirrors: one series per replica, base name
        # absent (no unlabeled collision for the per-engine counters)
        reg = core.registry()
        for rid in ('r0', 'r1'):
            labeled = reg.get('serving/batches_total{replica=%s}' % rid)
            assert labeled is not None and labeled.snapshot() > 0
        assert reg.get('serving/batches_total') is None
        total = sum(
            reg.get('serving/batches_total{replica=%s}' % rid).snapshot()
            for rid in ('r0', 'r1'))
        assert total == sum(r['batches'] for r in stats['replicas'])
        # fleet-level mesh metrics ride unlabeled
        assert reg.get('mesh/requests_total').snapshot() == 24
    finally:
        mesh.close()
        core.disable()
        core.reset()


# ------------------------------------------------------- replica breaker
def test_breaker_trips_replica_out_without_queue_wedge(model):
    """K consecutive dispatch failures open one replica's breaker; the
    shared queue redirects to its sibling (no wedge, no lost work
    beyond the failed dispatches), and the half-open probe closes the
    breaker once dispatch heals."""
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0, breaker_threshold=2,
                              breaker_cooldown_secs=60.0)
    try:
        slot0 = mesh._replicas[0]
        engine0 = slot0.transport.engine
        real_dispatch = engine0.dispatch_external

        def boom(tier, taken, rows):
            exc = RuntimeError('injected replica-0 dispatch failure')
            for request in taken:
                request.fail(exc)
            raise exc

        engine0.dispatch_external = boom
        # feed singles until r0 has failed enough claims to trip; r1
        # keeps serving its share throughout
        sacrificed = 0
        deadline = time.perf_counter() + 20.0
        while slot0.breaker_state != mesh_lib._BREAKER_OPEN:
            assert time.perf_counter() < deadline, \
                'breaker never tripped'
            future = mesh.submit([PREDICT_LINES[0]], tier='topk')
            try:
                future.result(timeout=60)
            except RuntimeError:
                sacrificed += 1
        assert sacrificed >= 2  # the threshold's consecutive failures
        assert mesh.stats()['replica_breaker_open_total'] >= 1
        # weighted out: traffic flows entirely through r1, queue never
        # wedges
        before = slot0.batches
        results = [mesh.predict([PREDICT_LINES[i % 3]], tier='topk',
                                timeout=60)
                   for i in range(10)]
        assert all(r[0].topk_predicted_words for r in results)
        assert slot0.batches == before
        # heal + force the cooldown over: the half-open probe batch
        # closes the breaker and r0 serves again
        engine0.dispatch_external = real_dispatch
        with mesh._lock:
            slot0.breaker_open_until = time.perf_counter() - 1.0
        _wait_until(
            lambda: (mesh.predict([PREDICT_LINES[0]], tier='topk',
                                  timeout=60) and
                     slot0.breaker_state == mesh_lib._BREAKER_CLOSED),
            timeout=20.0, what='half-open probe to close the breaker')
        assert slot0.batches > before
    finally:
        mesh.close()


# -------------------------------------------------- coordinated rollover
def test_coordinated_rollover_fleet_swap_and_rollback_zero_compiles(model):
    """Acceptance: canary on ONE replica, fleet swap on agreement with
    zero post-warmup compiles; a failed canary rolls back and leaves
    EVERY replica serving the old params."""
    import jax
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    core.reset()
    core.enable()
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0)
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
        broken = jax.tree_util.tree_map(lambda leaf: -leaf, model.params)
        jax.block_until_ready(broken)
        mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        warm = compiles.value

        # ---- canary PASS on one replica -> every replica swaps
        handle = mesh.load_params(same, canary_batches=2,
                                  min_agreement=0.9)
        canary_rid = mesh._rollover['replica'].rid
        for _ in range(12):
            if handle.done():
                break
            mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        report = handle.result(timeout=60)
        assert report['swapped'] is True
        assert report['canary_replica'] == canary_rid
        assert report['replicas_swapped'] == 2
        for slot in mesh._replicas:
            assert slot.transport.engine.params is same, slot.rid

        # ---- canary FAIL -> rollback, every replica keeps old params
        handle = mesh.load_params(broken, canary_batches=2,
                                  min_agreement=0.9)
        for _ in range(12):
            if handle.done():
                break
            mesh.predict(PREDICT_LINES, tier='topk', timeout=60)
        report = handle.result(timeout=60)
        assert report['swapped'] is False
        assert report['replicas_swapped'] == 0
        for slot in mesh._replicas:
            assert slot.transport.engine.params is same, slot.rid
        stats = mesh.stats()
        assert stats['rollover_total'] == 1
        assert stats['rollover_rollbacks_total'] == 1
        assert compiles.value - warm == 0, (
            '%d XLA compiles during coordinated rollover'
            % (compiles.value - warm))
        # the fleet concluded: a fresh rollover arms cleanly
        assert mesh.load_params(
            same, canary_batches=0).result(60)['swapped'] is True
    finally:
        mesh.close()
        core.disable()
        core.reset()


def test_rollover_guards(model):
    import jax
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0)
    same = jax.tree_util.tree_map(lambda leaf: leaf, model.params)
    try:
        armed = mesh.load_params(same, canary_batches=50)
        with pytest.raises(RuntimeError, match='already in flight'):
            mesh.load_params(same, canary_batches=1)
        # a mesh-replica engine refuses direct submit/follow: the mesh
        # owns admission and the fleet rollover
        engine0 = mesh._replicas[0].transport.engine
        with pytest.raises(RuntimeError, match='mesh replica'):
            engine0.submit(PREDICT_LINES, tier='topk')
        with pytest.raises(RuntimeError, match='mesh replica'):
            engine0.follow_checkpoints(poll_secs=1.0)
    finally:
        mesh.close()
    assert isinstance(armed.exception(timeout=10), EngineClosed)
    with pytest.raises(EngineClosed):
        mesh.load_params(same, canary_batches=0)


# ------------------------------------------------------ retirement drain
def test_replica_retirement_drains_and_queue_redirects(model):
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0)
    try:
        inflight = [mesh.submit([PREDICT_LINES[i % 3]], tier='topk')
                    for i in range(12)]
        mesh.retire('r0')
        for future in inflight:
            assert future.result(timeout=60)
        # the retired replica's engine is closed; the queue redirects
        retired = mesh._replicas[0]
        assert retired.retired and not retired.thread.is_alive()
        before = mesh._replicas[1].batches
        results = [mesh.predict([line], tier='topk', timeout=60)
                   for line in PREDICT_LINES]
        assert all(r[0].topk_predicted_words for r in results)
        assert mesh._replicas[1].batches > before
        assert mesh._replicas[0].batches + mesh._replicas[1].batches \
            >= len(results)
        assert mesh.stats()['replicas'][0]['retired'] is True
        mesh.retire('r0')  # idempotent
        with pytest.raises(ValueError, match='no replica'):
            mesh.retire('r9')
    finally:
        mesh.close()


# ---------------------------------------------------- fleet overload drill
def test_fleet_overload_drill_typed_shed_and_expiry(model):
    """The ISSUE 13 overload drill through the existing fault grammar's
    serving points, at FLEET level: reject_all sheds typed at the
    SHARED queue; slow_dispatch stalls both replicas so deadlined work
    expires typed in the shared queue; admitted work still returns
    results identical to the unloaded path."""
    line = PREDICT_LINES[0]
    unloaded = model.predict([line])[0]
    # ---- reject_all fires at mesh admission
    with model.serving_mesh(replicas=2, tiers=('topk',),
                            max_delay_ms=0.0, queue_bound=64) as mesh:
        faults.configure('reject_all@req=0..1')
        for _ in range(2):
            with pytest.raises(EngineOverloaded):
                mesh.submit([line], tier='topk')
        faults.configure('')
        (result,) = mesh.predict([line], tier='topk', timeout=60)
        assert result.topk_predicted_words == \
            unloaded.topk_predicted_words
        assert mesh.stats()['shed_total'] == 2

    # ---- slow_dispatch + bounded shared queue: expiry and shed typed
    # max_inflight=1: a replica is BUSY for the whole >=250ms stall of
    # its one claimed batch, so the deadlined requests below stay
    # queued past their SLO deterministically
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=0.0, queue_bound=8,
                              max_inflight=1)
    try:
        faults.configure('slow_dispatch@req=0..255')
        # plug BOTH replicas, one at a time (two queued plugs would
        # coalesce into ONE replica's batch): each claims one stalled
        # batch and is busy for the whole >=250ms stall
        plugs = []
        for _ in range(2):
            plugs.append(mesh.submit([line], tier='topk'))
            _wait_until(lambda: mesh._queue.depth_rows() == 0,
                        what='a replica to claim the plug batch')
        _wait_until(lambda: all(s.inflight >= 1
                                for s in mesh._replicas),
                    what='both replicas to hold a stalled batch')
        # deadlined requests queue behind >=250ms stalls with a 50ms
        # SLO: they must expire typed at pop, never dispatch
        doomed = [mesh.submit([line], tier='topk', deadline_ms=50.0)
                  for _ in range(4)]
        # open-loop burst past the bound: typed sheds
        shed = 0
        admitted = []
        for _ in range(12):
            try:
                admitted.append(mesh.submit([line], tier='topk'))
            except EngineOverloaded:
                shed += 1
        assert shed > 0
        assert mesh._queue.peak_rows() <= 8
        for future in doomed:
            assert isinstance(future.exception(timeout=60),
                              DeadlineExceeded)
        faults.configure('')
        for future in admitted + plugs:
            (result,) = future.result(timeout=60)
            assert result.original_name == unloaded.original_name
            assert result.topk_predicted_words == \
                unloaded.topk_predicted_words
            np.testing.assert_array_equal(
                result.topk_predicted_words_scores,
                unloaded.topk_predicted_words_scores)
        stats = mesh.stats()
        assert stats['shed_total'] == shed
        assert stats['expired_total'] == 4
    finally:
        faults.configure('')
        mesh.close()


# -------------------------------------------------------- close semantics
def test_mesh_close_failfast_and_drain(model):
    line = PREDICT_LINES[0]
    # fail-fast: queued work fails typed, in-flight still delivers
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              max_delay_ms=0.0)
    faults.configure('slow_dispatch@req=0..63')
    plug = mesh.submit([line], tier='topk')
    _wait_until(lambda: mesh._queue.depth_rows() == 0,
                what='puller to claim the plug')
    queued = [mesh.submit([line], tier='topk') for _ in range(3)]
    mesh.close()
    faults.configure('')
    assert plug.result(timeout=60)[0].topk_predicted_words
    for future in queued:
        assert isinstance(future.exception(timeout=10), EngineClosed)
    with pytest.raises(EngineClosed):
        mesh.submit([line], tier='topk')

    # drain: everything admitted is served first
    mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                              max_delay_ms=10_000.0)
    futures = [mesh.submit([ln], tier='topk') for ln in PREDICT_LINES]
    mesh.close(drain=True)
    for future, ln in zip(futures, PREDICT_LINES):
        (result,) = future.result(timeout=60)
        assert result.topk_predicted_words == \
            model.predict([ln])[0].topk_predicted_words
    assert not any(s.thread.is_alive() for s in mesh._replicas)


# -------------------------------------------------- process-replica wire
def test_process_replica_mode_serves_and_rolls(tmp_path_factory):
    """One spawned worker process per replica on the same dispatch
    wire: results match the parent's model, stats cross the pipe, and
    a fleet rollover ships the checkpoint REF (worker restores from
    the store)."""
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('mesh_proc'))
    save_path = str(tmp_path_factory.mktemp('mesh_proc_model') / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_WARM_TIERS='topk')
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)  # step 0
    direct = model.predict(PREDICT_LINES)
    mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                              mode='process', max_delay_ms=0.0)
    try:
        served = mesh.predict(PREDICT_LINES, tier='topk', timeout=120)
        for s, d in zip(served, direct):
            assert s.original_name == d.original_name
            assert s.topk_predicted_words == d.topk_predicted_words
        stats = mesh.stats()
        assert stats['mode'] == 'process'
        assert stats['replicas'][0]['batches'] >= 1
        per_replica = mesh.replica_stats()
        assert per_replica[0]['replica'] == 'r0'
        # rollover by checkpoint ref across the wire (no canary: the
        # deterministic restore-and-swap leg)
        report = mesh.load_params(0, canary_batches=0).result(timeout=120)
        assert report['swapped'] is True
        # pytrees do not cross the wire: typed refusal
        with pytest.raises(RuntimeError, match='checkpoint refs'):
            mesh.load_params(model.params, canary_batches=0)
    finally:
        mesh.close()
        model.close_stores()


# ---------------------------------------------------- self-healing (14)
@contextlib.contextmanager
def _cfg(model, **fields):
    """Temporarily override config fields (worker processes rebuild
    their Config from the live fields via the mesh's overrides)."""
    old = {name: getattr(model.config, name) for name in fields}
    for name, value in fields.items():
        setattr(model.config, name, value)
    try:
        yield
    finally:
        for name, value in old.items():
            setattr(model.config, name, value)


def _checkpointed_model(tmp_path_factory, tag):
    from code2vec_tpu.model_api import Code2VecModel
    prefix = make_dataset(tmp_path_factory.mktemp('mesh_%s' % tag))
    save_path = str(tmp_path_factory.mktemp('mesh_%s_model' % tag)
                    / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_WARM_TIERS='topk')
    model = Code2VecModel(config)
    model.save(state=model.state, epoch=0, wait=True)  # step 0
    return model


@pytest.fixture(scope='module')
def proc_model(tmp_path_factory):
    model = _checkpointed_model(tmp_path_factory, 'heal')
    yield model
    model.close_stores()


def _assert_healing_threads_reaped(mesh):
    """ISSUE 14 small fix: close() must reap the supervisor, liveness
    monitor, and socket listener (threads AND sockets)."""
    if mesh._supervisor is not None:
        assert not mesh._supervisor.is_alive()
    if mesh._liveness_thread is not None:
        assert not mesh._liveness_thread.is_alive()
    if mesh._listener is not None:
        assert mesh._listener.closed


def test_socket_kill_drill_redispatch_restart_rejoin(tmp_path_factory):
    """The ISSUE 14 acceptance drill, on the TCP transport: SIGKILL a
    worker replica mid-batch -> every admitted request still completes
    (crash-safe redispatch onto the sibling, zero hung futures), the
    supervisor restores fleet capacity without operator action, and the
    restarted worker rejoins at the params step the fleet rolled to
    WHILE it was down — all with zero post-warmup compiles in the
    parent (telemetry compile counter)."""
    import jax.numpy as jnp
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    model = _checkpointed_model(tmp_path_factory, 'kill')
    core.reset()
    core.enable()
    mesh = None
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        # worker-side slow_dispatch holds every worker batch >=250ms so
        # the SIGKILL deterministically lands MID-batch
        with _cfg(model, FAULT_INJECT='slow_dispatch@req=0..63',
                  MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=4,
                  MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5):
            mesh = model.serving_mesh(replicas=2, tiers=('topk',),
                                      mode='socket', max_delay_ms=0.0)
        unloaded = {line: model.predict([line])[0]
                    for line in PREDICT_LINES}
        (first,) = mesh.predict([PREDICT_LINES[0]], tier='topk',
                                timeout=120)
        assert first.topk_predicted_words == \
            unloaded[PREDICT_LINES[0]].topk_predicted_words
        warm = compiles.value
        slot0 = mesh._replicas[0]
        # 10 x 3-row requests = 30 rows over 8-row buckets: several
        # micro-batches are in flight at once, so BOTH replicas hold
        # batches when the SIGKILL lands (one claim cannot hoover the
        # whole queue)
        batches_lines = [[PREDICT_LINES[(i + j) % 3] for j in range(3)]
                         for i in range(10)]
        admitted = [mesh.submit(lines, tier='topk')
                    for lines in batches_lines]
        _wait_until(lambda: slot0.inflight >= 1, timeout=30.0,
                    what='r0 to hold an in-flight batch')
        os.kill(slot0.transport.pid, signal.SIGKILL)
        # zero hung futures, zero lost admitted requests: everything
        # completes on the sibling (or the restarted worker)
        for lines, future in zip(batches_lines, admitted):
            results = future.result(timeout=120)
            assert len(results) == len(lines)
            for line, result in zip(lines, results):
                assert result.topk_predicted_words == \
                    unloaded[line].topk_predicted_words
        _wait_until(lambda: slot0.dead or slot0.restarts >= 1,
                    timeout=30.0, what='the death verdict on r0')
        stats = mesh.stats()
        assert stats['redispatched_total'] >= 1
        # roll the fleet WHILE r0 is down (or restarting): the sibling
        # swaps; r0 must rejoin at the rolled-to step, not its
        # cold-start one
        newer = model.state._replace(step=jnp.asarray(7, jnp.int32))
        model.save(state=newer, epoch=0, wait=True)
        report = mesh.load_params(7, canary_batches=0).result(timeout=120)
        assert report['swapped'] is True
        _wait_until(lambda: mesh.stats()['restarts_total'] >= 1
                    and not mesh._replicas[0].dead,
                    timeout=120.0, what='supervised restart of r0')
        # capacity is restored: r0 pulls again, serving step 7
        before = slot0.batches
        deadline = time.perf_counter() + 60.0
        while slot0.batches == before:
            assert time.perf_counter() < deadline, \
                'restarted r0 never served'
            mesh.predict([PREDICT_LINES[0]], tier='topk', timeout=120)
        per_replica = {s.get('replica'): s for s in mesh.replica_stats()}
        assert per_replica['r0'].get('params_step') == 7, per_replica['r0']
        assert mesh.stats()['params_step'] == 7
        assert mesh.stats()['replicas_live'] == 2
        assert compiles.value - warm == 0, (
            '%d parent-side compiles during the kill drill'
            % (compiles.value - warm))
    finally:
        if mesh is not None:
            mesh.close()
            _assert_healing_threads_reaped(mesh)
        model.close_stores()
        core.disable()
        core.reset()


def test_heartbeat_miss_restarts_then_budget_retires_typed(proc_model):
    """Liveness distinct from dispatch health: a worker that stays
    connected but stops heartbeating (drop_heartbeat drill — nothing in
    flight, so the breaker sees NOTHING) is declared dead and
    restarted; when the restarted worker flaps the same way, the
    window-scoped restart budget retires the replica permanently and
    the mesh refuses new work typed instead of hanging it."""
    model = proc_model
    with _cfg(model, FAULT_INJECT='drop_heartbeat@beat=2..9999',
              MESH_HEARTBEAT_SECS=0.2, MESH_HEARTBEAT_MISSES=2,
              MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=1,
              MESH_RESTART_WINDOW_SECS=300.0):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='process', max_delay_ms=0.0)
        try:
            # the worker serves fine — it is connected and healthy,
            # only its liveness signal is gone
            assert mesh.predict([PREDICT_LINES[0]], tier='topk',
                                timeout=120)[0].topk_predicted_words
            _wait_until(lambda: mesh.stats()['restarts_total'] >= 1,
                        timeout=90.0,
                        what='liveness kill + supervised restart')
            assert mesh.stats()['heartbeat_misses_total'] >= 1
            # the restarted worker flaps identically -> budget (1 per
            # window) is spent -> permanent retirement, typed refusal
            _wait_until(lambda: mesh._replicas[0].retired, timeout=90.0,
                        what='restart budget to retire the replica')
            with pytest.raises(EngineClosed, match='retired'):
                mesh.submit([PREDICT_LINES[0]], tier='topk')
            stats = mesh.stats()
            assert stats['replicas'][0]['retired'] is True
            assert stats['replicas_live'] == 0
        finally:
            mesh.close()
            _assert_healing_threads_reaped(mesh)


def test_partition_liveness_detects_and_redispatches(proc_model):
    """A network partition (parent-side frames blackholed while both
    endpoints stay up) is invisible to the dispatch breaker; the
    heartbeat monitor catches it, the blackholed in-flight batch is
    redispatched, and the answer still arrives once the supervised
    restart rejoins — a partition costs latency, not answers."""
    model = proc_model
    with _cfg(model, MESH_HEARTBEAT_SECS=0.2, MESH_HEARTBEAT_MISSES=2,
              MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5,
              MESH_RESTART_WINDOW_SECS=300.0):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='process', max_delay_ms=0.0)
        try:
            unloaded = model.predict([PREDICT_LINES[1]])[0]
            assert mesh.predict([PREDICT_LINES[1]], tier='topk',
                                timeout=120)[0].topk_predicted_words
            # blackhole every frame the parent receives: the worker
            # keeps computing and beating into the void
            faults.configure('partition@frame=0..99999')
            doomed = mesh.submit([PREDICT_LINES[1]], tier='topk')
            _wait_until(lambda: mesh._replicas[0].dead
                        or mesh.stats()['restarts_total'] >= 1,
                        timeout=90.0,
                        what='liveness to declare the partition')
            assert mesh.stats()['heartbeat_misses_total'] >= 1
            # partition heals; the restarted incarnation's frames pass
            faults.configure('')
            (result,) = doomed.result(timeout=120)
            assert result.topk_predicted_words == \
                unloaded.topk_predicted_words
            _wait_until(lambda: mesh.stats()['restarts_total'] >= 1,
                        timeout=120.0, what='restart after partition')
            assert mesh.stats()['redispatched_total'] >= 1
        finally:
            faults.configure('')
            mesh.close()
            _assert_healing_threads_reaped(mesh)


# -------------------------------------------------- elastic fleet (18)
def test_partition_device_indices_disjoint_and_bounded():
    """Placement math (parallel/mesh.py): contiguous, disjoint,
    exhaustion-checked against the visible device count."""
    from code2vec_tpu.parallel import mesh as pmesh
    slices = pmesh.partition_device_indices(4, 2)
    assert slices == [[0, 1], [2, 3], [4, 5], [6, 7]]
    flat = [i for s in slices for i in s]
    assert len(flat) == len(set(flat))
    with pytest.raises(ValueError, match='device'):
        pmesh.partition_device_indices(5, 2)  # 10 > 8 visible
    assert pmesh.device_slice('4,5') is not None
    with pytest.raises(ValueError):
        pmesh.device_slice('4,99')


def test_placement_rejects_thread_mode(model):
    with _cfg(model, MESH_DEVICES_PER_REPLICA=2):
        with pytest.raises(ValueError, match='worker mode'):
            model.serving_mesh(replicas=1, tiers=('topk',),
                               mode='thread', warmup=False)


class _StubQueue:
    def __init__(self):
        self.next = (0.0, 0, 1.0)

    def drain_seconds(self):
        return self.next

    def kick(self):
        pass


class _StubMesh:
    """Just enough mesh for Autoscaler's unit surface: a replica
    table under _lock, a queue drain estimate, and the two verbs."""

    def __init__(self, n=1):
        self._lock = threading.Lock()
        self._queue = _StubQueue()
        self._slo = None
        self._replicas = [mesh_lib._ReplicaSlot('r%d' % i, None)
                          for i in range(n)]
        self.retired = []

    def add_replica(self):
        rid = 'r%d' % len(self._replicas)
        self._replicas.append(mesh_lib._ReplicaSlot(rid, None))
        return rid

    def retire(self, rid, timeout=120.0, reason='drain'):
        for slot in self._replicas:
            if slot.rid == rid:
                slot.retired = True
                slot.retired_reason = reason
        self.retired.append((rid, reason))
        return True


def _asc_cfg(**overrides):
    fields = dict(AUTOSCALE_MIN_REPLICAS=1, AUTOSCALE_MAX_REPLICAS=3,
                  AUTOSCALE_INTERVAL_SECS=3600.0,
                  AUTOSCALE_UP_QUEUE_SECS=2.0, AUTOSCALE_UP_BURN=0.0,
                  AUTOSCALE_DOWN_IDLE_SECS=0.0,
                  AUTOSCALE_DOWN_UTILIZATION=0.5,
                  AUTOSCALE_UP_COOLDOWN_SECS=0.0,
                  AUTOSCALE_DOWN_COOLDOWN_SECS=0.0,
                  AUTOSCALE_FLAP_WINDOW_SECS=120.0,
                  AUTOSCALE_FLAP_LIMIT=2)
    fields.update(overrides)
    return types.SimpleNamespace(**fields)


def test_autoscaler_decisions_bounds_cooldowns_and_flap_guard():
    """Control-loop unit: backlog scales up under the max bound and
    the up-cooldown; an empty queue scales down only after SUSTAINED
    low pressure and never below the min; direction thrash trips the
    flap guard into a freeze instead of oscillating."""
    mesh = _StubMesh(1)
    asc = Autoscaler(mesh, _asc_cfg(AUTOSCALE_UP_COOLDOWN_SECS=30.0))
    mesh._queue.next = (10.0, 80, 8.0)  # drain 10s > 2s threshold
    assert asc.tick() == 'up'
    assert len(mesh._replicas) == 2
    assert asc.stats()['scale_up_total'] == 1
    assert asc.stats()['replicas_target'] == 2
    # same backlog, inside the up-cooldown: hold, not storm
    assert asc.tick() == 'hold'
    assert len(mesh._replicas) == 2

    # ---- scale-down: sustained idleness, min bound, LIFO victim ----
    mesh2 = _StubMesh(2)
    mesh2._replicas[1].adopted = True  # orchestrator-owned: never drain
    asc2 = Autoscaler(mesh2, _asc_cfg(AUTOSCALE_DOWN_IDLE_SECS=0.2))
    mesh2._queue.next = (0.0, 0, 8.0)
    assert asc2.tick() == 'hold'  # idle clock starts; not sustained yet
    time.sleep(0.25)
    assert asc2.tick() == 'down'
    # r1 is adopted, so LIFO falls back to r0... but r0 draining would
    # drop the fleet to only the adopted worker — that IS the contract:
    # the victim must be the newest LOCAL replica
    assert mesh2.retired == [('r0', 'autoscale')]
    # min bound: fleet of 1 serving (r1) never drains below min
    time.sleep(0.25)
    assert asc2.tick() == 'hold'

    # ---- flap guard: up -> down -> (blocked up) freezes scaling ----
    mesh3 = _StubMesh(1)
    asc3 = Autoscaler(mesh3, _asc_cfg(AUTOSCALE_FLAP_LIMIT=2,
                                      AUTOSCALE_FLAP_WINDOW_SECS=60.0))
    mesh3._queue.next = (10.0, 80, 8.0)
    assert asc3.tick() == 'up'
    mesh3._queue.next = (0.0, 0, 8.0)
    assert asc3.tick() == 'down'  # reversal 1
    mesh3._queue.next = (10.0, 80, 8.0)
    tick = asc3.tick()  # reversal 2 == limit: freeze, no transition
    assert asc3.stats()['flap_freezes_total'] == 1
    assert asc3.tick() == 'frozen'
    assert len([s for s in mesh3._replicas if not s.retired]) == 1

    # ---- spawn hook: capacity requested, not locally spawned ----
    mesh4 = _StubMesh(1)
    asked = []
    asc4 = Autoscaler(mesh4, _asc_cfg(), spawn=asked.append)
    mesh4._queue.next = (float('inf'), 40, 0.0)  # stalled fleet
    assert asc4.tick() == 'up'
    assert asked == [mesh4] and len(mesh4._replicas) == 1


def test_frontqueue_drain_seconds_estimate():
    queue = frontqueue_lib.FrontQueue(('topk',), bound=None,
                                      fleet_rate=lambda: 4.0)
    assert queue.drain_seconds() == (0.0, 0, 4.0)
    queue.admit(8, 'topk', None)
    queue.enqueue('topk', [_fake_request(8)], 8)
    drain_s, rows, rate = queue.drain_seconds()
    assert (drain_s, rows, rate) == (2.0, 8, 4.0)
    stalled = frontqueue_lib.FrontQueue(('topk',), bound=None,
                                        fleet_rate=lambda: 0.0)
    stalled.admit(4, 'topk', None)
    stalled.enqueue('topk', [_fake_request(4)], 4)
    assert stalled.drain_seconds()[0] == float('inf')


def _dial_raw(mesh, rid, proto=None):
    """Hand-rolled worker dial-in: the wire any external orchestrator
    speaks (scripts/mesh_worker.py does exactly this via transport.dial)."""
    import socket as socket_lib
    conn = socket_lib.create_connection(tuple(mesh._listener.address),
                                        timeout=30.0)
    channel = transport_lib.SocketTransport(conn)
    channel.send(('hello', rid,
                  transport_lib.WIRE_PROTO if proto is None else proto,
                  4242))
    return channel


def _ready_frame(model, step, tiers=('topk',), devices=None):
    caps = {'tiers': list(tiers), 'wire': model.config.BATCH_WIRE_FORMAT,
            'proto': transport_lib.WIRE_PROTO}
    if devices is not None:
        caps['devices'] = list(devices)
    return ('ready', {'params_step': step,
                      't_mono': time.perf_counter(),
                      'capabilities': caps})


def test_adoption_dialins_validated_and_adopted_death_budget_free(
        proc_model):
    """Adoption edges (SERVING.md "Elastic fleet"): a wrong-proto
    dial-in is rejected typed at the listener; a dial-in that never
    reports ready is dropped typed after the bounded adoption wait
    (the adopt_stall shape); a ready worker missing a warm tier is
    turned away typed; a WELL-FORMED unknown-rid dial-in is adopted
    and seated; a duplicate rid is refused; and the adopted worker's
    death retires its slot typed WITHOUT charging the local restart
    budget — its restart supervision belongs to the orchestrator."""
    model = proc_model
    with _cfg(model, MESH_HEARTBEAT_SECS=30.0, MESH_HEARTBEAT_MISSES=2,
              MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='socket', max_delay_ms=0.0)
    mesh.adopt_ready_timeout_s = 1.0
    channels = []
    try:
        # (a) wrong wire proto: typed rejection AT the listener
        bad = _dial_raw(mesh, 'ext-proto', proto=1)
        channels.append(bad)
        kind, why = bad.recv()[:2]
        assert kind == 'adopt_rejected' and 'proto' in why
        _wait_until(lambda: mesh.stats()['proto_rejected_total'] >= 1,
                    what='listener proto rejection counter')
        # (b) dialed in but never ready: bounded wait, typed drop (the
        # adopt_stall drill's shape — the frame arrives BEFORE the
        # close, so the orchestrator's logs learn why)
        ghost = _dial_raw(mesh, 'ext-ghost')
        channels.append(ghost)
        kind, why = ghost.recv()[:2]  # blocks ~adopt_ready_timeout_s
        assert kind == 'adopt_rejected' and 'ready' in why
        # the typed frame can reach the client a beat before the
        # adoption loop's counter lands: wait, don't read-once
        _wait_until(lambda: mesh.stats()['adoption_rejected_total'] == 1,
                    what='never-ready rejection counter')
        # (c) ready but missing a warm tier this mesh serves: typed
        cold = _dial_raw(mesh, 'ext-cold')
        channels.append(cold)
        cold.send(_ready_frame(model, 0, tiers=()))
        kind, why = cold.recv()[:2]
        assert kind == 'adopt_rejected' and 'tier' in why
        _wait_until(lambda: mesh.stats()['adoption_rejected_total'] == 2,
                    what='missing-tier rejection counter')
        # (d) a well-formed unknown-rid dial-in IS adopted and seated
        step = mesh.stats()['params_step']
        good = _dial_raw(mesh, 'ext-fake')
        channels.append(good)
        good.send(_ready_frame(model, step, devices=(6, 7)))
        _wait_until(lambda: mesh.stats()['adopted_total'] == 1,
                    timeout=30.0, what='adoption of ext-fake')
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['ext-fake']['adopted'] is True
        assert rows['ext-fake']['devices'] == [6, 7]
        assert rows['ext-fake']['retired'] is False
        # (e) duplicate rid while the first incarnation serves: typed
        dupe = _dial_raw(mesh, 'ext-fake')
        channels.append(dupe)
        kind, why = dupe.recv()[:2]
        assert kind == 'adopt_rejected' and 'unique' in why
        _wait_until(lambda: mesh.stats()['adoption_rejected_total'] == 3,
                    what='duplicate-rid rejection counter')
        # (f) adopted worker dies -> typed retirement, ZERO charge on
        # the LOCAL restart budget (the orchestrator owns its restarts)
        restarts_before = mesh.stats()['restarts_total']
        good.close()  # the worker's end of the wire drops
        slot = next(s for s in mesh._replicas if s.rid == 'ext-fake')
        _wait_until(lambda: slot.retired, timeout=30.0,
                    what='adopted-worker death retirement')
        assert slot.retired_reason == 'adopted_worker_exit'
        time.sleep(0.3)  # a (wrong) supervised restart would act now
        assert mesh.stats()['restarts_total'] == restarts_before
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['ext-fake']['retired_reason'] == 'adopted_worker_exit'
        assert mesh.stats()['retired_total'] == 1
        # the local fleet is untouched: r0 still serves
        assert mesh.predict([PREDICT_LINES[0]], tier='topk',
                            timeout=120)[0].topk_predicted_words
    finally:
        for channel in channels:
            try:
                channel.close()
            except Exception:
                pass
        mesh.close()
        _assert_healing_threads_reaped(mesh)


def _fake_worker_loop(channel, calls):
    """Worker-side control protocol, just enough for adoption: answer
    the re-adopt (load_params + poll_rollover) and the close."""
    try:
        while True:
            msg = channel.recv()
            kind, seq = msg[0], msg[1]
            if kind == 'load_params':
                calls.append(('load_params', msg[2]))
                channel.send(('result', seq, True))
            elif kind == 'poll_rollover':
                channel.send(('result', seq, {'swapped': True}))
            elif kind == 'stats':
                channel.send(('result', seq, {'replica': 'fake'}))
            elif kind == 'close':
                channel.send(('closed', seq))
                return
    except Exception:
        return


def test_adoption_mid_rollover_waits_then_serves_fleet_step(proc_model):
    """An adoption landing while a fleet rollover is in flight WAITS
    the rollover out, then re-adopts the dial-in onto the step the
    fleet settled on — never the step the worker cold-started at."""
    model = proc_model
    with _cfg(model, MESH_HEARTBEAT_SECS=30.0, MESH_HEARTBEAT_MISSES=2):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='socket', max_delay_ms=0.0)
    try:
        with mesh._cond:
            mesh._rollover = {'drill': 'held-open'}
        channel = _dial_raw(mesh, 'ext-roll')
        calls = []
        threading.Thread(target=_fake_worker_loop, args=(channel, calls),
                         daemon=True, name='fake-ext-roll').start()
        channel.send(_ready_frame(model, 123))  # a stale cold-start step
        time.sleep(0.8)  # validated by now; parked on the rollover gate
        assert mesh.stats()['adopted_total'] == 0
        assert calls == []  # NOT re-adopted against the in-flight step
        with mesh._cond:
            mesh._params_step = 5  # the step the rollover settled on
            mesh._rollover = None
            mesh._cond.notify_all()
        _wait_until(lambda: mesh.stats()['adopted_total'] == 1,
                    timeout=30.0, what='post-rollover adoption')
        assert calls == [('load_params', 5)]
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['ext-roll']['adopted'] is True
    finally:
        mesh.close()
        _assert_healing_threads_reaped(mesh)


def test_autoscale_scale_up_spawn_failure_counted_not_fatal(proc_model):
    """The spawn_fail fault point: a scale-up whose worker spawn
    refuses is counted (autoscale/scale_up_failed_total), leaves the
    fleet intact, and every admitted request still drains on the
    existing replicas — a failed scale-up costs latency, not answers."""
    model = proc_model
    with _cfg(model, FAULT_INJECT='slow_dispatch@req=0..63',
              AUTOSCALE_MAX_REPLICAS=2, AUTOSCALE_MIN_REPLICAS=1,
              AUTOSCALE_INTERVAL_SECS=3600.0,
              AUTOSCALE_UP_QUEUE_SECS=0.05,
              AUTOSCALE_UP_COOLDOWN_SECS=0.0,
              MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=4,
              MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5):
        mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                  mode='process', max_delay_ms=0.0)
    try:
        asc = mesh._autoscaler
        assert asc is not None
        mesh.predict([PREDICT_LINES[0]], tier='topk', timeout=120)
        # slow workers + a backlog: the drain estimate crosses the
        # scale-up threshold
        admitted = [mesh.submit([PREDICT_LINES[(i + j) % 3]
                                 for j in range(3)], tier='topk')
                    for i in range(20)]
        faults.configure('spawn_fail@spawn=0')  # parent-side plan
        assert asc.tick() == 'up'
        asc_stats = mesh.stats()['autoscaler']
        assert asc_stats['scale_up_failed_total'] == 1
        assert asc_stats['scale_up_total'] == 0
        assert mesh.stats()['replicas_live'] == 1
        faults.configure('')
        for future in admitted:  # zero lost admitted requests
            assert future.result(timeout=120)
    finally:
        mesh.close()
        _assert_healing_threads_reaped(mesh)


def test_elastic_fleet_acceptance_drill(tmp_path_factory, tmp_path):
    """The ISSUE 18 acceptance drill: a placed socket fleet under
    stepped offered load and mid-batch worker-kill chaos scales
    1 -> 2 -> 1 through the SLO/queue-driven autoscaler with ZERO lost
    admitted requests and ZERO post-warmup parent compiles; replicas
    land on DISJOINT device slices (asserted from placement stats);
    and an EXTERNALLY-spawned worker (scripts/mesh_worker.py, the
    orchestrator path) is adopted mid-run and serves bit-identical
    results on its own slice.  The kill is the kill_worker chaos
    shape — a SIGKILL landing mid-batch (slow_dispatch holds worker
    batches >=250ms so the kill deterministically interrupts one)."""
    from code2vec_tpu.telemetry import core
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener
    model = _checkpointed_model(tmp_path_factory, 'elastic')
    core.reset()
    core.enable()
    mesh = None
    ext = None
    try:
        assert install_compile_listener()
        compiles = core.registry().counter('jit/compiles_total')
        with _cfg(model, FAULT_INJECT='slow_dispatch@req=0..63',
                  MESH_DEVICES_PER_REPLICA=2,
                  AUTOSCALE_MAX_REPLICAS=2, AUTOSCALE_MIN_REPLICAS=1,
                  AUTOSCALE_INTERVAL_SECS=3600.0,  # drills drive tick()
                  AUTOSCALE_UP_QUEUE_SECS=0.05,
                  AUTOSCALE_UP_COOLDOWN_SECS=0.0,
                  AUTOSCALE_DOWN_COOLDOWN_SECS=0.0,
                  AUTOSCALE_DOWN_IDLE_SECS=0.3,
                  AUTOSCALE_DOWN_UTILIZATION=0.9,
                  AUTOSCALE_FLAP_LIMIT=10,
                  MESH_HEARTBEAT_SECS=0.25, MESH_HEARTBEAT_MISSES=4,
                  MESH_RESTART_BACKOFF_SECS=0.05, MESH_RESTART_LIMIT=5):
            mesh = model.serving_mesh(replicas=1, tiers=('topk',),
                                      mode='socket', max_delay_ms=0.0)
        asc = mesh._autoscaler
        assert asc is not None
        stats = mesh.stats()
        assert stats['placement'] == {'devices_per_replica': 2,
                                      'slices': 2, 'data_axis': 2}
        assert stats['replicas'][0]['devices'] == [0, 1]
        # the fleet's reference answers (replica r0 on its 2-device
        # slice): every later result — sibling, restarted, adopted —
        # must match these BIT-identically
        expected = {
            line: mesh.predict([line], tier='topk',
                               timeout=180)[0].topk_predicted_words
            for line in PREDICT_LINES}
        warm = compiles.value
        # external orchestrator leg: exec scripts/mesh_worker.py
        # against the listener with its own disjoint slice; it cold
        # starts CONCURRENTLY with the load phases below
        overrides = dict(mesh._model_config_overrides)
        cfg_path = tmp_path / 'ext_worker.json'
        cfg_path.write_text(json.dumps(overrides))
        host, port = mesh._listener.address
        script = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(mesh_lib.__file__))), '..', 'scripts',
            'mesh_worker.py')
        ext = subprocess.Popen(
            [sys.executable, os.path.abspath(script),
             '--address', '%s:%d' % (host, port), '--rid', 'ext-drill',
             '--config-json', str(cfg_path), '--device-indices', '4,5'],
            env=dict(os.environ, JAX_PLATFORMS='cpu'),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        # ---- load step UP: backlog outruns one slow replica ----
        wave1 = [[PREDICT_LINES[(i + j) % 3] for j in range(3)]
                 for i in range(20)]
        admitted = [(lines, mesh.submit(lines, tier='topk'))
                    for lines in wave1]
        t_up = time.perf_counter()
        assert asc.tick() == 'up'  # blocks through the worker spawn
        scale_up_s = time.perf_counter() - t_up
        assert scale_up_s < 150.0
        assert mesh.stats()['autoscaler']['scale_up_total'] == 1
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['r1']['devices'] == [2, 3]  # disjoint slice
        # ---- chaos: SIGKILL r0 mid-batch while load is in flight ----
        wave2 = [[PREDICT_LINES[(i + j) % 3] for j in range(3)]
                 for i in range(20)]
        admitted += [(lines, mesh.submit(lines, tier='topk'))
                     for lines in wave2]
        slot0 = mesh._replicas[0]
        _wait_until(lambda: slot0.inflight >= 1, timeout=60.0,
                    what='r0 to hold an in-flight batch')
        os.kill(slot0.transport.pid, signal.SIGKILL)
        # zero lost admitted requests, all answers bit-identical
        for lines, future in admitted:
            results = future.result(timeout=180)
            assert len(results) == len(lines)
            for line, result in zip(lines, results):
                assert result.topk_predicted_words == expected[line]
        _wait_until(lambda: mesh.stats()['restarts_total'] >= 1,
                    timeout=120.0, what='supervised restart of r0')
        # ---- adoption lands mid-run ----
        _wait_until(lambda: mesh.stats()['adopted_total'] >= 1,
                    timeout=300.0, what='adoption of ext-drill')
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['ext-drill']['adopted'] is True
        assert rows['ext-drill']['devices'] == [4, 5]  # its own slice
        ext_slot = next(s for s in mesh._replicas
                        if s.rid == 'ext-drill')
        deadline = time.perf_counter() + 120.0
        while ext_slot.batches == 0:  # until the adoptee itself served
            assert time.perf_counter() < deadline, \
                'adopted worker never served'
            for line in PREDICT_LINES:
                (res,) = mesh.predict([line], tier='topk', timeout=180)
                assert res.topk_predicted_words == expected[line]
        per = {s.get('replica'): s for s in mesh.replica_stats()}
        assert per['ext-drill'].get('params_step') == \
            mesh.stats()['params_step']
        # ---- orchestrator-owned death: no local budget charge ----
        restarts_before = mesh.stats()['restarts_total']
        ext.kill()
        _wait_until(lambda: ext_slot.retired, timeout=60.0,
                    what='adopted-worker exit retirement')
        assert ext_slot.retired_reason == 'adopted_worker_exit'
        time.sleep(0.6)
        assert mesh.stats()['restarts_total'] == restarts_before
        # ---- load steps DOWN: sustained idleness drains r1 out ----
        assert asc.tick() in ('hold', 'down')  # idle clock starts
        time.sleep(0.4)
        _wait_until(lambda: asc.tick() in ('down', 'hold')
                    and mesh.stats()['autoscaler']['scale_down_total']
                    >= 1, timeout=60.0, what='autoscaler scale-down')
        rows = {r['replica']: r for r in mesh.stats()['replicas']}
        assert rows['r1']['retired'] is True
        assert rows['r1']['retired_reason'] == 'autoscale'
        assert mesh.stats()['replicas_live'] == 1
        # the drained fleet still serves, still bit-identical
        (res,) = mesh.predict([PREDICT_LINES[1]], tier='topk',
                              timeout=180)
        assert res.topk_predicted_words == expected[PREDICT_LINES[1]]
        # zero post-warmup compiles in the parent across scale-up,
        # kill+restart, adoption, and scale-down
        assert compiles.value - warm == 0, (
            '%d parent-side compiles during the elastic drill'
            % (compiles.value - warm))
    finally:
        if ext is not None:
            ext.kill()
            ext.wait(timeout=60)
        if mesh is not None:
            mesh.close()
            _assert_healing_threads_reaped(mesh)
        model.close_stores()
        core.disable()
        core.reset()
