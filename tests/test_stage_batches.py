"""Device staging (Trainer.stage_batches) semantics: order preservation,
host-batch ride-along, and the CPU lookahead gate (the XLA:CPU in-process
collective rendezvous can deadlock with extra async placements in flight,
so on CPU meshes the depth must degenerate to place-then-consume)."""
import numpy as np

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import Batch
from code2vec_tpu.models.backends import create_backend
from code2vec_tpu.training.trainer import Trainer
from code2vec_tpu.vocab import SizeOnlyVocabs


def make_trainer(**overrides):
    config = Config(
        TRAIN_DATA_PATH_PREFIX='unused', DL_FRAMEWORK='jax',
        VERBOSE_MODE=0, READER_USE_NATIVE=False, MAX_CONTEXTS=4,
        TRAIN_BATCH_SIZE=8, TEST_BATCH_SIZE=8, COMPUTE_DTYPE='float32',
        MAX_TOKEN_VOCAB_SIZE=32, MAX_PATH_VOCAB_SIZE=16,
        MAX_TARGET_VOCAB_SIZE=16, TOKEN_EMBEDDINGS_SIZE=8,
        PATH_EMBEDDINGS_SIZE=8, CODE_VECTOR_SIZE=24,
        TARGET_EMBEDDINGS_SIZE=24, **overrides)
    backend = create_backend(config, SizeOnlyVocabs(32, 16, 16))
    return Trainer(config, backend)


def make_batches(n, batch=8, contexts=4):
    rng = np.random.default_rng(0)
    return [Batch(
        source=rng.integers(1, 32, (batch, contexts)).astype(np.int32),
        path=rng.integers(1, 16, (batch, contexts)).astype(np.int32),
        target=rng.integers(1, 32, (batch, contexts)).astype(np.int32),
        mask=np.ones((batch, contexts), np.float32),
        label=np.full((batch,), i % 16, np.int32),
        weight=np.ones((batch,), np.float32)) for i in range(n)]


def test_stage_batches_preserves_order_and_batches():
    trainer = make_trainer(DEVICE_PREFETCH_BATCHES=2)
    batches = make_batches(5)
    out = list(trainer.stage_batches(iter(batches)))
    assert len(out) == 5
    for i, (arrays, host_batch) in enumerate(out):
        assert host_batch is batches[i]
        # placed arrays hold the same values as the host batch
        np.testing.assert_array_equal(np.asarray(arrays[0]),
                                      batches[i].source)
        np.testing.assert_array_equal(np.asarray(arrays[4]), batches[i].label)


def test_stage_batches_cpu_lookahead_is_disabled():
    """On a CPU mesh the generator must not place ahead of consumption:
    after pulling item k, exactly k+1 placements may have happened."""
    trainer = make_trainer(DEVICE_PREFETCH_BATCHES=4)
    placed_log = []
    orig = trainer.mesh  # the gate keys off the mesh devices
    assert orig.devices.flat[0].platform.lower() == 'cpu'

    from code2vec_tpu.parallel import mesh as mesh_lib
    real_shard_batch = mesh_lib.shard_batch

    def counting_shard_batch(arrays, mesh, shard_contexts, **kwargs):
        placed_log.append(1)
        return real_shard_batch(arrays, mesh, shard_contexts, **kwargs)

    mesh_lib.shard_batch, saved = counting_shard_batch, real_shard_batch
    try:
        gen = trainer.stage_batches(iter(make_batches(4)))
        next(gen)
        assert sum(placed_log) == 1  # no lookahead on CPU
        next(gen)
        assert sum(placed_log) == 2
        gen.close()
    finally:
        mesh_lib.shard_batch = saved


def test_stage_batches_empty_iterator():
    trainer = make_trainer()
    assert list(trainer.stage_batches(iter([]))) == []


class TestGroupedTopK:
    """grouped_top_k must match lax.top_k exactly, ties included."""

    def _check(self, x, k, group_size):
        import jax
        import jax.numpy as jnp
        from code2vec_tpu.ops.topk import grouped_top_k
        want_v, want_i = jax.lax.top_k(jnp.asarray(x), k)
        got_v, got_i = grouped_top_k(jnp.asarray(x), k,
                                     group_size=group_size)
        np.testing.assert_array_equal(np.asarray(got_v), np.asarray(want_v))
        np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))

    def test_random_matches_lax(self):
        rng = np.random.default_rng(0)
        self._check(rng.normal(size=(7, 1000)).astype(np.float32), 10, 64)

    def test_uneven_group_padding(self):
        rng = np.random.default_rng(1)
        self._check(rng.normal(size=(3, 997)).astype(np.float32), 10, 64)

    def test_ties_break_by_lowest_index(self):
        # many duplicate values spread across group boundaries
        rng = np.random.default_rng(2)
        x = rng.integers(0, 5, size=(5, 512)).astype(np.float32)
        self._check(x, 16, 32)

    def test_small_vocab_falls_back(self):
        rng = np.random.default_rng(3)
        self._check(rng.normal(size=(2, 50)).astype(np.float32), 10, 64)

    def test_k_not_exceeding_group(self):
        rng = np.random.default_rng(4)
        self._check(rng.normal(size=(2, 300)).astype(np.float32), 40, 32)

