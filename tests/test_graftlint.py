"""graftlint engine + rules (ISSUE 6 tentpole; ANALYSIS.md).

Three layers of guarantee:

1. **per-rule**: each rule fires on a seeded-violation snippet and stays
   quiet on the fixed version (the rule demonstrably detects what it
   claims to);
2. **mechanics**: inline suppressions need reasons, baselines need
   reasons, stale baseline entries and stale catalogs are findings;
3. **tier-1 guard**: the repo itself is CLEAN — zero unbaselined,
   unsuppressed findings across every registered rule — and the full
   pass stays far under its latency budget.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from code2vec_tpu.analysis import engine  # noqa: E402
from code2vec_tpu.analysis import rules as _rules  # noqa: E402,F401
from code2vec_tpu.analysis.core import all_rules  # noqa: E402
from code2vec_tpu.analysis.walker import SourceTree  # noqa: E402


def lint(tmp_path, code, rule_names, extra_files=None):
    """Run rules over one synthetic module in a tmp tree."""
    pkg = tmp_path / 'pkg'
    pkg.mkdir(exist_ok=True)
    (pkg / 'mod.py').write_text(code)
    for name, text in (extra_files or {}).items():
        (tmp_path / name).write_text(text)
    tree = SourceTree(str(tmp_path), scan_dirs=('pkg',), scan_files=(),
                      package_dirs=('pkg',))
    return engine.run(root=str(tmp_path), rule_names=rule_names,
                      baseline_path='', tree=tree)


def by_rule(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ------------------------------------------------------ recompile-hazard
SEEDED_UNBUCKETED = '''
import jax
import numpy as np

program = jax.jit(lambda x: x)

def hot(rows):
    n = len(rows)
    batch = np.zeros((n, 8), np.float32)
    return program(batch)
'''

FIXED_BUCKETED = '''
import jax
import numpy as np

program = jax.jit(lambda x: x)
LADDER = (8, 64, 512)

def hot(rows):
    n = pick_bucket(len(rows), LADDER)
    batch = np.zeros((n, 8), np.float32)
    return program(batch)
'''


def test_recompile_hazard_fires_on_unbucketed_shape(tmp_path):
    report = lint(tmp_path, SEEDED_UNBUCKETED, ['recompile-hazard'])
    found = by_rule(report, 'recompile-hazard')
    assert len(found) == 1, report.findings
    assert 'batch' in found[0].message and 'len()' in found[0].message


def test_recompile_hazard_quiet_on_warm_ladder(tmp_path):
    report = lint(tmp_path, FIXED_BUCKETED, ['recompile-hazard'])
    assert not by_rule(report, 'recompile-hazard'), report.findings


def test_recompile_hazard_flags_keyword_args_too(tmp_path):
    # `program(x=pad)` is the same hazard as `program(pad)`
    code = ('import jax\n'
            'import numpy as np\n'
            'program = jax.jit(lambda x: x)\n'
            'def hot(rows):\n'
            '    pad = np.zeros((len(rows), 4))\n'
            '    return program(x=pad)\n')
    report = lint(tmp_path, code, ['recompile-hazard'])
    found = by_rule(report, 'recompile-hazard')
    assert len(found) == 1 and 'pad' in found[0].message


def test_recompile_hazard_flags_inline_jit(tmp_path):
    code = ('import jax\n'
            'def resize(leaf):\n'
            '    return jax.jit(lambda x: x * 2)(leaf)\n')
    report = lint(tmp_path, code, ['recompile-hazard'])
    found = by_rule(report, 'recompile-hazard')
    assert len(found) == 1 and 'inline jax.jit' in found[0].message


def test_recompile_hazard_flags_nested_def_jit(tmp_path):
    code = ('import jax\n'
            'def build(data):\n'
            '    @jax.jit\n'
            '    def step(x):\n'
            '        return x\n'
            '    return step(data)\n')
    report = lint(tmp_path, code, ['recompile-hazard'])
    assert any('nested def' in f.message
               for f in by_rule(report, 'recompile-hazard'))


def test_recompile_hazard_pad_to_bucket_idiom_is_warm(tmp_path):
    # the np.concatenate([x, zeros((bucket - n, d))]) pad idiom: the
    # WARM pad launders the join (exact.py/ivf.py query padding)
    code = ('import jax\n'
            'import numpy as np\n'
            'program = jax.jit(lambda x: x)\n'
            'def hot(queries, ladder):\n'
            '    n = queries.shape[0]\n'
            '    bucket = pick_bucket(n, ladder)\n'
            '    if bucket != n:\n'
            '        queries = np.concatenate(\n'
            '            [queries, np.zeros((bucket - n, 4))])\n'
            '    return program(queries)\n')
    report = lint(tmp_path, code, ['recompile-hazard'])
    assert not by_rule(report, 'recompile-hazard'), report.findings


# ------------------------------------------------------------- host-sync
SEEDED_SYNC = '''
import jax
import numpy as np

def hot(trainer, state, arrays):
    state, loss = trainer.train_step_placed(state, arrays)
    return state, float(loss)

def drain(xs):
    return jax.device_get(xs)

def wait(tree):
    jax.block_until_ready(tree)

def scalar(x):
    return x.item()
'''

FIXED_SYNC = '''
def hot(trainer, state, arrays):
    state, loss = trainer.train_step_placed(state, arrays)
    return state, loss  # stays on device; the log window syncs later
'''


def test_host_sync_fires_on_all_four_kinds(tmp_path):
    report = lint(tmp_path, SEEDED_SYNC, ['host-sync'])
    found = by_rule(report, 'host-sync')
    kinds = sorted(f.message.split('(')[1].split(')')[0] for f in found)
    assert kinds == ['block_until_ready', 'device_get', 'fetch', 'item']


def test_host_sync_quiet_when_value_stays_on_device(tmp_path):
    report = lint(tmp_path, FIXED_SYNC, ['host-sync'])
    assert not by_rule(report, 'host-sync'), report.findings


def test_host_sync_fetch_requires_device_taint(tmp_path):
    # np.asarray over plain host data is NOT a sync — the staging
    # pipeline np.asarray's constantly
    code = ('import numpy as np\n'
            'def stage(batch):\n'
            '    return [np.asarray(a) for a in batch]\n')
    report = lint(tmp_path, code, ['host-sync'])
    assert not by_rule(report, 'host-sync'), report.findings


def test_host_sync_catalog_counts_are_exact():
    """The repo's sanctioned-sync catalog matches reality site-for-site
    (counts pinned, nothing stale) — asserted via the full repo run in
    test_repo_is_clean; here: the catalog is non-trivial."""
    from code2vec_tpu.analysis.catalog import SANCTIONED_SYNCS
    assert len(SANCTIONED_SYNCS) >= 10
    for entry in SANCTIONED_SYNCS:
        assert entry['reason'].strip(), entry
        assert entry['count'] >= 1


# ------------------------------------------------------- donation-safety
SEEDED_DONATION = '''
def fit(self, state, arrays):
    state, loss = self._train_step(state, arrays)
    total = arrays[0].sum()   # read-after-donate
    return state, total
'''

FIXED_DONATION = '''
def fit(self, state, arrays):
    total = arrays[0].sum()   # read BEFORE the donating dispatch
    state, loss = self._train_step(state, arrays)
    return state, total
'''


def test_donation_fires_on_read_after_donate(tmp_path):
    report = lint(tmp_path, SEEDED_DONATION, ['donation-safety'])
    found = by_rule(report, 'donation-safety')
    assert len(found) == 1 and '`arrays`' in found[0].message


def test_donation_quiet_when_read_moves_before(tmp_path):
    report = lint(tmp_path, FIXED_DONATION, ['donation-safety'])
    assert not by_rule(report, 'donation-safety'), report.findings


def test_donation_ignores_sibling_branches(tmp_path):
    # the trainer's arity dispatch: packed and planes arms are exclusive
    code = ('def step(self, state, arrays):\n'
            '    if len(arrays) == 4:\n'
            '        return self._train_step_packed(state, arrays)\n'
            '    return self._train_step(state, arrays)\n')
    report = lint(tmp_path, code, ['donation-safety'])
    assert not by_rule(report, 'donation-safety'), report.findings


# ----------------------------------------------------------- jit-purity
SEEDED_IMPURE = '''
import time

import jax


@jax.jit
def step(x):
    t0 = time.perf_counter()
    return x * t0
'''

FIXED_PURE = '''
import jax


@jax.jit
def step(x, key):
    noise = jax.random.normal(key, x.shape)  # jax.random is the pure way
    return x + noise
'''


def test_jit_purity_fires_on_time_in_jitted_body(tmp_path):
    report = lint(tmp_path, SEEDED_IMPURE, ['jit-purity'])
    found = by_rule(report, 'jit-purity')
    assert len(found) == 1 and 'time.perf_counter' in found[0].message


def test_jit_purity_quiet_on_jax_random(tmp_path):
    report = lint(tmp_path, FIXED_PURE, ['jit-purity'])
    assert not by_rule(report, 'jit-purity'), report.findings


def test_jit_purity_covers_every_jit_spelling(tmp_path):
    # by-name discovery must agree with the taint pass on what counts
    # as jitted: pjit's full path and the partial(jax.jit, ...) form
    code = ('import functools\n'
            'import time\n'
            'import jax\n'
            'def body_a(x):\n'
            '    return x * time.time()\n'
            'def body_b(x):\n'
            '    return x * time.time()\n'
            'prog_a = jax.experimental.pjit.pjit(body_a)\n'
            'prog_b = functools.partial(jax.jit, donate_argnums=0)('
            'body_b)\n')
    report = lint(tmp_path, code, ['jit-purity'])
    found = by_rule(report, 'jit-purity')
    assert len(found) == 2, report.findings


def test_jit_purity_covers_jit_by_reference_and_nested_defs(tmp_path):
    code = ('import jax\n'
            'import numpy as np\n'
            'def build():\n'
            '    def train_step(state):\n'
            '        def loss_fn(p):\n'
            '            return p * np.random.rand()\n'
            '        return loss_fn(state)\n'
            '    return jax.jit(train_step)\n')
    report = lint(tmp_path, code, ['jit-purity'])
    found = by_rule(report, 'jit-purity')
    assert len(found) == 1 and 'np.random.rand' in found[0].message


# ------------------------------------------------------- lock-discipline
SEEDED_UNGUARDED = '''
import threading


class Engine:
    # graftlint: guard Engine._queue by _lock
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []

    def put(self, item):
        self._queue.append(item)

    def depth_locked(self):
        return len(self._queue)

    def get(self):
        with self._lock:
            return self._queue.pop()
'''

FIXED_GUARDED = SEEDED_UNGUARDED.replace(
    '''    def put(self, item):
        self._queue.append(item)
''',
    '''    def put(self, item):
        with self._lock:
            self._queue.append(item)
''')


def test_lock_discipline_fires_on_unguarded_access(tmp_path):
    report = lint(tmp_path, SEEDED_UNGUARDED, ['lock-discipline'])
    found = by_rule(report, 'lock-discipline')
    # exactly the `put` access: __init__ and *_locked are exempt, `get`
    # holds the lock
    assert len(found) == 1, report.findings
    assert '`put`' in found[0].message


def test_lock_discipline_quiet_when_guarded(tmp_path):
    report = lint(tmp_path, FIXED_GUARDED, ['lock-discipline'])
    assert not by_rule(report, 'lock-discipline'), report.findings


def test_lock_discipline_flags_stale_annotation(tmp_path):
    code = ('import threading\n'
            'class Thing:\n'
            '    # graftlint: guard Thing._ghost by _lock\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n')
    report = lint(tmp_path, code, ['lock-discipline'])
    assert any('stale guard annotation' in f.message
               for f in by_rule(report, 'lock-discipline'))


def test_lock_discipline_wrong_lock_does_not_count(tmp_path):
    # two guard groups on one class stay separate: holding lock A does
    # not guard a field declared under lock B
    code = ('import threading\n'
            'class E:\n'
            '    # graftlint: guard E._queue by _lock\n'
            '    # graftlint: guard E._warm by _warm_lock\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '        self._warm_lock = threading.Lock()\n'
            '        self._queue = []\n'
            '        self._warm = False\n'
            '    def bad(self):\n'
            '        with self._lock:\n'
            '            self._warm = True\n'
            '    def good(self):\n'
            '        with self._warm_lock:\n'
            '            self._warm = True\n'
            '        with self._lock:\n'
            '            self._queue.append(1)\n')
    report = lint(tmp_path, code, ['lock-discipline'])
    found = by_rule(report, 'lock-discipline')
    assert len(found) == 1, report.findings
    assert '`bad`' in found[0].message and '_warm' in found[0].message


def test_lock_discipline_condition_alias(tmp_path):
    code = ('import threading\n'
            'class W:\n'
            '    # graftlint: guard W._stop by _lock|_cond\n'
            '    def __init__(self):\n'
            '        self._lock = threading.Lock()\n'
            '        self._cond = threading.Condition(self._lock)\n'
            '        self._stop = False\n'
            '    def shutdown(self):\n'
            '        with self._cond:\n'
            '            self._stop = True\n')
    report = lint(tmp_path, code, ['lock-discipline'])
    assert not by_rule(report, 'lock-discipline'), report.findings


# ------------------------------------------------------ config-knob-docs
def test_config_knob_fires_on_undocumented_env_var(tmp_path):
    code = ("import os\n"
            "LIMIT = os.environ.get('PKG_SECRET_LIMIT', '8')\n")
    report = lint(tmp_path, code, ['config-knob-docs'],
                  extra_files={'README.md': '# docs\nnothing here\n'})
    found = by_rule(report, 'config-knob-docs')
    assert len(found) == 1 and 'PKG_SECRET_LIMIT' in found[0].message


def test_config_knob_quiet_when_documented(tmp_path):
    code = ("import os\n"
            "LIMIT = os.environ.get('PKG_SECRET_LIMIT', '8')\n")
    report = lint(tmp_path, code, ['config-knob-docs'],
                  extra_files={'README.md': 'set `PKG_SECRET_LIMIT`\n'})
    assert not by_rule(report, 'config-knob-docs'), report.findings


def test_config_knob_changelog_mention_is_not_documentation(tmp_path):
    # CHANGES.md names every flag a PR adds; counting it as docs would
    # make the rule structurally vacuous
    code = ("import os\n"
            "LIMIT = os.environ.get('PKG_SECRET_LIMIT', '8')\n")
    report = lint(tmp_path, code, ['config-knob-docs'],
                  extra_files={'CHANGES.md': 'adds PKG_SECRET_LIMIT\n',
                               'README.md': 'real docs, knob absent\n'})
    found = by_rule(report, 'config-knob-docs')
    assert len(found) == 1 and 'PKG_SECRET_LIMIT' in found[0].message


def test_standalone_cli_reports_only_its_own_rule(tmp_path, monkeypatch):
    # an unrelated graftlint meta-finding (reason-less suppression) must
    # not fail the standalone metrics CLI as a 'metric-schema violation'
    import check_metrics_schema as cms
    from code2vec_tpu.telemetry.catalog import CATALOG
    pkg = tmp_path / 'code2vec_tpu'
    pkg.mkdir()
    (pkg / 'mod.py').write_text(
        '# graftlint: disable=host-sync\nX = 1\n')
    (tmp_path / 'OBSERVABILITY.md').write_text('\n'.join(CATALOG))
    monkeypatch.setattr(cms, 'REPO', str(tmp_path))
    assert cms.main([]) == 0


def test_taint_analysis_is_cached_per_file(tmp_path):
    from code2vec_tpu.analysis import taint
    pkg = tmp_path / 'pkg'
    pkg.mkdir()
    (pkg / 'mod.py').write_text('def f(x):\n    return x\n')
    tree = SourceTree(str(tmp_path), scan_dirs=('pkg',), scan_files=(),
                      package_dirs=('pkg',))
    source = tree.files('all')[0]
    assert taint.analyze_file(source) is taint.analyze_file(source)


# --------------------------------------------------------- span-catalog
SEEDED_SPAN = '''
def serve(tracer):
    trace = tracer.begin('serving.bogus_phase')
    trace.span_at('serving.also_bogus', 0.0, 1.0)
    trace.finish()
'''


def test_span_catalog_fires_on_uncataloged_span(tmp_path):
    report = lint(tmp_path, SEEDED_SPAN, ['span-catalog'])
    messages = [f.message for f in by_rule(report, 'span-catalog')]
    assert any('serving.bogus_phase' in m for m in messages), messages
    assert any('serving.also_bogus' in m for m in messages), messages


def test_span_catalog_quiet_on_cataloged_names(tmp_path):
    code = SEEDED_SPAN.replace('serving.bogus_phase', 'serving.request') \
                      .replace('serving.also_bogus', 'serving.pack')
    report = lint(tmp_path, code, ['span-catalog'])
    # the synthetic module itself is clean (the tree-wide stale-entry /
    # doc findings attach to the catalog file and the doc, not pkg/)
    offending = [f for f in by_rule(report, 'span-catalog')
                 if f.file.startswith('pkg')]
    assert not offending, offending


def test_span_catalog_flags_stale_unwired_entries(tmp_path):
    # a tree that wires nothing: every cataloged span is a stale entry
    report = lint(tmp_path, 'X = 1\n', ['span-catalog'])
    messages = [f.message for f in by_rule(report, 'span-catalog')]
    assert any('no emission site' in m and 'serving.request' in m
               for m in messages), messages


def test_span_catalog_doc_coverage(tmp_path):
    from code2vec_tpu.telemetry.tracing import SPAN_CATALOG
    # doc names every span -> no 'undocumented' findings; drop one name
    # -> exactly that finding appears
    full_doc = '\n'.join(SPAN_CATALOG)
    report = lint(tmp_path, 'X = 1\n', ['span-catalog'],
                  extra_files={'OBSERVABILITY.md': full_doc})
    assert not any('undocumented' in f.message
                   for f in by_rule(report, 'span-catalog'))
    partial = full_doc.replace('serving.device_execute', '')
    report = lint(tmp_path, 'X = 1\n', ['span-catalog'],
                  extra_files={'OBSERVABILITY.md': partial})
    undocumented = [f.message for f in by_rule(report, 'span-catalog')
                    if 'undocumented' in f.message]
    assert undocumented == ["cataloged span 'serving.device_execute' "
                            'is undocumented'], undocumented


def test_span_catalog_ignores_non_dotted_and_variable_names(tmp_path):
    # threading.Event()/argparse-ish calls and variable-name forwarding
    # must not count as span sites
    code = ('def f(trace, name, evt):\n'
            "    evt.begin('not_dotted')\n"
            '    trace.span(name)\n'
            "    d = {}.get('a/b')\n")
    report = lint(tmp_path, code, ['span-catalog'])
    offending = [f for f in by_rule(report, 'span-catalog')
                 if f.file.startswith('pkg')]
    assert not offending, offending


# ------------------------------------------------- suppression mechanics
def test_suppression_with_reason_silences(tmp_path):
    code = SEEDED_DONATION.replace(
        '    total = arrays[0].sum()   # read-after-donate',
        '    # graftlint: disable=donation-safety -- test: sanctioned\n'
        '    total = arrays[0].sum()')
    report = lint(tmp_path, code, ['donation-safety'])
    assert not report.findings, report.findings
    assert len(report.suppressed) == 1


def test_suppression_without_reason_is_a_finding_and_inert(tmp_path):
    code = SEEDED_DONATION.replace(
        '    total = arrays[0].sum()   # read-after-donate',
        '    # graftlint: disable=donation-safety\n'
        '    total = arrays[0].sum()')
    report = lint(tmp_path, code, ['donation-safety'])
    rules = {f.rule for f in report.findings}
    # the original finding survives AND the bare suppression is flagged
    assert rules == {'donation-safety', 'graftlint'}, report.findings


def test_suppression_disable_all_is_rejected(tmp_path):
    code = ('# graftlint: disable-file=all -- lazy\n'
            + SEEDED_DONATION)
    report = lint(tmp_path, code, ['donation-safety'])
    assert any('disable=all' in f.message or 'blanket' in f.message
               for f in report.findings), report.findings
    assert by_rule(report, 'donation-safety'), 'all must not suppress'


def test_stale_suppression_is_a_finding(tmp_path):
    # suppression left behind after the code under it was fixed
    code = FIXED_DONATION.replace(
        '    total = arrays[0].sum()   # read BEFORE the donating dispatch',
        '    # graftlint: disable=donation-safety -- obsolete: fixed below\n'
        '    total = arrays[0].sum()')
    report = lint(tmp_path, code, ['donation-safety'])
    assert any('stale suppression' in f.message
               for f in report.findings), report.findings


def test_stale_suppression_ignores_unrun_rules(tmp_path):
    # a --rules subset must not flag other rules' suppressions as stale
    code = ('# graftlint: disable=jit-purity -- owned by a rule not run\n'
            'X = 1\n')
    report = lint(tmp_path, code, ['donation-safety'])
    assert not report.findings, report.findings


def test_docstring_examples_are_not_suppressions(tmp_path):
    code = ('"""Doc: use `# graftlint: disable=donation-safety -- why`\n'
            'on the offending line."""\n' + SEEDED_DONATION)
    report = lint(tmp_path, code, ['donation-safety'])
    assert by_rule(report, 'donation-safety'), \
        'a docstring example must not suppress anything'


# --------------------------------------------------- baseline mechanics
def run_with_baseline(tmp_path, code, entries):
    pkg = tmp_path / 'pkg'
    pkg.mkdir(exist_ok=True)
    (pkg / 'mod.py').write_text(code)
    baseline = tmp_path / 'graftlint_baseline.json'
    baseline.write_text(json.dumps({'entries': entries}))
    tree = SourceTree(str(tmp_path), scan_dirs=('pkg',), scan_files=(),
                      package_dirs=('pkg',))
    return engine.run(root=str(tmp_path), rule_names=['donation-safety'],
                      baseline_path=str(baseline), tree=tree)


DONATION_MSG = ('read of `arrays` in `fit` after it was donated to '
                '`_train_step` (arg 1) — the step may alias/overwrite '
                'its buffer; rebind or copy before the dispatch')


def test_baseline_entry_absorbs_finding(tmp_path):
    report = run_with_baseline(tmp_path, SEEDED_DONATION, [
        {'rule': 'donation-safety', 'file': os.path.join('pkg', 'mod.py'),
         'message': DONATION_MSG, 'reason': 'test: accepted debt'}])
    assert not report.findings, report.findings
    assert len(report.baselined) == 1


def test_bare_baseline_entry_is_a_finding(tmp_path):
    report = run_with_baseline(tmp_path, SEEDED_DONATION, [
        {'rule': 'donation-safety', 'file': os.path.join('pkg', 'mod.py'),
         'message': DONATION_MSG, 'reason': 'TODO'}])
    assert any('bare baseline entry' in f.message
               for f in report.findings), report.findings


def test_stale_baseline_entry_is_a_finding(tmp_path):
    report = run_with_baseline(tmp_path, FIXED_DONATION, [
        {'rule': 'donation-safety', 'file': os.path.join('pkg', 'mod.py'),
         'message': DONATION_MSG, 'reason': 'test: accepted debt'}])
    assert any('stale baseline entry' in f.message
               for f in report.findings), report.findings


def test_rule_subset_run_ignores_other_rules_baseline_entries(tmp_path):
    # a --rules subset run must not report another rule's baseline
    # entries as stale (they had no chance to match)
    report = run_with_baseline(tmp_path, FIXED_DONATION, [
        {'rule': 'jit-purity', 'file': os.path.join('pkg', 'mod.py'),
         'message': 'some other rule finding',
         'reason': 'test: owned by a rule this run does not execute'}])
    assert not report.findings, report.findings


def test_rule_subset_run_against_repo_baseline_is_clean():
    # the CLI-documented `--rules host-sync` usage: the repo baseline's
    # recompile-hazard entries must not surface as stale
    report = engine.run(rule_names=['host-sync'])
    assert report.clean, [f.format() for f in report.findings]


def test_write_baseline_preserves_unrun_rules_entries(tmp_path):
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'graftlint.py'),
         '--rules', 'host-sync', '--write-baseline',
         '--baseline', str(tmp_path / 'bl.json')],
        capture_output=True, text=True,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert result.returncode == 0, result.stdout + result.stderr
    # seed the target with another rule's reviewed entry, rewrite with a
    # subset, and check the entry (and its reason) survived
    entry = {'rule': 'recompile-hazard', 'file': 'code2vec_tpu/x.py',
             'message': 'reviewed finding', 'reason': 'reviewed reason'}
    (tmp_path / 'bl.json').write_text(json.dumps({'entries': [entry]}))
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'graftlint.py'),
         '--rules', 'host-sync', '--write-baseline',
         '--baseline', str(tmp_path / 'bl.json')],
        capture_output=True, text=True,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert result.returncode == 0, result.stdout + result.stderr
    data = json.loads((tmp_path / 'bl.json').read_text())
    assert entry in data['entries'], data


# ------------------------------------------------------- tier-1 guards
def test_every_rule_is_registered_and_documented():
    names = {rule.name for rule in all_rules()}
    assert {'recompile-hazard', 'host-sync', 'donation-safety',
            'jit-purity', 'lock-discipline', 'config-knob-docs',
            'metrics-schema', 'fault-points', 'span-catalog'} <= names
    with open(os.path.join(REPO, 'ANALYSIS.md')) as f:
        doc = f.read()
    for name in sorted(names):
        assert name in doc, \
            'rule %r is missing from the ANALYSIS.md catalog' % name


def test_repo_is_clean():
    """THE tier-1 guard: zero unbaselined, unsuppressed findings across
    every rule, and every suppression/baseline carries a reason (the
    engine turns reason-less ones into findings)."""
    report = engine.run()
    assert report.clean, 'graftlint findings:\n%s' % '\n'.join(
        f.format() for f in report.findings)
    # the invariants the rules exist for are actually being exercised
    assert report.suppressed, 'expected at least one reasoned suppression'
    assert report.baselined, 'expected at least one reasoned baseline hit'


def test_full_pass_is_fast():
    """The lint pass must stay far from the tier-1 cliff (<20s budget,
    ANALYSIS.md; typically ~2s)."""
    report = engine.run()
    assert report.elapsed_s < 20, report.elapsed_s


def test_lint_all_cli_exits_zero():
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, 'scripts', 'lint_all.py')],
        capture_output=True, text=True,
        env={**os.environ, 'JAX_PLATFORMS': 'cpu'})
    assert result.returncode == 0, result.stdout + result.stderr
    assert '0 finding(s)' in result.stdout
