"""Device-memory ledger tests (ISSUE 9): attribution semantics,
snapshot diffs as the leak check, the HBM budget gate (typed failure
BEFORE allocation + forensic dump), OOM forensics, MEM_NOW, the
reconciliation acceptance on a CPU fit and a serving+index smoke, the
canaried-rollover leak drill, zero-host-sync bookkeeping, and the
graftlint ``alloc-catalog`` rule."""
import gc
import json
import os
import sys

import numpy as np
import pytest

from code2vec_tpu.telemetry import core
from code2vec_tpu.telemetry import memory
from code2vec_tpu.telemetry.memory import (MemoryBudgetExceeded,
                                           MemoryLedger)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_state():
    """Ledger + registry reset around every test: both are
    process-global by design."""
    memory.reset()
    core.reset()
    core.disable()
    yield
    memory.reset()
    core.reset()
    core.disable()


def live_device_bytes() -> int:
    gc.collect()
    return memory.backend_memory()['live_bytes']


# ---------------------------------------------------------------- units
def test_tree_nbytes_arrays_and_abstract():
    import jax
    import jax.numpy as jnp
    tree = {'a': jnp.zeros((4, 8), jnp.float32),
            'b': np.zeros((3,), np.int32),
            'c': jax.ShapeDtypeStruct((2, 2), jnp.bfloat16)}
    assert memory.tree_nbytes(tree) == 4 * 8 * 4 + 3 * 4 + 2 * 2 * 2


def test_register_replace_release_and_watermarks():
    led = memory.ledger()
    assert led.register('params', 'k', 1000) == 1000
    # replace, not accumulate: same (bucket, key) is one owner
    led.register('params', 'k', 400)
    assert led.bucket_bytes('params') == 400
    assert led.attributed_bytes() == 400
    # the watermark remembers the peak
    snap = led.snapshot(reconcile=False)
    assert snap['watermarks']['params'] == 1000
    assert led.release('params', 'k') == 400
    assert led.release('params', 'k') == 0  # idempotent
    with pytest.raises(ValueError, match='unknown ledger bucket'):
        led.register('bogus', 'k', 1)


def test_executables_excluded_from_attribution():
    led = memory.ledger()
    led.register('params', 'p', 100)
    led.register('executables', 'e', 900, kind='executable',
                 attrs={'tier': 'topk', 'bucket': 8, 'capacity': 64})
    assert led.attributed_bytes() == 100
    snap = led.snapshot(reconcile=False)
    assert snap['executables_bytes'] == 900
    assert snap['buckets']['executables']['entries'][0]['attrs'][
        'tier'] == 'topk'


def test_owner_finalizer_releases_on_gc():
    led = memory.ledger()

    class Owner:
        pass

    owner = Owner()
    led.register('index', 'fin', 256, owner=owner)
    assert led.bucket_bytes('index') == 256
    del owner
    gc.collect()
    assert led.bucket_bytes('index') == 0


def test_snapshot_diff_flags_intentionally_retained_buffer():
    """The leak-detection primitive: a device buffer retained between
    two snapshots shows up either as unattributed growth (nobody
    registered it) or as a grown ledger entry (its owner did)."""
    import jax.numpy as jnp
    led = memory.ledger()
    before = led.snapshot()
    retained = jnp.zeros((256, 128), jnp.float32)  # noqa: F841 — the leak
    gc.collect()
    after = led.snapshot()
    diff = MemoryLedger.diff(before, after)
    assert diff['backend_live_delta'] >= retained.nbytes
    assert diff['unattributed_delta'] >= retained.nbytes
    # once its owner registers it, the residual clears and the entry
    # names the holder
    led.register('staging', 'retained', retained)
    attributed = led.snapshot()
    diff2 = MemoryLedger.diff(before, attributed)
    assert diff2['buckets']['staging']['entries']['retained'] \
        == retained.nbytes
    assert diff2['unattributed_delta'] < retained.nbytes


# ------------------------------------------------------ budget + forensics
def test_budget_blocks_exact_index_attach_without_allocating(tmp_path):
    from code2vec_tpu.index.exact import ExactIndex
    memory.configure(budget_bytes=10_000, dump_dir=str(tmp_path))
    vectors = np.random.default_rng(0).normal(
        size=(4096, 64)).astype(np.float32)  # ~1 MiB >> budget
    before = live_device_bytes()
    with pytest.raises(MemoryBudgetExceeded, match='index attach'):
        ExactIndex(vectors, mesh=None)
    # typed failure BEFORE allocation: nothing landed on device
    assert live_device_bytes() == before
    # and the forensic ledger dump exists and parses
    dump = tmp_path / memory.OOM_DUMP_NAME
    assert dump.is_file()
    payload = json.loads(dump.read_text())
    assert payload['reason'].startswith('budget')
    assert payload['budget_bytes'] == 10_000
    # with headroom the same attach succeeds and registers
    memory.configure(budget_bytes=100 * 1024 * 1024)
    index = ExactIndex(vectors, mesh=None)
    assert memory.ledger().bucket_bytes('index') >= vectors.nbytes
    del index
    gc.collect()
    assert memory.ledger().bucket_bytes('index') == 0


def test_budget_resolves_from_env_var(monkeypatch):
    monkeypatch.setenv(memory.ENV_BUDGET, '12345')
    assert memory.ledger().budget_bytes() == 12345
    memory.configure(budget_bytes=99)  # config pins over env
    assert memory.ledger().budget_bytes() == 99


def test_note_oom_dumps_only_on_oom_errors(tmp_path):
    memory.configure(dump_dir=str(tmp_path))
    led = memory.ledger()
    led.register('params', 'p', 777)
    assert led.note_oom(ValueError('unrelated'), 'ctx') is None
    assert not (tmp_path / memory.OOM_DUMP_NAME).exists()
    path = led.note_oom(
        RuntimeError('RESOURCE_EXHAUSTED: Out of memory allocating '
                     '1073741824 bytes'), 'serving.dispatch')
    assert path is not None
    payload = json.loads(open(path).read())
    assert payload['reason'].startswith('oom: serving.dispatch')
    assert payload['buckets']['params']['bytes'] == 777
    assert payload['events'][-1]['key'] == 'p'


def test_ledger_bookkeeping_never_syncs_the_device(monkeypatch,
                                                   tmp_path):
    """The zero-host-sync contract: register / snapshot / dump touch
    array METADATA only — never device_get / block_until_ready."""
    import jax
    import jax.numpy as jnp
    memory.configure(dump_dir=str(tmp_path))
    params = {'w': jnp.ones((64, 32)), 'b': jnp.zeros((32,))}

    def forbidden(*_a, **_k):
        raise AssertionError('ledger bookkeeping synced the device')

    monkeypatch.setattr(jax, 'device_get', forbidden)
    monkeypatch.setattr(jax, 'block_until_ready', forbidden)
    led = memory.ledger()
    led.register('params', 'p', params)
    led.export_gauges()
    snap = led.snapshot()  # reconciles via live_arrays: still no sync
    assert snap['attributed_bytes'] == memory.tree_nbytes(params)
    led.dump(reason='guard')
    led.release('params', 'p')


# ------------------------------------------------- e2e: CPU fit acceptance
def test_fit_reconciliation_mem_now_and_gauges(tmp_path):
    """ISSUE 9 acceptance, training half: on a CPU fit with telemetry
    on, attributed + unattributed ≡ backend live bytes (the snapshot
    identity) and the unattributed residual of the run's own growth
    stays under 10%; MEM_NOW yields a live snapshot; the mem/* gauges
    land in metrics.jsonl; the staging bucket drains to zero."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from tests.test_train_overfit import make_dataset

    prefix = make_dataset(tmp_path)
    tele_dir = tmp_path / 'tele'
    tele_dir.mkdir()
    (tele_dir / memory.TOUCH_FILE_NAME).touch()  # MEM_NOW pre-armed
    gc.collect()
    before = memory.ledger().snapshot()
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), DL_FRAMEWORK='jax',
        COMPUTE_DTYPE='float32', MAX_CONTEXTS=6, TRAIN_BATCH_SIZE=16,
        TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1, SHUFFLE_BUFFER_SIZE=64,
        VERBOSE_MODE=0, READER_USE_NATIVE=False,
        NUM_BATCHES_TO_LOG_PROGRESS=2, TELEMETRY=True,
        TELEMETRY_DIR=str(tele_dir), TELEMETRY_FLUSH_EVERY_STEPS=1)
    model = Code2VecModel(config)
    model.train()
    gc.collect()
    after = memory.ledger().snapshot()

    # the snapshot identity: attributed + unattributed == live, exactly
    assert (after['attributed_bytes'] + after['unattributed_bytes']
            == after['backend']['live_bytes'])
    # this run's growth reconciles: the residual (loss scalars, rng
    # keys, in-flight batch) is under 10% of what the run brought up
    diff = MemoryLedger.diff(before, after)
    assert diff['backend_live_delta'] > 0
    assert diff['attributed_delta'] > 0
    assert abs(diff['unattributed_delta']) \
        < 0.10 * diff['backend_live_delta'], diff
    # params + opt state attributed; the staging ring drained clean
    assert after['buckets']['params']['bytes'] > 0
    assert after['buckets']['opt_state']['bytes'] > 0
    assert diff['buckets']['staging']['bytes_delta'] == 0
    assert not diff['buckets']['staging']['entries']

    # MEM_NOW: consumed, snapshot written, renderable
    assert not (tele_dir / memory.TOUCH_FILE_NAME).exists()
    mem_snaps = sorted(tele_dir.glob('memory_step*.json'))
    assert mem_snaps, list(tele_dir.iterdir())
    payload = json.loads(mem_snaps[0].read_text())
    assert payload['reason'].startswith('MEM_NOW')
    assert payload['buckets']['params']['bytes'] > 0

    # mem/* gauges exported through the standard JSONL stream
    tags = set()
    with open(tele_dir / 'metrics.jsonl') as f:
        for line in f:
            tags.add(json.loads(line)['tag'])
    for tag in ('mem/params_bytes', 'mem/opt_state_bytes',
                'mem/staging_bytes', 'mem/attributed_bytes',
                'mem/budget_bytes'):
        assert tag in tags, sorted(t for t in tags if t.startswith('mem'))


# --------------------------------- e2e: serving + index smoke acceptance
@pytest.fixture(scope='module')
def served_model(tmp_path_factory):
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_api import Code2VecModel
    from tests.test_train_overfit import make_dataset
    prefix = make_dataset(tmp_path_factory.mktemp('memserve'))
    save_path = str(tmp_path_factory.mktemp('memserve_model') / 'model')
    config = Config(
        TRAIN_DATA_PATH_PREFIX=str(prefix), MODEL_SAVE_PATH=save_path,
        DL_FRAMEWORK='jax', COMPUTE_DTYPE='float32', MAX_CONTEXTS=6,
        TRAIN_BATCH_SIZE=16, TEST_BATCH_SIZE=16, NUM_TRAIN_EPOCHS=1,
        SHUFFLE_BUFFER_SIZE=64, VERBOSE_MODE=0, READER_USE_NATIVE=False,
        SERVING_BATCH_BUCKETS='8', SERVING_CANARY_TIMEOUT_SECS=0.0,
        # the telemetry LAYER gates the warmup executable measurement
        TELEMETRY=True)
    return Code2VecModel(config)


PREDICT_LINES = [
    'get|a toka0,pA,toka1 toka1,pB,toka2',
    'set|b tokb0,pA,tokb1',
]


def test_serving_index_smoke_reconciles_and_stays_warm(served_model):
    """ISSUE 9 acceptance, serving half: engine + attached exact index
    reconcile (residual < 10% of the smoke's growth), the warm ladder's
    executables are measured per (bucket x capacity x tier), and ledger
    work adds ZERO post-warmup compiles."""
    from code2vec_tpu.index.exact import ExactIndex
    from code2vec_tpu.telemetry.jit_tracker import install_compile_listener

    # the model (and its params) predate this test (and the autouse
    # ledger reset): re-register its state — same keys, so this is the
    # idempotent replace — then measure the smoke's own growth
    served_model.trainer.register_state_memory(
        served_model.params, served_model.state.opt_state)
    gc.collect()
    before = memory.ledger().snapshot()
    core.enable()  # as a telemetry-on serving run would be
    install_compile_listener()
    compiles = core.registry().counter('jit/compiles_total')
    rng = np.random.default_rng(0)
    store = rng.normal(size=(128, 384)).astype(np.float32)
    engine = served_model.serving_engine(tiers=('topk', 'vectors'),
                                         max_delay_ms=0.0)
    try:
        index = ExactIndex(store, mesh=None,
                           labels=np.array(['m%d' % i
                                            for i in range(128)]))
        index.warmup(k=4)
        engine.attach_index(index)
        warm_compiles = compiles.value
        for _ in range(2):
            engine.predict(PREDICT_LINES, tier='topk')
        neighbors = engine.predict_neighbors(PREDICT_LINES, k=4)
        assert len(neighbors) == len(PREDICT_LINES)
        gc.collect()
        after = memory.ledger().snapshot()
        # zero post-warmup compiles: ledger bookkeeping (register,
        # snapshot, reconcile) never traces or dispatches
        assert compiles.value == warm_compiles

        # the index is attributed, and the warm ladder was measured
        assert after['buckets']['index']['bytes'] >= store.nbytes
        executables = after['buckets']['executables']['entries']
        assert executables, 'warmup measured no executables'
        seen = {(e['attrs']['tier'], e['attrs']['bucket'])
                for e in executables}
        assert ('topk', 8) in seen and ('vectors', 8) in seen
        for entry in executables:
            assert entry['attrs']['argument_bytes'] > 0

        # reconciliation: identity holds, and the smoke's residual
        # (tokenizer tables, decode buffers) is bounded
        assert (after['attributed_bytes'] + after['unattributed_bytes']
                == after['backend']['live_bytes'])
        diff = MemoryLedger.diff(before, after)
        assert diff['backend_live_delta'] > 0
        assert abs(diff['unattributed_delta']) \
            < 0.10 * max(diff['backend_live_delta'],
                         after['buckets']['index']['bytes']), diff
    finally:
        engine.close()
    # a closed engine retires its entries; the index releases on GC
    assert memory.ledger().bucket_bytes('params') \
        == memory.tree_nbytes(served_model.params)


def test_rollover_leak_drill_params_return_to_baseline(served_model):
    """The rollover leak drill (ISSUE 9 satellite): repeated CANARIED
    load_params rollovers must return the params-bucket footprint to
    baseline after every swap — the old set is actually freed, not
    pinned by the shadow scorer or an armed-rollover remnant."""
    served_model.save(state=served_model.state, epoch=0, wait=True)
    served_model.trainer.register_state_memory(
        served_model.params, served_model.state.opt_state)
    set_bytes = memory.tree_nbytes(served_model.params)
    engine = served_model.serving_engine(tiers=('topk',),
                                         max_delay_ms=0.0)
    try:
        def one_rollover():
            handle = engine.load_params(0, canary_batches=1,
                                        min_agreement=0.0)
            # the armed canary's SECOND copy is ledger-visible
            snap = memory.ledger().snapshot(reconcile=False)
            keys = [e['key'] for e in
                    snap['buckets']['params']['entries']]
            assert any(k.endswith('/candidate') for k in keys), keys
            engine.predict(PREDICT_LINES, tier='topk')  # concludes it
            report = handle.result(timeout=60)
            assert report['swapped'] is True
            gc.collect()
            return (memory.ledger().bucket_bytes('params'),
                    live_device_bytes())

        baseline_params, baseline_live = one_rollover()
        # baseline holds the model's set + the engine's swapped-in set
        assert baseline_params >= 2 * set_bytes
        for _ in range(2):
            params_bytes, live = one_rollover()
            # ledger: exactly back to baseline after every swap
            assert params_bytes == baseline_params
            # backend: no param-set accumulation (a leak of even one
            # retained set would show up whole)
            assert abs(live - baseline_live) < 0.5 * set_bytes, \
                (live, baseline_live, set_bytes)
    finally:
        engine.close()
    gc.collect()
    assert memory.ledger().bucket_bytes('params') \
        == memory.tree_nbytes(served_model.params)


# ------------------------------------------------------- report CLI
def test_memory_report_cli_render_diff_and_json(tmp_path, capsys):
    scripts_dir = os.path.join(REPO, 'scripts')
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import memory_report
    led = memory.ledger()
    led.register('params', 'trainer', 4096)
    led.register('executables', 'engine/topk/b8/c64', 512,
                 kind='executable',
                 attrs={'tier': 'topk', 'bucket': 8, 'capacity': 64,
                        'generated_code_bytes': 512, 'temp_bytes': 0,
                        'argument_bytes': 1024, 'output_bytes': 64})
    a_path = str(tmp_path / 'a.json')
    led.dump(a_path, reason='before')
    led.register('staging', 'leaked', 2048)
    b_path = str(tmp_path / 'b.json')
    led.dump(b_path, reason='after')

    assert memory_report.main([a_path]) == 0
    out = capsys.readouterr().out
    assert 'params' in out and 'unattributed residual' in out
    assert 'warm serving ladder' in out and 'topk' in out

    assert memory_report.main([b_path, '--diff', a_path]) == 0
    out = capsys.readouterr().out
    assert 'leaked' in out and 'staging' in out and 'added' in out

    assert memory_report.main([b_path, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['buckets']['staging'] == 2048


# ------------------------------------------------ graftlint alloc-catalog
CLEAN_OWNER = '''
import jax

class ExactIndex:
    def __init__(self, vectors, neg_mask):
        self._matrix = jax.device_put(vectors)
        self._neg_mask = jax.device_put(neg_mask)
        self._a = jax.device_put(vectors)
        self._b = jax.device_put(neg_mask)
'''


def lint_alloc(tmp_path, exact_py_text):
    from code2vec_tpu.analysis import engine as lint_engine
    pkg = tmp_path / 'code2vec_tpu' / 'index'
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / 'exact.py').write_text(exact_py_text)
    return lint_engine.run(root=str(tmp_path),
                           rule_names=['alloc-catalog'],
                           baseline_path='')


def test_alloc_catalog_quiet_on_cataloged_counts(tmp_path):
    # 4 device_put sites in ExactIndex.__init__ — exactly the pinned
    # count, so the owner file is clean
    report = lint_alloc(tmp_path, CLEAN_OWNER)
    assert not report.findings, report.findings


def test_alloc_catalog_fires_on_uncataloged_site(tmp_path):
    code = CLEAN_OWNER + '''

def rogue(x):
    return jax.device_put(x)
'''
    report = lint_alloc(tmp_path, code)
    messages = [f.message for f in report.findings]
    assert any('rogue' in m and 'not in the alloc catalog' in m
               for m in messages), messages


def test_alloc_catalog_pins_counts(tmp_path):
    # a FIFTH device_put inside the cataloged function: count drift
    code = CLEAN_OWNER.replace(
        '        self._b = jax.device_put(neg_mask)',
        '        self._b = jax.device_put(neg_mask)\n'
        '        self._extra = jax.device_put(vectors)')
    report = lint_alloc(tmp_path, code)
    messages = [f.message for f in report.findings]
    assert any('pins 4 allocation site(s)' in m and 'found 5' in m
               for m in messages), messages


def test_alloc_catalog_flags_stale_entries(tmp_path):
    # the owner file exists but the cataloged function allocates
    # nothing: stale entry
    report = lint_alloc(tmp_path, 'X = 1\n')
    messages = [f.message for f in report.findings]
    assert any('ExactIndex.__init__ is stale' in m
               for m in messages), messages


def test_alloc_catalog_suppression_with_reason(tmp_path):
    code = CLEAN_OWNER + '''

def rogue(x):
    # graftlint: disable=alloc-catalog -- test: sanctioned one-off
    return jax.device_put(x)
'''
    report = lint_alloc(tmp_path, code)
    assert not report.findings, report.findings
    assert len(report.suppressed) == 1


def test_alloc_catalog_ignores_docstrings(tmp_path):
    code = CLEAN_OWNER + '''

def documented(x):
    """Mentions jax.device_put(x) and jnp.zeros(n) in prose only."""
    return x
'''
    report = lint_alloc(tmp_path, code)
    assert not report.findings, report.findings
