"""Fused-encode Pallas kernel vs the plain jnp math (interpreter mode —
no TPU needed for correctness)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.ops import pallas_encode

pytestmark = pytest.mark.skipif(not pallas_encode.PALLAS_AVAILABLE,
                                reason='pallas unavailable')


@pytest.mark.parametrize('n', [512, 1024, 700])  # incl. non-multiple of tile
def test_fused_matches_reference_math(n):
    rng = np.random.default_rng(0)
    token_dim, path_dim, code_dim = 16, 16, 48
    src = rng.standard_normal((n, token_dim)).astype(np.float32)
    path = rng.standard_normal((n, path_dim)).astype(np.float32)
    tgt = rng.standard_normal((n, token_dim)).astype(np.float32)
    transform = rng.standard_normal(
        (2 * token_dim + path_dim, code_dim)).astype(np.float32) * 0.1
    attention = rng.standard_normal((code_dim, 1)).astype(np.float32)

    x, scores = pallas_encode.fused_context_transform(
        src, path, tgt, transform, attention, interpret=True)

    ctx = np.concatenate([src, path, tgt], axis=1)
    ref_x = np.tanh(ctx @ transform)
    ref_scores = ref_x @ attention
    np.testing.assert_allclose(np.asarray(x), ref_x, rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(scores), ref_scores, rtol=2e-5,
                               atol=1e-6)


def test_encode_with_pallas_flag_matches_plain_path():
    """On CPU the flag falls back to the jnp path (the kernel only routes
    on a real TPU backend) — this asserts the flag is safe everywhere; the
    kernel itself is covered by the interpret-mode tests above."""
    from code2vec_tpu.models import functional
    params = functional.init_params(
        jax.random.PRNGKey(0), token_vocab_size=20, path_vocab_size=10,
        target_vocab_size=8, token_dim=8, path_dim=8, code_dim=16)
    rng = np.random.default_rng(3)
    source = rng.integers(0, 20, (4, 6)).astype(np.int32)
    path = rng.integers(0, 10, (4, 6)).astype(np.int32)
    target = rng.integers(0, 20, (4, 6)).astype(np.int32)
    mask = np.ones((4, 6), np.float32)
    code_plain, attn_plain = functional.encode(
        params, source, path, target, mask)
    code_fused, attn_fused = functional.encode(
        params, source, path, target, mask, use_pallas=True)
    np.testing.assert_allclose(np.asarray(code_plain),
                               np.asarray(code_fused), rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(attn_plain),
                               np.asarray(attn_fused), rtol=2e-5, atol=1e-6)


def test_fused_under_jit_composition():
    rng = np.random.default_rng(1)
    src = rng.standard_normal((256, 8)).astype(np.float32)
    path = rng.standard_normal((256, 8)).astype(np.float32)
    tgt = rng.standard_normal((256, 8)).astype(np.float32)
    transform = rng.standard_normal((24, 16)).astype(np.float32)
    attention = rng.standard_normal((16, 1)).astype(np.float32)

    @jax.jit
    def run(a, b, c):
        x, s = pallas_encode.fused_context_transform(
            a, b, c, transform, attention, interpret=True)
        return x.sum() + s.sum()

    value = float(run(src, path, tgt))
    ctx = np.concatenate([src, path, tgt], axis=1)
    ref_x = np.tanh(ctx @ transform)
    ref = ref_x.sum() + (ref_x @ attention).sum()
    np.testing.assert_allclose(value, ref, rtol=1e-4)


def test_fused_at_long_context_java14m_dims():
    """C=1024 long-context shape at the real java14m dims (d=128 each,
    code_dim=384): the kernel the watcher's pallas_c1024 stage measures
    on chip is logic-correct at exactly that row count and width — only
    the Mosaic compile/perf half stays chip-gated (VERDICT r4 weak #4)."""
    rng = np.random.default_rng(0)
    n = 4 * 1024                       # B=4 at MAX_CONTEXTS=1024
    src = rng.standard_normal((n, 128)).astype(np.float32)
    path = rng.standard_normal((n, 128)).astype(np.float32)
    tgt = rng.standard_normal((n, 128)).astype(np.float32)
    transform = (rng.standard_normal((384, 384)) * 0.05).astype(np.float32)
    attention = rng.standard_normal((384, 1)).astype(np.float32)

    x, scores = pallas_encode.fused_context_transform(
        src, path, tgt, transform, attention, interpret=True)

    ctx = np.concatenate([src, path, tgt], axis=1)
    ref_x = np.tanh(ctx @ transform)
    np.testing.assert_allclose(np.asarray(x), ref_x, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(scores), ref_x @ attention,
                               rtol=2e-4, atol=2e-5)
