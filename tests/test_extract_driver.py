"""Fault-tolerant extraction driver: poison isolation, timeouts, fan-out."""
import io
import os
import sys

import pytest

from code2vec_tpu.data.extract_driver import ExtractionDriver

from tests.extractor_bin import BINARY, binary_missing_reason

pytestmark = pytest.mark.skipif(
    binary_missing_reason() is not None or not os.path.isfile(BINARY),
    reason=str(binary_missing_reason() or 'extractor binary not built'))


def _make_tree(tmp_path):
    good = tmp_path / 'projA' / 'src'
    good.mkdir(parents=True)
    (good / 'Good.java').write_text(
        'class G { int add(int a, int b) { return a + b; } }')
    (good / 'Also.java').write_text(
        'class H { int sub(int a, int b) { return a - b; } }')
    loose = tmp_path / 'Loose.java'
    loose.write_text('class L { int one() { return 1; } }')
    return tmp_path


def test_extracts_all_dirs_and_loose_files(tmp_path):
    root = _make_tree(tmp_path)
    driver = ExtractionDriver([BINARY], log=lambda m: None)
    out = io.StringIO()
    driver.extract(str(root), out, workers=2)
    labels = sorted(line.split(' ')[0]
                    for line in out.getvalue().splitlines())
    assert labels == ['add', 'one', 'sub']


def test_poison_file_isolated_not_sinking_project(tmp_path):
    root = _make_tree(tmp_path)
    # a "poison" wrapper: fails on --dir projB (simulating a crash inside
    # the project) and on the Bad file itself, so recursion must isolate it
    wrapper = tmp_path / 'wrapper.py'
    wrapper.write_text(
        'import subprocess, sys\n'
        'args = sys.argv[1:]\n'
        'if any(a.endswith("projB") or "Bad" in a for a in args):\n'
        '    sys.exit(1)\n'
        'r = subprocess.run([%r] + args, capture_output=True, text=True)\n'
        'sys.stdout.write(r.stdout)\n'
        'sys.exit(r.returncode)\n' % BINARY)
    bad_dir = root / 'projB'
    bad_dir.mkdir()
    (bad_dir / 'Bad.java').write_text('class B { int f() { return 2; } }')
    (bad_dir / 'Fine.java').write_text('class F { int g() { return 3; } }')

    logs = []
    driver = ExtractionDriver([sys.executable, str(wrapper)],
                              timeout_seconds=60, log=logs.append)
    out = io.StringIO()
    driver.extract(str(root), out, workers=1)
    labels = sorted(line.split(' ')[0]
                    for line in out.getvalue().splitlines())
    # Fine.java survives via recursion; Bad.java skipped as poison
    assert labels == ['add', 'g', 'one', 'sub']
    assert driver.nr_failed_files == 1
    assert any('poison' in m for m in logs)


def test_timeout_triggers_isolation(tmp_path):
    root = tmp_path
    proj = root / 'proj'
    proj.mkdir()
    (proj / 'Slow.java').write_text('class S { int f() { return 1; } }')
    # wrapper: hang on --dir, work on --file
    wrapper = tmp_path / 'hang.py'
    wrapper.write_text(
        'import subprocess, sys, time\n'
        'args = sys.argv[1:]\n'
        'if "--dir" in args:\n'
        '    time.sleep(60)\n'
        'r = subprocess.run([%r] + args, capture_output=True, text=True)\n'
        'sys.stdout.write(r.stdout)\n'
        'sys.exit(r.returncode)\n' % BINARY)
    driver = ExtractionDriver([sys.executable, str(wrapper)],
                              timeout_seconds=3, log=lambda m: None)
    out = io.StringIO()
    driver.extract(str(root), out, workers=1)
    assert 'f ' in out.getvalue()  # extracted via per-file fallback
